"""Keyring-class secret storage — pluggable OS-backed secret stores.

Role of ``crates/crypto/src/keys/keyring/`` (the reference's Linux
Secret-Service / macOS Keychain layer used by keymanager.rs): somewhere to
park the key manager's root secret so the library auto-unlocks across
process restarts WITHOUT a plaintext secret readable from disk.

Backends (pluggable, picked by :func:`default_store`):

- :class:`KernelKeyringStore` — the Linux kernel **user keyring** via raw
  ``add_key``/``request_key``/``keyctl`` syscalls (ctypes; no daemon, no
  deps). Secrets live in kernel memory, scoped to the uid, never touch
  disk, and survive process restarts until reboot — the same lifetime
  class as an unlocked desktop keyring session.
- :class:`FileSecretStore` — the portable fallback: secrets sealed with
  XChaCha20-Poly1305 under a key derived from the machine identity
  (/etc/machine-id) + uid + a fixed context string, stored 0600. This
  keeps plaintext out of the keystore directory and binds the blob to
  this machine/user. Honest threat model: it defeats exfiltration of the
  data directory alone (the common backup/sync scope) — a FULL disk image
  also contains /etc/machine-id and so defeats it, as it defeats any
  file-backed keyring fallback; prefer the kernel keyring where
  available, or keep auto-unlock off for at-rest protection that rests
  on the argon2id master password.

The key manager consumes this through ``enable_auto_unlock`` /
``try_auto_unlock`` (keymanager.py).
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import os
from pathlib import Path
from typing import Protocol

SERVICE = "spacedrive_tpu"

# keyctl-family syscall numbers are per-architecture; an unmapped arch must
# never issue a mismapped syscall with secret bytes as arguments
_SYSCALLS = {
    "x86_64": (248, 249, 250),    # add_key, request_key, keyctl
    "aarch64": (217, 218, 219),
}


def _syscall_numbers() -> tuple[int, int, int] | None:
    import platform

    return _SYSCALLS.get(platform.machine())

_KEY_SPEC_USER_KEYRING = -4
_KEYCTL_READ = 11
_KEYCTL_UNLINK = 9


class SecretStore(Protocol):
    name: str

    def get(self, account: str) -> bytes | None: ...
    def set(self, account: str, secret: bytes) -> None: ...
    def delete(self, account: str) -> None: ...


class KeyringError(Exception):
    pass


class KernelKeyringStore:
    """Linux kernel user-keyring backend ("user" key type)."""

    name = "kernel-keyring"

    def __init__(self) -> None:
        nums = _syscall_numbers()
        if nums is None:
            raise KeyringError("kernel keyring: unmapped architecture")
        self._sys_add_key, self._sys_request_key, self._sys_keyctl = nums
        self._libc = ctypes.CDLL(None, use_errno=True)

    def _desc(self, account: str) -> bytes:
        return f"{SERVICE}:{account}".encode()

    def set(self, account: str, secret: bytes) -> None:
        kid = self._libc.syscall(
            self._sys_add_key, b"user", self._desc(account), secret, len(secret),
            _KEY_SPEC_USER_KEYRING)
        if kid < 0:
            raise KeyringError(f"add_key failed: errno {ctypes.get_errno()}")

    def _find(self, account: str) -> int:
        kid = self._libc.syscall(
            self._sys_request_key, b"user", self._desc(account), None,
            _KEY_SPEC_USER_KEYRING)
        return int(kid)

    def get(self, account: str) -> bytes | None:
        kid = self._find(account)
        if kid < 0:
            return None
        size = self._libc.syscall(self._sys_keyctl, _KEYCTL_READ, kid, None, 0)
        if size < 0:
            return None
        buf = ctypes.create_string_buffer(size)
        got = self._libc.syscall(self._sys_keyctl, _KEYCTL_READ, kid, buf, size)
        if got < 0:
            return None
        return buf.raw[:got]

    def delete(self, account: str) -> None:
        kid = self._find(account)
        if kid >= 0:
            self._libc.syscall(self._sys_keyctl, _KEYCTL_UNLINK, kid,
                               _KEY_SPEC_USER_KEYRING)

    @classmethod
    def available(cls) -> bool:
        try:
            store = cls()
            probe = f"__probe__{os.getpid()}"
            store.set(probe, b"x")
            ok = store.get(probe) == b"x"
            store.delete(probe)
            return ok
        except Exception:
            return False


class FileSecretStore:
    """Machine-bound encrypted file fallback (see module docstring)."""

    name = "file"

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)

    def _machine_key(self) -> bytes:
        try:
            machine = Path("/etc/machine-id").read_text().strip()
        except OSError:
            machine = "no-machine-id"
        material = f"{SERVICE}-keyring|{machine}|{os.getuid()}".encode()
        return hashlib.sha256(material).digest()

    def _load(self) -> dict:
        try:
            return json.loads(self.path.read_text())
        except (OSError, json.JSONDecodeError):
            return {}

    def _save(self, blob: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        fd = os.open(str(tmp), os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o600)
        with os.fdopen(fd, "w") as fh:
            json.dump(blob, fh)
        tmp.replace(self.path)

    def set(self, account: str, secret: bytes) -> None:
        from .primitives import Protected
        from .stream import Algorithm, Encryptor

        algorithm = Algorithm.XCHACHA20_POLY1305
        nonce = algorithm.generate_nonce()
        sealed = Encryptor.encrypt_bytes(
            Protected(self._machine_key()), nonce, algorithm, secret)
        blob = self._load()
        blob[account] = {"nonce": nonce.hex(), "sealed": sealed.hex(),
                         "algorithm": algorithm.value}
        self._save(blob)

    def get(self, account: str) -> bytes | None:
        from .primitives import Protected
        from .stream import Algorithm, Decryptor

        rec = self._load().get(account)
        if rec is None:
            return None
        try:
            return Decryptor.decrypt_bytes(
                Protected(self._machine_key()), bytes.fromhex(rec["nonce"]),
                Algorithm(rec["algorithm"]),
                bytes.fromhex(rec["sealed"])).expose()
        except Exception:
            return None

    def delete(self, account: str) -> None:
        blob = self._load()
        if blob.pop(account, None) is not None:
            self._save(blob)


def default_store(data_dir: str | Path) -> SecretStore:
    """Kernel keyring when the host allows it, else the machine-bound
    encrypted file beside the keystore."""
    if KernelKeyringStore.available():
        return KernelKeyringStore()
    return FileSecretStore(Path(data_dir) / "keyring.json")
