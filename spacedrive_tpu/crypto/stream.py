"""AEAD streams: 1MiB blocks under an LE31 STREAM construction.

Reference: crates/crypto/src/crypto/stream.rs — Encryptor/Decryptor over
XChaCha20Poly1305 or AES-256-GCM, reading BLOCK_LEN blocks and sealing each
with the `aead` crate's EncryptorLE31. The LE31 scheme (implemented here
from its definition) extends the caller's nonce with a 4-byte little-endian
word carrying a 31-bit block counter and a last-block bit, so blocks cannot
be reordered, truncated, or spliced across streams. Caller nonce lengths
match the reference's Algorithm::nonce_len(): 20 bytes for XChaCha (full 24
minus 4) and 8 for AES-GCM (full 12 minus 4) — types.rs:139-143.

AAD (the serialized header) is bound to the FIRST block only, exactly like
encrypt_streams (stream.rs: aad passed on block 0).
"""

from __future__ import annotations

import enum
from typing import BinaryIO

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # dependency-gated: encrypt/decrypt raise at USE time
    class AESGCM:  # type: ignore[no-redef]
        def __init__(self, *_a: object, **_k: object) -> None:
            raise RuntimeError(
                "AEAD crypto requires the 'cryptography' package")

from .primitives import AEAD_TAG_LEN, BLOCK_LEN, Protected, generate_nonce
from .xchacha import XChaCha20Poly1305


class CryptoError(Exception):
    pass


class Algorithm(enum.Enum):
    XCHACHA20_POLY1305 = 0
    AES_256_GCM = 1

    @property
    def nonce_len(self) -> int:
        # stream nonce = full AEAD nonce minus the 4-byte LE31 word
        return 20 if self is Algorithm.XCHACHA20_POLY1305 else 8

    def generate_nonce(self) -> bytes:
        return generate_nonce(self.nonce_len)

    def _aead(self, key: bytes):
        if self is Algorithm.XCHACHA20_POLY1305:
            return XChaCha20Poly1305(key)
        return AESGCM(key)


_LAST_BLOCK = 1 << 31


class _Stream:
    def __init__(self, key: Protected, nonce: bytes, algorithm: Algorithm) -> None:
        if len(nonce) != algorithm.nonce_len:
            raise CryptoError(
                f"nonce length mismatch: got {len(nonce)}, "
                f"want {algorithm.nonce_len} for {algorithm.name}")
        if len(key) != 32:
            raise CryptoError("key must be 32 bytes")
        self._aead = algorithm._aead(key.expose())
        self._nonce = nonce
        self._counter = 0
        self._finished = False

    def _next_nonce(self, last: bool) -> bytes:
        if self._finished:
            raise CryptoError("stream already finalized")
        if self._counter >= _LAST_BLOCK:
            raise CryptoError("LE31 counter exhausted")
        word = self._counter | (_LAST_BLOCK if last else 0)
        if last:
            self._finished = True
        else:
            self._counter += 1
        return self._nonce + word.to_bytes(4, "little")


class Encryptor(_Stream):
    def encrypt_next(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        return self._aead.encrypt(self._next_nonce(False), plaintext, aad or None)

    def encrypt_last(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        return self._aead.encrypt(self._next_nonce(True), plaintext, aad or None)

    @classmethod
    def encrypt_streams(cls, key: Protected, nonce: bytes, algorithm: Algorithm,
                        reader: BinaryIO, writer: BinaryIO,
                        aad: bytes = b"") -> int:
        """Block-by-block file encryption (stream.rs encrypt_streams): read
        BLOCK_LEN, seal, write; AAD authenticated with block 0. Returns
        ciphertext bytes written."""
        enc = cls(key, nonce, algorithm)
        written = 0
        block = reader.read(BLOCK_LEN)
        first = True
        while True:
            nxt = reader.read(BLOCK_LEN)
            this_aad = aad if first else b""
            if nxt:
                out = enc.encrypt_next(block, this_aad)
            else:
                out = enc.encrypt_last(block, this_aad)
            writer.write(out)
            written += len(out)
            if not nxt:
                return written
            block, first = nxt, False

    @classmethod
    def encrypt_bytes(cls, key: Protected, nonce: bytes, algorithm: Algorithm,
                      data: bytes, aad: bytes = b"") -> bytes:
        """One-shot small-payload seal (stream.rs encrypt_bytes) — used for
        master keys in keyslots and header metadata blobs."""
        return cls(key, nonce, algorithm).encrypt_last(data, aad)


class Decryptor(_Stream):
    def decrypt_next(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        try:
            return self._aead.decrypt(self._next_nonce(False), ciphertext, aad or None)
        except Exception as e:
            raise CryptoError("decryption failed (wrong key or corrupt data)") from e

    def decrypt_last(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        try:
            return self._aead.decrypt(self._next_nonce(True), ciphertext, aad or None)
        except Exception as e:
            raise CryptoError("decryption failed (wrong key or corrupt data)") from e

    @classmethod
    def decrypt_streams(cls, key: Protected, nonce: bytes, algorithm: Algorithm,
                        reader: BinaryIO, writer: BinaryIO,
                        aad: bytes = b"") -> int:
        dec = cls(key, nonce, algorithm)
        cipher_block = BLOCK_LEN + AEAD_TAG_LEN
        written = 0
        block = reader.read(cipher_block)
        first = True
        while True:
            nxt = reader.read(cipher_block)
            this_aad = aad if first else b""
            if nxt:
                out = dec.decrypt_next(block, this_aad)
            else:
                out = dec.decrypt_last(block, this_aad)
            writer.write(out)
            written += len(out)
            if not nxt:
                return written
            block, first = nxt, False

    @classmethod
    def decrypt_bytes(cls, key: Protected, nonce: bytes, algorithm: Algorithm,
                      data: bytes, aad: bytes = b"") -> Protected:
        return Protected(cls(key, nonce, algorithm).decrypt_last(data, aad))
