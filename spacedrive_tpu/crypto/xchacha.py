"""XChaCha20-Poly1305 on top of the stdlib-adjacent `cryptography` package.

`cryptography` ships IETF ChaCha20Poly1305 (96-bit nonce) but not XChaCha.
The extended-nonce construction (draft-irtf-cfrg-xchacha) is: derive a
subkey with HChaCha20 over the first 16 nonce bytes, then run ChaCha20
Poly1305 with a 12-byte nonce of 4 zero bytes ‖ the remaining 8 nonce bytes.
HChaCha20 is implemented here from the ChaCha20 quarter-round spec (RFC 8439
§2.1-2.3) — pure Python is fine: it runs once per stream, not per block.

The reference gets XChaCha20Poly1305 from the `chacha20poly1305` crate
(crates/crypto/src/crypto/stream.rs:13); capability parity, new code.
"""

from __future__ import annotations

import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # dependency-gated: encrypt/decrypt raise at USE time
    class ChaCha20Poly1305:  # type: ignore[no-redef]
        def __init__(self, *_a: object, **_k: object) -> None:
            raise RuntimeError(
                "AEAD crypto requires the 'cryptography' package")

_MASK = 0xFFFFFFFF
_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)  # "expand 32-byte k"

NONCE_LEN = 24


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _quarter(state: list[int], a: int, b: int, c: int, d: int) -> None:
    state[a] = (state[a] + state[b]) & _MASK
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & _MASK
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotl(state[b] ^ state[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation: 20 ChaCha rounds, no final addition;
    output is state words 0-3 and 12-15."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20 needs a 32-byte key and 16-byte nonce")
    state = list(_CONSTANTS) + list(struct.unpack("<8I", key)) \
        + list(struct.unpack("<4I", nonce16))
    for _ in range(10):
        _quarter(state, 0, 4, 8, 12)
        _quarter(state, 1, 5, 9, 13)
        _quarter(state, 2, 6, 10, 14)
        _quarter(state, 3, 7, 11, 15)
        _quarter(state, 0, 5, 10, 15)
        _quarter(state, 1, 6, 11, 12)
        _quarter(state, 2, 7, 8, 13)
        _quarter(state, 3, 4, 9, 14)
    return struct.pack("<8I", *(state[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


class XChaCha20Poly1305:
    """Same call surface as cryptography's AEAD classes, 24-byte nonces."""

    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("key must be 32 bytes")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple[ChaCha20Poly1305, bytes]:
        if len(nonce) != NONCE_LEN:
            raise ValueError("XChaCha20Poly1305 nonce must be 24 bytes")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00\x00\x00\x00" + nonce[16:]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None = None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, data, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None = None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, data, aad)
