"""Key manager: a root key unlocked by a master password, guarding stored keys.

Reference: crates/crypto/src/keys/keymanager.rs (note the reference ships it
disconnected — library.rs:48-49 and api/mod.rs:173 comment out `keys.mount()`;
here it is wired into the encrypt/decrypt jobs as an optional key source).

Model: `setup(master_password)` creates a random root key, seals it into a
keyslot-style record persisted as JSON-in-library-dir; `unlock` recovers it.
Stored keys are random 32-byte keys sealed under the root key; `mount(uuid)`
exposes one to jobs, `unmount` drops it from memory. Secrets never persist
unencrypted.
"""

from __future__ import annotations

import base64
import json
import threading
import uuid as uuid_mod
from pathlib import Path

from .hashing import HashingAlgorithm
from .header import Keyslot
from .primitives import Protected, generate_master_key
from .stream import Algorithm, CryptoError, Decryptor, Encryptor


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode())


def _slot_to_json(slot: Keyslot) -> dict:
    return {
        "version": slot.version,
        "algorithm": slot.algorithm.value,
        "hashing": _b64(slot.hashing_algorithm.encode()),
        "salt": _b64(slot.salt),
        "content_salt": _b64(slot.content_salt),
        "master_key": _b64(slot.master_key),
        "nonce": _b64(slot.nonce),
    }


def _slot_from_json(obj: dict) -> Keyslot:
    return Keyslot(
        version=obj["version"],
        algorithm=Algorithm(obj["algorithm"]),
        hashing_algorithm=HashingAlgorithm.decode(_unb64(obj["hashing"])),
        salt=_unb64(obj["salt"]),
        content_salt=_unb64(obj["content_salt"]),
        master_key=_unb64(obj["master_key"]),
        nonce=_unb64(obj["nonce"]),
    )


class KeyManagerError(Exception):
    pass


class KeyManager:
    def __init__(self, store_path: str | Path) -> None:
        self.store_path = Path(store_path)
        self._lock = threading.RLock()
        self._root: Protected | None = None
        self._mounted: dict[str, Protected] = {}
        self._store = self._load()
        # persistence runs OUTSIDE self._lock (mount/get_key on the
        # encrypt/decrypt job path must never wait on keystore disk
        # I/O); the save lock only orders writers, and the version gate
        # keeps a stale snapshot from clobbering a newer one on disk
        self._save_lock = threading.Lock()
        self._store_version = 0
        self._saved_version = 0

    # -- persistence ---------------------------------------------------------
    def _load(self) -> dict:
        if self.store_path.exists():
            try:
                return json.loads(self.store_path.read_text())
            except (OSError, json.JSONDecodeError):
                pass
        return {"root_slot": None, "keys": {}, "default": None}

    def _snapshot(self) -> tuple[int, str]:
        """Serialize the store (call under ``self._lock``); hand the
        result to :meth:`_persist` AFTER releasing the lock."""
        self._store_version += 1
        return self._store_version, json.dumps(self._store, indent=1)

    def _persist(self, snap: tuple[int, str]) -> None:
        version, payload = snap
        with self._save_lock:
            if version <= self._saved_version:
                return  # a newer snapshot already reached disk
            self.store_path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.store_path.with_suffix(".tmp")
            # the ONE write under a lock: _save_lock exists solely to
            # order keystore writes (see robustness.md known waivers)
            tmp.write_text(payload)  # lint: ok(hold-blocking)
            tmp.replace(self.store_path)
            self._saved_version = version

    # -- lifecycle -----------------------------------------------------------
    @property
    def is_setup(self) -> bool:
        return self._store.get("root_slot") is not None

    @property
    def is_unlocked(self) -> bool:
        return self._root is not None

    def setup(self, master_password: str | Protected) -> None:
        with self._lock:
            if self.is_setup:
                raise KeyManagerError("key manager is already set up")
            pw = master_password if isinstance(master_password, Protected) \
                else Protected(master_password)
            root = generate_master_key()
            slot = Keyslot.new(Algorithm.XCHACHA20_POLY1305,
                               HashingAlgorithm.argon2id(), pw, root)
            self._store["root_slot"] = _slot_to_json(slot)
            self._root = root
            snap = self._snapshot()
        self._persist(snap)

    def unlock(self, master_password: str | Protected) -> None:
        with self._lock:
            if not self.is_setup:
                raise KeyManagerError("key manager is not set up")
            pw = master_password if isinstance(master_password, Protected) \
                else Protected(master_password)
            slot = _slot_from_json(self._store["root_slot"])
            try:
                self._root = slot.unseal(pw)
            except CryptoError as e:
                raise KeyManagerError("incorrect master password") from e
            # automount (updateAutomountStatus): flagged keys surface as
            # soon as the manager unlocks
            self._automount()

    def change_master_password(self, current: str | Protected,
                               new: str | Protected) -> None:
        """Re-seal the root key under a new master password (keymanager.rs
        change_master_password). Stored keys are untouched — they are
        sealed under the root key, which does not change."""
        with self._lock:
            self.unlock(current)  # verifies `current`, sets self._root
            pw = new if isinstance(new, Protected) else Protected(new)
            slot = Keyslot.new(Algorithm.XCHACHA20_POLY1305,
                               HashingAlgorithm.argon2id(), pw, self._root)
            self._store["root_slot"] = _slot_to_json(slot)
            snap = self._snapshot()
        self._persist(snap)

    def clear_master_password(self) -> None:
        """Drop the in-memory root key WITHOUT unmounting keys: already-
        mounted keys keep working, but nothing new can be unsealed until
        the next unlock (keys.rs clearMasterPassword semantics)."""
        with self._lock:
            if self._root is not None:
                self._root.zeroize()
            self._root = None

    def lock(self) -> None:
        with self._lock:
            if self._root is not None:
                self._root.zeroize()
            self._root = None
            for key in self._mounted.values():
                key.zeroize()
            self._mounted.clear()

    # -- keyring auto-unlock -------------------------------------------------
    def _keyring_account(self) -> str:
        import hashlib

        tag = hashlib.sha256(str(self.store_path).encode()).hexdigest()[:16]
        return f"km-root:{tag}"

    def _default_keyring(self):
        from .keyring import default_store

        return default_store(self.store_path.parent)

    def _recorded_keyring(self):
        """The backend RECORDED at enable time — disable/try must talk to
        the store that actually holds the secret, not whatever
        default_store() resolves to today (backend availability can flip
        between runs: seccomp, containers)."""
        from .keyring import FileSecretStore, KernelKeyringStore

        name = self._store.get("auto_unlock")
        if name == "kernel-keyring":
            return KernelKeyringStore()
        if name == "file":
            return FileSecretStore(self.store_path.parent / "keyring.json")
        return self._default_keyring()

    def enable_auto_unlock(self, store=None) -> str:
        """Park the root secret in an OS-backed secret store (crates/crypto
        keys/keyring role) so this keystore auto-unlocks across process
        restarts without the master password and with no plaintext on
        disk. Returns the backend name."""
        import hashlib

        with self._lock:
            root = self._require_root()
            store = store or self._default_keyring()
            store.set(self._keyring_account(), root.expose())
            self._store["auto_unlock"] = store.name
            # check value: a stale/foreign keyring entry must never be
            # installed as the root (preimage-resistant, reveals nothing
            # about the random 256-bit key)
            self._store["auto_unlock_check"] = hashlib.sha256(
                b"sd-km-check|" + root.expose()).hexdigest()
            name = store.name
            snap = self._snapshot()
        self._persist(snap)
        return name

    def disable_auto_unlock(self, store=None) -> None:
        with self._lock:
            store = store or self._recorded_keyring()
            store.delete(self._keyring_account())
            self._store.pop("auto_unlock", None)
            self._store.pop("auto_unlock_check", None)
            snap = self._snapshot()
        self._persist(snap)

    def try_auto_unlock(self, store=None) -> bool:
        """Unlock from the secret store when enabled; False when the store
        has no (or a stale) secret — the password path still works."""
        with self._lock:
            if not self.is_setup or self.is_unlocked \
                    or not self._store.get("auto_unlock"):
                return False
            import hashlib

            store = store or self._recorded_keyring()
            secret = store.get(self._keyring_account())
            if not secret:
                return False
            check = hashlib.sha256(b"sd-km-check|" + secret).hexdigest()
            if check != self._store.get("auto_unlock_check"):
                return False  # stale/foreign entry: never install it
            self._root = Protected(secret)
            self._automount()
            return True

    def _automount(self) -> None:
        import logging

        for kid, rec in self._store["keys"].items():
            if rec.get("automount"):
                try:
                    self.mount(kid)
                except Exception:
                    # one corrupt key record (truncated base64, bad AEAD
                    # tag) must not make unlock itself fail
                    logging.getLogger(__name__).warning(
                        "automount failed for key %s", kid, exc_info=True)

    def _require_root(self) -> Protected:
        if self._root is None:
            raise KeyManagerError("key manager is locked")
        return self._root

    # -- stored keys ---------------------------------------------------------
    def add_key(self, name: str = "") -> str:
        """Create + persist a new random key sealed under the root key;
        returns its uuid (auto-mounted)."""
        with self._lock:
            root = self._require_root()
            key = generate_master_key()
            algorithm = Algorithm.XCHACHA20_POLY1305
            nonce = algorithm.generate_nonce()
            sealed = Encryptor.encrypt_bytes(root, nonce, algorithm, key.expose())
            kid = str(uuid_mod.uuid4())
            self._store["keys"][kid] = {
                "name": name, "algorithm": algorithm.value,
                "nonce": _b64(nonce), "key": _b64(sealed),
            }
            self._mounted[kid] = key
            snap = self._snapshot()
        self._persist(snap)
        return kid

    def mount(self, kid: str) -> None:
        with self._lock:
            root = self._require_root()
            rec = self._store["keys"].get(kid)
            if rec is None:
                raise KeyManagerError(f"no stored key {kid}")
            if kid in self._mounted:
                return
            self._mounted[kid] = Decryptor.decrypt_bytes(
                root, _unb64(rec["nonce"]), Algorithm(rec["algorithm"]),
                _unb64(rec["key"]))

    def unmount(self, kid: str) -> None:
        with self._lock:
            key = self._mounted.pop(kid, None)
            if key is not None:
                key.zeroize()

    def get_key(self, kid: str) -> Protected:
        with self._lock:
            if kid not in self._mounted:
                self.mount(kid)
            return self._mounted[kid]

    def delete_key(self, kid: str) -> None:
        with self._lock:
            self.unmount(kid)
            self._store["keys"].pop(kid, None)
            snap = self._snapshot()
        self._persist(snap)

    def unmount_all(self) -> int:
        with self._lock:
            n = len(self._mounted)
            for key in self._mounted.values():
                key.zeroize()
            self._mounted.clear()
            return n

    def list_keys(self) -> list[dict]:
        with self._lock:
            return [{"uuid": kid, "name": rec.get("name", ""),
                     "mounted": kid in self._mounted,
                     "automount": bool(rec.get("automount")),
                     "default": kid == self._store.get("default")}
                    for kid, rec in self._store["keys"].items()]

    def list_mounted(self) -> list[str]:
        with self._lock:
            return list(self._mounted)

    # -- default key / automount --------------------------------------------
    def set_default(self, kid: str) -> None:
        with self._lock:
            if kid not in self._store["keys"]:
                raise KeyManagerError(f"no stored key {kid}")
            self._store["default"] = kid
            snap = self._snapshot()
        self._persist(snap)

    def get_default(self) -> str | None:
        with self._lock:
            return self._store.get("default")

    def set_automount(self, kid: str, status: bool) -> None:
        with self._lock:
            rec = self._store["keys"].get(kid)
            if rec is None:
                raise KeyManagerError(f"no stored key {kid}")
            rec["automount"] = bool(status)
            snap = self._snapshot()
        self._persist(snap)

    # -- keystore backup / restore -------------------------------------------
    def backup_keystore(self, path: str | Path) -> int:
        """Copy the (everything-sealed) keystore out; returns key count."""
        with self._lock:
            payload = json.dumps(self._store, indent=1)
            count = len(self._store["keys"])
        Path(path).write_text(payload)
        return count

    def restore_keystore(self, path: str | Path,
                         password: str | Protected) -> int:
        """Merge keys from a backup keystore, verifying with THAT keystore's
        master password and re-sealing each key under our root key. Returns
        how many keys were imported (duplicates skipped)."""
        try:
            backup = json.loads(Path(path).read_text())
        except (OSError, json.JSONDecodeError) as e:
            raise KeyManagerError(f"unreadable backup: {e}") from e
        with self._lock:
            root = self._require_root()
            if not backup.get("root_slot"):
                raise KeyManagerError("backup has no root keyslot")
            pw = password if isinstance(password, Protected) \
                else Protected(password)
            try:
                their_root = _slot_from_json(backup["root_slot"]).unseal(pw)
            except (CryptoError, KeyError, ValueError) as e:
                raise KeyManagerError(
                    "incorrect backup master password") from e
            imported = 0
            for kid, rec in (backup.get("keys") or {}).items():
                if kid in self._store["keys"]:
                    continue
                try:
                    raw = Decryptor.decrypt_bytes(
                        their_root, _unb64(rec["nonce"]),
                        Algorithm(rec["algorithm"]), _unb64(rec["key"]))
                except (CryptoError, KeyError, ValueError):
                    continue  # damaged record: import the rest
                algorithm = Algorithm.XCHACHA20_POLY1305
                nonce = algorithm.generate_nonce()
                self._store["keys"][kid] = {
                    "name": rec.get("name", ""), "algorithm": algorithm.value,
                    "nonce": _b64(nonce),
                    "key": _b64(Encryptor.encrypt_bytes(
                        root, nonce, algorithm, raw.expose())),
                }
                raw.zeroize()
                imported += 1
            their_root.zeroize()
            snap = self._snapshot() if imported else None
        if snap is not None:
            self._persist(snap)
        return imported
