"""Password hashing (reference: crates/crypto/src/keys/hashing.rs:19-50).

Argon2id with the reference's exact parameter tiers (m_cost KiB, t=8, p=4:
Standard 131072 / Hardened 262144 / Paranoid 524288, hashing.rs:44-50) via
OpenSSL's Argon2id, and a clean-room BalloonBlake3 built on this repo's
spec-derived BLAKE3. Balloon in pure Python is slow, so its tiers scale the
space cost down by 64× relative to the reference's balloon params — the
algorithm shape (expand / mix with delta=3 dependencies / extract) matches
the published Balloon construction; Argon2id is the default everywhere.

A secret key (when provided) is mixed in as Argon2 secret / balloon key,
mirroring hashing.rs's optional SecretKey.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass

from ..objects import blake3_ref
from .primitives import KEY_LEN, Protected


class Params(enum.Enum):
    STANDARD = "standard"
    HARDENED = "hardened"
    PARANOID = "paranoid"


_ARGON2 = {  # (memory_cost KiB, iterations, lanes) — hashing.rs:44-50
    Params.STANDARD: (131_072, 8, 4),
    Params.HARDENED: (262_144, 8, 4),
    Params.PARANOID: (524_288, 8, 4),
}

_BALLOON = {  # (space_cost blocks, time_cost) — scaled-down tiers, see module doc
    Params.STANDARD: (2_048, 2),
    Params.HARDENED: (4_096, 2),
    Params.PARANOID: (8_192, 2),
}
_BALLOON_DELTA = 3


@dataclass(frozen=True)
class HashingAlgorithm:
    kind: str  # "Argon2id" | "BalloonBlake3"
    params: Params = Params.STANDARD

    @staticmethod
    def argon2id(params: Params = Params.STANDARD) -> "HashingAlgorithm":
        return HashingAlgorithm("Argon2id", params)

    @staticmethod
    def balloon_blake3(params: Params = Params.STANDARD) -> "HashingAlgorithm":
        return HashingAlgorithm("BalloonBlake3", params)

    def hash(self, password: Protected, salt: bytes,
             secret: Protected | None = None) -> Protected:
        if self.kind == "Argon2id":
            return _argon2id(password, salt, secret, self.params)
        if self.kind == "BalloonBlake3":
            return _balloon_blake3(password, salt, secret, self.params)
        raise ValueError(f"unknown hashing algorithm {self.kind}")

    # wire encoding for headers: 1 byte kind, 1 byte params
    def encode(self) -> bytes:
        kinds = {"Argon2id": 0, "BalloonBlake3": 1}
        tiers = {Params.STANDARD: 0, Params.HARDENED: 1, Params.PARANOID: 2}
        return bytes([kinds[self.kind], tiers[self.params]])

    @staticmethod
    def decode(raw: bytes) -> "HashingAlgorithm":
        kinds = {0: "Argon2id", 1: "BalloonBlake3"}
        tiers = {0: Params.STANDARD, 1: Params.HARDENED, 2: Params.PARANOID}
        return HashingAlgorithm(kinds[raw[0]], tiers[raw[1]])


def _argon2id(password: Protected, salt: bytes, secret: Protected | None,
              params: Params) -> Protected:
    from cryptography.hazmat.primitives.kdf.argon2 import Argon2id

    memory, iterations, lanes = _ARGON2[params]
    kdf = Argon2id(
        salt=salt, length=KEY_LEN, iterations=iterations, lanes=lanes,
        memory_cost=memory,
        secret=secret.expose() if secret is not None else None,
    )
    return Protected(kdf.derive(password.expose()))


def _balloon_blake3(password: Protected, salt: bytes, secret: Protected | None,
                    params: Params) -> Protected:
    """Balloon hashing (Boneh-Corrigan-Gibbs-Schechter) with BLAKE3 as H.
    Sequential-fill then time_cost mixing rounds with delta random-dependent
    blocks; extract is the last buffer block."""
    space, time_cost = _BALLOON[params]
    key = password.expose() + (secret.expose() if secret is not None else b"")

    def H(counter: int, *parts: bytes) -> bytes:
        buf = struct.pack("<Q", counter) + b"".join(parts)
        return blake3_ref.blake3(key + buf, KEY_LEN)

    counter = 0
    buf = [b""] * space
    buf[0] = H(counter, password.expose(), salt)
    counter += 1
    for i in range(1, space):
        buf[i] = H(counter, buf[i - 1])
        counter += 1
    for t in range(time_cost):
        for i in range(space):
            buf[i] = H(counter, buf[(i - 1) % space], buf[i])
            counter += 1
            for d in range(_BALLOON_DELTA):
                idx_block = H(counter, salt, struct.pack("<QQQ", t, i, d))
                counter += 1
                other = int.from_bytes(idx_block[:8], "little") % space
                buf[i] = H(counter, buf[i], buf[other])
                counter += 1
    return Protected(buf[space - 1])
