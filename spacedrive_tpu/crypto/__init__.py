"""sd-crypto equivalent: AEAD streams, password hashing, encrypted headers.

Clean-room counterpart of the reference's `crates/crypto` (4.8k LoC Rust):
same construction choices — XChaCha20Poly1305 / AES-256-GCM behind an LE31
STREAM, 1MiB blocks (primitives.rs:27), Argon2id / BalloonBlake3 password
hashing (keys/hashing.rs:19-50), magic-byte header with up to two keyslots
(header/file.rs, keyslot.rs) — implemented on Python's `cryptography`
primitives plus this repo's spec-derived BLAKE3 for key derivation. The
container format is this framework's own (the ecosystems are not
wire-compatible anyway); the capability surface matches.
"""

from .hashing import HashingAlgorithm, Params
from .header import FileHeader, Keyslot, MAGIC_BYTES
from .keymanager import KeyManager
from .primitives import (
    AEAD_TAG_LEN,
    BLOCK_LEN,
    ENCRYPTED_KEY_LEN,
    KEY_LEN,
    SALT_LEN,
    Protected,
    derive_key,
    generate_master_key,
    generate_nonce,
    generate_salt,
)
from .stream import Algorithm, Decryptor, Encryptor

__all__ = [
    "AEAD_TAG_LEN", "Algorithm", "BLOCK_LEN", "Decryptor", "ENCRYPTED_KEY_LEN",
    "Encryptor", "FileHeader", "HashingAlgorithm", "KEY_LEN", "KeyManager",
    "Keyslot", "MAGIC_BYTES", "Params", "Protected", "SALT_LEN", "derive_key",
    "generate_master_key", "generate_nonce", "generate_salt",
]
