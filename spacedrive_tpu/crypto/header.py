"""Encrypted-file header + keyslots (reference: crates/crypto/src/header/).

Layout (all little-endian, fixed-size prefix so the AAD and payload offset
are computable without parsing variable data):

    magic        7  b"sdtpenc"            (reference: 7-byte magic, file.rs:49)
    version      2  u16 = 1
    algorithm    1  Algorithm enum
    nonce       20  stream nonce, zero-padded to the max nonce length
    [AAD boundary — everything above authenticates every payload block 0]
    keyslots  2×112 fixed keyslot area (keyslot.rs KEYSLOT_SIZE=112)
    metadata     TLV: u8 present, then nonce(20) + u32 len + AEAD blob
    preview      TLV: same shape

A keyslot seals the master key under a KEK derived from the hashed password:
hash = HashingAlgorithm.hash(password, content_salt); KEK = BLAKE3
derive_key(hash ‖ salt, FILE_KEY_CONTEXT) — the two-salt scheme of
keyslot.rs:60-90. Two keyslots maximum (file.rs:83).
"""

from __future__ import annotations

import io
import json
import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO

from .hashing import HashingAlgorithm
from .primitives import (
    ENCRYPTED_KEY_LEN,
    FILE_KEY_CONTEXT,
    SALT_LEN,
    Protected,
    derive_key,
    generate_salt,
)
from .stream import Algorithm, CryptoError, Decryptor, Encryptor

MAGIC_BYTES = b"sdtpenc"
HEADER_VERSION = 1
KEYSLOT_SIZE = 112
MAX_KEYSLOTS = 2
_NONCE_PAD = 20


def _pad_nonce(nonce: bytes) -> bytes:
    return nonce + b"\x00" * (_NONCE_PAD - len(nonce))


@dataclass
class Keyslot:
    version: int
    algorithm: Algorithm
    hashing_algorithm: HashingAlgorithm
    salt: bytes           # KEK-derivation salt
    content_salt: bytes   # password-hashing salt
    master_key: bytes     # ENCRYPTED_KEY_LEN bytes (sealed)
    nonce: bytes

    @classmethod
    def new(cls, algorithm: Algorithm, hashing_algorithm: HashingAlgorithm,
            password: Protected, master_key: Protected,
            content_salt: bytes | None = None,
            secret: Protected | None = None) -> "Keyslot":
        """keyslot.rs Keyslot::new — hash the password, derive the KEK,
        seal the master key."""
        content_salt = content_salt or generate_salt()
        salt = generate_salt()
        nonce = algorithm.generate_nonce()
        hashed = hashing_algorithm.hash(password, content_salt, secret)
        kek = Protected(derive_key(hashed.expose(), salt, FILE_KEY_CONTEXT))
        hashed.zeroize()
        sealed = Encryptor.encrypt_bytes(kek, nonce, algorithm, master_key.expose())
        kek.zeroize()
        return cls(1, algorithm, hashing_algorithm, salt, content_salt,
                   sealed, nonce)

    def unseal(self, password: Protected,
               secret: Protected | None = None) -> Protected:
        hashed = self.hashing_algorithm.hash(password, self.content_salt, secret)
        kek = Protected(derive_key(hashed.expose(), self.salt, FILE_KEY_CONTEXT))
        hashed.zeroize()
        out = Decryptor.decrypt_bytes(kek, self.nonce, self.algorithm,
                                      self.master_key)
        kek.zeroize()
        return out

    def encode(self) -> bytes:
        raw = struct.pack("<HB", self.version, self.algorithm.value) \
            + self.hashing_algorithm.encode() \
            + self.salt + self.content_salt \
            + _pad_nonce(self.nonce) + self.master_key
        assert len(raw) <= KEYSLOT_SIZE, len(raw)
        return raw + b"\x00" * (KEYSLOT_SIZE - len(raw))

    @classmethod
    def decode(cls, raw: bytes) -> "Keyslot | None":
        if not any(raw):
            return None
        version, algo = struct.unpack_from("<HB", raw, 0)
        hashing = HashingAlgorithm.decode(raw[3:5])
        off = 5
        salt = raw[off:off + SALT_LEN]; off += SALT_LEN
        content_salt = raw[off:off + SALT_LEN]; off += SALT_LEN
        algorithm = Algorithm(algo)
        nonce = raw[off:off + algorithm.nonce_len]; off += _NONCE_PAD
        master_key = raw[off:off + ENCRYPTED_KEY_LEN]
        return cls(version, algorithm, hashing, salt, content_salt,
                   master_key, nonce)


@dataclass
class FileHeader:
    version: int
    algorithm: Algorithm
    nonce: bytes
    keyslots: list[Keyslot] = field(default_factory=list)
    metadata: bytes | None = None        # sealed blob: nonce ‖ ciphertext
    preview_media: bytes | None = None   # sealed blob: nonce ‖ ciphertext

    @classmethod
    def new(cls, algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305) -> "FileHeader":
        return cls(HEADER_VERSION, algorithm, algorithm.generate_nonce())

    # -- keyslots ------------------------------------------------------------
    def add_keyslot(self, password: Protected, master_key: Protected,
                    hashing_algorithm: HashingAlgorithm | None = None,
                    content_salt: bytes | None = None,
                    secret: Protected | None = None) -> None:
        if len(self.keyslots) >= MAX_KEYSLOTS:
            raise CryptoError("header already has the maximum of 2 keyslots")
        self.keyslots.append(Keyslot.new(
            self.algorithm, hashing_algorithm or HashingAlgorithm.argon2id(),
            password, master_key, content_salt, secret))

    def decrypt_master_key(self, password: Protected,
                           secret: Protected | None = None) -> Protected:
        """Try each keyslot (file.rs decrypt_master_key): wrong passwords
        surface as a single IncorrectPassword-style error."""
        for slot in self.keyslots:
            try:
                return slot.unseal(password, secret)
            except CryptoError:
                continue
        raise CryptoError("incorrect password (no keyslot matched)")

    # -- optional sealed attachments (header/metadata.rs, preview_media.rs) --
    def add_metadata(self, master_key: Protected, obj: Any) -> None:
        nonce = self.algorithm.generate_nonce()
        blob = Encryptor.encrypt_bytes(
            master_key, nonce, self.algorithm,
            json.dumps(obj, separators=(",", ":")).encode(), self.aad())
        self.metadata = _pad_nonce(nonce) + blob

    def decrypt_metadata(self, master_key: Protected) -> Any:
        if self.metadata is None:
            raise CryptoError("header has no metadata")
        nonce = self.metadata[:self.algorithm.nonce_len]
        out = Decryptor.decrypt_bytes(master_key, nonce, self.algorithm,
                                      self.metadata[_NONCE_PAD:], self.aad())
        return json.loads(out.expose().decode())

    def add_preview_media(self, master_key: Protected, media: bytes) -> None:
        nonce = self.algorithm.generate_nonce()
        blob = Encryptor.encrypt_bytes(master_key, nonce, self.algorithm,
                                       media, self.aad())
        self.preview_media = _pad_nonce(nonce) + blob

    def decrypt_preview_media(self, master_key: Protected) -> bytes:
        if self.preview_media is None:
            raise CryptoError("header has no preview media")
        nonce = self.preview_media[:self.algorithm.nonce_len]
        return Decryptor.decrypt_bytes(master_key, nonce, self.algorithm,
                                       self.preview_media[_NONCE_PAD:],
                                       self.aad()).expose()

    # -- serialization -------------------------------------------------------
    def aad(self) -> bytes:
        """The authenticated fixed prefix (file.rs generate_aad): bound to
        payload block 0 and to metadata/preview blobs."""
        return (MAGIC_BYTES + struct.pack("<HB", self.version, self.algorithm.value)
                + _pad_nonce(self.nonce))

    def serialize(self) -> bytes:
        out = bytearray(self.aad())
        slots = list(self.keyslots)[:MAX_KEYSLOTS]
        for slot in slots:
            out += slot.encode()
        for _ in range(MAX_KEYSLOTS - len(slots)):
            out += b"\x00" * KEYSLOT_SIZE
        for blob in (self.metadata, self.preview_media):
            if blob is None:
                out += b"\x00"
            else:
                out += b"\x01" + struct.pack("<I", len(blob)) + blob
        return bytes(out)

    def write(self, writer: BinaryIO) -> int:
        raw = self.serialize()
        writer.write(raw)
        return len(raw)

    @classmethod
    def from_reader(cls, reader: BinaryIO) -> "FileHeader":
        magic = reader.read(len(MAGIC_BYTES))
        if magic != MAGIC_BYTES:
            raise CryptoError("not an encrypted file (bad magic)")
        # truncated/corrupt headers surface as CryptoError — callers
        # (decrypt job per-file error handling, cli inspect) catch exactly
        # that, never struct.error/KeyError/ValueError from the guts
        try:
            version, algo = struct.unpack("<HB", reader.read(3))
            if version != HEADER_VERSION:
                raise CryptoError(f"unsupported header version {version}")
            algorithm = Algorithm(algo)
            nonce = reader.read(_NONCE_PAD)[:algorithm.nonce_len]
            keyslots = []
            for _ in range(MAX_KEYSLOTS):
                slot = Keyslot.decode(reader.read(KEYSLOT_SIZE))
                if slot is not None:
                    keyslots.append(slot)
            blobs: list[bytes | None] = []
            for _ in range(2):
                present = reader.read(1)
                if present == b"\x01":
                    (length,) = struct.unpack("<I", reader.read(4))
                    if length > 64 * 1024 * 1024:
                        raise CryptoError("header attachment too large")
                    blobs.append(reader.read(length))
                else:
                    blobs.append(None)
        except (struct.error, KeyError, ValueError, IndexError) as e:
            raise CryptoError(f"corrupt encrypted-file header: {e}") from e
        return cls(version, algorithm, nonce, keyslots, blobs[0], blobs[1])

    @classmethod
    def from_bytes(cls, raw: bytes) -> tuple["FileHeader", int]:
        buf = io.BytesIO(raw)
        header = cls.from_reader(buf)
        return header, buf.tell()
