"""Crypto constants + small helpers (reference: crates/crypto/src/primitives.rs)."""

from __future__ import annotations

import secrets

from ..objects import blake3_ref

#: streaming block size — 1 MiB (primitives.rs:27)
BLOCK_LEN = 1_048_576
#: Poly1305/GCM tag length (primitives.rs:30)
AEAD_TAG_LEN = 16
#: master keys are 32 bytes (primitives.rs:36)
KEY_LEN = 32
#: encrypted master key = key + tag (primitives.rs:33)
ENCRYPTED_KEY_LEN = KEY_LEN + AEAD_TAG_LEN
#: salt length (primitives.rs:19)
SALT_LEN = 16
#: secret-key length (primitives.rs:22)
SECRET_KEY_LEN = 18

#: domain-separation contexts for key derivation (primitives.rs:61-68; ours —
#: a clean-room format needs its own domains)
ROOT_KEY_CONTEXT = "spacedrive_tpu 2026-07-29 root key derivation"
MASTER_PASSWORD_CONTEXT = "spacedrive_tpu 2026-07-29 master password verification"
FILE_KEY_CONTEXT = "spacedrive_tpu 2026-07-29 file key derivation"


class Protected:
    """Best-effort zeroizing secret wrapper (reference protected.rs). Python
    cannot guarantee erasure of immutable bytes, so secrets are held in a
    mutable bytearray wiped on ``zeroize()``/GC, and ``repr`` never leaks."""

    __slots__ = ("_buf",)

    def __init__(self, value: bytes | bytearray | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._buf = bytearray(value)

    def expose(self) -> bytes:
        return bytes(self._buf)

    def zeroize(self) -> None:
        for i in range(len(self._buf)):
            self._buf[i] = 0
        self._buf.clear()

    def __len__(self) -> int:
        return len(self._buf)

    def __repr__(self) -> str:
        return "Protected(<redacted>)"

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Protected):
            return secrets.compare_digest(bytes(self._buf), bytes(other._buf))
        return NotImplemented

    def __del__(self) -> None:
        try:
            self.zeroize()
        except Exception:
            pass


def generate_master_key() -> Protected:
    return Protected(secrets.token_bytes(KEY_LEN))


def generate_salt() -> bytes:
    return secrets.token_bytes(SALT_LEN)


def generate_secret_key() -> Protected:
    return Protected(secrets.token_bytes(SECRET_KEY_LEN))


def generate_nonce(length: int) -> bytes:
    return secrets.token_bytes(length)


def derive_key(key: bytes, salt: bytes, context: str) -> bytes:
    """``Key::derive`` (keyslot.rs KEK derivation): BLAKE3 derive_key over
    key‖salt under a domain-separation context."""
    return blake3_ref.derive_key(context, key + salt, KEY_LEN)
