"""Boot-time crash recovery: DB integrity gate + the repair ladder.

The library SQLite file is the one artifact the whole system cannot
regenerate, and nothing used to look at it between "the process died" and
"the next scan wrote into it". This module is the boot-order gate
(``Libraries._load`` runs it BEFORE the model layer opens the file):

1. **WAL recovery** — opening the database replays a leftover ``-wal``
   sidecar (SQLite's own crash recovery); a non-empty sidecar at boot is
   counted (``sd_boot_integrity_wal_recovered_total``) so operators can
   see how often nodes die with un-checkpointed work.
2. **`PRAGMA quick_check`** — structural validation on a throwaway
   read-only-intent connection. Passing costs milliseconds on healthy
   files and is the gate for everything after it.
3. **Repair ladder on corruption** — quarantine the damaged file (plus
   WAL/SHM sidecars) under ``libraries/quarantine/``, then restore the
   newest VALID backup of that library (validated tarball + matching
   header ``library_id``, backups.py). No backup → the library comes up
   with a fresh empty DB next to its quarantined remains. Either way the
   node BOOTS — corruption is a repair event with telemetry and a stock
   alert (``db-quick-check-failed``), never a boot failure.

Disk-full accounting also lives here: every graceful-degradation site
(gather quarantine, committer checkpoint-pause, thumbnail skip, trace
export falling back to the in-memory ring, backup failure) reports
through :func:`note_disk_full`, so ``sd_recovery_disk_full_total{site}``
is the one series that says "this node is out of disk" regardless of
which subsystem hit ENOSPC first.
"""

from __future__ import annotations

import errno
import logging
import sqlite3
import time
from pathlib import Path
from typing import Any

from . import telemetry

logger = logging.getLogger(__name__)

_BOOT_CHECKS = telemetry.counter(
    "sd_boot_integrity_checks_total",
    "boot-time library DB integrity checks by outcome",
    labels=("outcome",))
_WAL_RECOVERED = telemetry.counter(
    "sd_boot_integrity_wal_recovered_total",
    "boots that found (and replayed) a non-empty WAL sidecar")
_CHECK_SECONDS = telemetry.histogram(
    "sd_boot_integrity_check_seconds",
    "latency of one boot-time quick_check pass")
_REPAIRS = telemetry.counter(
    "sd_recovery_repairs_total",
    "repair-ladder actions taken on a corrupt library DB",
    labels=("action",))
_COLD_RESUMED = telemetry.counter(
    "sd_recovery_cold_resumed_jobs_total",
    "interrupted jobs revived from their checkpoints at boot")
_DISK_FULL = telemetry.counter(
    "sd_recovery_disk_full_total",
    "ENOSPC hits absorbed by graceful degradation, per site",
    labels=("site",))


def is_disk_full(exc: BaseException) -> bool:
    """ENOSPC (and the quota-equivalent EDQUOT): the disk is full. Not
    transient — retrying cannot free space — but never fatal either: every
    wired seam degrades (quarantine / skip / pause / ring-only). SQLite
    reports the same condition as its own ``OperationalError`` (SQLITE_FULL,
    "database or disk is full") rather than an OSError — a real full disk
    mid-commit surfaces THAT way, so it must classify identically."""
    if isinstance(exc, OSError) and exc.errno in (
            errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC)):
        return True
    return (isinstance(exc, sqlite3.OperationalError)
            and "disk is full" in str(exc).lower())


def note_disk_full(site: str) -> None:
    """Record one absorbed ENOSPC at ``site`` (gather | commit | thumbnail
    | trace_export | backup | config) — counter + flight-recorder event."""
    _DISK_FULL.inc(site=site)
    telemetry.event("disk.full", site=site)


def note_cold_resumed(count: int = 1) -> None:
    if count > 0:
        _COLD_RESUMED.inc(count)


def quick_check_file(db_path: str | Path) -> list[str]:
    """``PRAGMA quick_check`` on a throwaway connection; ``[]`` = sound.
    An unopenable/not-a-database file reports as a single problem row
    instead of raising — the caller treats both identically (corrupt)."""
    try:
        conn = sqlite3.connect(db_path, timeout=10.0)
        try:
            rows = conn.execute("PRAGMA quick_check").fetchall()
        finally:
            conn.close()
    except sqlite3.Error as e:
        return [f"unopenable: {e}"]
    problems = [r[0] for r in rows]
    return [] if problems == ["ok"] else problems


def _quarantine(libraries_dir: Path, lib_id: str) -> Path | None:
    """Move the damaged DB (+ sidecars) into ``libraries/quarantine/`` so
    the evidence survives the repair; returns the quarantined DB path."""
    import os

    qdir = libraries_dir / "quarantine"
    qdir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    moved: Path | None = None
    for suffix in (".db", ".db-wal", ".db-shm"):
        src = libraries_dir / f"{lib_id}{suffix}"
        if not src.exists():
            continue
        dest = qdir / f"{lib_id}{suffix}.corrupt-{stamp}"
        n = 0
        while dest.exists():  # same-second double corruption in tests
            n += 1
            dest = qdir / f"{lib_id}{suffix}.corrupt-{stamp}.{n}"
        os.replace(src, dest)
        if suffix == ".db":
            moved = dest
    return moved


def ensure_library_integrity(libraries_dir: str | Path, lib_id: str,
                             backups_path: str | Path | None = None,
                             node: Any = None) -> dict[str, Any]:
    """The boot gate for one library DB; runs BEFORE the model layer opens
    the file. Returns a verdict dict (``outcome`` ∈ ok | missing | repaired
    | fresh) — and never raises: a corrupt DB becomes a repair, not a boot
    failure."""
    libraries_dir = Path(libraries_dir)
    db_path = libraries_dir / f"{lib_id}.db"
    if not db_path.exists():
        return {"outcome": "missing"}

    wal = libraries_dir / f"{lib_id}.db-wal"
    wal_pending = wal.exists() and wal.stat().st_size > 0

    t0 = time.perf_counter()
    problems = quick_check_file(db_path)
    _CHECK_SECONDS.observe(time.perf_counter() - t0)

    if not problems:
        _BOOT_CHECKS.inc(outcome="ok")
        if wal_pending:
            # quick_check's connection already replayed the WAL — the
            # interrupted process's durable-but-uncheckpointed work made it
            _WAL_RECOVERED.inc()
        return {"outcome": "ok", "wal_recovered": wal_pending}

    _BOOT_CHECKS.inc(outcome="corrupt")
    telemetry.event("db.quick_check_failed", library=lib_id,
                    problems=problems[:4])
    logger.error("library %s failed quick_check (%d problem(s): %s); "
                 "entering the repair ladder", lib_id[:8], len(problems),
                 problems[:2])
    quarantined = _quarantine(libraries_dir, lib_id)
    _REPAIRS.inc(action="quarantine")

    backup: Path | None = None
    if backups_path is not None and Path(backups_path).is_dir():
        from .backups import find_latest_backup

        backup = find_latest_backup(backups_path, lib_id)
    if backup is not None:
        try:
            from .backups import restore_files

            # find_latest_backup already ran the full validation walk on
            # this path — don't pay the gzip-CRC drain a second time
            restore_files(backup, lib_id, libraries_dir, pre_validated=True)
            _REPAIRS.inc(action="restore_backup")
            telemetry.event("db.restored_from_backup", library=lib_id,
                            backup=str(backup))
            logger.warning("library %s restored from backup %s "
                           "(damaged file kept at %s)", lib_id[:8],
                           backup.name, quarantined)
            _notify(node, lib_id, "restored_from_backup", str(backup))
            return {"outcome": "repaired", "backup": str(backup),
                    "quarantined": str(quarantined) if quarantined else None}
        except Exception:
            logger.exception("restore from %s failed; library %s starts "
                             "with a fresh DB", backup, lib_id[:8])
    _REPAIRS.inc(action="fresh_db")
    logger.warning("library %s has no restorable backup; starting with a "
                   "fresh DB (damaged file kept at %s)", lib_id[:8],
                   quarantined)
    _notify(node, lib_id, "fresh_db", None)
    return {"outcome": "fresh",
            "quarantined": str(quarantined) if quarantined else None}


def _notify(node: Any, lib_id: str, action: str,
            backup: str | None) -> None:
    """Loud surface for a repair (best-effort: notifications must never
    block a boot that is already recovering from corruption)."""
    if node is None:
        return
    try:
        from .notifications import emit_node_notification

        emit_node_notification(node, {
            "kind": "library_db_repaired", "library_id": lib_id,
            "action": action, "backup": backup})
    except Exception:
        logger.exception("repair notification could not be emitted")
