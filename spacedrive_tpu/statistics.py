"""Library statistics (the `library.statistics` procedure's backing store).

Parity with the reference's Statistics model (schema.prisma:99) + the
update-on-query pattern of api/libraries.rs:47: counts come from the library
DB, capacity from the volume the data dir lives on. Byte counters are stored
as TEXT to match the reference's schema (u64-in-string workaround) even
though SQLite INTEGER would hold them.

Split (ISSUE 15 satellite, serve rung a): :func:`compute_statistics` is a
PURE READER over ``(db, data_dir)`` — exactly the surface a serve-pool
worker holds — so the ``libraries.statistics`` handler runs ``pool=True``
under the worker-purity lint. :func:`update_statistics` (compute + persist
the snapshot row) remains for write-capable callers.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import TYPE_CHECKING, Any

from .models import Statistics, utc_now
from .volumes import volume_for_path

if TYPE_CHECKING:
    from .library import Library


def compute_statistics(db, data_dir: str | Path) -> dict[str, Any]:
    """Read-only statistics over a library DB + the node data dir. Safe
    on a serve-pool worker's ``Database(readonly=True)`` handle — no
    write surface, no node backrefs."""
    total_objects = db.query("SELECT COUNT(*) n FROM object")[0]["n"]
    totals = db.query(
        "SELECT COALESCE(SUM(size_in_bytes),0) s FROM file_path WHERE is_dir=0")[0]["s"]
    unique = db.query(
        "SELECT COALESCE(SUM(sz),0) s FROM (SELECT MIN(size_in_bytes) sz "
        "FROM file_path WHERE cas_id IS NOT NULL GROUP BY cas_id)")[0]["s"]
    try:
        db_size = os.path.getsize(db.path)
    except OSError:
        db_size = 0
    vol = volume_for_path(str(data_dir)) or {}
    return {
        "date_captured": utc_now(),
        "total_object_count": total_objects,
        "library_db_size": str(db_size),
        "total_bytes_used": str(totals),
        "total_unique_bytes": str(unique),
        "total_bytes_capacity": str(vol.get("total_capacity", 0)),
        "total_bytes_free": str(vol.get("available_capacity", 0)),
        "preview_media_bytes": str(_thumb_dir_size(data_dir)),
    }


def update_statistics(library: "Library") -> dict[str, Any]:
    """Compute + persist the Statistics snapshot row (write-capable
    callers only — the pool-pure query path uses compute_statistics;
    backups.do_backup persists an as-of snapshot through here)."""
    node = library.node
    data_dir = node.data_dir if node is not None \
        else Path(os.path.dirname(str(library.db.path)))
    row = compute_statistics(library.db, data_dir)
    db = library.db
    existing = db.find(Statistics, limit=1)
    if existing:
        db.update(Statistics, {"id": existing[0]["id"]}, row)
        row["id"] = existing[0]["id"]
    else:
        row["id"] = db.insert(Statistics, row)
    return row


def _thumb_dir_size(data_dir: str | Path) -> int:
    thumb_dir = Path(data_dir) / "thumbnails"
    total = 0
    if thumb_dir.is_dir():
        for dirpath, _dirs, files in os.walk(thumb_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    pass
    return total
