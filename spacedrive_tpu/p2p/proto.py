"""Wire protocol: framing, headers, sync-session and spaceblock messages.

Message surface mirrors the reference's:

- ``Header`` discriminators follow core/src/p2p/protocol.rs:13-27
  (0=Spacedrop, 1=Ping, 2=Pair, 3=Sync, 4=File, 5=Connected);
- sync sessions speak ``SyncMessage::NewOperations`` (core/src/p2p/sync/
  proto.rs), then a responder-driven ``MainRequest::GetOperations(GetOpsArgs)``
  / ``Operations`` pull loop (core/src/p2p/sync/mod.rs:257-440);
- spaceblock messages (Block/Cancelled) per crates/p2p/src/spaceblock/mod.rs.

Encoding is deliberately simple and debuggable: a 1-byte discriminator where
the reference has one, and u32-length-prefixed JSON frames for structured
payloads (the CRDT ops are already JSON-shaped on our wire; rmp adds nothing
on a LAN control plane). Block payloads are raw bytes after a fixed header —
never JSON.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Any

MAX_FRAME = 64 << 20  # defensive bound for a control-plane frame


class ProtocolError(Exception):
    pass


# -- framing -----------------------------------------------------------------

async def read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    try:
        return await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        raise ProtocolError(f"stream closed mid-read ({len(e.partial)}/{n})") from e


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    n = int.from_bytes(await read_exact(reader, 4), "big")
    if n > MAX_FRAME:
        raise ProtocolError(f"frame too large: {n}")
    return await read_exact(reader, n)


def frame(payload: bytes) -> bytes:
    return len(payload).to_bytes(4, "big") + payload


async def read_json(reader: asyncio.StreamReader) -> Any:
    return json.loads((await read_frame(reader)).decode())


async def read_json_sized(reader: asyncio.StreamReader) -> tuple[Any, int]:
    """(decoded frame, wire byte length) — the receive-path admission
    budget accounts bytes from the actual frame size, not an estimate."""
    raw = await read_frame(reader)
    return json.loads(raw.decode()), len(raw)


def json_frame(obj: Any) -> bytes:
    return frame(json.dumps(obj, separators=(",", ":")).encode())


# -- spaceblock requests -----------------------------------------------------

BLOCK_SIZES = tuple(1 << p for p in range(10, 28))  # 1KiB..128MiB


def block_size_for(file_size: int) -> int:
    """Power-of-two block size scaled to the transfer (block_size.rs:
    from_size). Small files move in one block; big ones in 128KiB+ blocks
    so progress events stay frequent without drowning in framing."""
    for size in BLOCK_SIZES:
        if file_size <= size * 256:
            return size
    return BLOCK_SIZES[-1]


@dataclass(frozen=True)
class Range:
    """Full file or byte sub-range [start, end) (spaceblock sb_request Range)."""

    start: int = 0
    end: int | None = None  # None = to EOF

    def to_wire(self) -> list:
        return [self.start, self.end]

    @classmethod
    def from_wire(cls, v: Any) -> "Range":
        if not v:
            return cls()
        return cls(int(v[0]), None if v[1] is None else int(v[1]))


@dataclass(frozen=True)
class SpaceblockRequest:
    """Offer/request metadata preceding a block transfer
    (spaceblock/sb_request.rs)."""

    name: str
    size: int
    block_size: int
    range: Range = field(default_factory=Range)

    def to_wire(self) -> dict:
        return {"name": self.name, "size": self.size,
                "block_size": self.block_size, "range": self.range.to_wire()}

    @classmethod
    def from_wire(cls, v: dict) -> "SpaceblockRequest":
        return cls(str(v["name"]), int(v["size"]), int(v["block_size"]),
                   Range.from_wire(v.get("range")))


# -- headers (protocol.rs:13-27) --------------------------------------------

H_SPACEDROP = 0
H_PING = 1
H_PAIR = 2
H_SYNC = 3
H_FILE = 4
H_CONNECTED = 5
H_THUMBNAIL = 6
H_HASH = 7
H_DELTA = 8
H_QUERY = 9


@dataclass(frozen=True)
class Header:
    kind: int
    payload: Any = None  # kind-specific

    # constructors ---------------------------------------------------------
    @classmethod
    def ping(cls) -> "Header":
        return cls(H_PING)

    @classmethod
    def pair(cls) -> "Header":
        return cls(H_PAIR)

    @classmethod
    def sync(cls, library_id: str) -> "Header":
        return cls(H_SYNC, library_id)

    @classmethod
    def spacedrop(cls, req: SpaceblockRequest) -> "Header":
        return cls(H_SPACEDROP, req)

    @classmethod
    def file(cls, library_id: str, file_path_pub_id: str, rng: Range) -> "Header":
        return cls(H_FILE, {"library_id": library_id,
                            "file_path_pub_id": file_path_pub_id,
                            "range": rng.to_wire()})

    @classmethod
    def connected(cls, identities: list[str]) -> "Header":
        return cls(H_CONNECTED, identities)

    @classmethod
    def thumbnail(cls, library_id: str, cas_id: str) -> "Header":
        """Fetch a member library's cached preview by cas_id — the on-demand
        form of the reference's sync_preview_media location knob."""
        return cls(H_THUMBNAIL, {"library_id": library_id, "cas_id": cas_id})

    @classmethod
    def hash_batch(cls, sizes: list[int],
                   ctx: dict | None = None) -> "Header":
        """Shared-hasher request (BASELINE config 5): ``sizes[i]`` bytes of
        pre-gathered cas message follow the header for each item; the peer
        replies with the cas_ids. ``ctx`` is an optional trace-context
        envelope (telemetry/mesh.py) so the server's hash-serve span
        parents under the requesting job's trace."""
        payload: dict = {"sizes": sizes}
        if ctx is not None:
            payload["ctx"] = ctx
        return cls(H_HASH, payload)

    @classmethod
    def delta(cls, transfer_id: str, name: str, size: int,
              chunks: list[list]) -> "Header":
        """Delta spacedrop offer (ISSUE 18): the sender's full chunk
        manifest (``[[chunk_hash, length], ...]`` in file order, ops/cdc.py
        geometry) rides the header; the receiver answers with the chunk
        hashes it already holds, and only the missing ones cross the wire
        as spaceblock block messages."""
        return cls(H_DELTA, {"transfer_id": transfer_id, "name": name,
                             "size": size, "chunks": chunks})

    @classmethod
    def query(cls, library_id: str, key: str, arg: Any,
              require: dict[str, int], ctx: dict | None = None) -> "Header":
        """Replica query dispatch (ISSUE 19): run the pool-marked rspc
        query ``key`` against the peer's replica of ``library_id``.
        ``require`` is the client's applied per-instance HLC clock map —
        the watermark the replica must cover to be eligible; a replica
        behind it answers NOT_ELIGIBLE, never a stale row. ``ctx`` is the
        optional trace-context envelope (telemetry/mesh.py)."""
        payload: dict = {"library_id": library_id, "key": key, "arg": arg,
                         "require": require}
        if ctx is not None:
            payload["ctx"] = ctx
        return cls(H_QUERY, payload)

    # wire -----------------------------------------------------------------
    def to_bytes(self) -> bytes:
        b = bytes([self.kind])
        if self.kind == H_PING:
            return b
        if self.kind == H_PAIR:
            return b
        if self.kind == H_SYNC:
            return b + json_frame(self.payload)
        if self.kind == H_SPACEDROP:
            return b + json_frame(self.payload.to_wire())
        if self.kind in (H_FILE, H_CONNECTED, H_THUMBNAIL, H_HASH, H_DELTA,
                         H_QUERY):
            return b + json_frame(self.payload)
        raise ProtocolError(f"unknown header kind {self.kind}")

    @classmethod
    async def from_stream(cls, reader: asyncio.StreamReader) -> "Header":
        kind = (await read_exact(reader, 1))[0]
        if kind in (H_PING, H_PAIR):
            return cls(kind)
        if kind == H_SYNC:
            return cls(kind, str(await read_json(reader)))
        if kind == H_SPACEDROP:
            return cls(kind, SpaceblockRequest.from_wire(await read_json(reader)))
        if kind in (H_FILE, H_CONNECTED, H_THUMBNAIL, H_HASH, H_DELTA,
                    H_QUERY):
            return cls(kind, await read_json(reader))
        raise ProtocolError(f"invalid header discriminator {kind}")


# -- sync session messages ---------------------------------------------------

SYNC_NEW_OPERATIONS = b"N"  # SyncMessage::NewOperations (sync/proto.rs)


def main_request_get_operations(clocks: dict[str, int], count: int) -> bytes:
    """Responder → originator: GetOpsArgs pull (sync/mod.rs responder loop)."""
    return json_frame({"req": "get_ops", "clocks": clocks, "count": count})


def main_request_done() -> bytes:
    return json_frame({"req": "done"})


def main_request_busy(retry_after_ms: int,
                      watermark: dict[str, int]) -> bytes:
    """Responder → originator: admission control shed this window.
    ``watermark`` is the responder's DURABLE per-instance clocks — an
    explicit acknowledgment of everything applied so far, so the
    originator resumes from it after ``retry_after_ms`` instead of
    restarting the push (docs/architecture/robustness.md, "Overload &
    admission control")."""
    return json_frame({"req": "busy", "retry_after_ms": int(retry_after_ms),
                       "watermark": watermark})


def operations_frame(ops: list[dict], has_more: bool,
                     ctx: dict | None = None) -> bytes:
    """Originator → responder: one batch of wire ops. ``ctx`` is the
    optional trace-context envelope (telemetry/mesh.py): trace_id, the
    sender-side span serving this window, the sender's HLC watermark and
    declared remaining backlog — what stitches cross-node traces and
    feeds the receiver's convergence-lag gauges."""
    payload: dict = {"ops": ops, "has_more": has_more}
    if ctx is not None:
        payload["ctx"] = ctx
    return json_frame(payload)


# -- spaceblock stream messages ---------------------------------------------

MSG_BLOCK = 0
MSG_CANCELLED = 1


def block_msg(offset: int, data: bytes) -> bytes:
    return (bytes([MSG_BLOCK]) + offset.to_bytes(8, "big")
            + len(data).to_bytes(4, "big") + data)


def cancel_msg() -> bytes:
    return bytes([MSG_CANCELLED])


async def read_block_msg(reader: asyncio.StreamReader) -> tuple[int, bytes] | None:
    """Returns (offset, data) or None for Cancelled."""
    kind = (await read_exact(reader, 1))[0]
    if kind == MSG_CANCELLED:
        return None
    if kind != MSG_BLOCK:
        raise ProtocolError(f"invalid spaceblock discriminator {kind}")
    offset = int.from_bytes(await read_exact(reader, 8), "big")
    n = int.from_bytes(await read_exact(reader, 4), "big")
    if n > MAX_FRAME:
        raise ProtocolError(f"block too large: {n}")
    return offset, await read_exact(reader, n)
