"""Pure-Python Ed25519 (RFC 8032) — the dependency-gated fallback keypair.

``p2p/identity.py`` prefers ``cryptography``'s libsodium-class ed25519; on
images without the package this reference implementation keeps instance
identities working (library create, pairing metadata, challenge-response
auth) instead of wedging every import of the p2p package. It is the RFC 8032
reference algorithm on the twisted Edwards curve in extended homogeneous
coordinates — a few ms per sign/verify, which identity creation and stream
handshakes tolerate; bulk crypto never routes through here.

Interop: byte-compatible with any RFC 8032 implementation (same seeds →
same public keys and signatures), so a fallback node pairs cleanly with a
``cryptography``-backed one.
"""

from __future__ import annotations

import hashlib
import os

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P

_GY = (4 * pow(5, _P - 2, _P)) % _P
_GX_SQ = (_GY * _GY - 1) * pow(_D * _GY * _GY + 1, _P - 2, _P) % _P


def _sqrt_mod(a: int) -> int:
    x = pow(a, (_P + 3) // 8, _P)
    if (x * x - a) % _P != 0:
        x = x * pow(2, (_P - 1) // 4, _P) % _P
    if (x * x - a) % _P != 0:
        raise ValueError("not a quadratic residue")
    return x


_GX = _sqrt_mod(_GX_SQ)
if _GX % 2 != 0:
    _GX = _P - _GX
_G = (_GX, _GY, 1, _GX * _GY % _P)  # extended coords (X, Y, Z, T)
_IDENT = (0, 1, 1, 0)


def _add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    d = 2 * z1 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _mul(s: int, p):
    q = _IDENT
    while s > 0:
        if s & 1:
            q = _add(q, p)
        p = _add(p, p)
        s >>= 1
    return q


def _compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, _P - 2, _P)
    x, y = x * zinv % _P, y * zinv % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(b: bytes):
    n = int.from_bytes(b, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    if y >= _P:
        raise ValueError("invalid point encoding")
    x_sq = (y * y - 1) * pow(_D * y * y + 1, _P - 2, _P) % _P
    x = _sqrt_mod(x_sq)
    if x == 0 and sign:
        raise ValueError("invalid point encoding")
    if x & 1 != sign:
        x = _P - x
    return (x, y, 1, x * y % _P)


def _h512(*parts: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"".join(parts)).digest(), "little")


def _expand(seed: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def generate_seed() -> bytes:
    return os.urandom(32)


def public_key(seed: bytes) -> bytes:
    a, _prefix = _expand(seed)
    return _compress(_mul(a, _G))


def sign(seed: bytes, message: bytes) -> bytes:
    a, prefix = _expand(seed)
    pub = _compress(_mul(a, _G))
    r = _h512(prefix, message) % _L
    r_enc = _compress(_mul(r, _G))
    s = (r + _h512(r_enc, pub, message) * a) % _L
    return r_enc + int.to_bytes(s, 32, "little")


def verify(pub: bytes, signature: bytes, message: bytes) -> bool:
    if len(signature) != 64 or len(pub) != 32:
        return False
    try:
        point_a = _decompress(pub)
        point_r = _decompress(signature[:32])
    except ValueError:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:
        return False
    k = _h512(signature[:32], pub, message) % _L
    left = _mul(8, _mul(s, _G))
    right = _mul(8, _add(point_r, _mul(k, point_a)))
    lz, rz = left[2], right[2]
    # compare projective points cross-multiplied (no inversions)
    return (left[0] * rz - right[0] * lz) % _P == 0 \
        and (left[1] * rz - right[1] * lz) % _P == 0
