"""Pairing: originator/responder handshake that mirrors a library to a peer.

Parity with core/src/p2p/pairing/mod.rs:38-44,75-230 and pairing/proto.rs:

- the originator mints a fresh per-library ed25519 instance identity +
  pub_id, sends ``Header::Pair`` + a PairingRequest carrying its Instance
  record, and waits;
- the responder surfaces a UI decision (``p2p.pairingResponse``; headless
  nodes can set the ``p2p_auto_accept_library`` config key), inserts the
  originator's instance into the chosen library, and replies Accepted with
  the library info plus every instance row it knows;
- the originator then creates the mirrored library with the SAME uuid
  (create_with_uuid path) holding its private identity, registers the other
  instances, and both sides kick off sync sessions so the op-logs converge.

PairingStatus progress events flow over the p2p event stream throughout.
"""

from __future__ import annotations

import asyncio
import datetime as dt
import itertools
import logging
import uuid
from typing import TYPE_CHECKING, Any

from .identity import Identity, encode_identity, remote_identity_of
from .proto import Header, json_frame, read_json

if TYPE_CHECKING:
    from .manager import P2PManager, Peer

logger = logging.getLogger(__name__)

DECISION_TIMEOUT = 60.0
RESPONSE_TIMEOUT = 120.0


def _instance_wire(row: dict[str, Any]) -> dict[str, Any]:
    """Instance row → wire form. The identity column crosses as the PUBLIC
    half only (identity_or_remote_identity.rs — private keys never leave)."""
    ident = remote_identity_of(row["identity"])
    iso = lambda v: v.isoformat() if isinstance(v, dt.datetime) else v
    return {"pub_id": row["pub_id"], "identity": "R:" + ident.encode(),
            "node_remote_identity": row.get("node_remote_identity"),
            "node_id": row["node_id"], "node_name": row["node_name"],
            "node_platform": row["node_platform"],
            "last_seen": iso(row["last_seen"]),
            "date_created": iso(row["date_created"])}


class PairingManager:
    def __init__(self, manager: "P2PManager") -> None:
        self.manager = manager
        self._ids = itertools.count(0)
        self._pending: dict[int, asyncio.Future] = {}

    def _emit(self, pairing_id: int, status: Any) -> None:
        self.manager.emit({"type": "PairingProgress", "id": pairing_id,
                           "status": status})

    def decision(self, pairing_id: int, decision: Any) -> None:
        """UI answer for a pending responder prompt: ``{"accept": library_id}``
        or anything falsy to reject (PairingDecision)."""
        fut = self._pending.pop(pairing_id, None)
        if fut is None:
            raise KeyError(f"no pending pairing {pairing_id}")
        self.manager._loop.call_soon_threadsafe(
            lambda: fut.done() or fut.set_result(decision))

    # -- originator ----------------------------------------------------------
    def originator(self, peer_id: str) -> int:
        pairing_id = next(self._ids)
        self._emit(pairing_id, "EstablishingConnection")
        self.manager.schedule(self._originator(pairing_id, peer_id))
        return pairing_id

    async def _originator(self, pairing_id: int, peer_id: str) -> None:
        node = self.manager.node
        try:
            reader, writer, _meta = await self.manager.open_stream(peer_id)
        except (OSError, KeyError) as e:
            self._emit(pairing_id, {"Error": f"connect failed: {e}"})
            return
        try:
            writer.write(Header.pair().to_bytes())
            # 1. mint this node's instance for the future mirrored library
            identity = Identity()
            instance_pub_id = str(uuid.uuid4())
            cfg = node.config.get()
            now = dt.datetime.now(dt.timezone.utc).isoformat()
            self._emit(pairing_id, "PairingRequested")
            writer.write(json_frame({"instance": {
                "pub_id": instance_pub_id,
                "identity": "R:" + identity.to_remote_identity().encode(),
                "node_id": cfg["id"], "node_name": cfg["name"],
                "node_platform": cfg["platform"],
                "last_seen": now, "date_created": now}}))
            await writer.drain()

            # 2. responder's verdict
            resp = await asyncio.wait_for(read_json(reader), RESPONSE_TIMEOUT)
            if resp.get("decision") != "accepted":
                self._emit(pairing_id, "PairingRejected")
                return
            library_id = resp["library_id"]
            self._emit(pairing_id, {"PairingInProgress": {
                "library_name": resp["library_name"],
                "library_description": resp.get("library_description", "")}})
            if any(lib.id == library_id for lib in node.libraries.list()):
                self._emit(pairing_id, "LibraryAlreadyExists")
                return

            # 3. mirror the library (create_with_uuid, manager/mod.rs)
            loop = asyncio.get_running_loop()
            library = await loop.run_in_executor(
                None, lambda: node.libraries.create(
                    resp["library_name"],
                    description=resp.get("library_description", ""),
                    lib_id=library_id,
                    instance_pub_id=instance_pub_id,
                    instance_identity=encode_identity(identity)))
            for inst in resp.get("instances", []):
                if inst["pub_id"] == instance_pub_id:
                    continue
                await loop.run_in_executor(
                    None, library.add_remote_instance, _parse_instance(inst))
            node.libraries.notify_instances_modified(library)
            self._emit(pairing_id, {"PairingComplete": library_id})

            # 4. both sides resync; ours announces (empty) state so the
            # responder learns our instance is live, and its originate pushes
            # the real data back to us
            await self.manager.nlm.originate(library)
        except (OSError, asyncio.TimeoutError) as e:
            self._emit(pairing_id, {"Error": str(e)})
        finally:
            writer.close()

    # -- responder -----------------------------------------------------------
    async def responder(self, reader, writer, peer: "Peer") -> None:
        node = self.manager.node
        req = await read_json(reader)
        inst = req["instance"]
        pairing_id = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[pairing_id] = fut
        self.manager.emit({"type": "PairingRequest", "id": pairing_id,
                           "identity": peer.identity,
                           "name": inst.get("node_name", "?")})

        auto = node.config.get().get("p2p_auto_accept_library")
        if auto:
            fut.set_result({"accept": auto})
        try:
            decision = await asyncio.wait_for(fut, DECISION_TIMEOUT)
        except asyncio.TimeoutError:
            decision = None
        finally:
            self._pending.pop(pairing_id, None)

        library_id = (decision or {}).get("accept") if isinstance(decision, dict) else None
        if not library_id:
            writer.write(json_frame({"decision": "rejected"}))
            await writer.drain()
            self._emit(pairing_id, "PairingRejected")
            return
        try:
            library = node.libraries.get(library_id)
        except KeyError:
            writer.write(json_frame({"decision": "rejected"}))
            await writer.drain()
            self._emit(pairing_id, {"Error": f"library {library_id} not loaded"})
            return

        loop = asyncio.get_running_loop()
        row = _parse_instance(inst)
        # the membership anchor is the HANDSHAKE-proven node identity, not
        # anything the request claims
        row["node_remote_identity"] = peer.identity
        await loop.run_in_executor(None, library.add_remote_instance, row)
        node.libraries.notify_instances_modified(library)

        from ..models import Instance

        rows = await loop.run_in_executor(None, library.db.find, Instance)
        instances = []
        for row in rows:
            try:
                instances.append(_instance_wire(row))
            except ValueError:
                continue  # placeholder identity (pre-p2p library)
        writer.write(json_frame({
            "decision": "accepted", "library_id": library.id,
            "library_name": library.name,
            "library_description": library.config.get("description", ""),
            "instances": instances}))
        await writer.drain()
        self._emit(pairing_id, {"PairingComplete": library.id})
        # push our data to the (new) peer as soon as it finishes mirroring
        self.manager.schedule(self._originate_soon(library))

    async def _originate_soon(self, library) -> None:
        await asyncio.sleep(0.5)  # let the originator finish creating the mirror
        await self.manager.nlm.originate(library)


def _parse_instance(inst: dict[str, Any]) -> dict[str, Any]:
    row = dict(inst)
    for key in ("last_seen", "date_created"):
        if isinstance(row.get(key), str):
            row[key] = dt.datetime.fromisoformat(row[key])
    row.setdefault("timestamp", 0)
    return row
