"""Spaceblock: block-based file transfer with progress + cancellation.

Parity with crates/p2p/src/spaceblock/mod.rs (BEP-inspired `Transfer`):
files move as fixed-size blocks over an authenticated stream, the receiver
assembles into a temp file then renames, either side can cancel, and a
progress callback fires per block (fed to the UI as P2PEvent progress).
"""

from __future__ import annotations

import asyncio
import logging
import os
from pathlib import Path
from typing import Awaitable, Callable

from .proto import Range  # re-exported: transfer call sites range-slice  # lint: ok
from .proto import (ProtocolError, SpaceblockRequest, block_msg,
                    cancel_msg, read_block_msg)

logger = logging.getLogger(__name__)

Progress = Callable[[int, int], None]  # (bytes_done, bytes_total)
#: sender-side net-model hook: awaited with each frame's wire length
#: BEFORE the write, so an armed faults.net plan shapes/ledgers whole-file
#: transfers exactly like delta frames (a cut raises out of the send)
Link = Callable[[int], Awaitable[None]]


async def send_file(writer: asyncio.StreamWriter, path: Path,
                    req: SpaceblockRequest,
                    progress: Progress | None = None,
                    cancelled: asyncio.Event | None = None,
                    link: Link | None = None) -> int:
    """Stream ``path``'s requested range as blocks; returns bytes sent."""
    loop = asyncio.get_running_loop()
    rng = req.range
    end = req.size if rng.end is None else min(rng.end, req.size)
    sent, offset = 0, rng.start
    with open(path, "rb") as fh:
        fh.seek(offset)
        while offset < end:
            if cancelled is not None and cancelled.is_set():
                msg = cancel_msg()
                if link is not None:
                    await link(len(msg))
                writer.write(msg)
                await writer.drain()
                return sent
            # disk reads go through the executor — a 128MiB block read inline
            # would stall every other session on the p2p loop
            data = await loop.run_in_executor(
                None, fh.read, min(req.block_size, end - offset))
            if not data:
                break
            msg = block_msg(offset, data)
            if link is not None:
                await link(len(msg))
            writer.write(msg)
            await writer.drain()
            offset += len(data)
            sent += len(data)
            if progress:
                progress(sent, end - rng.start)
    return sent


async def receive_file(reader: asyncio.StreamReader, target: Path,
                       req: SpaceblockRequest,
                       progress: Progress | None = None,
                       cancelled: asyncio.Event | None = None) -> bool:
    """Assemble blocks into ``target`` (temp-file + rename). Returns False if
    the sender cancelled or we did."""
    rng = req.range
    end = req.size if rng.end is None else min(rng.end, req.size)
    total = end - rng.start
    loop = asyncio.get_running_loop()
    tmp = target.with_name(target.name + ".sdpart")
    got = 0
    try:
        with open(tmp, "wb") as fh:
            if total > 0:
                await loop.run_in_executor(None, fh.truncate, total)
            while got < total:
                if cancelled is not None and cancelled.is_set():
                    return False
                msg = await read_block_msg(reader)
                if msg is None:  # sender cancelled
                    return False
                offset, data = msg
                rel = offset - rng.start
                if rel < 0 or rel + len(data) > total:
                    raise ProtocolError(f"block out of range: {offset}+{len(data)}")
                fh.seek(rel)
                await loop.run_in_executor(None, fh.write, data)
                got += len(data)
                if progress:
                    progress(got, total)
        os.replace(tmp, target)
        return True
    finally:
        tmp.unlink(missing_ok=True)
