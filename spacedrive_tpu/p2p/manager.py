"""P2PManager: authenticated streams, peer registry, header dispatch.

The architectural role of sd-p2p's ``Manager``/``ManagerStream``
(crates/p2p/src/manager.rs:34,62-79 — libp2p QUIC event loop) fused with the
core-side ``P2PManager`` event pump (core/src/p2p/p2p_manager.rs:88-260):

- one dedicated asyncio thread per Node runs the TCP listener, discovery
  beacons, and every session coroutine;
- a *stream* is one TCP connection carrying one header-tagged exchange
  (the reference opens a fresh QUIC substream per exchange — same shape);
- the connect handshake doubles as mutual authentication (ed25519
  challenge-response — stronger than the reference's TODO-stubbed Tunnel,
  crates/p2p/src/spacetunnel/tunnel.rs:23) and metadata exchange (so static
  ``host:port`` peers bootstrap without UDP discovery);
- inbound headers dispatch to pairing / sync sessions / spacedrop /
  file-serving, mirroring protocol.rs:13-27.

The *compute* plane stays on the device mesh (parallel/mesh.py); this module
is the host-side control plane the CRDT layer and file transfers ride on.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any

from .discovery import Discovery, DiscoveredPeer
from .identity import Identity, RemoteIdentity, remote_identity_of
from .mux import MuxConn
from . import delta as delta_proto
from .proto import (Header, H_DELTA, H_FILE, H_HASH, H_PAIR, H_PING,
                    H_QUERY, H_SPACEDROP, H_SYNC, H_THUMBNAIL, ProtocolError,
                    Range, SpaceblockRequest, block_size_for, json_frame,
                    read_block_msg, read_exact, read_json)
from .secure import (SecureReader, SecureWriter, derive_session_keys,
                     gen_ephemeral, transcript)
from .spaceblock import receive_file, send_file
from .. import telemetry
from ..telemetry import mesh

if TYPE_CHECKING:
    from ..node import Node

logger = logging.getLogger(__name__)

_HASH_REQS = telemetry.counter(
    "sd_p2p_hash_requests_total", "outbound remote-hasher batches")
_HASH_REQ_BYTES = telemetry.counter(
    "sd_p2p_hash_bytes_total",
    "cas-message bytes shipped to remote hashers")


#: deadline for reading a peer-declared H_HASH payload (tests shrink it)
HASH_PAYLOAD_TIMEOUT = 30.0


async def _read_all_payload(reader: asyncio.StreamReader, sizes: list[int],
                            collect: bool) -> list[bytes] | None:
    """Read every declared H_HASH payload segment; ``collect=False`` drains
    without keeping the bytes (the refusal paths). Callers wrap this in
    asyncio.wait_for — reading a peer-declared length must always carry a
    deadline."""
    if collect:
        return [await read_exact(reader, s) for s in sizes]
    for s in sizes:
        await read_exact(reader, s)
    return None

MAGIC = b"SDP4"  # bumped with multiplexed substreams over one session
SPACEDROP_TIMEOUT = 60.0  # p2p_manager.rs:42-43
HANDSHAKE_TIMEOUT = 20.0


class Peer:
    def __init__(self, identity: str, host: str, port: int,
                 metadata: dict[str, Any]) -> None:
        self.identity = identity
        self.host = host
        self.port = port
        self.metadata = metadata
        self.connected = False

    def to_wire(self) -> dict[str, Any]:
        return {"identity": self.identity, "host": self.host, "port": self.port,
                "connected": self.connected,
                "name": self.metadata.get("name"),
                "accelerator": self.metadata.get("accelerator")}


class P2PManager:
    def __init__(self, node: "Node") -> None:
        from .nlm import NetworkedLibraries
        from .pairing import PairingManager

        self.node = node
        cfg = node.config.get()
        self.identity = Identity.from_seed(cfg["keypair_seed"])
        self.remote_identity = self.identity.to_remote_identity()
        self.peers: dict[str, Peer] = {}
        self.port: int | None = None
        self.discovery: Discovery | None = None
        self.pairing = PairingManager(self)
        self.nlm = NetworkedLibraries(self)
        # accept-layer per-peer token bucket (throttle.py): a peer that
        # ignores BUSY gets its substreams RESET before any session
        # machinery runs; AutoBan escalates sustained throttling or
        # BUSY-ignoring re-dials into a timed ban at the same layer
        from .throttle import AutoBan, SessionThrottle

        self.session_throttle = SessionThrottle()
        # ban/strike state persists under the data dir (atomic writes),
        # reloaded with an expiry sweep at boot — a rebooted node must
        # not amnesty a mid-ban abuser (ISSUE 15 satellite, fleet rung c)
        self.auto_ban = AutoBan(
            persist_path=node.data_dir / "p2p_autoban.json")
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._stop: asyncio.Event | None = None
        self._server: asyncio.AbstractServer | None = None
        self._ready = threading.Event()
        self._start_error: BaseException | None = None
        self._spacedrop_in: dict[str, dict[str, Any]] = {}
        self._spacedrop_cancel: dict[str, asyncio.Event] = {}
        # one multiplexed connection per peer identity (spacetime semantics:
        # every exchange is a substream of a single authenticated session).
        # _muxes is the dial CACHE; _live_muxes tracks every connection for
        # shutdown (a cache eviction must not orphan a parked handler)
        self._muxes: dict[str, "MuxConn"] = {}
        self._live_muxes: set["MuxConn"] = set()
        self._mux_dial_locks: dict[str, asyncio.Lock] = {}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="p2p-manager")
        self._thread.start()
        if not self._ready.wait(15):
            raise RuntimeError("p2p manager failed to start")
        if self._start_error is not None:
            # surface bring-up failures (port in use, …) so the node falls
            # back to a clean offline state instead of a zombie manager
            raise RuntimeError(f"p2p bring-up failed: {self._start_error}")
        self.nlm.attach()

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except Exception as e:
            logger.exception("p2p event loop died")
            self._start_error = e
            self._ready.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        cfg = self.node.config.get()
        self._server = await asyncio.start_server(
            self._on_connection, "0.0.0.0", cfg.get("p2p_port") or 0)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("p2p listening on :%d as %s", self.port,
                    self.remote_identity.encode()[:12])

        disc_port = cfg.get("p2p_discovery_port")
        if disc_port:
            self.discovery = Discovery(
                int(disc_port), self.metadata,
                on_peer=self._on_discovered, on_expired=self._on_expired)
            await self.discovery.start()
        static = cfg.get("p2p_static_peers") or []
        pinger = asyncio.create_task(self._static_peer_loop(static)) if static else None

        self._ready.set()
        await self._stop.wait()
        if pinger:
            pinger.cancel()
        if self.discovery:
            await self.discovery.stop()
        # release every persistent session FIRST: 3.12's Server.wait_closed
        # waits for connection handlers, which park on mux.closed
        for mux in list(self._live_muxes):
            await mux.aclose()
        self._live_muxes.clear()
        self._muxes.clear()
        self._server.close()
        await self._server.wait_closed()

    def stop(self) -> None:
        if self._loop is None or self._stop is None:
            return
        try:
            self._loop.call_soon_threadsafe(self._stop.set)
            self._thread.join(timeout=10)
        except RuntimeError:
            pass
        # final strike-state snapshot (ban edges already saved eagerly)
        self.auto_ban.save()

    # -- metadata / events ---------------------------------------------------
    def metadata(self) -> dict[str, Any]:
        """PeerMetadata equivalent (core/src/p2p/peer_metadata.rs) + the
        TPU-native accelerator inventory for remote-hasher routing.

        Briefly cached: this runs on the p2p event loop (handshakes, beacon
        ticks) and scans every library's instance table — the cache keeps a
        long executor-side DB transaction from stalling the loop."""
        cached = getattr(self, "_metadata_cache", None)
        if cached is not None and time.monotonic() - cached[1] < 2.0:
            return cached[0]
        cfg = self.node.config.get()
        instances: dict[str, list[str]] = {}
        for library in self.node.libraries.list():
            idents = []
            from ..models import Instance

            for row in library.db.find(Instance):
                try:
                    idents.append(remote_identity_of(row["identity"]).encode())
                except ValueError:
                    continue  # pre-p2p placeholder identity
            instances[library.id] = idents
        meta = {"identity": self.remote_identity.encode(),
                "node_id": cfg["id"], "name": cfg["name"],
                "port": self.port, "operating_system": cfg["platform"],
                "instances": instances,
                "accelerator": cfg.get("accelerator", {})}
        self._metadata_cache = (meta, time.monotonic())
        return meta

    def emit(self, event: dict[str, Any]) -> None:
        self.node.emit("p2p", event)

    def _on_discovered(self, dp: DiscoveredPeer, is_new: bool) -> None:
        peer = self.peers.get(dp.identity)
        if peer is None:
            peer = Peer(dp.identity, dp.host, dp.port, dp.metadata)
            self.peers[dp.identity] = peer
        else:
            peer.host, peer.port, peer.metadata = dp.host, dp.port, dp.metadata
        if is_new:
            self.emit({"type": "DiscoveredPeer", "peer": peer.to_wire()})
        self.nlm.peer_seen(peer)

    def _on_expired(self, dp: DiscoveredPeer) -> None:
        peer = self.peers.pop(dp.identity, None)
        if peer is not None:
            self.emit({"type": "ExpiredPeer", "identity": dp.identity})
            self.nlm.peer_lost(peer)

    async def _static_peer_loop(self, static: list[str]) -> None:
        """Learn identities/metadata of configured host:port peers by pinging
        them; refresh periodically (mDNS replacement for filtered networks)."""
        while True:
            for entry in static:
                try:
                    host, port = entry.rsplit(":", 1)
                    await self._ping((host, int(port)))
                except Exception as e:
                    logger.debug("static peer %s unreachable: %s", entry, e)
            await asyncio.sleep(10)

    async def broadcast(self, data: bytes) -> int:
        """Send ``data`` down a fresh substream of every CONNECTED peer's
        live session (spacetime ``Manager::broadcast``,
        crates/p2p/src/manager.rs:155). Best-effort and concurrent: dead
        peers are skipped (their sessions get demoted by the failed open).
        Returns how many peers were reached."""
        async def one(peer_id: str) -> None:
            reader, writer, _meta = await self.open_stream(peer_id)
            try:
                writer.write(data)
                await writer.drain()
            finally:
                writer.close()

        targets = [p.identity for p in list(self.peers.values()) if p.connected]
        results = await asyncio.gather(*(one(t) for t in targets),
                                       return_exceptions=True)
        return sum(1 for r in results if not isinstance(r, BaseException))

    async def ping_all(self) -> int:
        """Ping every connected peer, refreshing its metadata from the reply
        (p2p_manager.rs:546's ``manager.broadcast(Header::Ping)`` — ours
        reads the metadata answer each ping exchange produces)."""
        async def one(peer: Peer) -> None:
            await self._ping((peer.host, peer.port))

        targets = [p for p in list(self.peers.values()) if p.connected]
        results = await asyncio.gather(*(one(t) for t in targets),
                                       return_exceptions=True)
        return sum(1 for r in results if not isinstance(r, BaseException))

    async def _ping(self, addr: tuple[str, int]) -> None:
        """Ping = metadata refresh: sessions now outlive the handshake, so
        the responder replies with CURRENT metadata (new libraries/instances
        advertised since connect) and the sender re-registers it."""
        reader, writer, _meta = await self.open_stream(f"{addr[0]}:{addr[1]}")
        try:
            writer.write(Header.ping().to_bytes())
            await writer.drain()
            fresh = await asyncio.wait_for(read_json(reader), 10)
            if fresh.get("identity"):
                self._register_connected(fresh, addr[0])
        finally:
            writer.close()

    # -- handshake -----------------------------------------------------------
    # SIGMA-style authenticated key exchange; see secure.py's module
    # docstring for the full protocol and its security argument. Every byte
    # after the two ephemeral keys travels ChaCha20Poly1305-encrypted.

    async def _handshake_out(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter,
                             expected_identity: str | None = None):
        eph, e_i = gen_ephemeral()
        writer.write(MAGIC + e_i)
        await writer.drain()
        e_r = await read_exact(reader, 32)
        k_i2r, k_r2i = derive_session_keys(eph, e_r, e_i, e_r)
        sr, sw = SecureReader(reader, k_r2i), SecureWriter(writer, k_i2r)
        auth = await read_json(sr)  # responder proves identity, nothing more
        peer_ident = RemoteIdentity.decode(auth["identity"])
        # pin: a discovery beacon may have planted this address for a known
        # identity — if whoever answered is not that identity, bail before
        # trusting anything it said
        if expected_identity is not None and auth["identity"] != expected_identity:
            raise ProtocolError("peer identity mismatch")
        if not peer_ident.verify(bytes.fromhex(auth["sig"]),
                                 transcript("resp", e_i, e_r, auth["identity"])):
            raise ProtocolError("peer failed challenge")
        my_ident = self.remote_identity.encode()
        sw.write(json_frame({**self.metadata(), "sig": self.identity.sign(
            transcript("init", e_i, e_r, my_ident, auth["identity"])).hex()}))
        await sw.drain()
        # responder metadata arrives only after it verified US — an
        # anonymous prober can learn the responder's (public, beaconed)
        # identity but not node names / library instance lists
        meta = await read_json(sr)
        return sr, sw, {**meta, "identity": auth["identity"]}

    async def _handshake_in(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter):
        if await read_exact(reader, 4) != MAGIC:
            raise ProtocolError("bad magic")
        e_i = await read_exact(reader, 32)
        eph, e_r = gen_ephemeral()
        writer.write(e_r)
        await writer.drain()
        k_i2r, k_r2i = derive_session_keys(eph, e_i, e_i, e_r)
        sr, sw = SecureReader(reader, k_i2r), SecureWriter(writer, k_r2i)
        my_ident = self.remote_identity.encode()
        # SIGMA-I ordering: prove identity first, disclose metadata only
        # after the initiator's signature verifies — an anonymous prober
        # must not harvest node names or per-library instance lists
        sw.write(json_frame({"identity": my_ident, "sig": self.identity.sign(
            transcript("resp", e_i, e_r, my_ident)).hex()}))
        await sw.drain()
        hello = await read_json(sr)
        peer_ident = RemoteIdentity.decode(hello["identity"])
        if not peer_ident.verify(bytes.fromhex(hello["sig"]),
                                 transcript("init", e_i, e_r,
                                            hello["identity"], my_ident)):
            raise ProtocolError("peer failed challenge")
        sw.write(json_frame(self.metadata()))
        await sw.drain()
        return sr, sw, hello

    def _register_connected(self, meta: dict[str, Any], host: str) -> Peer:
        ident = meta["identity"]
        peer = self.peers.get(ident)
        if peer is None:
            peer = Peer(ident, host, int(meta.get("port") or 0), meta)
            self.peers[ident] = peer
        else:
            peer.host, peer.metadata = host, meta
            if meta.get("port"):
                peer.port = int(meta["port"])
        first = not peer.connected
        peer.connected = True
        if first:
            self.emit({"type": "ConnectedPeer", "identity": ident})
        self.nlm.peer_seen(peer)
        return peer

    # -- outgoing streams ----------------------------------------------------
    def _resolve_addr(self, peer_id: str) -> tuple[str, int]:
        peer = self.peers.get(peer_id)
        if peer is not None:
            return peer.host, peer.port
        if ":" in peer_id:  # direct host:port addressing (static/test path)
            host, port = peer_id.rsplit(":", 1)
            return host, int(port)
        raise KeyError(f"unknown peer {peer_id}")

    async def _open_stream_addr(self, addr: tuple[str, int],
                                expected_identity: str | None = None):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(*addr), HANDSHAKE_TIMEOUT)
        try:
            sr, sw, meta = await asyncio.wait_for(
                self._handshake_out(reader, writer, expected_identity),
                HANDSHAKE_TIMEOUT)
        except Exception:
            writer.close()
            raise
        self._register_connected(meta, addr[0])
        return sr, sw, meta

    async def open_stream(self, peer_id: str):
        """(reader, writer, peer_metadata) — one SUBSTREAM of the peer's
        multiplexed authenticated session (``Manager::stream(peer_id)`` over
        the spacetime UnicastStream semantics): the first exchange dials and
        handshakes once; every further exchange multiplexes over the live
        connection. A failed connect demotes a known peer so dead static
        peers don't stay Connected and stall every sync round."""
        # a peer_id that is an identity (not host:port dialing) pins the
        # handshake to that identity
        expected = peer_id if peer_id in self.peers else None
        try:
            mux, meta = await self._get_mux(peer_id, expected)
            sub = mux.open_substream()
            return sub, sub, meta
        except (OSError, asyncio.TimeoutError, ProtocolError):
            peer = self.peers.get(peer_id)
            if peer is not None and peer.connected:
                peer.connected = False
                self.emit({"type": "DisconnectedPeer", "identity": peer.identity})
                self.nlm.peer_lost(peer)
            raise

    async def _get_mux(self, peer_id: str,
                       expected_identity: str | None) -> tuple[MuxConn, dict]:
        """Live mux for the peer, dialing + handshaking if needed. The dial
        is locked per peer so concurrent exchanges share ONE connection."""
        existing = self._muxes.get(peer_id)
        if existing is not None and existing.alive:
            return existing, existing.meta
        lock = self._mux_dial_locks.setdefault(peer_id, asyncio.Lock())
        async with lock:
            existing = self._muxes.get(peer_id)
            if existing is not None and existing.alive:
                return existing, existing.meta
            sr, sw, meta = await self._open_stream_addr(
                self._resolve_addr(peer_id), expected_identity)
            peer = self.peers[meta["identity"]]
            mux = self._adopt_connection(sr, sw, meta, peer, initiator=True)
            if peer_id != meta["identity"]:  # host:port dial: index both ways
                self._muxes[peer_id] = mux
            return mux, meta

    def _adopt_connection(self, sr, sw, meta: dict, peer: Peer,
                          initiator: bool) -> MuxConn:
        """Wrap a freshly-handshaken connection in a mux, register it, and
        arrange teardown bookkeeping."""
        ident = meta["identity"]

        async def on_inbound(sub) -> None:
            await self._dispatch_substream(sub, peer)

        mux = MuxConn(sr, sw, initiator=initiator, on_inbound=on_inbound,
                      name=f"{'out' if initiator else 'in'}:{ident[:8]}")
        mux.meta = meta
        old = self._muxes.get(ident)
        self._muxes[ident] = mux
        self._live_muxes.add(mux)

        async def reap() -> None:
            await mux.closed.wait()
            self._live_muxes.discard(mux)
            for key in [k for k, v in list(self._muxes.items()) if v is mux]:
                self._muxes.pop(key, None)
            # demote only when NO live session to this identity remains —
            # scanned over _live_muxes (a crossed-dial session may be alive
            # yet evicted from the dial cache)
            still_alive = [v for v in self._live_muxes
                           if v.alive
                           and getattr(v, "meta", {}).get("identity") == ident]
            if peer.connected and not still_alive:
                peer.connected = False
                self.emit({"type": "DisconnectedPeer", "identity": ident})
                self.nlm.peer_lost(peer)
            elif still_alive and self._muxes.get(ident) is None:
                # keep the surviving session reachable for future dials
                self._muxes[ident] = still_alive[0]

        task = asyncio.get_running_loop().create_task(reap())
        task.add_done_callback(self._log_task_error)
        if old is not None and old.alive and old is not mux:
            # simultaneous dial crossed an inbound connection; keep both
            # alive (streams on each still work), newest wins the index
            logger.debug("mux to %s replaced while alive", ident[:8])
        return mux

    # -- cross-thread helpers ------------------------------------------------
    def run_coro(self, coro, timeout: float | None = None):
        """Run a coroutine on the p2p loop from a sync caller (API thread)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    def schedule(self, coro) -> None:
        """Fire-and-forget a coroutine on the p2p loop."""
        def _spawn() -> None:
            task = self._loop.create_task(coro)
            task.add_done_callback(self._log_task_error)

        self._loop.call_soon_threadsafe(_spawn)

    @staticmethod
    def _log_task_error(task: asyncio.Task) -> None:
        if not task.cancelled() and task.exception() is not None:
            logger.error("p2p task failed", exc_info=task.exception())

    # -- inbound dispatch ----------------------------------------------------
    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        host = writer.get_extra_info("peername", ("?", 0))[0]
        try:
            sr, sw, meta = await asyncio.wait_for(
                self._handshake_in(reader, writer), HANDSHAKE_TIMEOUT)
            peer = self._register_connected(meta, host)
        except (ProtocolError, asyncio.TimeoutError, OSError) as e:
            logger.debug("p2p connection from %s failed: %s", host, e)
            writer.close()
            return
        except Exception:
            logger.exception("p2p connection handler crashed")
            writer.close()
            return
        # hold the accept callback open for the mux'd session's lifetime —
        # every exchange from this peer arrives as a substream
        mux = self._adopt_connection(sr, sw, meta, peer, initiator=False)
        await mux.closed.wait()

    async def _dispatch_substream(self, sub, peer: Peer) -> None:
        """One inbound substream = one header-tagged exchange
        (protocol.rs:13-27 dispatch, previously one-per-connection)."""
        # accept-layer ban, then throttle: one token per inbound exchange.
        # A peer serving a ban is refused before even the token-bucket
        # spend; a peer that ignores BUSY/backoff and floods sessions is
        # refused at the bucket — and each refusal is a strike toward a
        # timed ban — all BEFORE the header parse, the responder
        # coroutine, or the admission budget spend, with a RESET so its
        # dial fails fast.
        ban_left = self.auto_ban.check(peer.identity)
        if ban_left is not None:
            logger.warning("p2p substream from %s refused: banned for "
                           "another %.1fs", peer.identity[:8], ban_left)
            sub.reset()
            return
        if not self.session_throttle.admit(peer.identity):
            self.auto_ban.strike(peer.identity, "throttled")
            logger.warning("p2p substream from %s throttled at accept "
                           "(token bucket empty)", peer.identity[:8])
            sub.reset()
            return
        failed = True
        try:
            header = await Header.from_stream(sub)
            if header.kind == H_PING:
                # reply with CURRENT metadata: persistent sessions mean the
                # handshake snapshot goes stale as libraries/instances change
                sub.write(json_frame(
                    {**self.metadata(), "identity": self.remote_identity.encode()}))
                await sub.drain()
            elif header.kind == H_PAIR:
                await self.pairing.responder(sub, sub, peer)
            elif header.kind == H_SYNC:
                # BUSY compliance is judged HERE, on the protocol that was
                # shed — a sync re-dial before the deadline our BUSY frame
                # carried is a strike (pings/hash/file exchanges never are)
                if self.auto_ban.judge_busy_compliance(
                        peer.identity) is not None:
                    logger.warning("p2p sync substream from %s refused: "
                                   "ignored BUSY into a ban",
                                   peer.identity[:8])
                    return  # `failed` stays True: the finally RESETs
                await self.nlm.responder(sub, sub, header.payload, peer)
            elif header.kind == H_SPACEDROP:
                await self._spacedrop_receive(sub, sub, header.payload, peer)
            elif header.kind == H_FILE:
                await self._serve_file(sub, sub, header.payload, peer)
            elif header.kind == H_THUMBNAIL:
                await self._serve_thumbnail(sub, sub, header.payload, peer)
            elif header.kind == H_HASH:
                await self._serve_hash_batch(sub, sub, header.payload, peer)
            elif header.kind == H_DELTA:
                await delta_proto.serve_delta(self, sub, sub,
                                              header.payload, peer)
            elif header.kind == H_QUERY:
                await self._serve_query(sub, sub, header.payload, peer)
            else:
                logger.warning("unhandled header kind %s", header.kind)
            failed = False
        except (ProtocolError, asyncio.TimeoutError, OSError,
                asyncio.IncompleteReadError) as e:
            logger.debug("p2p exchange from %s failed: %s", peer.identity[:8], e)
        except Exception:
            logger.exception("p2p substream handler crashed")
        finally:
            if failed:
                # a crashed exchange RESETS so the remote fails fast instead
                # of pumping data into an unread buffer until the cap
                sub.reset()
            else:
                sub.close()

    # -- spacedrop -----------------------------------------------------------
    def spacedrop(self, peer_id: str, paths: list[str]) -> list[str]:
        """Offer files to a peer; returns drop ids (p2p_manager.rs spacedrop)."""
        ids = []
        for p in paths:
            drop_id = str(uuid.uuid4())
            ids.append(drop_id)
            self.schedule(self._spacedrop_send(drop_id, peer_id, Path(p)))
        return ids

    def spacedrop_delta(self, peer_id: str, paths: list[str]) -> list[str]:
        """Delta-aware spacedrop (ISSUE 18): negotiate the peer's chunk
        manifest and ship only the missing chunks (p2p/delta.py). Same
        accept/cancel surface and event stream as a plain drop."""
        ids = []
        for p in paths:
            drop_id = str(uuid.uuid4())
            ids.append(drop_id)
            self.schedule(delta_proto.send_delta(self, drop_id, peer_id,
                                                 Path(p)))
        return ids

    async def _spacedrop_send(self, drop_id: str, peer_id: str, path: Path) -> None:
        cancel = asyncio.Event()
        self._spacedrop_cancel[drop_id] = cancel
        try:
            size = path.stat().st_size
            req = SpaceblockRequest(name=path.name, size=size,
                                    block_size=block_size_for(size))
            reader, writer, _meta = await self.open_stream(peer_id)
            # whole-file frames ride the armed faults.net model like delta
            # frames (ISSUE 19 satellite): shaped/ledgered per link, and a
            # cut raises out of the send as a transport failure
            link = self._net_link_hook(_meta.get("identity") or peer_id)
            try:
                hdr = Header.spacedrop(req).to_bytes()
                await link(len(hdr))
                writer.write(hdr)
                await writer.drain()
                decision = await asyncio.wait_for(read_exact(reader, 1),
                                                  SPACEDROP_TIMEOUT)
                if decision != b"\x01":
                    self.emit({"type": "SpacedropRejected", "id": drop_id})
                    return
                sent = await send_file(
                    writer, path, req,
                    progress=lambda done, total: self.emit(
                        {"type": "SpacedropProgress", "id": drop_id,
                         "percent": int(done * 100 / max(1, total))}),
                    cancelled=cancel, link=link)
                await writer.drain()
                self.emit({"type": "SpacedropDone", "id": drop_id, "bytes": sent})
            finally:
                writer.close()
        except (OSError, asyncio.TimeoutError, ProtocolError) as e:
            self.emit({"type": "SpacedropFailed", "id": drop_id, "error": str(e)})
        finally:
            self._spacedrop_cancel.pop(drop_id, None)

    async def _spacedrop_receive(self, reader, writer,
                                 req: SpaceblockRequest, peer: Peer) -> None:
        drop_id = str(uuid.uuid4())
        fut: asyncio.Future = self._loop.create_future()
        self._spacedrop_in[drop_id] = {"future": fut, "req": req,
                                       "peer": peer.identity}
        self.emit({"type": "SpacedropRequest", "id": drop_id,
                   "identity": peer.identity, "name": req.name,
                   "size": req.size})
        try:
            target_dir = await asyncio.wait_for(fut, SPACEDROP_TIMEOUT)
        except asyncio.TimeoutError:
            target_dir = None
        finally:
            self._spacedrop_in.pop(drop_id, None)
        if target_dir is None:
            writer.write(b"\x00")
            await writer.drain()
            self.emit({"type": "SpacedropRejected", "id": drop_id})
            return
        writer.write(b"\x01")
        await writer.drain()
        from ..objects.fs import find_available_name

        # the offered name is attacker-controlled: keep only the basename so
        # "../../x" or an absolute path can never escape the chosen directory
        safe_name = Path(req.name).name or "received.bin"
        target = find_available_name(Path(target_dir) / safe_name)
        cancel = asyncio.Event()
        self._spacedrop_cancel[drop_id] = cancel
        try:
            ok = await receive_file(
                reader, target, req,
                progress=lambda done, total: self.emit(
                    {"type": "SpacedropProgress", "id": drop_id,
                     "percent": int(done * 100 / max(1, total))}),
                cancelled=cancel)
            self.emit({"type": "SpacedropDone" if ok else "SpacedropFailed",
                       "id": drop_id, "path": str(target)})
        finally:
            self._spacedrop_cancel.pop(drop_id, None)

    def accept_spacedrop(self, drop_id: str, target_dir: str | None) -> None:
        entry = self._spacedrop_in.get(drop_id)
        if entry is None:
            raise KeyError(f"no pending spacedrop {drop_id}")
        self._loop.call_soon_threadsafe(
            lambda: entry["future"].done() or entry["future"].set_result(target_dir))

    def cancel_spacedrop(self, drop_id: str) -> None:
        entry = self._spacedrop_in.get(drop_id)
        if entry is not None:
            self._loop.call_soon_threadsafe(
                lambda: entry["future"].done() or entry["future"].set_result(None))
            return
        cancel = self._spacedrop_cancel.get(drop_id)
        if cancel is not None:
            self._loop.call_soon_threadsafe(cancel.set)

    # -- files over p2p ------------------------------------------------------
    async def _serve_file(self, reader, writer, payload: dict, peer: Peer) -> None:
        """Serve a ranged file read to an authenticated peer
        (Header::File, p2p_manager.rs gated on files_over_p2p_flag)."""
        from ..config import BackendFeature
        from ..models import FilePath
        from ..objects.fs import file_path_abs

        if not self.node.config.has_feature(BackendFeature.FILES_OVER_P2P):
            writer.write(json_frame({"ok": False, "error": "filesOverP2P disabled"}))
            await writer.drain()
            return
        def _lookup():
            # blocking DB/stat work — off the p2p loop (the single-writer
            # DB lock being held by a scan must not stall every session)
            library = self.node.libraries.get(payload["library_id"])
            # only nodes paired into the library may read its files
            if peer.identity not in self.nlm.member_nodes(library):
                raise KeyError("not a member of this library")
            row = library.db.find_one(
                FilePath, {"pub_id": payload["file_path_pub_id"]})
            if row is None:
                raise KeyError("file_path not found")
            _row, p = file_path_abs(library.db, row["id"])
            return p, p.stat().st_size

        try:
            path, size = await asyncio.get_running_loop().run_in_executor(
                None, _lookup)
        except (KeyError, OSError) as e:
            writer.write(json_frame({"ok": False, "error": str(e)}))
            await writer.drain()
            return
        rng = Range.from_wire(payload.get("range"))
        req = SpaceblockRequest(name=path.name, size=size,
                                block_size=block_size_for(size), range=rng)
        # served file frames ride the armed faults.net model too — WE are
        # the sender on this substream, so the shaped direction is
        # us -> requesting peer
        link = self._net_link_hook(peer.identity)
        head = json_frame({"ok": True, **req.to_wire()})
        await link(len(head))
        writer.write(head)
        await writer.drain()
        await send_file(writer, path, req, link=link)
        await writer.drain()

    async def _serve_thumbnail(self, reader, writer, payload: dict,
                               peer: Peer) -> None:
        """Serve a cached preview to an authenticated library member — the
        on-demand form of the reference's sync_preview_media knob: previews
        travel when a paired node actually looks at the file."""
        from ..objects.media.thumbnail import thumbnail_path

        cas_id = str(payload.get("cas_id", ""))

        def _lookup() -> bytes:
            # blocking DB/disk work — off the p2p loop
            library = self.node.libraries.get(payload["library_id"])
            if peer.identity not in self.nlm.member_nodes(library):
                raise KeyError("not a member of this library")
            # only previews of content this library tracks are disclosable
            from ..models import FilePath

            if ("/" in cas_id or ".." in cas_id
                    or library.db.find_one(FilePath, {"cas_id": cas_id}) is None):
                raise KeyError("no such cas_id in this library")
            return thumbnail_path(self.node.data_dir, cas_id).read_bytes()

        try:
            body = await asyncio.get_running_loop().run_in_executor(
                None, _lookup)
        except (KeyError, OSError) as e:
            # fixed wire message: raw OSError strings leak local paths
            logger.debug("thumbnail serve refused (%s): %s", cas_id[:8], e)
            writer.write(json_frame({"ok": False, "error": "no such thumbnail"}))
            await writer.drain()
            return
        writer.write(json_frame({"ok": True, "size": len(body)}))
        writer.write(body)
        await writer.drain()

    # -- shared hasher service (H_HASH, BASELINE config 5) -------------------

    #: per-request limits the server enforces (and the client respects);
    #: the total must sit well under the mux's 64 MiB per-substream buffer
    #: or the demux guard resets the stream before the read completes
    HASH_MAX_COUNT = 4096
    HASH_MAX_MSG = 256 * 1024          # whole-file path tops out ≈100KiB+8
    HASH_MAX_TOTAL = 48 * 1024 * 1024

    async def _serve_hash_batch(self, reader, writer, payload: dict,
                                peer: Peer) -> None:
        """Hash a member peer's pre-gathered cas messages on OUR engine
        (device when present). Compute-sharing is restricted to nodes that
        share at least one library with us — the same trust boundary as
        file/preview serving."""
        sizes = payload.get("sizes")
        if (not isinstance(sizes, list) or not sizes
                or len(sizes) > self.HASH_MAX_COUNT
                or not all(isinstance(s, int) and 0 < s <= self.HASH_MAX_MSG
                           for s in sizes)
                or sum(sizes) > self.HASH_MAX_TOTAL):
            # drain whatever payload the declared sizes describe (bounded),
            # like the membership refusal below — otherwise the in-flight
            # bytes of an oversized batch hit the demux cap and the client
            # sees a stream reset instead of this error
            if isinstance(sizes, list):
                declared = sum(s for s in sizes
                               if isinstance(s, int) and s > 0)

                async def _drain(total: int) -> None:
                    for _ in range(total // 65536):
                        await read_exact(reader, 65536)
                    if total % 65536:
                        await read_exact(reader, total % 65536)

                try:
                    # bounded in bytes AND time: a peer declaring a payload
                    # it never sends must not park this coroutine forever
                    await asyncio.wait_for(
                        _drain(min(declared, 512 * 1024 * 1024)),
                        HASH_PAYLOAD_TIMEOUT)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                    pass
            writer.write(json_frame({"ok": False, "error": "bad batch shape"}))
            await writer.drain()
            return
        member = await asyncio.get_running_loop().run_in_executor(
            None, lambda: any(peer.identity in self.nlm.member_nodes(lib)
                              for lib in self.node.libraries.list()))
        if not member:
            # the client writes the payload before reading the reply —
            # drain it so refused bytes don't sit in the substream buffer
            # until teardown (and a big batch doesn't hit the demux cap).
            # Same 30s bound as the bad-shape drain: a connected peer that
            # declares sizes but never sends the bytes must not park this
            # coroutine and its substream forever.
            try:
                await asyncio.wait_for(
                    _read_all_payload(reader, sizes, collect=False),
                    HASH_PAYLOAD_TIMEOUT)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                pass
            writer.write(json_frame({"ok": False, "error": "not a member"}))
            await writer.drain()
            return
        # node-wide admission budget (shared with the sync receive path):
        # remote hash batches are ingest too — over budget, the peer gets
        # an explicit busy answer (with the advised backoff) instead of
        # this node buffering sum(sizes) more in-flight bytes. The payload
        # is still drained (bounded) so the refusal, like the membership
        # one, does not strand bytes in the substream buffer.
        admission = None
        budget = getattr(self.node, "ingest_budget", None)
        if budget is not None:
            from ..sync.admission import Busy

            verdict = budget.try_admit(mesh.peer_label(peer.identity),
                                       len(sizes), sum(sizes))
            if isinstance(verdict, Busy):
                mesh.record_busy_sent(mesh.peer_label(peer.identity))
                try:
                    await asyncio.wait_for(
                        _read_all_payload(reader, sizes, collect=False),
                        HASH_PAYLOAD_TIMEOUT)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                    pass
                writer.write(json_frame({
                    "ok": False, "error": "busy", "busy": True,
                    "retry_after_ms": verdict.retry_after_ms}))
                await writer.drain()
                return
            admission = verdict
        try:
            try:
                messages = await asyncio.wait_for(
                    _read_all_payload(reader, sizes, collect=True),
                    HASH_PAYLOAD_TIMEOUT)
            except asyncio.TimeoutError:
                writer.write(json_frame({"ok": False,
                                         "error": "payload read timed out"}))
                await writer.drain()
                return

            from ..objects.hasher import hash_messages

            loop = asyncio.get_running_loop()
            # trace propagation: the requester's envelope (if any) parents
            # our serving span under ITS job trace — `telemetry.jobTrace
            # <job_id>` on the requesting node then shows where the batch
            # went, and this node's ring carries the serve under the same
            # trace_id
            label = mesh.peer_label(peer.identity)
            ctx = mesh.TraceContext.from_wire(payload.get("ctx"))
            trace = mesh.continue_trace(
                ctx, origin=str(self.node.config.get().get("id") or ""),
                name="p2p.hash")
            with mesh.remote_span(trace, ctx, "p2p.hash_serve", peer=label,
                                  files=len(messages),
                                  bytes=sum(sizes)):
                ids = await loop.run_in_executor(None, hash_messages,
                                                 messages)
            mesh.record_hash_serve(label, sum(sizes))
            writer.write(json_frame({"ok": True, "ids": ids}))
            await writer.drain()
        finally:
            if admission is not None:
                admission.release()

    async def request_hash_batch(self, peer_id: str,
                                 messages: list[bytes],
                                 ctx: "mesh.TraceContext | None" = None
                                 ) -> list[str]:
        """Ship cas messages to a peer's hasher; returns cas_ids in order.
        ``ctx`` (captured on the CALLING thread — this coroutine runs on
        the p2p loop, which has no span context) rides the header so the
        serving peer's span stitches under the caller's job trace."""
        from .. import faults

        # chaos seam for outbound peer requests (raising kinds only — a
        # ``hang`` rule here would stall the shared event loop)
        faults.inject("p2p_send", key=peer_id)
        reader, writer, _meta = await self.open_stream(peer_id)
        try:
            writer.write(Header.hash_batch(
                [len(m) for m in messages],
                ctx=ctx.to_wire() if ctx is not None else None).to_bytes())
            for m in messages:
                writer.write(m)
            await writer.drain()
            reply = await read_json(reader)
            if not reply.get("ok"):
                if reply.get("busy"):
                    # the peer's admission budget shed the batch — surface
                    # the typed BUSY (transient) so the hasher's fallback
                    # routes the batch to the local engine instead of
                    # treating the peer as broken
                    from ..faults import PeerBusyError

                    mesh.record_busy_received(mesh.peer_label(peer_id))
                    raise PeerBusyError(
                        "peer hasher busy",
                        retry_after_ms=int(reply.get("retry_after_ms") or 0))
                raise ProtocolError(reply.get("error", "hash batch refused"))
            ids = reply["ids"]
            if len(ids) != len(messages):
                raise ProtocolError("hash batch reply count mismatch")
            # counted only after the peer answered: an offline peer (local
            # fallback takes the batch) must not inflate "bytes shipped"
            if telemetry.enabled():
                _HASH_REQS.inc()
                _HASH_REQ_BYTES.inc(sum(len(m) for m in messages))
            return [str(i) for i in ids]
        finally:
            writer.close()

    # -- distributed replica serving (H_QUERY, ISSUE 19) ---------------------

    def _net_link_hook(self, dst_identity: str):
        """Sender-side :mod:`faults.net` hook for per-frame traversal:
        whole-file spacedrop/file-serve frames ride the armed model like
        delta frames, so ``bytes_by_link()`` ledgers them and a one-way
        ``a>b`` shaping plan covers the transfer direction."""
        from ..faults import net

        self_id = self.remote_identity.encode()

        async def link(nbytes: int) -> None:
            await net.alink(self_id, dst_identity, nbytes)

        return link

    async def _serve_query(self, reader, writer, payload: dict,
                           peer: Peer) -> None:
        """The H_QUERY responder arm: answer a pool-marked query from OUR
        replica of the library — after the membership gate, through
        :func:`~..server.replica.serve_query` (watermark eligibility,
        admission, the ``replica_serve`` chaos seam) in an executor so
        the SQLite read never parks the p2p loop. Reply wire shape: one
        JSON head; ``ok`` heads carry ``size`` and the encoded page bytes
        follow verbatim."""
        from ..server.replica import serve_query

        def _serve() -> dict:
            try:
                library = self.node.libraries.get(payload.get("library_id"))
            except KeyError:
                return {"ok": False, "kind": "not_eligible", "watermark": {}}
            if peer.identity not in self.nlm.member_nodes(library):
                return {"ok": False, "kind": "error", "error": "not a member"}
            return serve_query(self.node, payload, peer=peer.identity)

        reply = await asyncio.get_running_loop().run_in_executor(None, _serve)
        raw = reply.pop("raw", None)
        link = self._net_link_hook(peer.identity)
        if reply.get("ok") and isinstance(raw, (bytes, bytearray)):
            head = json_frame({"ok": True, "size": len(raw)})
            await link(len(head) + len(raw))
            writer.write(head)
            writer.write(bytes(raw))
        else:
            head = json_frame(reply)
            await link(len(head))
            writer.write(head)
        await writer.drain()

    def query_peers(self, library_id: str) -> list[str]:
        """Connected peers paired into ``library_id`` — the ReplicaRouter's
        candidate set. Membership is the same trust boundary file/preview/
        hash serving enforces (nlm.member_nodes)."""
        try:
            library = self.node.libraries.get(library_id)
        except KeyError:
            return []
        try:
            members = self.nlm.member_nodes(library)
        except Exception:
            return []
        return [ident for ident in members
                if (p := self.peers.get(ident)) is not None and p.connected]

    async def request_query(self, peer_id: str, payload: dict) -> dict:
        """Dispatch one pool-marked query to a replica peer. Returns the
        reply dict in :func:`~..server.replica.serve_query` shape; raises
        ``PeerBusyError`` on an explicit BUSY so the ReplicaRouter's
        cooldown honors the advised backoff, and ConnectionError-family
        on link failure."""
        from .. import faults
        from ..faults import PeerBusyError
        from ..server.replica import replica_timeout_s

        # chaos seam for outbound peer requests (raising kinds only)
        faults.inject("p2p_send", key=peer_id)
        timeout = replica_timeout_s()
        reader, writer, meta = await self.open_stream(peer_id)
        link = self._net_link_hook(meta.get("identity") or peer_id)
        try:
            hdr = Header.query(payload["library_id"], payload["key"],
                               payload.get("arg"),
                               payload.get("require") or {},
                               ctx=payload.get("ctx")).to_bytes()
            await link(len(hdr))
            writer.write(hdr)
            await writer.drain()
            head = await asyncio.wait_for(read_json(reader), timeout)
            if head.get("ok"):
                size = int(head.get("size") or 0)
                if size < 0 or size > 64 << 20:
                    raise ProtocolError(f"absurd query reply size {size}")
                raw = await asyncio.wait_for(read_exact(reader, size),
                                             timeout)
                return {"ok": True, "raw": raw}
            if head.get("kind") == "busy":
                mesh.record_busy_received(mesh.peer_label(peer_id))
                raise PeerBusyError(
                    "replica busy",
                    retry_after_ms=int(head.get("retry_after_ms") or 250))
            return head
        finally:
            writer.close()

    async def request_thumbnail(self, peer_id: str, library_id: str,
                                cas_id: str) -> bytes:
        """Fetch a member peer's cached preview bytes (custom_uri's remote
        thumbnail path)."""
        reader, writer, _meta = await self.open_stream(peer_id)
        try:
            writer.write(Header.thumbnail(library_id, cas_id).to_bytes())
            await writer.drain()
            head = await read_json(reader)
            if not head.get("ok"):
                raise ProtocolError(head.get("error", "thumbnail refused"))
            size = int(head["size"])
            if size > 16 * 1024 * 1024:
                raise ProtocolError("absurd thumbnail size")
            return await read_exact(reader, size)
        finally:
            writer.close()

    async def request_file(self, peer_id: str, library_id: str,
                           file_path_pub_id: str, rng: Range,
                           sink) -> int:
        """Fetch a peer's file bytes into ``sink`` (a writable binary file
        object). Used by custom_uri's remote path (custom_uri.rs:64-69)."""
        reader, writer, _meta = await self.open_stream(peer_id)
        try:
            writer.write(Header.file(library_id, file_path_pub_id, rng).to_bytes())
            await writer.drain()
            head = await read_json(reader)
            if not head.get("ok"):
                raise ProtocolError(head.get("error", "file request refused"))
            req = SpaceblockRequest.from_wire(head)
            total = (req.size if req.range.end is None
                     else min(req.range.end, req.size)) - req.range.start
            got = 0
            while got < total:
                msg = await read_block_msg(reader)
                if msg is None:
                    raise ProtocolError("peer cancelled file transfer")
                _offset, data = msg
                sink.write(data)
                got += len(data)
            return got
        finally:
            writer.close()

    # -- state for the API ---------------------------------------------------
    def peer_list(self) -> list[dict[str, Any]]:
        return [p.to_wire() for p in self.peers.values()]

    def nlm_state(self) -> dict[str, Any]:
        return self.nlm.state()

    def pair(self, peer_id: str) -> int:
        return self.pairing.originator(peer_id)

    def pairing_response(self, pairing_id: int, decision: Any) -> None:
        self.pairing.decision(pairing_id, decision)
