"""Per-peer session throttling at the accept layer (ROADMAP fleet rung (c)).

Admission control (sync/admission.py) sheds *windows* with an explicit
BUSY and trusts the peer to back off. A malicious or broken peer can
ignore BUSY and just keep opening sessions — each one costs a header
parse, a responder coroutine, and an admission round-trip before it is
shed again. This module bounds that at the cheapest possible point: the
substream accept layer, BEFORE any session machinery runs.

:class:`SessionThrottle` is a classic token bucket per peer identity:
``SD_P2P_SESSION_RATE`` tokens/s (default 10) with a burst of
``SD_P2P_SESSION_BURST`` (default 30). Well-behaved peers (a handful of
sessions per push round plus hash batches) never notice it; a
BUSY-ignoring flooder drains its bucket and gets its substreams RESET at
accept, counted per peer in ``sd_p2p_throttled_sessions_total`` — the
series an operator (or a future auto-ban rung) watches.

Buckets are per-peer and bounded in number (LRU past ``MAX_PEERS``), so
an identity-churning flooder cannot balloon the map.
"""

from __future__ import annotations

import os
import threading
import time

from .. import telemetry
from ..telemetry import mesh

DEFAULT_RATE = float(os.environ.get("SD_P2P_SESSION_RATE", "10"))
DEFAULT_BURST = float(os.environ.get("SD_P2P_SESSION_BURST", "30"))

_THROTTLED = telemetry.counter(
    "sd_p2p_throttled_sessions_total",
    "inbound sessions refused by the per-peer accept-layer token bucket",
    labels=("peer",))


class SessionThrottle:
    """Token bucket per peer; ``admit(peer_id)`` spends one token or
    refuses. Thread-safe; ``clock`` is injectable for tests."""

    MAX_PEERS = 1024

    def __init__(self, rate: float = DEFAULT_RATE,
                 burst: float = DEFAULT_BURST, clock=time.monotonic) -> None:
        self.rate = max(0.1, float(rate))
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._lock = threading.Lock()
        #: peer id -> (tokens, last refill stamp); insertion-ordered for LRU
        self._buckets: dict[str, tuple[float, float]] = {}
        self._throttled = 0

    def admit(self, peer_id: str) -> bool:
        now = self._clock()
        label = mesh.peer_label(peer_id)
        with self._lock:
            tokens, last = self._buckets.pop(peer_id, (self.burst, now))
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            admitted = tokens >= 1.0
            if admitted:
                tokens -= 1.0
            else:
                self._throttled += 1
            self._buckets[peer_id] = (tokens, now)  # re-insert = LRU touch
            while len(self._buckets) > self.MAX_PEERS:
                self._buckets.pop(next(iter(self._buckets)))
        if not admitted:
            _THROTTLED.inc(peer=label)
            telemetry.event("p2p.session_throttled", peer=label)
        return admitted

    def retry_after_s(self, peer_id: str) -> float:
        """Seconds until the peer's bucket holds one token again."""
        with self._lock:
            tokens, _last = self._buckets.get(peer_id, (self.burst, 0.0))
        return max(0.0, (1.0 - tokens) / self.rate)

    def status(self) -> dict:
        with self._lock:
            return {"rate_per_s": self.rate, "burst": self.burst,
                    "tracked_peers": len(self._buckets),
                    "throttled_sessions": self._throttled}
