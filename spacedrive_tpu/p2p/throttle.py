"""Per-peer session throttling at the accept layer (ROADMAP fleet rung (c)).

Admission control (sync/admission.py) sheds *windows* with an explicit
BUSY and trusts the peer to back off. A malicious or broken peer can
ignore BUSY and just keep opening sessions — each one costs a header
parse, a responder coroutine, and an admission round-trip before it is
shed again. This module bounds that at the cheapest possible point: the
substream accept layer, BEFORE any session machinery runs.

:class:`SessionThrottle` is a classic token bucket per peer identity:
``SD_P2P_SESSION_RATE`` tokens/s (default 10) with a burst of
``SD_P2P_SESSION_BURST`` (default 30). Well-behaved peers (a handful of
sessions per push round plus hash batches) never notice it; a
BUSY-ignoring flooder drains its bucket and gets its substreams RESET at
accept, counted per peer in ``sd_p2p_throttled_sessions_total`` — the
series an operator (or a future auto-ban rung) watches.

Buckets are per-peer and bounded in number (LRU past ``MAX_PEERS``), so
an identity-churning flooder cannot balloon the map.

:class:`AutoBan` (ISSUE 13) is the escalation rung above the bucket: a
peer that keeps hitting the throttle, or that ignores an explicit BUSY
answer and re-dials before its ``retry_after_ms`` elapsed, accumulates
**strikes**; enough strikes inside the strike window escalate to a timed
**ban** enforced at the same accept layer — banned substreams are RESET
before the header parse, the responder coroutine, or any admission spend.
Bans walk a ladder (each repeat offense doubles the duration up to a cap)
and expire on their own; every ban/unban lands in the flight-recorder
event ring and the :meth:`AutoBan.ledger`, and ``sd_p2p_banned_peers`` /
``sd_p2p_bans_total{reason}`` expose the live state. Well-behaved peers
can never reach a ban: honoring BUSY and the session rate keeps the
strike count at zero.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path

from .. import telemetry
from ..telemetry import mesh
from ..utils.locks import SdLock

logger = logging.getLogger(__name__)

DEFAULT_RATE = float(os.environ.get("SD_P2P_SESSION_RATE", "10"))
DEFAULT_BURST = float(os.environ.get("SD_P2P_SESSION_BURST", "30"))

#: strikes inside the window that escalate to a ban
DEFAULT_BAN_STRIKES = int(os.environ.get("SD_P2P_BAN_STRIKES", "8"))
#: sliding strike window (seconds)
DEFAULT_BAN_WINDOW_S = float(os.environ.get("SD_P2P_BAN_WINDOW_S", "10"))
#: first ban duration; doubles per repeat offense (the ladder)
DEFAULT_BAN_S = float(os.environ.get("SD_P2P_BAN_S", "30"))
#: ladder cap
DEFAULT_BAN_MAX_S = float(os.environ.get("SD_P2P_BAN_MAX_S", "600"))

_THROTTLED = telemetry.counter(
    "sd_p2p_throttled_sessions_total",
    "inbound sessions refused by the per-peer accept-layer token bucket",
    labels=("peer",))
_BANNED_PEERS = telemetry.gauge(
    "sd_p2p_banned_peers",
    "peers currently serving an accept-layer ban")
_BANS_TOTAL = telemetry.counter(
    "sd_p2p_bans_total",
    "accept-layer bans imposed, by triggering reason",
    labels=("reason",))


class SessionThrottle:
    """Token bucket per peer; ``admit(peer_id)`` spends one token or
    refuses. Thread-safe; ``clock`` is injectable for tests."""

    MAX_PEERS = 1024

    def __init__(self, rate: float = DEFAULT_RATE,
                 burst: float = DEFAULT_BURST, clock=time.monotonic) -> None:
        self.rate = max(0.1, float(rate))
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._lock = SdLock("p2p.throttle.buckets")
        #: peer id -> (tokens, last refill stamp); insertion-ordered for LRU
        self._buckets: dict[str, tuple[float, float]] = {}
        self._throttled = 0

    def admit(self, peer_id: str) -> bool:
        now = self._clock()
        label = mesh.peer_label(peer_id)
        with self._lock:
            tokens, last = self._buckets.pop(peer_id, (self.burst, now))
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            admitted = tokens >= 1.0
            if admitted:
                tokens -= 1.0
            else:
                self._throttled += 1
            self._buckets[peer_id] = (tokens, now)  # re-insert = LRU touch
            while len(self._buckets) > self.MAX_PEERS:
                self._buckets.pop(next(iter(self._buckets)))
        if not admitted:
            _THROTTLED.inc(peer=label)
            telemetry.event("p2p.session_throttled", peer=label)
        return admitted

    def retry_after_s(self, peer_id: str) -> float:
        """Seconds until the peer's bucket holds one token again."""
        with self._lock:
            tokens, _last = self._buckets.get(peer_id, (self.burst, 0.0))
        return max(0.0, (1.0 - tokens) / self.rate)

    def status(self) -> dict:
        with self._lock:
            return {"rate_per_s": self.rate, "burst": self.burst,
                    "tracked_peers": len(self._buckets),
                    "throttled_sessions": self._throttled}


class PeerBannedError(ConnectionError):
    """This node is serving an accept-layer ban to the peer (or: a peer is
    serving one to us). Transient — an honest peer that somehow earned a
    ban backs off ``retry_after_ms`` and resumes from its watermark like a
    BUSY; a flooder that ignores it keeps getting reset for free."""

    sd_transient = True

    def __init__(self, msg: str, retry_after_ms: int = 1000) -> None:
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class AutoBan:
    """Strike accounting + the timed ban ladder at the accept layer.

    Call order per inbound exchange (manager._dispatch_substream and the
    fleet harness's wire-less responder half):

    1. ``check(peer)`` — returns remaining ban seconds (reject cheaply)
       or ``None``; expires due bans (emitting the unban event).
    2. on a token-bucket refusal — ``strike(peer, "throttled")``.
    3. after answering BUSY on a sync session —
       ``note_busy(peer, retry_after_ms)``; the next **sync** substream
       from that peer is judged by ``judge_busy_compliance(peer)`` — an
       early return is a ``busy_ignored`` strike. Compliance is scoped to
       the protocol that was shed: an honest peer's concurrent pings or
       hash batches must never strike (the manager judges only in its
       ``H_SYNC`` arm, after the header parse).

    Thread-safe; ``clock`` injectable for deterministic ladder tests. All
    per-peer maps are bounded: strike/deadline/offense state is LRU-capped
    like the token buckets, and ban entries are swept on expiry (plus a
    hard cap evicting the soonest-to-expire), so identity churn cannot
    balloon any of them.
    """

    MAX_PEERS = 1024
    #: compliance slack: arrivals this close to the BUSY deadline are not
    #: strikes (timer granularity, not abuse)
    BUSY_GRACE_S = 0.005

    #: persistence format version (p2p_autoban.json under the data dir)
    LEDGER_VERSION = 1

    def __init__(self, strikes: int = DEFAULT_BAN_STRIKES,
                 window_s: float = DEFAULT_BAN_WINDOW_S,
                 ban_s: float = DEFAULT_BAN_S,
                 max_ban_s: float = DEFAULT_BAN_MAX_S,
                 clock=time.monotonic,
                 persist_path: str | Path | None = None,
                 wall_clock=time.time) -> None:
        self.strikes = max(1, int(strikes))
        self.window_s = max(0.1, float(window_s))
        self.ban_s = max(0.1, float(ban_s))
        self.max_ban_s = max(self.ban_s, float(max_ban_s))
        self._clock = clock
        # persistence (ISSUE 15 satellite, fleet rung c): active bans +
        # strike/ladder state survive a restart, so a rebooted node does
        # not amnesty a mid-ban abuser. Monotonic stamps don't survive a
        # process, so everything is stored as wall-clock-relative
        # durations and rebased onto the fresh monotonic clock at load.
        self._persist_path = Path(persist_path) if persist_path else None
        self._wall = wall_clock
        # non-reentrant: judge_busy_compliance deliberately releases it
        # before calling strike() — the lockset pass enforces that shape
        self._lock = SdLock("p2p.throttle.autoban")
        #: peer id -> strike timestamps inside the sliding window
        self._strikes: dict[str, list[float]] = {}
        #: peer id -> ban expiry stamp
        self._bans: dict[str, float] = {}
        #: peer id -> prior ban count (the ladder rung)
        self._offenses: dict[str, int] = {}
        #: peer id -> earliest allowed return after our last BUSY answer
        self._busy_until: dict[str, float] = {}
        #: [{event, peer, reason?, t, duration_s?}] — the ban ledger the
        #: WAN soak diffs against the flooder script
        self._ledger: list[dict] = []
        if self._persist_path is not None:
            self._load()

    # -- persistence ---------------------------------------------------------
    def _load(self) -> None:
        """Reload bans/strikes/ladder from disk with an expiry sweep:
        elapsed wall time since the save is charged against every
        duration, so a ban that would have expired while the node was
        down stays expired."""
        try:
            raw = json.loads(self._persist_path.read_text())
        except (OSError, ValueError):
            return
        if not isinstance(raw, dict) or raw.get("v") != self.LEDGER_VERSION:
            return
        try:
            elapsed = max(0.0, self._wall() - float(raw.get("saved", 0.0)))
            now = self._clock()
            with self._lock:
                for peer, remaining in dict(raw.get("bans", {})).items():
                    rem = float(remaining) - elapsed
                    if rem > 0:
                        self._bans[str(peer)] = now + rem
                for peer, rung in dict(raw.get("offenses", {})).items():
                    self._offenses[str(peer)] = int(rung)
                for peer, ages in dict(raw.get("strikes", {})).items():
                    stamps = [now - (float(age) + elapsed) for age in ages
                              if float(age) + elapsed < self.window_s]
                    if stamps:
                        self._strikes[str(peer)] = sorted(stamps)
                self._prune_locked()
                if self._bans:
                    _BANNED_PEERS.set(len(self._bans))
        except (TypeError, ValueError):
            logger.warning("autoban ledger %s malformed; starting clean",
                           self._persist_path)

    def _snapshot_locked(self) -> str:
        now = self._clock()
        return json.dumps({
            "v": self.LEDGER_VERSION,
            "saved": self._wall(),
            "bans": {p: round(until - now, 3)
                     for p, until in self._bans.items() if until > now},
            "offenses": dict(self._offenses),
            "strikes": {p: [round(now - t, 3) for t in log]
                        for p, log in self._strikes.items() if log},
        })

    def save(self) -> None:
        """Persist the live ban/strike state (crash-safe tempfile→fsync→
        rename); called on every ban/unban edge and at manager stop."""
        if self._persist_path is None:
            return
        with self._lock:
            payload = self._snapshot_locked()
        try:
            from ..utils.atomic import atomic_write_text

            atomic_write_text(self._persist_path, payload)
        except OSError as e:
            # ENOSPC-class: the ban still holds in memory; next edge retries
            logger.warning("autoban ledger save failed: %s", e)

    # -- the accept-path entry points ----------------------------------------
    def _sweep_locked(self, now: float) -> list[str]:
        """Drop every expired ban (caller holds the lock); returns the
        unbanned labels so the caller can emit events outside the lock.
        Keeps ``_bans`` bounded by churn and the gauge honest — a banned
        identity that never re-dials must not count as banned forever."""
        expired = [p for p, until in self._bans.items() if now >= until]
        labels = []
        for peer_id in expired:
            del self._bans[peer_id]
            label = mesh.peer_label(peer_id)
            labels.append(label)
            self._ledger.append({"event": "unban", "peer": label, "t": now})
        if expired:
            _BANNED_PEERS.set(len(self._bans))
        return labels

    def check(self, peer_id: str) -> float | None:
        """Remaining ban seconds for ``peer_id``, or None when admissible.
        Sweeps due bans (emitting unban events). Ban ENFORCEMENT only —
        BUSY compliance is judged separately, per shed protocol, by
        :meth:`judge_busy_compliance`."""
        now = self._clock()
        with self._lock:
            unbanned = self._sweep_locked(now)
            until = self._bans.get(peer_id)
            remaining = until - now if until is not None else None
        for label in unbanned:
            telemetry.event("p2p.unban", peer=label)
        return remaining

    def judge_busy_compliance(self, peer_id: str) -> float | None:
        """Judge an arrival on the protocol we previously answered BUSY:
        earlier than the deadline → a ``busy_ignored`` strike, which may
        escalate to a ban (the fresh ban's remaining seconds are
        returned; None means proceed). Call only on the shed protocol's
        substreams (the manager's ``H_SYNC`` arm / the harness sessions)
        so unrelated honest traffic can never strike."""
        now = self._clock()
        with self._lock:
            deadline = self._busy_until.pop(peer_id, None)
        if deadline is None or now >= deadline - self.BUSY_GRACE_S:
            return None
        if self.strike(peer_id, "busy_ignored"):
            with self._lock:
                until = self._bans.get(peer_id)
                if until is not None and now < until:
                    return until - now
        return None

    def strike(self, peer_id: str, reason: str) -> bool:
        """Record one strike; returns True when it escalated to a ban."""
        now = self._clock()
        label = mesh.peer_label(peer_id)
        banned_for = None
        with self._lock:
            self._sweep_locked(now)
            if peer_id in self._bans:
                return False  # already serving one; don't extend per hit
            # pop+reinsert = LRU touch (the token-bucket discipline): an
            # actively-striking peer moves to the back of the eviction
            # order, so identity churn evicts idle entries, never the
            # live abuser's strike state
            log = self._strikes.pop(peer_id, [])
            log.append(now)
            cutoff = now - self.window_s
            while log and log[0] < cutoff:
                log.pop(0)
            self._strikes[peer_id] = log
            if len(log) >= self.strikes:
                rung = self._offenses.pop(peer_id, 0)
                banned_for = min(self.max_ban_s, self.ban_s * (2 ** rung))
                self._offenses[peer_id] = rung + 1
                self._bans[peer_id] = now + banned_for
                self._strikes.pop(peer_id, None)
                self._busy_until.pop(peer_id, None)
                self._ledger.append({"event": "ban", "peer": label,
                                     "reason": reason, "t": now,
                                     "duration_s": banned_for})
                _BANNED_PEERS.set(len(self._bans))
            self._prune_locked()
        if banned_for is not None:
            _BANS_TOTAL.inc(reason=reason)
            telemetry.event("p2p.ban", peer=label, reason=reason,
                            duration_s=banned_for)
            # ban edges are rate-limited by construction (one per ladder
            # escalation), so the durable write here cannot become an
            # attacker-driven IO amplifier the way per-strike saves would
            self.save()
            return True
        return False

    def note_busy(self, peer_id: str, retry_after_ms: int) -> None:
        """Remember the deadline we just handed the peer in a BUSY answer;
        an arrival before it is a ``busy_ignored`` strike."""
        if retry_after_ms <= 0:
            return
        with self._lock:
            self._busy_until.pop(peer_id, None)  # LRU touch on re-arm
            self._busy_until[peer_id] = (self._clock()
                                         + retry_after_ms / 1000.0)
            self._prune_locked()

    # -- introspection -------------------------------------------------------
    def is_banned(self, peer_id: str) -> bool:
        with self._lock:
            until = self._bans.get(peer_id)
            return until is not None and self._clock() < until

    def ledger(self) -> list[dict]:
        """Chronological ban/unban entries (labels, not raw identities).
        Lazy expiry means a still-banned-at-shutdown peer has no unban
        entry — callers ``check()`` first if they need the edge."""
        with self._lock:
            return [dict(e) for e in self._ledger]

    def status(self) -> dict:
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            return {
                "banned_peers": len(self._bans),
                "strike_threshold": self.strikes,
                "window_s": self.window_s,
                "base_ban_s": self.ban_s,
                "bans_imposed": sum(1 for e in self._ledger
                                    if e["event"] == "ban"),
            }

    def _prune_locked(self) -> None:
        # identity churn must not balloon the maps (same argument as the
        # token buckets); active bans are additionally swept on expiry —
        # past the hard cap the soonest-to-expire go first (the closest
        # to leaving anyway), each with its unban edge recorded so every
        # ban in the ledger stays paired and the gauge stays honest
        for m in (self._strikes, self._busy_until, self._offenses):
            while len(m) > self.MAX_PEERS:
                m.pop(next(iter(m)))
        evicted = False
        while len(self._bans) > self.MAX_PEERS:
            soonest = min(self._bans, key=self._bans.__getitem__)
            del self._bans[soonest]
            self._ledger.append({"event": "unban",
                                 "peer": mesh.peer_label(soonest),
                                 "t": self._clock()})
            evicted = True
        if evicted:
            _BANNED_PEERS.set(len(self._bans))
        if len(self._ledger) > 4096:
            del self._ledger[:-2048]
