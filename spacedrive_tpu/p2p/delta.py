"""Delta-aware spacedrop (ISSUE 18): ship only the chunks the peer lacks.

A classic spacedrop streams the whole file. With chunk manifests
(ops/cdc.py — content-defined boundaries, so an insertion early in the
file shifts nothing downstream), the sender can instead:

1. chunk the file and send an ``H_DELTA`` header carrying the full
   manifest (``[[chunk_hash, length], ...]`` in file order);
2. the receiver — after the usual accept decision (same
   ``accept_spacedrop`` future as a plain drop) — chunks its own copy of
   the same-named file in the chosen directory with the SAME geometry and
   answers with the chunk hashes it already holds;
3. the sender streams only the missing chunks (one copy per distinct
   hash) as spaceblock block messages, in admission-bounded windows: each
   window is offered as ``{"window", "count", "nbytes"}``, and the
   receiver grants it through the node-wide :class:`IngestBudget` — over
   budget it answers BUSY with a backoff, and the sender re-offers the
   SAME window after sleeping (acked windows are never re-sent, which is
   what makes BUSY resumable instead of restart-from-zero);
4. the receiver reassembles the file from its base copy plus the received
   chunks, verifies EVERY chunk hash (received chunks are re-hashed;
   base chunks were hashed during step 2), writes a ``.sdpart`` sibling
   and ``os.replace``s it into place under ``find_available_name``.

Every frame the sender writes rides the armed :mod:`faults.net` model
(``_net_link``), so bandwidth-shaped ``SD_NET_PLAN`` runs measure real
bytes-on-wire per link — ``NetModel.bytes_by_link()`` is the ledger the
delta gate reads.

This module deliberately does NOT import :mod:`.manager` (manager imports
us); the manager instance arrives as a duck-typed parameter.
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid
from pathlib import Path
from typing import Any

from .. import faults, telemetry
from ..faults import net
from ..ops import cdc
from ..telemetry import mesh
from .proto import (Header, ProtocolError, block_msg, json_frame,
                    read_block_msg, read_exact, read_json)

logger = logging.getLogger(__name__)

#: chunks per admission window — one IngestBudget spend per window, so a
#: BUSY sheds ~WINDOW chunks of in-flight buffering, not the whole file
WINDOW = 64

#: accept decision + per-frame reply deadline (tests shrink via monkeypatch)
DELTA_TIMEOUT = 60.0

#: defensive bound on a declared manifest (64 GiB at max_size chunks)
MAX_CHUNKS = 1 << 20

# -- telemetry: declared at import time (api/routers/p2p.py imports this
# module at mount, i.e. on every Node construction — even with
# SD_P2P_DISABLED the families render with zero samples, keeping the
# observability.md drift gate honest in both directions)
_TRANSFERS = telemetry.counter(
    "sd_delta_transfers_total", "delta spacedrop transfers completed",
    labels=("role",))
_CHUNKS = telemetry.counter(
    "sd_delta_chunks_total",
    "sender-side chunk outcomes: shipped over the wire vs reused from "
    "the receiver's base copy", labels=("kind",))
_BYTES = telemetry.counter(
    "sd_delta_bytes_total",
    "sender-side payload bytes: shipped over the wire vs avoided because "
    "the receiver already held the chunk", labels=("kind",))
_BUSY = telemetry.counter(
    "sd_delta_busy_total",
    "delta windows shed by the receiver's admission budget (each one is "
    "a sleep-and-re-offer, never a restart)")


async def _net_link(src: str, dst: str, nbytes: int) -> None:
    """Loop-safe :mod:`faults.net` inject point (nlm.py idiom): the armed
    model decides synchronously and the modeled delay rides
    ``asyncio.sleep``; LinkCut/LinkDropped propagate as transient flaps."""
    model = net.active()
    if model is None:
        return
    delay = model.decide(src, dst, nbytes)
    if delay > 0.0:
        await asyncio.sleep(delay)


def _manifest_offsets(manifest: list[tuple[str, int]]) -> list[int]:
    offsets, off = [], 0
    for _h, ln in manifest:
        offsets.append(off)
        off += ln
    return offsets


def _chunk_file(data: bytes) -> list[tuple[str, int]]:
    """Both ends chunk with DEFAULT_PARAMS and the env-resolved kernel —
    byte-identical boundaries + ids on every rung is cdc.py's contract,
    so sender and receiver never disagree about what a chunk is."""
    return cdc.build_manifest(data)


def _verify_ids(datas: list[bytes]) -> list[str]:
    """Chunk ids of already-cut chunks (one whole-buffer chunk each)."""
    ids = cdc.chunk_ids(datas, [[(0, len(d))] for d in datas])
    return [i[0] for i in ids]


# -- sender -------------------------------------------------------------------

async def send_delta(mgr: Any, drop_id: str, peer_id: str, path: Path) -> None:
    """Runs on the p2p loop (``mgr.schedule``). Emits the same
    Spacedrop{Rejected,Done,Failed,Progress} events as a plain drop, plus
    delta accounting in the Done payload."""
    cancel = asyncio.Event()
    mgr._spacedrop_cancel[drop_id] = cancel
    loop = asyncio.get_running_loop()
    try:
        data = await loop.run_in_executor(None, path.read_bytes)
        manifest = await loop.run_in_executor(None, _chunk_file, data)
        offsets = _manifest_offsets(manifest)
        # chaos seam for outbound peer requests (raising kinds only)
        faults.inject("p2p_send", key=peer_id)
        reader, writer, _meta = await mgr.open_stream(peer_id)
        self_id = mgr.remote_identity.encode()
        try:
            hdr = Header.delta(drop_id, path.name, len(data),
                               [[h, ln] for h, ln in manifest]).to_bytes()
            await _net_link(self_id, peer_id, len(hdr))
            writer.write(hdr)
            await writer.drain()
            decision = await asyncio.wait_for(read_exact(reader, 1),
                                              DELTA_TIMEOUT)
            if decision != b"\x01":
                mgr.emit({"type": "SpacedropRejected", "id": drop_id})
                return
            reply = await asyncio.wait_for(read_json(reader), DELTA_TIMEOUT)
            if not reply.get("ok"):
                raise ProtocolError(reply.get("error", "delta refused"))
            have = set(reply.get("have") or [])
            # one copy per distinct missing hash: the receiver reassembles
            # by hash, so within-file duplicate chunks ship once
            seen: set[str] = set()
            send_idx: list[int] = []
            for i, (h, _ln) in enumerate(manifest):
                if h in have or h in seen:
                    continue
                seen.add(h)
                send_idx.append(i)
            sent_bytes = 0
            total_send = sum(manifest[i][1] for i in send_idx) or 1
            windows = [send_idx[i:i + WINDOW]
                       for i in range(0, len(send_idx), WINDOW)]
            for w, idxs in enumerate(windows):
                while True:
                    if cancel.is_set():
                        raise ProtocolError("cancelled")
                    offer = json_frame({
                        "window": w, "count": len(idxs),
                        "nbytes": sum(manifest[i][1] for i in idxs)})
                    await _net_link(self_id, peer_id, len(offer))
                    writer.write(offer)
                    await writer.drain()
                    grant = await asyncio.wait_for(read_json(reader),
                                                   DELTA_TIMEOUT)
                    if grant.get("busy"):
                        # admission shed the window: sleep the advised
                        # backoff and re-offer THIS window — everything
                        # already acked stays acked
                        _BUSY.inc()
                        await asyncio.sleep(
                            max(0, int(grant.get("retry_after_ms") or 0))
                            / 1000.0)
                        continue
                    if not grant.get("go"):
                        raise ProtocolError("delta window refused")
                    for i in idxs:
                        off, ln = offsets[i], manifest[i][1]
                        msg = block_msg(off, data[off:off + ln])
                        await _net_link(self_id, peer_id, len(msg))
                        writer.write(msg)
                    await writer.drain()
                    ack = await asyncio.wait_for(read_json(reader),
                                                 DELTA_TIMEOUT)
                    if ack.get("ack") != w:
                        raise ProtocolError(f"bad delta ack: {ack!r}")
                    sent_bytes += sum(manifest[i][1] for i in idxs)
                    mgr.emit({"type": "SpacedropProgress", "id": drop_id,
                              "percent": int(sent_bytes * 100 / total_send)})
                    break
            done = json_frame({"done": True})
            await _net_link(self_id, peer_id, len(done))
            writer.write(done)
            await writer.drain()
            final = await asyncio.wait_for(read_json(reader), DELTA_TIMEOUT)
            if not final.get("ok"):
                raise ProtocolError(final.get("error", "delta assembly failed"))
            reused = len(manifest) - len(send_idx)
            _TRANSFERS.inc(role="sender")
            _CHUNKS.inc(len(send_idx), kind="sent")
            _CHUNKS.inc(reused, kind="reused")
            _BYTES.inc(sent_bytes, kind="sent")
            _BYTES.inc(len(data) - sent_bytes, kind="reused")
            mgr.emit({"type": "SpacedropDone", "id": drop_id,
                      "bytes": sent_bytes, "delta": True,
                      "chunks_sent": len(send_idx), "chunks_reused": reused,
                      "path": final.get("path")})
        finally:
            writer.close()
    except (OSError, asyncio.TimeoutError, ProtocolError) as e:
        mgr.emit({"type": "SpacedropFailed", "id": drop_id, "error": str(e)})
    finally:
        mgr._spacedrop_cancel.pop(drop_id, None)


# -- receiver -----------------------------------------------------------------

def _parse_manifest(payload: dict) -> tuple[str, int, list[tuple[str, int]]]:
    name = str(payload.get("name") or "received.bin")
    size = int(payload.get("size") or 0)
    raw = payload.get("chunks") or []
    if not isinstance(raw, list) or len(raw) > MAX_CHUNKS:
        raise ProtocolError("bad delta manifest shape")
    chunks: list[tuple[str, int]] = []
    for entry in raw:
        h, ln = str(entry[0]), int(entry[1])
        if ln <= 0 or len(h) != cdc.CHUNK_ID_HEX:
            raise ProtocolError("bad delta manifest entry")
        chunks.append((h, ln))
    if sum(ln for _h, ln in chunks) != size:
        raise ProtocolError("delta manifest does not cover the file")
    return name, size, chunks


async def serve_delta(mgr: Any, reader, writer, payload: dict, peer) -> None:
    """The ``H_DELTA`` responder (dispatched from the manager's substream
    elif chain). Raises into the dispatcher on protocol violations — the
    substream RESETs and the sender sees a fast failure."""
    from ..sync.admission import Busy

    name, size, chunks = _parse_manifest(payload)
    loop = asyncio.get_running_loop()
    drop_id = str(uuid.uuid4())
    fut: asyncio.Future = mgr._loop.create_future()
    mgr._spacedrop_in[drop_id] = {"future": fut, "req": payload,
                                  "peer": peer.identity}
    mgr.emit({"type": "SpacedropRequest", "id": drop_id,
              "identity": peer.identity, "name": name, "size": size,
              "delta": True, "chunks": len(chunks)})
    try:
        target_dir = await asyncio.wait_for(fut, DELTA_TIMEOUT)
    except asyncio.TimeoutError:
        target_dir = None
    finally:
        mgr._spacedrop_in.pop(drop_id, None)
    if target_dir is None:
        writer.write(b"\x00")
        await writer.drain()
        mgr.emit({"type": "SpacedropRejected", "id": drop_id})
        return
    writer.write(b"\x01")
    await writer.drain()

    # the offered name is attacker-controlled: basename only, same as the
    # plain spacedrop path
    safe_name = Path(name).name or "received.bin"
    base_path = Path(target_dir) / safe_name
    base_data = b""
    have: dict[str, tuple[int, int]] = {}  # hash -> (offset, length) in base
    if base_path.is_file():
        base_data = await loop.run_in_executor(None, base_path.read_bytes)
        base_manifest = await loop.run_in_executor(None, _chunk_file,
                                                   base_data)
        off = 0
        for h, ln in base_manifest:
            have.setdefault(h, (off, ln))
            off += ln
    # advertise only hashes the sender actually needs, length-checked
    needed = {h: ln for h, ln in chunks}
    usable = sorted(h for h, (_o, ln) in have.items()
                    if needed.get(h) == ln)
    writer.write(json_frame({"ok": True, "have": usable}))
    await writer.drain()

    offset_of = {off: i for i, off in
                 enumerate(_manifest_offsets([(h, ln) for h, ln in chunks]))}
    received: dict[str, bytes] = {}
    budget = getattr(mgr.node, "ingest_budget", None)
    while True:
        msg = await asyncio.wait_for(read_json(reader), DELTA_TIMEOUT)
        if msg.get("done"):
            break
        w = int(msg.get("window", -1))
        count = int(msg.get("count", 0))
        nbytes = int(msg.get("nbytes", 0))
        if count <= 0 or count > WINDOW or nbytes < 0:
            raise ProtocolError("bad delta window offer")
        admission = None
        if budget is not None:
            verdict = budget.try_admit(mesh.peer_label(peer.identity),
                                       count, nbytes)
            if isinstance(verdict, Busy):
                mesh.record_busy_sent(mesh.peer_label(peer.identity))
                writer.write(json_frame(
                    {"busy": True,
                     "retry_after_ms": verdict.retry_after_ms}))
                await writer.drain()
                continue
            admission = verdict
        try:
            writer.write(json_frame({"go": True}))
            await writer.drain()
            blocks: list[tuple[int, bytes]] = []
            for _ in range(count):
                blk = await asyncio.wait_for(read_block_msg(reader),
                                             DELTA_TIMEOUT)
                if blk is None:
                    raise ProtocolError("delta transfer cancelled")
                blocks.append(blk)
            # per-chunk integrity: re-hash every received chunk and match
            # it against the manifest entry at its declared offset
            ids = await loop.run_in_executor(
                None, _verify_ids, [d for _o, d in blocks])
            for (off, data_b), cid in zip(blocks, ids):
                idx = offset_of.get(off)
                if idx is None:
                    raise ProtocolError(f"block at unknown offset {off}")
                h, ln = chunks[idx]
                if len(data_b) != ln or cid != h:
                    raise ProtocolError(f"chunk hash mismatch at {off}")
                received[h] = data_b
            writer.write(json_frame({"ack": w}))
            await writer.drain()
        finally:
            if admission is not None:
                admission.release()

    # reassemble: base copy for advertised hashes, wire bytes for the rest
    parts: list[bytes] = []
    for h, ln in chunks:
        if h in received:
            parts.append(received[h])
        elif h in have and have[h][1] == ln:
            off = have[h][0]
            parts.append(base_data[off:off + ln])
        else:
            raise ProtocolError(f"chunk {h} never arrived")
    blob = b"".join(parts)
    if len(blob) != size:
        raise ProtocolError("reassembled size mismatch")

    from ..objects.fs import find_available_name

    target = find_available_name(Path(target_dir) / safe_name)
    part = target.with_name(target.name + ".sdpart")

    def _persist() -> None:
        part.write_bytes(blob)
        os.replace(part, target)

    await loop.run_in_executor(None, _persist)
    writer.write(json_frame({"ok": True, "path": str(target)}))
    await writer.drain()
    _TRANSFERS.inc(role="receiver")
    mgr.emit({"type": "SpacedropDone", "id": drop_id, "path": str(target),
              "delta": True, "chunks_received": len(received),
              "chunks_reused": len(chunks) - len(received)})
