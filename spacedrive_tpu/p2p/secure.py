"""Encrypted, authenticated p2p streams (the transport the reference left TODO).

The reference rides libp2p QUIC, whose TLS handshake authenticates the
connection (crates/p2p/src/manager.rs:62-79), while its application-level
``Tunnel`` encryption is an acknowledged stub (spacetunnel/tunnel.rs:23,39).
Our TCP control plane therefore carries its own AKE + record layer:

**Handshake (SIGMA-style sign-and-encrypt):**

1. initiator → responder: ``MAGIC || e_i`` (fresh X25519 public key)
2. responder → initiator: ``e_r`` (fresh X25519 public key)
3. both derive ``k_i2r, k_r2i = HKDF(DH(e_i, e_r), info=transcript)`` and
   switch the socket to the encrypted record layer — *everything* after the
   two ephemerals (metadata, signatures, headers, sync ops, file blocks) is
   ChaCha20Poly1305-sealed.
4. responder → initiator (encrypted): ``ident_r + sign_r(T("resp", e_i,
   e_r, ident_r))`` — identity proof ONLY, no metadata yet
5. initiator → responder (encrypted): metadata + ``sign_i(T("init", e_i,
   e_r, ident_i, ident_r))``
6. responder → initiator (encrypted): metadata — sent only after the
   initiator's signature verifies (SIGMA-I ordering), so an anonymous
   prober can learn the responder's beaconed public identity but never
   harvests node names or per-library instance lists

Why this kills the round-2 signature oracle: each party only ever signs a
domain-separated transcript containing an ephemeral key **it generated
itself this connection** — there is no way to extract a signature over
attacker-chosen material that verifies in any other session. A relay
(machine-in-the-middle) fails because the victim's signature binds the
victim's own DH share, which the relay cannot reuse: the downstream leg has
a different ephemeral pair, so the relayed signature's transcript never
matches. The responder completes no application read until the initiator's
signature verifies (no pre-auth signing service beyond the self-bound
transcript), and the initiator pins the responder's identity when it dialed
a known peer, so discovery beacons cannot redirect a dial to an impostor.

**Record layer:** 4-byte big-endian ciphertext length || ChaCha20Poly1305
ciphertext. Nonce = 12-byte little-endian record counter; separate keys per
direction, so counters never collide. Plaintext is chunked to ≤64KiB per
record to bound buffering; spaceblock's large blocks simply span records.
"""

from __future__ import annotations

import asyncio

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    HAVE_CRYPTOGRAPHY = True
except ImportError:
    # Dependency-gated (image without ``cryptography``): importing the p2p
    # package must not explode — library creation only needs identity.py,
    # which has a pure-Python fallback. Session crypto has none (X25519 +
    # ChaCha20Poly1305 are not reimplemented here), so every entry point
    # below raises at USE time and Node._start_p2p's existing try/except
    # keeps the node running offline.
    HAVE_CRYPTOGRAPHY = False

    class InvalidTag(Exception):  # type: ignore[no-redef]
        pass

    class _Unavailable:
        def __init__(self, *_a: object, **_k: object) -> None:
            raise RuntimeError(
                "p2p session crypto requires the 'cryptography' package")

        generate = classmethod(lambda cls: cls())
        from_public_bytes = classmethod(lambda cls, _raw: cls())

    X25519PrivateKey = X25519PublicKey = ChaCha20Poly1305 = HKDF = _Unavailable  # type: ignore[misc]
    hashes = None  # type: ignore[assignment]

from .proto import ProtocolError

RECORD_MAX = 64 * 1024          # plaintext bytes per record
_CIPHERTEXT_MAX = RECORD_MAX + 16  # + poly1305 tag

AKE_LABEL = b"SDP3-AKE1"  # versioned with manager.py's wire MAGIC (SDP3)


def gen_ephemeral() -> tuple[X25519PrivateKey, bytes]:
    """Fresh X25519 keypair; returns (private, raw 32-byte public)."""
    priv = X25519PrivateKey.generate()
    return priv, priv.public_key().public_bytes_raw()


def derive_session_keys(eph_priv: X25519PrivateKey, peer_pub: bytes,
                        e_i: bytes, e_r: bytes) -> tuple[bytes, bytes]:
    """(k_i2r, k_r2i) from the ephemeral DH, bound to the exact key shares."""
    if len(peer_pub) != 32:
        raise ProtocolError("bad ephemeral key length")
    shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(peer_pub))
    okm = HKDF(algorithm=hashes.SHA256(), length=64, salt=None,
               info=AKE_LABEL + b"|keys|" + e_i + e_r).derive(shared)
    return okm[:32], okm[32:]


def transcript(role: str, e_i: bytes, e_r: bytes, *identities: str) -> bytes:
    """Domain-separated signing transcript. ``role`` breaks init/resp
    symmetry; the ephemerals bind the signature to this one connection;
    identities prevent unknown-key-share rebinding."""
    return (AKE_LABEL + b"|" + role.encode() + b"|" + e_i + e_r + b"|"
            + "|".join(identities).encode())


class SecureReader:
    """Decrypting façade over an ``asyncio.StreamReader``; implements the
    one method (`readexactly`) the wire helpers in proto.py use."""

    def __init__(self, reader: asyncio.StreamReader, key: bytes) -> None:
        self._reader = reader
        self._aead = ChaCha20Poly1305(key)
        self._counter = 0
        self._buf = bytearray()

    async def _read_record(self) -> None:
        try:
            head = await self._reader.readexactly(4)
        except asyncio.IncompleteReadError as e:
            raise ProtocolError(
                f"stream closed mid-record ({len(e.partial)}/4)") from e
        n = int.from_bytes(head, "big")
        if not 16 <= n <= _CIPHERTEXT_MAX:
            raise ProtocolError(f"bad record length {n}")
        try:
            ct = await self._reader.readexactly(n)
        except asyncio.IncompleteReadError as e:
            raise ProtocolError(
                f"stream closed mid-record ({len(e.partial)}/{n})") from e
        nonce = self._counter.to_bytes(12, "little")
        self._counter += 1
        try:
            self._buf += self._aead.decrypt(nonce, ct, None)
        except InvalidTag as e:
            raise ProtocolError("record authentication failed") from e

    async def readexactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            await self._read_record()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class SecureWriter:
    """Encrypting façade over an ``asyncio.StreamWriter``."""

    def __init__(self, writer: asyncio.StreamWriter, key: bytes) -> None:
        self._writer = writer
        self._aead = ChaCha20Poly1305(key)
        self._counter = 0

    def write(self, data: bytes) -> None:
        for off in range(0, len(data), RECORD_MAX):
            chunk = bytes(data[off:off + RECORD_MAX])
            nonce = self._counter.to_bytes(12, "little")
            self._counter += 1
            ct = self._aead.encrypt(nonce, chunk, None)
            self._writer.write(len(ct).to_bytes(4, "big") + ct)

    async def drain(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        self._writer.close()

    async def wait_closed(self) -> None:
        await self._writer.wait_closed()

    def get_extra_info(self, name: str, default=None):
        return self._writer.get_extra_info(name, default)
