"""LAN peer discovery: UDP beacons + static peer list.

Role of the reference's mDNS daemon (crates/p2p/src/discovery/mdns.rs:20,
60s re-advertisement with metadata TXT records): each node periodically
broadcasts a small JSON beacon carrying its PeerMetadata equivalent
(peer_metadata.rs — node id/name, public identity, TCP port, per-library
instance identities, accelerator inventory for remote-hasher routing) and
expires peers it stops hearing from.

Design differences, deliberate for this environment:

- plain UDP broadcast (255.255.255.255 + 127.0.0.1) on a fixed port with
  SO_REUSEPORT instead of true mDNS — zero-dependency, works between
  processes on one host and on a flat LAN; beacons fail soft where the
  sandbox forbids broadcast;
- a static peer list (``p2p_static_peers`` node-config key) for networks
  where UDP is filtered — the manager handshake doubles as metadata
  exchange, so a bare ``host:port`` is enough to bootstrap.
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import time
from dataclasses import dataclass, field
from typing import Any, Callable

logger = logging.getLogger(__name__)

BEACON_INTERVAL = 10.0  # seconds (reference re-advertises every 60s)
PEER_EXPIRY = 3.5 * BEACON_INTERVAL


@dataclass
class DiscoveredPeer:
    identity: str            # RemoteIdentity b64 (the peer id)
    host: str
    port: int                # TCP listen port
    metadata: dict[str, Any] = field(default_factory=dict)
    last_seen: float = field(default_factory=time.monotonic)

    @property
    def addr(self) -> tuple[str, int]:
        return self.host, self.port


class _BeaconProtocol(asyncio.DatagramProtocol):
    def __init__(self, discovery: "Discovery") -> None:
        self.discovery = discovery

    def datagram_received(self, data: bytes, addr: tuple[str, int]) -> None:
        self.discovery._on_beacon(data, addr)


class Discovery:
    """Runs inside the P2P manager's event loop."""

    def __init__(self, port: int, metadata_fn: Callable[[], dict[str, Any]],
                 on_peer: Callable[[DiscoveredPeer, bool], None],
                 on_expired: Callable[[DiscoveredPeer], None]) -> None:
        self.port = port
        self.metadata_fn = metadata_fn  # fresh beacon payload each tick
        self.on_peer = on_peer          # (peer, is_new)
        self.on_expired = on_expired
        self.peers: dict[str, DiscoveredPeer] = {}
        self._transport: asyncio.DatagramTransport | None = None
        self._task: asyncio.Task | None = None
        self._own_identity: str | None = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if hasattr(socket, "SO_REUSEPORT"):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_BROADCAST, 1)
        sock.setblocking(False)
        sock.bind(("0.0.0.0", self.port))
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _BeaconProtocol(self), sock=sock)
        self._task = asyncio.create_task(self._tick_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._transport:
            self._transport.close()

    async def _tick_loop(self) -> None:
        while True:
            try:
                self._send_beacon()
                self._expire()
            except Exception:
                logger.exception("discovery tick failed")
            await asyncio.sleep(BEACON_INTERVAL)

    def _send_beacon(self) -> None:
        meta = self.metadata_fn()
        self._own_identity = meta.get("identity")
        payload = json.dumps({"sd": 1, **meta}).encode()
        for dest in ("255.255.255.255", "127.0.0.1"):
            try:
                self._transport.sendto(payload, (dest, self.port))
            except OSError as e:  # broadcast can be forbidden in sandboxes
                logger.debug("beacon to %s failed: %s", dest, e)

    def _expire(self) -> None:
        cutoff = time.monotonic() - PEER_EXPIRY
        for ident in [i for i, p in self.peers.items() if p.last_seen < cutoff]:
            peer = self.peers.pop(ident)
            logger.info("peer expired: %s", ident[:12])
            self.on_expired(peer)

    def _on_beacon(self, data: bytes, addr: tuple[str, int]) -> None:
        try:
            meta = json.loads(data.decode())
        except ValueError:
            return
        if meta.get("sd") != 1:
            return
        identity = meta.get("identity")
        if not identity or identity == self._own_identity:
            return  # our own broadcast reflected back
        is_new = identity not in self.peers
        peer = DiscoveredPeer(identity=identity, host=addr[0],
                              port=int(meta.get("port", 0)), metadata=meta)
        self.peers[identity] = peer
        self.on_peer(peer, is_new)
