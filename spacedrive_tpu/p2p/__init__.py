"""p2p: the host-side distribution control plane.

The reference's stack (crates/p2p + core/src/p2p — libp2p QUIC transport,
mDNS discovery, ed25519 spacetunnel identities, pairing, NetworkedLibraries,
spaceblock transfer) rebuilt on asyncio TCP streams with real
challenge-response stream auth. The TPU *compute* plane (device mesh,
collectives) lives in ``spacedrive_tpu.parallel``; this package is how nodes
find each other, pair libraries, replicate CRDT ops, and move file bytes.
"""

from .discovery import DiscoveredPeer, Discovery
from .identity import (Identity, RemoteIdentity, decode_identity,
                       encode_identity, remote_identity_of)
from .manager import P2PManager, Peer
from .nlm import NetworkedLibraries
from .pairing import PairingManager
from .proto import Header, Range, SpaceblockRequest

__all__ = [
    "DiscoveredPeer", "Discovery", "Header", "Identity", "NetworkedLibraries",
    "P2PManager", "PairingManager", "Peer", "Range", "RemoteIdentity",
    "SpaceblockRequest", "decode_identity", "encode_identity",
    "remote_identity_of",
]
