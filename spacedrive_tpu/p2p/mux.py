"""Spacetime substream multiplexing: many exchanges over ONE connection.

Reference: crates/p2p/src/spacetime/ — the custom libp2p NetworkBehaviour
giving the application unicast substreams over a single QUIC connection
("sits between libp2p and the application... authentication, chucking",
spacetime/mod.rs:1-2; UnicastStream in stream.rs). TCP has no native
substreams, so this module carries a yamux-shaped framing on top of the
encrypted record layer (secure.py):

    frame := type(1) ‖ stream_id(4 BE) ‖ length(4 BE) ‖ payload

    OPEN  — first frame of a new substream (payload empty)
    DATA  — payload bytes for the stream
    CLOSE — half-close: the sender is done writing (reader sees EOF)
    RESET — abort: both directions die, pending reads fail

Stream ids are odd for the connection initiator and even for the responder
(enforced on receive), so simultaneous opens cannot collide. Large writes
queue per-substream and are flushed frame-at-a-time inside drain() with the
event loop yielding between frames, so one bulk transfer interleaves fairly
with concurrent exchanges instead of monopolizing the pipe or buffering a
whole spaceblock in the transport. Each substream's receive side is a real
asyncio.StreamReader fed by the demux loop — existing protocol code
(Header.from_stream, read_json, spaceblock) works on substreams unchanged.
Per-stream receive buffering is bounded: a peer overflowing BUFFER_CAP on
an unread stream gets that stream RESET, never unbounded memory.

One mutually-authenticated handshake now covers every exchange between a
peer pair for the life of the connection (the reference's QUIC session has
the same property), instead of one AKE per exchange.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Any, Awaitable, Callable

logger = logging.getLogger(__name__)

T_OPEN = 1
T_DATA = 2
T_CLOSE = 3
T_RESET = 4

FRAME_MAX = 128 * 1024          # payload cap per DATA frame (fairness)
BUFFER_CAP = 64 * 1024 * 1024   # per-substream unread cap (abuse guard)

_HDR = struct.Struct(">BII")


class MuxError(ConnectionError):
    """ConnectionError subclass so every existing p2p error path that
    handles a dead socket (except OSError / ConnectionError) also handles a
    dead or reset substream."""


class Substream:
    """One virtual stream: StreamReader-compatible receive side + a writer
    facade matching asyncio.StreamWriter's surface (write/drain/close/
    wait_closed/get_extra_info)."""

    def __init__(self, conn: "MuxConn", stream_id: int) -> None:
        self._conn = conn
        self.stream_id = stream_id
        self.reader = asyncio.StreamReader()
        self._write_closed = False
        self._reset = False
        self._out: list[bytes] = []  # pending frame payloads (flushed in drain)

    # -- reader surface (delegates; demux feeds self.reader) ----------------
    async def readexactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def read(self, n: int = -1) -> bytes:
        return await self.reader.read(n)

    async def readline(self) -> bytes:
        return await self.reader.readline()

    def at_eof(self) -> bool:
        return self.reader.at_eof()

    # -- writer surface ------------------------------------------------------
    def write(self, data: bytes) -> None:
        if self._write_closed or self._reset:
            raise MuxError(f"substream {self.stream_id} is closed for writing")
        for off in range(0, len(data), FRAME_MAX):
            self._out.append(bytes(data[off:off + FRAME_MAX]))

    async def drain(self) -> None:
        """Flush pending frames one at a time, yielding between frames so
        concurrent substreams interleave on the wire."""
        while self._out:
            if self._reset:
                self._out.clear()
                raise MuxError(f"substream {self.stream_id} was reset")
            chunk = self._out.pop(0)
            await self._conn._write_frame(T_DATA, self.stream_id, chunk)
        await self._conn._drain()

    def close(self) -> None:
        """Half-close (CLOSE frame): remote reader sees EOF; our reader
        stays usable until the remote half-closes too. Pending frames are
        emitted synchronously first (callers that skip the final drain keep
        the old StreamWriter.close semantics)."""
        if self._write_closed or self._reset:
            return
        self._write_closed = True
        for chunk in self._out:
            self._conn._queue_sync(T_DATA, self.stream_id, chunk)
        self._out.clear()
        self._conn._queue_control(T_CLOSE, self.stream_id)
        self._conn._maybe_forget(self.stream_id)

    async def wait_closed(self) -> None:
        await self._conn._drain()

    def reset(self) -> None:
        if self._reset:
            return
        self._reset = True
        self._write_closed = True
        self._out.clear()
        self.reader.feed_eof()
        self._conn._queue_control(T_RESET, self.stream_id)
        self._conn._forget(self.stream_id)

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        return self._conn.writer.get_extra_info(name, default)


class MuxConn:
    """One encrypted connection carrying many substreams.

    ``on_inbound(substream)`` is awaited as a task for every remote OPEN.
    """

    def __init__(self, reader, writer, initiator: bool,
                 on_inbound: Callable[[Substream], Awaitable[None]],
                 name: str = "") -> None:
        self.reader = reader
        self.writer = writer
        self.name = name
        self._next_id = 1 if initiator else 2
        self._streams: dict[int, Substream] = {}
        self._half_closed_remote: set[int] = set()
        self._on_inbound = on_inbound
        self._write_lock = asyncio.Lock()
        self.closed = asyncio.Event()
        self._tasks: set[asyncio.Task] = set()
        self._read_task = asyncio.get_running_loop().create_task(
            self._read_loop())

    # -- opening -------------------------------------------------------------
    def open_substream(self) -> Substream:
        if self.closed.is_set():
            raise MuxError("connection is closed")
        stream_id = self._next_id
        # single event-loop thread: every open_substream runs on the loop,
        # and _write_lock is an asyncio.Lock serializing FRAME interleave,
        # not thread concurrency — the += can never race itself
        self._next_id += 2  # lint: ok(lockset)
        sub = Substream(self, stream_id)
        self._streams[stream_id] = sub
        self._queue_control(T_OPEN, stream_id)
        return sub

    # -- frame emission ------------------------------------------------------
    async def _write_frame(self, frame_type: int, stream_id: int,
                           payload: bytes) -> None:
        """One frame per lock hold: the await inside is the fairness point
        where other substreams' drains interleave."""
        async with self._write_lock:
            self.writer.write(_HDR.pack(frame_type, stream_id, len(payload))
                              + payload)
            await self.writer.drain()

    def _queue_sync(self, frame_type: int, stream_id: int,
                    payload: bytes) -> None:
        try:
            self.writer.write(_HDR.pack(frame_type, stream_id, len(payload))
                              + payload)
        except Exception:
            pass  # connection already torn down

    def _queue_control(self, frame_type: int, stream_id: int) -> None:
        self._queue_sync(frame_type, stream_id, b"")

    async def _drain(self) -> None:
        async with self._write_lock:
            await self.writer.drain()

    # -- demux loop ----------------------------------------------------------
    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self.reader.readexactly(_HDR.size)
                frame_type, stream_id, length = _HDR.unpack(head)
                payload = (await self.reader.readexactly(length)
                           if length else b"")
                if frame_type == T_OPEN:
                    # id-parity rule: the remote may only open ids from ITS
                    # half of the space (we are initiator → remote ids even)
                    remote_parity = 0 if self._next_id % 2 == 1 else 1
                    if stream_id % 2 != remote_parity:
                        logger.warning("mux %s: OPEN with local-side id %d "
                                       "rejected", self.name, stream_id)
                        self._queue_control(T_RESET, stream_id)
                        continue
                    if stream_id in self._streams:
                        continue  # duplicate OPEN: ignore
                    sub = Substream(self, stream_id)
                    self._streams[stream_id] = sub
                    task = asyncio.get_running_loop().create_task(
                        self._on_inbound(sub))
                    self._tasks.add(task)
                    task.add_done_callback(self._tasks.discard)
                elif frame_type == T_DATA:
                    sub = self._streams.get(stream_id)
                    if sub is None or stream_id in self._half_closed_remote:
                        continue  # stale/reset stream: drop
                    buffered = len(sub.reader._buffer)  # bounded-abuse guard
                    if buffered + length > BUFFER_CAP:
                        logger.warning("mux %s: stream %d overflowed %d bytes"
                                       " unread; resetting", self.name,
                                       stream_id, BUFFER_CAP)
                        sub.reset()
                        continue
                    sub.reader.feed_data(payload)
                elif frame_type == T_CLOSE:
                    sub = self._streams.get(stream_id)
                    self._half_closed_remote.add(stream_id)
                    if sub is not None:
                        sub.reader.feed_eof()
                        self._maybe_forget(stream_id)
                elif frame_type == T_RESET:
                    sub = self._streams.pop(stream_id, None)
                    self._half_closed_remote.discard(stream_id)
                    if sub is not None:
                        sub._reset = True
                        sub._write_closed = True
                        sub.reader.feed_eof()
                else:
                    raise MuxError(f"unknown frame type {frame_type}")
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # remote closed the connection
        except Exception as e:
            from .proto import ProtocolError

            if isinstance(e, ProtocolError):
                # secure-record EOF surfaces as ProtocolError: a normal close
                logger.debug("mux %s: closed (%s)", self.name, e)
            else:
                logger.exception("mux %s: demux loop failed", self.name)
        finally:
            await self._teardown()

    def _maybe_forget(self, stream_id: int) -> None:
        """Drop bookkeeping once BOTH directions are done."""
        sub = self._streams.get(stream_id)
        if (sub is not None and sub._write_closed
                and stream_id in self._half_closed_remote):
            self._streams.pop(stream_id, None)
            self._half_closed_remote.discard(stream_id)

    def _forget(self, stream_id: int) -> None:
        self._streams.pop(stream_id, None)
        self._half_closed_remote.discard(stream_id)

    async def _teardown(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        for sub in list(self._streams.values()):
            sub._reset = True
            sub._write_closed = True
            sub.reader.feed_eof()
        self._streams.clear()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass

    def close(self) -> None:
        self._read_task.cancel()
        for task in list(self._tasks):
            task.cancel()

    async def aclose(self) -> None:
        """Deterministic shutdown: cancel the demux + handlers and wait for
        teardown (closed set, transport closed)."""
        self.close()
        await self._teardown()

    @property
    def alive(self) -> bool:
        return not self.closed.is_set()
