"""NetworkedLibraries: per-library peer state + sync-over-wire sessions.

Parity with core/src/p2p/sync/mod.rs:

- tracks ``InstanceState::{Unavailable, Discovered, Connected}`` per library
  instance, keyed by the instance's RemoteIdentity (:31-50), rebuilt from the
  instance table on library load/edit/instances-modified events (:60-91);
- on ``SyncMessage::Created`` from a library's sync manager, *originates* a
  sync session to every connected peer: ``Header::Sync(library_id)`` +
  ``NewOperations`` notify, then answers the responder's GetOperations pulls
  from ``sync.get_ops`` (:257-343);
- as *responder*, drives the ingest side: request batches with the library's
  per-instance HLC clocks, feed them to the Ingester, loop while has_more
  (:343-440). DB work runs in the default executor so the p2p loop never
  blocks on SQLite.
"""

from __future__ import annotations

import asyncio
import logging
import random
import uuid
from typing import TYPE_CHECKING, Any

from .. import faults, telemetry
from ..faults import PeerBusyError, net
from ..telemetry import mesh
from ..utils.retry import RetryPolicy, is_transient
from .identity import remote_identity_of
from .proto import (SYNC_NEW_OPERATIONS, Header, main_request_busy,
                    main_request_done, main_request_get_operations,
                    operations_frame, read_exact, read_json, read_json_sized)

if TYPE_CHECKING:
    from ..library import Library
    from .manager import P2PManager, Peer

logger = logging.getLogger(__name__)

OPS_PER_REQUEST = 1000  # sync/mod.rs responder OPS_PER_REQUEST

#: backoff shape for re-originating a push session after a mid-session
#: flap or a peer's BUSY answer (utils/retry.py's one policy type; the
#: sleep itself is asyncio — retry_call's blocking quanta would park the
#: shared p2p event loop)
ORIGINATE_RETRY = RetryPolicy(attempts=5, base_s=0.2, max_s=5.0,
                              budget_s=60.0)

UNAVAILABLE = "Unavailable"
DISCOVERED = "Discovered"
CONNECTED = "Connected"


class NetworkedLibraries:
    def __init__(self, manager: "P2PManager") -> None:
        self.manager = manager
        self.node = manager.node
        # lib_id -> instance RemoteIdentity str -> {"state", "peer"}
        self._libraries: dict[str, dict[str, dict[str, Any]]] = {}
        self._hooked: set[str] = set()  # libraries whose sync we subscribed
        # (library_id, peer_id) -> the responder's last-ACKNOWLEDGED HLC
        # clocks (every GetOperations request declares what is durably
        # applied; a BUSY frame carries an explicit watermark). A session
        # retry resumes from this instead of re-pushing applied windows.
        self._ack_watermarks: dict[tuple[str, str], dict[str, int]] = {}
        # single-flight latches (p2p event-loop only, no lock needed): a
        # (library, peer) with a live push session coalesces further
        # CREATED events into one rerun instead of stacking sessions
        self._originating: set[tuple[str, str]] = set()
        self._rerun: set[tuple[str, str]] = set()

    def attach(self) -> None:
        """Subscribe to library manager events (replays Load for loaded
        libraries) — called once the p2p loop is up."""
        from ..library import LibraryManagerEvent as E

        def on_event(event: str, library) -> None:
            if event == E.DELETE:
                self._libraries.pop(library.id, None)
                self._hooked.discard(library.id)
                return
            self._load_library(library)

        self.node.libraries.subscribe(on_event)

    # -- state maintenance ---------------------------------------------------
    def _load_library(self, library: "Library") -> None:
        """Rebuild this library's instance map from its instance table
        (sync/mod.rs load_library)."""
        from ..models import Instance

        entry: dict[str, dict[str, Any]] = {}
        own = {self.manager.remote_identity.encode()}
        for row in library.db.find(Instance):
            try:
                ident = remote_identity_of(row["identity"]).encode()
            except ValueError:
                continue  # placeholder identity from pre-p2p pairing
            if ident in own:
                continue
            entry[ident] = {"state": UNAVAILABLE, "peer": None}
        self._libraries[library.id] = entry
        if library.id not in self._hooked and library.sync is not None:
            self._hooked.add(library.id)
            from ..sync.manager import SyncMessage

            library.sync.subscribe(
                lambda msg, lib=library: self._on_sync_message(lib, msg))
        # fold in what we already know about peers
        for peer in self.manager.peers.values():
            self.peer_seen(peer)

    def _on_sync_message(self, library: "Library", msg: str) -> None:
        from ..sync.manager import SyncMessage

        if msg == SyncMessage.CREATED:
            self.manager.schedule(self.originate(library))

    def peer_seen(self, peer: "Peer") -> None:
        """Update instance states from a peer's advertised per-library
        instance identities; trigger a resync when a shared library's peer
        first connects (p2p_manager.rs:190-205 PeerConnected resync)."""
        state = CONNECTED if peer.connected else DISCOVERED
        for lib_id, idents in (peer.metadata.get("instances") or {}).items():
            lib_entry = self._libraries.get(lib_id)
            if lib_entry is None:
                continue
            for ident in idents:
                if ident == self.manager.remote_identity.encode():
                    continue
                cur = lib_entry.setdefault(ident, {"state": UNAVAILABLE, "peer": None})
                newly_connected = state == CONNECTED and cur["state"] != CONNECTED
                cur["state"] = state
                cur["peer"] = peer.identity
                if newly_connected:
                    try:
                        library = self.node.libraries.get(lib_id)
                    except KeyError:
                        continue
                    self.manager.schedule(self.originate(library))

    def peer_lost(self, peer: "Peer") -> None:
        for lib_entry in self._libraries.values():
            for ident, cur in lib_entry.items():
                if cur["peer"] == peer.identity:
                    cur["state"] = UNAVAILABLE
                    cur["peer"] = None

    def state(self) -> dict[str, Any]:
        """nlmState procedure payload (LibraryData map, sync/mod.rs:38-43)."""
        return {lib_id: {"instances": dict(entry)}
                for lib_id, entry in self._libraries.items()}

    # -- membership ----------------------------------------------------------
    def member_nodes(self, library: "Library") -> set[str]:
        """Node RemoteIdentities authorized for this library — the
        handshake-proven identities recorded on its instance rows at
        create/pairing time. The authorization anchor for sync sessions and
        files-over-p2p (the reference leaves this to its TODO-stubbed Tunnel
        auth; here it is enforced)."""
        from ..models import Instance

        return {r["node_remote_identity"] for r in library.db.find(Instance)
                if r.get("node_remote_identity")}

    # -- acknowledged-watermark bookkeeping ----------------------------------
    def _record_ack(self, library_id: str, peer_id: str,
                    clocks: Any) -> None:
        """Fold a responder-declared clock map into the peer's acknowledged
        watermark (only-raise: clocks are monotone floors of what that peer
        has DURABLY applied). Every GetOperations request is an implicit
        ack; a BUSY frame is an explicit one."""
        if not isinstance(clocks, dict):
            return
        wm = self._ack_watermarks.setdefault((library_id, peer_id), {})
        for pub_id, ts in clocks.items():
            if isinstance(pub_id, str) and isinstance(ts, int) \
                    and ts > wm.get(pub_id, 0):
                wm[pub_id] = ts

    def ack_watermark(self, library_id: str,
                      peer_id: str) -> dict[str, int] | None:
        """The last clocks ``peer_id`` acknowledged for ``library_id`` (a
        copy), or None before any session reached the serve loop."""
        wm = self._ack_watermarks.get((library_id, peer_id))
        return dict(wm) if wm is not None else None

    def _acked_everything(self, library: "Library", peer_id: str) -> bool:
        """True when the peer's acknowledged watermark already covers every
        op we could serve — a session retry would push zero windows."""
        wm = self._ack_watermarks.get((library.id, peer_id))
        if wm is None:
            return False
        ops, _has_more = library.sync.get_ops(dict(wm), 1)
        return not ops

    # -- originator (push notify + serve pulls) ------------------------------
    async def originate(self, library: "Library") -> None:
        """Alert every connected MEMBER peer that this library has new ops;
        each receiver then pulls from us over the same stream. One direction
        only (sync/mod.rs:288 'REMEMBER: This only syncs one direction!')."""
        members = self.member_nodes(library)
        targets = {p.identity for p in self.manager.peers.values()
                   if p.connected and p.identity in members}
        # concurrent per peer: one busy/flapping peer's backoff budget
        # (up to ORIGINATE_RETRY.budget_s) must not delay healthy peers
        await asyncio.gather(
            *(self._originate_single_flight(library, p) for p in targets))

    async def _originate_single_flight(self, library: "Library",
                                       peer_id: str) -> None:
        """At most one live push session per (library, peer). A burst of
        CREATED events (every emitted op fires one) used to stack a task
        per event, each independently re-dialing a peer whose admission
        control was already shedding load — retry amplification against
        the node this PR is trying to protect. Now later events coalesce
        into a single rerun of the running session (which serves from the
        live op-log, so a rerun only matters for ops that land after its
        final GetOperations). Latch flips happen between awaits on the one
        p2p loop — no lock."""
        key = (library.id, peer_id)
        if key in self._originating:
            self._rerun.add(key)
            return
        self._originating.add(key)
        try:
            while True:
                self._rerun.discard(key)
                await self._originate_with_retry(library, peer_id)
                if key not in self._rerun:
                    return
        finally:
            self._originating.discard(key)
            self._rerun.discard(key)

    async def _originate_with_retry(self, library: "Library",
                                    peer_id: str) -> None:
        """Drive one push session to completion through transient faults.

        A mid-session flap or a peer's BUSY answer used to abandon the push
        until the next local CREATED event — with admission control that
        would strand shed windows indefinitely. Retries back off on
        ORIGINATE_RETRY's jittered schedule (asyncio sleeps: retry_call's
        blocking quanta would park the shared p2p loop) and RESUME: every
        GetOperations request and BUSY frame updates the peer's
        acknowledged HLC watermark, so a retry whose watermark already
        covers our op-log is dropped outright instead of re-dialing and
        re-serving applied windows (and re-inflating the peer's declared
        backlog / sd_sync_peer_lag_ops)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + ORIGINATE_RETRY.budget_s
        retries = 0
        while True:
            try:
                await self._originate_to(library, peer_id)
                return
            except Exception as e:
                if not is_transient(e):
                    logger.debug("sync originate to %s failed: %s",
                                 peer_id[:12], e)
                    return
                retries += 1
                if retries >= ORIGINATE_RETRY.attempts:
                    logger.warning("sync originate to %s gave up after %d "
                                   "attempts: %s", peer_id[:12], retries, e)
                    return
                delay = ORIGINATE_RETRY.delay(retries - 1, random)
                busy = isinstance(e, PeerBusyError)
                if busy:
                    # the peer TOLD us when to come back; never earlier
                    delay = max(delay, e.retry_after_ms / 1000.0)
                    mesh.record_busy_received(mesh.peer_label(peer_id))
                if loop.time() + delay > deadline:
                    logger.warning("sync originate to %s exhausted its "
                                   "retry budget: %s", peer_id[:12], e)
                    return
                # resume-from-watermark: if everything we have is already
                # acknowledged as durable on the peer, the retry has
                # nothing to push (the flap ate only the goodbye). A DB
                # hiccup here (locked under the very load that caused the
                # retry, library unloaded mid-backoff) must not escape the
                # wrapper — it just means "can't prove done, retry".
                try:
                    done = await loop.run_in_executor(
                        None, self._acked_everything, library, peer_id)
                except Exception as check_err:
                    logger.debug("sync originate to %s: watermark check "
                                 "failed: %s", peer_id[:12], check_err)
                    done = False
                if done:
                    return
                logger.debug("sync originate to %s: retry %d in %.2fs "
                             "after %r", peer_id[:12], retries, delay, e)
                if busy:
                    mesh.record_busy_backoff(delay)
                await asyncio.sleep(delay)

    async def _net_link(self, src: str, dst: str, nbytes: int = 0) -> None:
        """The ``p2p_link`` inject point, loop-safe: the armed NetModel
        DECIDES synchronously (lock + seeded RNG, microseconds) and the
        modeled delay rides ``asyncio.sleep`` — a slow link neither parks
        the shared p2p event loop nor occupies a default-executor thread
        per message under fan-out; LinkCut/LinkDropped propagate to the
        caller as the transient flaps they model."""
        model = net.active()
        if model is None:
            return
        delay = model.decide(src, dst, nbytes)
        if delay > 0.0:
            await asyncio.sleep(delay)

    async def _originate_to(self, library: "Library", peer_id: str) -> None:
        # chaos seam for the sync-session dial (raising kinds only; `flap`
        # simulates the mesh's connection churn) — the fleet-soak gate's
        # p2p_send:flap rides this alongside the hash-batch seam, and the
        # link-level net model (partitions, loss, latency) bites here too
        faults.inject("p2p_send", key=peer_id)
        self_id = self.manager.remote_identity.encode()
        await self._net_link(self_id, peer_id, 64)
        origin = str(self.node.config.get().get("id") or "")
        reader, writer, _meta = await self.manager.open_stream(peer_id)
        # one mesh trace per push session, created only once the dial
        # SUCCEEDED (an offline peer's retry loop must not fill the
        # bounded trace ring with unfinished sessions): the receiver's
        # sync.apply spans parent under our per-window serving spans
        # (stitched by trace_id across both nodes' JSONL exports)
        trace = mesh.new_trace(
            "sync.push", origin,
            f"sync-{library.id[:8]}-{uuid.uuid4().hex[:12]}",
            library_id=library.id, peer=mesh.peer_label(peer_id))
        windows = served = 0
        try:
            writer.write(Header.sync(library.id).to_bytes())
            writer.write(SYNC_NEW_OPERATIONS)
            await writer.drain()
            loop = asyncio.get_running_loop()
            while True:
                req = await read_json(reader)
                kind = req.get("req")
                if kind == "busy":
                    # admission control shed our last window: the frame's
                    # watermark is an explicit ack of everything durably
                    # applied — record it, then surface BUSY to the retry
                    # wrapper (back off retry_after_ms, resume from there)
                    self._record_ack(library.id, peer_id,
                                     req.get("watermark"))
                    raise PeerBusyError(
                        f"peer {peer_id[:12]} shed the window",
                        retry_after_ms=int(req.get("retry_after_ms") or 0))
                if kind != "get_ops":
                    break  # done
                clocks = req.get("clocks") or {}
                # the request's clocks are the peer's durable floors — an
                # implicit acknowledgment of every op at-or-below them
                self._record_ack(library.id, peer_id, clocks)
                count = int(req.get("count") or OPS_PER_REQUEST)

                def _serve(clocks=clocks, count=count):
                    ops, has_more = library.sync.get_ops(clocks, count)
                    # backlog left AFTER this window — the receiver's
                    # sd_sync_peer_lag_ops signal rides the envelope
                    pending = (max(0, library.sync.ops_pending(clocks)
                                   - len(ops)) if has_more else 0)
                    return ops, has_more, pending

                with telemetry.span(trace, "sync.window") as span:
                    ops, has_more, pending = await loop.run_in_executor(
                        None, _serve)
                    span.set(ops=len(ops), has_more=has_more,
                             pending=pending)
                    ctx = None
                    if trace is not None:
                        ctx = mesh.TraceContext(
                            trace.trace_id, span.span_id, origin,
                            hlc=library.sync.clock.last,
                            pending=pending).to_wire()
                    frame = operations_frame(ops, has_more, ctx=ctx)
                    # every serving window crosses the modeled link (a
                    # partition or drop here mid-session surfaces as the
                    # transient the retry wrapper resumes from)
                    await self._net_link(self_id, peer_id, len(frame))
                    writer.write(frame)
                    await writer.drain()
                windows += 1
                served += len(ops)
        finally:
            writer.close()
            if trace is not None:
                trace.attrs.update(windows=windows, ops=served)
                node = self.node

                def _export() -> None:
                    telemetry.finish_trace(trace, export_dir=node.data_dir)
                    mesh.prune_session_traces(node.data_dir)

                await asyncio.get_running_loop().run_in_executor(
                    None, _export)
        telemetry.event("sync.push", peer=mesh.peer_label(peer_id),
                        library_id=library.id, windows=windows, ops=served)

    # -- responder (pull + ingest) -------------------------------------------
    async def responder(self, reader, writer, library_id: str,
                        peer: "Peer") -> None:
        """Drive the ingest pull loop for an incoming Sync stream."""
        try:
            library = self.node.libraries.get(library_id)
        except KeyError:
            writer.write(main_request_done())
            await writer.drain()
            return
        if peer.identity not in self.member_nodes(library):
            logger.warning("rejected sync for %s from non-member %s",
                           library_id[:8], peer.identity[:12])
            writer.write(main_request_done())
            await writer.drain()
            return
        notify = await read_exact(reader, 1)
        if notify != SYNC_NEW_OPERATIONS:
            logger.warning("unexpected sync message %r", notify)
            return
        from ..sync.admission import Busy
        from ..sync.ingest import Ingester
        from ..sync.lanes import get_lane_pool, lane_count

        ingester = Ingester(library, peer=peer.identity)
        label = mesh.peer_label(peer.identity)
        budget = getattr(self.node, "ingest_budget", None)
        loop = asyncio.get_running_loop()
        windows = total_ops = 0
        shed = False
        last_ctx: mesh.TraceContext | None = None
        while True:
            clocks = await loop.run_in_executor(None, library.sync.timestamps)
            writer.write(main_request_get_operations(clocks, OPS_PER_REQUEST))
            await writer.drain()
            batch, nbytes = await read_json_sized(reader)
            ops = batch.get("ops") or []
            # inbound half of the p2p_link seam: the peer's frame crosses
            # the modeled link toward us (loss/partition ends the session;
            # the peer's originate retry resumes from our durable clocks)
            await self._net_link(peer.identity,
                                 self.manager.remote_identity.encode(),
                                 nbytes)
            # the sender's trace-context envelope: stitches our apply spans
            # under its serving spans and carries the lag signal
            ctx = mesh.TraceContext.from_wire(batch.get("ctx"))
            if ctx is not None:
                last_ctx = ctx
            if ops:
                # admission control: the node-wide ingest budget bounds
                # (ops, bytes) admitted-but-not-yet-durable across EVERY
                # concurrent session. Over budget → answer BUSY with our
                # durable clocks (the ack watermark the originator resumes
                # from) instead of buffering the window, and end the
                # session — shed, don't crash.
                admission = None
                if budget is not None:
                    verdict = budget.try_admit(label, len(ops), nbytes)
                    if isinstance(verdict, Busy):
                        mesh.record_busy_sent(label)
                        # arm BUSY-compliance: a re-dial before this
                        # deadline is a strike toward an accept-layer ban
                        self.manager.auto_ban.note_busy(
                            peer.identity, verdict.retry_after_ms)
                        writer.write(main_request_busy(
                            verdict.retry_after_ms, clocks))
                        await writer.drain()
                        shed = True
                        break
                    admission = verdict

                def _apply(ops=ops, ctx=ctx):
                    if lane_count() > 1:
                        _applied, advanced = get_lane_pool(library).receive(
                            ops, ctx, peer=peer.identity)
                        ingester.last_floor_advanced = advanced
                    else:
                        ingester.receive(ops, ctx)

                try:
                    await loop.run_in_executor(None, _apply)
                finally:
                    if admission is not None:
                        admission.release()  # durable (or rolled back)
                windows += 1
                total_ops += len(ops)
                if not ingester.last_floor_advanced:
                    # every op in the window was skipped (malformed /
                    # transient poison) — the peer would hand us the
                    # identical window forever; stop the session instead
                    # of hot-looping on it
                    logger.warning("sync session with %s made no progress; "
                                   "ending round", peer.identity[:12])
                    break
            if not batch.get("has_more"):
                break
        if not shed:
            writer.write(main_request_done())
            await writer.drain()
        if last_ctx is not None:
            # persist our half of the stitched trace: the sender's export
            # holds the root + window spans, ours the apply spans — merged
            # by trace_id they are one tree
            from ..telemetry import spans as _spans

            trace = _spans.get_trace(last_ctx.trace_id)
            node = self.node
            if trace is not None:
                await loop.run_in_executor(
                    None, lambda: mesh.export_partial(trace, node.data_dir))
        mesh.record_session(label)
        telemetry.event("sync.session", peer=label,
                        library_id=library_id, windows=windows,
                        ops=total_ops)
        self.manager.emit({"type": "SyncIngested", "library_id": library_id,
                           "from": peer.identity})
