"""NetworkedLibraries: per-library peer state + sync-over-wire sessions.

Parity with core/src/p2p/sync/mod.rs:

- tracks ``InstanceState::{Unavailable, Discovered, Connected}`` per library
  instance, keyed by the instance's RemoteIdentity (:31-50), rebuilt from the
  instance table on library load/edit/instances-modified events (:60-91);
- on ``SyncMessage::Created`` from a library's sync manager, *originates* a
  sync session to every connected peer: ``Header::Sync(library_id)`` +
  ``NewOperations`` notify, then answers the responder's GetOperations pulls
  from ``sync.get_ops`` (:257-343);
- as *responder*, drives the ingest side: request batches with the library's
  per-instance HLC clocks, feed them to the Ingester, loop while has_more
  (:343-440). DB work runs in the default executor so the p2p loop never
  blocks on SQLite.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any

from .identity import remote_identity_of
from .proto import (SYNC_NEW_OPERATIONS, Header, main_request_done,
                    main_request_get_operations, operations_frame, read_exact,
                    read_json)

if TYPE_CHECKING:
    from ..library import Library
    from .manager import P2PManager, Peer

logger = logging.getLogger(__name__)

OPS_PER_REQUEST = 1000  # sync/mod.rs responder OPS_PER_REQUEST

UNAVAILABLE = "Unavailable"
DISCOVERED = "Discovered"
CONNECTED = "Connected"


class NetworkedLibraries:
    def __init__(self, manager: "P2PManager") -> None:
        self.manager = manager
        self.node = manager.node
        # lib_id -> instance RemoteIdentity str -> {"state", "peer"}
        self._libraries: dict[str, dict[str, dict[str, Any]]] = {}
        self._hooked: set[str] = set()  # libraries whose sync we subscribed

    def attach(self) -> None:
        """Subscribe to library manager events (replays Load for loaded
        libraries) — called once the p2p loop is up."""
        from ..library import LibraryManagerEvent as E

        def on_event(event: str, library) -> None:
            if event == E.DELETE:
                self._libraries.pop(library.id, None)
                self._hooked.discard(library.id)
                return
            self._load_library(library)

        self.node.libraries.subscribe(on_event)

    # -- state maintenance ---------------------------------------------------
    def _load_library(self, library: "Library") -> None:
        """Rebuild this library's instance map from its instance table
        (sync/mod.rs load_library)."""
        from ..models import Instance

        entry: dict[str, dict[str, Any]] = {}
        own = {self.manager.remote_identity.encode()}
        for row in library.db.find(Instance):
            try:
                ident = remote_identity_of(row["identity"]).encode()
            except ValueError:
                continue  # placeholder identity from pre-p2p pairing
            if ident in own:
                continue
            entry[ident] = {"state": UNAVAILABLE, "peer": None}
        self._libraries[library.id] = entry
        if library.id not in self._hooked and library.sync is not None:
            self._hooked.add(library.id)
            from ..sync.manager import SyncMessage

            library.sync.subscribe(
                lambda msg, lib=library: self._on_sync_message(lib, msg))
        # fold in what we already know about peers
        for peer in self.manager.peers.values():
            self.peer_seen(peer)

    def _on_sync_message(self, library: "Library", msg: str) -> None:
        from ..sync.manager import SyncMessage

        if msg == SyncMessage.CREATED:
            self.manager.schedule(self.originate(library))

    def peer_seen(self, peer: "Peer") -> None:
        """Update instance states from a peer's advertised per-library
        instance identities; trigger a resync when a shared library's peer
        first connects (p2p_manager.rs:190-205 PeerConnected resync)."""
        state = CONNECTED if peer.connected else DISCOVERED
        for lib_id, idents in (peer.metadata.get("instances") or {}).items():
            lib_entry = self._libraries.get(lib_id)
            if lib_entry is None:
                continue
            for ident in idents:
                if ident == self.manager.remote_identity.encode():
                    continue
                cur = lib_entry.setdefault(ident, {"state": UNAVAILABLE, "peer": None})
                newly_connected = state == CONNECTED and cur["state"] != CONNECTED
                cur["state"] = state
                cur["peer"] = peer.identity
                if newly_connected:
                    try:
                        library = self.node.libraries.get(lib_id)
                    except KeyError:
                        continue
                    self.manager.schedule(self.originate(library))

    def peer_lost(self, peer: "Peer") -> None:
        for lib_entry in self._libraries.values():
            for ident, cur in lib_entry.items():
                if cur["peer"] == peer.identity:
                    cur["state"] = UNAVAILABLE
                    cur["peer"] = None

    def state(self) -> dict[str, Any]:
        """nlmState procedure payload (LibraryData map, sync/mod.rs:38-43)."""
        return {lib_id: {"instances": dict(entry)}
                for lib_id, entry in self._libraries.items()}

    # -- membership ----------------------------------------------------------
    def member_nodes(self, library: "Library") -> set[str]:
        """Node RemoteIdentities authorized for this library — the
        handshake-proven identities recorded on its instance rows at
        create/pairing time. The authorization anchor for sync sessions and
        files-over-p2p (the reference leaves this to its TODO-stubbed Tunnel
        auth; here it is enforced)."""
        from ..models import Instance

        return {r["node_remote_identity"] for r in library.db.find(Instance)
                if r.get("node_remote_identity")}

    # -- originator (push notify + serve pulls) ------------------------------
    async def originate(self, library: "Library") -> None:
        """Alert every connected MEMBER peer that this library has new ops;
        each receiver then pulls from us over the same stream. One direction
        only (sync/mod.rs:288 'REMEMBER: This only syncs one direction!')."""
        members = self.member_nodes(library)
        targets = {p.identity for p in self.manager.peers.values()
                   if p.connected and p.identity in members}
        for peer_id in targets:
            try:
                await self._originate_to(library, peer_id)
            except Exception as e:
                logger.debug("sync originate to %s failed: %s", peer_id[:12], e)

    async def _originate_to(self, library: "Library", peer_id: str) -> None:
        reader, writer, _meta = await self.manager.open_stream(peer_id)
        try:
            writer.write(Header.sync(library.id).to_bytes())
            writer.write(SYNC_NEW_OPERATIONS)
            await writer.drain()
            loop = asyncio.get_running_loop()
            while True:
                req = await read_json(reader)
                if req.get("req") != "get_ops":
                    break  # done
                ops, has_more = await loop.run_in_executor(
                    None, library.sync.get_ops, req.get("clocks") or {},
                    int(req.get("count") or OPS_PER_REQUEST))
                writer.write(operations_frame(ops, has_more))
                await writer.drain()
        finally:
            writer.close()

    # -- responder (pull + ingest) -------------------------------------------
    async def responder(self, reader, writer, library_id: str,
                        peer: "Peer") -> None:
        """Drive the ingest pull loop for an incoming Sync stream."""
        try:
            library = self.node.libraries.get(library_id)
        except KeyError:
            writer.write(main_request_done())
            await writer.drain()
            return
        if peer.identity not in self.member_nodes(library):
            logger.warning("rejected sync for %s from non-member %s",
                           library_id[:8], peer.identity[:12])
            writer.write(main_request_done())
            await writer.drain()
            return
        notify = await read_exact(reader, 1)
        if notify != SYNC_NEW_OPERATIONS:
            logger.warning("unexpected sync message %r", notify)
            return
        from ..sync.ingest import Ingester

        ingester = Ingester(library)
        loop = asyncio.get_running_loop()
        while True:
            clocks = await loop.run_in_executor(None, library.sync.timestamps)
            writer.write(main_request_get_operations(clocks, OPS_PER_REQUEST))
            await writer.drain()
            batch = await read_json(reader)
            ops = batch.get("ops") or []
            if ops:
                await loop.run_in_executor(None, ingester.receive, ops)
                if not ingester.last_floor_advanced:
                    # every op in the window was skipped (malformed /
                    # transient poison) — the peer would hand us the
                    # identical window forever; stop the session instead
                    # of hot-looping on it
                    logger.warning("sync session with %s made no progress; "
                                   "ending round", peer.identity[:12])
                    break
            if not batch.get("has_more"):
                break
        writer.write(main_request_done())
        await writer.drain()
        self.manager.emit({"type": "SyncIngested", "library_id": library_id,
                           "from": peer.identity})
