"""ed25519 instance identities (spacetunnel).

Parity with crates/p2p/src/spacetunnel/identity.rs:19 (Identity/RemoteIdentity
keypairs) and core/src/p2p/identity_or_remote_identity.rs:48 (the tagged
encoding stored in the ``instance.identity`` DB column). The reference's
Tunnel e2e-encryption is a TODO stub (tunnel.rs:23,39); here the identities
are used for real challenge-response stream authentication instead
(manager.py handshake).

Keys ride on ``cryptography``'s ed25519 (the environment's libsodium-class
primitive) when the package is present; otherwise the RFC 8032 reference
implementation (``ed25519_ref``) takes over with identical bytes on the
wire — images without ``cryptography`` must not wedge every import of the
p2p package (library creation mints an identity). The wire/DB encoding is
urlsafe base64 of the raw 32-byte seed or public key, tagged ``I:`` (we
hold the private key) or ``R:`` (peer's public key only).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)

    _RAW = serialization.Encoding.Raw
    _RAW_PUB = serialization.PublicFormat.Raw
    _RAW_PRIV = serialization.PrivateFormat.Raw
    _NOENC = serialization.NoEncryption()
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # dependency-gated: pure-Python RFC 8032 fallback
    from . import ed25519_ref as _ref

    HAVE_CRYPTOGRAPHY = False

    class InvalidSignature(Exception):  # type: ignore[no-redef]
        pass

    class Ed25519PublicKey:  # type: ignore[no-redef]
        def __init__(self, raw: bytes) -> None:
            if len(raw) != 32:  # parity with cryptography's parse-time check
                raise ValueError("ed25519 public key must be 32 bytes")
            self._raw = raw

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
            return cls(raw)

        def public_bytes(self, *_: object) -> bytes:
            return self._raw

        def verify(self, signature: bytes, message: bytes) -> None:
            if not _ref.verify(self._raw, signature, message):
                raise InvalidSignature()

    class Ed25519PrivateKey:  # type: ignore[no-redef]
        def __init__(self, seed: bytes) -> None:
            if len(seed) != 32:  # a short/corrupt seed must fail loudly,
                # not silently derive a DIFFERENT keypair than the stored
                # identity (cryptography raises here too)
                raise ValueError("ed25519 private key must be 32 bytes")
            self._seed = seed

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(_ref.generate_seed())

        @classmethod
        def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
            return cls(seed)

        def private_bytes(self, *_: object) -> bytes:
            return self._seed

        def sign(self, message: bytes) -> bytes:
            return _ref.sign(self._seed, message)

        def public_key(self) -> Ed25519PublicKey:
            return Ed25519PublicKey(_ref.public_key(self._seed))

    _RAW = _RAW_PUB = _RAW_PRIV = _NOENC = None


def _b64e(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def _b64d(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


@dataclass(frozen=True)
class RemoteIdentity:
    """A peer's public key — the stable address of an instance/node."""

    public_bytes: bytes  # 32 raw bytes

    def __post_init__(self) -> None:
        if len(self.public_bytes) != 32:
            raise ValueError("ed25519 public key must be 32 bytes")

    def verify(self, signature: bytes, message: bytes) -> bool:
        key = Ed25519PublicKey.from_public_bytes(self.public_bytes)
        try:
            key.verify(signature, message)
            return True
        except InvalidSignature:
            return False

    def encode(self) -> str:
        return _b64e(self.public_bytes)

    @classmethod
    def decode(cls, s: str) -> "RemoteIdentity":
        return cls(_b64d(s))

    def __str__(self) -> str:  # peer id in events / UI
        return self.encode()


class Identity:
    """An ed25519 keypair we hold the private half of."""

    def __init__(self, private: Ed25519PrivateKey | None = None) -> None:
        self._key = private or Ed25519PrivateKey.generate()

    @classmethod
    def from_seed(cls, seed: bytes | str) -> "Identity":
        if isinstance(seed, str):
            seed = bytes.fromhex(seed) if len(seed) == 64 else _b64d(seed)
        return cls(Ed25519PrivateKey.from_private_bytes(seed[:32]))

    def seed(self) -> bytes:
        return self._key.private_bytes(_RAW, _RAW_PRIV, _NOENC)

    def sign(self, message: bytes) -> bytes:
        return self._key.sign(message)

    def to_remote_identity(self) -> RemoteIdentity:
        return RemoteIdentity(self._key.public_key().public_bytes(_RAW, _RAW_PUB))

    def encode(self) -> str:
        return _b64e(self.seed())

    @classmethod
    def decode(cls, s: str) -> "Identity":
        return cls.from_seed(_b64d(s))


# -- instance.identity column encoding --------------------------------------
# identity_or_remote_identity.rs:48 — one column stores either our private
# identity (for the instance this node owns) or a peer's public identity.

_I_TAG, _R_TAG = "I:", "R:"


def encode_identity(value: Identity | RemoteIdentity) -> str:
    if isinstance(value, Identity):
        return _I_TAG + value.encode()
    return _R_TAG + value.encode()


def decode_identity(s: str) -> Identity | RemoteIdentity:
    if s.startswith(_I_TAG):
        return Identity.decode(s[len(_I_TAG):])
    if s.startswith(_R_TAG):
        return RemoteIdentity.decode(s[len(_R_TAG):])
    raise ValueError(f"not an identity encoding: {s[:8]!r}")


def remote_identity_of(s: str) -> RemoteIdentity:
    """Public identity regardless of which side of the pair we hold."""
    v = decode_identity(s)
    return v.to_remote_identity() if isinstance(v, Identity) else v
