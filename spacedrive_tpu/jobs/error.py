"""Job errors (core/src/job/error.rs)."""

from __future__ import annotations


class JobError(Exception):
    """Fatal job failure → status Failed."""


class JobPaused(Exception):  # JobError::Paused(state, signal)
    """Raised by the command check to unwind the run loop; carries the
    serialized checkpoint."""

    def __init__(self, state_blob: bytes, from_shutdown: bool = False,
                 errors: list[str] | None = None) -> None:
        super().__init__("job paused")
        self.state_blob = state_blob
        self.from_shutdown = from_shutdown
        # soft step errors accumulated before the pause; persisted so a
        # resumed run still ends CompletedWithErrors (job/mod.rs:834-841).
        # List IDENTITY is kept (no `or []` collapse of an empty list): the
        # pipeline drain appends its leaked-stage soft error while this
        # exception is already in flight, and the worker must see it.
        self.errors = errors if errors is not None else []


class JobCanceled(Exception):  # JobError::Canceled
    pass


class EarlyFinish(Exception):  # JobError::EarlyFinish — clean no-op completion
    def __init__(self, reason: str = "nothing to do") -> None:
        super().__init__(reason)


class JobAlreadyRunning(JobError):
    """Dedup rejection: same job hash running or queued (manager.rs:109-114)."""
