"""Worker: one thread per running job.

Equivalent of core/src/job/worker.rs — owns the command channel, publishes
timed progress events as ``CoreEvent::JobProgress``, persists report
transitions, and computes ETA from step cadence.
"""

from __future__ import annotations

import datetime as dt
import logging
import queue
import threading
import time
from typing import TYPE_CHECKING

from .. import telemetry
from ..models import utc_now
from .error import JobCanceled, JobPaused
from .job import DynJob
from .report import JobStatus

if TYPE_CHECKING:
    from ..library import Library
    from .manager import Jobs

logger = logging.getLogger(__name__)

PROGRESS_THROTTLE_S = 0.05

_QUEUE_WAIT = telemetry.histogram(
    "sd_job_queue_wait_seconds", "dispatch-queue wait per job",
    labels=("lane",))
_COMPLETED = telemetry.counter(
    "sd_jobs_completed_total", "finished jobs by name and status",
    labels=("job", "status"))


class WorkerCommand:
    PAUSE = "pause"
    CANCEL = "cancel"
    SHUTDOWN = "shutdown"


class WorkerContext:
    """Passed to job code: progress reporting + command polling + library
    access (WorkerContext, worker.rs:53-88)."""

    def __init__(self, worker: "Worker") -> None:
        self._worker = worker
        self.library = worker.library
        self.node = worker.library.node if worker.library else None
        #: the job's telemetry trace (None with SD_TELEMETRY=off) — job code
        #: opens child spans with ``telemetry.span(ctx.trace, ...)``
        self.trace = getattr(worker, "trace", None)

    def progress(self, completed_task_count: int | None = None,
                 task_count: int | None = None, message: str | None = None) -> None:
        self._worker.update_progress(completed_task_count, task_count, message)

    def check_commands(self, dyn_job: DynJob) -> None:
        """Between-steps poll; raises JobPaused/JobCanceled to unwind."""
        cmd = self._worker.poll_command()
        if cmd is None:
            return
        if cmd == WorkerCommand.CANCEL:
            raise JobCanceled()
        if cmd in (WorkerCommand.PAUSE, WorkerCommand.SHUTDOWN):
            raise JobPaused(dyn_job.serialize_state(),
                            from_shutdown=cmd == WorkerCommand.SHUTDOWN,
                            errors=getattr(dyn_job, "_soft_errors", []))


class Worker:
    def __init__(self, manager: "Jobs", library: "Library", dyn_job: DynJob) -> None:
        self.manager = manager
        self.library = library
        self.dyn_job = dyn_job
        self.report = dyn_job.report
        # bounded (queue-discipline): the command vocabulary is 3 deep and
        # each is idempotent — 32 pending commands already means the job
        # loop is wedged, and more buffering would not unwedge it
        self._commands: queue.Queue[str] = queue.Queue(maxsize=32)
        self._thread: threading.Thread | None = None
        self._started_at = 0.0
        self._last_progress_emit = 0.0
        self.trace = None  # opened at _do_work start

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._do_work, name=f"job-{self.report.name}-{self.report.id[:8]}",
            daemon=True,
        )
        self._thread.start()

    def send_command(self, command: str) -> None:
        while True:
            try:
                self._commands.put_nowait(command)
                return
            except queue.Full:
                # displace the OLDEST pending command: each is idempotent
                # and the newest reflects current intent — but a pending
                # cancel must never be lost behind pause/resume toggles,
                # so displacing a cancel sheds the incoming toggle and
                # re-queues the cancel in its place
                try:
                    dropped = self._commands.get_nowait()
                except queue.Empty:
                    continue
                if dropped == "cancel" and command != "cancel":
                    dropped, command = command, dropped
                logger.warning("job %s command queue full; displaced %s",
                               self.report.id[:8], dropped)

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def poll_command(self) -> str | None:
        try:
            return self._commands.get_nowait()
        except queue.Empty:
            return None

    # -- progress -----------------------------------------------------------
    def update_progress(self, completed: int | None, total: int | None,
                        message: str | None) -> None:
        r = self.report
        if completed is not None:
            r.completed_task_count = completed
        if total is not None:
            r.task_count = total
        if message is not None:
            r.message = message
        # ETA from cadence so far (worker.rs estimated_completion)
        if r.completed_task_count and r.task_count:
            elapsed = time.monotonic() - self._started_at
            remaining = elapsed / r.completed_task_count * (
                r.task_count - r.completed_task_count
            )
            r.date_estimated_completion = utc_now() + dt.timedelta(seconds=remaining)
        now = time.monotonic()
        if now - self._last_progress_emit >= PROGRESS_THROTTLE_S:
            self._last_progress_emit = now
            self._emit_progress()

    def _emit_progress(self) -> None:
        self.library.emit("job_progress", self.report.progress_payload())

    # -- the work loop ------------------------------------------------------
    def _do_work(self) -> None:
        r = self.report
        r.status = JobStatus.RUNNING
        r.date_started = utc_now()
        r.upsert(self.library.db)
        # flight-recorder edges: job state transitions are what a live
        # tail (telemetry.watch / SSE) narrates between metric scrapes
        telemetry.event("job.status", job=r.name, id=r.id,
                        status=JobStatus.NAMES[JobStatus.RUNNING])
        self._started_at = time.monotonic()
        queued_at = getattr(self.dyn_job, "_queued_at_monotonic", None)
        if queued_at is not None:
            _QUEUE_WAIT.observe(max(0.0, self._started_at - queued_at),
                                lane=self.dyn_job.job.LANE)
        # the job's trace: root span = the whole run; pipeline stages and
        # job code nest under it. trace_id == report id so jobTrace(job_id)
        # resolves directly. resume=True: an in-process pause left the
        # trace open in the ring, and the resumed run continues it so the
        # final tree's span sums match the report's accumulated metadata.
        self.trace = telemetry.start_trace(
            f"job.{r.name}", trace_id=r.id, resume=True,
            job=r.name, job_id=r.id, lane=self.dyn_job.job.LANE,
            library_id=self.library.id if self.library else None)
        self.dyn_job.trace = self.trace
        ctx = WorkerContext(self)
        run_time = 0.0
        next_job: DynJob | None = None
        try:
            metadata, errors = self.dyn_job.run(ctx)
            run_time = time.monotonic() - self._started_at
            r.metadata = metadata
            if errors:
                r.status = JobStatus.COMPLETED_WITH_ERRORS
                r.errors_text = "\n\n".join(errors)
            else:
                r.status = JobStatus.COMPLETED
            r.date_completed = utc_now()
            next_job = self.dyn_job.next_jobs.pop(0) if self.dyn_job.next_jobs else None
            if next_job is not None:
                next_job.next_jobs = self.dyn_job.next_jobs
        except JobPaused as p:
            r.status = JobStatus.PAUSED
            r.data = p.state_blob
            if p.errors:
                r.errors_text = "\n\n".join(p.errors)
            self._pause_children(p.state_blob)
        except JobCanceled:
            r.status = JobStatus.CANCELED
            r.date_completed = utc_now()
            self._cancel_children()
        except Exception as e:
            logger.exception("job %s failed", r.name)
            r.status = JobStatus.FAILED
            r.errors_text = repr(e)
            r.date_completed = utc_now()
            self._cancel_children()
        finally:
            telemetry.event("job.status", job=r.name, id=r.id,
                            status=JobStatus.NAMES.get(r.status,
                                                       str(r.status)))
            self._finish_telemetry()
            r.upsert(self.library.db)
            self._emit_progress()
            # serve-pool invalidation (ISSUE 11): every job exit emits a
            # final post-commit signal. Mid-run, pipelined jobs emit
            # db.commit per group and sequential/non-pipelined jobs ride
            # the job_progress bump — but progress is THROTTLED, so the
            # last batch's emit can be suppressed and a worker page cached
            # just before it would otherwise stay stale until some
            # unrelated event bumped the library. The job's writes are
            # durable here (autocommit steps / the executor committed
            # before returning), so the bump can never precede its commit.
            self.library.emit("db.commit", {"source": "job.exit",
                                            "job": r.name})
            logger.info("job %s -> %s (total run time %.3fs)",
                        r.name, JobStatus.NAMES[r.status], run_time)
            self.manager.complete(self.library, self, next_job)

    def _finish_telemetry(self) -> None:
        """Close the trace, export its JSONL under the node data dir, and
        attach the summarized span totals to the report's metadata (paused
        jobs keep their trace in the ring only — metadata is reserved for
        the final run)."""
        r = self.report
        # count TERMINAL exits only — a pause is not a completion, and a
        # paused-then-resumed job must not count twice
        if r.status in JobStatus.FINISHED:
            # both label sets are closed registries the rules can't see
            # through: r.name comes from JOB_REGISTRY keys (job NAME
            # class constants) and the status map is the fixed
            # JobStatus.NAMES enum
            _COMPLETED.inc(job=r.name,  # lint: ok(cardinality-discipline)
                           status=JobStatus.NAMES.get(  # lint: ok(cardinality-discipline)
                               r.status, str(r.status)))
        if self.trace is None:
            return
        if r.status not in JobStatus.FINISHED:
            # paused: the trace stays OPEN in the ring — an in-process
            # resume continues it (start_trace resume=True), and only the
            # terminal run finishes, exports, and summarizes the complete
            # tree (so span sums reconcile with the job's accumulated
            # metadata even across a pause)
            return
        try:
            node = self.library.node if self.library else None
            summary = telemetry.finish_trace(
                self.trace, export_dir=node.data_dir if node else None)
            if summary:
                r.metadata = {**(r.metadata or {}), "trace": summary}
        except Exception:
            logger.exception("trace finalization failed for job %s", r.id)

    def _pause_children(self, _blob: bytes) -> None:
        """Persist queued-next chain as Paused reports (job/mod.rs:917-951)."""
        for child in self.dyn_job.next_jobs:
            child.report.status = JobStatus.PAUSED
            child.report.upsert(self.library.db)

    def _cancel_children(self) -> None:
        for child in self.dyn_job.next_jobs:
            child.report.status = JobStatus.CANCELED
            child.report.upsert(self.library.db)
