"""The StatefulJob protocol and its type-erased runner.

Semantics ported from the reference (not its tokio mechanics): a job is
``init()`` → a list of serializable steps → ``execute_step()`` per step →
``finalize()`` (StatefulJob trait, core/src/job/mod.rs:68-110). Between steps
the runner polls its command channel; Pause/Shutdown serialize the full
``JobState{init, data, steps, step_number, run_metadata}`` into the report
(job/mod.rs:679-781) so a later ``new_from_report`` resumes at the exact step
(job/mod.rs:215-233). Steps may append more steps (the indexer's Walk steps);
per-step errors accumulate into CompletedWithErrors instead of aborting
(job/mod.rs:834-841); EarlyFinish is a clean skip (error.rs).

State is JSON — every job's ``init_args``/``data``/steps must be plain
JSON-serializable values, which keeps checkpoints portable and debuggable.

TPU note: a "step" is the checkpoint quantum. Batched jobs (IS_BATCHED) size
steps to one device batch, so a killed hashing run resumes at the last
completed batch and device work quiesces at step granularity on Pause —
the property §5.4 of SURVEY.md calls out.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from typing import TYPE_CHECKING, Any, ClassVar

from .. import telemetry
from .error import EarlyFinish, JobError
from .report import JobReport

if TYPE_CHECKING:
    from .worker import WorkerContext

logger = logging.getLogger(__name__)

_STEP_SECONDS = telemetry.histogram(
    "sd_job_step_seconds", "sequential step latency per job",
    labels=("job",))

JOB_REGISTRY: dict[str, type["StatefulJob"]] = {}


class StepResult:
    """What one execute_step returns."""

    __slots__ = ("more_steps", "metadata", "errors")

    def __init__(self, more_steps: list[Any] | None = None,
                 metadata: dict[str, Any] | None = None,
                 errors: list[str] | None = None) -> None:
        self.more_steps = more_steps or []
        self.metadata = metadata or {}
        self.errors = errors or []


class StatefulJob:
    """Subclass with NAME, init(), execute_step(); register for cold resume.

    ``init_args`` identify the job (dedup hash, job/mod.rs:84-90); ``data`` is
    shared working state produced by init; steps are the serializable work
    units.
    """

    NAME: ClassVar[str] = ""
    IS_BATCHED: ClassVar[bool] = False
    #: dispatch lane (jobs/manager.py): each lane runs at most one job, so a
    #: media-lane job can overlap the default lane's scan work without
    #: breaking the single-writer discipline (writes still serialize on the
    #: DB connection lock; the overlap is decode/IO/compute)
    LANE: ClassVar[str] = "default"
    #: init_args keys REDACTED from every persisted checkpoint (job table
    #: rows live in the unencrypted library DB — a plaintext password in a
    #: report would defeat the encryption job that stored it). A job
    #: resumed from a checkpoint sees these keys missing and must either
    #: fail that step cleanly or use a persistable reference (key_uuid).
    SECRET_INIT_KEYS: ClassVar[tuple[str, ...]] = ()

    def __init__(self, init_args: dict[str, Any]) -> None:
        self.init_args = init_args

    # -- identity -----------------------------------------------------------
    def hash(self) -> str:
        """Dedup identity: name + canonical init args (job/mod.rs:84-90)."""
        blob = json.dumps({"name": self.NAME, "args": self.init_args}, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:32]

    # -- lifecycle (override) ----------------------------------------------
    def init(self, ctx: "WorkerContext") -> tuple[dict[str, Any], list[Any], dict[str, Any]]:
        """Returns (data, steps, initial run_metadata). Raise EarlyFinish to
        complete with nothing to do."""
        raise NotImplementedError

    def execute_step(self, ctx: "WorkerContext", data: dict[str, Any],
                     step: Any, step_number: int) -> StepResult:
        raise NotImplementedError

    def finalize(self, ctx: "WorkerContext", data: dict[str, Any],
                 run_metadata: dict[str, Any]) -> dict[str, Any] | None:
        """Returns final metadata for the report."""
        return run_metadata or None

    def pipeline_spec(self) -> Any | None:
        """Batched jobs return a :class:`~spacedrive_tpu.pipeline.PipelineSpec`
        to run their steps through the streaming executor (prefetch/dispatch/
        commit overlapped); ``None`` keeps the sequential step loop."""
        return None

    # registration for name→type dispatch at cold resume (manager.rs:376-401)
    def __init_subclass__(cls, **kw: Any) -> None:
        super().__init_subclass__(**kw)
        if cls.NAME:
            JOB_REGISTRY[cls.NAME] = cls


def merge_metadata(acc: dict[str, Any], update: dict[str, Any]) -> None:
    """RunMetadata::update semantics: numeric values accumulate, lists extend,
    everything else overwrites."""
    for key, value in update.items():
        old = acc.get(key)
        if isinstance(old, (int, float)) and isinstance(value, (int, float)) and not isinstance(old, bool):
            acc[key] = old + value
        elif isinstance(old, list) and isinstance(value, list):
            acc[key] = old + value
        else:
            acc[key] = value


class JobState:
    """The checkpointable whole of a running job (job/mod.rs:247-288)."""

    def __init__(self, init_args: dict[str, Any], data: dict[str, Any] | None,
                 steps: list[Any], step_number: int, run_metadata: dict[str, Any]) -> None:
        self.init_args = init_args
        self.data = data
        self.steps = steps
        self.step_number = step_number
        self.run_metadata = run_metadata

    def serialize(self, secret_keys: tuple[str, ...] = ()) -> bytes:
        init_args = ({k: v for k, v in self.init_args.items()
                      if k not in secret_keys}
                     if secret_keys else self.init_args)
        return json.dumps({
            "init_args": init_args,
            "data": self.data,
            "steps": self.steps,
            "step_number": self.step_number,
            "run_metadata": self.run_metadata,
        }).encode()

    @classmethod
    def deserialize(cls, blob: bytes) -> "JobState":
        d = json.loads(blob.decode())
        return cls(d["init_args"], d["data"], d["steps"], d["step_number"], d["run_metadata"])


class DynJob:
    """Type-erased runner for one job + its queued-next chain
    (Job<SJob>/DynJob, job/mod.rs:113-245)."""

    def __init__(self, job: StatefulJob, report: JobReport,
                 state: JobState | None = None,
                 next_jobs: list["DynJob"] | None = None) -> None:
        self.job = job
        self.report = report
        self.state = state or JobState(job.init_args, None, [], 0, {})
        self.next_jobs = next_jobs or []

    @property
    def id(self) -> str:
        return self.report.id

    def hash(self) -> str:
        return self.job.hash()

    @classmethod
    def new_from_report(cls, report: JobReport) -> "DynJob":
        """Revive a job from its persisted report + checkpoint
        (job/mod.rs:215-233 + manager.rs:376-401 dispatch)."""
        job_type = JOB_REGISTRY.get(report.name)
        if job_type is None:
            raise JobError(f"unknown job name for resume: {report.name!r}")
        if report.data:
            state = JobState.deserialize(report.data)
        else:
            state = None
        job = job_type(state.init_args if state else {})
        return cls(job, report, state)

    # -- the run loop -------------------------------------------------------
    def run(self, ctx: "WorkerContext") -> tuple[dict[str, Any] | None, list[str]]:
        """Drive init/steps/finalize, checking commands between steps.

        Returns (metadata, errors). Raises JobPaused (with serialized state),
        JobCanceled, or JobError on fatal failure.
        """
        state = self.state
        run_t0 = time.perf_counter()  # per-phase timing (job/mod.rs:591,798,858)
        #: True when this run continues a checkpoint (pause/cold resume) —
        #: whole-job rate gauges must not divide accumulated totals by
        #: only this run's elapsed time
        self.was_resumed = state.data is not None
        errors: list[str] = list(filter(None, (self.report.errors_text or "").split("\n\n")))
        # expose to the pause path: JobPaused must carry these so they survive
        # the checkpoint (a resume re-reads them from report.errors_text)
        self._soft_errors = errors

        trace = getattr(self, "trace", None)
        if state.data is None:  # fresh run (not a resume)
            try:
                with telemetry.span(trace, "job.init") as init_sp:
                    data, steps, meta = self.job.init(ctx)
            except EarlyFinish as e:
                logger.info("job %s early finish: %s", self.job.NAME, e)
                return self.job.finalize(ctx, {}, {}), errors
            state.data = data
            state.steps = list(steps)
            state.run_metadata = dict(meta)
            state.step_number = 0
            ctx.progress(task_count=len(state.steps),
                         message=f"{self.job.NAME}: {len(state.steps)} steps")
            logger.debug("job %s init phase took %.3fs", self.job.NAME,
                         init_sp.duration_s)
            ctx.check_commands(self)  # a pause during init checkpoints cleanly

        spec = self.job.pipeline_spec()
        if spec is not None:
            from ..pipeline import PipelineExecutor, pipeline_enabled

            if not pipeline_enabled():
                spec = None
        if spec is not None:
            # streaming path: same step/checkpoint semantics, stages
            # overlapped (pipeline/executor.py); commits stay ordered so the
            # serialized state below is indistinguishable from sequential
            PipelineExecutor(spec, ctx, self, errors).run()

        while state.step_number < len(state.steps):
            ctx.check_commands(self)
            step = state.steps[state.step_number]
            try:
                with telemetry.span(trace, "job.step",
                                    step=state.step_number) as step_sp:
                    result = self.job.execute_step(ctx, state.data, step,
                                                   state.step_number)
                _STEP_SECONDS.observe(step_sp.duration_s, job=self.job.NAME)
            except EarlyFinish:
                break
            # a raised exception is fatal (reference: a step Err fails the job);
            # per-item soft errors come back in StepResult.errors and accumulate
            # into CompletedWithErrors (job/mod.rs:834-841)
            if result.more_steps:
                state.steps.extend(result.more_steps)
                ctx.progress(task_count=len(state.steps))
            if result.metadata:
                merge_metadata(state.run_metadata, result.metadata)
            errors.extend(result.errors)
            state.step_number += 1
            ctx.progress(completed_task_count=state.step_number)
            logger.debug("job %s step %d finished in %.3fs",
                         self.job.NAME, state.step_number - 1,
                         step_sp.duration_s)

        metadata = self.job.finalize(ctx, state.data or {}, state.run_metadata)
        logger.info("Total job run time %.3fs (%s, %d steps)",
                    time.perf_counter() - run_t0, self.job.NAME,
                    state.step_number)
        return metadata, errors

    def serialize_state(self) -> bytes:
        return self.state.serialize(self.job.SECRET_INIT_KEYS)
