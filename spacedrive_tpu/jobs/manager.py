"""Jobs manager: ingest/dispatch queue with single-writer discipline.

Equivalent of core/src/job/manager.rs. MAX_WORKERS stays 1 *per lane* for the
same reason as the reference ("db is single threaded, nerd", manager.rs:31-32):
the library DB has one writer, and the parallelism that matters — batched
hashing — happens *inside* a step on the TPU, not across jobs. Lanes
(StatefulJob.LANE) are the one sanctioned cross-job overlap: the media lane
runs thumbnail decode/encode (file I/O + compute, no sync ops) concurrently
with the default lane's scan chain, so media processing for identified
prefixes starts while the identifier is still hashing — DB writes still
serialize on the connection lock.

Lanes are **per library** (ISSUE 8): the single-writer argument is a
per-library-DB argument, so capacity is keyed by ``(library.id, LANE)`` —
one library's scan chain can never starve another library's jobs on a node
serving a fleet. The occupancy gauge keeps its bounded ``lane`` label
(summed across libraries). Dedup by job hash (:109-114), queue overflow
persisted as Queued reports (:162-177), chained-job completion (:180-205),
and cold resume of Paused/Running/Queued reports at startup (:269-319).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import TYPE_CHECKING

from .. import telemetry
from ..models import JobRow
from ..utils.locks import SdRLock
from .error import JobAlreadyRunning
from .job import DynJob, StatefulJob
from .report import JobReport, JobStatus
from .worker import Worker, WorkerCommand

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

MAX_WORKERS = 1

_RUNNING = telemetry.gauge("sd_jobs_running", "running workers per lane",
                           labels=("lane",))
_QUEUED = telemetry.gauge("sd_jobs_queued",
                          "jobs waiting for lane capacity")


class Jobs:
    def __init__(self) -> None:
        # re-entrant: complete() holds it while ingest()ing the chained
        # next job, which takes it again on the same thread
        self._lock = SdRLock("jobs.manager")
        self._running: dict[str, Worker] = {}  # job id -> worker
        # the overflow queue is deliberately unbounded IN MEMORY but bounded
        # in practice by job-hash dedup (one entry per distinct job) and
        # persisted as Queued reports — a maxlen deque would silently DROP
        # jobs, which is worse than the memory it saves
        self._queue: deque[tuple["Library", DynJob]] = deque()  # lint: ok(queue-discipline)
        self._shutting_down = False
        self._idle = threading.Event()
        self._idle.set()

    # -- public API ---------------------------------------------------------
    def spawn(self, library: "Library", jobs: list[StatefulJob],
              action: str | None = None) -> str:
        """Build a chained pipeline (JobBuilder::queue_next, job/mod.rs:194-212)
        and ingest its head. Returns the head job report id."""
        if not jobs:
            raise ValueError("spawn requires at least one job")
        dyn_jobs: list[DynJob] = []
        parent_id = None
        for i, job in enumerate(jobs):
            act = f"{action}-{i}" if action and i else action
            report = JobReport.new(job.NAME, action=act, parent_id=parent_id)
            dyn = DynJob(job, report)
            # persist init args up front so any later cold resume can rebuild
            # the job even if it never ran (children of a crashed head)
            report.data = dyn.serialize_state()
            dyn_jobs.append(dyn)
            if i == 0:
                parent_id = report.id
        head = dyn_jobs[0]
        head.next_jobs = dyn_jobs[1:]
        for dyn in dyn_jobs[1:]:
            dyn.report.status = JobStatus.QUEUED
            dyn.report.upsert(library.db)
        self.ingest(library, head)
        return head.id

    def _lane_load(self, library_id: str, lane: str) -> int:
        """Running workers in ``library_id``'s ``lane`` (callers hold the
        lock) — capacity is per (library, lane), never cross-library."""
        return sum(1 for w in self._running.values()
                   if w.library.id == library_id
                   and w.dyn_job.job.LANE == lane)

    def _update_occupancy(self, lane: str) -> None:
        """Lane-occupancy + queue-depth gauges (callers hold the lock).
        The gauge sums the lane across libraries: the label set must stay
        bounded by the lane vocabulary, not the library population."""
        _RUNNING.set(sum(1 for w in self._running.values()
                         if w.dyn_job.job.LANE == lane), lane=lane)
        _QUEUED.set(len(self._queue))

    def _pop_dispatchable(self) -> tuple["Library", DynJob] | None:
        """First queued job whose (library, lane) has capacity (callers
        hold the lock)."""
        for i, (lib, queued) in enumerate(self._queue):
            if self._lane_load(lib.id, queued.job.LANE) < MAX_WORKERS:
                del self._queue[i]
                return lib, queued
        return None

    def ingest(self, library: "Library", dyn_job: DynJob) -> None:
        # queue-wait accounting: the worker observes dispatch latency from
        # this stamp (immediately dispatched jobs record ~0)
        dyn_job._queued_at_monotonic = time.monotonic()
        with self._lock:
            if self._shutting_down:
                raise JobAlreadyRunning("job system is shutting down")
            new_hash = dyn_job.hash()
            for worker in self._running.values():
                if worker.dyn_job.hash() == new_hash:
                    raise JobAlreadyRunning(
                        f"job {dyn_job.job.NAME} already running (hash {new_hash[:8]})")
            for _, queued in self._queue:
                if queued.hash() == new_hash:
                    raise JobAlreadyRunning(
                        f"job {dyn_job.job.NAME} already queued (hash {new_hash[:8]})")
            if self._lane_load(library.id, dyn_job.job.LANE) < MAX_WORKERS:
                self._dispatch(library, dyn_job)
            else:
                dyn_job.report.status = JobStatus.QUEUED
                dyn_job.report.upsert(library.db)
                self._queue.append((library, dyn_job))
                self._update_occupancy(dyn_job.job.LANE)
                logger.debug("job %s queued (%d in queue)",
                             dyn_job.job.NAME, len(self._queue))

    def complete(self, library: "Library", worker: Worker,
                 next_job: DynJob | None) -> None:
        """Called by the worker thread as it exits; dispatches the chained next
        job or pops the queue (manager.rs:180-205)."""
        with self._lock:
            self._running.pop(worker.report.id, None)
            self._update_occupancy(worker.dyn_job.job.LANE)
            if not self._shutting_down:
                if next_job is not None:
                    try:
                        self.ingest(library, next_job)
                    except JobAlreadyRunning as e:
                        logger.warning("chained job dropped: %s", e)
                # refill any remaining lane capacity from the queue (the
                # chained job may have been dropped by dedup, or may itself
                # have queued)
                while True:
                    entry = self._pop_dispatchable()
                    if entry is None:
                        break
                    self._dispatch(*entry)
            if not self._running:
                self._idle.set()

    def _dispatch(self, library: "Library", dyn_job: DynJob) -> None:
        worker = Worker(self, library, dyn_job)
        self._running[dyn_job.id] = worker
        self._idle.clear()
        self._update_occupancy(dyn_job.job.LANE)
        logger.info("dispatching job %s (%s)", dyn_job.job.NAME, dyn_job.id[:8])
        worker.start()

    # -- control ------------------------------------------------------------
    def pause(self, job_id: str) -> bool:
        with self._lock:
            worker = self._running.get(job_id)
        if worker is None:
            return False
        worker.send_command(WorkerCommand.PAUSE)
        return True

    def cancel(self, job_id: str) -> bool:
        with self._lock:
            worker = self._running.get(job_id)
            if worker is None:  # maybe queued
                for i, (lib, queued) in enumerate(self._queue):
                    if queued.id == job_id:
                        del self._queue[i]
                        queued.report.status = JobStatus.CANCELED
                        queued.report.upsert(lib.db)
                        self._update_occupancy(queued.job.LANE)
                        return True
                return False
        worker.send_command(WorkerCommand.CANCEL)
        return True

    def resume(self, library: "Library", job_id: str) -> bool:
        """Revive a Paused report from its checkpoint."""
        row = library.db.find_one(JobRow, {"id": job_id})
        if row is None or row["status"] != JobStatus.PAUSED:
            return False
        dyn_job = DynJob.new_from_report(JobReport.from_row(row))
        dyn_job.next_jobs = self._load_children(library, job_id)
        self.ingest(library, dyn_job)
        return True

    def is_active(self) -> bool:
        with self._lock:
            return bool(self._running or self._queue)

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Test/shell helper: block until no jobs are running or queued."""
        while True:
            if not self._idle.wait(timeout):
                return False
            with self._lock:
                if not self._running and not self._queue:
                    return True
                entry = self._pop_dispatchable()
                if entry is not None:
                    self._dispatch(*entry)

    def shutdown(self, timeout: float = 30.0) -> None:
        """Graceful: every running job checkpoints (WorkerCommand::Shutdown →
        serialized state, manager.rs:56-62); queued jobs stay Queued in DB."""
        with self._lock:
            self._shutting_down = True
            workers = list(self._running.values())
            for lib, queued in self._queue:
                queued.report.status = JobStatus.QUEUED
                queued.report.upsert(lib.db)
            self._queue.clear()
        for worker in workers:
            worker.send_command(WorkerCommand.SHUTDOWN)
        for worker in workers:
            worker.join(timeout)

    # -- cold resume (manager.rs:269-319) -----------------------------------
    def cold_resume(self, library: "Library") -> int:
        """At library load: revive Paused/Running (crashed) jobs from their
        checkpoints and re-queue Queued ones; undeserializable → Canceled."""
        revived = 0
        crash_survivors = 0
        rows = library.db.query(
            "SELECT * FROM job WHERE status IN (?, ?, ?) AND parent_id IS NULL ORDER BY date_created",
            [JobStatus.PAUSED, JobStatus.RUNNING, JobStatus.QUEUED],
        )
        for raw in rows:
            row = JobRow.decode_row(raw)
            report = JobReport.from_row(row)
            try:
                dyn_job = DynJob.new_from_report(report)
                dyn_job.next_jobs = self._load_children(library, report.id)
                self.ingest(library, dyn_job)
                revived += 1
                # only a RUNNING row at boot is a crash survivor (no live
                # process lands one durably) — user-paused and still-queued
                # rows revive on every clean restart and must not read as
                # phantom recoveries in sd_recovery_* or the event stream
                if row["status"] == JobStatus.RUNNING:
                    crash_survivors += 1
                    telemetry.event("job.cold_resume", job=report.name,
                                    id=report.id)
            except Exception as e:
                # a checkpoint that cannot be revived is a FAILURE the user
                # must see (lost scan progress), not a silent Canceled: keep
                # the diagnostic in errors_text and push a notification.
                # The job is NOT re-queued — the corrupt blob would fail
                # identically forever.
                logger.warning("cold resume failed for %s (%s): %s; marking Failed",
                               report.name, report.id[:8], e)
                report.status = JobStatus.FAILED
                # APPEND: the checkpoint deliberately persisted the paused
                # run's soft errors (quarantined files etc.) — the user
                # still needs them after the resume failure
                failure = f"cold resume failed: {e!r}"
                report.errors_text = (f"{report.errors_text}\n\n{failure}"
                                      if report.errors_text else failure)
                report.upsert(library.db)
                try:
                    from ..notifications import emit_library_notification

                    emit_library_notification(library, {
                        "kind": "job_cold_resume_failed",
                        "job_name": report.name,
                        "job_id": report.id,
                        "error": str(e),
                    })
                except Exception:
                    logger.exception("cold-resume failure notification "
                                     "could not be emitted")
        if crash_survivors:
            from ..recovery import note_cold_resumed

            note_cold_resumed(crash_survivors)
        return revived

    def _load_children(self, library: "Library", parent_id: str) -> list[DynJob]:
        children = []
        for raw in library.db.find(JobRow, {"parent_id": parent_id},
                                   order_by="date_created"):
            report = JobReport.from_row(raw)
            if report.status in (JobStatus.PAUSED, JobStatus.QUEUED):
                try:
                    children.append(DynJob.new_from_report(report))
                except Exception as e:
                    logger.warning("dropping unresumable child %s: %s", report.id[:8], e)
        return children
