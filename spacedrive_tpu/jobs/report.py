"""Job reports: the DB-backed record of every job run.

Equivalent of the reference's JobReport (core/src/job/report.rs:41-62) and
JobStatus enum; persisted in the ``job`` table (schema.prisma:407-436) with
the serialized checkpoint state in ``data`` and chained-pipeline parentage in
``parent_id``.
"""

from __future__ import annotations

import dataclasses
import datetime as dt
import uuid
from typing import Any

from ..models import Database, JobRow, utc_now


class JobStatus:
    QUEUED = 0
    RUNNING = 1
    COMPLETED = 2
    CANCELED = 3
    FAILED = 4
    PAUSED = 5
    COMPLETED_WITH_ERRORS = 6

    FINISHED = (COMPLETED, CANCELED, FAILED, COMPLETED_WITH_ERRORS)

    NAMES = {
        0: "Queued", 1: "Running", 2: "Completed", 3: "Canceled",
        4: "Failed", 5: "Paused", 6: "CompletedWithErrors",
    }


@dataclasses.dataclass
class JobReport:
    id: str
    name: str
    status: int = JobStatus.QUEUED
    action: str | None = None
    errors_text: str | None = None
    data: bytes | None = None  # serialized JobState checkpoint
    metadata: dict[str, Any] | None = None
    parent_id: str | None = None
    task_count: int = 0
    completed_task_count: int = 0
    date_estimated_completion: dt.datetime | None = None
    date_created: dt.datetime | None = None
    date_started: dt.datetime | None = None
    date_completed: dt.datetime | None = None
    message: str = ""  # live progress message (not persisted)

    @classmethod
    def new(cls, name: str, action: str | None = None, parent_id: str | None = None) -> "JobReport":
        return cls(id=str(uuid.uuid4()), name=name, action=action,
                   parent_id=parent_id, date_created=utc_now())

    # -- persistence --------------------------------------------------------
    def create(self, db: Database) -> None:
        db.insert(JobRow, self._row())

    def update(self, db: Database) -> None:
        row = self._row()
        row.pop("id")
        db.update(JobRow, {"id": self.id}, row)

    def upsert(self, db: Database) -> None:
        if db.find_one(JobRow, {"id": self.id}) is None:
            self.create(db)
        else:
            self.update(db)

    def _row(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "action": self.action,
            "status": self.status,
            "errors_text": self.errors_text,
            "data": self.data,
            "metadata": self.metadata,
            "parent_id": self.parent_id,
            "task_count": self.task_count,
            "completed_task_count": self.completed_task_count,
            "date_estimated_completion": self.date_estimated_completion,
            "date_created": self.date_created,
            "date_started": self.date_started,
            "date_completed": self.date_completed,
        }

    @classmethod
    def from_row(cls, row: dict[str, Any]) -> "JobReport":
        fields = {f.name for f in dataclasses.fields(cls)} - {"message"}
        return cls(**{k: v for k, v in row.items() if k in fields})

    def progress_payload(self) -> dict[str, Any]:
        """The jobs.progress subscription payload (worker.rs:29-35)."""
        return {
            "id": self.id,
            "name": self.name,
            "status": self.status,
            "task_count": self.task_count,
            "completed_task_count": self.completed_task_count,
            "message": self.message,
            "estimated_completion": (
                self.date_estimated_completion.isoformat()
                if self.date_estimated_completion else None
            ),
        }
