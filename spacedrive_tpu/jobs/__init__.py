"""Stateful job engine: init/steps/finalize with checkpoint/resume."""

from .error import (
    EarlyFinish,
    JobAlreadyRunning,
    JobCanceled,
    JobError,
    JobPaused,
)
from .job import JOB_REGISTRY, DynJob, JobState, StatefulJob, StepResult, merge_metadata
from .manager import MAX_WORKERS, Jobs
from .report import JobReport, JobStatus
from .worker import Worker, WorkerCommand, WorkerContext

__all__ = [
    "EarlyFinish", "JobAlreadyRunning", "JobCanceled", "JobError", "JobPaused",
    "JOB_REGISTRY", "DynJob", "JobState", "StatefulJob", "StepResult",
    "merge_metadata", "MAX_WORKERS", "Jobs", "JobReport", "JobStatus",
    "Worker", "WorkerCommand", "WorkerContext",
]


def register_builtin_jobs() -> None:
    """Import every job-bearing module so JOB_REGISTRY is fully populated
    BEFORE cold resume runs — a checkpointed job whose module was never
    imported would otherwise be unresumable and get canceled (the
    reference's name→type dispatch macro lists all types statically,
    job/manager.rs:376-401; this is the import-time equivalent)."""
    from ..locations import indexer_job  # noqa: F401
    from ..objects import dedup, file_identifier, fs, validator  # noqa: F401
    from ..objects.media import processor  # noqa: F401
    try:
        from ..objects import crypto_jobs  # noqa: F401
    except ImportError as e:
        # dependency-gated (no ``cryptography``): the node still scans and
        # syncs; a checkpointed encrypt/decrypt job on such an image cold-
        # resumes as Canceled, which is the honest outcome
        import logging

        logging.getLogger(__name__).warning(
            "crypto jobs unavailable (%s); encrypt/decrypt not registered", e)
