"""Stateful job engine: init/steps/finalize with checkpoint/resume."""

from .error import (
    EarlyFinish,
    JobAlreadyRunning,
    JobCanceled,
    JobError,
    JobPaused,
)
from .job import JOB_REGISTRY, DynJob, JobState, StatefulJob, StepResult, merge_metadata
from .manager import MAX_WORKERS, Jobs
from .report import JobReport, JobStatus
from .worker import Worker, WorkerCommand, WorkerContext

__all__ = [
    "EarlyFinish", "JobAlreadyRunning", "JobCanceled", "JobError", "JobPaused",
    "JOB_REGISTRY", "DynJob", "JobState", "StatefulJob", "StepResult",
    "merge_metadata", "MAX_WORKERS", "Jobs", "JobReport", "JobStatus",
    "Worker", "WorkerCommand", "WorkerContext",
]
