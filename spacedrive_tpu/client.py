"""Typed Python client for a spacedrive_tpu server.

The analogue of packages/client's generated `core.ts` bindings
(api/mod.rs:205-212 codegen): the client fetches the server's /schema
export (the same document schema/api.json snapshots) and validates every
call against it — unknown procedures or kind misuse (mutating via query
etc.) fail client-side with the valid options listed, which is the
rspc-typed-client guarantee re-expressed at runtime.

Transports: queries/mutations over plain HTTP POST, subscriptions over the
/rspc/ws websocket (RFC 6455 client, stdlib only). Library-scoped
procedures take ``library_id=`` which the client folds into the
LibraryArgs envelope.

    client = SpacedriveClient("http://127.0.0.1:8080")
    libs = client.query("libraries.list")
    client.mutation("locations.fullRescan", {"location_id": 1},
                    library_id=libs[0]["id"])
    with client.subscribe("jobs.progress", library_id=libs[0]["id"]) as sub:
        for event in sub:
            ...
"""

from __future__ import annotations

import base64
import hashlib
import json
import queue
import secrets
import socket
import struct
import threading
import urllib.parse
import urllib.request
from typing import Any, Iterator

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class ClientError(Exception):
    pass


class _IdleTimeout(ClientError):
    """Socket read timed out at a frame BOUNDARY — pure idleness, the
    subscription pump retries; a mid-frame timeout stays fatal. Subclasses
    ClientError so pre-pump reads (the subscription-start ack) keep their
    existing cleanup/except behavior."""


class ProcedureError(ClientError):
    """Server-side procedure failure (the {"error": ...} envelope)."""


class SpacedriveClient:
    def __init__(self, base_url: str, auth: str | None = None,
                 timeout: float = 60.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._headers = {"content-type": "application/json"}
        if auth:
            self._headers["authorization"] = \
                "Basic " + base64.b64encode(auth.encode()).decode()
        self.schema = self._fetch_schema()
        self.procedures: dict[str, dict[str, Any]] = {
            p["key"]: p for p in self.schema["procedures"]}

    # -- plumbing ------------------------------------------------------------
    def _fetch_schema(self) -> dict[str, Any]:
        req = urllib.request.Request(self.base_url + "/schema",
                                     headers=self._headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:
            raise ClientError(f"could not fetch schema from {self.base_url}: {e}")

    def _check(self, key: str, kind: str) -> None:
        proc = self.procedures.get(key)
        if proc is None:
            options = [k for k in self.procedures
                       if k.split(".")[0] == key.split(".")[0]]
            raise ClientError(
                f"unknown procedure {key!r}; same-router options: {options}")
        if proc["kind"] != kind:
            raise ClientError(f"{key} is a {proc['kind']}, not a {kind}")

    def _call(self, key: str, arg: Any, library_id: str | None) -> Any:
        body = json.dumps({"arg": arg, "library_id": library_id}).encode()
        req = urllib.request.Request(f"{self.base_url}/rspc/{key}", data=body,
                                     headers=self._headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read().decode()).get("error", str(e))
            except Exception:
                message = str(e)
            raise ProcedureError(f"{key}: {message}")
        if "error" in payload:
            raise ProcedureError(f"{key}: {payload['error']}")
        return payload["result"]

    # -- public surface ------------------------------------------------------
    def query(self, key: str, arg: Any = None,
              library_id: str | None = None) -> Any:
        self._check(key, "query")
        return self._call(key, arg, library_id)

    def mutation(self, key: str, arg: Any = None,
                 library_id: str | None = None) -> Any:
        self._check(key, "mutation")
        return self._call(key, arg, library_id)

    def health(self) -> bool:
        req = urllib.request.Request(self.base_url + "/health",
                                     headers=self._headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read() == b"OK"

    def subscribe(self, key: str, arg: Any = None,
                  library_id: str | None = None) -> "ClientSubscription":
        self._check(key, "subscription")
        return ClientSubscription(self, key, arg, library_id)

    def file_url(self, library_id: str, location_id: int,
                 file_path_id: int) -> str:
        return (f"{self.base_url}/spacedrive/file/"
                f"{library_id}/{location_id}/{file_path_id}")

    def thumbnail_url(self, cas_id: str) -> str:
        return f"{self.base_url}/spacedrive/thumbnail/{cas_id[:2]}/{cas_id}.webp"

    def fetch_bytes(self, url: str, byte_range: tuple[int, int] | None = None
                    ) -> bytes:
        headers = dict(self._headers)
        if byte_range is not None:
            headers["range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()


class ClientSubscription:
    """Context-managed event stream over the websocket; iterate for events."""

    def __init__(self, client: SpacedriveClient, key: str, arg: Any,
                 library_id: str | None) -> None:
        self._client = client
        self._key = key
        self._id = 1
        self._q: queue.Queue[Any] = queue.Queue(maxsize=1024)
        self._closed = threading.Event()
        self._sock = self._upgrade()
        try:
            input_ = ({"library_id": library_id, "arg": arg}
                      if library_id is not None else arg)
            self._send({"id": self._id, "method": "subscription",
                        "params": {"path": key, "input": input_}})
            # events may legally arrive before the 'started' ack (the
            # server's pump races the ack send) — buffer, don't fail
            started = False
            first = None
            for _ in range(64):
                first = self._recv_msg(timeout=client.timeout)
                if first is None:
                    break
                rtype = first.get("result", {}).get("type")
                if rtype == "started":
                    started = True
                    break
                if rtype == "event":
                    self._offer(first["result"]["data"])
                    continue
                break
            if not started:
                raise ClientError(f"subscription {key} refused: {first}")
        except (ClientError, ConnectionError, OSError) as e:
            try:
                self._sock.close()  # no leaked fds on refused subscriptions
            except OSError:
                pass
            if isinstance(e, ClientError):
                raise
            raise ClientError(f"subscription {key} failed: {e}")
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"sub-{key}")
        self._thread.start()

    # -- ws plumbing ---------------------------------------------------------
    def _upgrade(self) -> socket.socket:
        parsed = urllib.parse.urlsplit(self._client.base_url)
        tls = parsed.scheme == "https"
        host = parsed.hostname
        port = parsed.port or (443 if tls else 80)
        sock = socket.create_connection((host, port),
                                        timeout=self._client.timeout)
        if tls:
            import ssl

            sock = ssl.create_default_context().wrap_socket(
                sock, server_hostname=host)
        key = base64.b64encode(secrets.token_bytes(16)).decode()
        auth_line = ""
        if "authorization" in self._client._headers:
            auth_line = (f"Authorization: "
                         f"{self._client._headers['authorization']}\r\n")
        sock.sendall(
            (f"GET /rspc/ws HTTP/1.1\r\nHost: {host}:{port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"{auth_line}"
             f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
             ).encode())
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = sock.recv(4096)
            if not chunk:
                raise ClientError("server closed during websocket upgrade")
            head += chunk
        status = head.split(b"\r\n", 1)[0]
        if b"101" not in status:
            raise ClientError(f"websocket upgrade refused: {status.decode()}")
        expect = base64.b64encode(hashlib.sha1(
            (key + _WS_GUID).encode()).digest()).decode()
        if expect.encode() not in head:
            raise ClientError("bad Sec-WebSocket-Accept")
        self._buf = head.split(b"\r\n\r\n", 1)[1]
        return sock

    def _send(self, obj: dict) -> None:
        payload = json.dumps(obj).encode()
        mask = secrets.token_bytes(4)
        head = bytearray([0x81])
        n = len(payload)
        if n < 126:
            head.append(0x80 | n)
        elif n < 1 << 16:
            head.append(0x80 | 126)
            head += struct.pack(">H", n)
        else:
            head.append(0x80 | 127)
            head += struct.pack(">Q", n)
        self._sock.sendall(bytes(head) + mask
                           + bytes(b ^ mask[i & 3]
                                   for i, b in enumerate(payload)))

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("websocket closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_msg(self, timeout: float) -> dict | None:
        self._sock.settimeout(timeout)
        while True:
            # a timeout before ANY frame byte is plain idleness (retryable);
            # one mid-frame means a desynced/stalled stream (close path)
            try:
                first = self._read_exact(1)
            except socket.timeout as e:
                raise _IdleTimeout() from e
            b1, b2 = first[0], self._read_exact(1)[0]
            opcode, length = b1 & 0x0F, b2 & 0x7F
            if length == 126:
                (length,) = struct.unpack(">H", self._read_exact(2))
            elif length == 127:
                (length,) = struct.unpack(">Q", self._read_exact(8))
            payload = self._read_exact(length)
            if opcode == 0x8:
                return None
            if opcode in (0x9, 0xA):
                continue
            return json.loads(payload.decode())

    def _offer(self, item: Any) -> None:
        """Non-blocking enqueue; lossy like the server-side broadcast."""
        try:
            self._q.put_nowait(item)
        except queue.Full:
            try:  # drop oldest to keep the close sentinel deliverable
                self._q.get_nowait()
                self._q.put_nowait(item)
            except (queue.Empty, queue.Full):
                pass

    def _pump(self) -> None:
        try:
            while not self._closed.is_set():
                try:
                    msg = self._recv_msg(timeout=3600)
                except _IdleTimeout:
                    # an idle hour is NOT a close: a quiet subscription
                    # (no job activity) must keep waiting, not silently
                    # end the caller's iteration
                    continue
                if msg is None:
                    break
                result = msg.get("result", {})
                if result.get("type") == "event":
                    self._offer(result["data"])
        except (ConnectionError, OSError):
            pass
        finally:
            self._offer(None)

    # -- consumption ---------------------------------------------------------
    def get(self, timeout: float | None = None) -> Any | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self) -> Iterator[Any]:
        while not self._closed.is_set():
            event = self._q.get()
            if event is None:
                return
            yield event

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._send({"id": self._id + 1, "method": "subscriptionStop",
                        "params": {"subscriptionId": self._id}})
        except (ConnectionError, OSError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._offer(None)

    def __enter__(self) -> "ClientSubscription":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
