"""Ephemeral (non-indexed) directory browsing.

Parity with core/src/location/non_indexed.rs:27-36: list any path outside a
location without touching the database — entries get kinds from the extension
registry, the seeded system rules filter noise (same rules the indexer
seeds), and image entries can produce on-the-fly thumbnails keyed by an
ephemeral cas_id (generate_cas_id over the real file).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

from ..objects.cas import generate_cas_id
from ..objects.kind import ObjectKind, kind_from_extension
from .rules import SYSTEM_RULES, CompiledRules, IndexerRuleSpec


def _default_rules(include_hidden: bool) -> CompiledRules:
    specs: list[IndexerRuleSpec] = [s for s in SYSTEM_RULES if s.default]
    if include_hidden:
        specs = [s for s in specs if s.name != "No Hidden"]
    return CompiledRules(specs)


def walk_ephemeral(path: str | Path, include_hidden: bool = False,
                   with_cas_ids: bool = False,
                   node: Any = None) -> dict[str, Any]:
    """One-directory listing → {entries, errors}; no DB writes.

    With ``node`` set (and cas_ids on), thumbnailable images get on-the-fly
    thumbnails into the node's sharded cache (non_indexed.rs:27-36), rows
    carry ``has_thumbnail``, and the cas_ids register with the thumbnail
    remover so the next GC sweep doesn't collect them (the reference's
    non_indexed_thumbnails channel, thumbnail_remover.rs)."""
    root = Path(path)
    if not root.is_dir():
        raise NotADirectoryError(str(root))
    rules = _default_rules(include_hidden)
    entries: list[dict[str, Any]] = []
    errors: list[str] = []
    try:
        listing = sorted(os.scandir(root), key=lambda e: e.name)
    except OSError as e:
        return {"entries": [], "errors": [f"scandir {root}: {e}"]}
    for entry in listing:
        try:
            if entry.is_symlink():
                continue
            is_dir = entry.is_dir(follow_symlinks=False)
            if not rules.allows_path(entry.name, is_dir, abs_path=entry.path):
                continue
            st = entry.stat(follow_symlinks=False)
            name, dot, ext = entry.name.rpartition(".")
            if is_dir or not dot or not name:
                name, ext = entry.name, ""
            kind = ObjectKind.FOLDER if is_dir else kind_from_extension(ext.lower(), False)
            row: dict[str, Any] = {
                "name": name, "extension": ext.lower() if not is_dir else "",
                "kind": kind, "is_dir": is_dir,
                "size_in_bytes": 0 if is_dir else st.st_size,
                "date_modified": st.st_mtime, "date_created": st.st_ctime,
                "hidden": entry.name.startswith("."),
                "path": entry.path,
            }
            if with_cas_ids and not is_dir and st.st_size > 0:
                try:
                    row["cas_id"] = generate_cas_id(entry.path, st.st_size)
                except (OSError, EOFError) as e:
                    errors.append(f"cas {entry.name}: {e}")
            entries.append(row)
        except OSError as e:
            errors.append(f"stat {entry.name}: {e}")
    if node is not None:
        _attach_thumbnails(node, entries, errors)
    return {"entries": entries, "errors": errors}


#: new thumbnails generated per ephemeralPaths request — keeps a first browse
#: of a huge folder bounded; remaining entries report pending and get their
#: thumbs on subsequent requests (cache hits are free and uncounted)
EPHEMERAL_THUMBS_PER_REQUEST = 32


def _attach_thumbnails(node: Any, entries: list[dict[str, Any]],
                       errors: list[str]) -> None:
    from ..objects.media.thumbnail import (can_generate_thumbnail,
                                           generate_thumbnail, thumbnail_path)

    remover = getattr(node, "thumbnail_remover", None)
    candidates = [row for row in entries
                  if row.get("cas_id")
                  and can_generate_thumbnail(row.get("extension"))]
    if remover is not None and candidates:
        # register BEFORE generating/advertising, ONCE for the whole request
        # (one registry save): a concurrent full sweep must not collect a
        # thumb the response is about to advertise
        remover.register_ephemeral([row["cas_id"] for row in candidates])

    generated = 0
    pending = 0
    for row in candidates:
        cas = row["cas_id"]
        out = thumbnail_path(node.data_dir, cas)
        if out.exists():
            row["has_thumbnail"] = True
            continue
        if generated >= EPHEMERAL_THUMBS_PER_REQUEST:
            pending += 1
            row["has_thumbnail"] = False
            continue
        made = generate_thumbnail(row["path"], node.data_dir, cas,
                                  row.get("extension"))
        generated += 1
        if made is None:
            errors.append(f"thumbnail {row['name']}")
            continue
        row["has_thumbnail"] = True
    if pending:
        # loud cap (no silent truncation): callers re-request to fill in
        errors.append(f"{pending} thumbnails deferred "
                      f"(cap {EPHEMERAL_THUMBS_PER_REQUEST}/request)")
