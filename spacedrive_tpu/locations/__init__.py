"""Locations: CRUD, scan orchestration, metadata dotfile.

Mirrors core/src/location/mod.rs — LocationCreateArgs (:~60), scan_location
building the chained indexer → file_identifier → media_processor pipeline
(:428-459), sub-path rescan (:461-498), and light (non-job) rescan (:500+).
The ``.spacedrive`` dotfile binds a directory to a (library, location) pair
for relink detection (location/metadata.rs).
"""

from __future__ import annotations

import json
import logging
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any

from ..models import FilePath, IndexerRule, IndexerRulesInLocation, Location, utc_now
from .indexer_job import IndexerJob
from .rules import SYSTEM_RULES, seed_rules

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

METADATA_FILE = ".spacedrive"


class LocationError(Exception):
    pass


def create_location(library: "Library", path: str | Path, name: str | None = None,
                    indexer_rule_names: list[str] | None = None,
                    hasher: str = "hybrid", dry_run: bool = False) -> dict[str, Any]:
    """LocationCreateArgs::create — validates the path, writes the metadata
    dotfile, inserts the row, links default indexer rules."""
    path = Path(path).resolve()
    if not path.is_dir():
        raise LocationError(f"not a directory: {path}")
    db = library.db
    for row in db.find(Location):
        existing = Path(row["path"] or "/nonexistent")
        if existing == path:
            raise LocationError(f"location already exists at {path}")
        if existing in path.parents or path in existing.parents:
            raise LocationError(
                f"nested locations are not allowed ({path} vs {existing})")
    if dry_run:
        return {"path": str(path), "name": name or path.name}

    seed_rules(db)
    location_id = db.insert(Location, {
        "pub_id": str(uuid.uuid4()),
        "name": name or path.name,
        "path": str(path),
        "date_created": utc_now(),
        "instance_id": library.instance_id,
        "hasher": hasher,
    })
    # link rules: defaults unless caller names specific ones
    wanted = indexer_rule_names if indexer_rule_names is not None else [
        spec.name for spec in SYSTEM_RULES if spec.default
    ]
    for rule_name in wanted:
        rule = db.find_one(IndexerRule, {"name": rule_name})
        if rule:
            db.insert(IndexerRulesInLocation,
                      {"location_id": location_id, "indexer_rule_id": rule["id"]},
                      or_ignore=True)
    _write_metadata(path, library.id, location_id)
    row = db.find_one(Location, {"id": location_id})
    sync = getattr(library, "sync", None)
    if sync is not None and getattr(sync, "emit_messages", False):
        sync.shared_create_many(Location, [row])
        sync.created()
    if library.node is not None and library.node.locations is not None:
        library.node.locations.add(library, location_id)
    library.emit("invalidate_query", {"key": "locations.list"})
    return row


def delete_location(library: "Library", location_id: int) -> None:
    db = library.db
    row = db.find_one(Location, {"id": location_id})
    if row is None:
        raise LocationError(f"location {location_id} not found")
    if library.node is not None and library.node.locations is not None:
        library.node.locations.remove(library, location_id)
    db.delete(IndexerRulesInLocation, {"location_id": location_id})
    db.delete(FilePath, {"location_id": location_id})
    db.delete(Location, {"id": location_id})
    if row["path"]:
        _remove_metadata_entry(Path(row["path"]), library.id)
    library.emit("invalidate_query", {"key": "locations.list"})


def scan_location(library: "Library", location_id: int,
                  sub_path: str | None = None) -> str:
    """The 3-stage chained pipeline (location/mod.rs:428-459):
    indexer → file_identifier → media_processor. Returns head job id."""
    from ..objects.dedup import DedupDetectorJob
    from ..objects.file_identifier import FileIdentifierJob
    from ..objects.media.processor import MediaProcessorJob

    row = library.db.find_one(Location, {"id": location_id})
    if row is None:
        raise LocationError(f"location {location_id} not found")
    args: dict[str, Any] = {"location_id": location_id}
    if sub_path:
        args["sub_path"] = sub_path
    jobs = [IndexerJob(args), FileIdentifierJob(dict(args))]
    if row.get("generate_preview_media") is not False:
        jobs.append(MediaProcessorJob(dict(args)))
    # 4th chained stage (ours): persist near-duplicate pairs found by the
    # device MinHash sweep — full scans only, sub-path rescans skip it
    if not sub_path:
        jobs.append(DedupDetectorJob({"location_id": location_id}))
    return library.node.jobs.spawn(library, jobs, action="scan_location")


def light_scan_location(library: "Library", location_id: int,
                        sub_path: str = "") -> dict[str, int]:
    """Shallow non-job rescan of one directory (light_scan_location,
    location/mod.rs:500+): inline walk + save, used by watcher/UI refresh."""
    from .rules import CompiledRules, rules_for_location
    from .walker import db_fetcher_for, walk_single_dir
    from .indexer_job import _entry_to_row

    db = library.db
    row = db.find_one(Location, {"id": location_id})
    if row is None:
        raise LocationError(f"location {location_id} not found")
    rules = CompiledRules(rules_for_location(db, location_id))
    result = walk_single_dir(location_id, row["path"], rules, sub_path,
                             db_fetcher_for(db, location_id))
    db.insert_many(FilePath, [_entry_to_row(e) for e in result.walked], or_ignore=True)
    for entry in result.to_update:
        r = _entry_to_row(entry)
        values = {"materialized_path": r["materialized_path"], "name": r["name"],
                  "extension": r["extension"], "size_in_bytes": r["size_in_bytes"],
                  "inode": r["inode"], "device": r["device"],
                  "date_modified": r["date_modified"]}
        if entry.content_changed:
            values["cas_id"] = None
            values["object_id"] = None
        db.update(FilePath, {"id": entry.row_id}, values)
    for gone in result.to_remove:
        db.delete(FilePath, {"id": gone["id"]})
    library.emit("invalidate_query", {"key": "search.paths"})
    return {"saved": len(result.walked), "updated": len(result.to_update),
            "removed": len(result.to_remove)}


def _write_metadata(path: Path, library_id: str, location_id: int) -> None:
    meta_path = path / METADATA_FILE
    data = {}
    if meta_path.exists():
        try:
            data = json.loads(meta_path.read_text())
        except (OSError, json.JSONDecodeError):
            data = {}
    data.setdefault("libraries", {})[library_id] = location_id
    try:
        meta_path.write_text(json.dumps(data, indent=2))
    except OSError as e:
        logger.warning("could not write %s: %s", meta_path, e)


def _remove_metadata_entry(path: Path, library_id: str) -> None:
    """Drop only this library's entry; other libraries keep their relink data."""
    meta_path = path / METADATA_FILE
    data = read_metadata(path)
    if data is None:
        return
    data.get("libraries", {}).pop(library_id, None)
    try:
        if data.get("libraries"):
            meta_path.write_text(json.dumps(data, indent=2))
        else:
            meta_path.unlink(missing_ok=True)
    except OSError as e:
        logger.warning("could not update %s: %s", meta_path, e)


def read_metadata(path: str | Path) -> dict[str, Any] | None:
    """Relink detection: which (library, location) does this dir claim?"""
    meta_path = Path(path) / METADATA_FILE
    if not meta_path.exists():
        return None
    try:
        return json.loads(meta_path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
