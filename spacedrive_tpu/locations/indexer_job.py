"""IndexerJob: walk a location and persist the file tree.

Mirrors core/src/location/indexer/indexer_job.rs — steps are Save(batch),
Update(batch), Remove(batch) and Walk(dir) continuations; BATCH_SIZE = 1000
(:40), initial walk budget 50,000 entries (:197). RunMetadata records
scan_read_time / db_write_time like IndexerJobRunMetadata (:70-72).
"""

from __future__ import annotations

import datetime as dt
import logging
import time
import uuid
from pathlib import Path
from typing import Any

from ..jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ..models import FilePath, Location, utc_now
from .rules import CompiledRules, rules_for_location
from .walker import WalkedEntry, WalkResult, db_fetcher_for, walk

logger = logging.getLogger(__name__)

BATCH_SIZE = 1000
WALK_LIMIT = 50_000


def _ts_to_dt(ts: float) -> str:
    return dt.datetime.fromtimestamp(ts, dt.timezone.utc).isoformat()


def _entry_to_row(entry: WalkedEntry) -> dict[str, Any]:
    iso, meta = entry.iso, entry.metadata
    return {
        "pub_id": str(uuid.uuid4()),
        **iso.db_fields(),
        "inode": meta.inode,
        "device": meta.device,
        "size_in_bytes": meta.size_in_bytes,
        "hidden": meta.hidden,
        "date_created": _ts_to_dt(meta.created_at),
        "date_modified": _ts_to_dt(meta.modified_at),
        "date_indexed": utc_now().isoformat(),
    }


def _batches(rows: list, size: int) -> list[list]:
    return [rows[i : i + size] for i in range(0, len(rows), size)]


class IndexerJob(StatefulJob):
    NAME = "indexer"

    def _location(self, ctx: WorkerContext) -> dict[str, Any]:
        row = ctx.library.db.find_one(Location, {"id": self.init_args["location_id"]})
        if row is None:
            raise JobError(f"location {self.init_args['location_id']} not found")
        return row

    def _steps_from_walk(self, result: WalkResult) -> tuple[list[dict], dict]:
        steps: list[dict] = []
        for batch in _batches([_entry_to_row(e) for e in result.walked], BATCH_SIZE):
            steps.append({"kind": "save", "rows": batch})
        updates = [
            {**_entry_to_row(e), "row_id": e.row_id, "content_changed": e.content_changed}
            for e in result.to_update
        ]
        for batch in _batches(updates, BATCH_SIZE):
            steps.append({"kind": "update", "rows": batch})
        if result.to_remove:
            steps.append({"kind": "remove", "ids": [r["id"] for r in result.to_remove]})
        for rel_dir in result.to_walk:
            steps.append({"kind": "walk", "dir": rel_dir})
        meta = {
            "total_paths": len(result.walked),
            "updated_paths": len(result.to_update),
            "removed_paths": len(result.to_remove),
            "indexer_errors": result.errors,
        }
        return steps, meta

    # -- lifecycle ----------------------------------------------------------
    def init(self, ctx: WorkerContext):
        location = self._location(ctx)
        location_path = location["path"]
        if not location_path or not Path(location_path).is_dir():
            raise JobError(f"location path missing on disk: {location_path}")
        sub_path = self.init_args.get("sub_path") or ""
        rules = CompiledRules(rules_for_location(ctx.library.db, location["id"]))
        t0 = time.perf_counter()
        result = walk(
            location["id"], location_path, rules,
            db_fetcher_for(ctx.library.db, location["id"]),
            sub_path=sub_path, limit=WALK_LIMIT,
        )
        scan_time = time.perf_counter() - t0
        steps, meta = self._steps_from_walk(result)
        meta["scan_read_time"] = scan_time
        meta["db_write_time"] = 0.0
        if not steps:
            raise EarlyFinish("location already up to date")
        data = {"location_id": location["id"], "location_path": location_path}
        return data, steps, meta

    def execute_step(self, ctx: WorkerContext, data: dict, step: dict,
                     step_number: int) -> StepResult:
        db = ctx.library.db
        kind = step["kind"]
        t0 = time.perf_counter()
        sync = getattr(ctx.library, "sync", None)
        emit = sync is not None and getattr(sync, "emit_messages", False)
        if kind == "save":
            with db.transaction():
                # or_ignore: a watcher may have raced us (unique indexes hold)
                db.insert_many(FilePath, step["rows"], or_ignore=True)
                if emit:
                    sync.shared_create_many(FilePath, step["rows"])
            if emit:
                sync.created()
            return StepResult(metadata={"db_write_time": time.perf_counter() - t0,
                                        "saved_rows": len(step["rows"])})
        if kind == "update":
            ops = []
            with db.transaction():
                for row in step["rows"]:
                    values = {
                        # renames carry the new identity fields; updates by row id
                        "materialized_path": row["materialized_path"],
                        "name": row["name"], "extension": row["extension"],
                        "size_in_bytes": row["size_in_bytes"],
                        "inode": row["inode"], "device": row["device"],
                        "date_modified": row["date_modified"],
                        "hidden": row["hidden"],
                    }
                    if row.get("content_changed", True):
                        # content changed: clear identity so re-identify runs;
                        # a pure rename keeps its cas_id/object link
                        values["cas_id"] = None
                        values["object_id"] = None
                    db.update(FilePath, {"id": row["row_id"]}, values)
                    if emit and row.get("pub_id"):
                        for field in ("materialized_path", "name", "extension",
                                      "size_in_bytes", "date_modified", "cas_id"):
                            if field in values:
                                v = values[field]
                                ops.append(sync.shared_update(
                                    FilePath, row["pub_id"], field,
                                    v.isoformat() if hasattr(v, "isoformat") else v))
                if ops:
                    sync.log_ops(ops)
            if ops:
                sync.created()
            return StepResult(metadata={"db_write_time": time.perf_counter() - t0,
                                        "updated_rows": len(step["rows"])})
        if kind == "remove":
            ops = []
            with db.transaction():
                for fp_id in step["ids"]:
                    if emit:
                        row = db.find_one(FilePath, {"id": fp_id})
                        if row is not None:
                            ops.append(sync.shared_delete(FilePath, row["pub_id"]))
                    db.delete(FilePath, {"id": fp_id})
                if ops:
                    sync.log_ops(ops)
            if ops:
                sync.created()
            return StepResult(metadata={"db_write_time": time.perf_counter() - t0})
        if kind == "walk":
            location = self._location(ctx)
            rules = CompiledRules(rules_for_location(db, location["id"]))
            result = walk(
                location["id"], data["location_path"], rules,
                db_fetcher_for(db, location["id"]),
                sub_path=step["dir"], limit=WALK_LIMIT,
                include_root=False,
            )
            more_steps, meta = self._steps_from_walk(result)
            meta["scan_read_time"] = time.perf_counter() - t0
            return StepResult(more_steps=more_steps, metadata=meta)
        raise JobError(f"unknown indexer step kind: {kind}")

    def finalize(self, ctx: WorkerContext, data: dict, run_metadata: dict):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        logger.info("indexer finished: %s", {k: v for k, v in run_metadata.items()
                                             if not k.endswith("errors")})
        return run_metadata
