"""IsolatedFilePathData: the canonical path representation.

Mirrors core/src/location/file_path_helper/isolated_file_path_data.rs:25-38:
a file_path row is (location_id, materialized_path, name, extension, is_dir)
where ``materialized_path`` is the parent directory path relative to the
location root, always "/"-wrapped (``"/"``, ``"/sub/dir/"``). The location
root itself is (``"/"``, ``""``, ``""``, is_dir=True).
"""

from __future__ import annotations

import dataclasses
import os
import re
from pathlib import Path, PurePosixPath
from typing import Any

# characters the reference's forbidden-name regexes reject in path components
_FORBIDDEN = re.compile(r'[<>:"\\|?*\x00-\x1f]')


class FilePathError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class IsolatedFilePathData:
    location_id: int
    materialized_path: str  # parent dir, "/"-wrapped
    name: str
    extension: str
    is_dir: bool

    def __post_init__(self) -> None:
        mp = self.materialized_path
        if not (mp.startswith("/") and mp.endswith("/")):
            raise FilePathError(f"materialized_path must be '/'-wrapped: {mp!r}")

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_relative(cls, location_id: int, rel_path: str | PurePosixPath,
                      is_dir: bool) -> "IsolatedFilePathData":
        """Build from a path relative to the location root ('' = root itself)."""
        rel = PurePosixPath(str(rel_path).strip("/"))
        if str(rel) in (".", ""):
            return cls(location_id, "/", "", "", True)
        parent = "/" + "/".join(rel.parts[:-1])
        if not parent.endswith("/"):
            parent += "/"
        leaf = rel.parts[-1]
        if is_dir:
            return cls(location_id, parent, leaf, "", True)
        stem, dot, ext = leaf.rpartition(".")
        if not dot or not stem:  # no extension, or dotfile like ".gitignore"
            return cls(location_id, parent, leaf, "", False)
        return cls(location_id, parent, stem, ext.lower(), False)

    @classmethod
    def from_parts(cls, location_id: int, rel_dir: str, leaf: str,
                   is_dir: bool) -> "IsolatedFilePathData":
        """Fast constructor for the walker's hot loop: the caller already
        holds the parent dir (location-relative, no slashes wrapping) and
        the entry name — pure string ops, no PurePosixPath parsing."""
        parent = f"/{rel_dir}/" if rel_dir else "/"
        if is_dir:
            return cls(location_id, parent, leaf, "", True)
        stem, dot, ext = leaf.rpartition(".")
        if not dot or not stem:
            return cls(location_id, parent, leaf, "", False)
        return cls(location_id, parent, stem, ext.lower(), False)

    @classmethod
    def from_db_row(cls, row: dict[str, Any]) -> "IsolatedFilePathData":
        return cls(
            location_id=row["location_id"],
            materialized_path=row["materialized_path"],
            name=row["name"] or "",
            extension=row["extension"] or "",
            is_dir=bool(row["is_dir"]),
        )

    # -- conversions --------------------------------------------------------
    @property
    def full_name(self) -> str:
        if self.is_dir or not self.extension:
            return self.name
        return f"{self.name}.{self.extension}"

    def relative_path(self) -> str:
        """Path relative to the location root, no leading slash."""
        return (self.materialized_path + self.full_name).lstrip("/")

    def absolute_path(self, location_path: str | Path) -> Path:
        return Path(location_path) / self.relative_path()

    def parent(self) -> "IsolatedFilePathData":
        if self.materialized_path == "/":
            return IsolatedFilePathData(self.location_id, "/", "", "", True)
        parts = self.materialized_path.strip("/").split("/")
        parent_mp = "/" + "/".join(parts[:-1])
        if not parent_mp.endswith("/"):
            parent_mp += "/"
        return IsolatedFilePathData(self.location_id, parent_mp, parts[-1], "", True)

    def child_materialized_path(self) -> str:
        """The materialized_path that children of this directory carry."""
        if not self.is_dir:
            raise FilePathError("files have no children")
        if self.name == "":
            return "/"
        return f"{self.materialized_path}{self.name}/"

    def db_fields(self) -> dict[str, Any]:
        return {
            "location_id": self.location_id,
            "materialized_path": self.materialized_path,
            "name": self.name,
            "extension": self.extension,
            "is_dir": self.is_dir,
        }


def validate_name(component: str) -> bool:
    """Reject forbidden path components (forbidden-name regexes in the
    reference's isolated_file_path_data.rs)."""
    return bool(component) and not _FORBIDDEN.search(component) and component not in (".", "..")


@dataclasses.dataclass(frozen=True)
class FilePathMetadata:
    """stat() capture carried alongside each walked entry."""

    inode: int
    device: int
    size_in_bytes: int
    created_at: float
    modified_at: float
    hidden: bool

    @classmethod
    def from_stat(cls, path: "Path | str", st: os.stat_result) -> "FilePathMetadata":
        name = path if isinstance(path, str) else path.name
        return cls(
            inode=st.st_ino,
            device=st.st_dev,
            size_in_bytes=st.st_size,
            created_at=getattr(st, "st_ctime", 0.0),
            modified_at=st.st_mtime,
            hidden=name.startswith("."),
        )
