"""Indexer rules: per-location accept/reject filtering.

Mirrors core/src/location/indexer/rules/mod.rs — four rule kinds
(:155-177): accept/reject files by glob, accept/reject directories by the
presence of named children — plus the seeded system rules (rules/seed.rs:
"No OS protected", "No Hidden", "No node_modules", "Only Git Repositories").

Globs are compiled to regexes with globset semantics (``**`` crosses
separators, ``*``/``?`` don't, ``{a,b}`` alternation, ``[...]`` classes).
"""

from __future__ import annotations

import dataclasses
import os
import re
import uuid
from pathlib import Path
from typing import Any, Iterable

from ..models import Database, IndexerRule, IndexerRulesInLocation, utc_now


class RuleKind:
    ACCEPT_FILES_BY_GLOB = 0
    REJECT_FILES_BY_GLOB = 1
    ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 2
    REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 3


def glob_to_regex(glob: str) -> str:
    """globset-compatible translation."""
    out = []
    i, n = 0, len(glob)
    while i < n:
        c = glob[i]
        if c == "*":
            if glob[i : i + 3] == "**/":
                out.append("(?:[^/]+/)*")
                i += 3
                continue
            if glob[i : i + 2] == "**":
                out.append(".*")
                i += 2
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = i + 1
            if j < n and glob[j] in "!^":
                j += 1
            if j < n and glob[j] == "]":
                j += 1
            while j < n and glob[j] != "]":
                j += 1
            if j >= n:
                out.append(re.escape(c))
            else:
                cls = glob[i + 1 : j].replace("\\", "\\\\")
                if cls.startswith(("!", "^")):
                    cls = "^" + cls[1:]
                out.append(f"[{cls}]")
                i = j
        elif c == "{":
            j = glob.find("}", i)
            if j == -1:
                out.append(re.escape(c))
            else:
                alts = glob[i + 1 : j].split(",")
                out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
                i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def compile_globs(globs: Iterable[str]) -> re.Pattern:
    return re.compile("|".join(f"(?:{glob_to_regex(g)})" for g in globs) or r"(?!x)x")


@dataclasses.dataclass
class IndexerRuleSpec:
    """One named rule = per-kind parameter lists (rules_per_kind in the DB)."""

    name: str
    default: bool
    rules: dict[int, list[str]]  # RuleKind -> globs or child names
    pub_id: str = dataclasses.field(default_factory=lambda: str(uuid.uuid4()))

    def to_row(self) -> dict[str, Any]:
        return {
            "pub_id": self.pub_id,
            "name": self.name,
            "default": self.default,
            "rules_per_kind": {str(k): v for k, v in self.rules.items()},
            "date_created": utc_now(),
            "date_modified": utc_now(),
        }

    @classmethod
    def from_row(cls, row: dict[str, Any]) -> "IndexerRuleSpec":
        return cls(
            name=row["name"],
            default=bool(row["default"]),
            rules={int(k): v for k, v in (row["rules_per_kind"] or {}).items()},
            pub_id=row["pub_id"],
        )


class CompiledRules:
    """All rules for one location, compiled once per walk."""

    def __init__(self, specs: list[IndexerRuleSpec]) -> None:
        accept, reject, reject_abs = [], [], []
        self.accept_children: list[set[str]] = []
        self.reject_children: list[set[str]] = []
        for spec in specs:
            accept += spec.rules.get(RuleKind.ACCEPT_FILES_BY_GLOB, [])
            for g in spec.rules.get(RuleKind.REJECT_FILES_BY_GLOB, []):
                # globs anchored at "/" target absolute OS paths (the seeded
                # /proc, /sys... guards) — entries are walked as
                # location-relative, so these match the absolute path instead
                (reject_abs if g.startswith("/") else reject).append(g)
            if RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT in spec.rules:
                self.accept_children.append(
                    set(spec.rules[RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT]))
            if RuleKind.REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT in spec.rules:
                self.reject_children.append(
                    set(spec.rules[RuleKind.REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT]))
        self._accept = compile_globs(accept) if accept else None
        self._reject = compile_globs(reject)
        self._reject_abs = compile_globs(reject_abs) if reject_abs else None

    def allows_path(self, rel_path: str, is_dir: bool, abs_path: str = "") -> bool:
        """Glob acceptance for one entry (path relative to location root;
        ``abs_path`` additionally screens the absolute-anchored rejects)."""
        if self._reject.fullmatch(rel_path):
            return False
        if self._reject_abs is not None and abs_path and self._reject_abs.fullmatch(abs_path):
            return False
        if self._accept is not None and not is_dir and not self._accept.fullmatch(rel_path):
            return False
        return True

    def allows_dir_by_children(self, dir_path: Path) -> bool:
        """Children-presence rules need a directory listing."""
        if not self.accept_children and not self.reject_children:
            return True
        try:
            children = {e.name for e in os.scandir(dir_path) if e.is_dir(follow_symlinks=False)}
        except OSError:
            return True
        for required in self.accept_children:
            if not (children & required):
                return False
        for banned in self.reject_children:
            if children & banned:
                return False
        return True


# -- seeded system rules (rules/seed.rs) ------------------------------------

NO_OS_PROTECTED = IndexerRuleSpec(
    name="No OS protected",
    default=True,
    rules={RuleKind.REJECT_FILES_BY_GLOB: [
        "**/.DS_Store", "**/Thumbs.db", "**/desktop.ini",
        # leading "/" = absolute-path rejects (see CompiledRules.allows_path)
        "/proc/**", "/sys/**", "/dev/**", "/run/**", "/boot/**",
        "**/System Volume Information/**", "**/$RECYCLE.BIN/**",
        "**/lost+found/**", "**/.Trash-*/**",
    ]},
)

NO_HIDDEN = IndexerRuleSpec(
    name="No Hidden",
    default=True,
    rules={RuleKind.REJECT_FILES_BY_GLOB: ["**/.*"]},
)

NO_NODE_MODULES = IndexerRuleSpec(
    name="No node_modules",
    default=True,
    rules={RuleKind.REJECT_FILES_BY_GLOB: ["**/node_modules", "**/node_modules/**"]},
)

ONLY_GIT_REPOSITORIES = IndexerRuleSpec(
    name="Only Git Repositories",
    default=False,
    rules={RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT: [".git"]},
)

SYSTEM_RULES = (NO_OS_PROTECTED, NO_HIDDEN, NO_NODE_MODULES, ONLY_GIT_REPOSITORIES)


def seed_rules(db: Database) -> None:
    """Insert system rules once per library (idempotent by name)."""
    for spec in SYSTEM_RULES:
        if db.find_one(IndexerRule, {"name": spec.name}) is None:
            db.insert(IndexerRule, spec.to_row())


def rules_for_location(db: Database, location_id: int) -> list[IndexerRuleSpec]:
    links = db.find(IndexerRulesInLocation, {"location_id": location_id})
    specs = []
    for link in links:
        row = db.find_one(IndexerRule, {"id": link["indexer_rule_id"]})
        if row:
            specs.append(IndexerRuleSpec.from_row(row))
    return specs
