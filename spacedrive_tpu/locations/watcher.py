"""Location watcher: live filesystem changes → DB + sync ops.

Mirrors core/src/location/manager/watcher/ — the per-OS backend seam of
mod.rs:32-39 (Linux here is raw inotify via ctypes; anything else, or an
inotify failure, falls back to a polling backend emitting the same normalized
event stream), the Linux event-handler debounce semantics of linux.rs
(100ms update debounce, rename-cookie matching, 1s dangling-rename eviction),
and the DB application helpers of utils.rs (create_dir :76, create_file :134,
update_file :338, rename :606 incl. descendant rewrite, remove :698).

Events that survive debouncing are applied inline on the watcher thread: the
Database is single-writer-locked, matching the reference's discipline of
funnelling watcher mutations through the library DB actor.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import dataclasses
import errno
import logging
import os
import select
import struct
import sys
import threading
import time
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any, Callable

from ..models import FilePath, Location, utc_now
from .paths import FilePathMetadata, IsolatedFilePathData
from .rules import CompiledRules, rules_for_location

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

ONE_SECOND = 1.0      # dangling-rename eviction (watcher/mod.rs:46)
HUNDRED_MILLIS = 0.1  # update debounce window (watcher/mod.rs:47)


# ---------------------------------------------------------------------------
# Normalized events (what every backend emits)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RawEvent:
    kind: str            # create | modify | moved_from | moved_to | delete | overflow
    path: str            # absolute path
    is_dir: bool = False
    cookie: int = 0      # links moved_from/moved_to pairs (inotify cookie)


# ---------------------------------------------------------------------------
# inotify backend (Linux)
# ---------------------------------------------------------------------------

IN_ACCESS = 0x0001
IN_MODIFY = 0x0002
IN_ATTRIB = 0x0004
IN_CLOSE_WRITE = 0x0008
IN_MOVED_FROM = 0x0040
IN_MOVED_TO = 0x0080
IN_CREATE = 0x0100
IN_DELETE = 0x0200
IN_DELETE_SELF = 0x0400
IN_MOVE_SELF = 0x0800
IN_Q_OVERFLOW = 0x4000
IN_ISDIR = 0x40000000
IN_ONLYDIR = 0x01000000

_WATCH_MASK = (IN_CREATE | IN_MODIFY | IN_ATTRIB | IN_CLOSE_WRITE
               | IN_MOVED_FROM | IN_MOVED_TO | IN_DELETE | IN_DELETE_SELF)

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


class InotifyBackend:
    """Raw inotify via libc. inotify watches are per-directory, so the backend
    mirrors the directory tree into a wd↔path map, growing it as directories
    appear and pruning on IN_DELETE_SELF/IN_IGNORED."""

    def __init__(self, root: str) -> None:
        libc_name = ctypes.util.find_library("c") or "libc.so.6"
        self._libc = ctypes.CDLL(libc_name, use_errno=True)
        self._fd = self._libc.inotify_init1(os.O_NONBLOCK)
        if self._fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._wd_to_path: dict[int, str] = {}
        self._path_to_wd: dict[str, int] = {}
        self._buf = b""
        self.root = root
        self._add_watch_recursive(root)

    def _add_watch(self, path: str) -> None:
        wd = self._libc.inotify_add_watch(
            self._fd, os.fsencode(path), _WATCH_MASK | IN_ONLYDIR)
        if wd < 0:
            err = ctypes.get_errno()
            if err not in (errno.ENOENT, errno.ENOTDIR):
                logger.warning("inotify_add_watch(%s): %s", path, os.strerror(err))
            return
        old = self._wd_to_path.get(wd)
        if old is not None:
            self._path_to_wd.pop(old, None)
        self._wd_to_path[wd] = path
        self._path_to_wd[path] = wd

    def _add_watch_recursive(self, path: str) -> None:
        self._add_watch(path)
        try:
            with os.scandir(path) as it:
                for entry in it:
                    if entry.is_dir(follow_symlinks=False):
                        self._add_watch_recursive(entry.path)
        except OSError:
            pass

    def note_dir_moved(self, from_path: str, to_path: str) -> None:
        """inotify wds follow inodes across renames; rebase our path map."""
        prefix = from_path.rstrip("/") + "/"
        for wd, path in list(self._wd_to_path.items()):
            if path == from_path or path.startswith(prefix):
                new = to_path + path[len(from_path):]
                self._path_to_wd.pop(path, None)
                self._wd_to_path[wd] = new
                self._path_to_wd[new] = wd

    def watch_new_dir(self, path: str) -> None:
        self._add_watch_recursive(path)

    def read(self, timeout: float) -> list[RawEvent]:
        try:
            ready, _, _ = select.select([self._fd], [], [], timeout)
        except OSError:
            return []
        if not ready:
            return []
        try:
            self._buf += os.read(self._fd, 65536)
        except BlockingIOError:
            return []
        except OSError:
            return []
        events: list[RawEvent] = []
        buf = self._buf
        offset = 0
        while offset + _EVENT_HDR.size <= len(buf):
            wd, mask, cookie, name_len = _EVENT_HDR.unpack_from(buf, offset)
            if offset + _EVENT_HDR.size + name_len > len(buf):
                break
            name = buf[offset + _EVENT_HDR.size: offset + _EVENT_HDR.size
                       + name_len].rstrip(b"\x00").decode(errors="surrogateescape")
            offset += _EVENT_HDR.size + name_len
            if mask & IN_Q_OVERFLOW:
                events.append(RawEvent("overflow", self.root))
                continue
            dir_path = self._wd_to_path.get(wd)
            if dir_path is None:
                continue
            if mask & IN_DELETE_SELF:
                self._path_to_wd.pop(dir_path, None)
                self._wd_to_path.pop(wd, None)
                continue
            path = os.path.join(dir_path, name) if name else dir_path
            is_dir = bool(mask & IN_ISDIR)
            if mask & IN_CREATE:
                events.append(RawEvent("create", path, is_dir, cookie))
            elif mask & (IN_CLOSE_WRITE | IN_MODIFY | IN_ATTRIB):
                events.append(RawEvent("modify", path, is_dir, cookie))
            elif mask & IN_MOVED_FROM:
                events.append(RawEvent("moved_from", path, is_dir, cookie))
            elif mask & IN_MOVED_TO:
                events.append(RawEvent("moved_to", path, is_dir, cookie))
            elif mask & IN_DELETE:
                events.append(RawEvent("delete", path, is_dir, cookie))
        self._buf = buf[offset:]
        return events

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Polling backend (fallback; also the deterministic backend for tests)
# ---------------------------------------------------------------------------

class PollingBackend:
    """Periodic scandir snapshot diff emitting the same normalized stream.
    Renames are recovered by inode identity between the vanished and the
    appeared sets (the same trick the walker's DB diffing uses)."""

    def __init__(self, root: str, interval: float = 0.5) -> None:
        self.root = root
        self.interval = interval
        self._snapshot = self._scan()
        self._last = time.monotonic()
        self._cookie = 0

    def _scan(self) -> dict[str, tuple[bool, float, int, int]]:
        snap: dict[str, tuple[bool, float, int, int]] = {}
        stack = [self.root]
        while stack:
            d = stack.pop()
            try:
                with os.scandir(d) as it:
                    entries = list(it)
            except OSError:
                continue
            for entry in entries:
                try:
                    if entry.is_symlink():
                        continue
                    is_dir = entry.is_dir(follow_symlinks=False)
                    st = entry.stat(follow_symlinks=False)
                except OSError:
                    continue
                snap[entry.path] = (is_dir, st.st_mtime, st.st_size, st.st_ino)
                if is_dir:
                    stack.append(entry.path)
        return snap

    def note_dir_moved(self, from_path: str, to_path: str) -> None:
        pass

    def watch_new_dir(self, path: str) -> None:
        pass

    def read(self, timeout: float) -> list[RawEvent]:
        now = time.monotonic()
        wait = min(timeout, max(0.0, self.interval - (now - self._last)))
        if wait > 0:
            time.sleep(wait)
        if time.monotonic() - self._last < self.interval:
            return []
        self._last = time.monotonic()
        new = self._scan()
        old = self._snapshot
        self._snapshot = new
        events: list[RawEvent] = []
        gone = {p: v for p, v in old.items() if p not in new}
        appeared = {p: v for p, v in new.items() if p not in old}
        # pair renames by inode
        gone_by_ino = {v[3]: p for p, v in gone.items()}
        for path, (is_dir, _, _, ino) in sorted(appeared.items()):
            src = gone_by_ino.pop(ino, None)
            if src is not None and gone[src][0] == is_dir:
                self._cookie += 1
                events.append(RawEvent("moved_from", src, is_dir, self._cookie))
                events.append(RawEvent("moved_to", path, is_dir, self._cookie))
                del gone[src]
            else:
                events.append(RawEvent("create", path, is_dir))
        for path, (is_dir, *_rest) in sorted(gone.items()):
            events.append(RawEvent("delete", path, is_dir))
        for path, (is_dir, mtime, size, ino) in new.items():
            if path in old and not is_dir:
                o = old[path]
                if o[1] != mtime or o[2] != size:
                    events.append(RawEvent("modify", path, is_dir))
        return events

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# DB application helpers (watcher/utils.rs semantics)
# ---------------------------------------------------------------------------

def _emit(library: "Library") -> tuple[Any, bool]:
    sync = getattr(library, "sync", None)
    return sync, sync is not None and getattr(sync, "emit_messages", False)


def _row_for(library: "Library", location_id: int,
             iso: IsolatedFilePathData) -> dict[str, Any] | None:
    return library.db.find_one(FilePath, {
        "location_id": location_id,
        "materialized_path": iso.materialized_path,
        "name": iso.name, "extension": iso.extension,
    })


def apply_create(library: "Library", location: dict[str, Any],
                 rel_path: str, is_dir: bool) -> bool:
    """create_dir/create_file (utils.rs:76,134): insert the row + sync op.
    Returns False when the path vanished before we could stat it."""
    db = library.db
    iso = IsolatedFilePathData.from_relative(location["id"], rel_path, is_dir)
    abs_path = iso.absolute_path(location["path"])
    try:
        st = abs_path.stat()
    except OSError:
        return False
    meta = FilePathMetadata.from_stat(abs_path, st)
    existing = _row_for(library, location["id"], iso)
    if existing is not None:
        return apply_update(library, location, rel_path, is_dir)
    row = {
        "pub_id": str(uuid.uuid4()),
        **iso.db_fields(),
        "inode": meta.inode, "device": meta.device,
        "size_in_bytes": meta.size_in_bytes, "hidden": meta.hidden,
        "date_created": _iso_ts(meta.created_at),
        "date_modified": _iso_ts(meta.modified_at),
        "date_indexed": utc_now().isoformat(),
    }
    sync, emit = _emit(library)
    with db.transaction():
        db.insert_many(FilePath, [row], or_ignore=True)
        if emit:
            sync.shared_create_many(FilePath, [row])
    if emit:
        sync.created()
    library.emit("invalidate_query", {"key": "search.paths"})
    return True


def apply_update(library: "Library", location: dict[str, Any],
                 rel_path: str, is_dir: bool) -> bool:
    """update_file (utils.rs:338): refresh metadata; content changes clear the
    cas_id/object link so re-identification runs."""
    db = library.db
    iso = IsolatedFilePathData.from_relative(location["id"], rel_path, is_dir)
    row = _row_for(library, location["id"], iso)
    if row is None:
        return apply_create(library, location, rel_path, is_dir)
    abs_path = iso.absolute_path(location["path"])
    try:
        st = abs_path.stat()
    except OSError:
        return False
    meta = FilePathMetadata.from_stat(abs_path, st)
    content_changed = ((row.get("size_in_bytes") or 0) != meta.size_in_bytes
                       or abs(_mtime_of(row) - meta.modified_at) > 0.001)
    values: dict[str, Any] = {
        "size_in_bytes": meta.size_in_bytes,
        "inode": meta.inode, "device": meta.device,
        "date_modified": _iso_ts(meta.modified_at),
        "hidden": meta.hidden,
    }
    if content_changed and not is_dir:
        values["cas_id"] = None
        values["object_id"] = None
    sync, emit = _emit(library)
    ops = []
    with db.transaction():
        db.update(FilePath, {"id": row["id"]}, values)
        if emit:
            for field in ("size_in_bytes", "date_modified", "cas_id"):
                if field in values:
                    ops.append(sync.shared_update(
                        FilePath, row["pub_id"], field, values[field]))
            if ops:
                sync.log_ops(ops)
    if emit and ops:
        sync.created()
    library.emit("invalidate_query", {"key": "search.paths"})
    return content_changed


def apply_rename(library: "Library", location: dict[str, Any],
                 from_rel: str, to_rel: str, is_dir: bool) -> None:
    """rename (utils.rs:606): move the row to its new identity; for
    directories rewrite every descendant's materialized_path prefix. Keeps
    cas_id/object (a rename is not a content change)."""
    db = library.db
    from_iso = IsolatedFilePathData.from_relative(location["id"], from_rel, is_dir)
    to_iso = IsolatedFilePathData.from_relative(location["id"], to_rel, is_dir)
    row = _row_for(library, location["id"], from_iso)
    if row is None:
        # never indexed (e.g. moved in and instantly renamed) — treat as create
        apply_create(library, location, to_rel, is_dir)
        return
    # if something already sits at the target identity, drop it first
    # (the reference checks for an existing file_path at the new path)
    clash = _row_for(library, location["id"], to_iso)
    if clash is not None and clash["id"] != row["id"]:
        apply_remove_row(library, clash)
    sync, emit = _emit(library)
    ops = []
    with db.transaction():
        db.update(FilePath, {"id": row["id"]}, {
            "materialized_path": to_iso.materialized_path,
            "name": to_iso.name, "extension": to_iso.extension,
            "date_modified": utc_now().isoformat(),
        })
        if emit:
            for field, value in (("materialized_path", to_iso.materialized_path),
                                 ("name", to_iso.name),
                                 ("extension", to_iso.extension)):
                ops.append(sync.shared_update(FilePath, row["pub_id"], field, value))
        if is_dir:
            old_prefix = from_iso.child_materialized_path()
            new_prefix = to_iso.child_materialized_path()
            descendants = db.query(
                "SELECT id, pub_id, materialized_path FROM file_path "
                "WHERE location_id = ? AND materialized_path LIKE ?",
                [location["id"], old_prefix + "%"])
            for d in descendants:
                new_mp = new_prefix + d["materialized_path"][len(old_prefix):]
                db.update(FilePath, {"id": d["id"]}, {"materialized_path": new_mp})
                if emit:
                    ops.append(sync.shared_update(
                        FilePath, d["pub_id"], "materialized_path", new_mp))
        if emit and ops:
            sync.log_ops(ops)
    if emit and ops:
        sync.created()
    library.emit("invalidate_query", {"key": "search.paths"})


def apply_remove_row(library: "Library", row: dict[str, Any]) -> None:
    from ..objects.fs import _remove_rows

    _remove_rows(library, row)
    library.emit("invalidate_query", {"key": "search.paths"})


def apply_remove(library: "Library", location: dict[str, Any],
                 rel_path: str) -> None:
    """remove (utils.rs:698): drop the row and, for directories, the whole
    subtree, emitting sync deletes."""
    for is_dir in (False, True):  # the delete event may not carry is_dir reliably
        iso = IsolatedFilePathData.from_relative(location["id"], rel_path, is_dir)
        row = _row_for(library, location["id"], iso)
        if row is not None:
            apply_remove_row(library, row)
            return


def _iso_ts(ts: float) -> str:
    import datetime as dt

    return dt.datetime.fromtimestamp(ts, dt.timezone.utc).isoformat()


def _mtime_of(row: dict[str, Any]) -> float:
    value = row.get("date_modified")
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        import datetime as dt

        try:
            return dt.datetime.fromisoformat(value).timestamp()
        except ValueError:
            return 0.0
    return value.timestamp()


# ---------------------------------------------------------------------------
# Event handler (linux.rs debounce semantics)
# ---------------------------------------------------------------------------

class EventHandler:
    """Normalized-event → DB actions with the Linux handler's buffering:
    create/modify are debounced 100ms per path (coalescing write bursts),
    rename pairs match on cookie, dangling moved_from evict to removes after
    1s, and changed files get re-identified in one shallow pass per flush."""

    def __init__(self, library: "Library", location: dict[str, Any],
                 rules: CompiledRules, backend) -> None:
        self.library = library
        self.location = location
        self.rules = rules
        self.backend = backend
        self.files_to_update: dict[str, float] = {}
        self.rename_from: dict[int, tuple[str, bool, float]] = {}
        self.need_identify = False

    def _rel(self, path: str) -> str | None:
        root = self.location["path"].rstrip("/")
        if path == root:
            return None
        if not path.startswith(root + "/"):
            return None
        return path[len(root) + 1:]

    def _allowed(self, rel_path: str, is_dir: bool, abs_path: str) -> bool:
        try:
            return self.rules.allows_path(rel_path, is_dir, abs_path=abs_path)
        except Exception:
            return True

    def handle(self, ev: RawEvent) -> None:
        if ev.kind == "overflow":
            # the kernel dropped events; reconcile with a full light pass
            from . import light_scan_location

            logger.warning("watcher queue overflow; rescanning location %s",
                           self.location["id"])
            try:
                light_scan_location(self.library, self.location["id"])
            except Exception:
                logger.exception("overflow rescan failed")
            return
        rel = self._rel(ev.path)
        if rel is None or os.path.basename(ev.path) == ".spacedrive":
            return
        if not self._allowed(rel, ev.is_dir, ev.path):
            return
        now = time.monotonic()
        if ev.kind == "create":
            if ev.is_dir:
                self.backend.watch_new_dir(ev.path)
                self._index_subtree(rel)
            else:
                self.files_to_update[ev.path] = now
        elif ev.kind == "modify":
            if not ev.is_dir:
                self.files_to_update[ev.path] = now
        elif ev.kind == "moved_from":
            self.rename_from[ev.cookie] = (ev.path, ev.is_dir, now)
        elif ev.kind == "moved_to":
            pending = self.rename_from.pop(ev.cookie, None)
            if pending is not None:
                from_path, is_dir, _ = pending
                from_rel = self._rel(from_path)
                if is_dir:
                    self.backend.note_dir_moved(from_path, ev.path)
                if from_rel is not None:
                    apply_rename(self.library, self.location, from_rel, rel, is_dir)
                else:
                    self._moved_in(rel, ev)
            else:
                self._moved_in(rel, ev)
        elif ev.kind == "delete":
            self.files_to_update.pop(ev.path, None)
            apply_remove(self.library, self.location, rel)

    def _moved_in(self, rel: str, ev: RawEvent) -> None:
        """moved_to with no matching moved_from = arrived from outside the
        watched tree (linux.rs module docs) — a plain create."""
        if ev.is_dir:
            self.backend.watch_new_dir(ev.path)
            self._index_subtree(rel)
        else:
            self.files_to_update[ev.path] = time.monotonic()

    def _index_subtree(self, rel_path: str) -> None:
        """A directory appeared (created or moved in): index it recursively —
        the reference receives a bare Create Dir and walks it."""
        from .indexer_job import _entry_to_row
        from .walker import db_fetcher_for, walk

        db = self.library.db
        if not apply_create(self.library, self.location, rel_path, True):
            return
        result = walk(self.location["id"], self.location["path"], self.rules,
                      db_fetcher_for(db, self.location["id"]),
                      sub_path=rel_path, include_root=False)
        rows = [_entry_to_row(e) for e in result.walked]
        if rows:
            sync, emit = _emit(self.library)
            with db.transaction():
                db.insert_many(FilePath, rows, or_ignore=True)
                if emit:
                    sync.shared_create_many(FilePath, rows)
            if emit:
                sync.created()
            self.need_identify = True
        self.library.emit("invalidate_query", {"key": "search.paths"})

    def tick(self) -> None:
        now = time.monotonic()
        # flush debounced updates older than the window
        ready = [p for p, t in self.files_to_update.items()
                 if now - t >= HUNDRED_MILLIS]
        for path in ready:
            del self.files_to_update[path]
            rel = self._rel(path)
            if rel is None:
                continue
            if apply_update(self.library, self.location, rel, False):
                self.need_identify = True
        # evict dangling renames (moved outside the location) to removes
        for cookie, (path, is_dir, t) in list(self.rename_from.items()):
            if now - t >= ONE_SECOND:
                del self.rename_from[cookie]
                rel = self._rel(path)
                if rel is not None:
                    apply_remove(self.library, self.location, rel)
        if self.need_identify and not self.files_to_update:
            self.need_identify = False
            from ..objects.file_identifier import shallow_identify

            try:
                shallow_identify(self.library, self.location["id"])
            except Exception:
                logger.exception("watcher re-identify failed")


# ---------------------------------------------------------------------------
# The watcher actor
# ---------------------------------------------------------------------------

def _make_backend(root: str):
    if sys.platform.startswith("linux"):
        try:
            return InotifyBackend(root)
        except OSError as e:
            logger.warning("inotify unavailable (%s); polling fallback", e)
    return PollingBackend(root)


class LocationWatcher:
    """Per-location watcher thread (LocationWatcher, watcher/mod.rs:69-76):
    owns a backend + handler, applies events until stopped. ``ignore_path``
    mirrors the IgnorePath channel that fs jobs use to mute their own writes."""

    def __init__(self, library: "Library", location_id: int,
                 backend_factory: Callable[[str], Any] | None = None,
                 poll_interval: float = 0.25) -> None:
        row = library.db.find_one(Location, {"id": location_id})
        if row is None or not row.get("path"):
            raise ValueError(f"location {location_id} has no path")
        if not Path(row["path"]).is_dir():
            raise ValueError(f"location path missing on disk: {row['path']}")
        self.library = library
        self.location = row
        self.poll_interval = poll_interval
        self._ignored: set[str] = set()
        self._ignored_lock = threading.Lock()
        self.backend = (backend_factory or _make_backend)(row["path"])
        rules = CompiledRules(rules_for_location(library.db, location_id))
        self.handler = EventHandler(library, row, rules, self.backend)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"watcher-{location_id}", daemon=True)
        self._thread.start()

    def ignore_path(self, path: str | Path, ignore: bool) -> None:
        with self._ignored_lock:
            if ignore:
                self._ignored.add(str(path))
            else:
                self._ignored.discard(str(path))

    def _is_ignored(self, path: str) -> bool:
        with self._ignored_lock:
            if not self._ignored:
                return False
            for ig in self._ignored:
                if path == ig or path.startswith(ig.rstrip("/") + "/"):
                    return True
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                events = self.backend.read(self.poll_interval)
                for ev in events:
                    if self._is_ignored(ev.path):
                        continue
                    self.handler.handle(ev)
                self.handler.tick()
            except Exception:
                logger.exception("watcher loop error (location %s)",
                                 self.location["id"])
                time.sleep(0.5)

    def flush(self, timeout: float = 3.0) -> None:
        """Testing/shutdown aid: wait until debounce buffers drain."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (not self.handler.files_to_update and not self.handler.rename_from
                    and not self.handler.need_identify):
                return
            time.sleep(0.05)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.backend.close()
