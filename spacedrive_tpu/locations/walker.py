"""Filesystem walker: rules-filtered, DB-diffing, budgeted BFS.

Mirrors the semantics of core/src/location/indexer/walk.rs — iterative walk
applying rules per entry (:116-186), keep-walking continuation for dirs beyond
the budget (:187-240), single-dir walk for shallow reindex (:242-310), and
existing-path diffing on (inode, device) + mtime >1ms delta (:355-372).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from collections import deque
from pathlib import Path
from typing import Any, Callable

from .paths import FilePathMetadata, IsolatedFilePathData
from .rules import CompiledRules

logger = logging.getLogger(__name__)

#: mtime delta below which a file is considered unchanged (walk.rs:361 uses 1ms)
MTIME_EPSILON_S = 0.001


@dataclasses.dataclass(frozen=True)
class WalkedEntry:
    iso: IsolatedFilePathData
    metadata: FilePathMetadata
    #: for updates: the matched DB row id, and whether content (not just the
    #: name — renames keep their cas_id/object) changed
    row_id: int | None = None
    content_changed: bool = True

    @property
    def rel_path(self) -> str:
        return self.iso.relative_path()


@dataclasses.dataclass
class WalkResult:
    walked: list[WalkedEntry]          # new entries to save
    to_update: list[WalkedEntry]       # existing entries whose metadata changed
    to_walk: list[str]                 # rel dir paths beyond the budget
    to_remove: list[dict[str, Any]]    # db rows no longer on disk
    errors: list[str]


DbFetcher = Callable[[str], list[dict[str, Any]]]
"""rel dir path -> existing file_path rows whose materialized_path is that dir
(the ``file_paths_db_fetcher_fn!`` seam, walk.rs)."""


def resolve_sub_path(root: Path, sub_path: str) -> Path:
    """Join + containment check: a sub_path may not escape the location root
    (the reference validates sub-paths via ensure_sub_path_is_in_location
    before walking). Raises ValueError with a clear message otherwise."""
    if not sub_path:
        return root
    start = (root / sub_path).resolve()
    if start != root.resolve() and root.resolve() not in start.parents:
        raise ValueError(f"sub_path {sub_path!r} escapes location root {root}")
    return start


def walk(
    location_id: int,
    location_path: str | Path,
    rules: CompiledRules,
    db_fetcher: DbFetcher | None = None,
    sub_path: str = "",
    limit: int = 50_000,
    include_root: bool = True,
    recurse: bool = True,
) -> WalkResult:
    """BFS from ``location_path/sub_path``; stops enqueuing new directories
    into the in-walk queue once ``limit`` entries have been produced, returning
    the remainder as ``to_walk`` continuation dirs (indexer_job.rs:183-198)."""
    root = Path(location_path)
    start = resolve_sub_path(root, sub_path)
    result = WalkResult([], [], [], [], [])

    if include_root and not sub_path:
        try:
            st = start.stat()
            result.walked.append(WalkedEntry(
                IsolatedFilePathData.from_relative(location_id, "", True),
                FilePathMetadata.from_stat(start, st),
            ))
        except OSError as e:
            result.errors.append(f"stat location root: {e}")
            return result

    # queue holds (absolute dir, location-relative dir) STRINGS — pathlib
    # object churn was ~60% of walk time at 20k entries (profiled), so the
    # hot loop below is pure string ops
    start_rel = start.relative_to(root).as_posix()
    queue: deque[tuple[str, str]] = deque(
        [(str(start), "" if start_rel == "." else start_rel)])
    produced = 0
    while queue:
        dir_path, rel_dir = queue.popleft()

        existing: dict[tuple[int, int], dict[str, Any]] = {}
        by_name: dict[str, dict[str, Any]] = {}
        if db_fetcher is not None:
            for row in db_fetcher(rel_dir):
                if row.get("inode") is not None:
                    existing[(row["inode"], row["device"])] = row
                name = (row.get("name") or "")
                ext = row.get("extension") or ""
                by_name[f"{name}.{ext}" if ext and not row.get("is_dir") else name] = row
        seen_names: set[str] = set()

        try:
            entries = sorted(os.scandir(dir_path), key=lambda e: e.name)
        except OSError as e:
            result.errors.append(f"scandir {rel_dir or '/'}: {e}")
            continue

        for entry in entries:
            rel_path = f"{rel_dir}/{entry.name}" if rel_dir else entry.name
            try:
                is_dir = entry.is_dir(follow_symlinks=False)
                if entry.is_symlink():
                    seen_names.add(entry.name)  # present on disk, just skipped
                    continue  # reference skips symlinks in the indexer walk
                if not rules.allows_path(rel_path, is_dir, abs_path=entry.path):
                    continue
                if is_dir and not rules.allows_dir_by_children(entry.path):
                    continue
                st = entry.stat(follow_symlinks=False)
            except OSError as e:
                result.errors.append(f"stat {rel_path}: {e}")
                # transient failure must NOT delete the row in the sweep below
                seen_names.add(entry.name)
                continue

            iso = IsolatedFilePathData.from_parts(
                location_id, rel_dir, entry.name, is_dir)
            meta = FilePathMetadata.from_stat(entry.name, st)
            seen_names.add(iso.full_name)

            row = existing.get((st.st_ino, st.st_dev))
            if row is None and db_fetcher is not None:
                row = by_name.get(iso.full_name)
            if row is not None:
                old_name = _full_name_of(row)
                renamed = old_name != iso.full_name
                if renamed:
                    seen_names.add(old_name)  # rename, not a removal
                content_changed = (
                    abs(meta.modified_at - _mtime_of(row)) > MTIME_EPSILON_S
                    or (row.get("size_in_bytes") or 0) != meta.size_in_bytes
                )
                if renamed or content_changed or row.get("inode") != meta.inode:
                    result.to_update.append(WalkedEntry(
                        iso, meta, row_id=row["id"], content_changed=content_changed))
            else:
                result.walked.append(WalkedEntry(iso, meta))
                produced += 1

            if is_dir and recurse:
                if produced < limit:
                    queue.append((entry.path, rel_path))
                else:
                    result.to_walk.append(rel_path)

        # rows in DB under this dir but no longer on disk (or now rule-rejected)
        for name, row in by_name.items():
            if name and name not in seen_names:
                result.to_remove.append(row)

    return result


def walk_single_dir(location_id: int, location_path: str | Path,
                    rules: CompiledRules, sub_path: str = "",
                    db_fetcher: DbFetcher | None = None) -> WalkResult:
    """Shallow single-directory walk (walk_single_dir, walk.rs:242-310) used by
    the watcher and UI refresh."""
    return walk(location_id, location_path, rules, db_fetcher,
                sub_path=sub_path, include_root=False, recurse=False)


def db_fetcher_for(db, location_id: int) -> DbFetcher:
    """The standard rel-dir → file_path-rows fetcher (file_paths_db_fetcher_fn!
    seam) shared by the indexer job and shallow rescans."""
    from ..models import FilePath

    def fetch(rel_dir: str) -> list[dict[str, Any]]:
        mp = "/" + (rel_dir + "/" if rel_dir else "")
        return db.find(FilePath, {"location_id": location_id, "materialized_path": mp})

    return fetch


def _full_name_of(row: dict[str, Any]) -> str:
    name = row.get("name") or ""
    ext = row.get("extension") or ""
    return f"{name}.{ext}" if ext and not row.get("is_dir") else name


def _mtime_of(row: dict[str, Any]) -> float:
    value = row.get("date_modified")
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    return value.timestamp()
