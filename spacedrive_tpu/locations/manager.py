"""Locations actor: watcher lifecycle + online-locations set.

Mirrors core/src/location/manager/mod.rs — tracks which locations are online
and owns per-location filesystem watchers (inotify on Linux; the per-OS
EventHandler seam of watcher/mod.rs:32-66 is kept for parity). The watcher is
attached lazily in the watcher milestone; the actor API is stable now so the
Node boot order matches the reference.
"""

from __future__ import annotations

import logging
import threading
from typing import TYPE_CHECKING

from ..library import LibraryManagerEvent

if TYPE_CHECKING:
    from ..library import Library
    from ..node import Node

logger = logging.getLogger(__name__)


class LocationsActor:
    def __init__(self, node: "Node") -> None:
        self.node = node
        self._lock = threading.Lock()
        self._online: set[tuple[str, int]] = set()  # (library_id, location_id)
        self._watchers: dict[tuple[str, int], object] = {}
        #: media warm-start dedup: (library_id, location_id, prefix) already
        #: handed to the media lane this process
        self._warm_started: set[tuple[str, int, str]] = set()
        node.libraries.subscribe(self._on_library_event)

    def _on_library_event(self, event: str, library: "Library") -> None:
        from ..models import Location

        if event == LibraryManagerEvent.LOAD:
            for row in library.db.find(Location):
                self.add(library, row["id"])
        elif event == LibraryManagerEvent.DELETE:
            with self._lock:
                for key in [k for k in self._online if k[0] == library.id]:
                    self._online.discard(key)
                    self._stop_watcher(key)

    def add(self, library: "Library", location_id: int) -> None:
        key = (library.id, location_id)
        with self._lock:
            self._online.add(key)
        self._start_watcher(library, location_id)

    def remove(self, library: "Library", location_id: int) -> None:
        key = (library.id, location_id)
        with self._lock:
            self._online.discard(key)
            self._stop_watcher(key)

    def is_online(self, library_id: str, location_id: int) -> bool:
        with self._lock:
            return (library_id, location_id) in self._online

    def online_ids(self, library_id: str) -> list[int]:
        with self._lock:
            return sorted(loc for lib, loc in self._online if lib == library_id)

    def media_warm_start(self, library: "Library", location_id: int,
                         prefixes: set[str]) -> None:
        """Start media processing for freshly identified prefixes instead of
        waiting for the whole identify job: spawns one media-lane
        MediaProcessorJob per new prefix (jobs/manager.py lanes), which runs
        concurrently with the default-lane scan chain. Best-effort — dedup
        by prefix per process, JobAlreadyRunning swallowed — because the
        chained whole-location media job sweeps up anything missed."""
        from ..jobs.error import JobAlreadyRunning
        from ..objects.media.processor import MediaProcessorJob

        jobs = getattr(self.node, "jobs", None)
        if jobs is None:
            return
        for prefix in sorted(prefixes):
            key = (library.id, location_id, prefix)
            with self._lock:
                if key in self._warm_started:
                    continue
                self._warm_started.add(key)
            try:
                jobs.spawn(library, [MediaProcessorJob(
                    {"location_id": location_id, "sub_path": prefix})],
                    action="media_warm_start")
            except JobAlreadyRunning:
                pass
            except Exception:
                logger.exception("media warm-start failed for %s", prefix)

    def _start_watcher(self, library: "Library", location_id: int) -> None:
        if not getattr(self.node, "watch_locations", True):
            return
        from .watcher import LocationWatcher

        key = (library.id, location_id)
        with self._lock:
            if key in self._watchers:
                return
            try:
                self._watchers[key] = LocationWatcher(library, location_id)
            except Exception as e:
                logger.warning("watcher for location %s failed to start: %s",
                               location_id, e)

    def watcher_for(self, library_id: str, location_id: int):
        """fs jobs use this to mute their own writes (IgnorePath channel)."""
        with self._lock:
            return self._watchers.get((library_id, location_id))

    def _stop_watcher(self, key: tuple[str, int]) -> None:
        watcher = self._watchers.pop(key, None)
        if watcher is not None:
            try:
                watcher.stop()  # type: ignore[attr-defined]
            except Exception:
                logger.exception("watcher stop failed")

    def stop(self) -> None:
        with self._lock:
            keys = list(self._watchers)
        for key in keys:
            with self._lock:
                self._stop_watcher(key)
