"""Library backups: tar.gz snapshots of config + DB with a magic header.

Parity with core/src/api/backups.rs:32-108: a backup file = fixed-size magic
header (magic bytes, backup id, timestamp, library id, library name) followed
by a tar.gz of the `.sdlibrary` config and `.db` database. Restore unloads
the library, untars over the originals, and reloads.
"""

from __future__ import annotations

import io
import json
import struct
import tarfile
import time
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from .node import Node

MAGIC = b"SDTPUBAK"  # 8 bytes
HEADER_LEN = 256


def _header(backup_id: str, library_id: str, library_name: str) -> bytes:
    meta = json.dumps({
        "id": backup_id, "timestamp": int(time.time() * 1000),
        "library_id": library_id, "library_name": library_name[:80],
    }).encode()
    if len(meta) > HEADER_LEN - 12:
        meta = meta[: HEADER_LEN - 12]
    return MAGIC + struct.pack("<I", len(meta)) + meta.ljust(HEADER_LEN - 12, b"\0")


def read_header(path: str | Path) -> dict[str, Any]:
    with open(path, "rb") as fh:
        head = fh.read(HEADER_LEN)
    if len(head) < HEADER_LEN or not head.startswith(MAGIC):
        raise ValueError(f"not a backup file: {path}")
    (meta_len,) = struct.unpack_from("<I", head, 8)
    return json.loads(head[12 : 12 + meta_len])


def backups_dir(node: "Node") -> Path:
    d = node.data_dir / "backups"
    d.mkdir(parents=True, exist_ok=True)
    return d


def list_backups(node: "Node") -> list[dict[str, Any]]:
    out = []
    for path in sorted(backups_dir(node).glob("*.bkp")):
        try:
            out.append({**read_header(path), "path": str(path)})
        except (ValueError, json.JSONDecodeError):
            continue
    return out


def do_backup(node: "Node", library_id: str) -> str:
    library = node.libraries.get(library_id)
    backup_id = str(uuid.uuid4())
    target = backups_dir(node) / f"{backup_id}.bkp"
    cfg_path = node.libraries.dir / f"{library_id}.sdlibrary"
    db_path = node.libraries.dir / f"{library_id}.db"
    library.db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        tar.add(cfg_path, arcname=f"{library_id}.sdlibrary")
        tar.add(db_path, arcname=f"{library_id}.db")
    with open(target, "wb") as fh:
        fh.write(_header(backup_id, library_id, library.name))
        fh.write(buf.getvalue())
    return backup_id


def do_restore(node: "Node", backup_path: str | Path) -> str:
    header = read_header(backup_path)
    library_id = header["library_id"]
    # unload if loaded (restore semantics: backups.rs restore)
    try:
        library = node.libraries.get(library_id)
        library.close()
        node.libraries._libraries.pop(library_id, None)
    except KeyError:
        pass
    with open(backup_path, "rb") as fh:
        fh.seek(HEADER_LEN)
        with tarfile.open(fileobj=io.BytesIO(fh.read()), mode="r:gz") as tar:
            members = [m for m in tar.getmembers()
                       if m.name in (f"{library_id}.sdlibrary", f"{library_id}.db")]
            tar.extractall(node.libraries.dir, members=members, filter="data")
    node.libraries._load(library_id)
    return library_id


def delete_backup(node: "Node", backup_id: str) -> None:
    path = backups_dir(node) / f"{backup_id}.bkp"
    if path.exists():
        path.unlink()
