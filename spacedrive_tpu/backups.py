"""Library backups: tar.gz snapshots of config + DB with a magic header.

Parity with core/src/api/backups.rs:32-108: a backup file = fixed-size magic
header (magic bytes, backup id, timestamp, library id, library name) followed
by a tar.gz of the `.sdlibrary` config and `.db` database.

Crash-consistency contract (ISSUE 9):

- **backup** writes are atomic (tempfile → fsync → rename, utils/atomic):
  a kill mid-backup leaves no ``.bkp`` at all, never a torn one;
- **restore** validates the tarball and the header ``library_id`` first,
  extracts into a temp dir next to the live files, and only then renames
  the validated files over the originals — a kill at ANY point during a
  restore leaves the old library intact (the renames are last, and
  per-file atomic);
- the boot-time integrity ladder (recovery.py) reuses the same validated
  extraction to repair a library whose DB fails ``PRAGMA quick_check``.
"""

from __future__ import annotations

import gzip
import io
import json
import logging
import struct
import tarfile
import time
import uuid
import zlib
from pathlib import Path
from typing import TYPE_CHECKING, Any

from . import faults
from .utils.atomic import TMP_MARK, atomic_write_bytes

if TYPE_CHECKING:
    from .node import Node

logger = logging.getLogger(__name__)

MAGIC = b"SDTPUBAK"  # 8 bytes
HEADER_LEN = 256


def _header(backup_id: str, library_id: str, library_name: str) -> bytes:
    meta = json.dumps({
        "id": backup_id, "timestamp": int(time.time() * 1000),
        "library_id": library_id, "library_name": library_name[:80],
    }).encode()
    if len(meta) > HEADER_LEN - 12:
        meta = meta[: HEADER_LEN - 12]
    return MAGIC + struct.pack("<I", len(meta)) + meta.ljust(HEADER_LEN - 12, b"\0")


def read_header(path: str | Path) -> dict[str, Any]:
    with open(path, "rb") as fh:
        head = fh.read(HEADER_LEN)
    if len(head) < HEADER_LEN or not head.startswith(MAGIC):
        raise ValueError(f"not a backup file: {path}")
    (meta_len,) = struct.unpack_from("<I", head, 8)
    return json.loads(head[12 : 12 + meta_len])


def backups_dir(node: "Node") -> Path:
    d = node.data_dir / "backups"
    d.mkdir(parents=True, exist_ok=True)
    return d


def list_backups(node: "Node") -> list[dict[str, Any]]:
    out = []
    for path in sorted(backups_dir(node).glob("*.bkp")):
        try:
            out.append({**read_header(path), "path": str(path)})
        except (ValueError, json.JSONDecodeError):
            continue
    return out


def _member_names(library_id: str) -> tuple[str, str]:
    return f"{library_id}.sdlibrary", f"{library_id}.db"


def validate_backup(path: str | Path,
                    expect_library_id: str | None = None) -> dict[str, Any]:
    """Full validation BEFORE any restore touches the live library: magic +
    header parse, ``library_id`` match, and a complete tar.gz walk (every
    member read end-to-end, which checks the gzip CRC — a truncated or
    bit-flipped backup fails here, not halfway through an extraction).
    Returns the parsed header; raises ``ValueError`` on any problem."""
    try:
        header = read_header(path)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        raise ValueError(f"backup {path}: unreadable header ({e})") from e
    library_id = header.get("library_id")
    if not library_id:
        raise ValueError(f"backup {path}: header missing library_id")
    if expect_library_id is not None and library_id != expect_library_id:
        raise ValueError(
            f"backup {path}: header library_id {library_id!r} does not match "
            f"the restore target {expect_library_id!r}")
    want = set(_member_names(library_id))
    try:
        with open(path, "rb") as fh:
            fh.seek(HEADER_LEN)
            body = io.BytesIO(fh.read())
        # full gzip drain FIRST: the stream CRC only verifies at EOF, and a
        # member-walk alone can skip trailing tar padding where a flipped
        # bit would otherwise hide
        with gzip.GzipFile(fileobj=body) as gz:
            while gz.read(1 << 20):
                pass
        body.seek(0)
        with tarfile.open(fileobj=body, mode="r:gz") as tar:
            seen = set()
            for member in tar:
                if member.name not in want:
                    continue  # forward-compat: extra members ignored
                seen.add(member.name)
                if not member.isreg():
                    raise ValueError(
                        f"backup {path}: member {member.name} is not a "
                        f"regular file")
    except ValueError:
        raise
    except (OSError, tarfile.TarError, EOFError, zlib.error) as e:
        raise ValueError(f"backup {path}: corrupt archive ({e})") from e
    missing = want - seen
    if missing:
        raise ValueError(f"backup {path}: missing member(s) {sorted(missing)}")
    return header


def find_latest_backup(backups_path: str | Path,
                       library_id: str) -> Path | None:
    """Newest VALID backup of ``library_id`` under ``backups_path`` (by
    header timestamp) — what the boot-repair ladder restores from.
    Invalid/foreign files are skipped, never raised on."""
    best: tuple[int, Path] | None = None
    for path in Path(backups_path).glob("*.bkp"):
        try:
            header = validate_backup(path, expect_library_id=library_id)
        except ValueError:
            continue
        ts = int(header.get("timestamp") or 0)
        if best is None or ts > best[0]:
            best = (ts, path)
    return best[1] if best else None


def do_backup(node: "Node", library_id: str) -> str:
    library = node.libraries.get(library_id)
    backup_id = str(uuid.uuid4())
    target = backups_dir(node) / f"{backup_id}.bkp"
    cfg_path = node.libraries.dir / f"{library_id}.sdlibrary"
    db_path = node.libraries.dir / f"{library_id}.db"
    # persist a statistics snapshot row into the backup (the reference's
    # update-on-query persistence moved here when libraries.statistics
    # became a pool-pure reader — the backup is the natural write-capable
    # moment for an as-of snapshot); best-effort, never blocks the backup
    try:
        from .statistics import update_statistics

        update_statistics(library)
    except Exception:
        logger.warning("statistics snapshot before backup failed",
                       exc_info=True)
    # fold the WAL into the main file so the tar'd .db is self-contained
    library.db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
    # chaos seam: enospc degrades gracefully (no torn .bkp thanks to the
    # atomic write), kill rehearses a mid-backup process death
    faults.inject("backup", key=library_id)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        tar.add(cfg_path, arcname=f"{library_id}.sdlibrary")
        tar.add(db_path, arcname=f"{library_id}.db")
    faults.inject("backup", key="write")
    try:
        atomic_write_bytes(
            target,
            _header(backup_id, library_id, library.name) + buf.getvalue())
    except OSError as e:
        from .recovery import is_disk_full, note_disk_full

        if is_disk_full(e):
            # the atomic write guarantees no torn .bkp survived; the
            # counter tells the operator WHY the backup is missing
            note_disk_full("backup")
        raise
    return backup_id


def extract_validated(backup_path: str | Path, library_id: str,
                      dest_dir: Path) -> tuple[Path, Path]:
    """Extract the config + DB members into ``dest_dir`` (the caller's temp
    dir, same filesystem as the live files so the final renames are
    atomic). Returns ``(cfg_tmp, db_tmp)``."""
    cfg_name, db_name = _member_names(library_id)
    with open(backup_path, "rb") as fh:
        fh.seek(HEADER_LEN)
        # buffered: extractall seeks backwards in the gzip stream, and a
        # gzip rewind over the raw file would land on the magic header
        buf = io.BytesIO(fh.read())
    with tarfile.open(fileobj=buf, mode="r:gz") as tar:
        members = [m for m in tar.getmembers()
                   if m.name in (cfg_name, db_name)]
        tar.extractall(dest_dir, members=members, filter="data")
    return dest_dir / cfg_name, dest_dir / db_name


def restore_files(backup_path: str | Path, library_id: str,
                  libraries_dir: Path, pre_validated: bool = False) -> None:
    """The crash-safe half of a restore: validated temp-dir extraction +
    atomic renames over the live files. Shared by :func:`do_restore` and
    the boot-repair ladder (recovery.py), which runs before any Library
    object exists. A kill anywhere before the renames leaves the old
    library untouched; the renames themselves are per-file atomic (DB
    first, then config — the pair comes from one snapshot either way).

    ``pre_validated`` skips the validation walk when the caller just ran
    :func:`validate_backup` on this path — a full gzip-CRC drain reads the
    whole archive, so a multi-GB restore should not pay it twice."""
    if not pre_validated:
        validate_backup(backup_path, expect_library_id=library_id)
    tmp_dir = libraries_dir / f"{library_id}{TMP_MARK}.restore"
    import shutil

    shutil.rmtree(tmp_dir, ignore_errors=True)  # stale prior attempt
    tmp_dir.mkdir(parents=True)
    try:
        cfg_tmp, db_tmp = extract_validated(backup_path, library_id, tmp_dir)
        # chaos seam: a kill here proves the originals survive a mid-restore
        # process death (everything so far touched only the temp dir)
        faults.inject("restore", key=library_id)
        # stale WAL/SHM sidecars of the OLD database must not be replayed
        # into the restored file
        (libraries_dir / f"{library_id}.db-wal").unlink(missing_ok=True)
        (libraries_dir / f"{library_id}.db-shm").unlink(missing_ok=True)
        import os

        os.replace(db_tmp, libraries_dir / f"{library_id}.db")
        os.replace(cfg_tmp, libraries_dir / f"{library_id}.sdlibrary")
    finally:
        shutil.rmtree(tmp_dir, ignore_errors=True)


def do_restore(node: "Node", backup_path: str | Path) -> str:
    header = validate_backup(backup_path)
    library_id = header["library_id"]
    # unload if loaded (restore semantics: backups.rs restore) — only after
    # validation passed, so a bad backup never takes the library down
    try:
        library = node.libraries.get(library_id)
        library.close()
        node.libraries._libraries.pop(library_id, None)
    except KeyError:
        pass
    restore_files(backup_path, library_id, node.libraries.dir,
                  pre_validated=True)
    node.libraries._load(library_id)
    # the DB FILE was swapped (os.replace): long-lived readers — the
    # serve-pool workers' read-only connections (ISSUE 11) — still hold
    # the old inode, so a watermark bump alone cannot help; this event
    # advances the library's reader EPOCH, forcing every worker to
    # close and reopen before serving another read
    node.emit("library.reload", {"source": "restore"},
              library_id=library_id)
    return library_id


def delete_backup(node: "Node", backup_id: str) -> None:
    path = backups_dir(node) / f"{backup_id}.bkp"
    if path.exists():
        path.unlink()
