"""MinHash near-duplicate detection — the TPU-native dedup engine.

The reference's dedup is exact-only: identical cas_ids collapse to one Object
(file_identifier/mod.rs:136-335). This op family adds *near*-duplicate
detection (BASELINE.json config 4) designed for the TPU:

- **Signatures ride the identify batch.** During file_identifier the sampled
  message rows are already resident on device for BLAKE3; the MinHash kernel
  reuses them: 8-byte shingles at 8-byte stride, K universal hash functions
  (odd-multiplier mix on the VPU), min-reduce over shingles. No extra
  host↔device traffic — the expensive transfer was already paid for cas_id.
- **All-pairs compare is blocked compute.** Similarity(i,j) = fraction of
  equal signature components. A lax.scan over row-blocks compares
  (block, N, K) at once — O(N²K) element ops that saturate the VPU while
  only N*K*4 bytes ever cross the wire. The CPU equivalent (numpy blocked
  compare, same algorithm) is the bench baseline.

Estimator: P[min-hash match] = Jaccard(shingle sets), so `threshold=0.8`
finds files sharing ≥~80% of sampled content shingles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_u32 = jnp.uint32
_u64 = jnp.uint64

#: signature width (hash count) — 64 keeps the estimator std ≈ 0.05
K = 64

#: deterministic odd multipliers + offsets for the K universal hashes
_rng = np.random.default_rng(0x5D)  # stable seed
_A = (_rng.integers(0, 1 << 32, K, dtype=np.uint64) | 1).astype(np.uint32)
_B = (_rng.integers(0, 1 << 32, K, dtype=np.uint64) | 1).astype(np.uint32)
_C = _rng.integers(0, 1 << 32, K, dtype=np.uint64).astype(np.uint32)


def _mix(x: jax.Array) -> jax.Array:
    """xorshift-multiply finalizer (murmur-style avalanche) on u32 lanes."""
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> np.uint32(15))
    x = x * np.uint32(0x846CA68B)
    return x ^ (x >> np.uint32(16))


@jax.jit
def minhash_rows(rows: jax.Array, lengths: jax.Array) -> jax.Array:
    """Signatures for B messages. ``rows``: (B, W) uint32 — the same row
    layout blake3_batch_rows consumes; ``lengths``: (B,) true byte lengths.
    Returns (B, K) uint32. Shingle = consecutive u32 pair (8 bytes)."""
    B, W = rows.shape
    lo = rows[:, 0::2]  # (B, W/2)
    hi = rows[:, 1::2]
    n_shingles = jnp.maximum(1, (lengths.astype(jnp.int32) // 8))  # (B,)
    idx = jnp.arange(W // 2, dtype=jnp.int32)[None, :]  # (1, W/2)
    valid = idx < n_shingles[:, None]  # (B, W/2)

    def one_hash(carry, params):
        a, b, c = params
        h = _mix(lo * a + hi * b + c)  # (B, W/2)
        h = jnp.where(valid, h, jnp.uint32(0xFFFFFFFF))
        return carry, jnp.min(h, axis=1)  # (B,)

    _, sigs = lax.scan(one_hash, None,
                       (jnp.asarray(_A), jnp.asarray(_B), jnp.asarray(_C)))
    return jnp.transpose(sigs)  # (B, K)


#: rows per compare block — (BLOCK, N, K) u32 intermediate stays < ~2GB HBM
BLOCK = 512


@functools.partial(jax.jit, static_argnames=("threshold_k",))
def similar_pairs_count(sigs: jax.Array, valid: jax.Array,
                        threshold_k: int) -> tuple[jax.Array, jax.Array]:
    """All-pairs signature compare.

    ``sigs``: (N, K) uint32 (N must be a multiple of BLOCK — pad with
    invalid lanes); ``valid``: (N,) bool. A pair (i < j) is "similar" when
    >= threshold_k of K components match. Returns (total pair count,
    per-row flag marking rows that have a similar earlier row — the
    near-dup analogue of the identify step's exact-dup flag)."""
    N = sigs.shape[0]
    row_idx = jnp.arange(N, dtype=jnp.int32)

    def block_body(carry, start):
        total, dup = carry
        blk = lax.dynamic_slice(sigs, (start, 0), (BLOCK, K))  # (BLOCK, K)
        bvalid = lax.dynamic_slice(valid, (start,), (BLOCK,))
        bidx = start + jnp.arange(BLOCK, dtype=jnp.int32)
        eq = (blk[:, None, :] == sigs[None, :, :]).sum(axis=2)  # (BLOCK, N)
        pairmask = (eq >= threshold_k) & bvalid[:, None] & valid[None, :]
        earlier = bidx[:, None] > row_idx[None, :]  # j < i
        hits = pairmask & earlier
        total = total + hits.sum()
        dup = lax.dynamic_update_slice(dup, jnp.any(hits, axis=1), (start,))
        return (total, dup), None

    starts = jnp.arange(0, N, BLOCK, dtype=jnp.int32)
    (total, dup), _ = lax.scan(
        block_body, (jnp.zeros((), jnp.int64)
                     if jax.config.jax_enable_x64 else jnp.zeros((), jnp.int32),
                     jnp.zeros((N,), bool)),
        starts)
    return total, dup


def similar_pairs_count_cpu(sigs: np.ndarray, valid: np.ndarray,
                            threshold_k: int) -> tuple[int, np.ndarray]:
    """Reference/baseline: same blocked algorithm in numpy."""
    N, k = sigs.shape
    total = 0
    dup = np.zeros(N, bool)
    row_idx = np.arange(N)
    for start in range(0, N, BLOCK):
        blk = sigs[start : start + BLOCK]
        eq = (blk[:, None, :] == sigs[None, :, :]).sum(axis=2)
        pairmask = (eq >= threshold_k) & valid[start : start + BLOCK, None] & valid[None, :]
        earlier = (start + np.arange(blk.shape[0]))[:, None] > row_idx[None, :]
        hits = pairmask & earlier
        total += int(hits.sum())
        dup[start : start + BLOCK] = hits.any(axis=1)
    return total, dup


def pad_for_blocks(sigs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Pad N up to a BLOCK multiple; padding lanes are invalid."""
    N = sigs.shape[0]
    Np = -(-N // BLOCK) * BLOCK
    valid = np.zeros(Np, bool)
    valid[:N] = True
    if Np != N:
        sigs = np.concatenate([sigs, np.zeros((Np - N, sigs.shape[1]),
                                              sigs.dtype)])
    return sigs, valid


# ---------------------------------------------------------------------------
# LSH banding — corpus-scale candidate generation (the all-pairs sweep is
# O(N²K); banding is O(N·BANDS) with exact verification only on candidates,
# the standard banded-MinHash construction the extreme-scale dedup
# literature builds on, e.g. LSHBloom, arxiv 2411.04257)
# ---------------------------------------------------------------------------

BANDS = 16
BAND_ROWS = K // BANDS  # 4

#: buckets larger than this pair members against ONE representative
#: instead of all-pairs (a bucket of thousands of identical signatures —
#: exactly the most-duplicated content — must stay detected without
#: re-quadratizing the pass); callers surface how many were collapsed
MAX_BUCKET = 256


def band_keys(sigs: np.ndarray) -> np.ndarray:
    """(N, BANDS) uint64 bucket keys: FNV-style fold of each band's rows,
    salted per band. Two rows sharing ≥ one band key are candidates.
    With s = true similarity, P[candidate] = 1 - (1 - s^BAND_ROWS)^BANDS:
    ≈ 0.9998 at s=0.8, ≈ 0.12 at s=0.3 — high-recall at the 0.8 default
    threshold, false positives removed by exact verification."""
    n = sigs.shape[0]
    bands = sigs.reshape(n, BANDS, BAND_ROWS).astype(np.uint64)
    with np.errstate(over="ignore"):
        key = np.full((n, BANDS), 0xCBF29CE484222325, np.uint64)
        for r in range(BAND_ROWS):
            key ^= bands[:, :, r]
            key *= np.uint64(0x100000001B3)
        key ^= np.arange(BANDS, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    return key


def banded_candidate_pairs(keys: np.ndarray,
                           valid: np.ndarray) -> tuple[np.ndarray, int]:
    """Candidate pairs (ndarray (P, 2), i < j, unique) from shared band
    buckets; returns (pairs, oversized_bucket_count). Oversized buckets
    collapse to representative pairing — (first member, each other member)
    — keeping candidate generation linear while every member stays
    reachable (the later union-find re-joins the clique through the
    representative).

    Fully vectorized (BASELINE config 4 runs this over 1M objects): per
    band, a sort groups equal keys into runs; runs batch BY LENGTH so each
    batch emits its within-run pairs with one triu-indexed gather; the
    cross-band union dedups through one np.unique over packed (i<<32)|j
    codes. A Python dict/set version of the same construction tops out
    around 20k objects/s — this one sustains millions."""
    valid = np.asarray(valid, bool)
    if valid.shape[0] != keys.shape[0]:
        raise ValueError(f"valid mask has {valid.shape[0]} entries for "
                         f"{keys.shape[0]} signatures")
    idx_valid = np.flatnonzero(valid)
    chunks: list[np.ndarray] = []
    oversized = 0
    for b in range(BANDS):
        k = keys[idx_valid, b]
        order = np.argsort(k, kind="stable")
        ks = k[order]
        ids = idx_valid[order]
        if ks.size == 0:
            continue
        run_start = np.flatnonzero(np.r_[True, ks[1:] != ks[:-1]])
        run_len = np.diff(np.r_[run_start, ks.size])
        for length in np.unique(run_len):
            if length < 2:
                continue
            starts = run_start[run_len == length]
            members = ids[starts[:, None] + np.arange(length)]
            if length > MAX_BUCKET:
                oversized += len(starts)
                a = np.repeat(members[:, 0], length - 1)
                c = members[:, 1:].ravel()
            else:
                iu, ju = np.triu_indices(int(length), 1)
                a = members[:, iu].ravel()
                c = members[:, ju].ravel()
            lo = np.minimum(a, c).astype(np.uint64)
            hi = np.maximum(a, c).astype(np.uint64)
            chunks.append((lo << np.uint64(32)) | hi)
    if not chunks:
        return np.empty((0, 2), np.int64), oversized
    packed = np.unique(np.concatenate(chunks))
    pairs = np.empty((packed.size, 2), np.int64)
    pairs[:, 0] = (packed >> np.uint64(32)).astype(np.int64)
    pairs[:, 1] = (packed & np.uint64(0xFFFFFFFF)).astype(np.int64)
    return pairs, oversized


def verify_pairs(sigs: np.ndarray, pairs, threshold_k: int) -> list:
    """Exact signature compare over candidate pairs (vectorized);
    returns [(i, j, matching_components)] for pairs clearing threshold.
    ``pairs``: the (P, 2) array banded_candidate_pairs emits (a set of
    tuples still works)."""
    if isinstance(pairs, np.ndarray):
        arr = pairs
    else:
        if not pairs:
            return []
        arr = np.asarray(sorted(pairs), np.int64)
    if arr.size == 0:
        return []
    out = []
    for start in range(0, len(arr), 65536):
        chunk = arr[start:start + 65536]
        eq = (sigs[chunk[:, 0]] == sigs[chunk[:, 1]]).sum(axis=1)
        keep = eq >= threshold_k
        for (i, j), m in zip(chunk[keep], eq[keep]):
            out.append((int(i), int(j), int(m)))
    return out
