"""Batched Gear content-defined chunking — the rolling-hash half of the
identifier hot path (the BASELINE north star names "rolling-hash + BLAKE3
kernels"; blake3_jax.py shipped the second half, this module ships the first).

Gear CDC (arxiv 2508.05797, 2505.21194) slides a 32-byte window over the
file: ``h_i = ((h_{i-1} << 1) + G[b_i]) mod 2^32`` with a random 256-entry
``G`` table, cutting where ``h & mask == 0``. The left-shift expires every
byte after 32 steps, so the recurrence *is* a windowed sum::

    h_i = sum_{k=0..31} G[b_{i-k}] << k   (mod 2^32)

— position-independent and therefore lane-parallel: no carried state, just
32 shifted adds over a ``(batch, length)`` u32 plane. That is the whole
vectorization story, and it is exactly the shape the repo already routes to
the device for BLAKE3. (Classic serial Gear resets ``h`` at each cut; the
windowed form is the non-resetting variant — still content-defined and
shift-resistant, and the per-byte pure-Python oracle below matches it
exactly, so every rung agrees byte-for-byte.)

Three rungs, selected per call (or ``SD_CDC_KERNEL=numpy|xla|pallas``):

- ``numpy``: the vectorized native-CPU rung (the BackendRouter's "cpu"
  engine) — 32 in-place shifted adds with natural uint32 wraparound;
- ``xla``: the same plane algebra jit-compiled (the router's "device"
  engine on a real accelerator);
- ``pallas``: a hand-tiled kernel — the gear-mapped u32 plane is cut into
  128-column output tiles each carrying a 128-column left halo (built by an
  XLA gather *outside* the kernel: a 256-way data-dependent byte lookup has
  no efficient VPU lowering, so the table lookup stays in XLA and the
  kernel does the pure shift/add/mask arithmetic — a deliberate deviation
  from "table in SMEM"), grid over ``(row tiles, column tiles)``,
  ``(8, 128)``-aligned VMEM blocks, the boundary mask as an SMEM scalar.
  Interpret mode on CPU (blake3_pallas.interpret_mode).

All three rungs emit the identical *candidate bitmap*; one shared host-side
resolver then applies the min/max clamps with a forward scan over candidate
cut positions — so cross-rung byte-identity of the final boundaries holds by
construction, and the tests prove the bitmaps too.

Per-chunk ids reuse blake3_jax.blake3_batch_hex (chunks from every file in a
batch flatten into one device call; max chunk 64 KiB = 64 BLAKE3 chunks).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .blake3_jax import blake3_batch_hex
from .blake3_pallas import interpret_mode

_u32 = jnp.uint32

logger = logging.getLogger(__name__)

#: the three chunking rungs (module docstring)
KERNELS = ("numpy", "xla", "pallas")

#: rolling window width implied by the u32 left-shift recurrence
WINDOW = 32

#: truncated per-chunk BLAKE3 id length (hex chars; 128 bits — chunk ids key
#: cross-file dedup and delta reassembly, so they carry twice the cas_id's 64)
CHUNK_ID_HEX = 32


def resolve_kernel(kernel: str | None = None) -> str:
    """Explicit argument wins; else ``SD_CDC_KERNEL``; else ``xla``.
    Resolved per call (never memoized) so subprocess tests stay hermetic."""
    if kernel is None:
        kernel = os.environ.get("SD_CDC_KERNEL", "").strip().lower() or "xla"
    if kernel not in KERNELS:
        logger.warning("unknown SD_CDC_KERNEL=%r; using xla", kernel)
        kernel = "xla"
    return kernel


def _gear_table() -> np.ndarray:
    """The 256-entry u32 gear table, derived entry-by-entry from SHA-256 of a
    versioned label — deterministic across platforms and library versions
    (an RNG stream would tie chunk ids to a numpy version)."""
    out = np.empty(256, np.uint32)
    for i in range(256):
        d = hashlib.sha256(b"sd-cdc-gear-v1:%d" % i).digest()
        out[i] = int.from_bytes(d[:4], "little")
    return out


GEAR = _gear_table()


@dataclasses.dataclass(frozen=True)
class ChunkParams:
    """Clamp geometry. ``avg_size`` must be a power of two (it becomes the
    boundary mask); a cut candidate at position ``c`` (exclusive end offset)
    is accepted only when ``cur + min_size <= c <= min(cur + max_size, n)``,
    else the chunk is force-cut at that upper bound."""

    min_size: int = 2048
    avg_size: int = 8192
    max_size: int = 65536

    def __post_init__(self) -> None:
        if self.avg_size & (self.avg_size - 1):
            raise ValueError("avg_size must be a power of two")
        if not (0 < self.min_size <= self.avg_size <= self.max_size):
            raise ValueError("need 0 < min <= avg <= max")

    @property
    def mask(self) -> int:
        return self.avg_size - 1


DEFAULT_PARAMS = ChunkParams()


# --------------------------------------------------------------------------
# pure-Python oracle (rung 0 — per-byte recurrence, tests/bench only)
# --------------------------------------------------------------------------


def chunk_boundaries_ref(data: bytes, params: ChunkParams = DEFAULT_PARAMS) -> list[int]:
    """Cut positions (exclusive end offsets) for one file, one byte at a
    time. The single source of truth the batched rungs are proven against."""
    n = len(data)
    mask = params.mask
    h = 0
    candidates = []
    for i in range(n):
        h = ((h << 1) + int(GEAR[data[i]])) & 0xFFFFFFFF
        if (h & mask) == 0:
            candidates.append(i + 1)
    return resolve_cuts(candidates, n, params)


def chunk_ref(data: bytes, params: ChunkParams = DEFAULT_PARAMS) -> list[tuple[int, int]]:
    """Oracle chunking as ``(offset, length)`` pairs."""
    return cuts_to_chunks(chunk_boundaries_ref(data, params))


# --------------------------------------------------------------------------
# shared clamp resolver (every rung funnels its candidate bitmap here)
# --------------------------------------------------------------------------


def resolve_cuts(candidates: "list[int] | np.ndarray", n: int,
                 params: ChunkParams = DEFAULT_PARAMS) -> list[int]:
    """Apply min/max clamps to ascending candidate positions: a forward scan
    that jumps to the first candidate inside the current chunk's admissible
    window, force-cutting at ``min(cur + max_size, n)`` when none lands.
    An empty file yields no chunks."""
    cuts: list[int] = []
    cur = 0
    ci = 0
    m = len(candidates)
    while cur < n:
        lo = cur + params.min_size
        hi = min(cur + params.max_size, n)
        cut = hi
        while ci < m and candidates[ci] <= hi:
            c = int(candidates[ci])
            ci += 1
            if c >= lo:
                cut = c
                break
        cuts.append(cut)
        cur = cut
    return cuts


def cuts_to_chunks(cuts: list[int]) -> list[tuple[int, int]]:
    out: list[tuple[int, int]] = []
    prev = 0
    for c in cuts:
        out.append((prev, c - prev))
        prev = c
    return out


# --------------------------------------------------------------------------
# rung 1: vectorized numpy (native-CPU router engine)
# --------------------------------------------------------------------------


def _candidates_numpy(buf: np.ndarray, lengths: np.ndarray,
                      mask: int) -> np.ndarray:
    """(B, L) u8 plane → (B, L) bool candidate bitmap (bit i ⇒ cut at i+1).
    32 in-place shifted adds; uint32 wraparound is the mod-2^32."""
    B, L = buf.shape
    g = GEAR[buf]  # (B, L) u32 table lookup
    h = np.zeros((B, L), np.uint32)
    for k in range(min(WINDOW, L)):
        h[:, k:] += g[:, : L - k] << np.uint32(k)
    cand = (h & np.uint32(mask)) == 0
    cand &= np.arange(L)[None, :] < lengths[:, None]
    return cand


# --------------------------------------------------------------------------
# rung 2: the same plane algebra, jit-compiled
# --------------------------------------------------------------------------


@jax.jit
def _candidates_xla(g: jax.Array, lengths: jax.Array,
                    mask: jax.Array) -> jax.Array:
    L = g.shape[1]
    h = jnp.zeros_like(g)
    for k in range(min(WINDOW, L)):
        h = h + (jnp.pad(g, ((0, 0), (k, 0)))[:, :L] << _u32(k))
    cand = (h & mask) == 0
    return cand & (jnp.arange(L)[None, :] < lengths[:, None])


# --------------------------------------------------------------------------
# rung 3: hand-tiled Pallas kernel
# --------------------------------------------------------------------------

#: sublane rows per grid step — the VPU's native u32 tile is (8, 128)
TILE_ROWS = 8
#: output columns per grid step; each input tile carries a full extra
#: 128-column left halo (only the last WINDOW-1 columns are read) so both
#: tile axes stay 128-aligned
TILE_COLS = 128


def _cdc_kernel(g_ref, mask_ref, out_ref):
    """One (TILE_ROWS, TILE_COLS) tile of boundary candidates. ``g_ref`` is
    the haloed gear plane block (TILE_ROWS, 1, 2*TILE_COLS): local column
    ``TILE_COLS + j`` is global position ``t*TILE_COLS + j``, so the k-th
    window term for all 128 outputs is one static slice — 32 shifted adds,
    all live values in vector registers, then the SMEM mask compare."""
    g = g_ref[:, 0, :]
    h = jnp.zeros((TILE_ROWS, TILE_COLS), _u32)
    for k in range(WINDOW):
        h = h + (g[:, TILE_COLS - k : 2 * TILE_COLS - k] << _u32(k))
    out_ref[:, 0, :] = jnp.where((h & mask_ref[0]) == 0, _u32(1), _u32(0))


@jax.jit
def _candidates_pallas(g: jax.Array, lengths: jax.Array,
                       mask: jax.Array) -> jax.Array:
    B, L = g.shape  # B % TILE_ROWS == 0, L % TILE_COLS == 0 (caller pads)
    nt = L // TILE_COLS
    # materialize haloed tiles with one pad + gather-free slicing: tile t
    # covers global columns [t*128 - 128, t*128 + 128)
    gh = jnp.pad(g, ((0, 0), (TILE_COLS, 0)))
    tiles = jnp.stack(
        [gh[:, t * TILE_COLS : (t + 2) * TILE_COLS] for t in range(nt)], axis=1
    )  # (B, nt, 256)
    out = pl.pallas_call(
        _cdc_kernel,
        grid=(B // TILE_ROWS, nt),
        in_specs=[
            pl.BlockSpec((TILE_ROWS, 1, 2 * TILE_COLS),
                         lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((TILE_ROWS, 1, TILE_COLS),
                               lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, nt, TILE_COLS), _u32),
        interpret=interpret_mode(),
    )(tiles, jnp.asarray([mask], _u32).reshape(1))
    cand = out.reshape(B, L) != 0
    return cand & (jnp.arange(L)[None, :] < lengths[:, None])


# --------------------------------------------------------------------------
# batched entry point
# --------------------------------------------------------------------------

#: length tiers (padded plane width) so XLA compiles a handful of shapes
_LEN_TIER_MIN = 256
#: batch-size tiers (padded lane count)
_BATCH_TIERS = (8, 32, 128, 512)
#: per-call padded-cell ceiling (u32 plane cells ≈ 4 bytes each); groups
#: larger than this split into multiple device calls
_CELL_BUDGET = 1 << 23


def _len_tier(n: int) -> int:
    return max(_LEN_TIER_MIN, 1 << max(0, (n - 1)).bit_length())


def _batch_tier(b: int) -> int:
    for t in _BATCH_TIERS:
        if t >= b:
            return t
    return -(-b // _BATCH_TIERS[-1]) * _BATCH_TIERS[-1]


def candidate_bitmaps(datas: list[bytes], params: ChunkParams,
                      kernel: str) -> list[np.ndarray]:
    """Per-file boolean candidate bitmaps (bit i ⇒ cut at i+1) from the
    resolved rung, identical across rungs. Caller applies resolve_cuts."""
    Lp = _len_tier(max((len(d) for d in datas), default=1) or 1)
    Bp = _batch_tier(len(datas))
    plane = np.zeros((Bp, Lp), np.uint8)
    lengths = np.zeros(Bp, np.int32)
    for i, d in enumerate(datas):
        plane[i, : len(d)] = np.frombuffer(d, np.uint8)
        lengths[i] = len(d)
    if kernel == "numpy":
        cand = _candidates_numpy(plane, lengths, params.mask)
    else:
        g = jnp.take(jnp.asarray(GEAR), jnp.asarray(plane).astype(jnp.int32),
                     axis=0)
        fn = _candidates_pallas if kernel == "pallas" else _candidates_xla
        cand = np.asarray(fn(g, jnp.asarray(lengths),
                             jnp.asarray(params.mask, jnp.uint32)))
    return [cand[i, : len(d)] for i, d in enumerate(datas)]


def chunk_batch(datas: list[bytes], params: ChunkParams = DEFAULT_PARAMS,
                kernel: str | None = None) -> list[list[tuple[int, int]]]:
    """Chunk B files at once: per-file ``(offset, length)`` lists, in input
    order. Files group by padded-length tier under a cell budget so one
    pathological batch can't demand an unbounded plane."""
    k = resolve_kernel(kernel)
    results: list[list[tuple[int, int]] | None] = [None] * len(datas)
    groups: dict[int, list[int]] = {}
    for i, d in enumerate(datas):
        groups.setdefault(_len_tier(len(d)), []).append(i)
    for tier, idxs in sorted(groups.items()):
        per_call = max(1, _CELL_BUDGET // tier)
        for s in range(0, len(idxs), per_call):
            part = idxs[s : s + per_call]
            bitmaps = candidate_bitmaps([datas[i] for i in part], params, k)
            for i, bm in zip(part, bitmaps):
                cuts = resolve_cuts(np.flatnonzero(bm) + 1, len(datas[i]), params)
                results[i] = cuts_to_chunks(cuts)
    return results  # type: ignore[return-value]


# --------------------------------------------------------------------------
# per-chunk BLAKE3 ids (reuses the PR 2 kernel)
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def _b3_max_chunks(max_size: int) -> int:
    return max(1, -(-max_size // 1024))


def chunk_ids(datas: list[bytes], chunk_lists: list[list[tuple[int, int]]],
              params: ChunkParams = DEFAULT_PARAMS,
              kernel: str | None = None) -> list[list[str]]:
    """Per-file ordered chunk-id lists: every chunk of every file flattens
    into one blake3_batch_hex call (ids truncated to CHUNK_ID_HEX chars).
    ``kernel`` here picks the BLAKE3 compression rung (pallas for the CDC
    pallas rung, else the blake3 default) — chunk *boundaries* came from
    chunk_batch."""
    msgs: list[bytes] = []
    spans: list[int] = []
    for data, chunks in zip(datas, chunk_lists):
        spans.append(len(chunks))
        for off, ln in chunks:
            msgs.append(data[off : off + ln])
    b3_kernel = "pallas" if kernel == "pallas" else None
    hexes = blake3_batch_hex(msgs, max_chunks=_b3_max_chunks(params.max_size),
                             kernel=b3_kernel)
    out: list[list[str]] = []
    pos = 0
    for n in spans:
        out.append([h[:CHUNK_ID_HEX] for h in hexes[pos : pos + n]])
        pos += n
    return out


def build_manifest(data: bytes, params: ChunkParams = DEFAULT_PARAMS,
                   kernel: str | None = None) -> list[tuple[str, int]]:
    """One file → ordered ``(chunk_id, length)`` pairs — the manifest row
    payload, and what the delta sender/receiver compute locally."""
    chunks = chunk_batch([data], params, kernel)[0]
    ids = chunk_ids([data], [chunks], params, kernel)[0]
    return [(cid, ln) for cid, (_, ln) in zip(ids, chunks)]
