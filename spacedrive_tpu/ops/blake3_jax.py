"""Batched BLAKE3 for TPU — the cas_id hot-path kernel.

Byte-identical to the pure-Python oracle (objects/blake3_ref.py) and therefore
to the reference's `blake3` crate output (core/src/object/cas.rs). Designed for
XLA/TPU rather than translated from any CPU implementation:

- **Chunk-parallel phase 1.** BLAKE3's serial dependency is only *within* a
  1024-byte chunk (16 chained block compressions); chunks are independent
  leaves of the merkle tree. So the kernel treats ``chunks x batch`` as one
  giant lane grid and runs a single 16-step ``lax.scan`` over block position —
  every step advances every chunk of every message at once on the VPU's 8x128
  lanes. A batch of 4096 sampled files is 57x4096 ≈ 233k parallel lanes.
- **Log-depth merkle phase 2.** The chunk-stack of streaming implementations
  is a CPU artifact. Level-wise adjacent pairing (odd tail promoted unchanged)
  yields exactly BLAKE3's left-heavy tree, so the merge is ceil(log2(C))
  vectorized parent compressions, each over all pairs of all lanes at once.
  Per-lane root detection (`nodes_left == 2`) applies the ROOT flag.
- **Static shapes.** Messages are zero-padded into fixed chunk capacities
  (57 for the fixed 57,352-byte sampled path, small-file buckets otherwise);
  per-lane byte lengths drive block-count/flag masks computed on device.

Everything is uint32 add/xor/rotate — pure VPU work; the rounds/permutation
schedule is unrolled (static), only the lanes are data.

Two interchangeable compression kernels sit under this orchestration,
selected per call (or via ``SD_BLAKE3_KERNEL=pallas|xla``, default xla):

- ``xla``: the graph-compiled :func:`compress` below (rounds as a 7-step
  ``lax.scan`` — small HLO, XLA schedules everything);
- ``pallas``: the hand-tiled register-resident kernel in blake3_pallas.py
  (8×128 u32 lane tiles, rounds unrolled, permutation baked into the
  schedule). Byte-identical outputs — tests prove both against the
  objects/blake3_ref.py oracle, in Pallas interpret mode on CPU.

Multi-device: shard the batch axis with ``jax.sharding``; see parallel/mesh.py.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# spec constants: the oracle is the single source of truth
from ..objects.blake3_ref import (  # noqa: E402
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    IV,
    MSG_PERMUTATION,
    PARENT,
    ROOT,
)

BLOCKS_PER_CHUNK = CHUNK_LEN // BLOCK_LEN

_u32 = jnp.uint32

logger = logging.getLogger(__name__)

#: the two compression kernels behind the orchestration (module docstring)
KERNELS = ("xla", "pallas")


def resolve_kernel(kernel: str | None = None) -> str:
    """Explicit argument wins; else ``SD_BLAKE3_KERNEL``; else ``xla``.
    Resolved per call (never memoized) so subprocess tests stay hermetic —
    each jit cache entry is keyed by the resolved name."""
    if kernel is None:
        kernel = os.environ.get("SD_BLAKE3_KERNEL", "").strip().lower() or "xla"
    if kernel not in KERNELS:
        logger.warning("unknown SD_BLAKE3_KERNEL=%r; using xla", kernel)
        kernel = "xla"
    return kernel


def _compress_fn(kernel: str):
    """The compression primitive for a resolved kernel name. The pallas
    module imports lazily so xla-only processes never touch it."""
    if kernel == "pallas":
        from .blake3_pallas import compress_pallas

        return compress_pallas
    return compress


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _g(s, a, b, c, d, mx, my):
    s[a] = s[a] + s[b] + mx
    s[d] = _rotr(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotr(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b] + my
    s[d] = _rotr(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotr(s[b] ^ s[c], 7)


_PERM = np.array(MSG_PERMUTATION)


def compress(cv, m, counter, block_len, flags):
    """One BLAKE3 compression, broadcast over any lane shape.

    ``cv``: list of 8 arrays; ``m``: list of 16 arrays or a stacked
    ``(16, ...)`` array; ``counter``/``block_len``/``flags``: arrays
    broadcastable to the lane shape (counter high word is 0 — the cas domain
    never exceeds 2^32 chunks). Returns the first 8 output words (chaining
    value / digest head).

    The 7 rounds run as a ``lax.scan`` with the message permutation as a
    static gather — NOT unrolled: rounds are serial anyway so unrolling buys
    no parallelism, and a ~450-op unrolled body sent XLA:CPU's
    post-layout simplification fixed-point into multi-minute compiles.
    """
    zero = jnp.zeros(jnp.broadcast_shapes(cv[0].shape, block_len.shape, flags.shape), _u32)
    s0 = (
        cv[0] + zero, cv[1] + zero, cv[2] + zero, cv[3] + zero,
        cv[4] + zero, cv[5] + zero, cv[6] + zero, cv[7] + zero,
        zero + _u32(IV[0]), zero + _u32(IV[1]), zero + _u32(IV[2]), zero + _u32(IV[3]),
        counter.astype(_u32) + zero, zero,
        block_len.astype(_u32) + zero, flags.astype(_u32) + zero,
    )
    if isinstance(m, (list, tuple)):
        m = jnp.stack([mw + jnp.zeros_like(zero) for mw in m])
    else:
        m = m + jnp.zeros_like(zero)[None]

    def round_body(carry, _):
        s, m = carry
        s = list(s)
        _g(s, 0, 4, 8, 12, m[0], m[1])
        _g(s, 1, 5, 9, 13, m[2], m[3])
        _g(s, 2, 6, 10, 14, m[4], m[5])
        _g(s, 3, 7, 11, 15, m[6], m[7])
        _g(s, 0, 5, 10, 15, m[8], m[9])
        _g(s, 1, 6, 11, 12, m[10], m[11])
        _g(s, 2, 7, 8, 13, m[12], m[13])
        _g(s, 3, 4, 9, 14, m[14], m[15])
        # permuting after the final round too is harmless: m is discarded
        return (tuple(s), m[_PERM]), None

    (s, _), _ = lax.scan(round_body, (s0, m), None, length=7)
    return [s[i] ^ s[i + 8] for i in range(8)]


def _iv_lanes(shape) -> list[jax.Array]:
    return [jnp.full(shape, w, _u32) for w in IV]


def blake3_batch(words: jax.Array, lengths: jax.Array,
                 kernel: str | None = None) -> jax.Array:
    """Hash B zero-padded messages.

    ``words``: (16 blocks, 16 words, C chunks, B) uint32, little-endian packed
    (see :func:`pack_messages`); ``lengths``: (B,) int32 true byte lengths,
    each <= C*1024. Returns (8, B) digest words — 32 bytes LE per lane.
    ``kernel`` picks the compression primitive (:func:`resolve_kernel`).
    """
    return _blake3_batch_impl(words, lengths, kernel=resolve_kernel(kernel))


@functools.partial(jax.jit, static_argnames=("kernel",))
def _blake3_batch_impl(words: jax.Array, lengths: jax.Array, *,
                       kernel: str = "xla") -> jax.Array:
    compress_k = _compress_fn(kernel)
    _, _, C, B = words.shape
    lengths = lengths.astype(jnp.int32)
    n_chunks = jnp.maximum(1, (lengths + (CHUNK_LEN - 1)) // CHUNK_LEN)  # (B,)

    chunk_idx = jnp.arange(C, dtype=jnp.int32)[:, None]  # (C, 1)
    chunk_len = jnp.clip(lengths[None, :] - chunk_idx * CHUNK_LEN, 0, CHUNK_LEN)  # (C, B)
    n_blocks = jnp.maximum(1, (chunk_len + (BLOCK_LEN - 1)) // BLOCK_LEN)  # (C, B)

    # ---- phase 1: all chunk CVs via one 16-step block scan over (C, B) lanes
    def block_body(cv, xs):
        j, m = xs  # j scalar, m (16, C, B)
        block_len = jnp.clip(chunk_len - j * BLOCK_LEN, 0, BLOCK_LEN).astype(_u32)
        flags = (
            jnp.where(j == 0, _u32(CHUNK_START), _u32(0))
            | jnp.where(j == n_blocks - 1, _u32(CHUNK_END), _u32(0))
        )
        out = compress_k(cv, [m[w] for w in range(16)],
                         jnp.broadcast_to(chunk_idx, (C, B)), block_len, flags)
        keep = j < n_blocks  # (C, B)
        return [jnp.where(keep, out[w], cv[w]) for w in range(8)], None

    cvs, _ = lax.scan(block_body, _iv_lanes((C, B)), (jnp.arange(BLOCKS_PER_CHUNK), words))

    # ---- single-chunk lanes: rerun chunk 0 with ROOT on each lane's final block
    single_root = _single_chunk_root(words[:, :, 0, :], lengths, kernel)  # (8, B)

    # ---- phase 2: log-depth merkle merge (adjacent pairing == BLAKE3 tree).
    # One fixed-shape lax.scan over levels — NOT an unrolled width-shrinking
    # loop, which would instantiate a distinct ~450-op compress per level and
    # blow up XLA compile time. Active nodes stay packed in the array prefix;
    # lanes whose remaining count runs out promote their left node (the odd
    # tail of BLAKE3's left-heavy tree); slots past the prefix carry garbage
    # that the masks never read.
    if C > 1:
        Cp = 1 << (C - 1).bit_length()  # pad chunk axis to a power of two
        nodes = jnp.stack([
            jnp.pad(cv, ((0, Cp - C), (0, 0))) if Cp != C else cv for cv in cvs
        ])  # (8, Cp, B)
        half = Cp // 2
        pair_idx = jnp.arange(half, dtype=jnp.int32)[:, None]  # (half, 1)
        zero = jnp.zeros((half, B), _u32)

        def level(carry, _):
            nodes, remaining, root8 = carry
            left = nodes[:, 0 : 2 * half : 2]  # (8, half, B)
            right = nodes[:, 1 : 2 * half : 2]
            has_right = (2 * pair_idx + 1) < remaining[None, :]  # (half, B)
            is_root_pair = (pair_idx == 0) & (remaining[None, :] == 2)
            flags = jnp.where(is_root_pair, _u32(PARENT | ROOT), _u32(PARENT))
            parent = compress_k(
                _iv_lanes((half, B)),
                [left[w] for w in range(8)] + [right[w] for w in range(8)],
                zero, zero + _u32(BLOCK_LEN), flags,
            )
            merged = jnp.stack(
                [jnp.where(has_right, parent[w], left[w, :, :]) for w in range(8)]
            )
            root8 = jnp.stack(
                [jnp.where(is_root_pair[0], parent[w][0], root8[w]) for w in range(8)]
            )
            nodes = jnp.concatenate(
                [merged, jnp.zeros((8, Cp - half, B), _u32)], axis=1
            )
            return (nodes, (remaining + 1) // 2, root8), None

        carry0 = (nodes, n_chunks, jnp.zeros((8, B), _u32))
        (_, _, root8), _ = lax.scan(level, carry0, None, length=Cp.bit_length() - 1)
        digest = [jnp.where(n_chunks == 1, single_root[w], root8[w]) for w in range(8)]
    else:
        digest = single_root
    return jnp.stack(digest)


def _single_chunk_root(words0: jax.Array, lengths: jax.Array,
                       kernel: str = "xla") -> list[jax.Array]:
    """Digest for lanes whose whole message fits one chunk. ``words0``:
    (16, 16, B). One compression per block: non-final blocks chain the CV,
    each lane's final block takes CHUNK_END|ROOT and emits the digest."""
    compress_k = _compress_fn(kernel)
    B = words0.shape[-1]
    chunk_len = jnp.clip(lengths, 0, CHUNK_LEN)
    n_blocks = jnp.maximum(1, (chunk_len + (BLOCK_LEN - 1)) // BLOCK_LEN)  # (B,)
    zero = jnp.zeros((B,), _u32)

    def body(carry, xs):
        cv, digest = carry
        j, m = xs
        is_final = j == n_blocks - 1
        block_len = jnp.clip(chunk_len - j * BLOCK_LEN, 0, BLOCK_LEN).astype(_u32)
        flags = jnp.where(j == 0, _u32(CHUNK_START), _u32(0)) | jnp.where(
            is_final, _u32(CHUNK_END | ROOT), _u32(0)
        )
        out = compress_k(cv, [m[w] for w in range(16)], zero, block_len, flags)
        # chain only through non-final blocks (a non-final block is always full)
        new_cv = [jnp.where(j < n_blocks - 1, out[w], cv[w]) for w in range(8)]
        new_digest = [jnp.where(is_final, out[w], digest[w]) for w in range(8)]
        return (new_cv, new_digest), None

    carry0 = (_iv_lanes((B,)), [zero] * 8)
    (_, digest), _ = lax.scan(body, carry0, (jnp.arange(BLOCKS_PER_CHUNK), words0))
    return digest


def blake3_batch_rows(rows: jax.Array, lengths: jax.Array,
                      kernel: str | None = None,
                      donate: bool = False) -> jax.Array:
    """Row-major entry: ``rows`` is (B, C*256) uint32 — each row one message
    in natural byte order (the layout the native gather writes). The
    (block, word, chunk, batch) permutation the scan wants happens ON DEVICE,
    where a 120MB transpose is ~free, instead of in a host numpy transpose
    that used to dominate the pipeline profile.

    ``donate=True`` (the hasher pipeline's fused path) DONATES the row
    buffer on a real accelerator: each staged sub-batch is ``device_put``
    once and never touched again, so XLA may reuse the (tier × 57KiB)
    transfer buffer as scratch instead of allocating per batch —
    double-buffered H2D without doubling resident memory. Donation is
    OPT-IN because it invalidates the caller's array: callers that reuse a
    device-resident input across calls (the device micro-bench's repeat
    loop) must keep the default. The CPU backend doesn't implement
    donation (and would warn), so it always takes the plain entry point.
    """
    k = resolve_kernel(kernel)
    if donate and _backend_supports_donation():
        return _blake3_batch_rows_donated(rows, lengths, kernel=k)
    return _blake3_batch_rows_impl(rows, lengths, kernel=k)


@functools.lru_cache(maxsize=1)
def _backend_supports_donation() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _rows_hash_body(rows: jax.Array, lengths: jax.Array,
                    kernel: str) -> jax.Array:
    B, W = rows.shape
    C = W // (BLOCKS_PER_CHUNK * 16)
    words = rows.reshape(B, C, BLOCKS_PER_CHUNK, 16).transpose(2, 3, 1, 0)
    return _blake3_batch_impl(words, lengths, kernel=kernel)


@functools.partial(jax.jit, static_argnames=("kernel",))
def _blake3_batch_rows_impl(rows: jax.Array, lengths: jax.Array, *,
                            kernel: str = "xla") -> jax.Array:
    return _rows_hash_body(rows, lengths, kernel)


@functools.partial(jax.jit, static_argnames=("kernel",), donate_argnums=(0,))
def _blake3_batch_rows_donated(rows: jax.Array, lengths: jax.Array, *,
                               kernel: str = "xla") -> jax.Array:
    return _rows_hash_body(rows, lengths, kernel)


# --------------------------------------------------------------------------
# host packing
# --------------------------------------------------------------------------


def pack_messages(messages: list[bytes], max_chunks: int) -> tuple[np.ndarray, np.ndarray]:
    """Zero-pad B messages into the (16, 16, max_chunks, B) batch-minor layout
    plus (B,) int32 lengths."""
    B = len(messages)
    cap = max_chunks * CHUNK_LEN
    buf = np.zeros((B, cap), np.uint8)
    lengths = np.empty(B, np.int32)
    for i, msg in enumerate(messages):
        n = len(msg)
        if n > cap:
            raise ValueError(f"message {i} ({n}B) exceeds capacity {cap}B")
        buf[i, :n] = np.frombuffer(msg, np.uint8)
        lengths[i] = n
    words = buf.view("<u4").reshape(B, max_chunks, BLOCKS_PER_CHUNK, 16)
    # (B, C, blocks, words) -> (blocks, words, C, B)
    return np.ascontiguousarray(words.transpose(2, 3, 1, 0)), lengths


def digests_to_hex(digest_words: np.ndarray) -> list[str]:
    """(8, B) uint32 → per-lane 64-char hex digests (cas_id takes [:16])."""
    words = np.asarray(digest_words).astype("<u4")
    b = np.ascontiguousarray(words.T).tobytes()  # B rows of 32 bytes
    return [b[i * 32 : (i + 1) * 32].hex() for i in range(words.shape[1])]


#: batch-size tiers: every call pads its lane count up to a tier so XLA
#: compiles a handful of (chunks, batch) shapes total, never per-call shapes
BATCH_TIERS = (8, 64, 512, 1024, 2048, 4096)


def _pad_to_tier(n: int) -> int:
    for t in BATCH_TIERS:
        if t >= n:
            return t
    return -(-n // BATCH_TIERS[-1]) * BATCH_TIERS[-1]


def blake3_batch_hex(messages: list[bytes], max_chunks: int | None = None,
                     kernel: str | None = None) -> list[str]:
    """Convenience one-shot: pack → device hash → hex digests. Pads the batch
    to a size tier (empty-message lanes) to bound compiled-shape count."""
    if not messages:
        return []
    if max_chunks is None:
        need = max(1, max((len(m) + CHUNK_LEN - 1) // CHUNK_LEN for m in messages))
        max_chunks = 1 << (need - 1).bit_length()  # tier to a power of two
    B = len(messages)
    padded = messages + [b""] * (_pad_to_tier(B) - B)
    words, lengths = pack_messages(padded, max_chunks)
    out = digests_to_hex(np.asarray(
        blake3_batch(jnp.asarray(words), jnp.asarray(lengths), kernel=kernel)))
    return out[:B]
