"""Batched bilinear resize on device — the thumbnailer's hot loop, TPU-first.

The reference resizes one image at a time on CPU (sd-images +
thumbnail/mod.rs:95-110 √(262144/wh) scale). Thumbnails have per-image
target sizes, which naively breaks batching; the shapes are made static
with the pad-and-mask scheme the BLAKE3 kernel uses:

- inputs pad into a fixed (B, H_in, W_in, 3) canvas (host pre-reduces
  anything bigger by integer box factors — cheap and antialiasing-friendly);
- every output lives in a fixed (B, 512, 512, 3) canvas — 512² is exactly
  the 262,144 px² target area, so any aspect ratio's thumbnail fits;
- per-image (src_h, src_w) and (tgt_h, tgt_w) vectors drive the sampling
  arithmetic as data, not shape, so ONE compiled program serves every batch
  (no recompilation storms).

MXU formulation: bilinear resampling is separable, so instead of 4 gathers
per output pixel (gathers are slow paths on TPU) each image is resized by
two dense contractions with per-image interpolation matrices built on
device from the dim vectors:

    out[b] = A_y[b] (512×H_in) · img[b] (H_in×W_in×3) · A_x[b]ᵀ (W_in×512)

Each A row holds the two bilinear taps for one output coordinate (rows past
the image's own target dims are all-zero, which doubles as the mask). The
contractions are plain batched matmuls — exactly what the systolic array is
for — and XLA fuses the A-matrix construction into the pipeline. Compute is
float32 (bf16's ~8 mantissa bits would band 8-bit channels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

#: output canvas edge: ceil(sqrt(262144)) — thumbnail/mod.rs target area
CANVAS = 512


def _interp_matrix(size_in: int, actual, target, canvas: int) -> jax.Array:
    """(canvas, size_in) bilinear resampling matrix for one axis: row i
    carries weights (1-w, w) at source taps floor(s), floor(s)+1 where
    s = (i+0.5)·actual/target − 0.5; rows i ≥ target are zero (mask)."""
    actual_f = actual.astype(jnp.float32)
    target_f = target.astype(jnp.float32)
    idx = jnp.arange(canvas, dtype=jnp.float32)
    src = jnp.clip((idx + 0.5) * (actual_f / target_f) - 0.5,
                   0.0, actual_f - 1.0)
    i0 = jnp.floor(src).astype(jnp.int32)
    i1 = jnp.minimum(i0 + 1, actual - 1)
    w = src - i0.astype(jnp.float32)
    cols = jnp.arange(size_in, dtype=jnp.int32)
    # i0 == i1 at the clamped edge: the two one-hots overlap and the
    # weights still sum to 1
    m = ((cols[None, :] == i0[:, None]) * (1.0 - w)[:, None]
         + (cols[None, :] == i1[:, None]) * w[:, None])
    return jnp.where((idx < target_f)[:, None], m, 0.0)


@functools.partial(jax.jit, static_argnames=("canvas",))
def resize_batch(images: jax.Array, src_hw: jax.Array, tgt_hw: jax.Array,
                 canvas: int = CANVAS) -> jax.Array:
    """(B, H_in, W_in, 3) uint8 → (B, canvas, canvas, 3) uint8.

    src_hw/tgt_hw: (B, 2) int32 actual and target (h, w) per image; the
    region outside each image's (tgt_h, tgt_w) is zeroed.
    """
    _, h_in, w_in, _ = images.shape
    images_f = images.astype(jnp.float32)

    ay = jax.vmap(lambda s, t: _interp_matrix(h_in, s, t, canvas))(
        src_hw[:, 0], tgt_hw[:, 0])                      # (B, canvas, H_in)
    ax = jax.vmap(lambda s, t: _interp_matrix(w_in, s, t, canvas))(
        src_hw[:, 1], tgt_hw[:, 1])                      # (B, canvas, W_in)

    rows = jnp.einsum("bih,bhwc->biwc", ay, images_f)    # vertical pass
    out = jnp.einsum("bjw,biwc->bijc", ax, rows)         # horizontal pass
    return jnp.clip(jnp.round(out), 0.0, 255.0).astype(jnp.uint8)


def target_dims(w: int, h: int, target_px: float = float(CANVAS * CANVAS)
                ) -> tuple[int, int]:
    """√(target/wh) scale preserving aspect (thumbnail/mod.rs:95-100);
    returns (th, tw). Deviation from the scalar path: an extreme-aspect
    image whose longer edge exceeds the canvas is scaled down further so it
    fits — aspect is preserved, only the degenerate very-long-thin case
    shrinks below the 262144 px² budget."""
    import math

    if w * h <= target_px:
        factor = 1.0
    else:
        factor = math.sqrt(target_px / (w * h))
    longest = max(w, h) * factor
    if longest > CANVAS:
        factor *= CANVAS / longest
    th = max(1, min(CANVAS, round(h * factor)))
    tw = max(1, min(CANVAS, round(w * factor)))
    return th, tw


def resize_batch_host(arrays: list[np.ndarray],
                      max_input_edge: int = 2048) -> list[np.ndarray]:
    """Host convenience wrapper: decoded RGB uint8 arrays (any sizes) →
    per-image thumbnails (cropped to their own target dims).

    Arrays larger than ``max_input_edge`` must be pre-reduced by the caller
    (PIL ``Image.reduce`` by an integer factor keeps this cheap); the batch
    pads to the largest input in the batch.
    """
    if not arrays:
        return []
    bad = [i for i, a in enumerate(arrays)
           if max(a.shape[0], a.shape[1]) > max_input_edge]
    if bad:
        raise ValueError(f"inputs {bad} exceed max_input_edge={max_input_edge}")
    # shape buckets: dims round up to 256-multiples and the batch count to a
    # power of two, so the jitted kernel compiles O(few dozen) variants total
    # instead of one per distinct batch shape (the recompilation storm the
    # pad-and-mask design exists to prevent)
    h_in = _bucket(max(a.shape[0] for a in arrays), max_input_edge)
    w_in = _bucket(max(a.shape[1] for a in arrays), max_input_edge)
    n_real = len(arrays)
    n = max(1, 1 << (n_real - 1).bit_length())
    batch = np.zeros((n, h_in, w_in, 3), np.uint8)
    src = np.ones((n, 2), np.int32)   # padding lanes: 1×1 src → 1×1 tgt
    tgt = np.ones((n, 2), np.int32)
    for i, a in enumerate(arrays):
        batch[i, : a.shape[0], : a.shape[1]] = a
        src[i] = (a.shape[0], a.shape[1])
        tgt[i] = target_dims(a.shape[1], a.shape[0])
    out = np.asarray(resize_batch(jnp.asarray(batch), jnp.asarray(src),
                                  jnp.asarray(tgt)))
    return [out[i, : tgt[i, 0], : tgt[i, 1]] for i in range(n_real)]


def _bucket(value: int, cap: int) -> int:
    return min(cap, ((value + 255) // 256) * 256)
