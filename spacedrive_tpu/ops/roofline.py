"""u32-VPU roofline model for the BLAKE3 kernel — MFU accounting.

Kernel progress has so far been expressed against a 1-core CPU baseline
(``vs_baseline`` in the bench), which says nothing about how much of the
*chip* a kernel uses. This module pins the arithmetic-intensity model and
the hardware peak so the bench can report MFU (model-flop-utilization, here
model-op-utilization of the u32 VPU) per run.

Ops/byte — the 12.5 model
-------------------------
One BLAKE3 compression processes a 64-byte block with 7 rounds × 8 G
functions. Each G is 14 u32 VPU ops (6 adds, 4 xors, 4 rotates — a rotate
is one VPU op on TPU, as on any machine with a hardware rotate/funnel
shift), plus the 8 output-feedforward xors:

    7 × 8 × 14 + 8 = 792 ≈ 800 ops / 64 B = 12.5 ops/byte

Parent (merkle) compressions add ~1/16 on top (one parent per 1 KiB chunk
pair); the model deliberately excludes them — the figure tracks *payload*
bytes, so MFU is a slight underestimate, never flattered.

Peak u32 ops/s
--------------
The VPU is an 8×128 lane grid with 4 ALUs per lane slot. At the ~940 MHz
clock of a v4-class core that is

    8 × 128 × 4 × 0.94e9 ≈ 3.85e12 u32 ops/s per core.

Override with ``SD_TPU_PEAK_U32_OPS`` when the harness chip differs (the
tunneled harness does not expose its chip generation; the default keeps
MFU comparable across rounds until it does). The derived roofline for this
model: peak_bytes/s = peak_ops/s ÷ 12.5 ≈ 308 GB/s device-resident — see
docs/architecture/tpu-backend.md ("Roofline and MFU").
"""

from __future__ import annotations

import os

#: u32 VPU ops per payload byte (derivation above; rotate = 1 op)
OPS_PER_BYTE = 12.5

#: default per-core peak, v4-class VPU (8×128 lanes × 4 ALUs × 0.94 GHz)
DEFAULT_PEAK_U32_OPS = 8 * 128 * 4 * 0.94e9  # ≈ 3.85e12


def peak_u32_ops() -> float:
    """Chip peak u32 ops/s — ``SD_TPU_PEAK_U32_OPS`` overrides the default
    (read per call so bench subprocesses stay hermetic)."""
    raw = os.environ.get("SD_TPU_PEAK_U32_OPS", "").strip()
    if raw:
        try:
            value = float(raw)
            if value > 0:
                return value
        except ValueError:
            pass
    return DEFAULT_PEAK_U32_OPS


def roofline_bytes_per_sec() -> float:
    """The compute roofline for BLAKE3 payload bytes: peak ÷ ops/byte."""
    return peak_u32_ops() / OPS_PER_BYTE


def mfu(bytes_per_sec: float) -> float:
    """Achieved fraction of the u32 roofline for a measured payload rate."""
    if bytes_per_sec <= 0:
        return 0.0
    return bytes_per_sec * OPS_PER_BYTE / peak_u32_ops()
