"""Hand-tiled Pallas BLAKE3 compression kernel — the register-resident path.

The XLA kernel (blake3_jax.compress) expresses one compression as a 7-step
``lax.scan`` whose body gathers the permuted message each round; XLA is then
free to spill the 16 state words and 16 message words between rounds, and on
TPU the scan carry round-trips through VMEM every step. This kernel removes
both degrees of freedom, the way SIMD BLAKE3 implementations win on CPUs
(keep rounds in registers, saturate vector lanes — arxiv 2508.05797):

- **8×128 u32 lane tiles.** Lanes (independent compressions: chunk×batch in
  phase 1, parent pairs in phase 2) are flattened and tiled to the VPU's
  native (8, 128) uint32 shape; each grid step owns ``TILE_ROWS`` sublane
  rows so the working set (16 state + 16 message words × tile) stays far
  under VMEM.
- **Rounds unrolled in registers.** The 7 rounds are unrolled inside the
  kernel body — ~800 straight-line VPU ops per tile with no loop carry, so
  Mosaic keeps the 32 live words in vector registers across rounds.
- **Permutation baked into the schedule.** Instead of permuting the message
  arrays between rounds, ``MSG_SCHEDULE[r]`` precomputes which original word
  each G-slot reads in round ``r`` — the permutation costs zero data
  movement (the same trick as the reference implementation's compile-time
  round schedule).

The chunk-chaining and merkle-merge orchestration stays in blake3_jax —
this module only replaces the compression primitive, selected per call via
``SD_BLAKE3_KERNEL=pallas`` (see blake3_jax.resolve_kernel). On non-TPU
backends the kernel runs in Pallas interpret mode (pure-JAX evaluation), so
byte-identical parity against the objects/blake3_ref.py oracle is provable
on CPU while the device relay is down.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..objects.blake3_ref import IV, MSG_PERMUTATION

_u32 = jnp.uint32

#: sublane rows per grid step; 8×128 is the VPU's native u32 tile, and 8
#: rows (1024 lanes) keeps per-tile VMEM (33 × 4 KiB blocks ≈ 132 KiB)
#: comfortably double-bufferable
TILE_ROWS = 8
LANES = 128
_TILE = TILE_ROWS * LANES


def _schedule() -> tuple[tuple[int, ...], ...]:
    """Per-round message word order: round r, slot s reads original word
    ``schedule[r][s]``. Baking the permutation here means the kernel never
    moves message data between rounds."""
    rounds = [tuple(range(16))]
    for _ in range(6):
        prev = rounds[-1]
        rounds.append(tuple(prev[p] for p in MSG_PERMUTATION))
    return tuple(rounds)


MSG_SCHEDULE = _schedule()


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _g(v: list[jax.Array], a: int, b: int, c: int, d: int,
       mx: jax.Array, my: jax.Array) -> None:
    v[a] = v[a] + v[b] + mx
    v[d] = _rotr(v[d] ^ v[a], 16)
    v[c] = v[c] + v[d]
    v[b] = _rotr(v[b] ^ v[c], 12)
    v[a] = v[a] + v[b] + my
    v[d] = _rotr(v[d] ^ v[a], 8)
    v[c] = v[c] + v[d]
    v[b] = _rotr(v[b] ^ v[c], 7)


def _compress_kernel(cv_ref, m_ref, ctr_ref, blen_ref, flags_ref, out_ref):
    """One tile of compressions: every array is (TILE_ROWS, 128) u32 lanes;
    cv/m/out carry a leading word axis. Fully unrolled — no scan carry."""
    v = [cv_ref[i] for i in range(8)]
    v += [jnp.full((TILE_ROWS, LANES), w, _u32) for w in IV[:4]]
    v += [ctr_ref[...], jnp.zeros((TILE_ROWS, LANES), _u32),
          blen_ref[...], flags_ref[...]]
    m = [m_ref[i] for i in range(16)]
    for r in range(7):
        s = MSG_SCHEDULE[r]
        _g(v, 0, 4, 8, 12, m[s[0]], m[s[1]])
        _g(v, 1, 5, 9, 13, m[s[2]], m[s[3]])
        _g(v, 2, 6, 10, 14, m[s[4]], m[s[5]])
        _g(v, 3, 7, 11, 15, m[s[6]], m[s[7]])
        _g(v, 0, 5, 10, 15, m[s[8]], m[s[9]])
        _g(v, 1, 6, 11, 12, m[s[10]], m[s[11]])
        _g(v, 2, 7, 8, 13, m[s[12]], m[s[13]])
        _g(v, 3, 4, 9, 14, m[s[14]], m[s[15]])
    for i in range(8):
        out_ref[i] = v[i] ^ v[i + 8]


def interpret_mode() -> bool:
    """Pallas interpret (pure-JAX) evaluation: forced by SD_PALLAS_INTERPRET,
    else on whenever the default backend isn't a real TPU. Read at trace
    time — each jit cache entry captures the mode it was traced under."""
    forced = os.environ.get("SD_PALLAS_INTERPRET", "").strip()
    if forced:
        return forced not in ("0", "false", "no")
    return jax.default_backend() != "tpu"


def compress_pallas(cv, m, counter, block_len, flags):
    """Drop-in for blake3_jax.compress: same contract (list-of-8 cv, 16
    message words as list or stacked array, broadcastable counter/len/flags;
    returns the 8 output words at the broadcast lane shape).

    Lanes are flattened, zero-padded up to a whole number of 8×128 tiles
    (padding lanes compute garbage nobody reads), and the grid walks tiles.
    """
    if isinstance(m, (list, tuple)):
        m = jnp.stack([jnp.asarray(w) for w in m])
    lane_shape = jnp.broadcast_shapes(
        cv[0].shape, m.shape[1:], jnp.shape(counter),
        jnp.shape(block_len), jnp.shape(flags))
    n = int(np.prod(lane_shape, dtype=np.int64)) if lane_shape else 1
    padded = max(_TILE, -(-n // _TILE) * _TILE)
    rows = padded // LANES

    def lanes(x):
        flat = jnp.broadcast_to(jnp.asarray(x).astype(_u32),
                                lane_shape).reshape(n)
        if padded != n:
            flat = jnp.pad(flat, (0, padded - n))
        return flat.reshape(rows, LANES)

    cvf = jnp.stack([lanes(w) for w in cv])                       # (8, R, 128)
    mf = jnp.stack([lanes(m[i]) for i in range(16)])              # (16, R, 128)
    word3 = lambda nw: pl.BlockSpec(                              # noqa: E731
        (nw, TILE_ROWS, LANES), lambda i: (0, i, 0),
        memory_space=pltpu.VMEM)
    lane2 = pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _compress_kernel,
        grid=(rows // TILE_ROWS,),
        in_specs=[word3(8), word3(16), lane2, lane2, lane2],
        out_specs=word3(8),
        out_shape=jax.ShapeDtypeStruct((8, rows, LANES), _u32),
        interpret=interpret_mode(),
    )(cvf, mf, lanes(counter), lanes(block_len), lanes(flags))
    out = out.reshape(8, padded)[:, :n]
    return [out[i].reshape(lane_shape) for i in range(8)]
