"""Declarative SQLite model layer.

Replaces the reference's prisma-client-rust + sd-sync-generator codegen pair
(core/prisma/schema.prisma, crates/sync-generator/src/lib.rs:22-36): models are
declared once in Python with field specs AND sync annotations; the same
declaration drives (a) CREATE TABLE DDL, (b) typed row access, and (c) the
CRDT sync layer's per-model dispatch (which fields replicate, what the stable
sync id is) — no codegen step needed.

Sync annotations mirror ModelSyncType (sync-generator lib.rs:22-36):
  - ``sync=None``                → local-only model (not replicated)
  - ``sync=Shared(id="pub_id")`` → record-level LWW replication
  - ``sync=Relation(item, group)`` → many-many link replication

Writes flow through a single-writer connection (SQLite WAL single-writer
discipline the reference keeps with MAX_WORKERS=1, job/manager.rs:31-32).
"""

from __future__ import annotations

import functools
import dataclasses
import datetime as _dt
import json
import os
import re
import sqlite3
import threading
import time
from pathlib import Path
from typing import Any, ClassVar, Iterable

from .. import faults, telemetry
from ..telemetry import spans as _tspans
from ..utils.locks import SdLock, SdRLock
from ..utils.retry import RetryPolicy, is_sqlite_busy, retry_call

#: reader/writer contention instrument (ISSUE 10): observed only for
#: CONTENDED reader-lock acquisitions (the uncontended fast path pays one
#: non-blocking try-acquire, no timing, no observe), so a serving tier
#: queueing behind a long reader shows up without taxing the common case
_READER_WAIT = telemetry.histogram(
    "sd_db_reader_wait_seconds",
    "time reads spent waiting for the WAL reader connection lock "
    "(contended acquisitions only — reader/writer contention under "
    "serving load)")


# --------------------------------------------------------------------------
# field + sync specs
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Field:
    type: str  # INTEGER | TEXT | REAL | BLOB | BOOLEAN | DATETIME | JSON | BYTES
    primary_key: bool = False
    nullable: bool = True
    unique: bool = False
    default: Any = None
    references: str | None = None  # "table.column"
    on_delete: str = "CASCADE"  # CASCADE | RESTRICT | "SET NULL" (FK policy)
    autoincrement: bool = False

    SQL_TYPES: ClassVar[dict[str, str]] = {
        "INTEGER": "INTEGER",
        "TEXT": "TEXT",
        "REAL": "REAL",
        "BLOB": "BLOB",
        "BYTES": "BLOB",
        "BOOLEAN": "INTEGER",
        "DATETIME": "TEXT",
        "JSON": "TEXT",
        "BIGINT": "INTEGER",
    }


@dataclasses.dataclass(frozen=True)
class Shared:
    """Record-level last-write-wins replication (``/// @shared(id: ...)``)."""

    id: str = "pub_id"


@dataclasses.dataclass(frozen=True)
class Relation:
    """Many-many link replication (``/// @relation(item, group)``)."""

    item: str
    group: str


MODEL_REGISTRY: dict[str, type["Model"]] = {}


class Model:
    """Base class. Subclasses set TABLE, FIELDS, optional UNIQUES/INDEXES/SYNC."""

    TABLE: ClassVar[str]
    FIELDS: ClassVar[dict[str, Field]]
    UNIQUES: ClassVar[tuple[tuple[str, ...], ...]] = ()
    INDEXES: ClassVar[tuple[tuple[str, ...], ...]] = ()
    SYNC: ClassVar[Shared | Relation | None] = None
    # fields excluded from sync replication even on shared models (local ids)
    SYNC_SKIP: ClassVar[tuple[str, ...]] = ("id",)

    def __init_subclass__(cls, **kw: Any) -> None:
        super().__init_subclass__(**kw)
        if hasattr(cls, "TABLE"):
            MODEL_REGISTRY[cls.TABLE] = cls

    # -- DDL ----------------------------------------------------------------
    @classmethod
    def ddl(cls) -> list[str]:
        cols = []
        for name, f in cls.FIELDS.items():
            parts = [f'"{name}"', Field.SQL_TYPES[f.type]]
            if f.primary_key:
                parts.append("PRIMARY KEY")
                if f.autoincrement:
                    parts.append("AUTOINCREMENT")
            if not f.nullable and not f.primary_key:
                parts.append("NOT NULL")
            if f.unique:
                parts.append("UNIQUE")
            if f.default is not None:
                parts.append(f"DEFAULT {json.dumps(f.default)}")
            if f.references:
                table, col = f.references.split(".")
                parts.append(f"REFERENCES {table}({col}) ON DELETE {f.on_delete}")
            cols.append(" ".join(parts))
        for unique in cls.UNIQUES:
            quoted = ", ".join(f'"{c}"' for c in unique)
            cols.append(f"UNIQUE ({quoted})")
        stmts = [f"CREATE TABLE IF NOT EXISTS {cls.TABLE} ({', '.join(cols)})"]
        for idx in cls.INDEXES:
            # an entry with a space carries SQL modifiers ("materialized_path
            # COLLATE NOCASE") and passes through unquoted; the index name
            # folds the modifiers in so it can never collide with the plain
            # index over the same columns
            quoted = ", ".join(f'"{c}"' if " " not in c else c for c in idx)
            name = "_".join("_".join(c.lower().split()) for c in idx)
            stmts.append(
                f"CREATE INDEX IF NOT EXISTS idx_{cls.TABLE}_{name} "
                f"ON {cls.TABLE} ({quoted})"
            )
        return stmts

    # -- value encoding -----------------------------------------------------
    @classmethod
    def encode(cls, name: str, value: Any) -> Any:
        e = cls.encoder(name)
        return value if e is None else e(value)

    @classmethod
    def decode(cls, name: str, value: Any) -> Any:
        f = cls.FIELDS.get(name)
        if value is None or f is None:
            return value
        if f.type == "BOOLEAN":
            return bool(value)
        if f.type == "DATETIME":
            return _dt.datetime.fromisoformat(value) if isinstance(value, str) else value
        if f.type == "JSON":
            return json.loads(value) if isinstance(value, str) else value
        return value

    @classmethod
    def decode_row(cls, row: sqlite3.Row) -> dict[str, Any]:
        return {k: cls.decode(k, row[k]) for k in row.keys()}

    @classmethod
    @functools.lru_cache(maxsize=4096)
    def _encoder_cached(cls, name: str):
        f = cls.FIELDS[name]
        if f.type == "BOOLEAN":
            return lambda v: None if v is None else int(bool(v))
        if f.type == "DATETIME":
            return lambda v: (v.astimezone(_dt.timezone.utc).isoformat()
                              if isinstance(v, _dt.datetime) else v)
        if f.type == "JSON":
            return lambda v: None if v is None else json.dumps(v, sort_keys=True)
        return None

    @classmethod
    def encoder(cls, name: str):
        """Per-column encode callable (cached per model+column), or None
        for passthrough columns — the single source of encoding truth;
        :meth:`encode` and the bulk writers both resolve through it."""
        return cls._encoder_cached(name)


def utc_now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


# --------------------------------------------------------------------------
# row-change journal (the search engine's incremental-refresh feed)
# --------------------------------------------------------------------------


class RowJournal:
    """Per-table changed-row accounting on a writer Database.

    The device search engine (ISSUE 15, spacedrive_tpu/search/) refreshes
    its columnar index incrementally: appends ride a ``id > max_id`` scan,
    everything else needs to know WHICH rows changed. Every model-helper
    write (insert/update/delete) notes the touched row's ``id`` or
    ``pub_id`` here; raw SQL writes that bypass the helpers are caught by
    a table-name sniff in :meth:`Database.execute` and degrade that
    table to a **flood** (consumer does a full rebuild) — over-noting is
    always safe, silent under-noting would serve stale rows.

    Notes made inside an open transaction are buffered per-thread and
    published when the outermost transaction closes: the consumer reads
    the last COMMITTED snapshot, so a note must never be drainable before
    its rows are visible (a drained-then-invisible note would be lost to
    the next refresh). Publishing on rollback too is deliberate — a
    re-select of an unchanged row is idempotent.

    Bounded: past ``CAP`` noted rows per table the journal floods that
    table instead of growing.
    """

    CAP = 8192
    _WRITE_VERB = re.compile(r"^\s*(insert|update|delete|replace)\b", re.I)

    def __init__(self, tables: Iterable[str],
                 flood_on_delete: Iterable[str] = ()) -> None:
        self.tables = frozenset(tables)
        #: tables whose DELETEs flood instead of noting the row: an FK
        #: cascade (``ON DELETE SET NULL`` on file_path.object_id) mutates
        #: OTHER tracked rows the statement never names
        self.flood_on_delete = frozenset(flood_on_delete)
        self._lock = threading.Lock()
        self._ids: dict[str, set[int]] = {t: set() for t in self.tables}
        self._pub_ids: dict[str, set[str]] = {t: set() for t in self.tables}
        self._flood: set[str] = set()
        #: thread ident -> notes buffered inside that thread's open txn
        self._pending: dict[int, list[tuple[str, str, Any]]] = {}

    def _apply_locked(self, table: str, key: str, value: Any) -> None:
        if key == "flood" or value is None:
            self._flood.add(table)
        elif key == "id":
            bucket = self._ids[table]
            bucket.add(int(value))
            if len(bucket) > self.CAP:
                self._flood.add(table)
        elif key == "pub_id":
            bucket = self._pub_ids[table]
            bucket.add(str(value))
            if len(bucket) > self.CAP:
                self._flood.add(table)

    def publish_one(self, table: str, key: str, value: Any) -> None:
        with self._lock:
            self._apply_locked(table, key, value)

    def buffer(self, ident: int, table: str, key: str, value: Any) -> None:
        with self._lock:
            self._pending.setdefault(ident, []).append((table, key, value))

    def publish_thread(self, ident: int) -> None:
        """Outermost-transaction close: the thread's buffered notes become
        drainable (the rows are now committed — or rolled back, which a
        re-select absorbs)."""
        with self._lock:
            for table, key, value in self._pending.pop(ident, ()):
                self._apply_locked(table, key, value)

    def sniff(self, sql: str) -> str | None:
        """Raw-write detection: returns the tracked table a bypassing
        write names, or None (the caller then routes a flood note through
        the txn-aware path)."""
        if not self._WRITE_VERB.match(sql):
            return None
        head = sql[:256].lower()
        for table in self.tables:
            if re.search(rf"\b{table}\b", head):
                return table
        return None

    def drain(self) -> dict[str, Any]:
        """Atomically take the published notes (buffered ones stay)."""
        with self._lock:
            out = {
                "ids": {t: s for t, s in self._ids.items() if s},
                "pub_ids": {t: s for t, s in self._pub_ids.items() if s},
                "flood": set(self._flood),
            }
            self._ids = {t: set() for t in self.tables}
            self._pub_ids = {t: set() for t in self.tables}
            self._flood = set()
        return out


# --------------------------------------------------------------------------
# database handle
# --------------------------------------------------------------------------


class Database:
    """A single SQLite library database with single-writer discipline.

    The reference leans on SQLite's WAL single-writer ("db is single threaded,
    nerd", job/manager.rs:31-32); here all writes funnel through one mutex'd
    connection. Reads take a dedicated WAL reader connection (last committed
    snapshot, never queued behind the writer lock) unless the calling thread
    owns the open transaction — then they read the writer so the txn sees its
    own uncommitted rows. This is what keeps the pipeline prefetcher paging
    while the committer holds a multi-page group-commit transaction.
    """

    def __init__(self, path: str | Path, models: Iterable[type[Model]],
                 readonly: bool = False) -> None:
        self.path = str(path)
        self.readonly = readonly
        if readonly:
            # per-process reader bootstrap (ISSUE 11): the serve-pool
            # workers open each library with ONE read-only connection —
            # no writer, no migrate (the node process owns DDL), every
            # SELECT a fresh WAL snapshot. ``mode=ro`` + ``query_only``
            # is defense in depth: a write attempt raises instead of
            # contending the node's single-writer discipline.
            self.models = list(models)
            self._lock = SdRLock("db.writer")
            self._conn = sqlite3.connect(
                f"file:{self.path}?mode=ro", uri=True,
                check_same_thread=False, cached_statements=512)
            self._conn.row_factory = sqlite3.Row
            self._conn.execute("PRAGMA query_only=ON")
            self._txn_depth = 0
            self._txn_thread = None
            self._read_conn = self._conn
            self._read_lock = SdLock("db.reader")
            self._closed = False
            self._journal = None
            return
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self.models = list(models)
        # re-entrant: _Txn join + the upsert → find_one → query chain
        # re-enter on the owning thread (named for the sanitizer soaks)
        self._lock = SdRLock("db.writer")
        # autocommit mode; transactions are managed explicitly by _Txn so a
        # single connection can serve both one-shot writes and atomic batches.
        # cached_statements: the sync-ingest hot loop cycles through dozens of
        # IN(...) shapes per window (one per chunk size × table) plus the
        # apply/log statements — the sqlite3 default of 128 thrashes at
        # production pull windows, re-preparing statements per batch
        self._conn = sqlite3.connect(self.path, check_same_thread=False,
                                     isolation_level=None,
                                     cached_statements=512)
        self._conn.row_factory = sqlite3.Row
        self._txn_depth = 0
        #: thread that currently owns the open transaction (mid-txn reads
        #: from that thread must see its own uncommitted writes; every
        #: other thread reads the last committed WAL snapshot)
        self._txn_thread: int | None = None
        # WAL reader connection (lazy): SELECTs from threads that are not
        # inside the write transaction go here, so the pipeline prefetcher's
        # page SELECT never serializes behind a (group-)commit transaction
        # holding the writer lock. ":memory:" databases get no reader — a
        # second :memory: connection would be a different database.
        self._read_conn: sqlite3.Connection | None = None
        self._read_lock = SdLock("db.reader")
        self._closed = False
        #: row-change journal (attached by the search engine; None = the
        #: write path pays nothing)
        self._journal: RowJournal | None = None
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA foreign_keys=ON")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self.migrate()
        self._migrate_columns()

    def migrate(self) -> None:
        with self._lock:
            for model in self.models:
                for stmt in model.ddl():
                    self._conn.execute(stmt)

    def _migrate_columns(self) -> None:
        """Additive schema evolution: columns declared on a model but missing
        from an existing DB file are ALTER TABLE'd in (the micro analogue of
        prisma migrate for the append-only schema changes this framework
        makes; destructive changes go through backups/restore)."""
        with self._lock:
            for model in self.models:
                have = {r["name"] for r in self._conn.execute(
                    f"PRAGMA table_info({model.TABLE})")}
                for name, field in model.FIELDS.items():
                    if name in have:
                        continue
                    col = f'"{name}" {field.type}'
                    if field.default is not None:
                        col += f" DEFAULT {model.encode(name, field.default)!r}"
                    self._conn.execute(f"ALTER TABLE {model.TABLE} ADD COLUMN {col}")

    def close(self) -> None:
        with self._read_lock:
            self._closed = True
            if self._read_conn is not None:
                self._read_conn.close()
                self._read_conn = None
        with self._lock:
            self._conn.close()

    # -- row-change journal (search-engine refresh feed) ---------------------
    def attach_row_journal(self, tables: Iterable[str],
                           flood_on_delete: Iterable[str] = ()) -> RowJournal:
        """Idempotent per table set; the single consumer drains it."""
        journal = self._journal
        if journal is None or journal.tables != frozenset(tables):
            journal = RowJournal(tables, flood_on_delete=flood_on_delete)
            self._journal = journal
        return journal

    def _journal_note(self, table: str, key: str, value: Any) -> None:
        """Txn-aware note routing: inside an open transaction the note is
        buffered until the OUTERMOST close publishes it — a drainable
        note must never precede its rows' visibility to readers."""
        journal = self._journal
        if journal is None or table not in journal.tables:
            return
        if self._txn_depth and self._txn_thread == threading.get_ident():
            journal.buffer(threading.get_ident(), table, key, value)
        else:
            journal.publish_one(table, key, value)

    def _journal_sniff(self, sql: str) -> None:
        journal = self._journal
        if journal is not None:
            table = journal.sniff(sql)
            if table is not None:
                self._journal_note(table, "flood", None)

    # -- low-level ----------------------------------------------------------
    def execute(self, sql: str, params: tuple | list = (), *,
                _noted: bool = False) -> sqlite3.Cursor:
        if self.readonly:
            raise sqlite3.ProgrammingError(
                "read-only database handle (serve-pool reader)")
        with self._lock:
            cur = self._conn.execute(sql, params)
        if not _noted:
            # AFTER the statement: an autocommit write is visible now, so
            # the note can never be drained ahead of its rows (txn-scoped
            # writes buffer until the outermost close either way)
            self._journal_sniff(sql)
        return cur

    def executemany_noted(self, sql: str, seq: list[tuple], table: str,
                          row_ids: Iterable[int]) -> None:
        """Raw batch write over a journal-tracked table with the touched
        row ids declared up front — the un-forgettable form of the
        sniff-suppressing ``_noted`` idiom: the statement and its notes
        travel in one call, so a caller can never suppress the sniff and
        then forget the notes (which would serve stale search rows)."""
        self.executemany(sql, seq, _noted=True)
        for row_id in row_ids:
            self._journal_note(table, "id", row_id)

    def executemany(self, sql: str, seq: list[tuple], *,
                    _noted: bool = False) -> None:
        if self.readonly:
            raise sqlite3.ProgrammingError(
                "read-only database handle (serve-pool reader)")
        with self._lock:
            if self._txn_depth:
                self._conn.executemany(sql, seq)
            else:  # batch inserts get their own transaction for speed
                with _Txn(self):
                    self._conn.executemany(sql, seq)
        if not _noted:
            self._journal_sniff(sql)

    def _reader(self) -> sqlite3.Connection | None:
        """The WAL reader connection (None for :memory:). Opened lazily —
        after migrate() ran on the writer, so DDL is always visible. A
        closed Database raises like the writer path would, instead of
        silently re-opening a leaked connection."""
        if self._closed:
            raise sqlite3.ProgrammingError(
                "Cannot operate on a closed database.")
        if self.path == ":memory:":
            return None
        if self._read_conn is None:
            conn = sqlite3.connect(self.path, check_same_thread=False,
                                   cached_statements=512)
            conn.row_factory = sqlite3.Row
            # defense in depth: the reader must never become a second
            # writer behind the single-writer discipline
            conn.execute("PRAGMA query_only=ON")
            self._read_conn = conn
        return self._read_conn

    def query(self, sql: str, params: tuple | list = ()) -> list[sqlite3.Row]:
        # mid-transaction reads from the txn-owning thread must go through
        # the writer (they see the open txn's uncommitted rows); everyone
        # else reads the last committed snapshot off the reader connection
        # WITHOUT queueing on the writer lock. The unlocked depth/thread
        # peek is safe: only the owning thread sets _txn_thread to its own
        # id, so a stale read from any other thread routes to the reader —
        # exactly where a non-owner belongs.
        if self._txn_depth and self._txn_thread == threading.get_ident():
            with self._lock:
                rows = self._conn.execute(sql, params).fetchall()
            # the txn-owner path CAN carry writes (objects/gc.py issues
            # DELETEs through query() inside its transaction) — sniff
            # them like execute() does, or the row journal would
            # under-note and the search index would serve stale rows.
            # Reads pay one failed regex match on the first token.
            self._journal_sniff(sql)
            return rows
        # request traces (telemetry/requests.py) opt into per-SELECT spans
        # so a slow rspc query shows its SQL/reader-wait breakdown; job
        # traces never set record_db_spans — their per-batch recording
        # discipline stays intact
        trace = _tspans.current_trace()
        sp = (trace.span("db.query", sql=sql[:120])
              if trace is not None
              and getattr(trace, "record_db_spans", False) else None)
        try:
            if sp is not None:
                sp.__enter__()
            if not self._read_lock.acquire(blocking=False):
                t0 = time.perf_counter()
                self._read_lock.acquire()
                wait_s = time.perf_counter() - t0
                _READER_WAIT.observe(wait_s)
                if sp is not None:
                    sp.set(reader_wait_s=round(wait_s, 6))
            try:
                reader = self._reader()
                if reader is not None:
                    return reader.execute(sql, params).fetchall()
            finally:
                self._read_lock.release()
            with self._lock:
                return self._conn.execute(sql, params).fetchall()
        finally:
            if sp is not None:
                sp.__exit__(None, None, None)

    def transaction(self):
        """Context manager for an atomic multi-statement write (the analogue of
        prisma's ``_batch`` used by sync write_ops, manager.rs:62-99)."""
        if self.readonly:
            raise sqlite3.ProgrammingError(
                "read-only database handle (serve-pool reader)")
        return _Txn(self)

    def quick_check(self) -> list[str]:
        """``PRAGMA quick_check`` on the writer connection: ``[]`` when the
        database is structurally sound, else the problem rows. The boot-time
        integrity gate (recovery.py) runs this on a throwaway connection
        BEFORE the library loads; this method serves on-demand checks on a
        live handle (API surface, tests)."""
        with self._lock:
            rows = self._conn.execute("PRAGMA quick_check").fetchall()
        problems = [r[0] for r in rows]
        return [] if problems == ["ok"] else problems

    # -- model helpers ------------------------------------------------------
    @staticmethod
    @functools.lru_cache(maxsize=512)
    def _insert_sql_cached(table: str, cols: tuple[str, ...], or_ignore: bool) -> str:
        collist = ", ".join(f'"{c}"' for c in cols)
        return (
            f"INSERT {'OR IGNORE ' if or_ignore else ''}INTO {table} "
            f"({collist}) VALUES ({', '.join('?' for _ in cols)})"
        )

    @classmethod
    def _insert_sql(cls, model: type[Model], cols: list[str], or_ignore: bool) -> str:
        return cls._insert_sql_cached(model.TABLE, tuple(cols), or_ignore)

    @staticmethod
    def _where_sql(model: type[Model], where: dict[str, Any]) -> tuple[str, list[Any]]:
        """None values compare with IS NULL (``col = NULL`` matches nothing)."""
        parts: list[str] = []
        params: list[Any] = []
        for c, v in where.items():
            if v is None:
                parts.append(f'"{c}" IS NULL')
            else:
                parts.append(f'"{c}" = ?')
                params.append(model.encode(c, v))
        return " AND ".join(parts), params

    def _journal_where(self, table: str, where: dict[str, Any]) -> None:
        """Note an update/delete by its where-key: a unique row key notes
        that row exactly; anything else floods the table (the consumer
        full-rebuilds — over-noting is safe, a missed row is not)."""
        if self._journal is None or table not in self._journal.tables:
            return
        if where.get("id") is not None:
            self._journal_note(table, "id", where["id"])
        elif where.get("pub_id") is not None:
            self._journal_note(table, "pub_id", where["pub_id"])
        else:
            self._journal_note(table, "flood", None)

    def insert(self, model: type[Model], row: dict[str, Any], or_ignore: bool = False) -> int:
        cols = [c for c in row.keys() if c in model.FIELDS]
        sql = self._insert_sql(model, cols, or_ignore)
        cur = self.execute(sql, [model.encode(c, row[c]) for c in cols],
                           _noted=True)
        if cur.rowcount > 0:
            self._journal_note(model.TABLE, "id", cur.lastrowid)
        return cur.lastrowid

    def insert_ignore(self, model: type[Model], row: dict[str, Any]) -> bool:
        """INSERT OR IGNORE; True iff a row was actually inserted — the
        one-statement half of rowcount-based upserts (sync apply hot path)."""
        cols = [c for c in row.keys() if c in model.FIELDS]
        sql = self._insert_sql(model, cols, True)
        cur = self.execute(sql, [model.encode(c, row[c]) for c in cols],
                           _noted=True)
        inserted = cur.rowcount > 0
        if inserted:
            self._journal_note(model.TABLE, "id", cur.lastrowid)
        return inserted

    def insert_many(self, model: type[Model], rows: list[dict[str, Any]], or_ignore: bool = False) -> int:
        if not rows:
            return 0
        cols = [c for c in rows[0].keys() if c in model.FIELDS]
        sql = self._insert_sql(model, cols, or_ignore)
        # per-column encoders once per call (None = passthrough) instead of
        # a 4-branch method dispatch per value
        encs = [(c, model.encoder(c)) for c in cols]
        self.executemany(sql, [
            tuple(r.get(c) if e is None else e(r.get(c)) for c, e in encs)
            for r in rows], _noted=True)
        # fresh AUTOINCREMENT ids ride the consumer's id > max_id append
        # scan; only explicit-id rows need notes
        if "id" in cols:
            for r in rows:
                self._journal_note(model.TABLE, "id", r.get("id"))
        return len(rows)

    def update(self, model: type[Model], where: dict[str, Any], values: dict[str, Any]) -> int:
        if not values:
            return 0
        set_sql = ", ".join(f'"{c}" = ?' for c in values)
        where_sql, where_params = self._where_sql(model, where)
        params = [model.encode(c, v) for c, v in values.items()] + where_params
        cur = self.execute(f"UPDATE {model.TABLE} SET {set_sql} WHERE {where_sql}", params,
                           _noted=True)
        self._journal_where(model.TABLE, where)
        return cur.rowcount

    def delete(self, model: type[Model], where: dict[str, Any]) -> int:
        where_sql, params = self._where_sql(model, where)
        cur = self.execute(f"DELETE FROM {model.TABLE} WHERE {where_sql}", params,
                           _noted=True)
        journal = self._journal
        if journal is not None and model.TABLE in journal.flood_on_delete:
            self._journal_note(model.TABLE, "flood", None)
        else:
            self._journal_where(model.TABLE, where)
        return cur.rowcount

    def find(
        self,
        model: type[Model],
        where: dict[str, Any] | None = None,
        order_by: str | None = None,
        limit: int | None = None,
        offset: int | None = None,
    ) -> list[dict[str, Any]]:
        sql = f"SELECT * FROM {model.TABLE}"
        params: list[Any] = []
        if where:
            where_sql, params = self._where_sql(model, where)
            sql += f" WHERE {where_sql}"
        if order_by:
            sql += f" ORDER BY {order_by}"
        if limit is not None:
            sql += " LIMIT ?"
            params.append(limit)
        if offset is not None:
            sql += " OFFSET ?"
            params.append(offset)
        return [model.decode_row(r) for r in self.query(sql, params)]

    def find_one(self, model: type[Model], where: dict[str, Any]) -> dict[str, Any] | None:
        rows = self.find(model, where, limit=1)
        return rows[0] if rows else None

    def count(self, model: type[Model], where: dict[str, Any] | None = None) -> int:
        sql = f"SELECT COUNT(*) AS n FROM {model.TABLE}"
        params: list[Any] = []
        if where:
            where_sql, params = self._where_sql(model, where)
            sql += f" WHERE {where_sql}"
        return self.query(sql, params)[0]["n"]

    def upsert(
        self, model: type[Model], where: dict[str, Any], create: dict[str, Any], update: dict[str, Any]
    ) -> None:
        with self._lock:
            if self.find_one(model, where) is None:
                self.insert(model, {**where, **create})
            else:
                self.update(model, where, update)


#: SQLITE_BUSY retry for transaction BEGIN/COMMIT: bounded and fast (the
#: backoff runs while the connection RLock is held, so the budget stays
#: small — lock convoys resolve in milliseconds; anything longer escalates
#: to the caller's own policy, e.g. the pipeline committer's cancel-aware
#: retry). SD_TXN_RETRY_ATTEMPTS=1 disables the inner retry (chaos tests
#: use it to force escalation).
TXN_RETRY = RetryPolicy(
    attempts=max(1, int(os.environ.get("SD_TXN_RETRY_ATTEMPTS", "6"))),
    base_s=0.005, max_s=0.25, multiplier=2.0, jitter=0.5, budget_s=2.0)


class _Txn:
    """Re-entrant transaction scope: nested uses join the outer transaction.

    BEGIN and COMMIT retry SQLITE_BUSY under :data:`TXN_RETRY` (another
    process holding the file lock is transient by definition); ROLLBACK is
    never retried — it either succeeds or the connection is gone. The
    ``commit`` fault seam sits inside the retried region so injected busy
    storms exercise exactly the production path.
    """

    def __init__(self, db: Database) -> None:
        self.db = db

    def _begin(self) -> None:
        faults.inject("commit", key="begin")
        self.db._conn.execute("BEGIN IMMEDIATE")

    def _commit(self) -> None:
        faults.inject("commit", key="commit")
        self.db._conn.execute("COMMIT")

    def __enter__(self) -> Database:
        self.db._lock.acquire()
        try:
            if self.db._txn_depth == 0:
                retry_call(self._begin, policy=TXN_RETRY,
                           classify=is_sqlite_busy, label="txn-begin")
                self.db._txn_thread = threading.get_ident()
            self.db._txn_depth += 1
        except BaseException:
            self.db._lock.release()
            raise
        return self.db

    def __exit__(self, exc_type, *_: Any) -> None:
        try:
            self.db._txn_depth -= 1
            if self.db._txn_depth == 0:
                self.db._txn_thread = None
                try:
                    if exc_type is None:
                        try:
                            retry_call(self._commit, policy=TXN_RETRY,
                                       classify=is_sqlite_busy,
                                       label="txn-commit")
                        except BaseException:
                            # a COMMIT that stayed busy past the budget
                            # leaves the transaction open: roll it back so
                            # the connection is reusable, then surface it
                            try:
                                self.db._conn.execute("ROLLBACK")
                            except sqlite3.Error:
                                pass
                            raise
                    else:
                        self.db._conn.execute("ROLLBACK")
                finally:
                    # buffered row-journal notes become drainable only now
                    # (commit OR rollback: the rows are visible or
                    # unchanged — either way a re-select is truthful)
                    journal = self.db._journal
                    if journal is not None:
                        journal.publish_thread(threading.get_ident())
        finally:
            self.db._lock.release()
