"""Library database schema.

Mirrors the reference's 26-model Prisma schema (core/prisma/schema.prisma) —
op-log tables :21-54, Instance :73, Location :130, FilePath :154, Object :204,
MediaData :296, Tag/Label/Space/Album + link tables :323-464, Job :407,
IndexerRule :482, Preference :509, Notification :516 — with sync annotations
(the ``/// @shared(id:..)`` / ``@local`` / ``@relation(item,group)``
doc-comments that sd-sync-generator consumes) carried as ``SYNC`` class
attributes so the CRDT layer needs no codegen.

Deviations from the reference, deliberate:
  - ``pub_id`` is stored as a TEXT uuid (the reference stores raw uuid Bytes;
    TEXT keys are debuggable and SQLite-index-friendly, and the sync protocol
    is ours to define).
  - ``inode``/``device`` are INTEGERs (SQLite INTEGER is i64; the reference
    works around prisma's lack of u64 with Bytes, schema.prisma:180-181).
  - ``size_in_bytes`` keeps only the non-deprecated bytes form, as INTEGER.
"""

from __future__ import annotations

from .base import Field, Model, Relation, Shared

_I = "INTEGER"
_T = "TEXT"
_B = "BOOLEAN"
_D = "DATETIME"
_BY = "BYTES"
_J = "JSON"


def _pk() -> Field:
    return Field(_I, primary_key=True, autoincrement=True)


def _pub_id() -> Field:
    return Field(_T, nullable=False, unique=True)


# ---- sync op log (schema.prisma:21-54) -----------------------------------


class SharedOperationRow(Model):
    TABLE = "shared_operation"
    FIELDS = {
        "id": Field(_T, primary_key=True),  # op uuid
        "timestamp": Field(_I, nullable=False),  # NTP64 HLC
        "model": Field(_T, nullable=False),
        "record_id": Field(_T, nullable=False),
        "kind": Field(_T, nullable=False),  # c | u:<field> | d
        "data": Field(_J),
        "instance_id": Field(_I, nullable=False, references="instance.id", on_delete="RESTRICT"),
    }
    #: (timestamp, id) serves get_ops' ORDER BY + LIMIT without a sort
    INDEXES = (("instance_id", "timestamp"), ("model", "record_id"),
               ("timestamp", "id"))


class RelationOperationRow(Model):
    TABLE = "relation_operation"
    FIELDS = {
        "id": Field(_T, primary_key=True),
        "timestamp": Field(_I, nullable=False),
        "relation": Field(_T, nullable=False),
        "item_id": Field(_T, nullable=False),
        "group_id": Field(_T, nullable=False),
        "kind": Field(_T, nullable=False),
        "data": Field(_J),
        "instance_id": Field(_I, nullable=False, references="instance.id", on_delete="RESTRICT"),
    }
    INDEXES = (("instance_id", "timestamp"),
               ("relation", "item_id", "group_id"), ("timestamp", "id"))


# ---- identity / stats (schema.prisma:57-127) -----------------------------


class NodeRow(Model):
    """Deprecated in the reference (schema.prisma:56-68) but kept for parity."""

    TABLE = "node"
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T, nullable=False),
        "platform": Field(_I, nullable=False),
        "date_created": Field(_D, nullable=False),
        "identity": Field(_BY),
    }


class Instance(Model):
    """A paired `.db` instance of this library (schema.prisma:70-97).
    ``timestamp`` persists the per-instance HLC clock (sync ingest.rs:136-159)."""

    TABLE = "instance"
    SYNC = None  # @local(id: pub_id)
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "identity": Field(_T, nullable=False),  # IdentityOrRemoteIdentity encoding
        # owning NODE's RemoteIdentity (proven by the p2p handshake) — the
        # authorization anchor for sync sessions + files-over-p2p
        "node_remote_identity": Field(_T),
        "node_id": Field(_T, nullable=False),
        "node_name": Field(_T, nullable=False),
        "node_platform": Field(_I, nullable=False),
        "last_seen": Field(_D, nullable=False),
        "date_created": Field(_D, nullable=False),
        "timestamp": Field(_I),
    }


class Statistics(Model):
    TABLE = "statistics"
    FIELDS = {
        "id": _pk(),
        "date_captured": Field(_D, nullable=False),
        "total_object_count": Field(_I, default=0),
        "library_db_size": Field(_T, default="0"),
        "total_bytes_used": Field(_T, default="0"),
        "total_bytes_capacity": Field(_T, default="0"),
        "total_unique_bytes": Field(_T, default="0"),
        "total_bytes_free": Field(_T, default="0"),
        "preview_media_bytes": Field(_T, default="0"),
    }


class Volume(Model):
    TABLE = "volume"
    SYNC = None  # @local
    FIELDS = {
        "id": _pk(),
        "name": Field(_T, nullable=False),
        "mount_point": Field(_T, nullable=False),
        "total_bytes_capacity": Field(_T, default="0"),
        "total_bytes_available": Field(_T, default="0"),
        "disk_type": Field(_T),
        "filesystem": Field(_T),
        "is_system": Field(_B, default=0),
        "date_modified": Field(_D),
    }
    UNIQUES = (("mount_point", "name"),)


# ---- core domain (schema.prisma:129-318) ---------------------------------


class Location(Model):
    TABLE = "location"
    SYNC = Shared(id="pub_id")
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "path": Field(_T),
        "total_capacity": Field(_I),
        "available_capacity": Field(_I),
        "is_archived": Field(_B),
        "generate_preview_media": Field(_B),
        "sync_preview_media": Field(_B),
        "hidden": Field(_B),
        "date_created": Field(_D),
        # declared FK so sync emission rewrites it as an instance-pub_id ref
        # (a raw local int would mis-attribute ownership on mirrored nodes)
        "instance_id": Field(_I, references="instance.id",
                             on_delete="SET NULL"),
        # TPU-native: which hasher backend identifies files in this location
        # ("cpu" | "tpu"), the `hasher = "tpu"` flag of BASELINE.json
        "hasher": Field(_T, default="tpu"),
    }


class FilePath(Model):
    TABLE = "file_path"
    SYNC = Shared(id="pub_id")
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "is_dir": Field(_B),
        "cas_id": Field(_T),
        "integrity_checksum": Field(_T),
        "location_id": Field(_I, references="location.id",
                             on_delete="CASCADE"),
        "materialized_path": Field(_T),
        "name": Field(_T),
        "extension": Field(_T),
        "hidden": Field(_B),
        "size_in_bytes": Field(_I),
        "inode": Field(_I),
        "device": Field(_I),
        "object_id": Field(_I, references="object.id", on_delete="SET NULL"),
        "key_id": Field(_I),  # no key table yet (keymanager keeps its own store)
        "date_created": Field(_D),
        "date_modified": Field(_D),
        "date_indexed": Field(_D),
    }
    UNIQUES = (
        ("location_id", "materialized_path", "name", "extension"),
        ("location_id", "inode", "device"),
    )
    # serving-tier read-path indexes (ISSUE 11 satellite): the explorer's
    # directory listing filters on materialized_path WITHOUT a location
    # (plain prefix index), the watcher/identifier/rename sweeps run
    # ``location_id = ? AND materialized_path LIKE 'prefix%'`` (the NOCASE
    # collation is what lets SQLite's LIKE optimization turn the default
    # case-insensitive LIKE into an index range scan), and the pathsCount
    # badge COUNTs over (location_id, hidden) — covering, index-only
    INDEXES = (("location_id",), ("location_id", "materialized_path"),
               ("cas_id",), ("object_id",),
               ("materialized_path", "is_dir", "name"),
               ("location_id", "materialized_path COLLATE NOCASE"),
               ("location_id", "hidden"))


class Object(Model):
    TABLE = "object"
    SYNC = Shared(id="pub_id")
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "kind": Field(_I),
        "key_id": Field(_I),
        "hidden": Field(_B),
        "favorite": Field(_B),
        "important": Field(_B),
        "note": Field(_T),
        "date_created": Field(_D),
        "date_accessed": Field(_D),
    }


class MediaData(Model):
    TABLE = "media_data"
    FIELDS = {
        "id": _pk(),
        "dimensions": Field(_J),
        "media_date": Field(_T),
        "media_location": Field(_J),
        "camera_data": Field(_J),
        "artist": Field(_T),
        "description": Field(_T),
        "copyright": Field(_T),
        "exif_version": Field(_T),
        # audio/video stream metadata (ffprobe extractor; the reference's
        # audio_data/video_data are stubs — schema.prisma:296 MediaData)
        "duration_seconds": Field("REAL"),
        "bit_rate": Field(_I),
        "streams": Field(_J),
        "object_id": Field(_I, nullable=False, unique=True, references="object.id", on_delete="CASCADE"),
    }


# ---- tags / labels / spaces / albums (schema.prisma:320-464) --------------


class Tag(Model):
    TABLE = "tag"
    SYNC = Shared(id="pub_id")
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "color": Field(_T),
        "redundancy_goal": Field(_I),
        "date_created": Field(_D),
        "date_modified": Field(_D),
    }


class TagOnObject(Model):
    TABLE = "tag_on_object"
    SYNC = Relation(item="tag", group="object")
    FIELDS = {
        "tag_id": Field(_I, nullable=False, references="tag.id", on_delete="RESTRICT"),
        "object_id": Field(_I, nullable=False, references="object.id", on_delete="RESTRICT"),
    }
    UNIQUES = (("tag_id", "object_id"),)


class Label(Model):
    TABLE = "label"
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "date_created": Field(_D),
        "date_modified": Field(_D),
    }


class LabelOnObject(Model):
    TABLE = "label_on_object"
    FIELDS = {
        "date_created": Field(_D),
        "label_id": Field(_I, nullable=False, references="label.id", on_delete="RESTRICT"),
        "object_id": Field(_I, nullable=False, references="object.id", on_delete="RESTRICT"),
    }
    UNIQUES = (("label_id", "object_id"),)


class Space(Model):
    TABLE = "space"
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "description": Field(_T),
        "date_created": Field(_D),
        "date_modified": Field(_D),
    }


class ObjectInSpace(Model):
    TABLE = "object_in_space"
    FIELDS = {
        "space_id": Field(_I, nullable=False, references="space.id", on_delete="RESTRICT"),
        "object_id": Field(_I, nullable=False, references="object.id", on_delete="RESTRICT"),
    }
    UNIQUES = (("space_id", "object_id"),)


class Album(Model):
    TABLE = "album"
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "is_hidden": Field(_B),
        "date_created": Field(_D),
        "date_modified": Field(_D),
    }


class ObjectInAlbum(Model):
    TABLE = "object_in_album"
    FIELDS = {
        "date_created": Field(_D),
        "album_id": Field(_I, nullable=False, references="album.id", on_delete="RESTRICT"),
        "object_id": Field(_I, nullable=False, references="object.id", on_delete="RESTRICT"),
    }
    UNIQUES = (("album_id", "object_id"),)


# ---- jobs (schema.prisma:407-436) ----------------------------------------


class JobRow(Model):
    """Persisted job reports; ``data`` holds the serialized checkpoint state for
    pause/resume (job/report.rs:41-62), ``parent_id`` chains job pipelines."""

    TABLE = "job"
    FIELDS = {
        "id": Field(_T, primary_key=True),  # job uuid
        "name": Field(_T),
        "action": Field(_T),
        "status": Field(_I),
        "errors_text": Field(_T),
        "data": Field(_BY),
        "metadata": Field(_J),
        "parent_id": Field(_T),
        "task_count": Field(_I),
        "completed_task_count": Field(_I),
        "date_estimated_completion": Field(_D),
        "date_created": Field(_D),
        "date_started": Field(_D),
        "date_completed": Field(_D),
    }
    INDEXES = (("status",), ("parent_id",))


# ---- indexer rules (schema.prisma:482-506) -------------------------------


class IndexerRule(Model):
    TABLE = "indexer_rule"
    FIELDS = {
        "id": _pk(),
        "pub_id": _pub_id(),
        "name": Field(_T),
        "default": Field(_B),
        "rules_per_kind": Field(_J),
        "date_created": Field(_D),
        "date_modified": Field(_D),
    }


class IndexerRulesInLocation(Model):
    TABLE = "indexer_rule_in_location"
    FIELDS = {
        "location_id": Field(_I, nullable=False, references="location.id", on_delete="RESTRICT"),
        "indexer_rule_id": Field(_I, nullable=False, references="indexer_rule.id", on_delete="RESTRICT"),
    }
    UNIQUES = (("location_id", "indexer_rule_id"),)


# ---- prefs / notifications (schema.prisma:508-524) -----------------------


class Preference(Model):
    TABLE = "preference"
    SYNC = Shared(id="key")
    SYNC_SKIP = ()
    FIELDS = {
        "key": Field(_T, primary_key=True),
        "value": Field(_J),
    }


class Notification(Model):
    TABLE = "notification"
    FIELDS = {
        "id": _pk(),
        "read": Field(_B, default=0),
        "data": Field(_J, nullable=False),
        "expires_at": Field(_D),
    }


class NearDuplicate(Model):
    """Near-duplicate pair found by the MinHash detector (this framework's
    extension — the reference only collapses exact cas_id matches). Derived,
    local-only data (like thumbnails): not synced, rebuilt by rescans, rows
    cascade away with their file_paths."""

    TABLE = "near_duplicate"
    FIELDS = {
        "id": _pk(),
        "file_path_a_id": Field(_I, nullable=False,
                                references="file_path.id", on_delete="CASCADE"),
        "file_path_b_id": Field(_I, nullable=False,
                                references="file_path.id", on_delete="CASCADE"),
        "similarity": Field("REAL", nullable=False),
        "date_detected": Field(_D),
    }
    UNIQUES = (("file_path_a_id", "file_path_b_id"),)


class ChunkManifest(Model):
    """One content-defined chunk of an object (ops/cdc.py gear chunker;
    this framework's extension — the reference has no sub-file identity).
    Row-per-chunk so the chunk-hash inverted map is one indexed GROUP BY.
    Derived, local-only data like NearDuplicate: not synced, rebuilt by
    rescans (the manifest stage overwrites per object), rows cascade away
    with their objects — but RowJournal-noted so the device query engine
    sees manifest churn."""

    TABLE = "chunk_manifest"
    FIELDS = {
        "id": _pk(),
        "object_id": Field(_I, nullable=False,
                           references="object.id", on_delete="CASCADE"),
        "seq": Field(_I, nullable=False),
        "chunk_hash": Field(_T, nullable=False),
        "length": Field(_I, nullable=False),
    }
    UNIQUES = (("object_id", "seq"),)
    INDEXES = (("chunk_hash",),)


ALL_MODELS: tuple[type[Model], ...] = (
    Instance,  # referenced by op-log tables, create first
    SharedOperationRow,
    RelationOperationRow,
    NodeRow,
    Statistics,
    Volume,
    Location,
    FilePath,
    Object,
    MediaData,
    Tag,
    TagOnObject,
    Label,
    LabelOnObject,
    Space,
    ObjectInSpace,
    Album,
    ObjectInAlbum,
    JobRow,
    IndexerRule,
    IndexerRulesInLocation,
    Preference,
    Notification,
    NearDuplicate,
    ChunkManifest,
)

SYNCED_MODELS: dict[str, type[Model]] = {
    m.TABLE: m for m in ALL_MODELS if m.SYNC is not None
}
