"""Declarative SQLite model layer (replaces prisma-client-rust + sync-generator)."""

from .base import MODEL_REGISTRY, Database, Field, Model, Relation, Shared, utc_now
from .schema import *  # noqa: F401,F403
from .schema import ALL_MODELS, SYNCED_MODELS  # noqa: F401
