"""Deterministic, seedable fault injection (the chaos seam layer).

Production-scale scanning treats partial failure as the steady state; this
package makes every failure mode *rehearsable*. A fault plan is armed from
``SD_FAULTS`` (grammar in :mod:`.spec`; seed via ``SD_FAULTS_SEED``) and
consulted at named seams in the hot paths (kinds include ``enospc`` for
the full-disk story and ``kill`` — a literal SIGKILL at the seam — for
the crash-recovery harness):

    from spacedrive_tpu import faults
    faults.inject("gather", key=str(path))   # no-op unless armed

Zero overhead when unset: ``inject`` is one module-global read and an
immediate return — no env lookup, no dict walk, nothing allocated. The
plan is parsed once (at import from the environment, or by
:func:`install`/:func:`reload` in tests and benches).

The taxonomy the seams synthesize (transient vs fatal, and which layer
absorbs what) is documented in docs/architecture/robustness.md.
"""

from __future__ import annotations

import logging
import os

from .spec import (INJECTED_ATTR, KINDS, DeviceWedgeError, FaultInjected,
                   FaultPlan, FaultSpecError, IngestOverloadError,
                   PeerBusyError)

__all__ = [
    "DeviceWedgeError", "FaultInjected", "FaultPlan", "FaultSpecError",
    "INJECTED_ATTR", "IngestOverloadError", "KINDS", "PeerBusyError",
    "active", "clear", "fired", "inject", "install", "is_injected",
    "reload", "seam_armed",
]

logger = logging.getLogger(__name__)

_PLAN: FaultPlan | None = None


def install(spec: str, seed: int | None = None) -> FaultPlan:
    """Arm a plan programmatically (tests, bench chaos mode)."""
    global _PLAN
    if seed is None:
        seed = _seed_from_env()
    _PLAN = FaultPlan(spec, seed=seed)
    logger.warning("fault injection ARMED: %s (seed %d)", spec, seed)
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def reload() -> FaultPlan | None:
    """Re-read ``SD_FAULTS`` (after an in-process env change)."""
    global _PLAN
    spec = os.environ.get("SD_FAULTS", "").strip()
    _PLAN = FaultPlan(spec, seed=_seed_from_env()) if spec else None
    if _PLAN is not None:
        logger.warning("fault injection ARMED from env: %s", spec)
    return _PLAN


def active() -> FaultPlan | None:
    return _PLAN


def seam_armed(seam: str) -> bool:
    """True when the armed plan carries rules for ``seam`` — hot paths with
    a batch-granular fast lane (the native gather) use this to fall back to
    their per-item path so per-item rules keep their semantics."""
    return _PLAN is not None and _PLAN.has_seam(seam)


def inject(seam: str, key: str = "") -> None:
    """The seam entry point: raise/hang if an armed rule fires, else no-op."""
    plan = _PLAN
    if plan is None:
        return
    plan.check(seam, key)


def fired() -> dict[str, int]:
    plan = _PLAN
    return plan.fired() if plan is not None else {}


def is_injected(exc: BaseException) -> bool:
    return getattr(exc, INJECTED_ATTR, False)


def _seed_from_env() -> int:
    try:
        return int(os.environ.get("SD_FAULTS_SEED", "0"))
    except ValueError:
        return 0


# arm from the environment once at import — chaos runs set SD_FAULTS before
# the process starts, so seam checks never touch os.environ again
try:
    reload()
except FaultSpecError:
    logger.exception("SD_FAULTS spec rejected; fault injection DISARMED")
    _PLAN = None

# the link-level network fault model is a sibling dimension (SD_NET_PLAN);
# importing it here arms it from the environment alongside SD_FAULTS
from . import net  # noqa: E402  (import-time arming is the point)
