"""Fault-spec grammar and the deterministic, seedable fault plan.

A spec is a ``;``-separated list of rules, each ``seam:kind[:trigger]``:

    SD_FAULTS="gather:eio:0.01;hash:wedge:once;commit:sqlite_busy:3"

- **seam** — the named injection point (`faults.inject("<seam>")` sites).
  Installed seams: ``gather`` (per-file cas sample read), ``hash`` (the
  identifier's hash dispatch; ``hash_dispatch`` is an accepted alias,
  normalized at parse), ``commit`` (DB transaction begin/commit),
  ``sync_apply`` (CRDT op materialization), ``sync_ingest`` (the receive
  path's admission check — kind ``overload`` synthesizes budget
  exhaustion there), ``p2p_send`` (outbound peer requests; kind ``busy``
  synthesizes a peer's BUSY answer), ``relay_probe`` (the jax_guard relay
  liveness check), ``chunk`` (the manifest stage: per-file payload reads
  — inside the transient retry, so ``eio`` storms retry clean — and the
  CDC dispatch, where ``wedge`` exercises the chunk router's degrade
  ladder), ``manifest_commit`` (inside the identifier's transaction just
  before the chunk_manifest writes — the kill matrix pins a SIGKILL
  there). The set is open: any string names a seam; rules for seams that
  never fire are inert.
- **kind** — which failure to synthesize (:data:`KINDS`); each maps to
  the exception class the real failure mode raises, so the production
  handlers are exercised, not test doubles. ``hang`` blocks instead of
  raising (the wedged-device failure mode).
- **trigger** — when the rule fires at a seam hit:
    * absent            → every hit
    * ``once``          → the first hit only
    * integer ``N``     → the first N hits
    * ``skipN``         → every hit AFTER the first N (pins a ``kill`` to
      an exact mid-workload point: ``commit:kill:skip3`` dies at the 4th
      transaction seam hit)
    * float ``p``       → each hit independently with probability p,
      drawn from the rule's own seeded RNG (``SD_FAULTS_SEED``, default
      0) — two runs with the same seed and the same call sequence fire
      identically.

The plan is process-global and thread-safe; counters/RNGs live per rule
under one lock, so concurrent pipeline stages draw a deterministic
sequence per seam (each installed seam is hit from a single thread).
"""

from __future__ import annotations

import errno as _errno
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from random import Random
from typing import Callable

from .. import telemetry

#: every fired rule is visible to operators, not only to chaos benches:
#: ``sd_faults_fired_total{seam,kind}`` on the unified registry
_FIRED_TOTAL = telemetry.counter(
    "sd_faults_fired_total", "injected faults fired, per seam:kind",
    labels=("seam", "kind"))


class FaultInjected(RuntimeError):
    """Generic injected crash (kind ``crash``) — classified transient
    (``sd_transient``) so stage supervision checkpoint-pauses on it."""

    sd_transient = True


class DeviceWedgeError(RuntimeError):
    """Injected device wedge (kind ``wedge``): the mid-batch hasher
    degradation ladder (device → native CPU) must absorb it."""

    sd_transient = True


class PeerBusyError(RuntimeError):
    """A peer shed our request with an explicit BUSY answer (admission
    control) — kind ``busy`` synthesizes it at the ``p2p_send`` seam. The
    caller backs off for ``retry_after_ms`` and resumes from its
    acknowledged watermark; it must never treat BUSY as a dead peer."""

    sd_transient = True
    sd_busy = True

    def __init__(self, msg: str, retry_after_ms: int = 250) -> None:
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class IngestOverloadError(RuntimeError):
    """Injected admission-budget exhaustion (kind ``overload``, seam
    ``sync_ingest``): forces the receive path's admission check to shed
    the window exactly as a real over-budget node would."""

    sd_transient = True


#: sentinel marker on every injected exception so reports/tests can tell
#: synthesized faults from organic ones
INJECTED_ATTR = "sd_injected"

#: how long a ``hang`` fault blocks; the pipeline drain must give up on
#: the thread long before this (it is the "never returns" simulation)
HANG_S = 3600.0


def _stall_s() -> float:
    """How long a ``stall`` fault sleeps before returning normally — the
    "slow, not broken" failure mode (cold cache, lock convoy, GC pause).
    The serving-tier slow-request ring is gated on exactly this shape."""
    try:
        return max(0.0, float(os.environ.get("SD_FAULT_STALL_S", "0.3")))
    except ValueError:
        return 0.3


def _oserror(no: int, msg: str) -> Callable[[str], BaseException]:
    def make(key: str) -> BaseException:
        exc = OSError(no, f"{msg} [injected{': ' + key if key else ''}]")
        return exc
    return make


def _mk(cls: type[BaseException], msg: str) -> Callable[[str], BaseException]:
    def make(key: str) -> BaseException:
        return cls(f"{msg} [injected{': ' + key if key else ''}]")
    return make


KINDS: dict[str, Callable[[str], BaseException]] = {
    "eio": _oserror(_errno.EIO, "I/O error"),
    "eintr": _oserror(_errno.EINTR, "interrupted system call"),
    "enoent": lambda key: FileNotFoundError(
        _errno.ENOENT, f"no such file [injected{': ' + key if key else ''}]"),
    "eacces": lambda key: PermissionError(
        _errno.EACCES, f"permission denied [injected{': ' + key if key else ''}]"),
    "enospc": _oserror(_errno.ENOSPC, "no space left on device"),
    "truncate": _mk(EOFError, "short read"),
    "sqlite_busy": _mk(sqlite3.OperationalError, "database is locked"),
    "wedge": _mk(DeviceWedgeError, "device wedge"),
    "crash": _mk(FaultInjected, "injected crash"),
    "flap": _mk(ConnectionRefusedError, "connection refused"),
    "busy": _mk(PeerBusyError, "peer busy"),
    "overload": _mk(IngestOverloadError, "ingest overload"),
    "hang": None,  # type: ignore[dict-item]  # blocks, never raises
    "kill": None,  # type: ignore[dict-item]  # SIGKILLs the process
    "stall": None,  # type: ignore[dict-item]  # sleeps STALL_S, then returns
}


class FaultSpecError(ValueError):
    """Malformed SD_FAULTS spec — raised at parse, never at a seam."""


#: spelling aliases accepted in specs (normalized at parse, so ``fired()``
#: and the telemetry series always carry the canonical seam name): the
#: identifier's hash-dispatch seam reads naturally either way
SEAM_ALIASES = {"hash_dispatch": "hash"}


@dataclass
class FaultRule:
    seam: str
    kind: str
    #: "always" | "count" | "prob" | "skip"
    mode: str
    remaining: int = 0
    prob: float = 0.0
    rng: Random = field(default_factory=Random)
    fired: int = 0

    def should_fire(self) -> bool:
        """Caller holds the plan lock."""
        if self.mode == "count":
            if self.remaining <= 0:
                return False
            self.remaining -= 1
        elif self.mode == "prob":
            if self.rng.random() >= self.prob:
                return False
        elif self.mode == "skip":
            # fire on every hit AFTER the first N — how the crash harness
            # pins a kill to "the (N+1)th transaction commit" exactly
            if self.remaining > 0:
                self.remaining -= 1
                return False
        self.fired += 1
        return True


class FaultPlan:
    """Parsed, armed rules; ``check()`` is the hot seam entry point."""

    def __init__(self, spec: str, seed: int = 0) -> None:
        self.spec = spec
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: dict[str, list[FaultRule]] = {}
        for i, raw in enumerate(p for p in spec.split(";") if p.strip()):
            rule = self._parse_rule(raw.strip(), i, seed)
            self._rules.setdefault(rule.seam, []).append(rule)
        if not self._rules:
            raise FaultSpecError(f"empty fault spec {spec!r}")

    @staticmethod
    def _parse_rule(raw: str, index: int, seed: int) -> FaultRule:
        parts = raw.split(":")
        if len(parts) not in (2, 3):
            raise FaultSpecError(
                f"rule {raw!r}: expected seam:kind[:trigger]")
        seam, kind = parts[0].strip(), parts[1].strip()
        seam = SEAM_ALIASES.get(seam, seam)
        if kind not in KINDS:
            raise FaultSpecError(
                f"rule {raw!r}: unknown kind {kind!r} "
                f"(known: {', '.join(sorted(KINDS))})")
        rng = Random(f"{seed}:{index}:{seam}:{kind}")
        if len(parts) == 2:
            return FaultRule(seam, kind, "always", rng=rng)
        trig = parts[2].strip()
        if trig == "once":
            return FaultRule(seam, kind, "count", remaining=1, rng=rng)
        if trig.startswith("skip"):
            try:
                n = int(trig[4:])
            except ValueError:
                raise FaultSpecError(
                    f"rule {raw!r}: skip trigger must be 'skip<N>'") from None
            if n < 0:
                raise FaultSpecError(f"rule {raw!r}: skip count must be >= 0")
            return FaultRule(seam, kind, "skip", remaining=n, rng=rng)
        try:
            if "." in trig:
                p = float(trig)
                if not 0.0 < p <= 1.0:
                    raise FaultSpecError(
                        f"rule {raw!r}: probability must be in (0, 1]")
                return FaultRule(seam, kind, "prob", prob=p, rng=rng)
            n = int(trig)
            if n < 1:
                raise FaultSpecError(f"rule {raw!r}: count must be >= 1")
            return FaultRule(seam, kind, "count", remaining=n, rng=rng)
        except ValueError as e:
            if isinstance(e, FaultSpecError):
                raise
            raise FaultSpecError(
                f"rule {raw!r}: trigger must be 'once', an int count, or a "
                f"float probability") from None

    def has_seam(self, seam: str) -> bool:
        return seam in self._rules

    def check(self, seam: str, key: str = "") -> None:
        """Raise (or hang) if an armed rule for ``seam`` fires. At most ONE
        rule fires per hit (first in spec order): a hit can only fail one
        way, and co-armed once/count rules must not silently drain their
        budgets behind the rule that actually surfaced."""
        rules = self._rules.get(seam)
        if not rules:
            return
        fired_rule = None
        with self._lock:
            for r in rules:
                if r.should_fire():
                    fired_rule = r
                    break
        if fired_rule is None:
            return
        _FIRED_TOTAL.inc(seam=fired_rule.seam, kind=fired_rule.kind)
        # the firing is a flight-recorder event too: a chaos run tailed
        # live shows WHERE the storm is biting, not just how often
        telemetry.event("fault.fired", seam=fired_rule.seam,
                        kind=fired_rule.kind, key=key)
        if fired_rule.kind == "stall":
            # slow-not-broken: sleep a bounded window, then continue — the
            # call SUCCEEDS late (latency injection for the serving tier)
            time.sleep(_stall_s())
            return
        if fired_rule.kind == "hang":
            # the "never returns" failure mode (wedged tunnel, dead NFS):
            # block far past any drain deadline; daemon stage threads die
            # with the process
            threading.Event().wait(HANG_S)
            return
        if fired_rule.kind == "kill":
            # the real-crash failure mode: SIGKILL this process AT the seam
            # (no atexit, no flushes — exactly what the kernel OOM killer or
            # a power cut does). The crash-recovery harness arms this with a
            # skipN trigger to die mid-group-commit / mid-gather / mid-sync-
            # window deterministically.
            import os as _os
            import signal as _signal

            _os.kill(_os.getpid(), _signal.SIGKILL)
            threading.Event().wait(HANG_S)  # never reached; belt-and-braces
            return
        exc = KINDS[fired_rule.kind](key)
        setattr(exc, INJECTED_ATTR, True)
        raise exc

    def fired(self) -> dict[str, int]:
        """``{"seam:kind": hits}`` — for chaos benches and tests."""
        with self._lock:
            return {f"{r.seam}:{r.kind}": r.fired
                    for rules in self._rules.values() for r in rules
                    if r.fired}
