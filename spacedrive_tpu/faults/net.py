"""Link-level network fault model: the WAN as a first-class faults dimension.

``SD_FAULTS`` (spec.py) injects *node-local* failures — a flap at the dial,
a busy answer, a SIGKILL at a seam. Every peer pair is still a perfect
zero-latency pipe, so partitions, asymmetric loss, and slow links went
untested. This module models the **link itself**: a :class:`NetModel` keyed
by (src, dst) peer identity holds scheduled latency/jitter, probabilistic
drop, delay-modeled reorder, a bandwidth cap, and timed partition/heal
windows. The transport seams (``tests/fleet_harness.py`` wire-less sessions
and the ``p2p/nlm.py`` originate/responder paths) call :func:`link` — the
``p2p_link`` inject point — once per message traversal, so every push
window, BUSY frame, and hash batch crosses a modeled link.

Grammar (``SD_NET_PLAN``, rules ``;``-separated; seed via ``SD_NET_SEED``)::

    SD_NET_PLAN="*>*:lat=5,jitter=2,drop=0.01,bw=4MBps;part:peer-0*|*:@1.0+2.5"

- **link rule** — ``<srcpat>><dstpat>:<k>=<v>[,<k>=<v>...]``; patterns are
  ``fnmatch`` globs over peer identities, first matching rule wins (like
  SD_FAULTS, at most one rule shapes a traversal). Keys:
    * ``lat``     — base one-way latency; plain number = milliseconds,
      ``ms``/``s`` suffixes accepted (``lat=5``, ``lat=0.2s``)
    * ``jitter``  — ± uniform latency jitter, same units
    * ``drop``    — per-message drop probability in (0, 1]
    * ``reorder`` — probability a message is delivered LATE (an extra
      2×lat hold — the delay model of reordering: meaningful when
      concurrent streams share the link, pure jitter on a serial one)
    * ``bw``      — bandwidth cap as serialization delay, ``<float>``
      bytes/s with ``KBps``/``MBps``/``GBps`` (decimal) suffixes
- **partition rule** — ``part:<apat>|<bpat>:@<start>+<dur>`` cuts every
  link between a peer matching ``apat`` and one matching ``bpat`` (BOTH
  directions) during ``[start, start+dur)`` seconds from the model epoch
  (:meth:`NetModel.reset_epoch`; the fleet harness resets it at storm
  start so windows are storm-relative). Any number of windows; a link is
  cut while ANY window covers it.

Determinism: every (rule, concrete link) pair owns a seeded RNG
(``Random(f"{seed}:{rule_index}:{src}>{dst}")``) and each traversal draws
jitter → drop → reorder in fixed order, so two runs with the same seed,
plan, and per-link call sequence make identical decisions — the per-link
delivery :meth:`ledger` (seq, verdict, delay) is the byte-comparable proof
the determinism gate in tests/test_wan.py diffs. Partition membership is
time-based; tests that need partition determinism inject a virtual clock.

Verdicts surface as transient exceptions (:class:`LinkDropped`,
:class:`LinkCut` — ``ConnectionError`` subclasses, so the whole retry /
ack-watermark-resume stack absorbs them exactly like a real flap), and as
the bounded-cardinality ``sd_net_link_*`` telemetry families (no per-link
labels: a 64-peer mesh is 4k links).
"""

from __future__ import annotations

import fnmatch
import logging
import os
import threading
import time
from random import Random
from typing import Any, Callable

from .. import telemetry

__all__ = [
    "LinkCut", "LinkDropped", "NetModel", "NetPlanError", "PROFILES",
    "active", "clear", "install", "link", "profile_plan", "reload",
]

logger = logging.getLogger(__name__)

_MESSAGES = telemetry.counter(
    "sd_net_link_messages_total",
    "messages that crossed the modeled network, by verdict "
    "(ok | drop | cut)", labels=("verdict",))
_BYTES = telemetry.counter(
    "sd_net_link_bytes_total",
    "payload bytes delivered across the modeled network")
_DELAY_S = telemetry.counter(
    "sd_net_link_delay_seconds_total",
    "injected link delay (latency + jitter + serialization)")
_PARTITIONS = telemetry.gauge(
    "sd_net_link_partitions_active",
    "partition windows currently cutting at least one link")


class LinkDropped(ConnectionError):
    """The modeled link dropped this message (probabilistic loss). A
    ``ConnectionError`` so the transient taxonomy retries it like a real
    flap; the session resumes from its acknowledged watermark."""


class LinkCut(ConnectionError):
    """The link is inside a partition window — every traversal fails until
    the heal. Transient: the retry/backoff loop keeps the session alive
    across the window and resumes, never restarts."""


class NetPlanError(ValueError):
    """Malformed SD_NET_PLAN — raised at parse/install, never at a seam."""


#: hard sanity cap on one traversal's injected delay (a typo'd plan must
#: not wedge a session for minutes)
MAX_DELAY_S = 30.0

#: per-link delivery-ledger bound; past it only counters advance (the
#: determinism gate uses short runs, the 64-peer soak ~dozens/link)
LEDGER_CAP = 4096


def _parse_duration_ms(raw: str, where: str) -> float:
    raw = raw.strip()
    try:
        if raw.endswith("ms"):
            return float(raw[:-2])
        if raw.endswith("s"):
            return float(raw[:-1]) * 1000.0
        return float(raw)
    except ValueError:
        raise NetPlanError(f"{where}: bad duration {raw!r} "
                           f"(number, 'Nms' or 'Ns')") from None


def _parse_rate(raw: str, where: str) -> float:
    raw = raw.strip()
    mult = 1.0
    for suffix, m in (("GBps", 1e9), ("MBps", 1e6), ("KBps", 1e3)):
        if raw.endswith(suffix):
            raw, mult = raw[: -len(suffix)], m
            break
    try:
        rate = float(raw) * mult
    except ValueError:
        raise NetPlanError(f"{where}: bad rate {raw!r} "
                           f"(bytes/s, KBps/MBps/GBps suffixes)") from None
    if rate <= 0:
        raise NetPlanError(f"{where}: rate must be > 0")
    return rate


class _LinkRule:
    __slots__ = ("index", "src_pat", "dst_pat", "lat_s", "jitter_s",
                 "drop", "reorder", "bw")

    def __init__(self, index: int, src_pat: str, dst_pat: str,
                 body: str) -> None:
        self.index = index
        self.src_pat = src_pat
        self.dst_pat = dst_pat
        self.lat_s = 0.0
        self.jitter_s = 0.0
        self.drop = 0.0
        self.reorder = 0.0
        self.bw = 0.0  # 0 = uncapped
        where = f"link rule {src_pat}>{dst_pat}"
        if not body.strip():
            raise NetPlanError(f"{where}: empty directive list")
        for kv in body.split(","):
            if "=" not in kv:
                raise NetPlanError(f"{where}: directive {kv!r} is not k=v")
            key, val = (s.strip() for s in kv.split("=", 1))
            if key == "lat":
                self.lat_s = _parse_duration_ms(val, where) / 1000.0
            elif key == "jitter":
                self.jitter_s = _parse_duration_ms(val, where) / 1000.0
            elif key in ("drop", "reorder"):
                try:
                    p = float(val)
                except ValueError:
                    raise NetPlanError(
                        f"{where}: {key} must be a probability") from None
                if not 0.0 < p <= 1.0:
                    raise NetPlanError(
                        f"{where}: {key} must be in (0, 1], got {p}")
                setattr(self, key, p)
            elif key == "bw":
                self.bw = _parse_rate(val, where)
            else:
                raise NetPlanError(
                    f"{where}: unknown key {key!r} "
                    f"(known: lat, jitter, drop, reorder, bw)")
        if self.lat_s < 0 or self.jitter_s < 0:
            raise NetPlanError(f"{where}: negative duration")

    def matches(self, src: str, dst: str) -> bool:
        return (fnmatch.fnmatchcase(src, self.src_pat)
                and fnmatch.fnmatchcase(dst, self.dst_pat))


class _PartitionRule:
    __slots__ = ("index", "a_pat", "b_pat", "start_s", "end_s", "announced")

    def __init__(self, index: int, a_pat: str, b_pat: str,
                 window: str) -> None:
        self.index = index
        self.a_pat = a_pat
        self.b_pat = b_pat
        where = f"part rule {a_pat}|{b_pat}"
        window = window.strip()
        if not window.startswith("@") or "+" not in window:
            raise NetPlanError(f"{where}: window must be '@<start>+<dur>'")
        start_raw, dur_raw = window[1:].split("+", 1)
        try:
            start, dur = float(start_raw), float(dur_raw)
        except ValueError:
            raise NetPlanError(
                f"{where}: window bounds must be seconds (floats)") from None
        if start < 0 or dur <= 0:
            raise NetPlanError(
                f"{where}: start must be >= 0 and duration > 0")
        self.start_s = start
        self.end_s = start + dur
        #: 0 = not yet entered, 1 = partition announced, 2 = heal announced
        self.announced = 0

    def covers(self, src: str, dst: str) -> bool:
        """Both directions: a partition severs the pair, not one arrow."""
        return ((fnmatch.fnmatchcase(src, self.a_pat)
                 and fnmatch.fnmatchcase(dst, self.b_pat))
                or (fnmatch.fnmatchcase(src, self.b_pat)
                    and fnmatch.fnmatchcase(dst, self.a_pat)))


class NetModel:
    """Parsed, armed link plan; :meth:`traverse` is the seam entry point.

    ``clock``/``sleep`` are injectable so determinism and partition tests
    drive a virtual timeline; production uses the real monotonic clock."""

    def __init__(self, spec: str, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.spec = spec
        self.seed = seed
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._links: list[_LinkRule] = []
        self._parts: list[_PartitionRule] = []
        #: (rule index, "src>dst") -> per-link seeded RNG
        self._rngs: dict[tuple[int, str], Random] = {}
        #: "src>dst" -> [(seq, verdict, delay_ms)] — the delivery ledger
        self._ledger: dict[str, list[tuple[int, str, float]]] = {}
        self._seq: dict[str, int] = {}
        #: "src>dst" -> delivered payload bytes (additive; never capped)
        self._bytes: dict[str, int] = {}
        self._overflow = 0
        for i, raw in enumerate(p for p in spec.split(";") if p.strip()):
            self._parse_rule(raw.strip(), i)
        if not self._links and not self._parts:
            raise NetPlanError(f"empty net plan {spec!r}")
        self._epoch = self._clock()

    def _parse_rule(self, raw: str, index: int) -> None:
        if raw.startswith("part:"):
            body = raw[len("part:"):]
            groups, sep, window = body.rpartition(":")
            if not sep or "|" not in groups:
                raise NetPlanError(
                    f"rule {raw!r}: expected part:<a>|<b>:@<start>+<dur>")
            a_pat, b_pat = (s.strip() for s in groups.split("|", 1))
            if not a_pat or not b_pat:
                raise NetPlanError(f"rule {raw!r}: empty partition group")
            self._parts.append(_PartitionRule(index, a_pat, b_pat, window))
            return
        head, sep, body = raw.partition(":")
        if not sep or ">" not in head:
            raise NetPlanError(
                f"rule {raw!r}: expected <src>><dst>:<k>=<v>,... "
                f"or part:<a>|<b>:@<start>+<dur>")
        src_pat, dst_pat = (s.strip() for s in head.split(">", 1))
        if not src_pat or not dst_pat:
            raise NetPlanError(f"rule {raw!r}: empty link pattern")
        self._links.append(_LinkRule(index, src_pat, dst_pat, body))

    # -- the seam ------------------------------------------------------------
    def reset_epoch(self) -> None:
        """Re-base partition windows on 'now' (the harness calls this at
        storm start so ``@<start>+<dur>`` is storm-relative, not
        armed-relative) and re-arm their one-shot edge events."""
        with self._lock:
            self._epoch = self._clock()
            for part in self._parts:
                part.announced = 0

    def elapsed(self) -> float:
        return self._clock() - self._epoch

    def traverse(self, src: str, dst: str, nbytes: int = 0) -> float:
        """One message crossing ``src → dst``: raise :class:`LinkCut`
        inside a partition window, :class:`LinkDropped` on probabilistic
        loss, otherwise sleep the modeled delay and return it (seconds)."""
        delay = self.decide(src, dst, nbytes)
        if delay > 0.0:
            self._sleep(delay)
        return delay

    def decide(self, src: str, dst: str, nbytes: int = 0) -> float:
        """The verdict half of :meth:`traverse` — raises cut/drop or
        returns the modeled delay WITHOUT sleeping it. Async callers
        (p2p/nlm.py) use this so the delay rides ``asyncio.sleep`` on the
        event loop instead of parking a shared executor thread per
        message. The decision + ledger + counters are identical either
        way (the delay counter records the delay the caller is contracted
        to sleep)."""
        link = f"{src}>{dst}"
        now = self._clock()
        delay = 0.0
        with self._lock:
            elapsed = now - self._epoch
            verdict = "ok"
            active_parts = 0
            for part in self._parts:
                inside = part.start_s <= elapsed < part.end_s
                if inside:
                    active_parts += 1
                self._announce_locked(part, inside, elapsed)
                if inside and part.covers(src, dst):
                    verdict = "cut"
            _PARTITIONS.set(active_parts)
            rule = next((r for r in self._links if r.matches(src, dst)),
                        None)
            if verdict != "cut" and rule is not None:
                rng = self._rngs.get((rule.index, link))
                if rng is None:
                    rng = Random(f"{self.seed}:{rule.index}:{link}")
                    self._rngs[(rule.index, link)] = rng
                # fixed draw order per traversal — the determinism contract
                jitter = rng.uniform(-rule.jitter_s, rule.jitter_s)
                dropped = rng.random() < rule.drop if rule.drop else False
                late = rng.random() < rule.reorder if rule.reorder else False
                if dropped:
                    verdict = "drop"
                else:
                    delay = max(0.0, rule.lat_s + jitter)
                    if late:
                        delay += 2.0 * rule.lat_s
                    if rule.bw and nbytes:
                        delay += nbytes / rule.bw
                    delay = min(delay, MAX_DELAY_S)
            seq = self._seq.get(link, 0)
            self._seq[link] = seq + 1
            log = self._ledger.setdefault(link, [])
            if len(log) < LEDGER_CAP:
                log.append((seq, verdict, round(delay * 1000.0, 3)))
            else:
                self._overflow += 1
        _MESSAGES.inc(verdict=verdict)
        if verdict == "cut":
            raise LinkCut(f"partition: link {src} -> {dst} is cut "
                          f"[net plan, t={elapsed:.2f}s]")
        if verdict == "drop":
            raise LinkDropped(f"link {src} -> {dst} dropped the message "
                              f"[net plan]")
        if delay > 0.0:
            _DELAY_S.inc(delay)
        if nbytes:
            _BYTES.inc(nbytes)
            # delivered-bytes tally (additive, unbounded — unlike the
            # capped ledger): the delta-transfer gate's bytes-on-wire
            # accounting reads this per link
            with self._lock:
                self._bytes[link] = self._bytes.get(link, 0) + nbytes
        return delay

    def _announce_locked(self, part: _PartitionRule, inside: bool,
                         elapsed: float) -> None:
        """One flight-recorder event per partition edge (lazy: fired by the
        first traversal that observes the transition)."""
        if inside and part.announced == 0:
            part.announced = 1
            telemetry.event("net.partition", groups=f"{part.a_pat}|{part.b_pat}",
                            start_s=part.start_s, end_s=part.end_s)
        elif not inside and part.announced == 1 and elapsed >= part.end_s:
            part.announced = 2
            telemetry.event("net.heal", groups=f"{part.a_pat}|{part.b_pat}",
                            end_s=part.end_s)

    # -- introspection -------------------------------------------------------
    def partitioned(self, src: str, dst: str) -> bool:
        with self._lock:
            elapsed = self._clock() - self._epoch
            return any(p.start_s <= elapsed < p.end_s and p.covers(src, dst)
                       for p in self._parts)

    def last_heal_s(self) -> float:
        """Latest partition-window end, seconds from epoch (0.0 when the
        plan has no partitions) — the bench's heal-to-lag-zero anchor."""
        return max((p.end_s for p in self._parts), default=0.0)

    def ledger(self) -> dict[str, list[tuple[int, str, float]]]:
        """Per-link delivery log ``{"src>dst": [(seq, verdict, delay_ms)]}``
        — identical across runs with the same seed/plan/per-link call
        sequence (the determinism gate's comparator)."""
        with self._lock:
            return {k: list(v) for k, v in self._ledger.items()}

    def drops(self) -> dict[str, list[int]]:
        """Per-link dropped-message seqs (the 'drop set')."""
        with self._lock:
            return {k: [seq for seq, verdict, _ in v if verdict == "drop"]
                    for k, v in self._ledger.items()}

    def bytes_by_link(self) -> dict[str, int]:
        """Delivered payload bytes per link ``{"src>dst": n}`` — the
        delta-transfer gate asserts chunked sends ship strictly less than
        the whole file from exactly this accounting (the capped ledger
        keeps its tuple format; byte totals live here so the determinism
        comparator is untouched)."""
        with self._lock:
            return dict(self._bytes)

    def status(self) -> dict[str, Any]:
        with self._lock:
            verdicts: dict[str, int] = {}
            for log in self._ledger.values():
                for _seq, verdict, _d in log:
                    verdicts[verdict] = verdicts.get(verdict, 0) + 1
            return {"links_seen": len(self._ledger),
                    "messages": sum(self._seq.values()),
                    "verdicts": verdicts,
                    "ledger_overflow": self._overflow,
                    "partitions": len(self._parts),
                    "elapsed_s": round(self._clock() - self._epoch, 3)}


# -- module-level plan (the inject-point fast path) ----------------------------

_MODEL: NetModel | None = None


def install(spec: str, seed: int | None = None,
            clock: Callable[[], float] = time.monotonic,
            sleep: Callable[[float], None] = time.sleep) -> NetModel:
    """Arm a plan programmatically (tests, bench WAN mode)."""
    global _MODEL
    if seed is None:
        seed = _seed_from_env()
    _MODEL = NetModel(spec, seed=seed, clock=clock, sleep=sleep)
    logger.warning("network fault model ARMED: %s (seed %d)", spec, seed)
    return _MODEL


def clear() -> None:
    global _MODEL
    _MODEL = None
    _PARTITIONS.set(0)


def reload() -> NetModel | None:
    """Re-read ``SD_NET_PLAN`` (after an in-process env change)."""
    global _MODEL
    spec = os.environ.get("SD_NET_PLAN", "").strip()
    _MODEL = NetModel(spec, seed=_seed_from_env()) if spec else None
    if _MODEL is not None:
        logger.warning("network fault model ARMED from env: %s", spec)
    return _MODEL


def active() -> NetModel | None:
    return _MODEL


def link(src: str, dst: str, nbytes: int = 0) -> None:
    """The ``p2p_link`` inject point: model one message traversal, or
    no-op (one module-global read) when no plan is armed."""
    model = _MODEL
    if model is None:
        return
    model.traverse(src, dst, nbytes)


async def alink(src: str, dst: str, nbytes: int = 0) -> None:
    """Async traversal for sender-side p2p frames (delta offers, whole-file
    spacedrop blocks, replica queries): ``decide()`` runs inline — a cut or
    drop raises out of the send exactly like :func:`link` — but the modeled
    delay is paid with ``asyncio.sleep`` so one shaped transfer never parks
    the p2p event loop for every other session."""
    model = _MODEL
    if model is None:
        return
    delay = model.decide(src, dst, nbytes)
    if delay > 0.0:
        import asyncio

        await asyncio.sleep(delay)


def _seed_from_env() -> int:
    try:
        return int(os.environ.get("SD_NET_SEED", "0"))
    except ValueError:
        return 0


# -- the shared WAN topology profiles ------------------------------------------
# ONE place for the soak matrices: tests/test_wan.py and ``bench.py --fleet
# --wan <profile>`` both arm these, so the gate and the bench always speak
# the same topology. Peer patterns follow the fleet harness's identity
# scheme (``fleet-peer-NN`` / ``fleet-target``); the wildcard link rule
# covers any identity scheme.

PROFILES: dict[str, str] = {
    # same-switch LAN: sub-ms latency, no loss — the control matrix
    "lan": "*>*:lat=0.2,jitter=0.1",
    # healthy WAN: regional RTT, rare loss, a shaped uplink
    "wan": "*>*:lat=5,jitter=2,drop=0.002,bw=8MBps",
    # hostile WAN: loss + jitter + two partition waves (storm-relative;
    # the first cuts peers 0x from everything, the second peers 1x) —
    # the flaky-wan chaos soak's matrix
    "flaky-wan": ("*>*:lat=3,jitter=2,drop=0.01,bw=4MBps;"
                  "part:fleet-peer-0*|*:@1.0+2.5;"
                  "part:fleet-peer-1*|*:@5.0+2.0"),
}


def profile_plan(name: str) -> str:
    try:
        return PROFILES[name]
    except KeyError:
        raise NetPlanError(
            f"unknown WAN profile {name!r} "
            f"(known: {', '.join(sorted(PROFILES))})") from None


# arm from the environment once at import, like SD_FAULTS
try:
    reload()
except NetPlanError:
    logger.exception("SD_NET_PLAN spec rejected; network model DISARMED")
    _MODEL = None
