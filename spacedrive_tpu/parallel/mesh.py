"""Device mesh + sharded identity hashing — the ICI/DCN compute plane.

The reference's distribution layer is host-side networking (libp2p QUIC,
crates/p2p/src/manager.rs:62-79); its "parallel hashing" is a single worker
with intra-chunk `join_all` concurrency (core/src/object/file_identifier/
mod.rs:107-134). The TPU-native design replaces both on the compute plane:

- a `jax.sharding.Mesh` takes the architectural place of the reference's
  `ManagerStream` event loop for *compute* distribution: chips are addressed
  by named mesh axes, not peer ids;
- the batch ("data") axis shards independent files across chips — the analogue
  of the reference fanning file futures across a thread pool;
- the chunk ("seq") axis shards the *inside* of one huge message across chips
  (sequence parallelism): BLAKE3 phase 1 is chunk-local, and the log-depth
  merkle merge becomes XLA-inserted collectives over ICI at the top levels.
  This is the long-context path used by full-file integrity hashing
  (ObjectValidator, reference core/src/object/validation/hash.rs:24);
- cross-chip dedup (same cas_id appearing on different chips' shards) is an
  all-gather compare inside the jitted step — XLA lays the collective on ICI.

Everything here follows the scaling-book recipe: pick a mesh, annotate in/out
shardings, let XLA insert the collectives. No hand-written NCCL-style p2p.

Multi-host: `init_multihost()` wraps `jax.distributed.initialize`; the same
mesh code then spans hosts with DCN between slices.

Kernel selection: the sharded entry points trace `ops/blake3_jax` wrappers,
so `SD_BLAKE3_KERNEL` (xla|pallas) is captured at FIRST trace per mesh (the
lru_caches below memoize the jitted step) — set it before the first sharded
call, the way dryrun_multichip's subprocess harness does.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.blake3_jax import blake3_batch

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(n_devices: int | None = None, seq: int = 1) -> Mesh:
    """A (data, seq) mesh over the first ``n_devices`` devices.

    ``seq`` chips cooperate on one message's chunk axis (sequence parallel);
    the remaining factor shards the batch axis (data parallel). seq=1 is pure
    data parallelism — the right default for cas_id hashing where every
    message is small and independent.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    if n % seq != 0:
        raise ValueError(f"n_devices {n} not divisible by seq {seq}")
    arr = np.array(devs[:n]).reshape(n // seq, seq)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS))


def _sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


@functools.lru_cache(maxsize=None)
def sharded_hasher(mesh: Mesh):
    """``blake3_batch`` jitted with the batch axis sharded on ``data`` and the
    chunk axis on ``seq``. Digests come back sharded on ``data`` only."""
    return jax.jit(
        blake3_batch,
        in_shardings=(
            _sharding(mesh, None, None, SEQ_AXIS, DATA_AXIS),
            _sharding(mesh, DATA_AXIS),
        ),
        out_shardings=_sharding(mesh, None, DATA_AXIS),
    )


@functools.lru_cache(maxsize=None)
def sharded_row_hasher(mesh: Mesh):
    """Row-major entry (the native gather's layout) with the batch axis
    sharded on ``data``; the device-side permutation runs shard-local."""
    from ..ops.blake3_jax import blake3_batch_rows

    return jax.jit(
        blake3_batch_rows,
        in_shardings=(
            _sharding(mesh, DATA_AXIS, None),
            _sharding(mesh, DATA_AXIS),
        ),
        out_shardings=_sharding(mesh, None, DATA_AXIS),
    )


@functools.lru_cache(maxsize=None)
def identify_step(mesh: Mesh):
    """The framework's full device step: sharded hash + cross-chip dedup.

    Equivalent role to one `file_identifier` step chunk in the reference
    (file_identifier/mod.rs:100-134: hash ≤100 files, then detect which
    cas_ids already collide) — but over every chip of the mesh at once.

    Returns ``(digests (8,B) u32, dup (B,) bool)`` where ``dup[i]`` marks a
    lane whose 64-bit cas prefix already occurred at a lower lane index
    (across *all* chips — the compare is an XLA all-gather over ICI).
    Zero-length lanes are padding: never dup sources nor dup targets.
    """

    def step(words: jax.Array, lengths: jax.Array):
        digests = blake3_batch(words, lengths)
        # cas_id = first 16 hex chars = first two little-endian u32 words
        w0, w1 = digests[0], digests[1]
        valid = lengths > 0
        eq = (w0[:, None] == w0[None, :]) & (w1[:, None] == w1[None, :])
        i = jnp.arange(w0.shape[0])
        earlier = i[:, None] > i[None, :]
        dup = jnp.any(eq & earlier & valid[None, :], axis=1) & valid
        return digests, dup

    return jax.jit(
        step,
        in_shardings=(
            _sharding(mesh, None, None, SEQ_AXIS, DATA_AXIS),
            _sharding(mesh, DATA_AXIS),
        ),
        out_shardings=(
            _sharding(mesh, None, DATA_AXIS),
            _sharding(mesh, DATA_AXIS),
        ),
    )


@functools.lru_cache(maxsize=None)
def sharded_resizer(mesh: Mesh):
    """Batched thumbnail resize with the image batch sharded on ``data``
    (ops/resize_jax.py's matmul-formulated bilinear): each chip resizes its
    shard's images fully locally — embarrassingly parallel, no collectives —
    so a media_processor step's device batch scales linearly across the
    mesh the way the identify step's hashing does."""
    from ..ops.resize_jax import resize_batch

    return jax.jit(
        resize_batch,
        in_shardings=(
            _sharding(mesh, DATA_AXIS, None, None, None),
            _sharding(mesh, DATA_AXIS, None),
            _sharding(mesh, DATA_AXIS, None),
        ),
        out_shardings=_sharding(mesh, DATA_AXIS, None, None, None),
    )


def pad_batch_for_mesh(n: int, mesh: Mesh) -> int:
    """Smallest batch size >= n divisible by the data-axis size."""
    d = mesh.shape[DATA_AXIS]
    return max(d, math.ceil(n / d) * d)


def init_multihost(coordinator: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Multi-host DCN bring-up (analogue of the reference joining its QUIC
    mesh at Node::new, core/src/lib.rs:130). No-op when single-process."""
    if num_processes in (None, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
