"""spacedrive_tpu — a TPU-native virtual-distributed-filesystem engine.

A brand-new framework with the capabilities of Spacedrive's sd-core (reference:
/root/reference, studied in SURVEY.md): content-addressable filesystem indexing
into SQLite libraries, BLAKE3 cas_id dedup, a pausable/checkpointable stateful
job system, CRDT library sync with HLC ordering, p2p block transfer, and a typed
query/mutation/subscription API.

Unlike the reference's CPU-only Rust core, the indexing hot path (the
``file_identifier`` step, reference core/src/object/cas.rs:23-62) is TPU-first:
fixed-shape chunk batches stream into JAX BLAKE3 kernels sharded with
``jax.sharding`` over a device mesh; MinHash dedup reductions ride ``psum`` over
ICI. See ``spacedrive_tpu.ops`` for kernels and ``spacedrive_tpu.parallel`` for
the mesh layer.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  api/        typed router (queries/mutations/subscriptions + invalidation)
  node.py     Node bootstrap: config, event bus, managers, ordered start
  library.py  Library / Libraries manager (per-library DB + sync + identity)
  jobs/       stateful job engine (init/steps/finalize, checkpoint/resume)
  locations/  locations, indexer rules, walker, watcher
  objects/    cas hashing, file_identifier, validator, media, fs ops
  sync/       CRDT ops + HLC + manager/ingest actors
  p2p/        control plane (discovery, pairing, sync sessions, block transfer)
  models/     declarative SQLite model layer (replaces prisma-client-rust)
  ops/        TPU compute: BLAKE3 kernels, MinHash, batched image ops
  parallel/   device mesh, shardings, multi-host init
  utils/      migrator, version manager, misc infra
"""

__version__ = "0.1.0"
