/* App-shaped FFI host — the long-lived consumer the mobile shells are.
 *
 * The reference embeds the core behind handle_core_msg and runs a
 * continuous event listener thread beside the request path
 * (apps/mobile/modules/sd-core/core/src/lib.rs:61-117 + :119's
 * spawn_core_event_listener). This harness is the same composition in
 * plain C against sd_core_ffi.cc: boot, start a pump thread draining
 * sd_core_poll_event concurrently, create a library + location over the
 * JSON bridge, run a full scan, wait for the job chain to settle, list
 * the indexed paths, and only then stop the pump — asserting that
 * job_progress and invalidation events flowed WHILE requests ran.
 *
 * usage: sd_ffi_host <data_dir> <python_path> <tree_to_scan>
 * exit 0 => every step round-tripped and the event flow was observed.
 */
#include <pthread.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

extern int sd_core_init(const char* data_dir, const char* python_path);
extern char* sd_core_msg(const char* json);
extern char* sd_core_poll_event(int timeout_ms);
extern void sd_core_shutdown(void);
extern void sd_core_free(char* s);

static volatile int pump_stop = 0;
static volatile int ev_progress = 0;
static volatile int ev_invalidate = 0;
static volatile int ev_other = 0;

static void* event_pump(void* arg) {
  (void)arg;
  while (!pump_stop) {
    char* ev = sd_core_poll_event(250);
    if (ev && ev[0]) {
      if (strstr(ev, "job_progress")) ev_progress++;
      else if (strstr(ev, "invalidate")) ev_invalidate++;
      else ev_other++;
    }
    sd_core_free(ev);
  }
  return NULL;
}

/* naive field scanners — enough for the bridge's flat JSON envelopes */
static int extract_string(const char* json, const char* field, char* out,
                          size_t cap) {
  char pat[64];
  snprintf(pat, sizeof pat, "\"%s\": \"", field);
  const char* p = strstr(json, pat);
  if (!p) { snprintf(pat, sizeof pat, "\"%s\":\"", field); p = strstr(json, pat); }
  if (!p) return 0;
  p = strchr(p + strlen(pat) - 1, '"') + 1;  /* after opening quote */
  size_t i = 0;
  while (p[i] && p[i] != '"' && i + 1 < cap) { out[i] = p[i]; i++; }
  out[i] = 0;
  return i > 0;
}

static long extract_int(const char* json, const char* field) {
  char pat[64];
  snprintf(pat, sizeof pat, "\"%s\":", field);
  const char* p = strstr(json, pat);
  if (!p) return -1;
  p += strlen(pat);
  while (*p == ' ') p++;
  return strtol(p, NULL, 10);
}

static char* msgf(const char* fmt, ...) {
  char buf[4096];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof buf, fmt, ap);
  va_end(ap);
  return sd_core_msg(buf);
}

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <data_dir> <python_path> <tree>\n", argv[0]);
    return 2;
  }
  if (sd_core_init(argv[1], argv[2]) != 0) {
    fprintf(stderr, "sd_core_init failed\n");
    return 1;
  }

  pthread_t pump;
  pthread_create(&pump, NULL, event_pump, NULL);

  int rc = 1;
  char lib_id[128] = {0};
  char* resp = msgf("{\"id\":1,\"key\":\"libraries.create\","
                    "\"arg\":{\"name\":\"ffi-host\"}}");
  printf("create-lib: %s\n", resp);
  const char* body = resp ? strstr(resp, "\"result\"") : NULL;
  int ok = body != NULL &&
           extract_string(body, "id", lib_id, sizeof lib_id);
  sd_core_free(resp);
  if (!ok) goto done;

  resp = msgf("{\"id\":2,\"key\":\"locations.create\","
              "\"arg\":{\"path\":\"%s\"},\"library_id\":\"%s\"}",
              argv[3], lib_id);
  printf("create-loc: %s\n", resp);
  body = resp ? strstr(resp, "\"result\"") : NULL;
  long loc_id = body ? extract_int(body, "id") : -1;
  ok = body != NULL && loc_id > 0;
  sd_core_free(resp);
  if (!ok) goto done;

  /* locations.create chained the scan (indexer -> identifier -> media);
   * wait for the job chain to settle: reports exist and none running */
  int settled = 0;
  for (int i = 0; i < 300 && !settled; i++) {
    usleep(300 * 1000);
    resp = msgf("{\"id\":4,\"key\":\"jobs.reports\",\"arg\":null,"
                "\"library_id\":\"%s\"}", lib_id);
    if (resp && strstr(resp, "\"name\"") && !strstr(resp, "Running") &&
        !strstr(resp, "Queued"))
      settled = 1;
    sd_core_free(resp);
  }
  if (!settled) { fprintf(stderr, "scan never settled\n"); goto done; }

  resp = msgf("{\"id\":5,\"key\":\"search.paths\","
              "\"arg\":{\"location_id\":%ld},\"library_id\":\"%s\"}",
              loc_id, lib_id);
  long n_items = 0;
  if (resp) {
    for (const char* p = resp; (p = strstr(p, "\"name\"")) != NULL; p++)
      n_items++;
  }
  printf("paths: %ld rows\n", n_items);
  ok = resp && n_items > 0;
  sd_core_free(resp);
  if (!ok) goto done;
  rc = 0;

done:
  /* drain a beat longer so trailing completion events are observed */
  usleep(500 * 1000);
  pump_stop = 1;
  pthread_join(pump, NULL);
  printf("FFI_HOST events: progress=%d invalidate=%d other=%d\n",
         ev_progress, ev_invalidate, ev_other);
  if (rc == 0 && (ev_progress < 1 || ev_invalidate < 1)) {
    fprintf(stderr, "event flow missing (progress=%d invalidate=%d)\n",
            ev_progress, ev_invalidate);
    rc = 1;
  }
  if (rc == 0) printf("FFI_HOST_OK\n");
  sd_core_shutdown();
  return rc;
}
