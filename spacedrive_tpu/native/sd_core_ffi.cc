// Mobile/desktop FFI shim: a C ABI around the embedded core.
//
// Reference pattern: apps/mobile/modules/sd-core — the Rust core is built as
// a static lib exposing `handle_core_msg` over a C ABI so JNI (android) and
// Swift (ios) hosts can embed the whole Node in-process (core/src/lib.rs:
// 61-117 JSON-RPC string bridge + :119+ event pump). Here the core is
// Python, so the shim embeds CPython and forwards the same four calls to
// spacedrive_tpu.ffi. A host needs nothing but this header surface:
//
//     int   sd_core_init(const char* data_dir, const char* python_path);
//     char* sd_core_msg(const char* json);        // caller frees: sd_core_free
//     char* sd_core_poll_event(int timeout_ms);   // "" when none; free it
//     void  sd_core_shutdown(void);
//     void  sd_core_free(char* s);
//
// Build: g++ -shared -fPIC sd_core_ffi.cc $(python3-config --includes
//        --ldflags --embed) — native/__init__.py's build_ffi() does this.

#include <Python.h>

#include <cstdarg>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_mutex;
bool g_inited = false;
PyObject* g_module = nullptr;  // spacedrive_tpu.ffi
bool g_we_own_interpreter = false;

char* dup_cstr(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out != nullptr) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

// Call ffi.<fn>(<one arg built from format under the GIL>) and return its
// str result (empty string on error, with the Python error printed to
// stderr for the host's logcat equivalent). Argument CONSTRUCTION must also
// happen under the GIL — building PyObjects without it is a crash.
std::string call_str(const char* fn, const char* format, ...) {
  std::string out;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = nullptr;
  bool args_ok = true;
  if (format != nullptr) {
    va_list va;
    va_start(va, format);
    args = Py_VaBuildValue(format, va);
    va_end(va);
    args_ok = args != nullptr;
    if (!args_ok) PyErr_Print();
  }
  PyObject* callee = (g_module != nullptr && args_ok)
                         ? PyObject_GetAttrString(g_module, fn)
                         : nullptr;
  if (callee != nullptr) {
    PyObject* result = PyObject_CallObject(callee, args);
    if (result != nullptr) {
      const char* utf8 = PyUnicode_AsUTF8(result);
      if (utf8 != nullptr) out = utf8;
      Py_DECREF(result);
    } else {
      PyErr_Print();
    }
    Py_DECREF(callee);
  } else if (args_ok && g_module == nullptr) {
    std::fprintf(stderr, "sd_core: module not loaded\n");
  } else if (args_ok) {
    PyErr_Print();
  }
  Py_XDECREF(args);
  PyGILState_Release(gil);
  return out;
}

}  // namespace

extern "C" {

// Returns 0 on success. `python_path` (may be NULL) is prepended to
// sys.path so the host can point at the packaged spacedrive_tpu tree.
int sd_core_init(const char* data_dir, const char* python_path) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (g_inited) return 0;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: the host owns signals.
    g_we_own_interpreter = true;  // this thread now HOLDS the GIL
  }
  PyGILState_STATE gil{};
  if (!g_we_own_interpreter) gil = PyGILState_Ensure();
  int rc = -1;
  do {
    // embedded interpreters have no sys.argv; libraries that peek at it
    // (absl, multiprocessing) misbehave without one
    PyObject* argv = Py_BuildValue("[s]", "sd_core");
    if (argv != nullptr) {
      PySys_SetObject("argv", argv);
      Py_DECREF(argv);
    }
    if (python_path != nullptr && python_path[0] != '\0') {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      PyObject* entry = PyUnicode_FromString(python_path);
      if (sys_path == nullptr || entry == nullptr ||
          PyList_Insert(sys_path, 0, entry) != 0) {
        PyErr_Print();
        Py_XDECREF(entry);
        break;
      }
      Py_DECREF(entry);
    }
    g_module = PyImport_ImportModule("spacedrive_tpu.ffi");
    if (g_module == nullptr) {
      PyErr_Print();
      break;
    }
    PyObject* result = PyObject_CallMethod(g_module, "init_core", "s", data_dir);
    if (result == nullptr) {
      PyErr_Print();
      break;
    }
    const char* utf8 = PyUnicode_AsUTF8(result);
    bool ok = utf8 != nullptr && std::strstr(utf8, "\"ok\": true") != nullptr;
    if (!ok) {
      std::fprintf(stderr, "sd_core_init: init_core returned %s\n",
                   utf8 == nullptr ? "<non-str>" : utf8);
    }
    Py_DECREF(result);
    if (!ok) break;
    rc = 0;
    g_inited = true;
  } while (false);
  if (g_we_own_interpreter) {
    // release the init GIL so host threads can call in via PyGILState_Ensure.
    // Clear the flag: the GIL is no longer held by anyone, so a RETRY of
    // sd_core_init (e.g. after a bad python_path) must take the
    // PyGILState_Ensure path like every other caller — leaving the flag
    // set would run Python C-API calls without the GIL.
    PyEval_SaveThread();
    g_we_own_interpreter = false;
  } else {
    PyGILState_Release(gil);
  }
  return rc;
}

char* sd_core_msg(const char* json) {
  if (!g_inited) return dup_cstr("{\"error\":\"core not initialized\"}");
  return dup_cstr(call_str("handle_core_msg", "(s)",
                           json == nullptr ? "" : json));
}

char* sd_core_poll_event(int timeout_ms) {
  if (!g_inited) return dup_cstr("");
  return dup_cstr(call_str("poll_core_event", "(i)", timeout_ms));
}

void sd_core_shutdown(void) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (!g_inited) return;
  call_str("shutdown_core", nullptr);
  g_inited = false;
}

void sd_core_free(char* s) { std::free(s); }

}  // extern "C"
