// Native image decode/encode: the sd-images + sd-ffmpeg-thumbnail stand-in.
//
// Reference: crates/images (format_image handler registry over Rust image/
// libheif) and the thumbnailer's WebP encode (thumbnail/mod.rs:95-110 via
// the image crate). This unit links the system libjpeg/libpng/libwebp the
// same way those crates bind their C cores:
//
//   sd_image_decode_rgb: sniff magic → decode to tightly-packed RGB8. JPEG
//     uses libjpeg's DCT-space scale_num/8 downscaling so a 48MP photo
//     never materializes at full size when the caller only wants a
//     thumbnail-sized buffer (max_edge); PNG decodes full size (no cheap
//     in-decode scaling exists) and reports its dims for host reduction.
//   sd_image_encode_webp: RGB8 → WebP at the caller's quality.
//
// Every function is C-ABI for ctypes; buffers are caller-owned numpy arrays
// except the WebP output, which is malloc'd and released via sd_webp_free.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>
#include <png.h>
#include <webp/encode.h>

namespace {

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// returns bytes written to out (w*h*3) or -1
int decode_jpeg(FILE* fh, uint8_t* out, int64_t capacity, int max_edge,
                int32_t* w, int32_t* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fh);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  // DCT-space downscale: pick the largest 1/8..8/8 that still covers
  // max_edge (free antialiasing + bounded memory for huge photos)
  if (max_edge > 0) {
    unsigned edge = cinfo.image_width > cinfo.image_height
                        ? cinfo.image_width : cinfo.image_height;
    unsigned num = 8;
    while (num > 1 && (edge * (num - 1)) / 8 >= static_cast<unsigned>(max_edge))
      num--;
    cinfo.scale_num = num;
    cinfo.scale_denom = 8;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int64_t row_bytes = static_cast<int64_t>(cinfo.output_width) * 3;
  if (row_bytes * cinfo.output_height > capacity) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + static_cast<int64_t>(cinfo.output_scanline) * row_bytes;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  *w = static_cast<int32_t>(cinfo.output_width);
  *h = static_cast<int32_t>(cinfo.output_height);
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return static_cast<int>(row_bytes * *h);
}

int decode_png(FILE* fh, uint8_t* out, int64_t capacity,
               int32_t* w, int32_t* h) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING,
                                           nullptr, nullptr, nullptr);
  if (png == nullptr) return -1;
  png_infop info = png_create_info_struct(png);
  if (info == nullptr) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return -1;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -1;
  }
  png_init_io(png, fh);
  png_read_info(png, info);
  png_uint_32 width = png_get_image_width(png, info);
  png_uint_32 height = png_get_image_height(png, info);
  int color = png_get_color_type(png, info);
  int depth = png_get_bit_depth(png, info);
  // normalize every variant to 8-bit RGB
  if (depth == 16) png_set_strip_16(png);
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color == PNG_COLOR_TYPE_GRAY && depth < 8) png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_gray_to_rgb(png);
  png_set_strip_alpha(png);  // composite-free drop is fine for previews
  png_set_interlace_handling(png);  // Adam7 needs multi-pass reads
  png_read_update_info(png, info);
  const int64_t row_bytes = static_cast<int64_t>(width) * 3;
  if (row_bytes * height > capacity) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -1;
  }
  // png_read_image handles interlaced and linear layouts uniformly
  png_bytep* rows = static_cast<png_bytep*>(
      std::malloc(sizeof(png_bytep) * height));
  if (rows == nullptr) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -1;
  }
  for (png_uint_32 y = 0; y < height; y++) rows[y] = out + y * row_bytes;
  png_read_image(png, rows);
  std::free(rows);
  png_destroy_read_struct(&png, &info, nullptr);
  *w = static_cast<int32_t>(width);
  *h = static_cast<int32_t>(height);
  return static_cast<int>(row_bytes * height);
}

}  // namespace

extern "C" {

// Decode path into out (capacity bytes). Returns bytes written (w*h*3),
// 0 for unsupported format, -1 on decode error / too-large image.
int64_t sd_image_decode_rgb(const char* path, uint8_t* out, int64_t capacity,
                            int32_t max_edge, int32_t* w, int32_t* h) {
  FILE* fh = std::fopen(path, "rb");
  if (fh == nullptr) return -1;
  uint8_t magic[8] = {0};
  size_t got = std::fread(magic, 1, sizeof(magic), fh);
  std::rewind(fh);
  int64_t rc = 0;
  if (got >= 3 && magic[0] == 0xFF && magic[1] == 0xD8 && magic[2] == 0xFF) {
    rc = decode_jpeg(fh, out, capacity, max_edge, w, h);
  } else if (got >= 8 && std::memcmp(magic, "\x89PNG\r\n\x1a\n", 8) == 0) {
    rc = decode_png(fh, out, capacity, w, h);
  }
  std::fclose(fh);
  return rc;
}

// RGB8 → WebP. Returns malloc'd buffer via *out_ptr (sd_webp_free it);
// 0 length on failure.
uint64_t sd_image_encode_webp(const uint8_t* rgb, int32_t w, int32_t h,
                              float quality, uint8_t** out_ptr) {
  uint8_t* webp = nullptr;
  size_t n = WebPEncodeRGB(rgb, w, h, w * 3, quality, &webp);
  *out_ptr = webp;
  return static_cast<uint64_t>(n);
}

void sd_webp_free(uint8_t* p) { WebPFree(p); }

}  // extern "C"
