// Native FFmpeg wrapper: the sd-ffmpeg crate equivalent, linked — not a CLI
// subprocess.
//
// Reference: crates/ffmpeg/src/{movie_decoder,thumbnailer}.rs — a
// MovieDecoder over libavformat/libavcodec that (a) prefers an embedded
// cover-art stream (AV_DISPOSITION_ATTACHED_PIC) when present, else (b)
// decodes one probe frame, seeks to seek_percentage of the duration
// (thumbnailer.rs ThumbnailerBuilder: seek_percentage 0.1) and decodes the
// keyframe there, then scales to the target edge via libswscale
// (create_scale_string, movie_decoder.rs:589). WebP encoding stays in
// sd_images.cc / the Python layer so the frame crosses the ABI exactly once.
//
// Also exposed:
//   sd_ffmpeg_probe_json — stream/format metadata for the media-data
//     extractor (sd-media-metadata's audio/video side, done via linked
//     libavformat instead of an ffprobe subprocess).
//   sd_ffmpeg_write_test_video — a tiny encoder (mpeg4/mpeg1video) so the
//     test suite can synthesize sample videos on hosts with no ffmpeg CLI
//     (the reference's #[ignore]d tests need a ./samples dir; ours don't).
//
// All functions are C-ABI for ctypes. Errors return negative AVERROR codes;
// sd_ffmpeg_err_str renders them for Python exceptions.

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/imgutils.h>
#include <libavutil/opt.h>
#include <libswscale/swscale.h>
}

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

struct QuietLogs {
  QuietLogs() { av_log_set_level(AV_LOG_ERROR); }
} quiet_logs_;

constexpr int kErrNoVideo = -900001;   // no decodable video/cover stream
constexpr int kErrBufSmall = -900002;  // caller buffer too small
constexpr int kErrEncode = -900003;    // test-encoder setup failure

struct Input {
  AVFormatContext* fmt = nullptr;
  AVCodecContext* dec = nullptr;
  int stream_index = -1;
  bool attached_pic = false;

  ~Input() {
    if (dec) avcodec_free_context(&dec);
    if (fmt) avformat_close_input(&fmt);
  }
};

// Open `path` and set up a decoder for its best video stream. Mirrors
// find_preferred_video_stream (movie_decoder.rs:312): an attached_pic
// (cover art) stream wins when prefer_embedded is set, matching the
// reference's prefer_embedded_metadata default.
int open_video(const char* path, bool prefer_embedded, Input& in) {
  int rc = avformat_open_input(&in.fmt, path, nullptr, nullptr);
  if (rc < 0) return rc;
  rc = avformat_find_stream_info(in.fmt, nullptr);
  if (rc < 0) return rc;

  int best = av_find_best_stream(in.fmt, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
  if (prefer_embedded) {
    for (unsigned i = 0; i < in.fmt->nb_streams; i++) {
      AVStream* s = in.fmt->streams[i];
      if (s->codecpar->codec_type == AVMEDIA_TYPE_VIDEO &&
          (s->disposition & AV_DISPOSITION_ATTACHED_PIC)) {
        best = static_cast<int>(i);
        in.attached_pic = true;
        break;
      }
    }
  }
  if (best < 0) return kErrNoVideo;
  in.stream_index = best;

  AVCodecParameters* par = in.fmt->streams[best]->codecpar;
  const AVCodec* codec = avcodec_find_decoder(par->codec_id);
  if (!codec) return kErrNoVideo;
  in.dec = avcodec_alloc_context3(codec);
  if (!in.dec) return AVERROR(ENOMEM);
  rc = avcodec_parameters_to_context(in.dec, par);
  if (rc < 0) return rc;
  rc = avcodec_open2(in.dec, codec, nullptr);
  if (rc < 0) return rc;
  return 0;
}

// Decode frames until one comes out; caller owns the returned ref inside
// `frame`. Returns 0 on success.
int decode_next_frame(Input& in, AVFrame* frame) {
  AVPacket* pkt = av_packet_alloc();
  if (!pkt) return AVERROR(ENOMEM);
  int rc;
  for (;;) {
    rc = avcodec_receive_frame(in.dec, frame);
    if (rc == 0) break;
    if (rc != AVERROR(EAGAIN)) break;
    rc = av_read_frame(in.fmt, pkt);
    if (rc < 0) {  // EOF: flush the decoder once
      avcodec_send_packet(in.dec, nullptr);
      rc = avcodec_receive_frame(in.dec, frame);
      break;
    }
    if (pkt->stream_index == in.stream_index) {
      rc = avcodec_send_packet(in.dec, pkt);
      av_packet_unref(pkt);
      if (rc < 0 && rc != AVERROR(EAGAIN)) break;
    } else {
      av_packet_unref(pkt);
    }
  }
  av_packet_free(&pkt);
  return rc == 0 ? 0 : (rc < 0 ? rc : kErrNoVideo);
}

// Fixed-point 3-decimal formatting via integer math: snprintf("%f") obeys
// LC_NUMERIC, and an embedding host that called setlocale() to a comma-
// decimal locale would make the probe emit invalid JSON.
void append_fixed3(std::string& out, double v) {
  if (v < 0) {
    out += '-';
    v = -v;
  }
  auto milli = static_cast<long long>(v * 1000.0 + 0.5);
  char buf[64];
  snprintf(buf, sizeof buf, "%lld.%03lld", milli / 1000, milli % 1000);
  out += buf;
}

void json_escape(std::string& out, const char* s) {
  for (; *s; s++) {
    unsigned char c = static_cast<unsigned char>(*s);
    if (c == '"' || c == '\\') {
      out += '\\';
      out += static_cast<char>(c);
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", c);
      out += buf;
    } else {
      out += static_cast<char>(c);
    }
  }
}

}  // namespace

extern "C" {

// Render an error code (AVERROR or kErr*) into `out`.
void sd_ffmpeg_err_str(int code, char* out, int cap) {
  switch (code) {
    case kErrNoVideo:
      snprintf(out, cap, "no decodable video stream");
      return;
    case kErrBufSmall:
      snprintf(out, cap, "output buffer too small");
      return;
    case kErrEncode:
      snprintf(out, cap, "encoder setup failed");
      return;
    default:
      if (av_strerror(code, out, cap) < 0) snprintf(out, cap, "av error %d", code);
  }
}

// Probe format + streams into a JSON document (the extractor's input).
// Returns bytes written (excluding NUL) or a negative error.
int64_t sd_ffmpeg_probe_json(const char* path, char* out, int64_t cap) {
  Input in;
  int rc = avformat_open_input(&in.fmt, path, nullptr, nullptr);
  if (rc < 0) return rc;
  rc = avformat_find_stream_info(in.fmt, nullptr);
  if (rc < 0) return rc;

  std::string j = "{";
  char buf[256];
  if (in.fmt->iformat && in.fmt->iformat->name) {
    j += "\"format\":\"";
    json_escape(j, in.fmt->iformat->name);
    j += "\",";
  }
  if (in.fmt->duration > 0) {
    j += "\"duration_seconds\":";
    append_fixed3(j, static_cast<double>(in.fmt->duration) / AV_TIME_BASE);
    j += ",";
  }
  if (in.fmt->bit_rate > 0) {
    snprintf(buf, sizeof buf, "\"bit_rate\":%lld,",
             static_cast<long long>(in.fmt->bit_rate));
    j += buf;
  }
  // container tags the extractor maps to MediaData columns
  j += "\"tags\":{";
  bool first_tag = true;
  const AVDictionaryEntry* tag = nullptr;
  while ((tag = av_dict_get(in.fmt->metadata, "", tag, AV_DICT_IGNORE_SUFFIX))) {
    if (!first_tag) j += ",";
    first_tag = false;
    j += '"';
    json_escape(j, tag->key);
    j += "\":\"";
    json_escape(j, tag->value);
    j += '"';
  }
  j += "},\"streams\":[";
  for (unsigned i = 0; i < in.fmt->nb_streams; i++) {
    AVStream* s = in.fmt->streams[i];
    AVCodecParameters* par = s->codecpar;
    if (i) j += ",";
    j += "{\"codec_type\":\"";
    const char* type = av_get_media_type_string(par->codec_type);
    json_escape(j, type ? type : "unknown");
    j += "\"";
    const char* codec = avcodec_get_name(par->codec_id);
    if (codec) {
      j += ",\"codec\":\"";
      json_escape(j, codec);
      j += "\"";
    }
    if (par->codec_type == AVMEDIA_TYPE_VIDEO) {
      snprintf(buf, sizeof buf, ",\"width\":%d,\"height\":%d", par->width,
               par->height);
      j += buf;
      AVRational fr = s->avg_frame_rate;
      if (fr.num > 0 && fr.den > 0) {
        j += ",\"fps\":";
        append_fixed3(j, av_q2d(fr));
      }
      if (s->disposition & AV_DISPOSITION_ATTACHED_PIC)
        j += ",\"attached_pic\":true";
    } else if (par->codec_type == AVMEDIA_TYPE_AUDIO) {
      snprintf(buf, sizeof buf, ",\"channels\":%d,\"sample_rate\":%d",
#if LIBAVCODEC_VERSION_MAJOR >= 59
               par->ch_layout.nb_channels,
#else
               par->channels,
#endif
               par->sample_rate);
      j += buf;
    }
    j += "}";
  }
  j += "]}";
  if (static_cast<int64_t>(j.size()) + 1 > cap) return kErrBufSmall;
  memcpy(out, j.data(), j.size() + 1);
  return static_cast<int64_t>(j.size());
}

// Decode one representative frame as packed RGB24.
//
// seek_percent ∈ [0,1): position in the stream (thumbnailer.rs seeks to
// 0.1 × duration after a probe frame; attached cover art never seeks).
// target_edge > 0 scales so max(w,h) == min(target_edge, native edge),
// preserving aspect (create_scale_string semantics). Returns bytes written
// (w*h*3) with *out_w/*out_h set, or a negative error.
int64_t sd_ffmpeg_decode_frame_rgb(const char* path, double seek_percent,
                                   int32_t target_edge, uint8_t* out,
                                   int64_t cap, int32_t* out_w,
                                   int32_t* out_h) {
  Input in;
  int rc = open_video(path, /*prefer_embedded=*/true, in);
  if (rc < 0) return rc;

  AVFrame* frame = av_frame_alloc();
  if (!frame) return AVERROR(ENOMEM);

  // probe frame first — some demuxers only report usable metadata after one
  // decoded frame (thumbnailer.rs:55 "have to decode a frame to get some
  // metadata"); then seek and decode the real target frame
  rc = decode_next_frame(in, frame);
  if (rc == 0 && !in.attached_pic && seek_percent > 0 &&
      in.fmt->duration > 0) {
    int64_t ts = static_cast<int64_t>(in.fmt->duration * seek_percent);
    if (av_seek_frame(in.fmt, -1, ts, AVSEEK_FLAG_BACKWARD) >= 0) {
      avcodec_flush_buffers(in.dec);
      av_frame_unref(frame);
      if (decode_next_frame(in, frame) < 0) {
        // seek landed nowhere decodable — fall back to the first frame,
        // like thumbnailer.rs's "seeking failed, try the first frame again"
        av_frame_free(&frame);
        return sd_ffmpeg_decode_frame_rgb(path, 0.0, target_edge, out, cap,
                                          out_w, out_h);
      }
    }
  }
  if (rc < 0) {
    av_frame_free(&frame);
    return rc;
  }

  int w = frame->width, h = frame->height;
  if (w <= 0 || h <= 0) {
    av_frame_free(&frame);
    return kErrNoVideo;
  }
  int tw = w, th = h;
  int edge = std::max(w, h);
  if (target_edge > 0 && edge > target_edge) {
    tw = std::max(1, w * target_edge / edge);
    th = std::max(1, h * target_edge / edge);
  }

  SwsContext* sws = sws_getContext(
      w, h, static_cast<AVPixelFormat>(frame->format), tw, th,
      AV_PIX_FMT_RGB24, SWS_BILINEAR, nullptr, nullptr, nullptr);
  if (!sws) {
    av_frame_free(&frame);
    return kErrNoVideo;
  }
  int64_t need = static_cast<int64_t>(tw) * th * 3;
  if (need > cap) {
    sws_freeContext(sws);
    av_frame_free(&frame);
    return kErrBufSmall;
  }
  uint8_t* dst[4] = {out, nullptr, nullptr, nullptr};
  int dst_stride[4] = {tw * 3, 0, 0, 0};
  sws_scale(sws, frame->data, frame->linesize, 0, h, dst, dst_stride);
  sws_freeContext(sws);
  av_frame_free(&frame);
  *out_w = tw;
  *out_h = th;
  return need;
}

// Synthesize a short test video: per-frame color gradient, yuv420p.
// Muxer chosen from the filename (.mp4 → mpeg4, .mpg → mpeg1video, else
// whatever the container's default video codec is). Test-only helper.
int32_t sd_ffmpeg_write_test_video(const char* path, int32_t w, int32_t h,
                                   int32_t nframes, int32_t fps) {
  if (w <= 0 || h <= 0 || (w | h) & 1) return kErrEncode;  // yuv420p: even dims
  AVFormatContext* fmt = nullptr;
  if (avformat_alloc_output_context2(&fmt, nullptr, nullptr, path) < 0 || !fmt)
    return kErrEncode;

  AVCodecID codec_id = fmt->oformat->video_codec;
  if (codec_id == AV_CODEC_ID_NONE) codec_id = AV_CODEC_ID_MPEG4;
  const AVCodec* codec = avcodec_find_encoder(codec_id);
  if (!codec) codec = avcodec_find_encoder(AV_CODEC_ID_MPEG4);
  if (!codec) {
    avformat_free_context(fmt);
    return kErrEncode;
  }

  AVStream* stream = avformat_new_stream(fmt, nullptr);
  AVCodecContext* enc = avcodec_alloc_context3(codec);
  AVFrame* frame = av_frame_alloc();
  AVPacket* pkt = av_packet_alloc();
  SwsContext* sws = nullptr;
  uint8_t* rgb = nullptr;
  int rc = kErrEncode;

  if (!stream || !enc || !frame || !pkt) goto done;
  // MPEG-1/2 accept only standard frame rates
  if (codec->id == AV_CODEC_ID_MPEG1VIDEO || codec->id == AV_CODEC_ID_MPEG2VIDEO)
    fps = 25;
  enc->width = w;
  enc->height = h;
  enc->pix_fmt = AV_PIX_FMT_YUV420P;
  enc->time_base = AVRational{1, fps};
  enc->framerate = AVRational{fps, 1};
  enc->bit_rate = 400000;
  enc->gop_size = 12;
  if (fmt->oformat->flags & AVFMT_GLOBALHEADER)
    enc->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
  if (avcodec_open2(enc, codec, nullptr) < 0) goto done;
  if (avcodec_parameters_from_context(stream->codecpar, enc) < 0) goto done;
  stream->time_base = enc->time_base;

  if (!(fmt->oformat->flags & AVFMT_NOFILE) &&
      avio_open(&fmt->pb, path, AVIO_FLAG_WRITE) < 0)
    goto done;
  if (avformat_write_header(fmt, nullptr) < 0) goto done;

  frame->format = AV_PIX_FMT_YUV420P;
  frame->width = w;
  frame->height = h;
  if (av_frame_get_buffer(frame, 0) < 0) goto done;
  sws = sws_getContext(w, h, AV_PIX_FMT_RGB24, w, h, AV_PIX_FMT_YUV420P,
                       SWS_BILINEAR, nullptr, nullptr, nullptr);
  rgb = static_cast<uint8_t*>(av_malloc(static_cast<size_t>(w) * h * 3));
  if (!sws || !rgb) goto done;

  for (int i = 0; i < nframes; i++) {
    for (int y = 0; y < h; y++)
      for (int x = 0; x < w; x++) {
        uint8_t* p = rgb + (static_cast<size_t>(y) * w + x) * 3;
        p[0] = static_cast<uint8_t>((x * 255 / w + i * 16) & 0xff);
        p[1] = static_cast<uint8_t>((y * 255 / h) & 0xff);
        p[2] = static_cast<uint8_t>((i * 32) & 0xff);
      }
    if (av_frame_make_writable(frame) < 0) goto done;
    {
      const uint8_t* src[4] = {rgb, nullptr, nullptr, nullptr};
      int src_stride[4] = {w * 3, 0, 0, 0};
      sws_scale(sws, src, src_stride, 0, h, frame->data, frame->linesize);
    }
    frame->pts = i;
    if (avcodec_send_frame(enc, frame) < 0) goto done;
    while (avcodec_receive_packet(enc, pkt) == 0) {
      av_packet_rescale_ts(pkt, enc->time_base, stream->time_base);
      pkt->stream_index = stream->index;
      av_interleaved_write_frame(fmt, pkt);
    }
  }
  avcodec_send_frame(enc, nullptr);  // flush
  while (avcodec_receive_packet(enc, pkt) == 0) {
    av_packet_rescale_ts(pkt, enc->time_base, stream->time_base);
    pkt->stream_index = stream->index;
    av_interleaved_write_frame(fmt, pkt);
  }
  av_write_trailer(fmt);
  rc = 0;

done:
  if (rgb) av_free(rgb);
  if (sws) sws_freeContext(sws);
  av_packet_free(&pkt);
  av_frame_free(&frame);
  if (enc) avcodec_free_context(&enc);
  if (fmt) {
    if (!(fmt->oformat->flags & AVFMT_NOFILE) && fmt->pb) avio_closep(&fmt->pb);
    avformat_free_context(fmt);
  }
  return rc;
}

}  // extern "C"
