/* Foreign-host demo for the C-ABI core shim (sd_core_ffi.cc): a plain C
 * program — the stand-in for a JNI/Swift mobile host — embeds the core,
 * creates a library over the JSON bridge, lists it back, drains one event,
 * and shuts down. Exit 0 only if every step round-trips. */
#include <stdio.h>
#include <string.h>

extern int sd_core_init(const char* data_dir, const char* python_path);
extern char* sd_core_msg(const char* json);
extern char* sd_core_poll_event(int timeout_ms);
extern void sd_core_shutdown(void);
extern void sd_core_free(char* s);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <data_dir> <python_path>\n", argv[0]);
    return 2;
  }
  if (sd_core_init(argv[1], argv[2]) != 0) {
    fprintf(stderr, "sd_core_init failed\n");
    return 1;
  }
  char* resp = sd_core_msg(
      "{\"id\":1,\"key\":\"libraries.create\",\"arg\":{\"name\":\"ffi-lib\"}}");
  printf("create: %s\n", resp);
  int ok = resp != NULL && strstr(resp, "\"result\"") != NULL &&
           strstr(resp, "ffi-lib") != NULL;
  sd_core_free(resp);
  if (!ok) { sd_core_shutdown(); return 1; }

  resp = sd_core_msg("{\"id\":2,\"key\":\"libraries.list\",\"arg\":null}");
  printf("list: %s\n", resp);
  ok = resp != NULL && strstr(resp, "ffi-lib") != NULL;
  sd_core_free(resp);
  if (!ok) { sd_core_shutdown(); return 1; }

  /* library creation broadcast at least one invalidation event */
  char* event = sd_core_poll_event(2000);
  printf("event: %s\n", event);
  ok = event != NULL && strstr(event, "\"kind\"") != NULL;
  sd_core_free(event);

  /* error path: unknown key comes back as an error envelope, not a crash */
  resp = sd_core_msg("{\"id\":3,\"key\":\"no.suchProcedure\"}");
  printf("bad key: %s\n", resp);
  int err_ok = resp != NULL && strstr(resp, "\"error\"") != NULL;
  sd_core_free(resp);

  sd_core_shutdown();
  return (ok && err_ok) ? 0 : 1;
}
