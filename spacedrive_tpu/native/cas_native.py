"""ctypes binding for the C++ cas_id hasher (blake3_cas.cc).

Drop-in for the pure-Python scalar path: ``hash_batch(paths, sizes)`` returns
16-hex cas_ids with per-file OSError entries for unreadable/shrunk files —
same error routing as objects/cas.py::read_sampled_batch.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from pathlib import Path

from . import build_shared
from .. import telemetry

_lib = ctypes.CDLL(str(build_shared("sdcas", ["blake3_cas.cc"])))

_lib.sd_cas_hash_batch.argtypes = [
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_char_p,
]
_lib.sd_cas_hash_batch.restype = None

_lib.sd_blake3_hex.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
_lib.sd_blake3_hex.restype = None

_lib.sd_blake3_file_hex.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
_lib.sd_blake3_file_hex.restype = ctypes.c_int


def blake3_hex(data: bytes) -> str:
    """Full 64-hex BLAKE3 digest (used by the validator's integrity checksum)."""
    out = ctypes.create_string_buffer(65)
    _lib.sd_blake3_hex(data, len(data), out)
    return out.value.decode()


_lib.sd_cas_gather_batch.argtypes = [
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_void_p,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32),
]
_lib.sd_cas_gather_batch.restype = None


_GATHER_US = telemetry.gauge(
    "sd_gather_us_per_file",
    "EWMA serial-equivalent native gather cost per file (µs); drives the "
    "gather thread autotune")

# Thread autotune: the gather is syscall-WAIT bound, not compute bound, so
# the right worker count tracks the filesystem's per-file latency, not the
# core count. We keep an EWMA of the *serial-equivalent* cost per file
# (wall µs/file × workers used — invariant to the worker count it was
# measured under) and size the pool so wall/file lands near _TARGET_US.
# The old static 4×cores heuristic only seeds the cold start.
_EWMA_ALPHA = 0.3
_TARGET_US = 25.0
_EWMA_LOCK = threading.Lock()
_ewma_us: float | None = None


def _observe_gather(wall_s: float, n: int, threads: int) -> None:
    """Fold one batch's measured cost into the EWMA (µs/file, serialized)."""
    global _ewma_us
    if n <= 0 or wall_s <= 0.0:
        return
    serial_us = wall_s * 1e6 * max(1, threads) / n
    with _EWMA_LOCK:
        if _ewma_us is None:
            _ewma_us = serial_us
        else:
            _ewma_us = _EWMA_ALPHA * serial_us + (1.0 - _EWMA_ALPHA) * _ewma_us
        _GATHER_US.set(_ewma_us)


def _default_gather_threads(n: int) -> int:
    """Gather workers per batch. ``SD_CAS_GATHER_THREADS`` overrides; with a
    measured EWMA the count is sized so per-file wall cost lands near
    ``_TARGET_US``; cold start falls back to oversubscribing the cores
    (4× up to 16 — measured ~25% on the 2-core dev container: 196 → 148
    µs/file at 8 threads)."""
    raw = os.environ.get("SD_CAS_GATHER_THREADS", "").strip()
    if raw:
        try:
            return max(1, min(int(raw), n))
        except ValueError:
            pass
    with _EWMA_LOCK:
        ewma = _ewma_us
    if ewma is not None:
        return min(max(2, round(ewma / _TARGET_US)), 16, n)
    return min(max(2, (os.cpu_count() or 1) * 4), 16, n)


def gather_batch(paths: list[str | Path], sizes: list[int], out, lengths,
                 n_threads: int | None = None) -> None:
    """Fill rows of ``out`` (np.uint8, shape (>=n, row_stride), C-contiguous)
    with cas sample messages and ``lengths`` (np.int32, (>=n,)) with true
    message byte counts (0 = per-file IO error). The fused IO+pack host stage
    of the TPU hash pipeline."""
    n = len(paths)
    if n == 0:
        return
    assert out.dtype.itemsize == 1 and out.flags["C_CONTIGUOUS"]
    assert lengths.dtype.itemsize == 4 and lengths.flags["C_CONTIGUOUS"]
    if n_threads is None:
        n_threads = _default_gather_threads(n)
    c_paths = (ctypes.c_char_p * n)(*[os.fsencode(str(p)) for p in paths])
    c_sizes = (ctypes.c_uint64 * n)(*[int(s) for s in sizes])
    t0 = time.perf_counter()
    _lib.sd_cas_gather_batch(
        ctypes.cast(c_paths, ctypes.POINTER(ctypes.c_char_p)),
        ctypes.cast(c_sizes, ctypes.POINTER(ctypes.c_uint64)),
        n, n_threads,
        out.ctypes.data_as(ctypes.c_void_p),
        out.strides[0],
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    _observe_gather(time.perf_counter() - t0, n, n_threads)


_lib.sd_blake3_hex_batch.argtypes = [
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int32,
    ctypes.c_char_p,
]
_lib.sd_blake3_hex_batch.restype = None


def blake3_hex_batch(messages: list[bytes]) -> list[str]:
    """Full 64-hex BLAKE3 digests for independent messages, hashed with
    cross-message SIMD lane filling (the fast no-accelerator path of the
    shared-hasher service)."""
    n = len(messages)
    if n == 0:
        return []
    # length-sorted lane groups: a skewed 16-lane group pads its short
    # lanes to the longest message's chunk count (wasted SIMD passes)
    order = sorted(range(n), key=lambda i: len(messages[i]), reverse=True)
    bufs = (ctypes.c_char_p * n)(*[messages[i] for i in order])
    lens = (ctypes.c_uint64 * n)(*[len(messages[i]) for i in order])
    out = ctypes.create_string_buffer(n * 65)
    _lib.sd_blake3_hex_batch(
        ctypes.cast(bufs, ctypes.POINTER(ctypes.c_char_p)), lens, n, out)
    raw = out.raw
    result = [""] * n
    for k, i in enumerate(order):
        result[i] = raw[k * 65 : k * 65 + 64].decode()
    return result


def blake3_file_hex(path: str | Path) -> str:
    """Full-file BLAKE3 via mmap (validator integrity checksums)."""
    out = ctypes.create_string_buffer(65)
    rc = _lib.sd_blake3_file_hex(os.fsencode(str(path)), out)
    if rc != 0:
        raise OSError(f"blake3 file hash failed for {path}")
    return out.value.decode()


def hash_batch(paths: list[str | Path], sizes: list[int],
               n_threads: int | None = None) -> list[str | Exception]:
    n = len(paths)
    if n == 0:
        return []
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, n)
    c_paths = (ctypes.c_char_p * n)(*[os.fsencode(str(p)) for p in paths])
    c_sizes = (ctypes.c_uint64 * n)(*[int(s) for s in sizes])
    out = ctypes.create_string_buffer(n * 17)
    _lib.sd_cas_hash_batch(
        ctypes.cast(c_paths, ctypes.POINTER(ctypes.c_char_p)),
        ctypes.cast(c_sizes, ctypes.POINTER(ctypes.c_uint64)),
        n, n_threads, out,
    )
    results: list[str | Exception] = []
    raw = out.raw
    for i in range(n):
        row = raw[i * 17 : i * 17 + 16]
        if row[0] == 0:
            results.append(OSError(f"native cas hash failed for {paths[i]}"))
        else:
            results.append(row.decode())
    return results
