"""Native (C++) runtime components, built on demand with the system g++.

The reference's equivalents are Rust crates with SIMD/FFI cores (blake3 crate,
sd-crypto, sd-ffmpeg). Here each component is a small C++ translation unit
compiled to a shared library at first import and loaded with ctypes — no
pybind11 dependency. Build artifacts land in ``native/_build`` (gitignored);
a failed toolchain leaves the pure-Python path in charge.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from pathlib import Path

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"


def build_shared(name: str, sources: list[str], extra_flags: list[str] | None = None,
                 extra_libs: list[str] | None = None) -> Path:
    """Compile ``sources`` (relative to native/) into ``_build/lib<name>.so``,
    rebuilding only when a source is newer than the artifact. Concurrent
    builders race benignly: each compiles to a temp file then renames.
    ``extra_libs`` (-l/-L flags) go AFTER the sources — link order matters."""
    out = _BUILD / f"lib{name}.so"
    srcs = [_DIR / s for s in sources]
    if out.exists() and all(out.stat().st_mtime >= s.stat().st_mtime for s in srcs):
        return out
    _BUILD.mkdir(exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD)
    os.close(fd)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *(extra_flags or []),
        *map(str, srcs), "-o", tmp,
        *(extra_libs or []),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out


def build_ffi() -> Path:
    """Build the embedded-core C-ABI shim (sd_core_ffi.cc) against this
    interpreter's libpython (python3-config --embed flags)."""
    includes = subprocess.run(
        ["python3-config", "--includes"],
        check=True, capture_output=True, text=True).stdout.split()
    ldflags = subprocess.run(
        ["python3-config", "--ldflags", "--embed"],
        check=True, capture_output=True, text=True).stdout.split()
    return build_shared("sdcoreffi", ["sd_core_ffi.cc"],
                        extra_flags=includes, extra_libs=ldflags)
