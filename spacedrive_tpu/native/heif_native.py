"""ctypes binding for HEIF/AVIF decode (sd_heif.cc → dlopen'd libheif).

The sd-images `heif` feature equivalent (crates/images/src/lib.rs:27-28).
``available()`` is the capability gate — the shared lib always builds (it
has no link-time libheif dependency), but the runtime library may be
absent. The encode helper exists purely so tests can synthesize fixtures;
it reports None when this libheif build ships no HEVC/AV1 encoder.
"""

from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

from . import build_shared

_lib = ctypes.CDLL(str(build_shared("sdheif", ["sd_heif.cc"],
                                    extra_libs=["-ldl"])))

_lib.sd_heif_available.argtypes = []
_lib.sd_heif_available.restype = ctypes.c_int

_lib.sd_heif_decode_rgb.argtypes = [
    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
_lib.sd_heif_decode_rgb.restype = ctypes.c_int64

_lib.sd_heif_encode_file.argtypes = [
    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
    ctypes.c_int32]
_lib.sd_heif_encode_file.restype = ctypes.c_int32

_lib.sd_heif_dims.argtypes = [
    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32)]
_lib.sd_heif_dims.restype = ctypes.c_int32

HEIF_EXTENSIONS = {"heic", "heif", "avif"}

#: decode ceiling, same guard class as the reference's max-size checks in
#: crates/images (a hostile heic must not allocate unbounded memory)
MAX_PIXELS = 64 * 1024 * 1024


class HeifError(Exception):
    pass


def available() -> bool:
    return bool(_lib.sd_heif_available())


def dims(path: str | Path) -> tuple[int, int]:
    """(width, height) of the primary image — parses the container only,
    no HEVC decode (the metadata extractor's path)."""
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = _lib.sd_heif_dims(str(path).encode(), ctypes.byref(w),
                           ctypes.byref(h))
    if rc != 0:
        raise HeifError("libheif runtime not available" if rc == -1
                        else f"unreadable heif file ({rc})")
    return w.value, h.value


def decode_rgb(path: str | Path) -> np.ndarray:
    """Primary image as an (h, w, 3) uint8 array. The buffer is sized from
    the declared dimensions (probed without decoding), capped at
    MAX_PIXELS — not a fixed 192 MiB per call."""
    dw, dh = dims(path)
    if dw * dh > MAX_PIXELS:
        raise HeifError("image exceeds decode size limit")
    # the decoded plane may be slightly larger than declared (codec
    # alignment); leave modest headroom, the C side still bounds the copy
    cap = max(dw + 64, 64) * max(dh + 64, 64) * 3
    out = np.empty(cap, np.uint8)
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = _lib.sd_heif_decode_rgb(
        str(path).encode(), out.ctypes.data_as(ctypes.c_void_p), cap,
        ctypes.byref(w), ctypes.byref(h))
    if rc < 0:
        raise HeifError({-1: "libheif runtime not available",
                         -3: "image exceeds decode size limit"}.get(
                             int(rc), f"heif decode failed ({rc})"))
    return out[:rc].reshape(h.value, w.value, 3).copy()


def encode_file(path: str | Path, rgb: np.ndarray,
                quality: int = 60) -> bool:
    """Write RGB24 to .heic/.avif; False when no encoder is compiled into
    the local libheif (callers/tests treat that as 'skip')."""
    rgb = np.ascontiguousarray(rgb, np.uint8)
    h, w = rgb.shape[:2]
    rc = _lib.sd_heif_encode_file(
        str(path).encode(), rgb.ctypes.data_as(ctypes.c_void_p), w, h,
        int(quality))
    if rc == -4:
        return False
    if rc != 0:
        raise HeifError(f"heif encode failed ({rc})")
    return True
