// Native cas_id hasher: clean-room BLAKE3 (from the public spec) + the
// reference's sampling scheme (core/src/object/cas.rs:10-62) behind a C ABI.
//
// Role: CPU fast path / baseline for the TPU kernel (ops/blake3_jax.py) — the
// analogue of the reference's SIMD `blake3` crate. Like that crate, the
// chunk layer is SIMD: BLAKE3's merkle structure makes chunks independent,
// so groups of 8 full chunks hash in parallel AVX2 lanes (one 32-bit word
// lane per chunk, runtime-dispatched) and the parent merge stays scalar.
// Batch API fans files across a thread pool the way the reference's
// join_all fans futures (file_identifier/mod.rs:107-134).
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py). No deps; the AVX2
// path is compiled via target attributes and gated on cpuid at runtime.

#include <array>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

const uint32_t IV[8] = {0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
                        0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u};
const int MSG_PERM[16] = {2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8};

enum Flags : uint32_t {
  CHUNK_START = 1 << 0,
  CHUNK_END = 1 << 1,
  PARENT = 1 << 2,
  ROOT = 1 << 3,
};

constexpr size_t CHUNK_LEN = 1024;
constexpr size_t BLOCK_LEN = 64;

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline void g(uint32_t s[16], int a, int b, int c, int d, uint32_t mx, uint32_t my) {
  s[a] = s[a] + s[b] + mx;
  s[d] = rotr(s[d] ^ s[a], 16);
  s[c] = s[c] + s[d];
  s[b] = rotr(s[b] ^ s[c], 12);
  s[a] = s[a] + s[b] + my;
  s[d] = rotr(s[d] ^ s[a], 8);
  s[c] = s[c] + s[d];
  s[b] = rotr(s[b] ^ s[c], 7);
}

void compress(const uint32_t cv[8], const uint32_t block[16], uint64_t counter,
              uint32_t block_len, uint32_t flags, uint32_t out[8]) {
  uint32_t s[16] = {
      cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
      IV[0], IV[1], IV[2], IV[3],
      static_cast<uint32_t>(counter), static_cast<uint32_t>(counter >> 32),
      block_len, flags,
  };
  uint32_t m[16];
  std::memcpy(m, block, sizeof(m));
  for (int r = 0; r < 7; r++) {
    g(s, 0, 4, 8, 12, m[0], m[1]);
    g(s, 1, 5, 9, 13, m[2], m[3]);
    g(s, 2, 6, 10, 14, m[4], m[5]);
    g(s, 3, 7, 11, 15, m[6], m[7]);
    g(s, 0, 5, 10, 15, m[8], m[9]);
    g(s, 1, 6, 11, 12, m[10], m[11]);
    g(s, 2, 7, 8, 13, m[12], m[13]);
    g(s, 3, 4, 9, 14, m[14], m[15]);
    if (r < 6) {
      uint32_t t[16];
      for (int i = 0; i < 16; i++) t[i] = m[MSG_PERM[i]];
      std::memcpy(m, t, sizeof(m));
    }
  }
  for (int i = 0; i < 8; i++) out[i] = s[i] ^ s[i + 8];
}

// A finished-but-unfinalized tree node: its CV chains upward without ROOT;
// the root node recompresses with ROOT to emit the digest.
struct Node {
  uint32_t cv[8];
  uint32_t block[16];
  uint64_t counter;
  uint32_t block_len;
  uint32_t flags;
};

inline void load_block(const uint8_t* p, size_t n, uint32_t out[16]) {
  uint8_t buf[BLOCK_LEN] = {0};
  std::memcpy(buf, p, n);
  for (int i = 0; i < 16; i++) {
    out[i] = static_cast<uint32_t>(buf[4 * i]) |
             static_cast<uint32_t>(buf[4 * i + 1]) << 8 |
             static_cast<uint32_t>(buf[4 * i + 2]) << 16 |
             static_cast<uint32_t>(buf[4 * i + 3]) << 24;
  }
}

Node chunk_node(const uint8_t* data, size_t len, uint64_t counter) {
  Node n;
  std::memcpy(n.cv, IV, sizeof(IV));
  n.counter = counter;
  size_t nblocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  for (size_t j = 0; j + 1 < nblocks; j++) {
    uint32_t block[16];
    load_block(data + j * BLOCK_LEN, BLOCK_LEN, block);
    uint32_t flags = j == 0 ? CHUNK_START : 0;
    uint32_t out[8];
    compress(n.cv, block, counter, BLOCK_LEN, flags, out);
    std::memcpy(n.cv, out, sizeof(out));
  }
  size_t last_off = (nblocks - 1) * BLOCK_LEN;
  size_t last_len = len - last_off;
  load_block(data + last_off, last_len, n.block);
  n.block_len = static_cast<uint32_t>(last_len);
  n.flags = CHUNK_END | (nblocks == 1 ? CHUNK_START : 0);
  return n;
}

inline void chain(const Node& n, uint32_t out_cv[8]) {
  compress(n.cv, n.block, n.counter, n.block_len, n.flags, out_cv);
}

Node parent_node(const uint32_t l[8], const uint32_t r[8]) {
  Node n;
  std::memcpy(n.cv, IV, sizeof(IV));
  std::memcpy(n.block, l, 32);
  std::memcpy(n.block + 8, r, 32);
  n.counter = 0;
  n.block_len = BLOCK_LEN;
  n.flags = PARENT;
  return n;
}

#if defined(__x86_64__)

__attribute__((target("avx2"))) inline __m256i rotr16v(__m256i x) {
  const __m256i ctl = _mm256_setr_epi8(
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
  return _mm256_shuffle_epi8(x, ctl);
}

__attribute__((target("avx2"))) inline __m256i rotr8v(__m256i x) {
  const __m256i ctl = _mm256_setr_epi8(
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12,
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12);
  return _mm256_shuffle_epi8(x, ctl);
}

__attribute__((target("avx2"))) inline __m256i rotrv(__m256i x, int n) {
  return _mm256_or_si256(_mm256_srli_epi32(x, n), _mm256_slli_epi32(x, 32 - n));
}

__attribute__((target("avx2"))) inline void g8(__m256i s[16], int a, int b,
                                               int c, int d, __m256i mx,
                                               __m256i my) {
  s[a] = _mm256_add_epi32(_mm256_add_epi32(s[a], s[b]), mx);
  s[d] = rotr16v(_mm256_xor_si256(s[d], s[a]));
  s[c] = _mm256_add_epi32(s[c], s[d]);
  s[b] = rotrv(_mm256_xor_si256(s[b], s[c]), 12);
  s[a] = _mm256_add_epi32(_mm256_add_epi32(s[a], s[b]), my);
  s[d] = rotr8v(_mm256_xor_si256(s[d], s[a]));
  s[c] = _mm256_add_epi32(s[c], s[d]);
  s[b] = rotrv(_mm256_xor_si256(s[b], s[c]), 7);
}

// 8 consecutive FULL chunks (stride CHUNK_LEN) hashed in parallel word
// lanes: lane l carries chunk counter+l. Same compression schedule as the
// scalar `compress`, vectorized across lanes; outputs 8 chained CVs.
__attribute__((target("avx2")))
void hash8_full_chunks(const uint8_t* data, uint64_t counter,
                       uint32_t out_cvs[8][8]) {
  __m256i cv[8];
  for (int i = 0; i < 8; i++)
    cv[i] = _mm256_set1_epi32(static_cast<int>(IV[i]));
  // lane l reads at byte offset l*CHUNK_LEN (gather indices in int units)
  const __m256i vindex =
      _mm256_setr_epi32(0, 256, 512, 768, 1024, 1280, 1536, 1792);
  alignas(32) uint32_t lo[8], hi[8];
  for (int l = 0; l < 8; l++) {
    uint64_t c = counter + static_cast<uint64_t>(l);
    lo[l] = static_cast<uint32_t>(c);
    hi[l] = static_cast<uint32_t>(c >> 32);
  }
  const __m256i ctr_lo = _mm256_load_si256(reinterpret_cast<__m256i*>(lo));
  const __m256i ctr_hi = _mm256_load_si256(reinterpret_cast<__m256i*>(hi));
  const __m256i iv0 = _mm256_set1_epi32(static_cast<int>(IV[0]));
  const __m256i iv1 = _mm256_set1_epi32(static_cast<int>(IV[1]));
  const __m256i iv2 = _mm256_set1_epi32(static_cast<int>(IV[2]));
  const __m256i iv3 = _mm256_set1_epi32(static_cast<int>(IV[3]));
  const __m256i vlen = _mm256_set1_epi32(static_cast<int>(BLOCK_LEN));

  for (int b = 0; b < 16; b++) {
    __m256i m[16];
    const int* base = reinterpret_cast<const int*>(data + b * BLOCK_LEN);
    for (int w = 0; w < 16; w++)
      m[w] = _mm256_i32gather_epi32(base + w, vindex, 4);
    uint32_t flags = (b == 0 ? CHUNK_START : 0) | (b == 15 ? CHUNK_END : 0);
    __m256i s[16] = {cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
                     iv0, iv1, iv2, iv3, ctr_lo, ctr_hi, vlen,
                     _mm256_set1_epi32(static_cast<int>(flags))};
    for (int r = 0; r < 7; r++) {
      g8(s, 0, 4, 8, 12, m[0], m[1]);
      g8(s, 1, 5, 9, 13, m[2], m[3]);
      g8(s, 2, 6, 10, 14, m[4], m[5]);
      g8(s, 3, 7, 11, 15, m[6], m[7]);
      g8(s, 0, 5, 10, 15, m[8], m[9]);
      g8(s, 1, 6, 11, 12, m[10], m[11]);
      g8(s, 2, 7, 8, 13, m[12], m[13]);
      g8(s, 3, 4, 9, 14, m[14], m[15]);
      if (r < 6) {
        __m256i t[16];
        for (int i = 0; i < 16; i++) t[i] = m[MSG_PERM[i]];
        std::memcpy(m, t, sizeof(m));
      }
    }
    for (int i = 0; i < 8; i++) cv[i] = _mm256_xor_si256(s[i], s[i + 8]);
  }
  alignas(32) uint32_t tmp[8][8];
  for (int i = 0; i < 8; i++)
    _mm256_store_si256(reinterpret_cast<__m256i*>(tmp[i]), cv[i]);
  for (int l = 0; l < 8; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

bool have_avx2() {
  static const bool ok = __builtin_cpu_supports("avx2");
  return ok;
}

__attribute__((target("avx512f"))) inline void g16(__m512i s[16], int a,
                                                   int b, int c, int d,
                                                   __m512i mx, __m512i my) {
  s[a] = _mm512_add_epi32(_mm512_add_epi32(s[a], s[b]), mx);
  s[d] = _mm512_ror_epi32(_mm512_xor_si512(s[d], s[a]), 16);
  s[c] = _mm512_add_epi32(s[c], s[d]);
  s[b] = _mm512_ror_epi32(_mm512_xor_si512(s[b], s[c]), 12);
  s[a] = _mm512_add_epi32(_mm512_add_epi32(s[a], s[b]), my);
  s[d] = _mm512_ror_epi32(_mm512_xor_si512(s[d], s[a]), 8);
  s[c] = _mm512_add_epi32(s[c], s[d]);
  s[b] = _mm512_ror_epi32(_mm512_xor_si512(s[b], s[c]), 7);
}

// 16 consecutive FULL chunks in parallel word lanes (AVX-512: native
// 32-bit rotates and twice the lanes of the AVX2 path).
__attribute__((target("avx512f")))
void hash16_full_chunks(const uint8_t* data, uint64_t counter,
                        uint32_t out_cvs[16][8]) {
  __m512i cv[8];
  for (int i = 0; i < 8; i++)
    cv[i] = _mm512_set1_epi32(static_cast<int>(IV[i]));
  const __m512i vindex = _mm512_setr_epi32(
      0, 256, 512, 768, 1024, 1280, 1536, 1792, 2048, 2304, 2560, 2816,
      3072, 3328, 3584, 3840);
  alignas(64) uint32_t lo[16], hi[16];
  for (int l = 0; l < 16; l++) {
    uint64_t c = counter + static_cast<uint64_t>(l);
    lo[l] = static_cast<uint32_t>(c);
    hi[l] = static_cast<uint32_t>(c >> 32);
  }
  const __m512i ctr_lo = _mm512_load_si512(lo);
  const __m512i ctr_hi = _mm512_load_si512(hi);
  const __m512i vlen = _mm512_set1_epi32(static_cast<int>(BLOCK_LEN));

  for (int b = 0; b < 16; b++) {
    __m512i m[16];
    const int* base = reinterpret_cast<const int*>(data + b * BLOCK_LEN);
    for (int w = 0; w < 16; w++)
      m[w] = _mm512_i32gather_epi32(vindex, base + w, 4);
    uint32_t flags = (b == 0 ? CHUNK_START : 0) | (b == 15 ? CHUNK_END : 0);
    __m512i s[16] = {
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        _mm512_set1_epi32(static_cast<int>(IV[0])),
        _mm512_set1_epi32(static_cast<int>(IV[1])),
        _mm512_set1_epi32(static_cast<int>(IV[2])),
        _mm512_set1_epi32(static_cast<int>(IV[3])),
        ctr_lo, ctr_hi, vlen,
        _mm512_set1_epi32(static_cast<int>(flags))};
    for (int r = 0; r < 7; r++) {
      g16(s, 0, 4, 8, 12, m[0], m[1]);
      g16(s, 1, 5, 9, 13, m[2], m[3]);
      g16(s, 2, 6, 10, 14, m[4], m[5]);
      g16(s, 3, 7, 11, 15, m[6], m[7]);
      g16(s, 0, 5, 10, 15, m[8], m[9]);
      g16(s, 1, 6, 11, 12, m[10], m[11]);
      g16(s, 2, 7, 8, 13, m[12], m[13]);
      g16(s, 3, 4, 9, 14, m[14], m[15]);
      if (r < 6) {
        __m512i t[16];
        for (int i = 0; i < 16; i++) t[i] = m[MSG_PERM[i]];
        std::memcpy(m, t, sizeof(m));
      }
    }
    for (int i = 0; i < 8; i++) cv[i] = _mm512_xor_si512(s[i], s[i + 8]);
  }
  alignas(64) uint32_t tmp[8][16];
  for (int i = 0; i < 8; i++) _mm512_store_si512(tmp[i], cv[i]);
  for (int l = 0; l < 16; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

bool have_avx512() {
  static const bool ok = __builtin_cpu_supports("avx512f");
  return ok;
}

#endif  // __x86_64__

// Incremental log-depth merge stack (the spec's streaming construction):
// chunk CVs push left-to-right and completed equal-size subtrees fold
// eagerly, so memory stays O(log n) for multi-GB inputs (the mmap'd
// full-file path must not allocate size/32 bytes of CV buffer).
struct MergeStack {
  std::array<uint32_t, 8> stack[64];
  size_t depth = 0;
  uint64_t added = 0;

  void push_cv(const uint32_t cv[8]) {
    std::array<uint32_t, 8> top;
    std::memcpy(top.data(), cv, 32);
    added++;
    for (uint64_t t = added; (t & 1) == 0; t >>= 1) {
      uint32_t merged[8];
      chain(parent_node(stack[depth - 1].data(), top.data()), merged);
      std::memcpy(top.data(), merged, 32);
      depth--;
    }
    std::memcpy(stack[depth].data(), top.data(), 32);
    depth++;
  }

  // fold everything below the final (rightmost) subtree; returns the
  // UNFINALIZED root node (the caller applies ROOT)
  Node finish(const Node& last) {
    uint32_t right[8];
    chain(last, right);
    while (depth > 1) {
      uint32_t merged[8];
      chain(parent_node(stack[depth - 1].data(), right), merged);
      std::memcpy(right, merged, 32);
      depth--;
    }
    return parent_node(stack[0].data(), right);
  }
};

Node tree(const uint8_t* data, size_t len, uint64_t counter) {
  if (len <= CHUNK_LEN) return chunk_node(data, len, counter);
  size_t n_chunks = (len + CHUNK_LEN - 1) / CHUNK_LEN;
  size_t prefix = n_chunks - 1;  // all full; the last chunk may be partial
  MergeStack ms;
  size_t i = 0;
#if defined(__x86_64__)
  if (have_avx512()) {
    for (; i + 16 <= prefix; i += 16) {
      uint32_t out[16][8];
      hash16_full_chunks(data + i * CHUNK_LEN, counter + i, out);
      for (int l = 0; l < 16; l++) ms.push_cv(out[l]);
    }
  }
  if (have_avx2()) {
    for (; i + 8 <= prefix; i += 8) {
      uint32_t out[8][8];
      hash8_full_chunks(data + i * CHUNK_LEN, counter + i, out);
      for (int l = 0; l < 8; l++) ms.push_cv(out[l]);
    }
  }
#endif
  for (; i < prefix; i++) {
    uint32_t cv[8];
    chain(chunk_node(data + i * CHUNK_LEN, CHUNK_LEN, counter + i), cv);
    ms.push_cv(cv);
  }
  Node last = chunk_node(data + prefix * CHUNK_LEN, len - prefix * CHUNK_LEN,
                         counter + prefix);
  return ms.finish(last);
}

void blake3_digest(const uint8_t* data, size_t len, uint8_t out[32]) {
  Node root = tree(data, len, 0);
  uint32_t words[8];
  compress(root.cv, root.block, 0, root.block_len, root.flags | ROOT, words);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = static_cast<uint8_t>(words[i]);
    out[4 * i + 1] = static_cast<uint8_t>(words[i] >> 8);
    out[4 * i + 2] = static_cast<uint8_t>(words[i] >> 16);
    out[4 * i + 3] = static_cast<uint8_t>(words[i] >> 24);
  }
}

// ---- cas sampling (reference consts cas.rs:10-15) ----
constexpr uint64_t SAMPLE_COUNT = 4;
constexpr uint64_t SAMPLE_SIZE = 1024 * 10;
constexpr uint64_t HEADER_OR_FOOTER = 1024 * 8;
constexpr uint64_t MINIMUM_FILE_SIZE = 1024 * 100;

const char HEX[] = "0123456789abcdef";

// Returns 0 on success; writes 16 lowercase hex chars + NUL into out17.
int cas_id_for_fd(int fd, uint64_t size, char out17[17]) {
  std::vector<uint8_t> msg;
  msg.reserve(8 + (size <= MINIMUM_FILE_SIZE
                       ? size
                       : 2 * HEADER_OR_FOOTER + SAMPLE_COUNT * SAMPLE_SIZE));
  for (int i = 0; i < 8; i++) msg.push_back(static_cast<uint8_t>(size >> (8 * i)));

  auto read_exact = [&](uint64_t off, uint64_t len) -> bool {
    size_t base = msg.size();
    msg.resize(base + len);
    uint64_t got = 0;
    while (got < len) {
      ssize_t r = pread(fd, msg.data() + base + got, len - got, off + got);
      if (r <= 0) return false;
      got += static_cast<uint64_t>(r);
    }
    return true;
  };

  if (size <= MINIMUM_FILE_SIZE) {
    if (size > 0 && !read_exact(0, size)) return 1;
  } else {
    uint64_t seek_jump = (size - HEADER_OR_FOOTER * 2) / SAMPLE_COUNT;
    if (!read_exact(0, HEADER_OR_FOOTER)) return 1;
    for (uint64_t i = 0; i < SAMPLE_COUNT; i++) {
      if (!read_exact(HEADER_OR_FOOTER + i * seek_jump, SAMPLE_SIZE)) return 1;
    }
    if (!read_exact(size - HEADER_OR_FOOTER, HEADER_OR_FOOTER)) return 1;
  }

  uint8_t digest[32];
  blake3_digest(msg.data(), msg.size(), digest);
  for (int i = 0; i < 8; i++) {
    out17[2 * i] = HEX[digest[i] >> 4];
    out17[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out17[16] = '\0';
  return 0;
}

}  // namespace

extern "C" {

// Full 32-byte BLAKE3 of a buffer → 64 hex chars + NUL.
void sd_blake3_hex(const uint8_t* data, uint64_t len, char out65[65]) {
  uint8_t digest[32];
  blake3_digest(data, len, digest);
  for (int i = 0; i < 32; i++) {
    out65[2 * i] = HEX[digest[i] >> 4];
    out65[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out65[64] = '\0';
}

// Full-file BLAKE3 (the validator's integrity_checksum — distinct from the
// sampled cas_id, reference core/src/object/validation/hash.rs:24). mmap'd so
// multi-GB files hash without buffering. Returns 0 on success.
int sd_blake3_file_hex(const char* path, char out65[65]) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return 1;
  off_t size = lseek(fd, 0, SEEK_END);
  if (size < 0) { close(fd); return 1; }
  const uint8_t* data = nullptr;
  if (size > 0) {
    void* p = mmap(nullptr, static_cast<size_t>(size), PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) { close(fd); return 1; }
    data = static_cast<const uint8_t*>(p);
  }
  uint8_t digest[32];
  blake3_digest(data, static_cast<size_t>(size), digest);
  if (data) munmap(const_cast<uint8_t*>(data), static_cast<size_t>(size));
  close(fd);
  for (int i = 0; i < 32; i++) {
    out65[2 * i] = HEX[digest[i] >> 4];
    out65[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out65[64] = '\0';
  return 0;
}

// Gather stage for the TPU path: read each file's cas sample message
// (size_le8 ‖ samples, cas.rs layout) straight into row i of a zero-padded
// (n, row_stride) byte matrix — the host side of the batched device hash,
// fused with IO so Python never copies per-file. lengths[i] gets the true
// message byte count; err-rows get length 0 (caller routes per-file errors).
void sd_cas_gather_batch(const char* const* paths, const uint64_t* sizes,
                         int32_t n, int32_t n_threads, uint8_t* out,
                         int64_t row_stride, int32_t* lengths) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) break;
      uint8_t* row = out + static_cast<int64_t>(i) * row_stride;
      lengths[i] = 0;
      uint64_t size = sizes[i];
      uint64_t msg_len = 8 + (size <= MINIMUM_FILE_SIZE
                                  ? size
                                  : 2 * HEADER_OR_FOOTER + SAMPLE_COUNT * SAMPLE_SIZE);
      if (static_cast<int64_t>(msg_len) > row_stride) continue;
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) continue;
      for (int b = 0; b < 8; b++) row[b] = static_cast<uint8_t>(size >> (8 * b));
      uint8_t* dst = row + 8;
      auto read_exact = [&](uint64_t off, uint64_t len) -> bool {
        uint64_t got = 0;
        while (got < len) {
          ssize_t r = pread(fd, dst + got, len - got, off + got);
          if (r <= 0) return false;
          got += static_cast<uint64_t>(r);
        }
        dst += len;
        return true;
      };
      bool ok = true;
      if (size <= MINIMUM_FILE_SIZE) {
        ok = size == 0 || read_exact(0, size);
      } else {
        uint64_t seek_jump = (size - HEADER_OR_FOOTER * 2) / SAMPLE_COUNT;
        ok = read_exact(0, HEADER_OR_FOOTER);
        for (uint64_t s = 0; ok && s < SAMPLE_COUNT; s++) {
          ok = read_exact(HEADER_OR_FOOTER + s * seek_jump, SAMPLE_SIZE);
        }
        ok = ok && read_exact(size - HEADER_OR_FOOTER, HEADER_OR_FOOTER);
      }
      close(fd);
      if (ok) {
        // zero to the 64-byte block boundary: the device kernel compresses
        // whole blocks and relies on zero padding within the final one
        // (beyond that, per-lane block/chunk masks ignore the row tail)
        uint64_t pad = (64 - (msg_len & 63)) & 63;
        if (pad && static_cast<int64_t>(msg_len + pad) <= row_stride) {
          std::memset(row + msg_len, 0, pad);
        }
        lengths[i] = static_cast<int32_t>(msg_len);
      }
    }
  };
  if (n_threads == 1 || n == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  int32_t spawn = std::min<int32_t>(n_threads, n);
  threads.reserve(spawn);
  for (int32_t t = 0; t < spawn; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

// Batch cas_id over files. out = n rows of 17 bytes (16 hex + NUL); a row
// whose first byte is NUL means that file errored (caller raises per-file).
void sd_cas_hash_batch(const char* const* paths, const uint64_t* sizes,
                       int32_t n, int32_t n_threads, char* out) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) break;
      char* row = out + static_cast<size_t>(i) * 17;
      row[0] = '\0';
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) continue;
      cas_id_for_fd(fd, sizes[i], row);
      close(fd);
    }
  };
  if (n_threads == 1 || n == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  int32_t spawn = std::min<int32_t>(n_threads, n);
  threads.reserve(spawn);
  for (int32_t t = 0; t < spawn; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

}  // extern "C"
