// Native cas_id hasher: clean-room BLAKE3 (from the public spec) + the
// reference's sampling scheme (core/src/object/cas.rs:10-62) behind a C ABI.
//
// Role: CPU fast path / baseline for the TPU kernel (ops/blake3_jax.py) — the
// analogue of the reference's SIMD `blake3` crate. Like that crate, BOTH
// tree layers are SIMD: BLAKE3's merkle structure makes chunks independent,
// so groups of 16 (AVX-512) / 8 (AVX2) full chunks hash in parallel 32-bit
// word lanes, and parent nodes batch the same way (16/8 parent compressions
// per call) through a level-wise reduction that is provably the spec's
// left-largest-power-of-two tree. Message words are brought into lane order
// by contiguous loads + an in-register 16x16 (8x8) transpose — no gather
// instructions — and the round permutation is a compile-time schedule table,
// so the message registers never round-trip through memory. Measured on the
// 1-core AVX-512 host this build targets: ~4.8 GB/s single-message (57KiB
// cas messages), vs 2.14 GB/s for the gather+scalar-parent predecessor.
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py). No deps; SIMD paths
// are compiled via target attributes and gated on cpuid at runtime.

#include <array>
#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdlib>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#if defined(__linux__)
#include <linux/io_uring.h>
#include <sys/syscall.h>
#endif

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

const uint32_t IV[8] = {0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
                        0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u};
const int MSG_PERM[16] = {2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8};

// SCHED[r][i]: which ORIGINAL message word feeds position i in round r —
// the per-round permutation folded into a compile-time table so the 16
// message registers are never shuffled or spilled between rounds.
struct Sched {
  int v[7][16];
  constexpr Sched() : v{} {
    for (int i = 0; i < 16; i++) v[0][i] = i;
    for (int r = 1; r < 7; r++)
      for (int i = 0; i < 16; i++) v[r][i] = v[r - 1][MSG_PERM[i]];
  }
};
constexpr Sched SCHED;

enum Flags : uint32_t {
  CHUNK_START = 1 << 0,
  CHUNK_END = 1 << 1,
  PARENT = 1 << 2,
  ROOT = 1 << 3,
};

constexpr size_t CHUNK_LEN = 1024;
constexpr size_t BLOCK_LEN = 64;

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline void g(uint32_t s[16], int a, int b, int c, int d, uint32_t mx, uint32_t my) {
  s[a] = s[a] + s[b] + mx;
  s[d] = rotr(s[d] ^ s[a], 16);
  s[c] = s[c] + s[d];
  s[b] = rotr(s[b] ^ s[c], 12);
  s[a] = s[a] + s[b] + my;
  s[d] = rotr(s[d] ^ s[a], 8);
  s[c] = s[c] + s[d];
  s[b] = rotr(s[b] ^ s[c], 7);
}

void compress(const uint32_t cv[8], const uint32_t block[16], uint64_t counter,
              uint32_t block_len, uint32_t flags, uint32_t out[8]) {
  uint32_t s[16] = {
      cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
      IV[0], IV[1], IV[2], IV[3],
      static_cast<uint32_t>(counter), static_cast<uint32_t>(counter >> 32),
      block_len, flags,
  };
  const uint32_t* m = block;
  for (int r = 0; r < 7; r++) {
    const int* p = SCHED.v[r];
    g(s, 0, 4, 8, 12, m[p[0]], m[p[1]]);
    g(s, 1, 5, 9, 13, m[p[2]], m[p[3]]);
    g(s, 2, 6, 10, 14, m[p[4]], m[p[5]]);
    g(s, 3, 7, 11, 15, m[p[6]], m[p[7]]);
    g(s, 0, 5, 10, 15, m[p[8]], m[p[9]]);
    g(s, 1, 6, 11, 12, m[p[10]], m[p[11]]);
    g(s, 2, 7, 8, 13, m[p[12]], m[p[13]]);
    g(s, 3, 4, 9, 14, m[p[14]], m[p[15]]);
  }
  for (int i = 0; i < 8; i++) out[i] = s[i] ^ s[i + 8];
}

// A finished-but-unfinalized tree node: its CV chains upward without ROOT;
// the root node recompresses with ROOT to emit the digest.
struct Node {
  uint32_t cv[8];
  uint32_t block[16];
  uint64_t counter;
  uint32_t block_len;
  uint32_t flags;
};

inline void load_block(const uint8_t* p, size_t n, uint32_t out[16]) {
  uint8_t buf[BLOCK_LEN] = {0};
  std::memcpy(buf, p, n);
  for (int i = 0; i < 16; i++) {
    out[i] = static_cast<uint32_t>(buf[4 * i]) |
             static_cast<uint32_t>(buf[4 * i + 1]) << 8 |
             static_cast<uint32_t>(buf[4 * i + 2]) << 16 |
             static_cast<uint32_t>(buf[4 * i + 3]) << 24;
  }
}

Node chunk_node(const uint8_t* data, size_t len, uint64_t counter) {
  Node n;
  std::memcpy(n.cv, IV, sizeof(IV));
  n.counter = counter;
  size_t nblocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  for (size_t j = 0; j + 1 < nblocks; j++) {
    uint32_t block[16];
    load_block(data + j * BLOCK_LEN, BLOCK_LEN, block);
    uint32_t flags = j == 0 ? CHUNK_START : 0;
    uint32_t out[8];
    compress(n.cv, block, counter, BLOCK_LEN, flags, out);
    std::memcpy(n.cv, out, sizeof(out));
  }
  size_t last_off = (nblocks - 1) * BLOCK_LEN;
  size_t last_len = len - last_off;
  load_block(data + last_off, last_len, n.block);
  n.block_len = static_cast<uint32_t>(last_len);
  n.flags = CHUNK_END | (nblocks == 1 ? CHUNK_START : 0);
  return n;
}

inline void chain(const Node& n, uint32_t out_cv[8]) {
  compress(n.cv, n.block, n.counter, n.block_len, n.flags, out_cv);
}

Node parent_node(const uint32_t l[8], const uint32_t r[8]) {
  Node n;
  std::memcpy(n.cv, IV, sizeof(IV));
  std::memcpy(n.block, l, 32);
  std::memcpy(n.block + 8, r, 32);
  n.counter = 0;
  n.block_len = BLOCK_LEN;
  n.flags = PARENT;
  return n;
}

#if defined(__x86_64__)

bool have_avx2() {
  static const bool ok = __builtin_cpu_supports("avx2");
  return ok;
}

bool have_avx512() {
  static const bool ok = __builtin_cpu_supports("avx512f");
  return ok;
}

// ---------------- AVX-512: 16 word lanes ----------------

__attribute__((target("avx512f"))) inline void g16(__m512i s[16], int a,
                                                   int b, int c, int d,
                                                   __m512i mx, __m512i my) {
  s[a] = _mm512_add_epi32(_mm512_add_epi32(s[a], s[b]), mx);
  s[d] = _mm512_ror_epi32(_mm512_xor_si512(s[d], s[a]), 16);
  s[c] = _mm512_add_epi32(s[c], s[d]);
  s[b] = _mm512_ror_epi32(_mm512_xor_si512(s[b], s[c]), 12);
  s[a] = _mm512_add_epi32(_mm512_add_epi32(s[a], s[b]), my);
  s[d] = _mm512_ror_epi32(_mm512_xor_si512(s[d], s[a]), 8);
  s[c] = _mm512_add_epi32(s[c], s[d]);
  s[b] = _mm512_ror_epi32(_mm512_xor_si512(s[b], s[c]), 7);
}

// In-register 16x16 u32 transpose: v[i] holds one lane's 64-byte block on
// entry, word w of every lane on exit. unpack32 -> unpack64 -> two 128-bit
// lane stages; 64 shuffles total, no gathers, no memory round-trip.
__attribute__((target("avx512f")))
inline void transpose16(__m512i v[16]) {
  __m512i a[16], b[16];
  for (int i = 0; i < 16; i += 2) {
    a[i] = _mm512_unpacklo_epi32(v[i], v[i + 1]);
    a[i + 1] = _mm512_unpackhi_epi32(v[i], v[i + 1]);
  }
  for (int i = 0; i < 16; i += 4) {
    b[i] = _mm512_unpacklo_epi64(a[i], a[i + 2]);
    b[i + 1] = _mm512_unpackhi_epi64(a[i], a[i + 2]);
    b[i + 2] = _mm512_unpacklo_epi64(a[i + 1], a[i + 3]);
    b[i + 3] = _mm512_unpackhi_epi64(a[i + 1], a[i + 3]);
  }
  // b[4k+j] lane L = rows 4k..4k+3, column 4L+j; rebuild column c=4L'+j as
  // [b[j].L', b[4+j].L', b[8+j].L', b[12+j].L'] with two 128-lane stages.
  for (int j = 0; j < 4; j++) {
    __m512i t0 = _mm512_shuffle_i32x4(b[j], b[4 + j], 0x44);
    __m512i t1 = _mm512_shuffle_i32x4(b[j], b[4 + j], 0xee);
    __m512i u0 = _mm512_shuffle_i32x4(b[8 + j], b[12 + j], 0x44);
    __m512i u1 = _mm512_shuffle_i32x4(b[8 + j], b[12 + j], 0xee);
    v[j] = _mm512_shuffle_i32x4(t0, u0, 0x88);
    v[4 + j] = _mm512_shuffle_i32x4(t0, u0, 0xdd);
    v[8 + j] = _mm512_shuffle_i32x4(t1, u1, 0x88);
    v[12 + j] = _mm512_shuffle_i32x4(t1, u1, 0xdd);
  }
}

#define ROUNDS16(s, m)                                              \
  do {                                                              \
    for (int r = 0; r < 7; r++) {                                   \
      const int* p = SCHED.v[r];                                    \
      g16(s, 0, 4, 8, 12, m[p[0]], m[p[1]]);                        \
      g16(s, 1, 5, 9, 13, m[p[2]], m[p[3]]);                        \
      g16(s, 2, 6, 10, 14, m[p[4]], m[p[5]]);                       \
      g16(s, 3, 7, 11, 15, m[p[6]], m[p[7]]);                       \
      g16(s, 0, 5, 10, 15, m[p[8]], m[p[9]]);                       \
      g16(s, 1, 6, 11, 12, m[p[10]], m[p[11]]);                     \
      g16(s, 2, 7, 8, 13, m[p[12]], m[p[13]]);                      \
      g16(s, 3, 4, 9, 14, m[p[14]], m[p[15]]);                      \
    }                                                               \
  } while (0)

// A page of zeros dummy lanes read from: a masked group (fewer than 16
// real chunks) still runs as ONE AVX-512 call, its spare lanes hashing
// zeros whose CVs are simply not stored.
alignas(64) const uint8_t ZERO_CHUNK[CHUNK_LEN] = {0};

// 16 FULL chunks hashed in parallel word lanes — lane l reads its own
// base pointer ptrs[l] with chunk counter counters[l], so callers can fill
// lanes from anywhere (consecutive chunks of one message, remainder tails
// padded with ZERO_CHUNK, or chunks of different messages).
__attribute__((target("avx512f")))
void hash16_full_chunks(const uint8_t* const ptrs[16],
                        const uint64_t counters[16], uint32_t out_cvs[][8],
                        int nlanes) {
  __m512i cv[8];
  for (int i = 0; i < 8; i++) cv[i] = _mm512_set1_epi32(static_cast<int>(IV[i]));
  alignas(64) uint32_t lo[16], hi[16];
  for (int l = 0; l < 16; l++) {
    lo[l] = static_cast<uint32_t>(counters[l]);
    hi[l] = static_cast<uint32_t>(counters[l] >> 32);
  }
  const __m512i ctr_lo = _mm512_load_si512(lo);
  const __m512i ctr_hi = _mm512_load_si512(hi);
  const __m512i vlen = _mm512_set1_epi32(static_cast<int>(BLOCK_LEN));
  for (int b = 0; b < 16; b++) {
    __m512i m[16];
    for (int l = 0; l < 16; l++)
      m[l] = _mm512_loadu_si512(ptrs[l] + b * BLOCK_LEN);
    transpose16(m);
    uint32_t flags = (b == 0 ? CHUNK_START : 0) | (b == 15 ? CHUNK_END : 0);
    __m512i s[16] = {cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
                     _mm512_set1_epi32(static_cast<int>(IV[0])),
                     _mm512_set1_epi32(static_cast<int>(IV[1])),
                     _mm512_set1_epi32(static_cast<int>(IV[2])),
                     _mm512_set1_epi32(static_cast<int>(IV[3])),
                     ctr_lo, ctr_hi, vlen,
                     _mm512_set1_epi32(static_cast<int>(flags))};
    ROUNDS16(s, m);
    for (int i = 0; i < 8; i++) cv[i] = _mm512_xor_si512(s[i], s[i + 8]);
  }
  alignas(64) uint32_t tmp[8][16];
  for (int i = 0; i < 8; i++) _mm512_store_si512(tmp[i], cv[i]);
  for (int l = 0; l < nlanes; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

// 16 parent compressions in parallel: lane l's block is the CV pair
// (cvs[2l], cvs[2l+1]) — 64 contiguous bytes at cvs + 16*l words. The
// caller guarantees 1024 readable bytes at cvs (buffer padding); lanes
// >= npairs compute garbage that is simply not stored.
__attribute__((target("avx512f")))
void parents16(const uint32_t* cvs, int npairs, uint32_t out_cvs[][8]) {
  __m512i m[16];
  for (int l = 0; l < 16; l++) m[l] = _mm512_loadu_si512(cvs + 16 * l);
  transpose16(m);
  const __m512i zero = _mm512_setzero_si512();
  __m512i s[16] = {_mm512_set1_epi32(static_cast<int>(IV[0])),
                   _mm512_set1_epi32(static_cast<int>(IV[1])),
                   _mm512_set1_epi32(static_cast<int>(IV[2])),
                   _mm512_set1_epi32(static_cast<int>(IV[3])),
                   _mm512_set1_epi32(static_cast<int>(IV[4])),
                   _mm512_set1_epi32(static_cast<int>(IV[5])),
                   _mm512_set1_epi32(static_cast<int>(IV[6])),
                   _mm512_set1_epi32(static_cast<int>(IV[7])),
                   _mm512_set1_epi32(static_cast<int>(IV[0])),
                   _mm512_set1_epi32(static_cast<int>(IV[1])),
                   _mm512_set1_epi32(static_cast<int>(IV[2])),
                   _mm512_set1_epi32(static_cast<int>(IV[3])),
                   zero, zero,
                   _mm512_set1_epi32(static_cast<int>(BLOCK_LEN)),
                   _mm512_set1_epi32(static_cast<int>(PARENT))};
  ROUNDS16(s, m);
  alignas(64) uint32_t tmp[8][16];
  for (int i = 0; i < 8; i++)
    _mm512_store_si512(tmp[i], _mm512_xor_si512(s[i], s[i + 8]));
  for (int l = 0; l < npairs; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

#undef ROUNDS16

// ---------------- AVX2: 8 word lanes ----------------

__attribute__((target("avx2"))) inline __m256i rotr16v(__m256i x) {
  const __m256i ctl = _mm256_setr_epi8(
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
  return _mm256_shuffle_epi8(x, ctl);
}

__attribute__((target("avx2"))) inline __m256i rotr8v(__m256i x) {
  const __m256i ctl = _mm256_setr_epi8(
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12,
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12);
  return _mm256_shuffle_epi8(x, ctl);
}

__attribute__((target("avx2"))) inline __m256i rotrv(__m256i x, int n) {
  return _mm256_or_si256(_mm256_srli_epi32(x, n), _mm256_slli_epi32(x, 32 - n));
}

__attribute__((target("avx2"))) inline void g8(__m256i s[16], int a, int b,
                                               int c, int d, __m256i mx,
                                               __m256i my) {
  s[a] = _mm256_add_epi32(_mm256_add_epi32(s[a], s[b]), mx);
  s[d] = rotr16v(_mm256_xor_si256(s[d], s[a]));
  s[c] = _mm256_add_epi32(s[c], s[d]);
  s[b] = rotrv(_mm256_xor_si256(s[b], s[c]), 12);
  s[a] = _mm256_add_epi32(_mm256_add_epi32(s[a], s[b]), my);
  s[d] = rotr8v(_mm256_xor_si256(s[d], s[a]));
  s[c] = _mm256_add_epi32(s[c], s[d]);
  s[b] = rotrv(_mm256_xor_si256(s[b], s[c]), 7);
}

// 8x8 u32 transpose (same construction as transpose16, one stage shorter).
__attribute__((target("avx2")))
inline void transpose8(__m256i v[8]) {
  __m256i a[8], b[8];
  for (int i = 0; i < 8; i += 2) {
    a[i] = _mm256_unpacklo_epi32(v[i], v[i + 1]);
    a[i + 1] = _mm256_unpackhi_epi32(v[i], v[i + 1]);
  }
  for (int i = 0; i < 8; i += 4) {
    b[i] = _mm256_unpacklo_epi64(a[i], a[i + 2]);
    b[i + 1] = _mm256_unpackhi_epi64(a[i], a[i + 2]);
    b[i + 2] = _mm256_unpacklo_epi64(a[i + 1], a[i + 3]);
    b[i + 3] = _mm256_unpackhi_epi64(a[i + 1], a[i + 3]);
  }
  for (int j = 0; j < 4; j++) {
    v[j] = _mm256_permute2x128_si256(b[j], b[4 + j], 0x20);
    v[4 + j] = _mm256_permute2x128_si256(b[j], b[4 + j], 0x31);
  }
}

#define ROUNDS8(s, m)                                               \
  do {                                                              \
    for (int r = 0; r < 7; r++) {                                   \
      const int* p = SCHED.v[r];                                    \
      g8(s, 0, 4, 8, 12, m[p[0]], m[p[1]]);                         \
      g8(s, 1, 5, 9, 13, m[p[2]], m[p[3]]);                         \
      g8(s, 2, 6, 10, 14, m[p[4]], m[p[5]]);                        \
      g8(s, 3, 7, 11, 15, m[p[6]], m[p[7]]);                        \
      g8(s, 0, 5, 10, 15, m[p[8]], m[p[9]]);                        \
      g8(s, 1, 6, 11, 12, m[p[10]], m[p[11]]);                      \
      g8(s, 2, 7, 8, 13, m[p[12]], m[p[13]]);                       \
      g8(s, 3, 4, 9, 14, m[p[14]], m[p[15]]);                       \
    }                                                               \
  } while (0)

// 8 consecutive FULL chunks in parallel word lanes. Each lane's 64-byte
// block spans two ymm; the halves transpose independently into m[0..7]
// and m[8..15].
__attribute__((target("avx2")))
void hash8_full_chunks(const uint8_t* data, uint64_t counter,
                       uint32_t out_cvs[8][8]) {
  __m256i cv[8];
  for (int i = 0; i < 8; i++) cv[i] = _mm256_set1_epi32(static_cast<int>(IV[i]));
  alignas(32) uint32_t lo[8], hi[8];
  for (int l = 0; l < 8; l++) {
    uint64_t c = counter + static_cast<uint64_t>(l);
    lo[l] = static_cast<uint32_t>(c);
    hi[l] = static_cast<uint32_t>(c >> 32);
  }
  const __m256i ctr_lo = _mm256_load_si256(reinterpret_cast<__m256i*>(lo));
  const __m256i ctr_hi = _mm256_load_si256(reinterpret_cast<__m256i*>(hi));
  const __m256i vlen = _mm256_set1_epi32(static_cast<int>(BLOCK_LEN));
  for (int b = 0; b < 16; b++) {
    __m256i m[16];
    for (int l = 0; l < 8; l++) {
      const uint8_t* p = data + l * CHUNK_LEN + b * BLOCK_LEN;
      m[l] = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(p));
      m[8 + l] = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(p + 32));
    }
    transpose8(m);
    transpose8(m + 8);
    uint32_t flags = (b == 0 ? CHUNK_START : 0) | (b == 15 ? CHUNK_END : 0);
    __m256i s[16] = {cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
                     _mm256_set1_epi32(static_cast<int>(IV[0])),
                     _mm256_set1_epi32(static_cast<int>(IV[1])),
                     _mm256_set1_epi32(static_cast<int>(IV[2])),
                     _mm256_set1_epi32(static_cast<int>(IV[3])),
                     ctr_lo, ctr_hi, vlen,
                     _mm256_set1_epi32(static_cast<int>(flags))};
    ROUNDS8(s, m);
    for (int i = 0; i < 8; i++) cv[i] = _mm256_xor_si256(s[i], s[i + 8]);
  }
  alignas(32) uint32_t tmp[8][8];
  for (int i = 0; i < 8; i++)
    _mm256_store_si256(reinterpret_cast<__m256i*>(tmp[i]), cv[i]);
  for (int l = 0; l < 8; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

// 8 parent compressions in parallel; caller guarantees 512 readable bytes.
__attribute__((target("avx2")))
void parents8(const uint32_t* cvs, int npairs, uint32_t out_cvs[][8]) {
  __m256i m[16];
  for (int l = 0; l < 8; l++) {
    m[l] = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(cvs + 16 * l));
    m[8 + l] = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(cvs + 16 * l + 8));
  }
  transpose8(m);
  transpose8(m + 8);
  const __m256i zero = _mm256_setzero_si256();
  __m256i s[16] = {_mm256_set1_epi32(static_cast<int>(IV[0])),
                   _mm256_set1_epi32(static_cast<int>(IV[1])),
                   _mm256_set1_epi32(static_cast<int>(IV[2])),
                   _mm256_set1_epi32(static_cast<int>(IV[3])),
                   _mm256_set1_epi32(static_cast<int>(IV[4])),
                   _mm256_set1_epi32(static_cast<int>(IV[5])),
                   _mm256_set1_epi32(static_cast<int>(IV[6])),
                   _mm256_set1_epi32(static_cast<int>(IV[7])),
                   _mm256_set1_epi32(static_cast<int>(IV[0])),
                   _mm256_set1_epi32(static_cast<int>(IV[1])),
                   _mm256_set1_epi32(static_cast<int>(IV[2])),
                   _mm256_set1_epi32(static_cast<int>(IV[3])),
                   zero, zero,
                   _mm256_set1_epi32(static_cast<int>(BLOCK_LEN)),
                   _mm256_set1_epi32(static_cast<int>(PARENT))};
  ROUNDS8(s, m);
  alignas(32) uint32_t tmp[8][8];
  for (int i = 0; i < 8; i++)
    _mm256_store_si256(reinterpret_cast<__m256i*>(tmp[i]),
                       _mm256_xor_si256(s[i], s[i + 8]));
  for (int l = 0; l < npairs; l++)
    for (int i = 0; i < 8; i++) out_cvs[l][i] = tmp[i][l];
}

#undef ROUNDS8

#endif  // __x86_64__

using CV = std::array<uint32_t, 8>;

// One level of the merkle reduction over a contiguous CV buffer, in place:
// adjacent pairs compress to parents (SIMD-batched), an odd trailing CV
// carries down unchanged. Level-wise adjacent pairing with odd-carry builds
// exactly the spec's left-largest-power-of-two tree (each pairing step is
// the binary-counter merge the incremental construction performs), so the
// digests match the scalar path bit-for-bit. `cvs` must have 1024 readable
// bytes beyond the live prefix (the CvBuf below pads).
size_t reduce_level(CV* cvs, size_t count) {
  size_t npairs = count / 2;
  const uint32_t* in = cvs[0].data();
  size_t p = 0;
#if defined(__x86_64__)
  if (have_avx512()) {
    for (; p + 16 <= npairs; p += 16)
      parents16(in + 16 * p, 16, reinterpret_cast<uint32_t(*)[8]>(cvs + p));
    if (npairs - p >= 4) {  // partial group: still one vector call
      parents16(in + 16 * p, static_cast<int>(npairs - p),
                reinterpret_cast<uint32_t(*)[8]>(cvs + p));
      p = npairs;
    }
  } else if (have_avx2()) {
    for (; p + 8 <= npairs; p += 8)
      parents8(in + 16 * p, 8, reinterpret_cast<uint32_t(*)[8]>(cvs + p));
    if (npairs - p >= 3) {
      parents8(in + 16 * p, static_cast<int>(npairs - p),
               reinterpret_cast<uint32_t(*)[8]>(cvs + p));
      p = npairs;
    }
  }
#endif
  for (; p < npairs; p++) {
    uint32_t merged[8];
    chain(parent_node(cvs[2 * p].data(), cvs[2 * p + 1].data()), merged);
    std::memcpy(cvs[p].data(), merged, 32);
  }
  if (count & 1) {
    std::memcpy(cvs[npairs].data(), cvs[count - 1].data(), 32);
    return npairs + 1;
  }
  return npairs;
}

// Window of full chunks the in-memory reduction handles at once: 512 chunks
// = 512 KiB of input, 16 KiB of CVs — the multi-GB mmap path stays O(1).
constexpr size_t WINDOW_CHUNKS = 512;

struct CvBuf {
  // +32 slack CVs (1024 B) so vector parent loads never read past the live
  // prefix's end
  std::array<CV, WINDOW_CHUNKS + 32> buf;
  CV* data() { return buf.data(); }
};

// CVs of `n` consecutive FULL chunks into out[0..n). On AVX-512 hosts every
// group — including the final partial one — runs as a single 16-lane call
// (spare lanes hash ZERO_CHUNK and are discarded), so no chunk ever takes
// the scalar path; AVX2 hosts use 8-lane groups with a scalar tail.
void full_chunk_cvs(const uint8_t* data, size_t n, uint64_t counter, CV* out) {
  size_t i = 0;
#if defined(__x86_64__)
  if (have_avx512()) {
    while (i < n) {
      int lanes = static_cast<int>(n - i < 16 ? n - i : 16);
      const uint8_t* ptrs[16];
      uint64_t counters[16];
      for (int l = 0; l < 16; l++) {
        ptrs[l] = l < lanes ? data + (i + l) * CHUNK_LEN : ZERO_CHUNK;
        counters[l] = counter + i + (l < lanes ? l : 0);
      }
      hash16_full_chunks(ptrs, counters,
                         reinterpret_cast<uint32_t(*)[8]>(out + i), lanes);
      i += lanes;
    }
    return;
  }
  if (have_avx2()) {
    for (; i + 8 <= n; i += 8)
      hash8_full_chunks(data + i * CHUNK_LEN, counter + i,
                        reinterpret_cast<uint32_t(*)[8]>(out + i));
  }
#endif
  for (; i < n; i++)
    chain(chunk_node(data + i * CHUNK_LEN, CHUNK_LEN, counter + i),
          out[i].data());
}

// Precomputed full-chunk CVs (+ optional partial trailing chunk) -> the
// UNFINALIZED root node; shared by the per-message path and the
// cross-message batch hasher.
Node reduce_cvs(CV* cvs, size_t n_full, const uint8_t* tail, size_t tail_len,
                uint64_t tail_counter) {
  size_t count = n_full;
  if (tail_len) {
    chain(chunk_node(tail, tail_len, tail_counter), cvs[n_full].data());
    count++;
  }
  while (count > 2) count = reduce_level(cvs, count);
  return parent_node(cvs[0].data(), cvs[1].data());
}

// A range of <= WINDOW_CHUNKS chunks (full chunks + an optionally partial
// trailing one) -> the UNFINALIZED root node of its subtree. Full chunks —
// including a full-sized final chunk — all ride the SIMD lanes; only a
// genuinely partial trailing chunk (proportionally fewer blocks) goes
// through the scalar chunk path.
Node reduce_range(const uint8_t* data, size_t len, uint64_t counter) {
  if (len <= CHUNK_LEN) return chunk_node(data, len, counter);
  size_t n_full = len / CHUNK_LEN;
  size_t rem = len % CHUNK_LEN;
  CvBuf cb;
  CV* cvs = cb.data();
  full_chunk_cvs(data, n_full, counter, cvs);
  return reduce_cvs(cvs, n_full, data + n_full * CHUNK_LEN, rem,
                    counter + n_full);
}

// WINDOW_CHUNKS full chunks -> the chained CV of that complete subtree.
void window_root(const uint8_t* data, uint64_t counter, uint32_t out_cv[8]) {
  CvBuf cb;
  CV* cvs = cb.data();
  full_chunk_cvs(data, WINDOW_CHUNKS, counter, cvs);
  size_t count = WINDOW_CHUNKS;
  while (count > 1) count = reduce_level(cvs, count);
  std::memcpy(out_cv, cvs[0].data(), 32);
}

// Incremental log-depth merge stack over WINDOW-sized subtree roots (the
// spec's streaming construction, one entry per binary-counter bit): window
// roots push left-to-right and equal-size subtrees fold eagerly, so memory
// stays O(log n) for multi-GB inputs.
struct MergeStack {
  std::array<uint32_t, 8> stack[64];
  size_t depth = 0;
  uint64_t added = 0;

  void push_cv(const uint32_t cv[8]) {
    std::array<uint32_t, 8> top;
    std::memcpy(top.data(), cv, 32);
    added++;
    for (uint64_t t = added; (t & 1) == 0; t >>= 1) {
      uint32_t merged[8];
      chain(parent_node(stack[depth - 1].data(), top.data()), merged);
      std::memcpy(top.data(), merged, 32);
      depth--;
    }
    std::memcpy(stack[depth].data(), top.data(), 32);
    depth++;
  }

  // fold everything below the final (rightmost) subtree; returns the
  // UNFINALIZED root node (the caller applies ROOT)
  Node finish(const Node& last) {
    uint32_t right[8];
    chain(last, right);
    while (depth > 1) {
      uint32_t merged[8];
      chain(parent_node(stack[depth - 1].data(), right), merged);
      std::memcpy(right, merged, 32);
      depth--;
    }
    return parent_node(stack[0].data(), right);
  }
};

// ``evict(window_index)`` runs after each completed window — the mmap'd
// file path uses it to drop hashed pages; in-memory callers pass nothing.
template <typename Evict>
Node tree_windowed(const uint8_t* data, size_t len, uint64_t counter,
                   Evict evict) {
  if (len <= CHUNK_LEN) return chunk_node(data, len, counter);
  size_t n_chunks = (len + CHUNK_LEN - 1) / CHUNK_LEN;
  if (n_chunks <= WINDOW_CHUNKS) return reduce_range(data, len, counter);
  // Large input: aligned WINDOW_CHUNKS runs are complete subtrees of the
  // spec tree (the largest-power-of-two split always peels multiples of
  // the window until fewer than a window remain), so each reduces
  // independently and the roots stream through the merge stack. The tail
  // keeps at least one chunk so the unfinalized-root contract holds.
  size_t n_windows = (n_chunks - 1) / WINDOW_CHUNKS;
  MergeStack ms;
  for (size_t w = 0; w < n_windows; w++) {
    uint32_t cv[8];
    window_root(data + w * WINDOW_CHUNKS * CHUNK_LEN,
                counter + w * WINDOW_CHUNKS, cv);
    ms.push_cv(cv);
    evict(w);
  }
  size_t off = n_windows * WINDOW_CHUNKS * CHUNK_LEN;
  Node tail = reduce_range(data + off, len - off,
                           counter + n_windows * WINDOW_CHUNKS);
  return ms.finish(tail);
}

Node tree(const uint8_t* data, size_t len, uint64_t counter) {
  return tree_windowed(data, len, counter, [](size_t) {});
}

void finalize_root(const Node& root, uint8_t out[32]) {
  uint32_t words[8];
  compress(root.cv, root.block, 0, root.block_len, root.flags | ROOT, words);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = static_cast<uint8_t>(words[i]);
    out[4 * i + 1] = static_cast<uint8_t>(words[i] >> 8);
    out[4 * i + 2] = static_cast<uint8_t>(words[i] >> 16);
    out[4 * i + 3] = static_cast<uint8_t>(words[i] >> 24);
  }
}

void blake3_digest(const uint8_t* data, size_t len, uint8_t out[32]) {
  finalize_root(tree(data, len, 0), out);
}

// Hash up to 16 INDEPENDENT messages per SIMD pass, lane = message: the
// chunk phase iterates the chunk index across lanes (retired lanes park on
// the zero page), so occupancy stays full regardless of per-message chunk
// counts — the per-message path wastes lanes on every remainder group
// (e.g. a 57-chunk cas message runs 3 full passes + one 8/16 pass).
// Callers get the best batch occupancy by pre-sorting messages by length.
// Messages outside the windowed range (or non-AVX-512 hosts) fall back to
// the single-message tree.
void blake3_digest_batch(const uint8_t* const* msgs, const size_t* lens,
                         int32_t n, uint8_t (*out)[32]) {
#if defined(__x86_64__)
  if (!have_avx512()) {
#endif
    for (int32_t i = 0; i < n; i++) blake3_digest(msgs[i], lens[i], out[i]);
    return;
#if defined(__x86_64__)
  }
  std::vector<CvBuf> bufs(16);
  int32_t i = 0;
  while (i < n) {
    int lanes = 0;
    int32_t idx[16];
    while (i < n && lanes < 16) {
      size_t n_chunks = (lens[i] + CHUNK_LEN - 1) / CHUNK_LEN;
      if (lens[i] <= CHUNK_LEN || n_chunks > WINDOW_CHUNKS) {
        blake3_digest(msgs[i], lens[i], out[i]);
        i++;
        continue;
      }
      idx[lanes++] = i++;
    }
    if (lanes == 0) continue;
    size_t full[16];
    size_t max_full = 0;
    for (int l = 0; l < lanes; l++) {
      full[l] = lens[idx[l]] / CHUNK_LEN;
      max_full = std::max(max_full, full[l]);
    }
    const uint8_t* ptrs[16];
    uint64_t counters[16];
    uint32_t cvs16[16][8];
    for (size_t c = 0; c < max_full; c++) {
      for (int l = 0; l < 16; l++) {
        bool active = l < lanes && c < full[l];
        ptrs[l] = active ? msgs[idx[l]] + c * CHUNK_LEN : ZERO_CHUNK;
        counters[l] = active ? c : 0;
      }
      hash16_full_chunks(ptrs, counters, cvs16, 16);
      for (int l = 0; l < lanes; l++)
        if (c < full[l])
          std::memcpy(bufs[l].data()[c].data(), cvs16[l], 32);
    }
    for (int l = 0; l < lanes; l++) {
      const uint8_t* msg = msgs[idx[l]];
      size_t len = lens[idx[l]];
      size_t rem = len % CHUNK_LEN;
      finalize_root(reduce_cvs(bufs[l].data(), full[l],
                               msg + full[l] * CHUNK_LEN, rem, full[l]),
                    out[idx[l]]);
    }
  }
#endif
}

// ---- cas sampling (reference consts cas.rs:10-15) ----
constexpr uint64_t SAMPLE_COUNT = 4;
constexpr uint64_t SAMPLE_SIZE = 1024 * 10;
constexpr uint64_t HEADER_OR_FOOTER = 1024 * 8;
constexpr uint64_t MINIMUM_FILE_SIZE = 1024 * 100;

// cas message length for a file of `size` bytes: 8-byte size prefix, then
// either the whole file (small) or header + 4 samples + footer (sampled).
// The single source of truth for every gather/hash path below.
constexpr uint64_t msg_len_for(uint64_t size) {
  return 8 + (size <= MINIMUM_FILE_SIZE
                  ? size
                  : 2 * HEADER_OR_FOOTER + SAMPLE_COUNT * SAMPLE_SIZE);
}

const char HEX[] = "0123456789abcdef";

// Returns 0 on success; writes 16 lowercase hex chars + NUL into out17.
int cas_id_for_fd(int fd, uint64_t size, char out17[17]) {
  std::vector<uint8_t> msg;
  msg.reserve(msg_len_for(size));
  for (int i = 0; i < 8; i++) msg.push_back(static_cast<uint8_t>(size >> (8 * i)));

  auto read_exact = [&](uint64_t off, uint64_t len) -> bool {
    size_t base = msg.size();
    msg.resize(base + len);
    uint64_t got = 0;
    while (got < len) {
      ssize_t r = pread(fd, msg.data() + base + got, len - got, off + got);
      if (r <= 0) return false;
      got += static_cast<uint64_t>(r);
    }
    return true;
  };

  if (size <= MINIMUM_FILE_SIZE) {
    if (size > 0 && !read_exact(0, size)) return 1;
  } else {
    uint64_t seek_jump = (size - HEADER_OR_FOOTER * 2) / SAMPLE_COUNT;
    if (!read_exact(0, HEADER_OR_FOOTER)) return 1;
    for (uint64_t i = 0; i < SAMPLE_COUNT; i++) {
      if (!read_exact(HEADER_OR_FOOTER + i * seek_jump, SAMPLE_SIZE)) return 1;
    }
    if (!read_exact(size - HEADER_OR_FOOTER, HEADER_OR_FOOTER)) return 1;
  }

  uint8_t digest[32];
  blake3_digest(msg.data(), msg.size(), digest);
  for (int i = 0; i < 8; i++) {
    out17[2 * i] = HEX[digest[i] >> 4];
    out17[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out17[16] = '\0';
  return 0;
}

// Run fn(i) for i in [0, n) across up to n_threads workers (atomic work
// stealing); the single-threaded path spawns nothing. The one thread-pool
// idiom shared by the gather, hash-batch, and row-hash loops.
template <typename F>
void for_each_parallel(int32_t n, int32_t n_threads, F fn) {
  if (n_threads < 1) n_threads = 1;
  n_threads = std::min(n_threads, n);
  if (n_threads <= 1 || n <= 1) {
    for (int32_t i = 0; i < n; i++) fn(i);
    return;
  }
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) break;
      fn(i);
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t t = 0; t < n_threads; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

// ---- io_uring batched sample gather -------------------------------------
//
// The sampling pattern costs 9 syscalls per file (open, 6 preads, close)
// — on this host ~2/3 of the whole identify budget once hashing is SIMD.
// io_uring batches a whole group of files into a handful of
// submit-and-wait calls: one round of OPENATs, rounds of READs (with
// short-read resubmission), one round of CLOSEs. Falls back to the
// synchronous path when the kernel or sandbox refuses the ring.

#if defined(__linux__)

struct Uring {
  int ring_fd = -1;
  unsigned sq_entries = 0;
  void* sq_ring_ptr = nullptr;
  void* cq_ring_ptr = nullptr;
  size_t sq_ring_sz = 0, cq_ring_sz = 0;
  io_uring_sqe* sqes = nullptr;
  size_t sqes_sz = 0;
  unsigned *sq_tail = nullptr, *sq_mask = nullptr, *sq_array = nullptr;
  unsigned *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  unsigned to_submit = 0;

  // The ops this gather needs; probed at init so a kernel old enough to
  // have io_uring but not these (5.1–5.5: OPENAT/READ/CLOSE landed in 5.6)
  // fails init and the caller keeps the synchronous path. REGISTER_PROBE
  // itself is also 5.6+, so its absence likewise means "don't use uring".
  static bool ops_supported(int fd) {
    constexpr unsigned NOPS = 64;
    alignas(io_uring_probe) uint8_t buf[sizeof(io_uring_probe) +
                                        NOPS * sizeof(io_uring_probe_op)] = {};
    auto* probe = reinterpret_cast<io_uring_probe*>(buf);
    if (syscall(__NR_io_uring_register, fd, IORING_REGISTER_PROBE, probe,
                NOPS) < 0)
      return false;
    for (unsigned op : {static_cast<unsigned>(IORING_OP_OPENAT),
                        static_cast<unsigned>(IORING_OP_READ),
                        static_cast<unsigned>(IORING_OP_CLOSE)}) {
      if (op > probe->last_op || !(probe->ops[op].flags & IO_URING_OP_SUPPORTED))
        return false;
    }
    return true;
  }

  bool init(unsigned entries) {
    io_uring_params p{};
    ring_fd = static_cast<int>(syscall(__NR_io_uring_setup, entries, &p));
    if (ring_fd < 0) return false;
    if (!ops_supported(ring_fd)) {
      close(ring_fd);
      ring_fd = -1;
      return false;
    }
    sq_entries = p.sq_entries;
    sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    sq_ring_ptr = mmap(nullptr, sq_ring_sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    cq_ring_ptr = mmap(nullptr, cq_ring_sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_CQ_RING);
    sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
    sqes = static_cast<io_uring_sqe*>(
        mmap(nullptr, sqes_sz, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES));
    if (sq_ring_ptr == MAP_FAILED || cq_ring_ptr == MAP_FAILED ||
        sqes == MAP_FAILED) {
      destroy();
      return false;
    }
    auto* sq = static_cast<uint8_t*>(sq_ring_ptr);
    auto* cq = static_cast<uint8_t*>(cq_ring_ptr);
    sq_tail = reinterpret_cast<unsigned*>(sq + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    cq_head = reinterpret_cast<unsigned*>(cq + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cq + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cq + p.cq_off.cqes);
    return true;
  }

  void destroy() {
    if (sq_ring_ptr && sq_ring_ptr != MAP_FAILED) munmap(sq_ring_ptr, sq_ring_sz);
    if (cq_ring_ptr && cq_ring_ptr != MAP_FAILED) munmap(cq_ring_ptr, cq_ring_sz);
    if (sqes && sqes != reinterpret_cast<io_uring_sqe*>(MAP_FAILED))
      munmap(sqes, sqes_sz);
    if (ring_fd >= 0) close(ring_fd);
    ring_fd = -1;
  }
  ~Uring() { destroy(); }

  io_uring_sqe* next_sqe() {
    unsigned tail = *sq_tail;  // single-threaded: plain read of our own tail
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* s = &sqes[idx];
    std::memset(s, 0, sizeof(*s));
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    to_submit++;
    return s;
  }

  // submit everything queued and wait for that many completions; calls
  // cb(user_data, res) for each. Returns false on enter failure (EINTR is
  // retried — a blocking enter is signal-interruptible under a Python
  // host, and one signal must not poison a whole group of files).
  template <typename F>
  bool submit_wait(F cb) {
    unsigned want = to_submit;
    to_submit = 0;
    unsigned submitted = 0;
    while (submitted < want) {
      long r = syscall(__NR_io_uring_enter, ring_fd, want - submitted,
                       want - submitted, IORING_ENTER_GETEVENTS, nullptr, 0);
      if (r < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      submitted += static_cast<unsigned>(r);
    }
    unsigned got = 0;
    while (got < want) {
      unsigned head = __atomic_load_n(cq_head, __ATOMIC_ACQUIRE);
      unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
      while (head != tail && got < want) {
        const io_uring_cqe& c = cqes[head & *cq_mask];
        cb(c.user_data, c.res);
        head++;
        got++;
      }
      __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
      if (got < want) {
        long r = syscall(__NR_io_uring_enter, ring_fd, 0, want - got,
                         IORING_ENTER_GETEVENTS, nullptr, 0);
        if (r < 0 && errno != EINTR) return false;
      }
    }
    return true;
  }
};

bool uring_disabled() {
  static const bool disabled = [] {
    const char* e = getenv("SD_NO_URING");
    return e && *e && *e != '0';
  }();
  return disabled;
}

// Effective gather queue depth: files in flight per uring round
// (SD_CAS_GATHER_DEPTH, default 128, clamped 1..2048). Read per call, not
// statically cached — the bench sweep and tests mutate the environment at
// runtime. The sampled-file round queues 6 reads per file, so the ring
// must be sized (and the group clamped) to 6× the depth.
int32_t gather_depth() {
  int32_t depth = 128;
  const char* e = getenv("SD_CAS_GATHER_DEPTH");
  if (e && *e) {
    char* end = nullptr;
    long v = strtol(e, &end, 10);
    if (end != e && v > 0) depth = static_cast<int32_t>(std::min<long>(v, 2048));
  }
  return depth;
}

// Smallest power-of-two ring that fits a full reads round at this depth
// (io_uring_setup rounds entries up to a power of two anyway; 32768 is the
// kernel's default ceiling).
unsigned ring_entries_for(int32_t depth) {
  uint64_t need = static_cast<uint64_t>(depth) * 6;
  unsigned entries = 64;
  while (entries < need && entries < 32768) entries <<= 1;
  return entries;
}

// Fill rows exactly like the synchronous gather loop, via an
// already-initialized ring (reused across groups by the batch hasher).
// Returns false only on ring INFRASTRUCTURE failure (enter refused) — the
// group's fds are plain-closed and the caller must redo the whole batch on
// the synchronous path; per-file IO errors stay in-band as lengths[i]=0.
bool uring_gather_ring(Uring& ring, const char* const* paths,
                       const uint64_t* sizes, int32_t n, uint8_t* out,
                       int64_t row_stride, int32_t* lengths,
                       int32_t group_hint) {
  struct Read {
    int32_t file;
    uint8_t* dst;
    uint64_t off;
    uint32_t want;
  };
  // 6 reads/file: the group is clamped so one reads round can never
  // overflow the ring the caller initialized (next_sqe has no overflow
  // check by design — rounds are sized to fit)
  const int32_t GROUP = std::max<int32_t>(
      1, std::min(group_hint, static_cast<int32_t>(ring.sq_entries / 6)));
  std::vector<int> fds(GROUP);
  std::vector<Read> reads, retry;
  std::vector<int32_t> remaining(GROUP);  // per-file outstanding read count
  std::vector<uint8_t> failed(GROUP);

  auto bail = [&](int32_t gn) {  // infra failure: recover fds, let caller
    for (int32_t j = 0; j < gn; j++)  // fall back to the sync path
      if (fds[j] >= 0) close(fds[j]);
    return false;
  };

  for (int32_t g0 = 0; g0 < n; g0 += GROUP) {
    int32_t gn = std::min<int32_t>(GROUP, n - g0);
    // --- opens
    for (int32_t j = 0; j < gn; j++) {
      io_uring_sqe* s = ring.next_sqe();
      s->opcode = IORING_OP_OPENAT;
      s->fd = AT_FDCWD;
      s->addr = reinterpret_cast<uint64_t>(paths[g0 + j]);
      s->open_flags = O_RDONLY;
      s->user_data = static_cast<uint64_t>(j);
      fds[j] = -1;
    }
    if (!ring.submit_wait([&](uint64_t ud, int32_t res) {
          fds[ud] = res;  // negative on failure
        }))
      return bail(gn);

    // --- build read list (size prefix written inline; oversize rows and
    // failed opens are marked straight away)
    reads.clear();
    for (int32_t j = 0; j < gn; j++) {
      int32_t i = g0 + j;
      lengths[i] = 0;
      remaining[j] = 0;
      failed[j] = 1;
      uint64_t size = sizes[i];
      uint64_t msg_len = msg_len_for(size);
      if (fds[j] < 0 || static_cast<int64_t>(msg_len) > row_stride) continue;
      failed[j] = 0;
      uint8_t* row = out + static_cast<int64_t>(i) * row_stride;
      for (int b = 0; b < 8; b++)
        row[b] = static_cast<uint8_t>(size >> (8 * b));
      uint8_t* dst = row + 8;
      if (size <= MINIMUM_FILE_SIZE) {
        if (size > 0) {
          reads.push_back({j, dst, 0, static_cast<uint32_t>(size)});
          remaining[j] = 1;
        }
      } else {
        uint64_t seek_jump = (size - HEADER_OR_FOOTER * 2) / SAMPLE_COUNT;
        reads.push_back({j, dst, 0, static_cast<uint32_t>(HEADER_OR_FOOTER)});
        dst += HEADER_OR_FOOTER;
        for (uint64_t smp = 0; smp < SAMPLE_COUNT; smp++) {
          reads.push_back({j, dst, HEADER_OR_FOOTER + smp * seek_jump,
                           static_cast<uint32_t>(SAMPLE_SIZE)});
          dst += SAMPLE_SIZE;
        }
        reads.push_back({j, dst, size - HEADER_OR_FOOTER,
                         static_cast<uint32_t>(HEADER_OR_FOOTER)});
        remaining[j] = 6;
      }
    }

    // --- reads, resubmitting short reads until each op errors or fills
    while (!reads.empty()) {
      retry.clear();
      for (size_t k = 0; k < reads.size(); k++) {
        const Read& rd = reads[k];
        io_uring_sqe* s = ring.next_sqe();
        s->opcode = IORING_OP_READ;
        s->fd = fds[rd.file];
        s->addr = reinterpret_cast<uint64_t>(rd.dst);
        s->len = rd.want;
        s->off = rd.off;
        s->user_data = k;
      }
      bool ok = ring.submit_wait([&](uint64_t ud, int32_t res) {
        Read& rd = reads[ud];
        if (failed[rd.file]) return;
        if (res <= 0) {
          failed[rd.file] = 1;
        } else if (static_cast<uint32_t>(res) < rd.want) {
          retry.push_back({rd.file, rd.dst + res, rd.off + res,
                           rd.want - static_cast<uint32_t>(res)});
        } else {
          remaining[rd.file]--;
        }
      });
      if (!ok) return bail(gn);
      reads.swap(retry);
    }

    // --- closes (results ignored; fd exhaustion surfaces on the next open)
    for (int32_t j = 0; j < gn; j++) {
      if (fds[j] < 0) continue;
      io_uring_sqe* s = ring.next_sqe();
      s->opcode = IORING_OP_CLOSE;
      s->fd = fds[j];
      s->user_data = static_cast<uint64_t>(j);
    }
    // close-round enter failure: an unknown subset of the CLOSEs already
    // ran, so re-closing here could hit a recycled fd — accept a one-time
    // leak of <= GROUP fds instead and let the caller fall back
    if (!ring.submit_wait([](uint64_t, int32_t) {})) return false;

    // --- finalize rows
    for (int32_t j = 0; j < gn; j++) {
      if (failed[j] || remaining[j] != 0) continue;
      int32_t i = g0 + j;
      uint64_t msg_len = msg_len_for(sizes[i]);
      uint8_t* row = out + static_cast<int64_t>(i) * row_stride;
      uint64_t pad = (64 - (msg_len & 63)) & 63;
      if (pad && static_cast<int64_t>(msg_len + pad) <= row_stride)
        std::memset(row + msg_len, 0, pad);
      lengths[i] = static_cast<int32_t>(msg_len);
    }
  }
  return true;
}

// One-shot wrapper: own ring sized to the configured depth, whole batch.
bool uring_gather(const char* const* paths, const uint64_t* sizes, int32_t n,
                  uint8_t* out, int64_t row_stride, int32_t* lengths) {
  if (uring_disabled()) return false;
  int32_t depth = gather_depth();
  Uring ring;
  // a host that refuses the big ring (memlock limits) still gets the
  // default-depth one — the clamp in uring_gather_ring keeps rounds legal
  if (!ring.init(ring_entries_for(depth))) {
    ring.destroy();
    if (!ring.init(1024)) return false;
  }
  return uring_gather_ring(ring, paths, sizes, n, out, row_stride, lengths,
                           depth);
}

#else
struct Uring {
  bool init(unsigned) { return false; }
};
bool uring_disabled() { return true; }
int32_t gather_depth() { return 128; }
unsigned ring_entries_for(int32_t) { return 1024; }
bool uring_gather_ring(Uring&, const char* const*, const uint64_t*, int32_t,
                       uint8_t*, int64_t, int32_t*, int32_t) {
  return false;
}
bool uring_gather(const char* const*, const uint64_t*, int32_t, uint8_t*,
                  int64_t, int32_t*) {
  return false;
}
#endif  // __linux__

}  // namespace

extern "C" {

// Full 32-byte BLAKE3 of a buffer → 64 hex chars + NUL.
void sd_blake3_hex(const uint8_t* data, uint64_t len, char out65[65]) {
  uint8_t digest[32];
  blake3_digest(data, len, digest);
  for (int i = 0; i < 32; i++) {
    out65[2 * i] = HEX[digest[i] >> 4];
    out65[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out65[64] = '\0';
}

// Batch full BLAKE3 over independent in-memory messages (the H_HASH
// service's no-accelerator path): cross-message SIMD lane filling via
// blake3_digest_batch. out = n rows of 65 (64 hex + NUL).
void sd_blake3_hex_batch(const uint8_t* const* msgs, const uint64_t* lens,
                         int32_t n, char* out) {
  std::vector<size_t> sl(lens, lens + n);
  std::vector<std::array<uint8_t, 32>> digests(std::max(n, 1));
  blake3_digest_batch(msgs, sl.data(), n,
                      reinterpret_cast<uint8_t(*)[32]>(digests[0].data()));
  for (int32_t i = 0; i < n; i++) {
    char* row = out + static_cast<size_t>(i) * 65;
    for (int b = 0; b < 32; b++) {
      row[2 * b] = HEX[digests[i][b] >> 4];
      row[2 * b + 1] = HEX[digests[i][b] & 0xF];
    }
    row[64] = '\0';
  }
}

// Full-file BLAKE3 (the validator's integrity_checksum — distinct from the
// sampled cas_id, reference core/src/object/validation/hash.rs:24). mmap'd so
// multi-GB files hash without buffering. Returns 0 on success.
int sd_blake3_file_hex(const char* path, char out65[65]) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return 1;
  off_t size = lseek(fd, 0, SEEK_END);
  if (size < 0) { close(fd); return 1; }
  const uint8_t* data = nullptr;
  if (size > 0) {
    void* p = mmap(nullptr, static_cast<size_t>(size), PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) { close(fd); return 1; }
    data = static_cast<const uint8_t*>(p);
    madvise(p, static_cast<size_t>(size), MADV_SEQUENTIAL);
  }
  uint8_t digest[32];
  size_t len = static_cast<size_t>(size);
  // per-window eviction: the merge stack is O(log n), but neither the
  // mapping's resident pages (madvise) nor the kernel page cache
  // (posix_fadvise) drop on their own — a 500 GB validator pass must not
  // carry a 500 GB RSS or churn the host's whole page cache
  constexpr size_t WB = WINDOW_CHUNKS * CHUNK_LEN;
  finalize_root(tree_windowed(data, len, 0, [&](size_t w) {
    madvise(const_cast<uint8_t*>(data) + w * WB, WB, MADV_DONTNEED);
    posix_fadvise(fd, static_cast<off_t>(w * WB),
                  static_cast<off_t>(WB), POSIX_FADV_DONTNEED);
  }), digest);
  if (data) munmap(const_cast<uint8_t*>(data), static_cast<size_t>(size));
  close(fd);
  for (int i = 0; i < 32; i++) {
    out65[2 * i] = HEX[digest[i] >> 4];
    out65[2 * i + 1] = HEX[digest[i] & 0xF];
  }
  out65[64] = '\0';
  return 0;
}

// Gather stage for the TPU path: read each file's cas sample message
// (size_le8 ‖ samples, cas.rs layout) straight into row i of a zero-padded
// (n, row_stride) byte matrix — the host side of the batched device hash,
// fused with IO so Python never copies per-file. lengths[i] gets the true
// message byte count; err-rows get length 0 (caller routes per-file errors).
void sd_cas_gather_batch(const char* const* paths, const uint64_t* sizes,
                         int32_t n, int32_t n_threads, uint8_t* out,
                         int64_t row_stride, int32_t* lengths) {
  if (n >= 8 && uring_gather(paths, sizes, n, out, row_stride, lengths))
    return;
  for_each_parallel(n, n_threads, [&](int32_t i) {
      uint8_t* row = out + static_cast<int64_t>(i) * row_stride;
      lengths[i] = 0;
      uint64_t size = sizes[i];
      uint64_t msg_len = msg_len_for(size);
      if (static_cast<int64_t>(msg_len) > row_stride) return;
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) return;
      for (int b = 0; b < 8; b++) row[b] = static_cast<uint8_t>(size >> (8 * b));
      uint8_t* dst = row + 8;
      auto read_exact = [&](uint64_t off, uint64_t len) -> bool {
        uint64_t got = 0;
        while (got < len) {
          ssize_t r = pread(fd, dst + got, len - got, off + got);
          if (r <= 0) return false;
          got += static_cast<uint64_t>(r);
        }
        dst += len;
        return true;
      };
      bool ok = true;
      if (size <= MINIMUM_FILE_SIZE) {
        ok = size == 0 || read_exact(0, size);
      } else {
        uint64_t seek_jump = (size - HEADER_OR_FOOTER * 2) / SAMPLE_COUNT;
        ok = read_exact(0, HEADER_OR_FOOTER);
        for (uint64_t s = 0; ok && s < SAMPLE_COUNT; s++) {
          ok = read_exact(HEADER_OR_FOOTER + s * seek_jump, SAMPLE_SIZE);
        }
        ok = ok && read_exact(size - HEADER_OR_FOOTER, HEADER_OR_FOOTER);
      }
      close(fd);
      if (ok) {
        // zero to the 64-byte block boundary: the device kernel compresses
        // whole blocks and relies on zero padding within the final one
        // (beyond that, per-lane block/chunk masks ignore the row tail)
        uint64_t pad = (64 - (msg_len & 63)) & 63;
        if (pad && static_cast<int64_t>(msg_len + pad) <= row_stride) {
          std::memset(row + msg_len, 0, pad);
        }
        lengths[i] = static_cast<int32_t>(msg_len);
      }
  });
}

// Batch cas_id over files. out = n rows of 17 bytes (16 hex + NUL); a row
// whose first byte is NUL means that file errored (caller raises per-file).
void sd_cas_hash_batch(const char* const* paths, const uint64_t* sizes,
                       int32_t n, int32_t n_threads, char* out) {
  // Batched IO path: one ring for the whole call; gather sample messages
  // for a cache-sized group of files with io_uring, then hash the rows
  // (threaded when the host has cores to spare) — ~4 submit syscalls per
  // 128 files instead of 9 syscalls per file.
  //
  // done = first index the uring path did NOT complete: a mid-batch ring
  // failure falls through to the synchronous loop for the *remaining*
  // files only, instead of re-opening and re-hashing groups whose rows
  // are already final.
  int32_t done = 0;
  if (n >= 8 && !uring_disabled()) {
    Uring ring;
    if (ring.init(1024)) {
      uint64_t max_msg = 64;
      for (int32_t i = 0; i < n; i++) {
        uint64_t msg_len = msg_len_for(sizes[i]);
        if (msg_len > max_msg) max_msg = msg_len;
      }
      int64_t stride = static_cast<int64_t>((max_msg + 63) & ~63ull);
      int32_t group = static_cast<int32_t>(
          std::max<int64_t>(1, (4ll << 20) / stride));
      std::vector<uint8_t> rows(static_cast<size_t>(group) * stride);
      std::vector<int32_t> lens(group);
      int32_t hash_threads = std::max<int32_t>(1, std::min(n_threads, group));
      bool uring_ok = true;
      for (int32_t g0 = 0; g0 < n && uring_ok; g0 += group) {
        int32_t gn = std::min(group, n - g0);
        uring_ok = uring_gather_ring(ring, paths + g0, sizes + g0, gn,
                                     rows.data(), stride, lens.data(), group);
        if (!uring_ok) break;  // this group unwritten: done stays at g0
        // cross-message SIMD: sort the group's messages by length (uniform
        // lane groups), hash 16 per pass, then write the cas hex rows
        std::vector<int32_t> order;
        order.reserve(gn);
        for (int32_t j = 0; j < gn; j++) {
          if (lens[j] == 0)
            out[static_cast<size_t>(g0 + j) * 17] = '\0';
          else
            order.push_back(j);
        }
        std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
          return lens[a] > lens[b];
        });
        std::vector<const uint8_t*> mptr(order.size());
        std::vector<size_t> mlen(order.size());
        for (size_t k = 0; k < order.size(); k++) {
          mptr[k] = rows.data() + static_cast<int64_t>(order[k]) * stride;
          mlen[k] = static_cast<size_t>(lens[order[k]]);
        }
        std::vector<std::array<uint8_t, 32>> digests(order.size());
        // one slice per 16-message lane group, ALIGNED at 16: slices must
        // not straddle the descending length sort or a lane group mixes
        // long and short messages and pads the short lanes to the longest
        // (wasted SIMD passes); for_each_parallel's atomic counter
        // load-balances the skewed groups dynamically
        const int32_t per = 16;
        int32_t slices = std::max<int32_t>(
            1, static_cast<int32_t>(order.size() + per - 1) / per);
        for_each_parallel(slices, hash_threads, [&](int32_t s) {
          int32_t a = s * per;
          int32_t b = std::min<int32_t>(a + per,
                                        static_cast<int32_t>(order.size()));
          if (a < b)
            blake3_digest_batch(
                mptr.data() + a, mlen.data() + a, b - a,
                reinterpret_cast<uint8_t(*)[32]>(digests[a].data()));
        });
        for (size_t k = 0; k < order.size(); k++) {
          char* row_out = out + static_cast<size_t>(g0 + order[k]) * 17;
          for (int b = 0; b < 8; b++) {
            row_out[2 * b] = HEX[digests[k][b] >> 4];
            row_out[2 * b + 1] = HEX[digests[k][b] & 0xF];
          }
          row_out[16] = '\0';
        }
        done = g0 + gn;
      }
      if (uring_ok) return;
    }
  }
  for_each_parallel(n - done, n_threads, [&](int32_t j) {
      int32_t i = done + j;
      char* row = out + static_cast<size_t>(i) * 17;
      row[0] = '\0';
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) return;
      cas_id_for_fd(fd, sizes[i], row);
      close(fd);
  });
}

}  // extern "C"
