"""ctypes binding for the native image helper (sd_images.cc).

The sd-images equivalent: JPEG/PNG decode straight into numpy RGB buffers
(JPEG downscales in DCT space during decode) and WebP encoding via libwebp
— the same C cores the reference's image/webp crates bind. Import fails
cleanly on hosts without the toolchain/libs; callers fall back to PIL.
"""

from __future__ import annotations

import ctypes
import threading
from pathlib import Path

import numpy as np

from . import build_shared

_lib = ctypes.CDLL(str(build_shared(
    "sdimages", ["sd_images.cc"],
    extra_libs=["-ljpeg", "-lpng", "-lwebp"])))

_lib.sd_image_decode_rgb.argtypes = [
    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
]
_lib.sd_image_decode_rgb.restype = ctypes.c_int64

_lib.sd_image_encode_webp.argtypes = [
    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_float,
    ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
]
_lib.sd_image_encode_webp.restype = ctypes.c_uint64

_lib.sd_webp_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
_lib.sd_webp_free.restype = None

#: formats the native decoder handles; everything else goes to the fallback
NATIVE_DECODE_EXTENSIONS = {"jpg", "jpeg", "png"}


class ImageDecodeError(Exception):
    pass


_scratch = threading.local()


def _scratch_buf(nbytes: int) -> np.ndarray:
    """Per-thread reusable decode buffer: thumbnail batches call decode_rgb
    once per image, and reallocating ~190 MiB per call churns the allocator
    and spikes RSS next to the JAX runtime."""
    buf = getattr(_scratch, "buf", None)
    if buf is None or buf.nbytes < nbytes:
        buf = np.empty(nbytes, np.uint8)
        _scratch.buf = buf
    return buf


def decode_rgb(path: str | Path, max_edge: int = 0,
               max_pixels: int = 64_000_000) -> np.ndarray:
    """Decode to an (h, w, 3) uint8 array. ``max_edge`` > 0 lets JPEG
    downscale during decode (output edge stays above max_edge; the caller
    finishes with its own resampler). Raises ImageDecodeError on
    unsupported/corrupt input (sd-images' max-size guards kept via
    ``max_pixels``)."""
    buf = _scratch_buf(max_pixels * 3)
    w = ctypes.c_int32(0)
    h = ctypes.c_int32(0)
    n = _lib.sd_image_decode_rgb(
        str(path).encode(), buf.ctypes.data, buf.nbytes, max_edge,
        ctypes.byref(w), ctypes.byref(h))
    if n <= 0:
        raise ImageDecodeError(f"native decode failed for {path} (rc={n})")
    return buf[:n].reshape(h.value, w.value, 3).copy()


def encode_webp(rgb: np.ndarray, quality: float = 30.0) -> bytes:
    if rgb.ndim != 3 or rgb.shape[2] != 3 or rgb.dtype != np.uint8:
        raise ValueError("encode_webp wants (h, w, 3) uint8")
    rgb = np.ascontiguousarray(rgb)
    out = ctypes.POINTER(ctypes.c_uint8)()
    n = _lib.sd_image_encode_webp(
        rgb.ctypes.data, rgb.shape[1], rgb.shape[0], float(quality),
        ctypes.byref(out))
    if n == 0:
        raise ImageDecodeError("webp encode failed")
    try:
        return ctypes.string_at(out, n)
    finally:
        _lib.sd_webp_free(out)
