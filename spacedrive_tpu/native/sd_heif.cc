// HEIF/AVIF decode for the image stack — the sd-images `heif` feature
// (crates/images/src/lib.rs:27-28 gates a libheif handler).
//
// This host ships the libheif runtime (libheif.so.1) but not its dev
// package, so the binding goes through dlopen/dlsym against the library's
// stable public C API (declarations below are written from the documented
// libheif 1.x API surface, not copied headers). Everything degrades
// cleanly: sd_heif_available() reports whether the runtime loaded, and the
// encode helper (test fixture generator) reports whether an HEVC/AV1
// encoder was compiled into this libheif build.

#include <dlfcn.h>

#include <cstdint>
#include <cstring>

namespace {

// -- minimal API surface (libheif public C API, 1.x) ------------------------

struct heif_error_t {
  int code;      // 0 == Ok
  int subcode;
  const char* message;
};

constexpr int kColorspaceRGB = 1;       // heif_colorspace_RGB
constexpr int kChromaInterleavedRGB = 10;  // heif_chroma_interleaved_RGB
constexpr int kChannelInterleaved = 10;    // heif_channel_interleaved
constexpr int kCompressionHEVC = 1;        // heif_compression_HEVC
constexpr int kCompressionAV1 = 4;         // heif_compression_AV1

using ctx_alloc_t = void* (*)();
using ctx_free_t = void (*)(void*);
using ctx_read_file_t = heif_error_t (*)(void*, const char*, const void*);
using ctx_primary_handle_t = heif_error_t (*)(void*, void**);
using handle_release_t = void (*)(void*);
using handle_dim_t = int (*)(void*);
using decode_image_t = heif_error_t (*)(void*, void**, int, int, const void*);
using image_release_t = void (*)(void*);
using image_plane_ro_t = const uint8_t* (*)(void*, int, int*);
using image_get_dim_t = int (*)(const void*, int);
using ctx_get_encoder_t = heif_error_t (*)(void*, int, void**);
using encoder_release_t = void (*)(void*);
using encoder_lossy_q_t = heif_error_t (*)(void*, int);
using image_create_t = heif_error_t (*)(int, int, int, int, void**);
using image_add_plane_t = heif_error_t (*)(void*, int, int, int, int);
using image_plane_t = uint8_t* (*)(void*, int, int*);
using ctx_encode_t = heif_error_t (*)(void*, void*, void*, const void*, void**);
using ctx_write_file_t = heif_error_t (*)(void*, const char*);

struct Heif {
  void* dl = nullptr;
  ctx_alloc_t ctx_alloc;
  ctx_free_t ctx_free;
  ctx_read_file_t ctx_read_file;
  ctx_primary_handle_t ctx_primary_handle;
  handle_release_t handle_release;
  handle_dim_t handle_width;
  handle_dim_t handle_height;
  decode_image_t decode_image;
  image_release_t image_release;
  image_plane_ro_t image_plane_ro;
  image_get_dim_t image_width;
  image_get_dim_t image_height;
  ctx_get_encoder_t ctx_get_encoder;
  encoder_release_t encoder_release;
  encoder_lossy_q_t encoder_set_quality;
  image_create_t image_create;
  image_add_plane_t image_add_plane;
  image_plane_t image_plane;
  ctx_encode_t ctx_encode;
  ctx_write_file_t ctx_write_file;
};

Heif* load_heif() {
  static Heif heif;
  static bool attempted = false;
  if (attempted) return heif.dl ? &heif : nullptr;
  attempted = true;
  void* dl = dlopen("libheif.so.1", RTLD_NOW | RTLD_LOCAL);
  if (!dl) dl = dlopen("libheif.so", RTLD_NOW | RTLD_LOCAL);
  if (!dl) return nullptr;
  auto sym = [&](const char* name) { return dlsym(dl, name); };
#define SD_HEIF_LOAD(field, name, type)                       \
  heif.field = reinterpret_cast<type>(sym(name));             \
  if (!heif.field) {                                          \
    dlclose(dl);                                              \
    return nullptr;                                           \
  }
  SD_HEIF_LOAD(ctx_alloc, "heif_context_alloc", ctx_alloc_t)
  SD_HEIF_LOAD(ctx_free, "heif_context_free", ctx_free_t)
  SD_HEIF_LOAD(ctx_read_file, "heif_context_read_from_file", ctx_read_file_t)
  SD_HEIF_LOAD(ctx_primary_handle, "heif_context_get_primary_image_handle",
               ctx_primary_handle_t)
  SD_HEIF_LOAD(handle_release, "heif_image_handle_release", handle_release_t)
  SD_HEIF_LOAD(handle_width, "heif_image_handle_get_width", handle_dim_t)
  SD_HEIF_LOAD(handle_height, "heif_image_handle_get_height", handle_dim_t)
  SD_HEIF_LOAD(decode_image, "heif_decode_image", decode_image_t)
  SD_HEIF_LOAD(image_release, "heif_image_release", image_release_t)
  SD_HEIF_LOAD(image_plane_ro, "heif_image_get_plane_readonly",
               image_plane_ro_t)
  SD_HEIF_LOAD(image_width, "heif_image_get_width", image_get_dim_t)
  SD_HEIF_LOAD(image_height, "heif_image_get_height", image_get_dim_t)
  SD_HEIF_LOAD(ctx_get_encoder, "heif_context_get_encoder_for_format",
               ctx_get_encoder_t)
  SD_HEIF_LOAD(encoder_release, "heif_encoder_release", encoder_release_t)
  SD_HEIF_LOAD(encoder_set_quality, "heif_encoder_set_lossy_quality",
               encoder_lossy_q_t)
  SD_HEIF_LOAD(image_create, "heif_image_create", image_create_t)
  SD_HEIF_LOAD(image_add_plane, "heif_image_add_plane", image_add_plane_t)
  SD_HEIF_LOAD(image_plane, "heif_image_get_plane", image_plane_t)
  SD_HEIF_LOAD(ctx_encode, "heif_context_encode_image", ctx_encode_t)
  SD_HEIF_LOAD(ctx_write_file, "heif_context_write_to_file", ctx_write_file_t)
#undef SD_HEIF_LOAD
  heif.dl = dl;
  return &heif;
}

}  // namespace

extern "C" {

int sd_heif_available() { return load_heif() != nullptr; }

// Primary-image dimensions WITHOUT decoding (the metadata extractor's
// path: reading the handle's declared size costs parsing, not an HEVC
// decode). Returns 0 on success, -1 unavailable, -2 unreadable.
int32_t sd_heif_dims(const char* path, int32_t* out_w, int32_t* out_h) {
  Heif* h = load_heif();
  if (!h) return -1;
  void* ctx = h->ctx_alloc();
  if (!ctx) return -2;
  void* handle = nullptr;
  int32_t rc = -2;
  if (h->ctx_read_file(ctx, path, nullptr).code == 0 &&
      h->ctx_primary_handle(ctx, &handle).code == 0) {
    int w = h->handle_width(handle), hh = h->handle_height(handle);
    if (w > 0 && hh > 0) {
      *out_w = w;
      *out_h = hh;
      rc = 0;
    }
  }
  if (handle) h->handle_release(handle);
  h->ctx_free(ctx);
  return rc;
}

// Decode the primary image of a HEIF/AVIF file to packed RGB24.
// Returns bytes written (w*h*3) or negative: -1 unavailable, -2 decode
// failure, -3 buffer too small.
int64_t sd_heif_decode_rgb(const char* path, uint8_t* out, int64_t cap,
                           int32_t* out_w, int32_t* out_h) {
  Heif* h = load_heif();
  if (!h) return -1;
  void* ctx = h->ctx_alloc();
  if (!ctx) return -2;
  void* handle = nullptr;
  void* img = nullptr;
  int64_t rc = -2;
  int w = 0, hh = 0, stride = 0;
  const uint8_t* plane = nullptr;

  if (h->ctx_read_file(ctx, path, nullptr).code != 0) goto done;
  if (h->ctx_primary_handle(ctx, &handle).code != 0) goto done;
  // pre-decode guard on the DECLARED size (bounds the decode allocation)
  if (static_cast<int64_t>(h->handle_width(handle)) *
          h->handle_height(handle) * 3 > cap) {
    rc = -3;
    goto done;
  }
  if (h->decode_image(handle, &img, kColorspaceRGB, kChromaInterleavedRGB,
                      nullptr).code != 0)
    goto done;
  // dimensions MUST come from the decoded image, not the container's
  // declared (ispe) size — a crafted file whose header overstates the
  // dimensions would otherwise drive the row copy past the plane buffer
  w = h->image_width(img, kChannelInterleaved);
  hh = h->image_height(img, kChannelInterleaved);
  if (w <= 0 || hh <= 0) goto done;
  if (static_cast<int64_t>(w) * hh * 3 > cap) {
    rc = -3;
    goto done;
  }
  plane = h->image_plane_ro(img, kChannelInterleaved, &stride);
  if (!plane || stride < w * 3) goto done;
  for (int y = 0; y < hh; y++)
    memcpy(out + static_cast<int64_t>(y) * w * 3,
           plane + static_cast<int64_t>(y) * stride, static_cast<size_t>(w) * 3);
  *out_w = w;
  *out_h = hh;
  rc = static_cast<int64_t>(w) * hh * 3;

done:
  if (img) h->image_release(img);
  if (handle) h->handle_release(handle);
  h->ctx_free(ctx);
  return rc;
}

// Encode RGB24 to a .heic/.avif file (test fixture generator). Returns 0,
// or -1 unavailable, -4 when this libheif has no HEVC/AV1 encoder (tests
// skip), -2 other failure.
int32_t sd_heif_encode_file(const char* path, const uint8_t* rgb, int32_t w,
                            int32_t h_px, int32_t quality) {
  Heif* h = load_heif();
  if (!h) return -1;
  void* ctx = h->ctx_alloc();
  if (!ctx) return -2;
  void* enc = nullptr;
  void* img = nullptr;
  void* out_handle = nullptr;
  int32_t rc = -2;
  int stride = 0;
  uint8_t* plane = nullptr;

  if (h->ctx_get_encoder(ctx, kCompressionHEVC, &enc).code != 0 &&
      h->ctx_get_encoder(ctx, kCompressionAV1, &enc).code != 0) {
    rc = -4;
    goto done;
  }
  h->encoder_set_quality(enc, quality);
  if (h->image_create(w, h_px, kColorspaceRGB, kChromaInterleavedRGB, &img)
          .code != 0)
    goto done;
  if (h->image_add_plane(img, kChannelInterleaved, w, h_px, 8).code != 0)
    goto done;
  plane = h->image_plane(img, kChannelInterleaved, &stride);
  if (!plane) goto done;
  for (int y = 0; y < h_px; y++)
    memcpy(plane + static_cast<int64_t>(y) * stride,
           rgb + static_cast<int64_t>(y) * w * 3, static_cast<size_t>(w) * 3);
  if (h->ctx_encode(ctx, img, enc, nullptr, &out_handle).code != 0) goto done;
  if (h->ctx_write_file(ctx, path).code != 0) goto done;
  rc = 0;

done:
  if (out_handle) h->handle_release(out_handle);
  if (img) h->image_release(img);
  if (enc) h->encoder_release(enc);
  h->ctx_free(ctx);
  return rc;
}

}  // extern "C"
