"""ctypes binding for the linked FFmpeg wrapper (sd_ffmpeg.cc).

The sd-ffmpeg equivalent (crates/ffmpeg/src/lib.rs:9-33): video frame
decode for thumbnails — preferring embedded cover art, else seeking 10%
in — plus stream probing for the media-data extractor and a tiny test
encoder. Import fails cleanly on hosts without libav* dev headers; callers
fall back to the ffmpeg CLI or skip video handling.
"""

from __future__ import annotations

import ctypes
import json
from pathlib import Path
from typing import Any

import numpy as np

from . import build_shared

_lib = ctypes.CDLL(str(build_shared(
    "sdffmpeg", ["sd_ffmpeg.cc"],
    extra_libs=["-lavformat", "-lavcodec", "-lavutil", "-lswscale"])))

_lib.sd_ffmpeg_probe_json.argtypes = [
    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
_lib.sd_ffmpeg_probe_json.restype = ctypes.c_int64

_lib.sd_ffmpeg_decode_frame_rgb.argtypes = [
    ctypes.c_char_p, ctypes.c_double, ctypes.c_int32, ctypes.c_void_p,
    ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32)]
_lib.sd_ffmpeg_decode_frame_rgb.restype = ctypes.c_int64

_lib.sd_ffmpeg_write_test_video.argtypes = [
    ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ctypes.c_int32]
_lib.sd_ffmpeg_write_test_video.restype = ctypes.c_int32

_lib.sd_ffmpeg_err_str.argtypes = [
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
_lib.sd_ffmpeg_err_str.restype = None

#: seek point as a fraction of duration (thumbnailer.rs seek_percentage 0.1)
SEEK_PERCENTAGE = 0.1

#: default decode edge for video thumbnails (thumbnail/mod.rs:183 passes 256
#: to to_thumbnail; we decode a little larger so the √-area scale step has
#: headroom on wide aspect ratios)
DEFAULT_TARGET_EDGE = 768


class FfmpegError(Exception):
    def __init__(self, code: int):
        buf = ctypes.create_string_buffer(256)
        _lib.sd_ffmpeg_err_str(int(code), buf, 256)
        super().__init__(buf.value.decode(errors="replace"))
        self.code = int(code)


def probe(path: str | Path) -> dict[str, Any]:
    """Format/stream metadata: duration, bit_rate, container tags, streams
    (codec, dims, fps, channels, sample_rate, attached_pic)."""
    cap = 1 << 16
    buf = ctypes.create_string_buffer(cap)
    rc = _lib.sd_ffmpeg_probe_json(str(path).encode(), buf, cap)
    if rc < 0:
        raise FfmpegError(rc)
    return json.loads(buf.value.decode(errors="replace"))


def decode_frame_rgb(path: str | Path, seek_percent: float = SEEK_PERCENTAGE,
                     target_edge: int = DEFAULT_TARGET_EDGE) -> np.ndarray:
    """One representative RGB frame as an (h, w, 3) uint8 array."""
    edge = target_edge if target_edge > 0 else 8192
    cap = edge * edge * 3
    out = np.empty(cap, np.uint8)
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = _lib.sd_ffmpeg_decode_frame_rgb(
        str(path).encode(), float(seek_percent), int(target_edge),
        out.ctypes.data_as(ctypes.c_void_p), cap,
        ctypes.byref(w), ctypes.byref(h))
    if rc < 0:
        raise FfmpegError(rc)
    return out[:rc].reshape(h.value, w.value, 3).copy()


def write_test_video(path: str | Path, width: int = 64, height: int = 48,
                     frames: int = 24, fps: int = 12) -> None:
    """Encode a small gradient video (test fixture generator)."""
    rc = _lib.sd_ffmpeg_write_test_video(
        str(path).encode(), int(width), int(height), int(frames), int(fps))
    if rc != 0:
        raise FfmpegError(rc)
