"""Library preferences: a nested KV tree flattened into Preference rows.

Parity with core/src/preferences/{mod,kv,library}.rs: preferences are a JSON
tree (e.g. per-location explorer settings) stored as dotted-path keys so
partial updates touch only the affected rows (kv.rs:160's flatten). Keys are
synced via the Preference model's ``SYNC = Shared(id="key")`` annotation.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from .models import Preference

if TYPE_CHECKING:
    from .library import Library


def _flatten(tree: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, value in tree.items():
        if "." in key:
            raise ValueError(f"preference keys may not contain dots: {key!r}")
        path = f"{prefix}.{key}" if prefix else key
        if isinstance(value, dict) and value and all(isinstance(k, str) for k in value):
            out.update(_flatten(value, path))
        else:
            out[path] = value
    return out


def _unflatten(rows: dict[str, Any]) -> dict[str, Any]:
    tree: dict[str, Any] = {}
    for path, value in rows.items():
        node = tree
        parts = path.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
            if not isinstance(node, dict):
                break
        else:
            node[parts[-1]] = value
    return tree


def update_preferences(library: "Library", tree: dict[str, Any]) -> None:
    """Merge a (partial) preference tree; ``None`` leaves delete keys."""
    flat = _flatten(tree)
    db = library.db
    sync = getattr(library, "sync", None)
    emit = sync is not None and getattr(sync, "emit_messages", False)
    ops = []
    with db.transaction():
        for key, value in flat.items():
            if value is None:
                db.delete(Preference, {"key": key})
                if emit:
                    ops.append(sync.shared_delete(Preference, key))
            else:
                db.upsert(Preference, {"key": key}, {"value": value}, {"value": value})
                if emit:
                    ops.append(sync.shared_update(Preference, key, "value", value))
        if ops:
            sync.log_ops(ops)
    if ops:
        sync.created()
    library.emit("invalidate_query", {"key": "preferences.get"})


def get_preferences(library: "Library") -> dict[str, Any]:
    rows = {r["key"]: r["value"] for r in library.db.find(Preference)}
    return _unflatten(rows)
