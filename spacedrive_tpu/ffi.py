"""Mobile-FFI bridge: the JSON-string core interface a host shell embeds.

Reference: apps/mobile/modules/sd-core/core/src/lib.rs — the mobile shells
embed the whole Node in-process and talk to it through a JSON-RPC string
bridge (`handle_core_msg`, :61-117) plus an event pump
(`spawn_core_event_listener`, :119+). Same pattern here: the C shim
(native/sd_core_ffi.cc) embeds CPython and calls these four functions; a
JNI/Swift host needs nothing but a C ABI.

Wire shapes:
    handle_core_msg('{"id":1,"key":"libraries.list","arg":null,
                     "library_id":null}')
        → '{"id":1,"result":[...]}' or '{"id":1,"error":"..."}'
    poll_core_event(timeout_ms) → '{"kind":"job_progress",...}' or '' (none)
"""

from __future__ import annotations

import json
import threading

_node = None
_events = None
_lock = threading.Lock()


def init_core(data_dir: str) -> str:
    """Boot the Node (idempotent per process). Returns '{"ok":true}'."""
    global _node, _events
    with _lock:
        if _node is not None:
            return json.dumps({"ok": True, "already": True})
        from .node import Node

        try:
            # boot-once guard: the lock EXISTS to make concurrent callers
            # wait for the one Node construction (robustness.md waivers)
            _node = Node(data_dir)  # lint: ok(hold-blocking)
            _events = _node.events.subscribe()
        except Exception as e:
            return json.dumps({"ok": False, "error": repr(e)})
        return json.dumps({"ok": True})


def handle_core_msg(raw: str) -> str:
    """One JSON-RPC request → one JSON response (lib.rs:61-117)."""
    try:
        msg = json.loads(raw)
    except json.JSONDecodeError as e:
        return json.dumps({"id": None, "error": f"bad json: {e}"})
    msg_id = msg.get("id")
    if _node is None:
        return json.dumps({"id": msg_id, "error": "core not initialized"})
    try:
        result = _node.router.resolve(msg.get("key", ""), msg.get("arg"),
                                      msg.get("library_id"))
        return json.dumps({"id": msg_id, "result": result}, default=str)
    except Exception as e:
        return json.dumps({"id": msg_id, "error": str(e)})


def poll_core_event(timeout_ms: int = 0) -> str:
    """Next CoreEvent as JSON, or "" when none arrives in time (the event
    pump the host's listener thread drives, lib.rs:119+)."""
    if _events is None:
        return ""
    event = _events.get(timeout=max(0, timeout_ms) / 1000.0)
    if event is None:
        return ""
    return json.dumps({"kind": event.kind,
                       "payload": getattr(event, "payload", None),
                       "library_id": getattr(event, "library_id", None)},
                      default=str)


def shutdown_core() -> str:
    global _node, _events
    with _lock:
        if _node is None:
            return json.dumps({"ok": True, "already": True})
        try:
            if _events is not None:
                _events.close()
            _node.shutdown()
        finally:
            _node = None
            _events = None
        return json.dumps({"ok": True})
