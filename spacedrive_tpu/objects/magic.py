"""Magic-byte kind resolution for conflicting/unknown extensions.

Reference: crates/file-ext/src/magic.rs — extensions with several plausible
formats (`ExtensionPossibility::Conflicts`, e.g. ``ts`` TypeScript vs
MPEG-TS, ``db`` SQLite vs anything) are disambiguated by header signatures;
the identifier consults it at file_identifier/mod.rs:75. Table-driven here:
each signature is (offset, bytes) pairs that must all match within the
first 512 bytes.
"""

from __future__ import annotations

import logging
from pathlib import Path

from .kind import ObjectKind, kind_from_extension

logger = logging.getLogger(__name__)

HEADER_LEN = 512

#: (kind, [(offset, signature bytes), ...]) — first match wins, ordered
#: most-specific first (RIFF/ftyp containers before generic prefixes)
MAGIC_SIGNATURES: list[tuple[int, list[tuple[int, bytes]]]] = [
    # containers whose subtype picks the kind
    (ObjectKind.IMAGE, [(0, b"RIFF"), (8, b"WEBP")]),
    (ObjectKind.AUDIO, [(0, b"RIFF"), (8, b"WAVE")]),
    (ObjectKind.VIDEO, [(0, b"RIFF"), (8, b"AVI ")]),
    (ObjectKind.IMAGE, [(4, b"ftypheic")]),
    (ObjectKind.IMAGE, [(4, b"ftypheix")]),
    (ObjectKind.IMAGE, [(4, b"ftypavif")]),
    (ObjectKind.AUDIO, [(4, b"ftypM4A")]),
    (ObjectKind.VIDEO, [(4, b"ftyp")]),          # generic ISO-BMFF → video
    # images
    (ObjectKind.IMAGE, [(0, b"\x89PNG\r\n\x1a\n")]),
    (ObjectKind.IMAGE, [(0, b"\xff\xd8\xff")]),
    (ObjectKind.IMAGE, [(0, b"GIF87a")]),
    (ObjectKind.IMAGE, [(0, b"GIF89a")]),
    (ObjectKind.IMAGE, [(0, b"II*\x00")]),        # TIFF LE
    (ObjectKind.IMAGE, [(0, b"MM\x00*")]),        # TIFF BE
    (ObjectKind.IMAGE, [(0, b"BM")]),
    (ObjectKind.IMAGE, [(0, b"8BPS")]),           # psd
    # audio
    (ObjectKind.AUDIO, [(0, b"ID3")]),
    (ObjectKind.AUDIO, [(0, b"\xff\xfb")]),
    (ObjectKind.AUDIO, [(0, b"\xff\xf3")]),
    (ObjectKind.AUDIO, [(0, b"fLaC")]),
    (ObjectKind.AUDIO, [(0, b"OggS")]),
    (ObjectKind.AUDIO, [(0, b"MThd")]),           # midi
    # video
    (ObjectKind.VIDEO, [(0, b"\x1a\x45\xdf\xa3")]),  # EBML: mkv/webm
    (ObjectKind.VIDEO, [(0, b"\x47"), (188, b"\x47")]),  # MPEG-TS sync beat
    (ObjectKind.VIDEO, [(0, b"\x00\x00\x01\xba")]),  # MPEG-PS
    # archives
    (ObjectKind.ARCHIVE, [(0, b"PK\x03\x04")]),
    (ObjectKind.ARCHIVE, [(0, b"\x1f\x8b")]),     # gzip
    (ObjectKind.ARCHIVE, [(0, b"7z\xbc\xaf\x27\x1c")]),
    (ObjectKind.ARCHIVE, [(0, b"Rar!\x1a\x07")]),
    (ObjectKind.ARCHIVE, [(0, b"BZh")]),
    (ObjectKind.ARCHIVE, [(0, b"\xfd7zXZ\x00")]),
    (ObjectKind.ARCHIVE, [(0, b"\x28\xb5\x2f\xfd")]),  # zstd
    (ObjectKind.ARCHIVE, [(257, b"ustar")]),      # tar
    # executables
    (ObjectKind.EXECUTABLE, [(0, b"\x7fELF")]),
    (ObjectKind.EXECUTABLE, [(0, b"MZ")]),
    (ObjectKind.EXECUTABLE, [(0, b"\xca\xfe\xba\xbe")]),  # mach-o fat / class
    (ObjectKind.EXECUTABLE, [(0, b"\xcf\xfa\xed\xfe")]),  # mach-o 64
    # documents / databases / fonts / misc
    (ObjectKind.DOCUMENT, [(0, b"%PDF-")]),
    (ObjectKind.DATABASE, [(0, b"SQLite format 3\x00")]),
    (ObjectKind.FONT, [(0, b"\x00\x01\x00\x00\x00")]),  # ttf
    (ObjectKind.FONT, [(0, b"OTTO")]),
    (ObjectKind.FONT, [(0, b"wOFF")]),
    (ObjectKind.FONT, [(0, b"wOF2")]),
    (ObjectKind.ENCRYPTED, [(0, b"sdtpenc")]),    # this framework's header
    (ObjectKind.IMAGE, [(0, b"<svg")]),
    (ObjectKind.BOOK, [(0, b"%!PS")]),
]

#: extensions whose meaning is ambiguous enough that magic wins when found
#: (the Conflicts arm of ExtensionPossibility, magic.rs:12-15)
CONFLICTING_EXTENSIONS = {
    "ts",    # TypeScript vs MPEG-TS
    "mts",   # MPEG-TS vs Metal shader
    "m2ts",
    "db",    # SQLite vs generic data
    "key",   # key material vs Keynote
    "s",     # assembly vs other
    "raw",   # camera raw vs raw bytes
    "dat",
    "bin",
    "mid",   # midi vs other
}


# First-byte dispatch table: scanning all ~46 signatures per file costs
# ~90µs in the identifier's object-creation hot loop; bucketing by the
# first signature byte cuts the candidate set to 0–3 per file. Entries
# keep their MAGIC_SIGNATURES index so overlapping candidates (e.g. an
# offset-257 tar signature vs an offset-0 one) are still tried in the
# original priority order.
def _build_sniff_table() -> tuple[dict[int, list], dict[int, list]]:
    by_first: dict[int, list] = {}
    by_offset: dict[int, list] = {}  # first part not at offset 0
    for i, (kind, parts) in enumerate(MAGIC_SIGNATURES):
        off, sig = parts[0]
        if off == 0 and sig:
            by_first.setdefault(sig[0], []).append((i, kind, parts))
        else:
            # grouped by (offset, first byte): the common miss then costs
            # one byte compare per group instead of a candidate scan
            by_offset.setdefault(off, []).append((i, kind, parts))
    return ({b: sorted(v) for b, v in by_first.items()},
            {o: sorted(v) for o, v in by_offset.items()})


_SNIFF_BY_FIRST, _SNIFF_BY_OFFSET = _build_sniff_table()
_EMPTY: list = []


def sniff_kind(head: bytes) -> int | None:
    """Header bytes → ObjectKind, or None when no signature matches.
    Priority order (MAGIC_SIGNATURES index) is preserved across the
    offset-0 bucket and the offset groups."""
    if not head:
        return None
    candidates = _SNIFF_BY_FIRST.get(head[0], _EMPTY)
    extra: list = []
    for off, group in _SNIFF_BY_OFFSET.items():
        if len(head) > off and any(head[off] == g[2][0][1][0] for g in group):
            extra = extra + group
    if extra:
        candidates = sorted(candidates + extra)
    for _, kind, parts in candidates:
        if all(head[off:off + len(sig)] == sig for off, sig in parts):
            return kind
    return None


def looks_text(head: bytes) -> bool:
    """sd-file-ext's text detection: NUL-free, valid UTF-8 (tolerating a
    multibyte sequence cut at the sample edge), mostly printable."""
    if not head or b"\x00" in head:
        return False
    try:
        text = head.decode("utf-8")
    except UnicodeDecodeError as e:
        # only a full HEADER_LEN sample can have a cut multibyte tail, and
        # a sequence starting ≥4 bytes before the end had room to finish —
        # anything else is genuinely invalid, not truncated
        if len(head) < HEADER_LEN or e.start < len(head) - 3:
            return False
        text = head[:e.start].decode("utf-8")
        if not text:
            return False
    printable = sum(ch.isprintable() or ch in "\t\n\r\f" for ch in text)
    return printable >= 0.97 * len(text)


def _read_head(path: str | Path) -> bytes:
    try:
        with open(path, "rb") as fh:
            return fh.read(HEADER_LEN)
    except OSError:
        return b""


def resolve_kind(extension: str | None, path: str | Path | None = None,
                 is_dir: bool = False, head: bytes | None = None) -> int:
    """Extension-first resolution with magic-byte override for conflicting
    or unknown extensions (Extension::resolve_conflicting semantics):
    a confident extension wins without touching the disk; otherwise the
    header decides; the extension table is the fallback."""
    ext_kind = kind_from_extension(extension, is_dir)
    if is_dir:
        return ext_kind
    ext = (extension or "").lower().lstrip(".")
    needs_magic = ext in CONFLICTING_EXTENSIONS or ext_kind == ObjectKind.UNKNOWN
    if not needs_magic:
        return ext_kind
    if head is None:
        if path is None:
            return ext_kind
        head = _read_head(path)
    if not head:
        return ext_kind
    sniffed = sniff_kind(head)
    if sniffed is not None:
        return sniffed
    # no signature: an unknown extension with readable content is TEXT
    # (sd-file-ext text detection)
    if ext_kind == ObjectKind.UNKNOWN and looks_text(head):
        return ObjectKind.TEXT
    return ext_kind
