"""Opt-in chunk-manifest stage of FileIdentifierJob (``SD_CHUNK_MANIFESTS=1``).

The identifier's sharded gather already has every file's head bytes in
flight; with manifests on, each page additionally carries whole-file chunk
payloads (small files reuse the cas message body byte-for-byte — zero extra
I/O; larger files re-read once, capped at ``SD_CHUNK_MAX_BYTES``), the
process stage chunks them with the ops/cdc.py gear kernel behind a
:class:`~.hasher.BackendRouter` instance (EWMA device-vs-native-CPU per
batch, same hysteresis/exploration/degrade ladder as the hash router, its
own ``sd_chunk_router_*`` families), and the commit stage persists the
``chunk_manifest`` table inside the identifier's existing transaction —
RowJournal-noted, so the device query engine and sync both see manifest
churn.

Stage discipline mirrors the identifier exactly: the gather and process
helpers here are read-only/compute-only (sdlint's pipeline-ordering and
commit-discipline passes know these names), per-item failures quarantine
instead of killing the batch (``chunk`` fault seam: eio retries under the
same transient policy as the cas gather, so a transient storm yields
byte-identical manifests; persistent failures quarantine per item), and a
device wedge mid-dispatch degrades to the numpy rung over the same
payloads — byte-identical chunk ids by the cdc module's cross-rung
guarantee.
"""

from __future__ import annotations

import logging
import os
import time

from .. import faults, telemetry
from ..models import ChunkManifest
from ..ops import cdc
from ..utils.retry import RetryPolicy, is_transient_io, retry_call
from .hasher import BackendRouter

logger = logging.getLogger(__name__)

# -- telemetry: declared at import time (file_identifier imports this
# module unconditionally) so every family below renders on /metrics with
# zero samples and the observability.md drift gate holds both directions
_CHUNK_FILES = telemetry.counter(
    "sd_chunk_files_total", "files chunked into manifests")
_CHUNK_CHUNKS = telemetry.counter(
    "sd_chunk_chunks_total", "content-defined chunks produced")
_CHUNK_BYTES = telemetry.counter(
    "sd_chunk_bytes_total", "payload bytes run through the CDC kernel")
_CHUNK_QUARANTINED = telemetry.counter(
    "sd_chunk_quarantined_total",
    "per-item manifest failures quarantined (file still identifies)")
_CHUNK_SKIPPED = telemetry.counter(
    "sd_chunk_skipped_total",
    "files skipped by the manifest stage (payload over SD_CHUNK_MAX_BYTES)")
_CHUNK_ROUTER_BPS = telemetry.gauge(
    "sd_chunk_router_bytes_per_sec",
    "EWMA transfer-inclusive CDC payload bytes/s per engine (router input)",
    labels=("backend",))
_CHUNK_ROUTER_FLIPS = telemetry.counter(
    "sd_chunk_router_flips_total",
    "engine flips by the per-batch chunk router (hysteresis-damped)")
_CHUNK_ROUTER_BATCHES = telemetry.counter(
    "sd_chunk_router_batches_total",
    "chunk (sub-)batches the router dispatched per engine",
    labels=("backend",))

#: the chunk stage's own router instance — same logic as the hash router,
#: separate EWMAs (CDC arithmetic intensity is nothing like BLAKE3's)
router = BackendRouter(flips_counter=_CHUNK_ROUTER_FLIPS,
                       batches_counter=_CHUNK_ROUTER_BATCHES,
                       bps_gauge=_CHUNK_ROUTER_BPS, mfu_gauge=None,
                       event_prefix="chunk_router")

#: transient payload-read retries (same shape as cas.GATHER_RETRY): an
#: injected/organic EIO storm retries clean, so manifests under chaos stay
#: byte-identical to the fault-free run
PAYLOAD_RETRY = RetryPolicy(attempts=3, base_s=0.01, max_s=0.1, budget_s=1.0)

#: files above the whole-payload cap skip manifests (sd_chunk_skipped_total)
DEFAULT_MAX_BYTES = 4 * 1024 * 1024

#: the cas message is size_le_8 ‖ content for files at or under this
#: (cas.MINIMUM_FILE_SIZE) — their payload is the message body, free
_SMALL = 102400


def manifests_enabled() -> bool:
    return os.environ.get("SD_CHUNK_MANIFESTS", "").strip().lower() in (
        "1", "true", "on", "yes")


def payload_cap() -> int:
    raw = os.environ.get("SD_CHUNK_MAX_BYTES", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return DEFAULT_MAX_BYTES


# -- stage 1 half: payload gather (rides _gather_rows, read-only) -----------


def _read_payload(path: str, msg: "bytes | Exception", size: int) -> bytes:
    """One file's whole-content chunk payload. The ``chunk`` fault seam sits
    here — inside the retry, like the cas gather's — so ``chunk:eio:p``
    storms retry clean and ``chunk:kill`` dies at the exact read."""
    faults.inject("chunk", key=path)
    if size <= _SMALL and not isinstance(msg, Exception):
        return bytes(msg[8:])
    with open(path, "rb") as fh:
        return fh.read(size)


def pipeline_chunk_gather(paths: list[str], rows: list[dict],
                          messages: list) -> None:
    """Attach ``row['_chunk_payload']`` to every hashable row: the payload
    bytes, ``None`` (cas gather already failed the file, or it is over the
    cap — skipped, not quarantined), or the post-retry Exception (per-item
    quarantine at commit). Read-only: payloads ride the row dicts through
    shard-merge concatenation untouched."""
    cap = payload_cap()
    for path, row, msg in zip(paths, rows, messages):
        if isinstance(msg, Exception):
            row["_chunk_payload"] = None  # quarantined by the cas path
            continue
        size = row["size_in_bytes"] or 0
        if size > cap:
            row["_chunk_payload"] = None
            _CHUNK_SKIPPED.inc()
            continue
        try:
            row["_chunk_payload"] = retry_call(
                lambda p=path, m=msg, s=size: _read_payload(p, m, s),
                policy=PAYLOAD_RETRY, classify=is_transient_io,
                label="chunk-gather")
        except Exception as e:  # noqa: BLE001 — per-item quarantine
            row["_chunk_payload"] = e


# -- stage 2 half: chunk + id behind the router (compute-only) --------------


def _chunk_slice(payloads: list[bytes], engine: str) -> list[list[tuple[str, int]]]:
    """Chunk one engine's slice: boundaries + per-chunk BLAKE3 ids. The
    ``cpu`` engine is the vectorized numpy rung; ``device`` resolves
    ``SD_CDC_KERNEL`` (xla default, pallas opt-in). Byte-identical either
    way — that is the cdc module's contract, so routing is pure economics."""
    kernel = "numpy" if engine == "cpu" else cdc.resolve_kernel(None)
    chunks = cdc.chunk_batch(payloads, kernel=kernel)
    ids = cdc.chunk_ids(payloads, chunks, kernel=kernel)
    return [[(cid, ln) for cid, (_off, ln) in zip(fid, fch)]
            for fid, fch in zip(ids, chunks)]


def _dispatch(payloads: list[bytes], engine: str) -> list[list[tuple[str, int]]]:
    faults.inject("chunk", key=f"dispatch:{engine}")
    t0 = time.perf_counter()
    out = _chunk_slice(payloads, engine)
    router.observe(engine, sum(len(p) for p in payloads),
                   time.perf_counter() - t0)
    return out


def pipeline_chunk_process(rows: list[dict], trace=None) -> None:
    """Chunk every gathered payload in the page, routed per batch. Device
    failures (wedge, dying backend) re-dispatch the slice on the numpy rung
    over the same payloads and degrade the router — same ladder as the
    hasher, with byte-identical output by construction. Results land as
    ``row['_chunk_manifest']`` (ordered ``(chunk_id, length)`` pairs);
    failures become ``row['_chunk_payload']`` Exceptions for the committer's
    quarantine loop."""
    work = [r for r in rows if isinstance(r.get("_chunk_payload"), bytes)]
    if not work:
        return
    payloads = [r["_chunk_payload"] for r in work]
    nbytes = sum(len(p) for p in payloads)
    with telemetry.span(trace, "identifier.chunk", files=len(work),
                        bytes=nbytes):
        main, probe = router.route()
        split = 0
        results: list[list[tuple[str, int]]] = []
        if probe is not None and len(work) > 1:
            split = min(router.PROBE_SLICE, len(work) // 2 or 1)
            try:
                results.extend(_dispatch(payloads[:split], probe))
            except Exception as e:  # noqa: BLE001 — probe slice redoes on numpy
                if probe == "device":
                    router.degrade(repr(e))
                results.extend(_chunk_slice(payloads[:split], "cpu"))
        try:
            results.extend(_dispatch(payloads[split:], main))
        except Exception as e:  # noqa: BLE001 — degradation ladder
            logger.exception("chunk dispatch failed mid-batch; re-dispatching "
                             "on the numpy rung")
            if main == "device":
                router.degrade(repr(e))
            results.extend(_chunk_slice(payloads[split:], "cpu"))
    for row, manifest in zip(work, results):
        row["_chunk_manifest"] = manifest
        row["_chunk_payload"] = None  # the payload bytes are dead weight now
    _CHUNK_FILES.inc(len(work))
    _CHUNK_CHUNKS.inc(sum(len(m) for m in results))
    _CHUNK_BYTES.inc(nbytes)


# -- stage 3 half: persist (called INSIDE the identifier's transaction) -----


def commit_manifest_rows(db, items: list[tuple[int, list[tuple[str, int]]]]) -> int:
    """Overwrite-then-insert the batch's manifests. ``items`` is
    ``(object_id, manifest)`` — already deduped by object (within-batch
    cas-duplicates carry identical manifests, one copy wins). Both the
    delete and the insert are RowJournal-noted; the caller owns the
    transaction."""
    rows = []
    for oid, manifest in items:
        db.delete(ChunkManifest, {"object_id": oid})
        for seq, (chunk_hash, length) in enumerate(manifest):
            rows.append({"object_id": oid, "seq": seq,
                         "chunk_hash": chunk_hash, "length": length})
    if rows:
        db.insert_many(ChunkManifest, rows)
    return len(items)


def quarantine_errors(rows: list[dict], location_path: str) -> list[str]:
    """Post-process quarantine sweep: rows whose payload ended as an
    Exception lose only their manifest — the file still identified. Returns
    the soft-error strings for the step result."""
    from .file_identifier import _abs_path

    errs = []
    n = 0
    for row in rows:
        p = row.get("_chunk_payload")
        if isinstance(p, Exception):
            errs.append(f"chunk manifest quarantined "
                        f"{_abs_path(location_path, row)}: {p!r}")
            row["_chunk_payload"] = None
            n += 1
    if n:
        _CHUNK_QUARANTINED.inc(n)
    return errs
