"""GC actors: orphan-object remover + thumbnail remover.

Reference semantics:
- core/src/object/orphan_remover.rs:12-13 — per-library actor, 1-minute tick
  plus an `invoke()` signal, debounced to at most one cleanup per 10s;
  deletes objects with no file_paths in batches of 512 together with their
  link rows.
- core/src/object/thumbnail_remover.rs:31-32 — node-level actor over every
  loaded library, 30s cadence for explicitly-marked cas_ids and a half-hour
  full sweep deleting cached thumbnails whose cas_id exists in no library.

Both are plain daemon threads here (the repo's actor idiom); intervals are
constructor args so tests tick them deterministically.
"""

from __future__ import annotations

import logging
import threading
from pathlib import Path
from typing import TYPE_CHECKING, Iterable

if TYPE_CHECKING:
    from ..library import Library
    from ..node import Node

logger = logging.getLogger(__name__)

TEN_SECONDS = 10.0
ONE_MINUTE = 60.0
THIRTY_SECS = 30.0
HALF_HOUR = 30.0 * 60.0

_ORPHAN_BATCH = 512


class OrphanRemoverActor:
    """Deletes Objects that no longer have any FilePath pointing at them
    (orphan_remover.rs process_clean_up)."""

    def __init__(self, library: "Library", tick_interval: float = ONE_MINUTE,
                 debounce: float = TEN_SECONDS) -> None:
        self.library = library
        self.tick_interval = tick_interval
        self.debounce = debounce
        self._signal = threading.Event()
        self._stop = threading.Event()
        self._last_checked = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"orphan-remover-{library.id[:8]}")
        self._thread.start()

    def invoke(self) -> None:
        self._signal.set()

    def _run(self) -> None:
        import time

        while not self._stop.is_set():
            self._signal.wait(self.tick_interval)
            if self._stop.is_set():
                return
            self._signal.clear()
            # debounce: at most one cleanup per `debounce` seconds — an
            # invoke inside the window is deferred to the boundary, not
            # dropped into the next full tick
            wait_left = self.debounce - (time.monotonic() - self._last_checked)
            if wait_left > 0 and self._stop.wait(wait_left):
                return
            try:
                self.process_clean_up()
            except Exception:
                logger.exception("orphan cleanup failed")
            self._last_checked = time.monotonic()

    def process_clean_up(self) -> int:
        """Batched delete loop; returns total objects removed."""
        db = self.library.db
        removed = 0
        while True:
            rows = db.query(
                "SELECT o.id FROM object o WHERE NOT EXISTS "
                "(SELECT 1 FROM file_path fp WHERE fp.object_id = o.id) "
                "LIMIT ?", [_ORPHAN_BATCH])
            ids = [r["id"] for r in rows]
            if not ids:
                return removed
            marks = ",".join("?" for _ in ids)
            # the orphan predicate is repeated inside every DELETE: an object
            # that gained a file_path link since the SELECT must survive
            # (the reference's delete_many carries the same filter)
            still_orphan = (f"object_id IN (SELECT o.id FROM object o "
                            f"WHERE o.id IN ({marks}) AND NOT EXISTS "
                            f"(SELECT 1 FROM file_path fp WHERE fp.object_id = o.id))")
            with db.transaction():
                # link rows first (tag_on_object in the reference; this
                # schema also carries label/space/album links + media_data)
                for table in ("tag_on_object", "label_on_object",
                              "object_in_space", "object_in_album",
                              "media_data"):
                    db.query(f"DELETE FROM {table} WHERE {still_orphan}", ids)
                db.query(
                    f"DELETE FROM object WHERE id IN ({marks}) AND NOT EXISTS "
                    f"(SELECT 1 FROM file_path fp WHERE fp.object_id = object.id)",
                    ids)
            removed += len(ids)  # counts candidates; re-linked ones survive
            logger.debug("removed %d orphaned objects", len(ids))

    def stop(self) -> None:
        self._stop.set()
        self._signal.set()
        self._thread.join(timeout=5)


class ThumbnailRemoverActor:
    """Sweeps the cas-sharded thumbnail cache, deleting entries whose cas_id
    is referenced by no loaded library (thumbnail_remover.rs worker)."""

    def __init__(self, node: "Node", batch_interval: float = THIRTY_SECS,
                 full_interval: float = HALF_HOUR) -> None:
        self.node = node
        self.batch_interval = batch_interval
        self.full_interval = full_interval
        self._marked: set[str] = set()
        # cas_id → last browse time; persisted so the 24h TTL survives a
        # node restart (the first post-boot sweep must not collect thumbs
        # browsed minutes before the restart)
        self._ephemeral: dict[str, float] = self._load_ephemeral()
        self._marked_lock = threading.Lock()
        self._signal = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="thumbnail-remover")
        self._thread.start()

    #: ephemeral (non-indexed) thumbnails survive sweeps this long after
    #: their last browse (the reference keeps a registry instead of a TTL —
    #: non_indexed_thumbnails_cas_ids channel, thumbnail_remover.rs)
    EPHEMERAL_TTL = 24 * 3600.0

    def mark_for_deletion(self, cas_ids: Iterable[str]) -> None:
        """Explicit enqueue (cas_ids_to_delete channel in the reference):
        deleted right away on the next short tick, no liveness check."""
        with self._marked_lock:
            self._marked.update(cas_ids)
        self._signal.set()

    def register_ephemeral(self, cas_ids: Iterable[str]) -> None:
        """Shield non-indexed thumbnails (no library row references them)
        from the full sweep while they're recently browsed."""
        import time

        now = time.time()
        with self._marked_lock:
            for cas in cas_ids:
                self._ephemeral[cas] = now
            snapshot = dict(self._ephemeral)
        self._save_ephemeral(snapshot)

    def _ephemeral_path(self) -> Path:
        return self._thumb_dir() / "ephemeral.json"

    def _load_ephemeral(self) -> dict[str, float]:
        import json

        try:
            raw = json.loads(self._ephemeral_path().read_text())
            return {str(k): float(v) for k, v in raw.items()}
        except Exception:  # best-effort side-file: wrong shape = empty
            return {}

    def _save_ephemeral(self, snapshot: dict[str, float]) -> None:
        import json

        try:
            path = self._ephemeral_path()
            tmp = path.with_suffix(".tmp")
            tmp.write_text(json.dumps(snapshot))
            tmp.replace(path)
        except OSError as e:
            logger.debug("could not persist ephemeral registry: %s", e)

    def _run(self) -> None:
        import time

        last_full = 0.0
        while not self._stop.is_set():
            self._signal.wait(self.batch_interval)
            if self._stop.is_set():
                return
            self._signal.clear()
            try:
                self.process_marked()
                if time.monotonic() - last_full >= self.full_interval:
                    self.full_sweep()
                    last_full = time.monotonic()
            except Exception:
                logger.exception("thumbnail GC failed")

    def _thumb_dir(self) -> Path:
        from .media.thumbnail import thumbnail_dir

        return Path(thumbnail_dir(self.node.data_dir))

    def process_marked(self) -> int:
        with self._marked_lock:
            marked, self._marked = self._marked, set()
        base = self._thumb_dir()
        removed = 0
        for cas_id in marked:
            if self._delete_thumb(base, cas_id):
                removed += 1
        return removed

    def full_sweep(self) -> int:
        """Delete every cached thumbnail whose cas_id no library references
        (the half-hour pass of thumbnail_remover.rs)."""
        base = self._thumb_dir()
        if not base.is_dir():
            return 0
        on_disk: list[str] = []
        for shard in base.iterdir():
            if not shard.is_dir():
                continue
            for entry in shard.glob("*.webp"):
                on_disk.append(entry.stem)
        if not on_disk:
            return 0
        alive: set[str] = set()
        for library in self.node.libraries.list():
            for start in range(0, len(on_disk), 500):
                chunk = on_disk[start:start + 500]
                marks = ",".join("?" for _ in chunk)
                for row in library.db.query(
                        f"SELECT DISTINCT cas_id FROM file_path "
                        f"WHERE cas_id IN ({marks})", chunk):
                    alive.add(row["cas_id"])
        import time

        cutoff = time.time() - self.EPHEMERAL_TTL
        with self._marked_lock:
            self._ephemeral = {c: t for c, t in self._ephemeral.items()
                               if t >= cutoff}
        removed = 0
        # resolve the cache dir BEFORE the loop: the first call per
        # process mkdirs + version-stamps it (blocking file I/O that must
        # not run under the registrar's lock — browses mark() through it)
        base = self._thumb_dir()
        for cas_id in on_disk:
            if cas_id in alive:
                continue
            # shield check under the registrar's lock, immediately before
            # the unlink: a browse that registered after the sweep started
            # must still protect its thumbnail
            with self._marked_lock:
                if cas_id in self._ephemeral:
                    continue
                if self._delete_thumb(base, cas_id):
                    removed += 1
        if removed:
            logger.info("thumbnail GC removed %d stale thumbnails", removed)
        return removed

    def _delete_thumb(self, base: Path, cas_id: str) -> bool:
        path = base / cas_id[:2] / f"{cas_id}.webp"
        try:
            path.unlink()
        except FileNotFoundError:
            return False
        except OSError as e:
            logger.warning("could not delete thumbnail %s: %s", cas_id, e)
            return False
        # prune empty shard dirs
        try:
            path.parent.rmdir()
        except OSError:
            pass
        return True

    def stop(self) -> None:
        self._stop.set()
        self._signal.set()
        self._thread.join(timeout=5)
