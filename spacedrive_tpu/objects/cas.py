"""Content-addressable-storage id (cas_id) generation.

Byte-exact port of the reference's sampling scheme (core/src/object/cas.rs:23-62):

    cas_id = hex(BLAKE3(size_le_8 ‖ samples))[:16]

where samples are the whole file when ``size <= 100KiB``, else:

    header  = bytes[0      : 8KiB]
    sample_i = bytes[8KiB + i*seek_jump : +10KiB]   for i in 0..3,
               seek_jump = (size - 16KiB) // 4
    footer  = bytes[size-8KiB : size]

(consts cas.rs:10-15; loop trace :42-51 — four samples at offsets
``8KiB + i*seek_jump``, then the footer.)

For files > 100KiB the hashed message is therefore a FIXED 57,352 bytes
(8 + 8192 + 4*10240 + 8192) — a static shape, which is exactly what the
batched TPU kernel wants. This module provides the host-side gather stage
(shared by every backend) and the scalar CPU path; the batched TPU path
lives in ops/blake3_jax.py behind the same sample layout.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import BinaryIO

from .. import faults
from ..utils.retry import RetryPolicy, is_transient_io, retry_call
from .blake3_ref import blake3

SAMPLE_COUNT = 4
SAMPLE_SIZE = 1024 * 10
HEADER_OR_FOOTER_SIZE = 1024 * 8
MINIMUM_FILE_SIZE = 1024 * 100

# cas.rs:18-21 static asserts
assert HEADER_OR_FOOTER_SIZE * 2 + SAMPLE_COUNT * SAMPLE_SIZE < MINIMUM_FILE_SIZE
assert SAMPLE_SIZE > HEADER_OR_FOOTER_SIZE

#: total hashed message length for the sampled (large-file) path
SAMPLED_MESSAGE_LEN = 8 + 2 * HEADER_OR_FOOTER_SIZE + SAMPLE_COUNT * SAMPLE_SIZE  # 57352
#: max hashed message length for the whole-file (small) path
SMALL_MESSAGE_MAX_LEN = 8 + MINIMUM_FILE_SIZE  # 102408


def sample_offsets(size: int) -> list[tuple[int, int]]:
    """(offset, length) reads for a file of ``size`` bytes (> MINIMUM_FILE_SIZE),
    in hash order: header, 4 strided samples, footer."""
    seek_jump = (size - HEADER_OR_FOOTER_SIZE * 2) // SAMPLE_COUNT
    reads = [(0, HEADER_OR_FOOTER_SIZE)]
    reads += [
        (HEADER_OR_FOOTER_SIZE + i * seek_jump, SAMPLE_SIZE) for i in range(SAMPLE_COUNT)
    ]
    reads.append((size - HEADER_OR_FOOTER_SIZE, HEADER_OR_FOOTER_SIZE))
    return reads


def cas_message_from_file(fh: BinaryIO, size: int) -> bytes:
    """The exact byte string the reference feeds its hasher."""
    parts = [struct.pack("<Q", size)]
    if size <= MINIMUM_FILE_SIZE:
        fh.seek(0)
        data = fh.read(size)
        if len(data) != size:
            raise EOFError(f"file shrank while hashing: got {len(data)}, want {size}")
        parts.append(data)
    else:
        for offset, length in sample_offsets(size):
            fh.seek(offset)
            chunk = fh.read(length)
            if len(chunk) != length:  # read_exact semantics (cas.rs:36,43,56)
                raise EOFError(f"short read at {offset}: got {len(chunk)}, want {length}")
            parts.append(chunk)
    return b"".join(parts)


def generate_cas_id(path: str | Path, size: int | None = None) -> str:
    """Scalar CPU path, identical output to the reference's generate_cas_id."""
    path = Path(path)
    if size is None:
        size = path.stat().st_size
    with open(path, "rb", buffering=0) as fh:
        message = cas_message_from_file(fh, size)
    return blake3(message).hex()[:16]


def cas_message_from_bytes(data: bytes, size: int | None = None) -> bytes:
    """Hashed message for an in-memory file image (same layout as
    :func:`cas_message_from_file`). A ``size`` exceeding the available bytes
    raises EOFError (read_exact semantics), never hashes short samples."""
    size = len(data) if size is None else size
    if size > len(data):
        raise EOFError(f"buffer shorter than declared size: {len(data)} < {size}")
    parts = [struct.pack("<Q", size)]
    if size <= MINIMUM_FILE_SIZE:
        parts.append(data[:size])
    else:
        for offset, length in sample_offsets(size):
            parts.append(data[offset : offset + length])
    return b"".join(parts)


def generate_cas_id_from_bytes(data: bytes, size: int | None = None) -> str:
    """cas_id for an in-memory file image (ephemeral/non-indexed browsing path)."""
    return blake3(cas_message_from_bytes(data, size)).hex()[:16]


#: per-file gather retry: EINTR/EIO-class read errors are transient (flaky
#: media, interrupted syscalls) — re-read a couple of times before the file
#: quarantines; vanished/permission-denied/truncated raise through untouched
GATHER_RETRY = RetryPolicy(attempts=3, base_s=0.01, max_s=0.1, budget_s=1.0)


def _read_one_sampled(path: str | Path, size: int) -> bytes:
    faults.inject("gather", key=str(path))
    with open(path, "rb", buffering=0) as fh:
        return cas_message_from_file(fh, size)


def read_sampled_batch(paths: list[str | Path], sizes: list[int]) -> list[bytes | Exception]:
    """Gather stage for the batched backends: one message per file, hash order.

    Per-file errors (deleted/shrunk files mid-scan) are returned in place as
    the Exception instance rather than aborting the batch — callers route them
    into JobRunErrors (the reference accumulates per-step errors instead of
    failing the job, job/mod.rs:834-841). Transient read errors (EINTR/EIO)
    retry under GATHER_RETRY before they count as a per-file failure.
    """
    out: list[bytes | Exception] = []
    for path, size in zip(paths, sizes):
        try:
            out.append(retry_call(
                lambda p=path, s=size: _read_one_sampled(p, s),
                policy=GATHER_RETRY, classify=is_transient_io,
                label="cas-gather"))
        except (OSError, EOFError) as e:
            out.append(e)
    return out


def read_sampled_batch_fast(paths: list[str | Path],
                            sizes: list[int]) -> list[bytes | Exception]:
    """``read_sampled_batch`` through the native fused gather (io_uring /
    threaded pread, GIL released for the whole batch) when the toolchain is
    present — the prefetch stage of the streaming scan pipeline runs here so
    its I/O truly overlaps the committer. Byte-identical messages; per-file
    errors come back as OSError entries like the python path."""
    if not paths:
        return []
    # an armed gather fault plan needs per-file seam hits; the fused native
    # call is one opaque batch — route through the python path so injected
    # per-file faults (and their retries) keep exact semantics
    if faults.seam_armed("gather"):
        return read_sampled_batch(paths, sizes)
    try:
        import numpy as np

        from ..native import cas_native
    except Exception:
        return read_sampled_batch(paths, sizes)

    msg_lens = [8 + s if s <= MINIMUM_FILE_SIZE else SAMPLED_MESSAGE_LEN
                for s in sizes]
    # the native gather zero-pads each row to a 64-byte block boundary;
    # stride must cover that, not just the longest message
    stride = (max(msg_lens) + 63) // 64 * 64
    rows = np.zeros((len(paths), stride), np.uint8)
    lengths = np.zeros(len(paths), np.int32)
    cas_native.gather_batch(paths, sizes, rows, lengths)
    out: list[bytes | Exception] = []
    for i, path in enumerate(paths):
        if lengths[i] == 0 and msg_lens[i] != 8:
            # degradation ladder, rung one: the fused gather reports only
            # pass/fail per row — re-read the failed file on the python
            # path (with its transient retry) to either recover it or get
            # the real errno for the quarantine record
            out.append(read_sampled_batch([path], [sizes[i]])[0])
        else:
            out.append(bytes(rows[i, : lengths[i]]))
    return out
