"""ObjectValidatorJob: full-file BLAKE3 integrity checksums.

Parity with core/src/object/validation/{validator_job,hash.rs}: for every
file_path under a location (optionally a sub_path) that has a cas_id but no
``integrity_checksum``, compute the FULL-file BLAKE3 (hash.rs:24 — distinct
from the sampled cas_id) and store it. Re-validation compares stored vs
recomputed and reports mismatches (bit-rot / tamper detection).

Hashing runs in the native C++ core via mmap (native/blake3_cas.cc) — the
analogue of the reference's SIMD blake3 crate. Very large files can instead
ride the sequence-parallel TPU mesh (parallel/mesh.py seq axis), but the
validator is IO-bound, so native is the default.
"""

from __future__ import annotations

import logging
from typing import Any

from ..jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ..models import FilePath, Location
from .fs import file_path_abs

logger = logging.getLogger(__name__)

BATCH = 100


def full_file_hash(path) -> str:
    try:
        from ..native import cas_native

        return cas_native.blake3_file_hex(path)
    except ImportError:  # toolchain-less host: pure-Python oracle
        from .blake3_ref import blake3

        with open(path, "rb") as fh:
            return blake3(fh.read()).hex()


class ObjectValidatorJob(StatefulJob):
    """init_args: location_id, sub_path?, revalidate? (check existing sums)."""

    NAME = "object_validator"

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        if db.find_one(Location, {"id": location_id}) is None:
            raise JobError(f"location {location_id} not found")
        revalidate = bool(self.init_args.get("revalidate"))
        where = "location_id = ? AND is_dir = 0 AND cas_id IS NOT NULL"
        params: list[Any] = [location_id]
        if not revalidate:
            where += " AND integrity_checksum IS NULL"
        if self.init_args.get("sub_path"):
            where += " AND materialized_path LIKE ?"
            params.append(f"/{self.init_args['sub_path'].strip('/')}/%")
        count = db.query(f"SELECT COUNT(*) n FROM file_path WHERE {where}", params)[0]["n"]
        if count == 0:
            raise EarlyFinish("no file paths to validate")
        steps = [{"kind": "validate"} for _ in range(-(-count // BATCH))]
        return ({"location_id": location_id, "where": where, "params": params,
                 "cursor": 0, "revalidate": revalidate},
                steps, {"validated": 0, "mismatched": 0})

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        db = ctx.library.db
        rows = [FilePath.decode_row(r) for r in db.query(
            f"SELECT * FROM file_path WHERE {data['where']} AND id > ? "
            f"ORDER BY id LIMIT ?", data["params"] + [data["cursor"], BATCH])]
        if not rows:
            return StepResult()
        data["cursor"] = rows[-1]["id"]
        errors, validated, mismatched = [], 0, 0
        for row in rows:
            try:
                _, path = file_path_abs(db, row["id"])
                checksum = full_file_hash(path)
            except (OSError, JobError) as e:
                errors.append(f"validate {row['name']}: {e}")
                continue
            if data["revalidate"] and row["integrity_checksum"]:
                if row["integrity_checksum"] != checksum:
                    mismatched += 1
                    errors.append(
                        f"integrity MISMATCH {row['materialized_path']}{row['name']}: "
                        f"stored {row['integrity_checksum'][:16]}… != {checksum[:16]}…")
                    continue
            db.update(FilePath, {"id": row["id"]}, {"integrity_checksum": checksum})
            validated += 1
        return StepResult(metadata={"validated": validated, "mismatched": mismatched},
                          errors=errors)

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        logger.info("validator finished: %s", run_metadata)
        return run_metadata
