"""MediaProcessorJob: thumbnails + media metadata, chained after identify.

Mirrors core/src/object/media/media_processor/job.rs — BATCH_SIZE = 10
(:34); per entry: thumbnail into the sharded cache + EXIF rows; emits
``new_thumbnail`` CoreEvents as previews land.
"""

from __future__ import annotations

import logging
import time

from ...jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ...models import FilePath, Location, MediaData
from .metadata import extract_media_data
from .thumbnail import (can_generate_thumbnail, generate_thumbnail,
                        generate_thumbnails_batched)

logger = logging.getLogger(__name__)

BATCH_SIZE = 10


class MediaProcessorJob(StatefulJob):
    NAME = "media_processor"

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        location = db.find_one(Location, {"id": location_id})
        if location is None:
            raise JobError(f"location {location_id} not found")
        if location.get("generate_preview_media") is False:
            raise EarlyFinish("preview media disabled for location")

        exts = sorted({e for e in _thumbable_extensions()})
        marks = ",".join("?" for _ in exts)
        sub = self.init_args.get("sub_path")
        sub_sql, sub_params = ("", [])
        if sub:
            sub_sql = " AND materialized_path LIKE ?"
            sub_params = [f"/{sub.strip('/')}/%"]
        rows = db.query(
            f"SELECT id FROM file_path WHERE location_id = ? AND is_dir = 0 "
            f"AND cas_id IS NOT NULL AND lower(extension) IN ({marks}){sub_sql} "
            f"ORDER BY id",
            [location_id, *exts, *sub_params],
        )
        ids = [r["id"] for r in rows]
        if not ids:
            raise EarlyFinish("no media to process")
        steps = [{"kind": "media", "ids": ids[i : i + BATCH_SIZE]}
                 for i in range(0, len(ids), BATCH_SIZE)]
        data = {"location_id": location_id, "location_path": location["path"]}
        return data, steps, {"thumbnails_created": 0, "media_data_extracted": 0,
                             "media_time": 0.0}

    def execute_step(self, ctx: WorkerContext, data: dict, step: dict,
                     step_number: int) -> StepResult:
        from ...config import BackendFeature
        from ..file_identifier import _abs_path

        db = ctx.library.db
        node = ctx.library.node
        data_dir = node.data_dir if node else "."
        use_device = (node is not None
                      and node.config.has_feature(BackendFeature.TPU_THUMBNAILS))
        errors: list[str] = []
        thumbs = 0
        extracted = 0
        t0 = time.perf_counter()

        entries = []  # (row, path, ext)
        for fp_id in step["ids"]:
            row = db.find_one(FilePath, {"id": fp_id})
            if row is None or not row.get("cas_id"):
                continue
            entries.append((row, _abs_path(data["location_path"], row),
                            (row.get("extension") or "").lower()))

        made: dict[str, object] = {}
        if use_device:
            # the step IS the device batch: one resize call per 10 entries
            try:
                made = generate_thumbnails_batched(
                    [(path, row["cas_id"], ext)
                     for row, path, ext in entries if can_generate_thumbnail(ext)],
                    data_dir)
            except Exception as e:
                errors.append(f"batched thumbnails: {e!r}")
                use_device = False

        for row, path, ext in entries:
            try:
                if can_generate_thumbnail(ext):
                    if use_device:
                        out = made.get(row["cas_id"])
                        if out is None:
                            # device batch skipped it (decode/encode failed):
                            # scalar retry, and the failure goes on record
                            out = generate_thumbnail(path, data_dir,
                                                     row["cas_id"], ext)
                            if out is None:
                                errors.append(f"{path}: thumbnail failed "
                                              f"(device batch + scalar retry)")
                    else:
                        out = generate_thumbnail(path, data_dir, row["cas_id"], ext)
                    if out is not None:
                        thumbs += 1
                        ctx.library.emit("new_thumbnail", {"cas_id": row["cas_id"]})
                media = extract_media_data(path, ext)
                if media and row.get("object_id"):
                    db.upsert(MediaData, {"object_id": row["object_id"]},
                              media, media)
                    extracted += 1
            except Exception as e:
                errors.append(f"{path}: {e!r}")
        return StepResult(metadata={"thumbnails_created": thumbs,
                                    "media_data_extracted": extracted,
                                    "media_time": time.perf_counter() - t0},
                          errors=errors)

    def finalize(self, ctx: WorkerContext, data: dict, run_metadata: dict):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        logger.info("media_processor finished: %s", run_metadata)
        return run_metadata


def _thumbable_extensions() -> set[str]:
    from .thumbnail import (
        HEIF_EXTENSIONS,
        THUMBNAILABLE_IMAGE_EXTENSIONS,
        THUMBNAILABLE_VIDEO_EXTENSIONS,
    )

    return (THUMBNAILABLE_IMAGE_EXTENSIONS | THUMBNAILABLE_VIDEO_EXTENSIONS
            | HEIF_EXTENSIONS)
