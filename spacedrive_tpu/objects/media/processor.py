"""MediaProcessorJob: thumbnails + media metadata, chained after identify.

Mirrors core/src/object/media/media_processor/job.rs — but the reference's
``BATCH_SIZE = 10`` (:34) is a scalar-CPU tuning; here the step is the
device batch (256 entries), sized so the batched resize amortizes one
dispatch per step and the pipelined stages have real work to overlap.
Thumbnails always route through ``generate_thumbnails_batched``, which
carries the get_hasher-style engine verdict internally (device resize when
it measures faster, the scalar PIL path otherwise — on CPU fallback that
means PIL, never a losing jax resize).

Runs in the **media lane** (jobs/manager.py): decode/encode and EXIF
extraction are file I/O + compute with no sync ops, so media jobs overlap
the default lane's scan chain — LocationsActor.media_warm_start spawns one
per identified prefix while the identifier is still hashing.

Step execution is split into the streaming-pipeline stages: ``pipeline_page``
(row fetch, read-only), ``pipeline_process`` (decode → resize → encode +
EXIF), ``pipeline_commit`` (MediaData upserts + ``new_thumbnail`` events).
"""

from __future__ import annotations

import logging

from ... import telemetry
from ...jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ...models import FilePath, Location, MediaData
from .metadata import extract_media_data
from .thumbnail import (can_generate_thumbnail, generate_thumbnail,
                        generate_thumbnails_batched)

logger = logging.getLogger(__name__)

BATCH_SIZE = 256


class MediaProcessorJob(StatefulJob):
    NAME = "media_processor"
    IS_BATCHED = True
    LANE = "media"

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        location = db.find_one(Location, {"id": location_id})
        if location is None:
            raise JobError(f"location {location_id} not found")
        if location.get("generate_preview_media") is False:
            raise EarlyFinish("preview media disabled for location")

        exts = sorted({e for e in _thumbable_extensions()})
        marks = ",".join("?" for _ in exts)
        sub = self.init_args.get("sub_path")
        sub_sql, sub_params = ("", [])
        if sub:
            sub_sql = " AND materialized_path LIKE ?"
            sub_params = [f"/{sub.strip('/')}/%"]
        rows = db.query(
            f"SELECT id FROM file_path WHERE location_id = ? AND is_dir = 0 "
            f"AND cas_id IS NOT NULL AND lower(extension) IN ({marks}){sub_sql} "
            f"ORDER BY id",
            [location_id, *exts, *sub_params],
        )
        ids = [r["id"] for r in rows]
        if not ids:
            raise EarlyFinish("no media to process")
        steps = [{"kind": "media", "ids": ids[i : i + BATCH_SIZE]}
                 for i in range(0, len(ids), BATCH_SIZE)]
        data = {"location_id": location_id, "location_path": location["path"]}
        return data, steps, {"thumbnails_created": 0, "media_data_extracted": 0,
                             "media_time": 0.0}

    def pipeline_spec(self):
        from ...pipeline import PipelineSpec

        return PipelineSpec(page=self.pipeline_page,
                            process=self.pipeline_process,
                            commit=self.pipeline_commit)

    def execute_step(self, ctx: WorkerContext, data: dict, step: dict,
                     step_number: int) -> StepResult:
        scratch = {"steps": [step], "step_index": 0}
        batch = self.pipeline_page(ctx, data, scratch)
        if batch is None:
            return StepResult()
        return self.pipeline_commit(ctx, data,
                                    self.pipeline_process(ctx, data, batch))

    # -- stage 1: prefetch (row fetch, read-only) ----------------------------
    def pipeline_page(self, ctx: WorkerContext, data: dict,
                      scratch: dict) -> dict | None:
        from ..file_identifier import _abs_path

        i = scratch.get("step_index", 0)
        steps = scratch.get("steps") or []
        if i >= len(steps):
            return None
        scratch["step_index"] = i + 1
        db = ctx.library.db

        entries = []  # (row, path, ext)
        for fp_id in steps[i]["ids"]:
            row = db.find_one(FilePath, {"id": fp_id})
            if row is None or not row.get("cas_id"):
                continue
            entries.append((row, _abs_path(data["location_path"], row),
                            (row.get("extension") or "").lower()))
        return {"entries": entries}

    # -- stage 2: dispatch (decode → resize → encode + EXIF, no DB) ----------
    def pipeline_process(self, ctx: WorkerContext, data: dict,
                         batch: dict) -> dict:
        from ...config import BackendFeature

        node = ctx.library.node
        data_dir = node.data_dir if node else "."
        errors: list[str] = []
        entries = batch["entries"]

        with telemetry.span(getattr(ctx, "trace", None), "media.process",
                            entries=len(entries)) as media_sp:
            # the step IS the device batch: routed resize calls per step
            # (generate_thumbnails_batched chunks to RESIZE_SUB_BATCH and
            # falls back to scalar PIL when the device path loses or is
            # absent). The tpuThumbnails feature stays the operator opt-in
            # for device resize: off → the scalar pipeline, exactly the
            # pre-lane behavior
            allow_device = (node is not None and node.config.has_feature(
                BackendFeature.TPU_THUMBNAILS))
            made: dict[str, object] = {}
            try:
                made = generate_thumbnails_batched(
                    [(path, row["cas_id"], ext) for row, path, ext in entries
                     if can_generate_thumbnail(ext)],
                    data_dir, allow_device=allow_device)
            except Exception as e:
                errors.append(f"batched thumbnails: {e!r}")

            thumbed: list[str] = []  # cas_ids with a fresh thumbnail
            media_rows: list[tuple[int, dict]] = []  # (object_id, fields)
            extracted = 0
            for row, path, ext in entries:
                try:
                    if can_generate_thumbnail(ext):
                        out = made.get(row["cas_id"])
                        if out is None:
                            # batch skipped it (decode/encode failed):
                            # scalar retry, and the failure goes on record
                            out = generate_thumbnail(path, data_dir,
                                                     row["cas_id"], ext)
                            if out is None:
                                errors.append(f"{path}: thumbnail failed "
                                              f"(batched + scalar retry)")
                        if out is not None:
                            thumbed.append(row["cas_id"])
                    media = extract_media_data(path, ext)
                    if media and row.get("object_id"):
                        media_rows.append((row["object_id"], media))
                        extracted += 1
                except Exception as e:
                    errors.append(f"{path}: {e!r}")
        return {"thumbed": thumbed, "media_rows": media_rows,
                "extracted": extracted, "errors": errors,
                "media_time": media_sp.duration_s}

    # -- stage 3: commit (MediaData upserts + events) ------------------------
    def pipeline_commit(self, ctx: WorkerContext, data: dict,
                        batch: dict) -> StepResult:
        db = ctx.library.db
        # one transaction per batch: atomic under the committer's retry,
        # and it joins the executor's group-commit scope when armed
        with db.transaction():
            for object_id, media in batch["media_rows"]:
                db.upsert(MediaData, {"object_id": object_id}, media, media)
        for cas_id in batch["thumbed"]:
            ctx.library.emit("new_thumbnail", {"cas_id": cas_id})
        return StepResult(metadata={"thumbnails_created": len(batch["thumbed"]),
                                    "media_data_extracted": batch["extracted"],
                                    "media_time": batch["media_time"]},
                          errors=batch["errors"])

    def finalize(self, ctx: WorkerContext, data: dict, run_metadata: dict):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        logger.info("media_processor finished: %s", run_metadata)
        return run_metadata


def _thumbable_extensions() -> set[str]:
    from .thumbnail import (
        HEIF_EXTENSIONS,
        THUMBNAILABLE_IMAGE_EXTENSIONS,
        THUMBNAILABLE_VIDEO_EXTENSIONS,
    )

    return (THUMBNAILABLE_IMAGE_EXTENSIONS | THUMBNAILABLE_VIDEO_EXTENSIONS
            | HEIF_EXTENSIONS)
