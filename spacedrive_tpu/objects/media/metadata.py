"""Media metadata extraction (EXIF → MediaData rows).

Mirrors core/src/object/media/media_data_extractor.rs + sd-media-metadata:
image dimensions, capture date, camera fields, GPS location. PIL's EXIF
reader replaces the Rust exif crate; audio/video metadata are stubs in the
reference too.
"""

from __future__ import annotations

import logging
from typing import Any

logger = logging.getLogger(__name__)

_EXIF_TAGS = {
    271: "camera_make", 272: "camera_model", 306: "media_date",
    36867: "media_date", 315: "artist", 33432: "copyright", 36864: "exif_version",
}


def extract_media_data(path: str, extension: str) -> dict[str, Any] | None:
    from .thumbnail import THUMBNAILABLE_IMAGE_EXTENSIONS

    if extension not in THUMBNAILABLE_IMAGE_EXTENSIONS:
        return None
    try:
        from PIL import Image

        with Image.open(path) as img:
            out: dict[str, Any] = {"dimensions": {"width": img.width, "height": img.height}}
            exif = img.getexif()
            camera: dict[str, Any] = {}
            for tag, value in exif.items():
                name = _EXIF_TAGS.get(tag)
                if name in ("artist", "copyright", "media_date", "exif_version"):
                    out[name] = str(value)
                elif name in ("camera_make", "camera_model"):
                    camera[name] = str(value)
            if camera:
                out["camera_data"] = camera
            gps = exif.get_ifd(0x8825) if hasattr(exif, "get_ifd") else None
            if gps:
                loc = _gps_to_decimal(gps)
                if loc:
                    out["media_location"] = loc
            return out
    except Exception as e:
        logger.debug("no media data for %s: %s", path, e)
        return None


def _gps_to_decimal(gps: dict) -> dict[str, float] | None:
    try:
        lat, lat_ref = gps.get(2), gps.get(1, "N")
        lon, lon_ref = gps.get(4), gps.get(3, "E")
        if not lat or not lon:
            return None

        def to_deg(v):
            d, m, s = (float(x) for x in v)
            return d + m / 60 + s / 3600

        latitude = to_deg(lat) * (-1 if lat_ref in ("S", b"S") else 1)
        longitude = to_deg(lon) * (-1 if lon_ref in ("W", b"W") else 1)
        return {"latitude": latitude, "longitude": longitude}
    except Exception:
        return None
