"""Media metadata extraction (EXIF / stream probing → MediaData rows).

Mirrors core/src/object/media/media_data_extractor.rs + sd-media-metadata:
image dimensions, capture date, camera fields (exposure/aperture/ISO/
focal length/lens/orientation), GPS location with plus-code encoding
(image/geographic/pluscodes.rs — Open Location Code implemented from the
public spec), and audio/video stream metadata via the linked libavformat
probe (sd_ffmpeg.cc) with an ffprobe-CLI fallback (the reference's
audio/video extractors are stubs; here they are real).
"""

from __future__ import annotations

import json
import logging
import shutil
import subprocess
from typing import Any

logger = logging.getLogger(__name__)

_EXIF_TAGS = {
    271: "camera_make", 272: "camera_model", 306: "media_date",
    36867: "media_date", 315: "artist", 33432: "copyright", 36864: "exif_version",
}

#: ExifIFD (0x8769) camera detail tags → camera_data keys
_EXIF_IFD_TAGS = {
    33434: "exposure_time", 33437: "f_number", 34855: "iso",
    37386: "focal_length", 37385: "flash", 42035: "lens_make",
    42036: "lens_model",
}

AUDIO_EXTENSIONS = {"mp3", "wav", "flac", "ogg", "m4a", "aac", "opus", "wma"}

_FFPROBE = shutil.which("ffprobe")


def extract_media_data(path: str, extension: str) -> dict[str, Any] | None:
    from .thumbnail import (
        HEIF_EXTENSIONS,
        THUMBNAILABLE_IMAGE_EXTENSIONS,
        THUMBNAILABLE_VIDEO_EXTENSIONS,
    )

    if extension in THUMBNAILABLE_IMAGE_EXTENSIONS:
        return _extract_image(path)
    if extension in HEIF_EXTENSIONS:
        return _extract_heif(path)
    if extension in THUMBNAILABLE_VIDEO_EXTENSIONS or extension in AUDIO_EXTENSIONS:
        return _extract_av(path)
    return None


def _extract_heif(path: str) -> dict[str, Any] | None:
    """Dimensions for HEIF/AVIF primaries, read from the container without
    an HEVC decode (PIL can't open them; EXIF inside HEIF containers is
    left for a fuller parser)."""
    from .thumbnail import _native_heif

    heif = _native_heif()
    if heif is None:
        return None
    try:
        w, h = heif.dims(path)
    except Exception as e:
        logger.debug("no media data for %s: %s", path, e)
        return None
    return {"dimensions": {"width": w, "height": h}}


def _extract_image(path: str) -> dict[str, Any] | None:
    try:
        from PIL import Image

        with Image.open(path) as img:
            out: dict[str, Any] = {"dimensions": {"width": img.width, "height": img.height}}
            exif = img.getexif()
            camera: dict[str, Any] = {}
            for tag, value in exif.items():
                name = _EXIF_TAGS.get(tag)
                if name in ("artist", "copyright", "media_date", "exif_version"):
                    out[name] = str(value)
                elif name in ("camera_make", "camera_model"):
                    camera[name] = str(value)
            orientation = exif.get(274)
            if orientation:
                camera["orientation"] = int(orientation)
            software = exif.get(305)
            if software:
                camera["software"] = str(software)
            try:
                ifd = exif.get_ifd(0x8769)
                for tag, name in _EXIF_IFD_TAGS.items():
                    if tag in ifd:
                        value = ifd[tag]
                        camera[name] = (float(value)
                                        if isinstance(value, (int, float)) or
                                        hasattr(value, "__float__")
                                        else str(value))
            except Exception:
                # the file still gets base metadata; only the EXIF sub-IFD
                # (exposure/aperture/ISO) is skipped — but say so, or a
                # corrupt IFD looks like a camera that wrote no EXIF at all
                logger.debug("unreadable EXIF sub-IFD in %s", path,
                             exc_info=True)
            if camera:
                out["camera_data"] = camera
            gps = exif.get_ifd(0x8825) if hasattr(exif, "get_ifd") else None
            if gps:
                loc = _gps_to_decimal(gps)
                if loc:
                    loc["pluscode"] = encode_pluscode(
                        loc["latitude"], loc["longitude"])
                    out["media_location"] = loc
            return out
    except Exception as e:
        logger.debug("no media data for %s: %s", path, e)
        return None


def _extract_av(path: str) -> dict[str, Any] | None:
    """Stream metadata (duration, codecs, dims, rates): linked libavformat
    when the native helper builds, else an ffprobe subprocess."""
    native = _native_probe(path)
    if native is not None:
        return native
    if _FFPROBE is None:
        return None
    try:
        proc = subprocess.run(
            [_FFPROBE, "-v", "error", "-print_format", "json",
             "-show_format", "-show_streams", path],
            capture_output=True, timeout=30, check=True)
        probe = json.loads(proc.stdout.decode())
    except Exception as e:
        logger.debug("ffprobe failed for %s: %s", path, e)
        return None
    out: dict[str, Any] = {}
    fmt = probe.get("format", {})
    streams_out = []
    for stream in probe.get("streams", []):
        entry: dict[str, Any] = {
            "codec_type": stream.get("codec_type"),
            "codec": stream.get("codec_name"),
        }
        if stream.get("codec_type") == "video":
            entry["width"] = stream.get("width")
            entry["height"] = stream.get("height")
            rate = stream.get("avg_frame_rate", "0/1")
            try:
                num, _, den = rate.partition("/")
                entry["fps"] = round(float(num) / float(den or 1), 3)
            except (ValueError, ZeroDivisionError):
                pass
            # first real video stream defines dimensions; cover art must
            # not (same rule as the native probe — identical row shapes)
            attached = (stream.get("disposition") or {}).get("attached_pic")
            if "width" in stream and "height" in stream and not attached:
                out.setdefault("dimensions", {"width": stream["width"],
                                              "height": stream["height"]})
        elif stream.get("codec_type") == "audio":
            entry["channels"] = stream.get("channels")
            # ffprobe JSON encodes sample_rate as a string; the native
            # probe emits ints — both backends must shape rows identically
            rate = stream.get("sample_rate")
            entry["sample_rate"] = int(rate) if rate is not None else None
        streams_out.append(entry)
    duration = fmt.get("duration")
    if duration is not None:
        out["duration_seconds"] = round(float(duration), 3)
    if fmt.get("bit_rate"):
        out["bit_rate"] = int(fmt["bit_rate"])
    if streams_out:
        out["streams"] = streams_out
    tags = fmt.get("tags", {}) or {}
    for src, dst in (("artist", "artist"), ("copyright", "copyright"),
                     ("creation_time", "media_date")):
        if tags.get(src):
            out[dst] = str(tags[src])
    return out or None


def _native_probe(path: str) -> dict[str, Any] | None:
    """MediaData dict from the linked FFmpeg probe, shaped identically to
    the ffprobe path so either backend fills the same columns."""
    from .thumbnail import _native_ffmpeg

    native = _native_ffmpeg()
    if native is None:
        return None
    try:
        probe = native.probe(path)
    except Exception as e:
        logger.debug("native probe failed for %s: %s", path, e)
        return None
    out: dict[str, Any] = {}
    streams_out = []
    for stream in probe.get("streams", []):
        entry: dict[str, Any] = {
            "codec_type": stream.get("codec_type"),
            "codec": stream.get("codec"),
        }
        if stream.get("codec_type") == "video":
            entry["width"] = stream.get("width")
            entry["height"] = stream.get("height")
            if stream.get("fps"):
                entry["fps"] = stream["fps"]
            # cover-art streams must not define the media's dimensions
            if not stream.get("attached_pic") and "width" in stream:
                out.setdefault("dimensions", {"width": stream["width"],
                                              "height": stream["height"]})
        elif stream.get("codec_type") == "audio":
            entry["channels"] = stream.get("channels")
            entry["sample_rate"] = stream.get("sample_rate")
        streams_out.append(entry)
    if probe.get("duration_seconds") is not None:
        out["duration_seconds"] = probe["duration_seconds"]
    if probe.get("bit_rate"):
        out["bit_rate"] = int(probe["bit_rate"])
    if streams_out:
        out["streams"] = streams_out
    tags = probe.get("tags", {}) or {}
    lower = {k.lower(): v for k, v in tags.items()}
    for src, dst in (("artist", "artist"), ("copyright", "copyright"),
                     ("creation_time", "media_date")):
        if lower.get(src):
            out[dst] = str(lower[src])
    return out or None


def _gps_to_decimal(gps: dict) -> dict[str, float] | None:
    try:
        lat, lat_ref = gps.get(2), gps.get(1, "N")
        lon, lon_ref = gps.get(4), gps.get(3, "E")
        if not lat or not lon:
            return None

        def to_deg(v):
            d, m, s = (float(x) for x in v)
            return d + m / 60 + s / 3600

        latitude = to_deg(lat) * (-1 if lat_ref in ("S", b"S") else 1)
        longitude = to_deg(lon) * (-1 if lon_ref in ("W", b"W") else 1)
        return {"latitude": latitude, "longitude": longitude}
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Open Location Code (plus codes) — implemented from the public spec
# (reference: sd-media-metadata image/geographic/pluscodes.rs)
# ---------------------------------------------------------------------------

_OLC_ALPHABET = "23456789CFGHJMPQRVWX"
_OLC_SEPARATOR = "+"
_OLC_PAIR_CODE_LEN = 10


def encode_pluscode(latitude: float, longitude: float) -> str:
    """Standard 10-digit plus code (e.g. 8FVC9G8F+6X)."""
    lat = min(90.0, max(-90.0, latitude))
    lon = longitude
    while lon < -180.0:
        lon += 360.0
    while lon >= 180.0:
        lon -= 360.0
    # positive integer space at the finest pair resolution: 1/8000 degree
    # (5 base-20 digit pairs); the 90°/180° edge clips into the last cell
    lat_val = min(int((lat + 90.0) * 8000), 180 * 8000 - 1)
    lon_val = min(int((lon + 180.0) * 8000), 360 * 8000 - 1)
    digits: list[str] = []
    for _ in range(_OLC_PAIR_CODE_LEN // 2):
        digits.append(_OLC_ALPHABET[lon_val % 20])
        digits.append(_OLC_ALPHABET[lat_val % 20])
        lat_val //= 20
        lon_val //= 20
    code = "".join(reversed(digits))
    return code[:8] + _OLC_SEPARATOR + code[8:]
