"""Thumbnailer: WebP previews in a cas_id-sharded cache.

Mirrors core/src/object/media/thumbnail/ — target area 262,144 px² at WebP
quality 30 (mod.rs:95-110), cache layout ``thumbnails/<shard>/<cas_id>.webp``
where the shard is the first 2 hex chars of the cas_id (shard.rs:8), and a
versioned thumbnails directory (directory.rs).

Image decode prefers the native C++ helpers (sd_images.cc: libjpeg/libpng/
libwebp) with a PIL fallback; video frame extraction links FFmpeg the way
the reference's sd-ffmpeg crate does (sd_ffmpeg.cc over libavformat/
libavcodec/libswscale, preferring embedded cover art then seeking 10% in —
crates/ffmpeg/src/thumbnailer.rs), with an ffmpeg-CLI fallback.
"""

from __future__ import annotations

import logging
import math
import shutil
import threading
import subprocess
from pathlib import Path

from ... import faults
from ...recovery import is_disk_full, note_disk_full
from ...utils.atomic import atomic_path, atomic_write_bytes, atomic_write_text

logger = logging.getLogger(__name__)

TARGET_PX = 262_144.0
WEBP_QUALITY = 30
THUMBNAIL_VERSION = 1

THUMBNAILABLE_IMAGE_EXTENSIONS = {
    "jpg", "jpeg", "png", "gif", "bmp", "webp", "tiff", "tif", "ico",
}
THUMBNAILABLE_VIDEO_EXTENSIONS = {
    "mp4", "mkv", "avi", "mov", "webm", "m4v", "mpg", "mpeg",
}
#: decoded via dlopen'd libheif (sd_heif.cc) when the runtime is present
HEIF_EXTENSIONS = {"heic", "heif", "avif"}

_FFMPEG = shutil.which("ffmpeg")


_THUMB_DIRS_READY: set[str] = set()


def thumbnail_dir(data_dir: str | Path) -> Path:
    d = Path(data_dir) / "thumbnails"
    # mkdir/version-stamp once per data_dir per process: this runs on hot
    # listing paths (one call per thumbnail_path)
    key = str(d)
    if key not in _THUMB_DIRS_READY:
        d.mkdir(parents=True, exist_ok=True)
        version_file = d / "version.txt"
        if not version_file.exists():
            atomic_write_text(version_file, str(THUMBNAIL_VERSION))
        # benign race: mkdir/version-stamp are idempotent and the set is a
        # pure memo — double work on a concurrent first call, never
        # corruption, and the hot listing path stays lock-free
        _THUMB_DIRS_READY.add(key)  # lint: ok(lock-discipline)
    return d


def thumbnail_path(data_dir: str | Path, cas_id: str) -> Path:
    """cas_id-sharded cache path (shard.rs: first two hex chars)."""
    return thumbnail_dir(data_dir) / cas_id[:2] / f"{cas_id}.webp"


def can_generate_thumbnail(extension: str | None) -> bool:
    ext = (extension or "").lower()
    return ext in THUMBNAILABLE_IMAGE_EXTENSIONS or (
        ext in THUMBNAILABLE_VIDEO_EXTENSIONS and _ffmpeg_capable()
    ) or (ext in HEIF_EXTENSIONS and _native_heif() is not None)


def _ffmpeg_capable() -> bool:
    """Can SOME backend decode video here? Answered without compiling:
    this runs on listing paths, where a synchronous g++ attempt (seconds,
    repeated each process on hosts where the build fails) is not
    acceptable. The real build happens on first generation, inside a job."""
    if _FFMPEG is not None:
        return True
    if _NATIVE_FFMPEG is not None:  # probe already ran: trust its answer
        return _NATIVE_FFMPEG[0] is not None
    import glob

    return bool(glob.glob("/usr/include/libavformat")
                or glob.glob("/usr/include/*/libavformat")
                or glob.glob("/usr/local/include/libavformat"))


def generate_thumbnail(source: str | Path, data_dir: str | Path, cas_id: str,
                       extension: str | None = None) -> Path | None:
    """Create (or reuse) the WebP thumbnail for one file; returns the path.

    Skip-and-log on ANY failure (including ENOSPC — the ``thumbnail``
    chaos seam rehearses it): a thumbnail is regenerable, so a full disk
    degrades to "no preview yet", never a failed media job. Writes are
    atomic (utils/atomic), so a kill mid-encode leaves no torn WebP for
    the explorer to render."""
    out = thumbnail_path(data_dir, cas_id)
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    ext = (extension or Path(source).suffix.lstrip(".")).lower()
    try:
        faults.inject("thumbnail", key=cas_id)
        if ext in THUMBNAILABLE_VIDEO_EXTENSIONS:
            return _video_thumbnail(Path(source), out)
        return _image_thumbnail(Path(source), out, ext)
    except Exception as e:
        if is_disk_full(e):
            note_disk_full("thumbnail")
        logger.warning("thumbnail failed for %s: %s", source, e)
        return None


_NATIVE_IMAGES: list | None = None  # [module_or_None] once probed
_NATIVE_FFMPEG: list | None = None
_NATIVE_HEIF: list | None = None


def _native_heif():
    """libheif-backed decode (sd-images `heif` feature) if the runtime
    loads; probe cached like the other native helpers."""
    global _NATIVE_HEIF
    if _NATIVE_HEIF is None:
        try:
            from ...native import heif_native

            _NATIVE_HEIF = [heif_native if heif_native.available() else None]
        except Exception as e:
            logger.info("heif support unavailable (%s)", e)
            _NATIVE_HEIF = [None]
    return _NATIVE_HEIF[0]


def _native_ffmpeg():
    """Linked FFmpeg decoder (sd_ffmpeg.cc) if buildable; probe cached like
    the image helper — a failed import involves a g++ attempt."""
    global _NATIVE_FFMPEG
    if _NATIVE_FFMPEG is None:
        try:
            from ...native import ffmpeg_native

            _NATIVE_FFMPEG = [ffmpeg_native]
        except Exception as e:
            logger.info("native ffmpeg unavailable (%s); using CLI if present", e)
            _NATIVE_FFMPEG = [None]
    return _NATIVE_FFMPEG[0]


def _native_images():
    """sd-images equivalent (C++ libjpeg/libpng/libwebp) if buildable.
    The probe result is cached — a failed import involves a g++ attempt and
    must not re-run per image."""
    global _NATIVE_IMAGES
    if _NATIVE_IMAGES is None:
        try:
            from ...native import images_native

            _NATIVE_IMAGES = [images_native]
        except Exception as e:
            logger.info("native image helper unavailable (%s); using PIL", e)
            _NATIVE_IMAGES = [None]
    return _NATIVE_IMAGES[0]


def _native_decode(source: Path, max_edge: int):
    """numpy RGB via the native decoder, or None → caller uses PIL."""
    native = _native_images()
    ext = source.suffix.lstrip(".").lower()
    if native is None or ext not in native.NATIVE_DECODE_EXTENSIONS:
        return None
    try:
        return native.decode_rgb(source, max_edge=max_edge)
    except Exception as e:
        logger.debug("native decode fell back to PIL for %s: %s", source, e)
        return None


def _image_thumbnail(source: Path, out: Path, ext: str | None = None) -> Path:
    from PIL import Image

    if (ext or source.suffix.lstrip(".").lower()) in HEIF_EXTENSIONS:
        heif = _native_heif()
        if heif is None:
            raise RuntimeError("libheif runtime not available")
        arr = heif.decode_rgb(source)
    else:
        # native decode (JPEG prescaled in DCT space near the target)
        arr = _native_decode(source, MAX_INPUT_EDGE)
    img = Image.fromarray(arr) if arr is not None else Image.open(source)
    with img:
        img = img.convert("RGB") if img.mode not in ("RGB", "RGBA") else img
        w, h = img.size
        # scale so w*h ≈ TARGET_PX (thumbnail/mod.rs:95-100 sqrt scale factor)
        if w * h > TARGET_PX:
            factor = math.sqrt(TARGET_PX / (w * h))
            img = img.resize((max(1, round(w * factor)), max(1, round(h * factor))))
        with atomic_path(out) as tmp:
            _save_webp(img, tmp)
    return out


def _save_webp(img, tmp: Path) -> None:
    native = _native_images()
    if native is not None:
        try:
            import numpy as np

            rgb = np.asarray(img.convert("RGB"), dtype=np.uint8)
            tmp.write_bytes(native.encode_webp(rgb, WEBP_QUALITY))
            return
        except Exception as e:
            logger.debug("native webp encode fell back to PIL: %s", e)
    img.save(tmp, "WEBP", quality=WEBP_QUALITY)


def _video_thumbnail(source: Path, out: Path) -> Path | None:
    native = _native_ffmpeg()
    if native is not None:
        try:
            from PIL import Image

            # one representative frame (cover art preferred, else 10% in),
            # then the same √(area) scale + WebP path images take
            frame = native.decode_frame_rgb(source)
            img = Image.fromarray(frame)
            w, h = img.size
            if w * h > TARGET_PX:
                factor = math.sqrt(TARGET_PX / (w * h))
                img = img.resize((max(1, round(w * factor)),
                                  max(1, round(h * factor))))
            with atomic_path(out) as tmp:
                _save_webp(img, tmp)
            return out
        except Exception as e:
            logger.debug("native video decode failed for %s (%s); CLI fallback",
                         source, e)
    if _FFMPEG is None:
        return None
    with atomic_path(out) as tmp:
        _cli_grab_frame(source, tmp, 512, webp_quality=WEBP_QUALITY)
    return out


def _cli_grab_frame(source: Path, out: Path, size: int,
                    webp_quality: int | None = None) -> None:
    """One frame via the ffmpeg CLI — the single place the grab command
    lives (seek heuristic, scale filter, timeout) so the thumbnail and
    bytes-helper paths can't drift apart."""
    cmd = [_FFMPEG, "-y", "-loglevel", "error", "-ss", "00:00:01",
           "-i", str(source), "-frames:v", "1",
           "-vf", f"scale='min({size},iw)':-2"]
    if webp_quality is not None:
        # explicit container: the atomic-write temp has no .webp suffix for
        # ffmpeg to infer the format from
        cmd += ["-f", "webp", "-quality", str(webp_quality)]
    subprocess.run(cmd + [str(out)], check=True, timeout=30,
                   capture_output=True)


# ---------------------------------------------------------------------------
# video helper surface (crates/ffmpeg/src/lib.rs:19-47 to_thumbnail /
# to_webp_bytes, film_strip.rs filter)
# ---------------------------------------------------------------------------


def film_strip_filter(arr):
    """Overlay sprocket-hole strips down both edges — the film_strip.rs
    effect, drawn procedurally (dark band, repeating light holes) instead
    of from baked pattern tiles."""
    import numpy as np

    arr = np.asarray(arr, dtype=np.uint8)
    h, w = arr.shape[:2]
    strip_w = max(4, w // 16)
    hole_h = max(2, strip_w // 2)
    period = hole_h * 2
    out = arr.copy()
    hole_w = max(1, strip_w // 2)
    x_off = (strip_w - hole_w) // 2
    for x0 in (0, w - strip_w):
        strip = out[:, x0:x0 + strip_w]
        strip[:] = (strip * 0.15).astype(np.uint8)
        for y0 in range(period // 2, h - hole_h, period):
            strip[y0:y0 + hole_h, x_off:x_off + hole_w] = 230
    return out


def video_to_webp_bytes(source: str | Path, size: int = 256,
                        quality: int = WEBP_QUALITY,
                        film_strip: bool = False) -> bytes:
    """One WebP-encoded video thumbnail as bytes (lib.rs to_webp_bytes;
    the builder's film_strip flag is opt-in here, like core's usage).
    Uses the linked decoder when it builds, else the ffmpeg CLI —
    the same capability set as generate_thumbnail's video path."""
    import io

    import numpy as np
    from PIL import Image

    frame = None
    native = _native_ffmpeg()
    if native is not None:
        try:
            frame = native.decode_frame_rgb(Path(source), target_edge=size)
        except Exception as e:
            logger.debug("native video decode failed for %s (%s); "
                         "CLI fallback", source, e)
    if frame is None:
        if _FFMPEG is None:
            raise RuntimeError("no video decode backend (libav libs or "
                               "ffmpeg CLI required)")
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            tmp = Path(td) / "frame.png"
            _cli_grab_frame(Path(source), tmp, size)
            with Image.open(tmp) as img:
                frame = np.asarray(img.convert("RGB"), dtype=np.uint8)
    if film_strip:
        frame = film_strip_filter(frame)
    native = _native_images()
    if native is not None:
        try:
            return native.encode_webp(frame, quality)
        except Exception:
            # PIL below produces the same artifact; log the fallback or a
            # broken native encoder silently halves encode throughput
            logger.debug("native webp encode failed; using PIL",
                         exc_info=True)
    buf = io.BytesIO()
    Image.fromarray(frame).save(buf, "WEBP", quality=quality)
    return buf.getvalue()


def video_to_thumbnail(source: str | Path, out: str | Path, size: int = 256,
                       quality: int = WEBP_QUALITY,
                       film_strip: bool = False) -> None:
    """Write a video thumbnail file (lib.rs to_thumbnail)."""
    out = Path(out)
    atomic_write_bytes(out, video_to_webp_bytes(source, size, quality,
                                                film_strip))


# ---------------------------------------------------------------------------
# batched device path (ops/resize_jax.py)
# ---------------------------------------------------------------------------

#: host box-reduce target: ≤2× the 512px output canvas, so the device's
#: 4-tap bilinear never skips source pixels (no aliasing) and transfers
#: stay 4× smaller than a 2048-edge canvas
MAX_INPUT_EDGE = 1024


def _decode_for_device(source: Path):
    """Decode (native libjpeg/libpng when available, JPEG prescaled in DCT
    space) + integer box-reduce to ≤MAX_INPUT_EDGE — cheap antialias
    pre-pass; the device kernel does the fractional bilinear step."""
    import numpy as np
    from PIL import Image

    if source.suffix.lstrip(".").lower() in HEIF_EXTENSIONS:
        heif = _native_heif()
        if heif is None:
            raise RuntimeError("libheif runtime not available")
        arr = heif.decode_rgb(source)
    else:
        arr = _native_decode(source, MAX_INPUT_EDGE)
    if arr is not None:
        edge = max(arr.shape[0], arr.shape[1])
        if edge > MAX_INPUT_EDGE:  # PNG has no in-decode scaling
            k = -(-edge // MAX_INPUT_EDGE)
            h, w = (arr.shape[0] // k) * k, (arr.shape[1] // k) * k
            arr = arr[:h, :w].reshape(h // k, k, w // k, k, 3) \
                .mean(axis=(1, 3)).astype(np.uint8)
        return arr
    with Image.open(source) as img:
        img = img.convert("RGB")
        edge = max(img.size)
        if edge > MAX_INPUT_EDGE:
            img = img.reduce(-(-edge // MAX_INPUT_EDGE))
        return np.asarray(img, dtype=np.uint8)


#: sticky per-process verdict on the device resize path: None = unmeasured,
#: True = device wins, False = device loses (every later batch goes scalar).
#: Even with the tpuThumbnails feature ON, the processor must never keep a
#: measurably losing path: on tunneled harnesses the per-image transfer
#: alone exceeds the whole scalar pipeline (see tpu-backend.md's ceiling
#: section), while a local-PCIe host measures a win and keeps batching.
#: The lock keeps concurrent first batches from probing simultaneously
#: (interleaved device calls would distort both measurements).
_DEVICE_VERDICT: dict = {"value": None}
_VERDICT_LOCK = threading.Lock()
#: batches smaller than this never decide the verdict — a 1–2 image call
#: charges the whole dispatch overhead to one image and would latch the
#: scalar path on hosts where normal batches win
_VERDICT_MIN_BATCH = 4


def _measure_device_verdict(batch_arrays, dt_device: float) -> bool:
    """Compare the (warm) device per-image resize time against PIL doing
    the same resize step on the same decoded arrays."""
    import time as _time

    import numpy as np
    from PIL import Image

    from ...ops.resize_jax import target_dims

    sample = batch_arrays[: min(8, len(batch_arrays))]
    t0 = _time.perf_counter()
    for arr in sample:
        th, tw = target_dims(arr.shape[1], arr.shape[0])
        np.asarray(Image.fromarray(arr).resize((tw, th), Image.BILINEAR))
    scalar_per_img = (_time.perf_counter() - t0) / len(sample)
    device_per_img = dt_device / len(batch_arrays)
    verdict = device_per_img <= scalar_per_img
    logger.info("thumbnail device verdict: device %.1f ms/img vs scalar "
                "%.1f ms/img — %s", device_per_img * 1e3, scalar_per_img * 1e3,
                "keeping device batching" if verdict
                else "routing to scalar for the rest of this process")
    return verdict


def _scalar_all(entries, data_dir: str | Path) -> dict:
    """Scalar pipeline over [(source, cas_id, ext)]; the shared fallback of
    every losing/failed device route."""
    out_paths: dict = {}
    for source, cas_id, ext in entries:
        made = generate_thumbnail(source, data_dir, cas_id, ext)
        if made is not None:
            out_paths[cas_id] = made
    return out_paths


def _device_resize_allowed() -> bool:
    """get_hasher-style routing gate for the batched resize: the sticky
    verdict when measured, else False outright on hosts with no accelerator
    platform — a jnp resize on pinned CPU loses to PIL by an order of
    magnitude (0.11× in BENCH_r05), so the fallback must never even decode
    for the device."""
    if _DEVICE_VERDICT["value"] is not None:
        return _DEVICE_VERDICT["value"]
    from ...objects.hasher import _accelerator_available

    if not _accelerator_available():
        with _VERDICT_LOCK:
            if _DEVICE_VERDICT["value"] is None:
                logger.info("thumbnail routing: no accelerator platform — "
                            "scalar PIL path for this process")
                _DEVICE_VERDICT["value"] = False
        return _DEVICE_VERDICT["value"]
    return True  # real accelerator, unmeasured: let the warm batch decide


def _pil_resize_all(arrays) -> list:
    """Scalar resize of decoded RGB arrays, dimension-identical to the
    device kernel (same target_dims math)."""
    import numpy as np
    from PIL import Image

    from ...ops.resize_jax import target_dims

    out = []
    for arr in arrays:
        th, tw = target_dims(arr.shape[1], arr.shape[0])
        if (th, tw) == (arr.shape[0], arr.shape[1]):
            out.append(arr)
        else:
            out.append(np.asarray(
                Image.fromarray(arr).resize((tw, th), Image.BILINEAR)))
    return out


def resize_images(arrays) -> list:
    """Routed batch resize over decoded RGB arrays — the one seam both the
    media processor and bench.py measure. Device kernel when the sticky
    verdict allows (first warm batch is timed against PIL on the same
    arrays); scalar PIL otherwise. Raises only if the device path dies
    mid-call — callers fall back to the full scalar pipeline then."""
    import time as _time

    from ...ops.resize_jax import resize_batch_host

    if not _device_resize_allowed():
        return _pil_resize_all(arrays)
    if _DEVICE_VERDICT["value"] is None:
        # EVERY device call synchronizes while the verdict is open — a
        # concurrent unmeasured batch would otherwise share the device with
        # the timed probe and distort the measurement
        with _VERDICT_LOCK:
            if (_DEVICE_VERDICT["value"] is None
                    and len(arrays) >= _VERDICT_MIN_BATCH):
                # measure the WARM device rate: run once for the compile,
                # once for the timing, score against scalar. Either way THIS
                # batch's device outputs are valid (dimension-identical), so
                # nothing is recomputed — only future batches change route.
                resize_batch_host(arrays)
                t0 = _time.perf_counter()
                thumbs = resize_batch_host(arrays)
                _DEVICE_VERDICT["value"] = _measure_device_verdict(
                    arrays, _time.perf_counter() - t0)
                return thumbs
            if _DEVICE_VERDICT["value"] is False:
                return _pil_resize_all(arrays)
            return resize_batch_host(arrays)
    return resize_batch_host(arrays)


#: images decoded+resized+encoded per device call: bounds the pad-and-mask
#: batch (resize_batch_host pads every lane to the batch max and rounds the
#: count to a power of two — 256 lanes of 1024² would be ~0.8 GB of uint8
#: before the kernel's float intermediates) AND the decoded-array working
#: set, while still amortizing one dispatch over dozens of images
RESIZE_SUB_BATCH = 32


def generate_thumbnails_batched(entries, data_dir: str | Path,
                                allow_device: bool = True):
    """Batch thumbnail generation: host decode → routed bilinear-resize in
    RESIZE_SUB_BATCH chunks → host WebP encode.

    ``entries``: [(source_path, cas_id, extension)]; returns {cas_id: Path}
    for every thumbnail produced. Videos and failed decodes fall back to the
    scalar path. The per-image outputs are dimension-identical to the scalar
    PIL path (same √(area) target math, target_dims).

    Routing is get_hasher-style hybrid (``resize_images``): no accelerator →
    scalar PIL outright; with one, the first (warm) device batch is timed
    against a scalar probe on the same decoded arrays and the sticky
    per-process verdict routes every later call — the caller always gets its
    thumbnails over whichever path measured fastest. ``allow_device=False``
    (the tpuThumbnails feature left off) skips the device unconditionally.
    """
    from PIL import Image

    from ...utils.jax_guard import ensure_jax_safe

    if not allow_device:
        return _scalar_all(entries, data_dir)
    ensure_jax_safe()  # wedged tunnel: run (and measure) on pinned CPU
    if not _device_resize_allowed():
        return _scalar_all(entries, data_dir)

    out_paths: dict[str, Path] = {}
    todo = []  # (source, cas_id, out_path, ext) still needing a thumbnail
    for source, cas_id, ext in entries:
        out = thumbnail_path(data_dir, cas_id)
        if out.exists():
            out_paths[cas_id] = out
            continue
        ext = (ext or Path(source).suffix.lstrip(".")).lower()
        if ext in THUMBNAILABLE_VIDEO_EXTENSIONS:
            made = generate_thumbnail(source, data_dir, cas_id, ext)
            if made is not None:
                out_paths[cas_id] = made
            continue
        todo.append((source, cas_id, out, ext))

    for start in range(0, len(todo), RESIZE_SUB_BATCH):
        sub = todo[start : start + RESIZE_SUB_BATCH]
        batch_arrays = []
        batch_meta = []
        for source, cas_id, out, ext in sub:
            try:
                batch_arrays.append(_decode_for_device(Path(source)))
                batch_meta.append((source, cas_id, out, ext))
            except Exception as e:
                logger.warning("decode failed for %s: %s", source, e)
        if not batch_arrays:
            continue
        try:
            thumbs = resize_images(batch_arrays)
        except Exception as e:
            logger.warning("device resize failed (%s); scalar fallback", e)
            out_paths.update(_scalar_all(
                [(s, c, e3) for s, c, _o, e3 in batch_meta], data_dir))
            continue
        for (_source, cas_id, out, _ext), thumb in zip(batch_meta, thumbs):
            try:
                faults.inject("thumbnail", key=cas_id)
                with atomic_path(out) as tmp:
                    _save_webp(Image.fromarray(thumb), tmp)
                out_paths[cas_id] = out
            except Exception as e:
                if is_disk_full(e):
                    note_disk_full("thumbnail")
                logger.warning("thumbnail encode failed for %s: %s", cas_id, e)
    return out_paths
