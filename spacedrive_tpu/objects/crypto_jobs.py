"""FileEncryptorJob / FileDecryptorJob.

Reference: core/src/object/fs/encrypt.rs + decrypt.rs (shipped commented-out
upstream; implemented live here). Output format: header (magic, keyslots,
optional sealed metadata blob) followed by the LE31 AEAD stream of 1MiB
blocks, written next to the source with the ``.bytes`` suffix
(fs/mod.rs:28 BYTES_EXT). The header bytes are the stream's AAD, so a
tampered header fails decryption of block 0.

Key sources: an explicit password, or a mounted key-manager key
(encrypt.rs:99 access_keymount) — the node's KeyManager lives at
node.key_manager; stored-key bytes act as the keyslot password.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

from ..crypto import Algorithm, FileHeader, Protected
from ..crypto.primitives import generate_master_key
from ..crypto.stream import CryptoError, Decryptor, Encryptor
from ..jobs import EarlyFinish, JobError, StepResult, WorkerContext
from .fs import _FsJob, find_available_name

logger = logging.getLogger(__name__)

BYTES_EXT = ".bytes"


def _resolve_key(ctx: WorkerContext, init_args: dict[str, Any]) -> Protected:
    if init_args.get("password"):
        return Protected(init_args["password"])
    key_uuid = init_args.get("key_uuid")
    if key_uuid:
        km = getattr(ctx.library.node, "key_manager", None)
        if km is None:
            raise JobError("no key manager on this node")
        return Protected(km.get_key(key_uuid).expose())
    raise JobError(
        "needs a password or a key_uuid (passwords are never persisted in "
        "checkpoints — a crypto job resumed after shutdown must use a "
        "key-manager key_uuid or be re-submitted)")


class FileEncryptorJob(_FsJob):
    """init_args: sources [file_path ids], password | key_uuid,
    algorithm ("XChaCha20Poly1305" | "Aes256Gcm"), metadata: bool,
    erase_original: bool."""

    NAME = "file_encryptor"
    SECRET_INIT_KEYS = ("password",)

    def init(self, ctx: WorkerContext):
        steps = []
        for row, src in self._sources(ctx):
            if row["is_dir"]:
                continue  # encrypt.rs only handles files
            steps.append({"file_path_id": row["id"], "src": str(src),
                          "location_id": row["location_id"],
                          "sub_path": (row["materialized_path"] or "/").strip("/")})
        if not steps:
            raise EarlyFinish("nothing to encrypt")
        _resolve_key(ctx, self.init_args)  # fail fast on bad key config
        algo = self.init_args.get("algorithm", "XChaCha20Poly1305")
        data = {
            "algorithm": (Algorithm.AES_256_GCM if algo == "Aes256Gcm"
                          else Algorithm.XCHACHA20_POLY1305).value,
            "metadata": bool(self.init_args.get("metadata")),
            "erase_original": bool(self.init_args.get("erase_original")),
            "rescan": sorted({(s["location_id"], s["sub_path"]) for s in steps}),
        }
        return data, steps, {"encrypted": 0, "bytes": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        src = Path(step["src"])
        if not src.is_file():
            return StepResult(errors=[f"encrypt {src}: no longer a file"])
        key = _resolve_key(ctx, self.init_args)
        algorithm = Algorithm(data["algorithm"])
        master_key = generate_master_key()
        header = FileHeader.new(algorithm)
        header.add_keyslot(key, master_key)
        if data["metadata"]:
            row = ctx.library.db.query(
                "SELECT fp.*, o.pub_id AS object_pub_id FROM file_path fp "
                "LEFT JOIN object o ON fp.object_id = o.id WHERE fp.id = ?",
                [step["file_path_id"]])
            meta = {"name": src.name, "size": src.stat().st_size}
            if row:
                meta["cas_id"] = row[0]["cas_id"]
                meta["object_pub_id"] = row[0]["object_pub_id"]
            header.add_metadata(master_key, meta)
        dst = find_available_name(src.with_name(src.name + BYTES_EXT))
        try:
            # streamed ciphertext (can be GBs — no tempfile copy); the
            # except path below unlinks the partial output, so a torn
            # write never survives as an openable artifact
            with open(src, "rb") as reader, open(dst, "wb") as writer:  # lint: ok(durability-discipline)
                header.write(writer)
                written = Encryptor.encrypt_streams(
                    master_key, header.nonce, algorithm, reader, writer,
                    header.aad())
            if data["erase_original"]:
                src.unlink()
        except (OSError, CryptoError) as e:
            dst.unlink(missing_ok=True)
            return StepResult(errors=[f"encrypt {src}: {e}"])
        finally:
            master_key.zeroize()
            key.zeroize()
        ctx.progress(message=f"encrypted {src.name}")
        return StepResult(metadata={"encrypted": 1, "bytes": written})

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        for loc_id, sub in data["rescan"]:
            self._rescan(ctx, loc_id, {sub})
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata


class FileDecryptorJob(_FsJob):
    """init_args: sources [file_path ids of .bytes files], password | key_uuid,
    erase_original: bool."""

    NAME = "file_decryptor"
    SECRET_INIT_KEYS = ("password",)

    def init(self, ctx: WorkerContext):
        steps = []
        for row, src in self._sources(ctx):
            if row["is_dir"]:
                continue
            steps.append({"file_path_id": row["id"], "src": str(src),
                          "location_id": row["location_id"],
                          "sub_path": (row["materialized_path"] or "/").strip("/")})
        if not steps:
            raise EarlyFinish("nothing to decrypt")
        _resolve_key(ctx, self.init_args)
        data = {
            "erase_original": bool(self.init_args.get("erase_original")),
            "rescan": sorted({(s["location_id"], s["sub_path"]) for s in steps}),
        }
        return data, steps, {"decrypted": 0, "bytes": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        src = Path(step["src"])
        key = _resolve_key(ctx, self.init_args)
        try:
            with open(src, "rb") as reader:
                header = FileHeader.from_reader(reader)
                master_key = header.decrypt_master_key(key)
                name = src.name[:-len(BYTES_EXT)] if src.name.endswith(BYTES_EXT) \
                    else src.name + ".decrypted"
                dst = find_available_name(src.with_name(name))
                try:
                    # streamed plaintext, partial output unlinked on failure
                    # (the CryptoError handler below) — same rationale as the
                    # encrypt side
                    with open(dst, "wb") as writer:  # lint: ok(durability-discipline)
                        written = Decryptor.decrypt_streams(
                            master_key, header.nonce, header.algorithm,
                            reader, writer, header.aad())
                except CryptoError:
                    dst.unlink(missing_ok=True)
                    raise
                finally:
                    master_key.zeroize()
            if data["erase_original"]:
                src.unlink()
        except (OSError, CryptoError) as e:
            return StepResult(errors=[f"decrypt {src}: {e}"])
        finally:
            key.zeroize()
        ctx.progress(message=f"decrypted {src.name}")
        return StepResult(metadata={"decrypted": 1, "bytes": written})

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        for loc_id, sub in data["rescan"]:
            self._rescan(ctx, loc_id, {sub})
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata
