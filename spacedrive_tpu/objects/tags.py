"""Tags + seeded categories.

Parity with core/src/object/tag/{mod,seed}.rs and library/cat.rs: tag CRUD,
object assignment (the many-many TagOnObject link), and the seeded category
list the overview screen groups by. All mutations emit CRDT ops when sync is
on (tags are the canonical Shared + Relation sync models).
"""

from __future__ import annotations

import uuid
from typing import TYPE_CHECKING, Any

from ..models import Object, Tag, TagOnObject, utc_now

if TYPE_CHECKING:
    from ..library import Library

#: library/cat.rs categories (overview grouping; ObjectKind-driven)
CATEGORIES = [
    "Recents", "Favorites", "Photos", "Videos", "Movies", "Music",
    "Documents", "Downloads", "Encrypted", "Projects", "Applications",
    "Archives", "Databases", "Games", "Books", "Contacts", "Trash",
]


def _emit(library: "Library", ops: list) -> None:
    sync = getattr(library, "sync", None)
    if sync is not None and getattr(sync, "emit_messages", False) and ops:
        sync.log_ops(ops)
        sync.created()


def _ops(library: "Library"):
    sync = getattr(library, "sync", None)
    if sync is not None and getattr(sync, "emit_messages", False):
        return sync
    return None


def create_tag(library: "Library", name: str, color: str | None = None) -> dict[str, Any]:
    pub_id = str(uuid.uuid4())
    row = {"pub_id": pub_id, "name": name, "color": color,
           "date_created": utc_now(), "date_modified": utc_now()}
    library.db.insert(Tag, row)
    sync = _ops(library)
    if sync:
        _emit(library, [sync.shared_create(Tag, pub_id, {
            "name": name, "color": color,
            "date_created": row["date_created"].isoformat()})])
    library.emit("invalidate_query", {"key": "tags.list"})
    return library.db.find_one(Tag, {"pub_id": pub_id})


def update_tag(library: "Library", tag_id: int, name: str | None = None,
               color: str | None = None) -> None:
    values: dict[str, Any] = {"date_modified": utc_now()}
    if name is not None:
        values["name"] = name
    if color is not None:
        values["color"] = color
    library.db.update(Tag, {"id": tag_id}, values)
    row = library.db.find_one(Tag, {"id": tag_id})
    sync = _ops(library)
    if sync and row:
        _emit(library, [sync.shared_update(Tag, row["pub_id"], k,
                                           v.isoformat() if hasattr(v, "isoformat") else v)
                        for k, v in values.items()])
    library.emit("invalidate_query", {"key": "tags.list"})


def delete_tag(library: "Library", tag_id: int) -> None:
    row = library.db.find_one(Tag, {"id": tag_id})
    if row is None:
        return
    library.db.delete(TagOnObject, {"tag_id": tag_id})
    library.db.delete(Tag, {"id": tag_id})
    sync = _ops(library)
    if sync:
        _emit(library, [sync.shared_delete(Tag, row["pub_id"])])
    library.emit("invalidate_query", {"key": "tags.list"})


def assign_tag(library: "Library", tag_id: int, object_ids: list[int],
               unassign: bool = False) -> None:
    """tags.assign: link/unlink a tag on objects (api/tags.rs assign)."""
    db = library.db
    tag = db.find_one(Tag, {"id": tag_id})
    if tag is None:
        raise ValueError(f"tag {tag_id} not found")
    sync = _ops(library)
    ops = []
    for oid in object_ids:
        obj = db.find_one(Object, {"id": oid})
        if obj is None:
            continue
        if unassign:
            db.delete(TagOnObject, {"tag_id": tag_id, "object_id": oid})
            if sync:
                ops.append(sync.relation_delete(TagOnObject, tag["pub_id"], obj["pub_id"]))
        else:
            db.insert(TagOnObject, {"tag_id": tag_id, "object_id": oid,
                                    "date_created": utc_now()}, or_ignore=True)
            if sync:
                ops.append(sync.relation_create(TagOnObject, tag["pub_id"], obj["pub_id"]))
    _emit(library, ops)
    library.emit("invalidate_query", {"key": "tags.getForObject"})


def tags_for_object(library: "Library", object_id: int) -> list[dict[str, Any]]:
    return [Tag.decode_row(r) for r in library.db.query(
        "SELECT t.* FROM tag t JOIN tag_on_object j ON j.tag_id = t.id "
        "WHERE j.object_id = ? ORDER BY t.name", [object_id])]


def objects_for_tag(library: "Library", tag_id: int) -> list[dict[str, Any]]:
    return [Object.decode_row(r) for r in library.db.query(
        "SELECT o.* FROM object o JOIN tag_on_object j ON j.object_id = o.id "
        "WHERE j.tag_id = ? ORDER BY o.id", [tag_id])]
