"""Pure-Python BLAKE3 — the framework's correctness oracle.

Implemented from the public BLAKE3 specification (the paper's reference
pseudocode; the upstream reference simply links the `blake3` Rust crate,
core/src/object/cas.rs:3). This implementation exists to (a) define the
byte-exact target the TPU kernel must match, and (b) hash the small tail
of files on hosts without the native helper. Throughput is irrelevant here;
the hot path runs on TPU (ops/blake3_jax.py) or via the C++ helper.

Two independent tree constructions are provided — the incremental chunk-stack
hasher and a recursive divide-and-conquer — so tree-chaining bugs cannot hide
behind a single implementation (they must agree on every input).
"""

from __future__ import annotations

import struct

OUT_LEN = 32
BLOCK_LEN = 64
CHUNK_LEN = 1024

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3
KEYED_HASH = 1 << 4
DERIVE_KEY_CONTEXT = 1 << 5
DERIVE_KEY_MATERIAL = 1 << 6

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

_MASK = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _MASK


def _g(state: list[int], a: int, b: int, c: int, d: int, mx: int, my: int) -> None:
    state[a] = (state[a] + state[b] + mx) & _MASK
    state[d] = _rotr(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotr(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b] + my) & _MASK
    state[d] = _rotr(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotr(state[b] ^ state[c], 7)


def _round(state: list[int], m: list[int]) -> None:
    # columns
    _g(state, 0, 4, 8, 12, m[0], m[1])
    _g(state, 1, 5, 9, 13, m[2], m[3])
    _g(state, 2, 6, 10, 14, m[4], m[5])
    _g(state, 3, 7, 11, 15, m[6], m[7])
    # diagonals
    _g(state, 0, 5, 10, 15, m[8], m[9])
    _g(state, 1, 6, 11, 12, m[10], m[11])
    _g(state, 2, 7, 8, 13, m[12], m[13])
    _g(state, 3, 4, 9, 14, m[14], m[15])


def compress(
    cv: tuple[int, ...] | list[int],
    block_words: list[int],
    counter: int,
    block_len: int,
    flags: int,
) -> list[int]:
    """The 7-round compression function; returns all 16 output words."""
    state = [
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        IV[0], IV[1], IV[2], IV[3],
        counter & _MASK, (counter >> 32) & _MASK, block_len, flags,
    ]
    m = list(block_words)
    for r in range(7):
        _round(state, m)
        if r < 6:
            m = [m[i] for i in MSG_PERMUTATION]
    for i in range(8):
        state[i] ^= state[i + 8]
        state[i + 8] ^= cv[i]
    return state


def _words_from_block(block: bytes) -> list[int]:
    if len(block) < BLOCK_LEN:
        block = block + b"\x00" * (BLOCK_LEN - len(block))
    return list(struct.unpack("<16I", block))


def _chunk_output(chunk: bytes, chunk_counter: int,
                  key_words: tuple[int, ...] | list[int] = IV,
                  base_flags: int = 0) -> tuple[list[int], list[int], int, int, int]:
    """Process a whole chunk except its final compression.

    Returns (input_cv, final_block_words, counter, final_block_len, final_flags)
    so the caller can decide whether the last compression is ROOT. ``key_words``
    + ``base_flags`` select the mode (hash / keyed_hash / derive_key).
    """
    cv: list[int] = list(key_words)
    blocks = [chunk[i : i + BLOCK_LEN] for i in range(0, len(chunk), BLOCK_LEN)] or [b""]
    for i, block in enumerate(blocks[:-1]):
        flags = base_flags | (CHUNK_START if i == 0 else 0)
        cv = compress(cv, _words_from_block(block), chunk_counter, BLOCK_LEN, flags)[:8]
    last = blocks[-1]
    flags = base_flags | CHUNK_END | (CHUNK_START if len(blocks) == 1 else 0)
    return cv, _words_from_block(last), chunk_counter, len(last), flags


def _parent_args(left_cv: list[int], right_cv: list[int],
                 key_words: tuple[int, ...] | list[int] = IV,
                 base_flags: int = 0) -> tuple[list[int], list[int], int, int, int]:
    return list(key_words), left_cv + right_cv, 0, BLOCK_LEN, PARENT | base_flags


def _root_bytes(args: tuple[list[int], list[int], int, int, int], out_len: int) -> bytes:
    """Extended output: re-run the root compression with incrementing counter."""
    cv, block_words, _, block_len, flags = args
    out = bytearray()
    counter = 0
    while len(out) < out_len:
        words = compress(cv, block_words, counter, block_len, flags | ROOT)
        out += struct.pack("<16I", *words)
        counter += 1
    return bytes(out[:out_len])


def blake3(data: bytes, out_len: int = OUT_LEN,
           key_words: tuple[int, ...] | list[int] = IV,
           base_flags: int = 0) -> bytes:
    """One-shot BLAKE3 via the incremental chunk-stack construction."""
    chunks = [data[i : i + CHUNK_LEN] for i in range(0, len(data), CHUNK_LEN)] or [b""]
    if len(chunks) == 1:
        cv, words, counter, block_len, flags = _chunk_output(chunks[0], 0, key_words, base_flags)
        return _root_bytes((cv, words, counter, block_len, flags), out_len)

    # chunk stack: push each chunk CV, merging completed subtrees whose size is
    # a power of two (count-trailing-zeros rule from the spec)
    stack: list[list[int]] = []
    total = 0
    for i, chunk in enumerate(chunks[:-1]):
        cv, words, counter, block_len, flags = _chunk_output(chunk, i, key_words, base_flags)
        new_cv = compress(cv, words, counter, block_len, flags)[:8]
        total += 1
        t = total
        while t & 1 == 0:
            left = stack.pop()
            new_cv = compress(*_parent_args(left, new_cv, key_words, base_flags))[:8]
            t >>= 1
        stack.append(new_cv)

    # final chunk stays un-finalized; fold the stack right-to-left
    cv, words, counter, block_len, flags = _chunk_output(
        chunks[-1], len(chunks) - 1, key_words, base_flags)
    right_cv = compress(cv, words, counter, block_len, flags)[:8]
    while len(stack) > 1:
        left = stack.pop()
        right_cv = compress(*_parent_args(left, right_cv, key_words, base_flags))[:8]
    return _root_bytes(_parent_args(stack[0], right_cv, key_words, base_flags), out_len)


def _key_words(key: bytes) -> tuple[int, ...]:
    if len(key) != 32:
        raise ValueError("BLAKE3 key must be exactly 32 bytes")
    return struct.unpack("<8I", key)


def blake3_keyed(key: bytes, data: bytes, out_len: int = OUT_LEN) -> bytes:
    """keyed_hash mode: the 32-byte key replaces the IV (spec §2.6)."""
    return blake3(data, out_len, _key_words(key), KEYED_HASH)


def derive_key(context: str | bytes, key_material: bytes, out_len: int = OUT_LEN) -> bytes:
    """derive_key mode (spec §2.6): hash the context string in
    DERIVE_KEY_CONTEXT mode, then the material keyed by that context key in
    DERIVE_KEY_MATERIAL mode. This is the KDF behind the reference's
    ``Key::derive`` (crates/crypto keyslot KEK derivation)."""
    ctx = context.encode() if isinstance(context, str) else context
    context_key = blake3(ctx, 32, IV, DERIVE_KEY_CONTEXT)
    return blake3(key_material, out_len, _key_words(context_key), DERIVE_KEY_MATERIAL)


def blake3_hex(data: bytes, out_len: int = OUT_LEN) -> str:
    return blake3(data, out_len).hex()


# --------------------------------------------------------------------------
# independent recursive construction (test cross-check only)
# --------------------------------------------------------------------------


def _subtree_cv(data: bytes, chunk_counter: int,
                key_words: tuple[int, ...] | list[int] = IV,
                base_flags: int = 0) -> list[int]:
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        cv, words, counter, block_len, flags = _chunk_output(
            data, chunk_counter, key_words, base_flags)
        return compress(cv, words, counter, block_len, flags)[:8]
    # left subtree takes the largest power-of-two chunk count strictly < n
    left_chunks = 1 << (n_chunks - 1).bit_length() - 1
    split = left_chunks * CHUNK_LEN
    left = _subtree_cv(data[:split], chunk_counter, key_words, base_flags)
    right = _subtree_cv(data[split:], chunk_counter + left_chunks, key_words, base_flags)
    return compress(*_parent_args(left, right, key_words, base_flags))[:8]


def blake3_recursive(data: bytes, out_len: int = OUT_LEN,
                     key_words: tuple[int, ...] | list[int] = IV,
                     base_flags: int = 0) -> bytes:
    """Divide-and-conquer construction; must agree with ``blake3`` everywhere."""
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        cv, words, counter, block_len, flags = _chunk_output(data, 0, key_words, base_flags)
        return _root_bytes((cv, words, counter, block_len, flags), out_len)
    left_chunks = 1 << (n_chunks - 1).bit_length() - 1
    split = left_chunks * CHUNK_LEN
    left = _subtree_cv(data[:split], 0, key_words, base_flags)
    right = _subtree_cv(data[split:], left_chunks, key_words, base_flags)
    return _root_bytes(_parent_args(left, right, key_words, base_flags), out_len)
