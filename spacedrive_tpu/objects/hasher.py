"""Hasher backends: the seam that makes identity hashing pluggable.

The reference hard-codes scalar CPU BLAKE3 inside FileMetadata::new
(file_identifier/mod.rs:80-88). Here the cas_id computation is a backend
behind the per-location ``hasher`` config ("cpu" | "tpu", BASELINE.json's
`hasher = "tpu"` flag) so the identifier job, dedup and sync stay
hasher-agnostic.

The TPU backend batches sampled messages into shape buckets:
- the fixed 57,352-byte sampled bucket (every file > 100KiB) — the hot path,
  one compiled kernel shape;
- a handful of small-file chunk-capacity buckets (1/4/16/32/64/101 chunks) to
  bound zero-padding waste while keeping the compiled-shape count constant.

The device compression kernel under every batched path here (row pipeline,
small-file buckets, sharded variants) is selected by ``SD_BLAKE3_KERNEL=
xla|pallas`` — resolved per call inside ops/blake3_jax's entry points, so
the hashers need no plumbing and a process switches kernels without
re-instantiating backends (each choice jit-caches separately).

Per-file IO errors come back as Exception entries; callers route them into
job errors instead of aborting the batch.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from pathlib import Path
from typing import Callable, Protocol

from .. import telemetry
from .cas import (MINIMUM_FILE_SIZE, SAMPLED_MESSAGE_LEN, generate_cas_id,
                  read_sampled_batch)

logger = logging.getLogger(__name__)

#: chunk capacities for small-file buckets (1 chunk = 1024 B); 101 covers the
#: largest whole-file message (100KiB + 8B size prefix)
SMALL_BUCKETS = (1, 4, 16, 32, 64, 101)
SAMPLED_CHUNKS = (SAMPLED_MESSAGE_LEN + 1023) // 1024  # 57


# -- dispatch telemetry --------------------------------------------------------
# Per-batch accounting on the unified registry: batches/files/payload-bytes
# per backend, plus the live files-per-sec / bytes-per-sec / MFU gauges the
# roofline model turns the last batch into. The decorators guard with a
# thread-local "outermost" flag so composed backends (hybrid → cpu/tpu,
# remote → hybrid fallback) count each batch exactly once, attributed to
# the entry-point backend.

_HASH_BATCHES = telemetry.counter(
    "sd_hash_batches_total", "hash batches dispatched per backend",
    labels=("backend",))
_HASH_FILES = telemetry.counter(
    "sd_hash_files_total", "files hashed per backend", labels=("backend",))
_HASH_BYTES = telemetry.counter(
    "sd_hash_bytes_total", "cas-message payload bytes hashed per backend",
    labels=("backend",))
_HASH_SECONDS = telemetry.histogram(
    "sd_hash_batch_seconds", "hash batch latency per backend",
    labels=("backend",))
_HASH_RATE = telemetry.gauge(
    "sd_hash_files_per_sec", "files/s of the last hash batch")
_HASH_BPS = telemetry.gauge(
    "sd_hash_bytes_per_sec", "payload bytes/s of the last hash batch")
_HASH_MFU = telemetry.gauge(
    "sd_hash_mfu",
    "u32-VPU model-op-utilization of the last hash batch "
    "(ops/roofline.py model)")

# -- per-batch router telemetry ------------------------------------------------
# The hybrid router's decision inputs and outcomes: live transfer-inclusive
# bytes/s per engine (EWMA over full dispatch wall time, H2D included),
# engine flips, and per-engine routed-batch counts. These are the series
# the bench's BENCH_r06 knobs (`router_flips`, per-backend batch counts)
# and the tpu-backend.md router docs read.
_ROUTER_BPS = telemetry.gauge(
    "sd_hash_router_bytes_per_sec",
    "EWMA transfer-inclusive payload bytes/s per engine (router input)",
    labels=("backend",))
_ROUTER_MFU = telemetry.gauge(
    "sd_hash_router_device_mfu",
    "u32-VPU MFU implied by the router's device-engine EWMA rate")
_ROUTER_FLIPS = telemetry.counter(
    "sd_hash_router_flips_total",
    "engine flips by the per-batch hash router (hysteresis-damped)")
_ROUTER_BATCHES = telemetry.counter(
    "sd_hash_router_batches_total",
    "hash (sub-)batches the hybrid router dispatched per engine",
    labels=("backend",))

class _OutermostGuard:
    """Process-wide outermost-call tracker (not thread-local: the
    hybrid's work-stealing branch runs the leaf backends on helper
    THREADS, and those sub-batches must still attribute to the one
    hybrid batch). Concurrent independent batches undercount to one —
    acceptable: the jobs manager runs one identify at a time per lane."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._depth = 0

    def enter(self) -> bool:
        with self._lock:
            self._depth += 1
            return self._depth == 1

    def leave(self) -> None:
        with self._lock:
            self._depth -= 1


_HASH_OUTERMOST = _OutermostGuard()


def _message_len(size: int) -> int:
    """Bytes of the cas message actually hashed for a file of ``size``
    (sampled layout caps at SAMPLED_MESSAGE_LEN; whole-file below)."""
    if size > MINIMUM_FILE_SIZE:
        return SAMPLED_MESSAGE_LEN
    return size + 8  # size-prefix + whole file


def _record_hash(backend: str, files: int, nbytes: int, seconds: float) -> None:
    _HASH_BATCHES.inc(backend=backend)
    _HASH_FILES.inc(files, backend=backend)
    _HASH_BYTES.inc(nbytes, backend=backend)
    _HASH_SECONDS.observe(seconds, backend=backend)
    if seconds > 0:
        from ..ops import roofline

        bps = nbytes / seconds
        _HASH_RATE.set(round(files / seconds, 1))
        _HASH_BPS.set(round(bps, 1))
        _HASH_MFU.set(round(roofline.mfu(bps), 6))


def _instrumented(bytes_of: Callable[[tuple], int]):
    """Wrap a ``hash_batch``/``hash_gathered`` method with outermost-only
    per-batch accounting; ``bytes_of(args)`` computes the payload size."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            # keyword invocations stay valid against the HasherBackend
            # protocol; they just skip the accounting (bytes_of reads
            # positional slots — every production call site is positional)
            if not telemetry.enabled() or kwargs:
                return fn(self, *args, **kwargs)
            outermost = _HASH_OUTERMOST.enter()
            t0 = time.perf_counter()
            try:
                result = fn(self, *args)
            finally:
                _HASH_OUTERMOST.leave()
            # record only COMPLETED batches: an aborted batch (device
            # wedge mid-call) hashed nothing — counting it would inflate
            # files/bytes and let the CPU re-dispatch double-count
            if outermost:
                _record_hash(self.name, len(args[0]), bytes_of(args),
                             time.perf_counter() - t0)
            return result
        return wrapper
    return deco


def _paths_bytes(args: tuple) -> int:
    _paths, sizes = args
    return sum(_message_len(s) for s in sizes)


def _messages_bytes(args: tuple) -> int:
    (messages,) = args
    return sum(len(m) for m in messages if not isinstance(m, Exception))


_count_hash_batch = _instrumented(_paths_bytes)
_count_hash_gathered = _instrumented(_messages_bytes)


class HasherBackend(Protocol):
    name: str
    #: True when hash_batch may touch the jax device backend — get_hasher
    #: runs the wedge guard before instantiating such backends
    USES_DEVICE: bool

    def hash_batch(self, paths: list[str | Path],
                   sizes: list[int]) -> list[str | Exception]: ...

    def hash_gathered(self,
                      messages: list[bytes | Exception]) -> list[str | Exception]: ...


class CpuHasher:
    """Scalar reference path; byte-exact oracle (objects/cas.py). The native
    C++ helper slots in here when present (native/)."""

    name = "cpu"

    def __init__(self) -> None:
        self._fast = _load_native_hasher()

    @_count_hash_batch
    def hash_batch(self, paths: list[str | Path], sizes: list[int]) -> list[str | Exception]:
        if self._fast is not None:
            return self._fast(paths, sizes)
        out: list[str | Exception] = []
        for path, size in zip(paths, sizes):
            try:
                out.append(generate_cas_id(path, size))
            except (OSError, EOFError) as e:
                out.append(e)
        return out

    @_count_hash_gathered
    def hash_gathered(self,
                      messages: list[bytes | Exception]) -> list[str | Exception]:
        """Hash pre-gathered cas messages (the pipelined prefetcher already
        did the file I/O): native C++ BLAKE3 batch, python oracle fallback.
        Exception entries (gather failures) pass through in place."""
        return _hash_gathered_messages(messages, _native_hex_batch())


#: files per device sub-batch in the pipelined sampled path
PIPELINE_BATCH = 2048


class TpuHasher:
    """Batched JAX/TPU path.

    Large (sampled) files take the fused pipeline: the native C++ gather
    reads each file's sample message straight into a row of the device-layout
    byte matrix (no per-file Python work), the (block,word,chunk,batch)
    permutation happens on device, and sub-batches are double-buffered so the
    next gather overlaps the previous batch's transfer+compute (async jax
    dispatch). Small files go through the bucketed whole-file path.
    """

    name = "tpu"
    USES_DEVICE = True

    @_count_hash_batch
    def hash_batch(self, paths: list[str | Path], sizes: list[int]) -> list[str | Exception]:
        from .cas import MINIMUM_FILE_SIZE

        out: list[str | Exception] = [None] * len(paths)  # type: ignore[list-item]
        sampled = [i for i, s in enumerate(sizes) if s > MINIMUM_FILE_SIZE]
        small = [i for i, s in enumerate(sizes) if s <= MINIMUM_FILE_SIZE]
        if sampled:
            self._hash_sampled(paths, sizes, sampled, out)
        if small:
            self._hash_small(paths, sizes, small, out)
        return out

    # -- sampled (fixed-shape) pipeline ------------------------------------
    def _hash_sampled(self, paths, sizes, indices: list[int], out: list) -> None:
        """Fused gather→hash with DOUBLE-BUFFERED H2D: while batch k's
        kernel executes on device, batch k+1's host gather runs and its
        rows are already staged device-side (``_stage_rows`` → async
        ``jax.device_put``), and batch k-1's digests come back. Three
        batches in flight — transfer is never serialized behind compute,
        which is exactly where the one-shot r05 device path lost
        (0.13 GB/s resident vs 0.07 GB/s transfer-inclusive)."""
        try:
            from ..native import cas_native
        except Exception:
            self._hash_python(paths, sizes, indices, out)
            return

        import numpy as np

        from ..ops.blake3_jax import _pad_to_tier, digests_to_hex

        stride = SAMPLED_CHUNKS * 1024
        pending = None  # (device result, host lengths, batch indices)

        def stage(idxs):
            """Host gather + device staging for one sub-batch (enqueued
            H2D overlaps whatever kernel is currently running)."""
            tier = self._pad_lanes(_pad_to_tier(len(idxs)))
            rows = np.zeros((tier, stride), np.uint8)
            lengths = np.zeros(tier, np.int32)
            cas_native.gather_batch([paths[i] for i in idxs],
                                    [sizes[i] for i in idxs], rows, lengths)
            rows32 = rows.view(np.uint32).reshape(tier, stride // 4)
            dev_rows, dev_lengths = self._stage_rows(rows32, lengths)
            return (dev_rows, dev_lengths, lengths, idxs)

        def collect(item):
            dev, lengths, idxs = item
            hexes = digests_to_hex(np.asarray(dev))
            for j, i in enumerate(idxs):
                if lengths[j] == 0:
                    out[i] = OSError(f"cas gather failed for {paths[i]}")
                else:
                    out[i] = hexes[j][:16]

        chunks = [indices[s : s + PIPELINE_BATCH]
                  for s in range(0, len(indices), PIPELINE_BATCH)]
        staged = stage(chunks[0])
        for nxt in chunks[1:] + [None]:
            dev_rows, dev_lengths, lengths, idxs = staged
            # enqueue batch k's kernel (async jax dispatch) ...
            dev = self._device_hash_rows(dev_rows, dev_lengths)
            # ... then gather + H2D-stage batch k+1 while it runs ...
            staged = stage(nxt) if nxt is not None else None
            # ... and only now block on batch k-1's D2H digest readback
            if pending is not None:
                collect(pending)
            pending = (dev, lengths, idxs)
        if pending is not None:
            collect(pending)

    # -- small files (variable size, bucketed) -----------------------------
    def _hash_small(self, paths, sizes, indices: list[int], out: list) -> None:
        messages = read_sampled_batch([paths[i] for i in indices],
                                      [sizes[i] for i in indices])
        ok = [j for j, m in enumerate(messages) if not isinstance(m, Exception)]
        for j, m in enumerate(messages):
            if isinstance(m, Exception):
                out[indices[j]] = m
        ids = _bucketed_hash([messages[j] for j in ok], self._hash_bucket)
        for j, cid in zip(ok, ids):
            out[indices[j]] = cid

    def _hash_python(self, paths, sizes, indices: list[int], out: list) -> None:
        """No native toolchain: pure-Python gather into the bucketed kernel."""
        messages = read_sampled_batch([paths[i] for i in indices],
                                      [sizes[i] for i in indices])
        ok = [j for j, m in enumerate(messages) if not isinstance(m, Exception)]
        for j, m in enumerate(messages):
            if isinstance(m, Exception):
                out[indices[j]] = m
        hexes = self._hash_bucket([messages[j] for j in ok], SAMPLED_CHUNKS)
        for j, h in zip(ok, hexes):
            out[indices[j]] = h[:16]

    def _hash_bucket(self, msgs: list[bytes], cap: int) -> list[str]:
        from ..ops.blake3_jax import blake3_batch_hex

        return blake3_batch_hex(msgs, max_chunks=cap)

    @_count_hash_gathered
    def hash_gathered(self,
                      messages: list[bytes | Exception]) -> list[str | Exception]:
        """Pre-gathered messages through the device bucket path (sampled
        57-chunk messages land in the 64-chunk bucket; same digests as the
        fused row pipeline, the message is identical either way)."""
        return _hash_gathered_messages(
            messages, lambda msgs: _bucketed_hash(msgs, self._hash_bucket))

    # hooks the sharded variant overrides
    def _pad_lanes(self, n: int) -> int:
        return n

    def _stage_rows(self, rows32, lengths):
        """Begin the H2D transfer for a gathered sub-batch (async enqueue;
        completion overlaps the in-flight kernel). The sharded variant
        keeps rows on host — the mesh decides placement per shard."""
        from ..utils.jax_guard import ensure_jax_safe

        ensure_jax_safe()  # memoized; device backends pass through get_hasher
        import jax

        return jax.device_put(rows32), jax.device_put(lengths)

    def _device_hash_rows(self, rows32, lengths):
        import jax.numpy as jnp

        from ..ops.blake3_jax import blake3_batch_rows

        # donate: each staged row buffer is used exactly once (stage() in
        # _hash_sampled allocates fresh per sub-batch)
        return blake3_batch_rows(jnp.asarray(rows32), jnp.asarray(lengths),
                                 donate=True)


def _bounded_call(fn, deadline_s: float, name: str):
    """Run ``fn`` on a bounded daemon worker: a wedged device service HANGS
    rather than raising, and no per-batch dispatch may park the scan.
    Returns ``("ok", value)``, ``("error", exc)``, or ``("timeout", None)``
    (the leaked worker is a daemon; its result is discarded)."""
    box: list = []

    def _run() -> None:
        try:
            box.append(("ok", fn()))
        except BaseException as e:  # noqa: BLE001 — scored by the caller
            box.append(("error", e))

    worker = threading.Thread(target=_run, daemon=True, name=name)
    worker.start()
    worker.join(timeout=deadline_s)
    if not box:
        return ("timeout", None)
    return box[0]


class BackendRouter:
    """Per-batch cpu-vs-device routing from LIVE transfer-inclusive rates.

    The one-shot probe verdict answered "which engine wins right now?" once
    per process — wrong whenever transfer conditions drift mid-scan (relay
    contention, page-cache state, a recovering tunnel). The router instead
    keeps an EWMA of each engine's *transfer-inclusive* payload bytes/s —
    measured around the full dispatch (host staging + H2D + kernel + D2H),
    exactly the number "GPUs as Storage System Accelerators" says decides
    offload — and re-picks per batch:

    - **hysteresis**: the losing engine must beat the incumbent's EWMA by
      ``HYSTERESIS``× to flip, so jittery rates don't flap the route;
    - **exploration**: every ``EXPLORE_EVERY`` batches a small capped
      sub-slice runs on the losing engine to keep its EWMA live (a rate
      nobody measures can never win back the route);
    - **degraded re-probe**: after a mid-batch device failure pins the
      route to CPU, a bounded device probe re-runs after ``REPROBE_AFTER``
      CPU-routed batches — a transient wedge without a relay-recovery
      event must not pin CPU for the whole scan (the recapture watcher
      stays the fast path when the relay *does* announce recovery).

    Decisions and inputs are published on the unified registry
    (``sd_hash_router_*``); MFU for the device EWMA comes from
    ops/roofline.py.
    """

    HYSTERESIS = 1.25
    EWMA_ALPHA = 0.3
    EXPLORE_EVERY = 32
    REPROBE_AFTER = 64
    #: messages per exploration/re-probe sub-slice — bounds the cost of
    #: measuring the losing engine to a sliver of one batch
    PROBE_SLICE = 128

    def __init__(self, flips_counter=None, batches_counter=None,
                 bps_gauge=None, mfu_gauge=None,
                 event_prefix: str = "hash_router") -> None:
        # metric handles default to the hash families; other subsystems
        # (the device search engine, ISSUE 15) reuse the routing logic
        # with their own sd_* families and event names
        self._flips_counter = flips_counter if flips_counter is not None \
            else _ROUTER_FLIPS
        self._batches_counter = batches_counter \
            if batches_counter is not None else _ROUTER_BATCHES
        self._bps_gauge = bps_gauge if bps_gauge is not None else _ROUTER_BPS
        self._mfu_gauge = mfu_gauge if mfu_gauge is not None else (
            _ROUTER_MFU if event_prefix == "hash_router" else None)
        self._event_prefix = event_prefix
        self._lock = threading.Lock()
        self.cpu_bps: float | None = None
        self.dev_bps: float | None = None
        self.current = "cpu"
        self.degraded = False
        self.flips = 0
        self._streak = 0
        self._cpu_since_degrade = 0

    def seed(self, cpu_bps: float, dev_bps: float) -> None:
        """Initialize from the one-time fused probe (both engines measured
        on real work); the EWMAs take over from here."""
        with self._lock:
            self.cpu_bps = cpu_bps
            self.dev_bps = dev_bps
            self.current = "device" if dev_bps > cpu_bps else "cpu"
            self.degraded = False
            self._streak = 0

    def reset(self) -> None:
        """Forget everything (relay recovery / test isolation): the next
        batch re-probes from scratch."""
        with self._lock:
            self.cpu_bps = self.dev_bps = None
            self.current = "cpu"
            self.degraded = False
            self._streak = 0
            self._cpu_since_degrade = 0

    def degrade(self, reason: str = "") -> None:
        """A device dispatch died mid-batch: pin the route to CPU until a
        bounded re-probe (or the recapture watcher) clears it."""
        with self._lock:
            self.degraded = True
            self.dev_bps = 0.0
            self._cpu_since_degrade = 0
            if self.current != "cpu":
                self._flip_locked("cpu")
        telemetry.event(f"{self._event_prefix}_degraded", reason=reason)

    def _flip_locked(self, to: str) -> None:
        self.current = to
        self.flips += 1
        self._streak = 0
        self._flips_counter.inc()
        # flight-recorder edge: router flips are exactly what an operator
        # tails a live node for (telemetry.watch / SSE)
        telemetry.event(f"{self._event_prefix}_flip", to=to,
                        cpu_bps=round(self.cpu_bps or 0.0),
                        device_bps=round(self.dev_bps or 0.0))
        logger.info("hash router: engine flipped to %s "
                    "(cpu %.2f MB/s, device %.2f MB/s)", to,
                    (self.cpu_bps or 0.0) / 1e6, (self.dev_bps or 0.0) / 1e6)

    def route(self) -> tuple[str, str | None]:
        """Pick engines for one batch: ``(main, probe)`` where ``probe``
        (None most batches) asks the caller to run a capped sub-slice on
        the named engine to refresh its live rate."""
        with self._lock:
            if self.degraded:
                self._cpu_since_degrade += 1
                if self._cpu_since_degrade >= self.REPROBE_AFTER:
                    # the counter is NOT reset here: a batch that cannot
                    # carry the probe (no routable messages) must not burn
                    # the token — the offer repeats until a probe actually
                    # runs, and then degrade() (failed/timed-out probe) or
                    # observe("device") (success) restarts the bound
                    return ("cpu", "device")
                return ("cpu", None)
            cur_bps = self.dev_bps if self.current == "device" else self.cpu_bps
            other = "cpu" if self.current == "device" else "device"
            other_bps = self.dev_bps if other == "device" else self.cpu_bps
            if (cur_bps is not None and other_bps is not None
                    and other_bps > cur_bps * self.HYSTERESIS):
                self._flip_locked(other)
                return (self.current, None)
            self._streak += 1
            if self._streak >= self.EXPLORE_EVERY:
                self._streak = 0
                return (self.current, other)
            return (self.current, None)

    def observe(self, engine: str, nbytes: int, seconds: float) -> None:
        """Fold one measured dispatch into the engine's EWMA. A measured
        device success also clears the degraded pin — the engine is
        demonstrably alive, so the rate comparison takes back over."""
        bps = nbytes / max(seconds, 1e-9)
        with self._lock:
            prev = self.dev_bps if engine == "device" else self.cpu_bps
            ewma = bps if not prev else \
                self.EWMA_ALPHA * bps + (1.0 - self.EWMA_ALPHA) * prev
            if engine == "device":
                self.dev_bps = ewma
                if self.degraded:
                    self.degraded = False
                    logger.info("hash router: device re-probe succeeded "
                                "(%.2f MB/s) — degraded pin cleared",
                                bps / 1e6)
            else:
                self.cpu_bps = ewma
        self._batches_counter.inc(backend=engine)
        self._bps_gauge.set(round(ewma, 1), backend=engine)
        if engine == "device" and self._mfu_gauge is not None:
            from ..ops import roofline

            self._mfu_gauge.set(round(roofline.mfu(ewma), 6))


class HybridHasher:
    """Adaptive heterogeneous executor over the native-CPU and TPU engines.

    On first use it probes each engine's solo throughput on real work (the
    results are kept, not discarded); the probe SEEDS a
    :class:`BackendRouter` that then re-picks the engine PER BATCH from
    live transfer-inclusive rates (EWMA, hysteresis-damped, with periodic
    exploration of the losing engine and a bounded re-probe out of the
    degraded pin). On the fused path, when the device holds the route,
    sampled chunks are work-stolen from one queue with a tail guard so the
    slower engine's last chunk never dominates the makespan. On rigs where
    the device loses (e.g. this harness: tunneled H2D is wire-limited AND
    device transfers collapse ~100x under concurrent CPU load — measured
    0.4s/chunk solo vs 39.7s under load), ALL sampled work routes native,
    so hybrid throughput equals the best available engine by construction
    instead of losing to contention.

    The reference has a single engine (CPU join_all, file_identifier/
    mod.rs:107-134); this seam is where a local-PCIe TPU host gets its
    speedup without any config change."""

    name = "hybrid"
    USES_DEVICE = True

    #: steal unit: small enough that the slower engine's last chunk can't
    #: dominate the makespan, large enough to amortize a device dispatch
    CHUNK = 128
    #: files used for the one-time engine rate probe
    PROBE = 64

    def __init__(self) -> None:
        self._tpu = TpuHasher()
        self._cpu = CpuHasher()
        self._cpu_rate: float | None = None
        self._device_rate: float | None = None
        #: per-batch engine router (live transfer-inclusive rates + EWMA
        #: hysteresis); seeded by the one-time fused probe below
        self.router = BackendRouter()

    def degrade_device(self, reason: str = "") -> None:
        """Pin the route to native CPU after a mid-batch device failure
        (wedge, dead tunnel). The pin is NOT forever: the router re-probes
        the device on a bounded sub-slice after ``REPROBE_AFTER``
        CPU-routed batches, and :func:`reset_device_verdicts` (the relay
        recapture watcher) re-arms the full probe immediately."""
        self._cpu_rate = self._cpu_rate or 1.0
        self._device_rate = 0.0
        self.router.degrade(reason)
        logger.warning("hybrid hasher degraded to native CPU%s",
                       f": {reason}" if reason else "")

    def reset_verdict(self) -> None:
        """Forget both engine measurements (recapture watcher path): the
        next batch re-runs the fused probe and re-seeds the router."""
        self._cpu_rate = self._device_rate = None
        self.router.reset()

    def _cpu_into(self, paths, sizes, idxs: list[int], out: list) -> None:
        """Native-CPU hash ``idxs`` and scatter results into ``out``."""
        res = self._cpu.hash_batch([paths[i] for i in idxs],
                                   [sizes[i] for i in idxs])
        for i, r in zip(idxs, res):
            out[i] = r

    #: floor rate for the bounded device deadline: a dispatch slower than
    #: this is indistinguishable from a wedge and gets abandoned
    DEVICE_FLOOR_BPS = 512 * 1024

    def _device_deadline_s(self, nbytes: int, probe: bool) -> float:
        """Deadline for a bounded device dispatch. Probe/exploration slices
        get a TIGHT bound derived from the CPU's live rate — the probe only
        exists to ask "could the device win?", and a device that cannot
        hash the slice within ~4× the CPU's time for the same bytes has
        already answered no; waiting out a generous wedge deadline would
        stall the scan ~40s per exploration on collapsed-transfer rigs.
        Main-route dispatches (the device actually won) keep the generous
        wedge-detection bound."""
        if probe:
            cpu_bps = self.router.cpu_bps or 0.0
            if cpu_bps > 0:
                return min(15.0, max(2.0, 4.0 * nbytes / cpu_bps))
            return 15.0
        return max(60.0, nbytes / self.DEVICE_FLOOR_BPS)

    def _dispatch_gathered(self, engine: str, idxs: list[int], messages,
                           out: list, probe: bool = False) -> None:
        """Run one routed sub-batch: measure the transfer-inclusive rate
        into the router's EWMA; a device failure/timeout finishes the
        sub-batch natively (byte-identical digests) and degrades the pin."""
        sub = [messages[i] for i in idxs]
        nbytes = sum(len(m) for m in sub)
        t0 = time.perf_counter()
        if engine == "device":
            status, res = _bounded_call(
                lambda: self._tpu.hash_gathered(sub),
                self._device_deadline_s(nbytes, probe),
                "hybrid-device-dispatch")
            if status == "ok":
                self.router.observe("device", nbytes,
                                    time.perf_counter() - t0)
            else:
                why = repr(res) if status == "error" else \
                    "deadline exceeded (wedged device?)"
                logger.warning("hybrid device dispatch failed mid-batch "
                               "(%s); re-dispatching on native CPU", why)
                self.degrade_device(why)
                res = self._cpu.hash_gathered(sub)
        else:
            res = self._cpu.hash_gathered(sub)
            self.router.observe("cpu", nbytes, time.perf_counter() - t0)
        for i, r in zip(idxs, res):
            out[i] = r

    @_count_hash_gathered
    def hash_gathered(self,
                      messages: list[bytes | Exception]) -> list[str | Exception]:
        """Gathered-message route: PER-BATCH engine choice by the router
        (live transfer-inclusive rates, hysteresis, bounded re-probe). An
        unprobed process routes native — the safe default on wire-limited
        rigs (the pipelined identifier runs its first batch through
        ``hash_batch`` precisely so the probe seeds the router). With no
        native lib there is nothing to race — mirror hash_batch's routing
        to the device path, never the python oracle."""
        if self._cpu._fast is None:
            return self._tpu.hash_gathered(messages)
        if self._cpu_rate is None or self._device_rate is None:
            return self._cpu.hash_gathered(messages)
        # mirror hash_batch's small/sampled split — short messages stay on
        # native CPU (IO-bound work whose varied lengths would fan the
        # device path across many bucket shapes); sampled-class messages
        # are the routable payload
        big = [i for i, m in enumerate(messages)
               if not isinstance(m, Exception) and len(m) >= SAMPLED_MESSAGE_LEN]
        if not big:
            return self._cpu.hash_gathered(messages)
        main, probe = self.router.route()
        big_set = set(big)
        rest = [i for i in range(len(messages)) if i not in big_set]
        out: list[str | Exception] = [None] * len(messages)  # type: ignore[list-item]
        if probe is not None and probe != main and len(big) > 1:
            # capped sub-slice on the losing engine keeps its EWMA live
            # (and is the degraded path's bounded device re-probe) — under
            # the TIGHT probe deadline, so a collapsed/wedged device costs
            # seconds, not a generous wedge-detection window
            cut = min(self.router.PROBE_SLICE, len(big) // 2)
            if cut > 0:
                self._dispatch_gathered(probe, big[:cut], messages, out,
                                        probe=True)
                big = big[cut:]
        if big:
            self._dispatch_gathered(main, big, messages, out)
        if rest:
            res = self._cpu.hash_gathered([messages[i] for i in rest])
            for i, r in zip(rest, res):
                out[i] = r
        return out

    def _probe_rates(self, paths, sizes, sampled: list[int],
                     out: list) -> list[int] | None:
        """Measure both engines on leading slices of the real workload;
        returns the still-unhashed indices — or None when the batch is too
        small to measure anything (rates stay unset so a real batch
        re-probes; the process-wide hasher must not pin itself to
        placeholder rates off a tiny first batch)."""
        import time as _time

        k = min(self.PROBE, len(sampled) // 2)
        if k < 8:
            return None
        cpu_part, dev_part, rest = sampled[:k], sampled[k:2 * k], sampled[2 * k:]
        t0 = _time.perf_counter()
        self._cpu_into(paths, sizes, cpu_part, out)
        cpu_rate = k / max(1e-9, _time.perf_counter() - t0)
        t0 = _time.perf_counter()
        # the device probe gets a hard deadline: a wedged device service
        # (dead tunnel) HANGS rather than raising, and a probe that never
        # returns would stall every scan — _bounded_call runs it on a
        # bounded daemon worker (the one wedge-handling policy)
        status, err = _bounded_call(
            lambda: self._tpu._hash_sampled(paths, sizes, dev_part, out),
            max(60.0, k * 0.5), "hybrid-device-probe")
        if status == "ok":
            device_rate = k / max(1e-9, _time.perf_counter() - t0)
        else:
            # timeout, or a dying device — either way it must not leave
            # half-set rates (permanently broken comparisons): score it
            # dead and finish on CPU (same values, benign overwrite)
            logger.warning(
                "hybrid probe: device engine %s; routing everything to "
                "native CPU",
                "unresponsive after deadline" if status == "timeout"
                else f"failed ({err!r})")
            self._cpu_into(paths, sizes, dev_part, out)
            device_rate = 0.0
        # set both rates atomically only once both probes concluded, and
        # seed the per-batch router's EWMAs (probe files/s × the sampled
        # message size = transfer-inclusive bytes/s on the probe slices)
        self._cpu_rate, self._device_rate = cpu_rate, device_rate
        self.router.seed(cpu_rate * SAMPLED_MESSAGE_LEN,
                         device_rate * SAMPLED_MESSAGE_LEN)
        logger.info("hybrid probe: cpu %.0f files/s, device %.0f files/s — %s",
                    self._cpu_rate, self._device_rate,
                    "engaging device" if self._device_rate > self._cpu_rate
                    else "routing to native CPU")
        return rest

    @_count_hash_batch
    def hash_batch(self, paths: list[str | Path], sizes: list[int]) -> list[str | Exception]:
        import queue as _q
        import threading

        from .cas import MINIMUM_FILE_SIZE

        n = len(paths)
        out: list[str | Exception] = [None] * n  # type: ignore[list-item]
        sampled = [i for i, s in enumerate(sizes) if s > MINIMUM_FILE_SIZE]
        small = [i for i, s in enumerate(sizes) if s <= MINIMUM_FILE_SIZE]
        if small:  # small files: native CPU batch (IO-bound, not worth device)
            self._cpu_into(paths, sizes, small, out)

        if not sampled:
            return out
        if self._cpu._fast is None:  # no native lib: nothing to race
            self._tpu._hash_sampled(paths, sizes, sampled, out)
            return out

        if self._cpu_rate is None:
            rest = self._probe_rates(paths, sizes, sampled, out)
            if rest is None:  # too small to probe — CPU for THIS batch only
                self._cpu_into(paths, sizes, sampled, out)
                return out
            sampled = rest
            if not sampled:
                return out

        if self._device_rate <= self._cpu_rate:
            self._cpu_into(paths, sizes, sampled, out)
            return out

        work: _q.Queue[list[int]] = _q.Queue()
        for start in range(0, len(sampled), self.CHUNK):
            work.put(sampled[start : start + self.CHUNK])

        # this branch only runs when the device won the probe, so the CPU is
        # the slower engine here — the tail guard (slower engine never takes
        # one of the last chunks, or its chunk latency becomes the makespan)
        # belongs on the CPU worker
        def cpu_worker():
            while True:
                if work.qsize() < 2:
                    return
                try:
                    idxs = work.get_nowait()
                except _q.Empty:
                    return
                self._cpu_into(paths, sizes, idxs, out)

        def tpu_worker():
            while True:
                try:
                    idxs = work.get_nowait()
                except _q.Empty:
                    return
                try:
                    self._tpu._hash_sampled(paths, sizes, idxs, out)
                except Exception:
                    # device died mid-batch: return the chunk to the queue
                    # and stop stealing — the drain below finishes natively
                    logger.exception("hybrid device worker failed mid-batch")
                    work.put(idxs)
                    return

        threads = [threading.Thread(target=cpu_worker, daemon=True),
                   threading.Thread(target=tpu_worker, daemon=True)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # drain of last resort: anything still queued (device died, CPU
        # stopped at the tail guard) is hashed natively so every index gets
        # a result — the list[str | Exception] contract allows no Nones
        while True:
            try:
                idxs = work.get_nowait()
            except _q.Empty:
                break
            self._cpu_into(paths, sizes, idxs, out)
        return out


class ShardedHasher(TpuHasher):
    """Multi-device variant: batch axis sharded over a data-parallel mesh
    (parallel/mesh.py) for both the row pipeline and the small-file buckets;
    lane counts pad to a multiple of the mesh's data-axis size."""

    name = "tpu-sharded"

    def __init__(self) -> None:
        from ..parallel.mesh import make_mesh

        self._mesh = make_mesh()

    def _pad_lanes(self, n: int) -> int:
        from ..parallel.mesh import pad_batch_for_mesh

        return pad_batch_for_mesh(n, self._mesh)

    def _stage_rows(self, rows32, lengths):
        # host-side staging: the sharded row hasher shards the batch axis
        # itself; a premature single-device put would just be resharded
        return rows32, lengths

    def _device_hash_rows(self, rows32, lengths):
        import jax.numpy as jnp

        from ..parallel.mesh import sharded_row_hasher

        return sharded_row_hasher(self._mesh)(jnp.asarray(rows32),
                                              jnp.asarray(lengths))

    def _hash_bucket(self, msgs: list[bytes], cap: int) -> list[str]:
        import jax.numpy as jnp
        import numpy as np

        from ..ops.blake3_jax import _pad_to_tier, digests_to_hex, pack_messages
        from ..parallel.mesh import pad_batch_for_mesh, sharded_hasher

        B = len(msgs)
        target = pad_batch_for_mesh(_pad_to_tier(B), self._mesh)
        words, lengths = pack_messages(msgs + [b""] * (target - B), cap)
        fn = sharded_hasher(self._mesh)
        out = digests_to_hex(np.asarray(fn(jnp.asarray(words), jnp.asarray(lengths))))
        return out[:B]


_BACKENDS: dict[str, Callable[[], HasherBackend]] = {
    "cpu": CpuHasher,
    "tpu": TpuHasher,
    "tpu-sharded": ShardedHasher,
    "hybrid": HybridHasher,
}

_instances: dict[str, HasherBackend] = {}


def reset_device_verdicts() -> None:
    """Re-arm the hybrid engine probes after a device recovery (called by
    the relay recapture watcher): a hasher degraded to native CPU by a
    mid-batch wedge re-measures both engines on its next batch instead of
    staying pinned to the loser forever. Snapshot the registry first: this
    runs on the recapture watcher thread while job threads may be inserting
    backends via get_hasher."""
    for backend in list(_instances.values()):
        if isinstance(backend, HybridHasher):
            backend.reset_verdict()
            logger.info("hybrid hasher verdict reset — will re-probe "
                        "engines on the next batch")


def get_hasher(name: str | None, node=None) -> HasherBackend:
    """Resolve a backend by location config; unknown/absent → tpu if JAX sees
    an accelerator, else the native cpu path. ``remote`` binds to the node's
    p2p mesh and is never cached (it must not outlive the node)."""
    if name == "remote":
        if node is not None:
            return RemoteHasher(node)
        logger.warning("remote hasher needs a node context; using local")
        name = "hybrid"
    if name not in _BACKENDS:
        if name is not None:
            logger.warning("unknown hasher backend %r, falling back to default", name)
        name = "tpu" if _accelerator_available() else "cpu"
    if getattr(_BACKENDS[name], "USES_DEVICE", False):
        # device-touching backends (incl. ones added via register_backend)
        # must not bypass the wedge guard: their first jnp op would
        # otherwise init the (possibly dead) tunnel in-process and park
        # the job worker forever
        from ..utils.jax_guard import ensure_jax_safe

        ensure_jax_safe()
    if name not in _instances:
        _instances[name] = _BACKENDS[name]()
    return _instances[name]


def _native_hex_batch():
    """The C++ ``blake3_hex_batch`` entry point, or None (probe memoized —
    a failed import involves a g++ attempt and must not re-run per batch)."""
    if not _NATIVE_HEX:
        try:
            from ..native import cas_native

            _NATIVE_HEX.append(cas_native.blake3_hex_batch)
        except Exception:
            _NATIVE_HEX.append(None)
    return _NATIVE_HEX[0]


_NATIVE_HEX: list = []


def _hash_gathered_messages(messages: list[bytes | Exception],
                            hex_batch) -> list[str | Exception]:
    """Shared gathered-message driver: Exception entries pass through in
    place, ok messages go through ``hex_batch(list[bytes]) -> list[hex]``
    (or the python oracle when it is None); cas_ids are the 16-hex prefix."""
    out: list[str | Exception] = [None] * len(messages)  # type: ignore[list-item]
    ok = [j for j, m in enumerate(messages) if not isinstance(m, Exception)]
    for j, m in enumerate(messages):
        if isinstance(m, Exception):
            out[j] = m
    if not ok:
        return out
    if hex_batch is not None:
        hexes = hex_batch([messages[j] for j in ok])
    else:
        from .blake3_ref import blake3

        hexes = [blake3(messages[j]).hex() for j in ok]
    for j, h in zip(ok, hexes):
        out[j] = h[:16]
    return out


def _bucketed_hash(messages: list[bytes], hash_bucket) -> list[str]:
    """Bucket variable-size cas messages by chunk count and hash each
    bucket through ``hash_bucket(msgs, cap)``; returns 16-hex cas_ids in
    input order. The one bucketing scheme shared by the local small-file
    path and the H_HASH service."""
    out: list[str | None] = [None] * len(messages)
    buckets: dict[int, list[int]] = {}
    for j, msg in enumerate(messages):
        chunks = max(1, (len(msg) + 1023) // 1024)
        cap = next((b for b in SMALL_BUCKETS if b >= chunks), chunks)
        buckets.setdefault(cap, []).append(j)
    for cap, js in sorted(buckets.items()):
        hexes = hash_bucket([messages[j] for j in js], cap)
        for j, h in zip(js, hexes):
            out[j] = h[:16]
    return out  # type: ignore[return-value]


def hash_messages(messages: list[bytes]) -> list[str]:
    """cas_ids for pre-gathered cas messages — the compute side of the
    shared-hasher service (H_HASH): device-bucketed when an accelerator is
    present, else native C++ BLAKE3, else the Python oracle."""
    if _accelerator_available():
        from ..ops.blake3_jax import blake3_batch_hex

        return _bucketed_hash(
            messages, lambda msgs, cap: blake3_batch_hex(msgs, max_chunks=cap))
    try:
        from ..native import cas_native

        return [h[:16] for h in cas_native.blake3_hex_batch(messages)]
    except Exception:
        from .blake3_ref import blake3

        return [blake3(m).hex()[:16] for m in messages]


class RemoteHasher:
    """Route hashing to a paired node that advertises an accelerator — the
    shared TPU hasher service of BASELINE config 5. Files are sampled
    LOCALLY (read_sampled_batch: the 56 KiB budget per file, cas.rs
    layout); only the cas messages travel, so the peer sees samples, never
    whole files, and only if it shares a library with us (the server
    enforces membership). Any remote failure falls back to the local
    hybrid engine for the remainder of the batch."""

    name = "remote"

    #: per-wire-request caps — bound peer memory, stay WELL under the mux's
    #: 64 MiB per-substream buffer, and keep a lost connection from wasting
    #: more than one sub-batch of work
    WIRE_BATCH = 1024
    WIRE_BATCH_BYTES = 32 * 1024 * 1024

    def __init__(self, node) -> None:
        self._node = node

    def _pick_peer(self) -> str | None:
        """A connected peer that (a) advertises an accelerator and (b)
        shares a library with us — the server refuses non-members, so
        offering it a batch would waste the whole upload."""
        p2p = getattr(self._node, "p2p", None)
        if p2p is None:
            return None
        members: set[str] = set()
        for library in self._node.libraries.list():
            members |= p2p.nlm.member_nodes(library)
        for peer in p2p.peer_list():
            accel = peer.get("accelerator") or {}
            if (peer.get("connected") and accel.get("devices")
                    and peer["identity"] in members):
                return peer["identity"]
        return None

    def _wire_batches(self, todo: list[int], messages) -> list[list[int]]:
        """Split by count AND cumulative bytes."""
        batches: list[list[int]] = []
        cur: list[int] = []
        cur_bytes = 0
        for i in todo:
            n = len(messages[i])
            if cur and (len(cur) >= self.WIRE_BATCH
                        or cur_bytes + n > self.WIRE_BATCH_BYTES):
                batches.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += n
        if cur:
            batches.append(cur)
        return batches

    @_count_hash_batch
    def hash_batch(self, paths: list[str | Path],
                   sizes: list[int]) -> list[str | Exception]:
        return self.hash_gathered(read_sampled_batch(paths, sizes))

    @_count_hash_gathered
    def hash_gathered(self,
                      messages: list[bytes | Exception]) -> list[str | Exception]:
        """The natural fit for the pipelined gather: this backend always
        worked on cas messages (only samples travel, never whole files)."""
        out: list[str | Exception | None] = [None] * len(messages)
        todo: list[int] = []
        for i, msg in enumerate(messages):
            if isinstance(msg, Exception):
                out[i] = msg
            else:
                todo.append(i)

        peer_id = self._pick_peer()
        failed: list[int] = []
        if peer_id is None:
            failed = todo
        else:
            from ..telemetry import mesh

            p2p = self._node.p2p
            # trace propagation: captured HERE (the pipeline hash thread,
            # which holds the job trace's open span) — the p2p loop the
            # coroutine runs on has no span context of its own
            ctx = mesh.outbound_context(
                origin=str(self._node.config.get().get("id") or ""))
            batches = self._wire_batches(todo, messages)
            for bi, idxs in enumerate(batches):
                try:
                    ids = p2p.run_coro(p2p.request_hash_batch(
                        peer_id, [messages[i] for i in idxs], ctx=ctx),
                        timeout=120)
                    for i, cid in zip(idxs, ids):
                        out[i] = cid
                except Exception as e:
                    logger.warning("remote hash batch via %s failed (%s); "
                                   "hashing locally", peer_id[:8], e)
                    for rest in batches[bi:]:
                        failed.extend(rest)
                    break

        if failed:
            local = get_hasher("hybrid")
            results = local.hash_gathered([messages[i] for i in failed])
            for i, r in zip(failed, results):
                out[i] = r
        return out  # type: ignore[return-value]


def register_backend(name: str, factory: Callable[[], HasherBackend]) -> None:
    _BACKENDS[name] = factory


def _accelerator_available() -> bool:
    """True only for a real accelerator — jax.devices() is never empty (it
    falls back to CPU), so count checks are vacuous; inspect the platform."""
    try:
        from ..utils.jax_guard import ensure_jax_safe

        if not ensure_jax_safe():
            return False  # process pinned to CPU: no accelerator
        import jax

        return any(d.platform not in ("cpu",) for d in jax.devices())
    except Exception:
        return False


def _load_native_hasher():
    """ctypes binding to the C++ blake3 helper (native/); None until built."""
    try:
        from ..native import cas_native

        return cas_native.hash_batch
    except Exception:
        return None
