"""Hasher backends: the seam that makes identity hashing pluggable.

The reference hard-codes scalar CPU BLAKE3 inside FileMetadata::new
(file_identifier/mod.rs:80-88). Here the cas_id computation is a backend
behind the per-location ``hasher`` config ("cpu" | "tpu", BASELINE.json's
`hasher = "tpu"` flag) so the identifier job, dedup and sync stay
hasher-agnostic.

The TPU backend batches sampled messages into shape buckets:
- the fixed 57,352-byte sampled bucket (every file > 100KiB) — the hot path,
  one compiled kernel shape;
- a handful of small-file chunk-capacity buckets (1/4/16/32/64/101 chunks) to
  bound zero-padding waste while keeping the compiled-shape count constant.

Per-file IO errors come back as Exception entries; callers route them into
job errors instead of aborting the batch.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Callable, Protocol

from .cas import SAMPLED_MESSAGE_LEN, generate_cas_id, read_sampled_batch

logger = logging.getLogger(__name__)

#: chunk capacities for small-file buckets (1 chunk = 1024 B); 101 covers the
#: largest whole-file message (100KiB + 8B size prefix)
SMALL_BUCKETS = (1, 4, 16, 32, 64, 101)
SAMPLED_CHUNKS = (SAMPLED_MESSAGE_LEN + 1023) // 1024  # 57


class HasherBackend(Protocol):
    name: str

    def hash_batch(self, paths: list[str | Path],
                   sizes: list[int]) -> list[str | Exception]: ...


class CpuHasher:
    """Scalar reference path; byte-exact oracle (objects/cas.py). The native
    C++ helper slots in here when present (native/)."""

    name = "cpu"

    def __init__(self) -> None:
        self._fast = _load_native_hasher()

    def hash_batch(self, paths: list[str | Path], sizes: list[int]) -> list[str | Exception]:
        if self._fast is not None:
            return self._fast(paths, sizes)
        out: list[str | Exception] = []
        for path, size in zip(paths, sizes):
            try:
                out.append(generate_cas_id(path, size))
            except (OSError, EOFError) as e:
                out.append(e)
        return out


class TpuHasher:
    """Batched JAX/TPU path: gather samples → bucket by shape → device hash."""

    name = "tpu"

    def hash_batch(self, paths: list[str | Path], sizes: list[int]) -> list[str | Exception]:
        import numpy as np

        from ..ops.blake3_jax import blake3_batch_hex

        messages = read_sampled_batch(paths, sizes)
        out: list[str | Exception] = [None] * len(messages)  # type: ignore[list-item]

        buckets: dict[int, list[int]] = {}
        for i, msg in enumerate(messages):
            if isinstance(msg, Exception):
                out[i] = msg
                continue
            n = len(msg)
            if n == SAMPLED_MESSAGE_LEN:
                cap = SAMPLED_CHUNKS
            else:
                chunks = max(1, (n + 1023) // 1024)
                cap = next(b for b in SMALL_BUCKETS if b >= chunks)
            buckets.setdefault(cap, []).append(i)

        for cap, indices in sorted(buckets.items()):
            hexes = self._hash_bucket([messages[i] for i in indices], cap)
            for i, h in zip(indices, hexes):
                out[i] = h[:16]
        return out

    def _hash_bucket(self, msgs: list[bytes], cap: int) -> list[str]:
        from ..ops.blake3_jax import blake3_batch_hex

        return blake3_batch_hex(msgs, max_chunks=cap)


class ShardedHasher(TpuHasher):
    """Multi-device variant: batch axis sharded over a data-parallel mesh
    (parallel/mesh.py). Same bucketing; each bucket's lane count additionally
    pads to a multiple of the mesh's data-axis size."""

    name = "tpu-sharded"

    def __init__(self) -> None:
        from ..parallel.mesh import make_mesh

        self._mesh = make_mesh()

    def _hash_bucket(self, msgs: list[bytes], cap: int) -> list[str]:
        import jax.numpy as jnp
        import numpy as np

        from ..ops.blake3_jax import _pad_to_tier, digests_to_hex, pack_messages
        from ..parallel.mesh import pad_batch_for_mesh, sharded_hasher

        B = len(msgs)
        target = pad_batch_for_mesh(_pad_to_tier(B), self._mesh)
        words, lengths = pack_messages(msgs + [b""] * (target - B), cap)
        fn = sharded_hasher(self._mesh)
        out = digests_to_hex(np.asarray(fn(jnp.asarray(words), jnp.asarray(lengths))))
        return out[:B]


_BACKENDS: dict[str, Callable[[], HasherBackend]] = {
    "cpu": CpuHasher,
    "tpu": TpuHasher,
    "tpu-sharded": ShardedHasher,
}

_instances: dict[str, HasherBackend] = {}


def get_hasher(name: str | None) -> HasherBackend:
    """Resolve a backend by location config; unknown/absent → tpu if JAX sees
    an accelerator, else the native cpu path."""
    if name not in _BACKENDS:
        if name is not None:
            logger.warning("unknown hasher backend %r, falling back to default", name)
        name = "tpu" if _accelerator_available() else "cpu"
    if name not in _instances:
        _instances[name] = _BACKENDS[name]()
    return _instances[name]


def register_backend(name: str, factory: Callable[[], HasherBackend]) -> None:
    _BACKENDS[name] = factory


def _accelerator_available() -> bool:
    """True only for a real accelerator — jax.devices() is never empty (it
    falls back to CPU), so count checks are vacuous; inspect the platform."""
    try:
        import jax

        return any(d.platform not in ("cpu",) for d in jax.devices())
    except Exception:
        return False


def _load_native_hasher():
    """ctypes binding to the C++ blake3 helper (native/); None until built."""
    try:
        from ..native import cas_native

        return cas_native.hash_batch
    except Exception:
        return None
