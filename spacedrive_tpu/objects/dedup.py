"""Near-duplicate detection over indexed files (the MinHash op as a feature).

The reference collapses only EXACT duplicates (same cas_id → one Object).
This module finds *near* duplicates — edited photos, re-encoded media,
truncated copies — by running the TPU MinHash pipeline (ops/minhash.py) over
a location's sampled content: native gather reads each file's cas sample
rows (the same bytes the identifier hashed), the device computes signatures,
and the all-pairs sweep returns similarity groups.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

import numpy as np

from ..jobs import EarlyFinish, StatefulJob, StepResult, WorkerContext
from ..models import FilePath
from .cas import MINIMUM_FILE_SIZE, SAMPLED_MESSAGE_LEN

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

SAMPLED_STRIDE = ((SAMPLED_MESSAGE_LEN + 1023) // 1024) * 1024  # 58368


def find_near_duplicates(library: "Library", location_id: int | None = None,
                         threshold: float = 0.8,
                         limit: int = 8192) -> dict[str, Any]:
    """Similarity groups among sampled-size files. Returns
    {groups: [[file_path rows...]], scanned, errors}."""
    import jax

    from ..ops.minhash import (K, minhash_rows, pad_for_blocks,
                               similar_pairs_count)

    db = library.db
    where = "is_dir = 0 AND size_in_bytes > ?"
    params: list[Any] = [MINIMUM_FILE_SIZE]
    if location_id is not None:
        where += " AND location_id = ?"
        params.append(location_id)
    rows_db = [FilePath.decode_row(r) for r in db.query(
        f"SELECT * FROM file_path WHERE {where} ORDER BY id LIMIT ?",
        params + [limit])]
    if len(rows_db) < 2:
        return {"groups": [], "pairs": [], "scanned": len(rows_db), "errors": []}

    from .fs import location_path_of

    paths, sizes, errors = [], [], []
    roots: dict[int, Any] = {}
    for r in rows_db:
        loc = r["location_id"]
        if loc not in roots:
            roots[loc] = location_path_of(db, loc)
        rel = (r["materialized_path"] or "/").lstrip("/")
        name = r["name"] + (f".{r['extension']}" if r["extension"] else "")
        paths.append(str(roots[loc] / rel / name))
        sizes.append(r["size_in_bytes"])

    # gather sampled rows (native if available, python fallback)
    n = len(paths)
    buf = np.zeros((n, SAMPLED_STRIDE), np.uint8)
    lengths = np.zeros(n, np.int32)
    try:
        from ..native import cas_native

        cas_native.gather_batch(paths, sizes, buf, lengths)
    except Exception:
        from .cas import read_sampled_batch

        msgs = read_sampled_batch(paths, sizes)
        for i, m in enumerate(msgs):
            if isinstance(m, Exception):
                errors.append(f"{paths[i]}: {m}")
                continue
            buf[i, : len(m)] = np.frombuffer(m, np.uint8)
            lengths[i] = len(m)
    errors += [paths[i] for i in range(n) if lengths[i] == 0]

    sigs = np.asarray(minhash_rows(
        jax.device_put(buf.view(np.uint32).reshape(n, SAMPLED_STRIDE // 4)),
        jax.device_put(lengths)))
    sigs_p, valid = pad_for_blocks(sigs)
    valid[:n] &= lengths > 0

    thr_k = max(1, int(threshold * K))
    _total, dup = similar_pairs_count(jax.device_put(sigs_p),
                                      jax.device_put(valid), thr_k)
    dup = np.asarray(dup)[:n]

    # group on host: union by best-match (pairwise check only against flagged
    # rows keeps this O(n_dup * n))
    groups: dict[int, list[int]] = {}
    assigned: dict[int, int] = {}
    pairs: list[dict[str, Any]] = []
    flagged = [i for i in range(n) if dup[i]]
    for i in flagged:
        eq = (sigs[i][None, :] == sigs[:i]).sum(axis=1)
        j = int(np.argmax(eq))
        if eq[j] >= thr_k:
            root = assigned.get(j, j)
            groups.setdefault(root, [root] if root not in assigned else []).append(i)
            assigned[i] = root
            pairs.append({"a": rows_db[j], "b": rows_db[i],
                          "similarity": float(eq[j]) / K})
    out_groups = []
    for root, members in groups.items():
        ids = sorted({root, *members})
        out_groups.append([rows_db[i] for i in ids])
    return {"groups": out_groups, "pairs": pairs, "scanned": n,
            "errors": errors}


class DedupDetectorJob(StatefulJob):
    """Chained detector persisting near-dup pairs into `near_duplicate`
    (this framework's 4th pipeline stage after indexer → identifier →
    media; the reference has no analogue — it only collapses exact
    cas_id matches). One step = one device MinHash batch over up to
    DEVICE_LIMIT sampled-size files; bigger locations are truncated
    loudly (no silent caps) until windowed all-pairs lands."""

    NAME = "dedup_detector"
    IS_BATCHED = True

    #: rows per detection pass (one device all-pairs batch)
    DEVICE_LIMIT = 8192

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        count = db.query(
            "SELECT COUNT(*) n FROM file_path WHERE is_dir = 0 "
            "AND location_id = ? AND size_in_bytes > ?",
            [location_id, MINIMUM_FILE_SIZE])[0]["n"]
        if count < 2:
            raise EarlyFinish("not enough sampled-size files for dedup")
        if count > self.DEVICE_LIMIT:
            logger.warning(
                "dedup_detector: location %s has %d eligible files; only the "
                "first %d are compared this pass", location_id, count,
                self.DEVICE_LIMIT)
        data = {"location_id": location_id,
                "threshold": float(self.init_args.get("threshold", 0.8))}
        return data, [{"kind": "detect"}], {"pairs_found": 0, "scanned": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number):
        from ..models import NearDuplicate, utc_now

        db = ctx.library.db
        result = find_near_duplicates(
            ctx.library, data["location_id"], threshold=data["threshold"],
            limit=self.DEVICE_LIMIT)
        rows = []
        for pair in result["pairs"]:
            a, b = pair["a"]["id"], pair["b"]["id"]
            rows.append({"file_path_a_id": min(a, b),
                         "file_path_b_id": max(a, b),
                         "similarity": pair["similarity"],
                         "date_detected": utc_now()})
        with db.transaction():
            # rescan refreshes the location's pair set
            db.query(
                "DELETE FROM near_duplicate WHERE file_path_a_id IN "
                "(SELECT id FROM file_path WHERE location_id = ?)",
                [data["location_id"]])
            if rows:
                db.insert_many(NearDuplicate, rows, or_ignore=True)
        ctx.progress(message=f"{len(rows)} near-duplicate pairs")
        return StepResult(metadata={"pairs_found": len(rows),
                                    "scanned": result["scanned"]},
                          errors=[str(e) for e in result["errors"]])

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        ctx.library.emit("invalidate_query", {"key": "search.duplicates"})
        return run_metadata
