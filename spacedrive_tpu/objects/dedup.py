"""Near-duplicate detection over indexed files (the MinHash op as a feature).

The reference collapses only EXACT duplicates (same cas_id → one Object).
This module finds *near* duplicates — edited photos, re-encoded media,
truncated copies — by running the TPU MinHash pipeline (ops/minhash.py) over
a location's sampled content: native gather reads each file's cas sample
rows (the same bytes the identifier hashed), the device computes signatures,
and the all-pairs sweep returns similarity groups.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

import numpy as np

from ..jobs import EarlyFinish, StatefulJob, StepResult, WorkerContext
from ..models import FilePath
from .cas import MINIMUM_FILE_SIZE, SAMPLED_MESSAGE_LEN

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

SAMPLED_STRIDE = ((SAMPLED_MESSAGE_LEN + 1023) // 1024) * 1024  # 58368


#: above this row count the all-pairs device sweep gives way to LSH
#: banding (candidate buckets + exact verification) — O(N·BANDS) instead
#: of O(N²K)
ALL_PAIRS_LIMIT = 8192

#: signature batch per device pass (gather + minhash)
SIG_BATCH = 8192


def _paths_of(db, rows_db) -> tuple[list[str], list[int]]:
    from .fs import location_path_of

    paths, sizes = [], []
    roots: dict[int, Any] = {}
    for r in rows_db:
        loc = r["location_id"]
        if loc not in roots:
            roots[loc] = location_path_of(db, loc)
        rel = (r["materialized_path"] or "/").lstrip("/")
        name = r["name"] + (f".{r['extension']}" if r["extension"] else "")
        paths.append(str(roots[loc] / rel / name))
        sizes.append(r["size_in_bytes"])
    return paths, sizes


def _signatures(paths: list[str], sizes: list[int],
                errors: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """(n, K) uint32 MinHash signatures + lengths, computed in SIG_BATCH
    device passes so corpus size never explodes host/device memory."""
    import jax

    from ..ops.minhash import K, minhash_rows

    n = len(paths)
    sigs = np.zeros((n, K), np.uint32)
    lengths = np.zeros(n, np.int32)
    for start in range(0, n, SIG_BATCH):
        stop = min(n, start + SIG_BATCH)
        cnt = stop - start
        buf = np.zeros((cnt, SAMPLED_STRIDE), np.uint8)
        lens = np.zeros(cnt, np.int32)
        try:
            from ..native import cas_native

            cas_native.gather_batch(paths[start:stop], sizes[start:stop],
                                    buf, lens)
        except Exception:
            from .cas import read_sampled_batch

            msgs = read_sampled_batch(paths[start:stop], sizes[start:stop])
            for i, m in enumerate(msgs):
                if isinstance(m, Exception):
                    errors.append(f"{paths[start + i]}: {m}")
                    continue
                buf[i, : len(m)] = np.frombuffer(m, np.uint8)
                lens[i] = len(m)
        sigs[start:stop] = np.asarray(minhash_rows(
            jax.device_put(buf.view(np.uint32).reshape(cnt, SAMPLED_STRIDE // 4)),
            jax.device_put(lens)))
        lengths[start:stop] = lens
    errors += [paths[i] for i in range(n) if lengths[i] == 0]
    return sigs, lengths


def find_near_duplicates(library: "Library", location_id: int | None = None,
                         threshold: float = 0.8, limit: int = ALL_PAIRS_LIMIT,
                         method: str = "auto") -> dict[str, Any]:
    """Similarity groups among sampled-size files. Returns
    {groups: [[file_path rows...]], pairs, scanned, method, errors}.

    ``method``: ``all_pairs`` (device O(N²K) sweep), ``banded`` (LSH
    candidate buckets + exact verify, corpus-scale), or ``auto`` (all-pairs
    up to ALL_PAIRS_LIMIT rows, banded beyond)."""
    from ..utils.jax_guard import ensure_jax_safe

    ensure_jax_safe()  # a wedged device tunnel must degrade to CPU, not
    # park the single job worker (and every queued scan) forever
    from ..ops.minhash import K

    db = library.db
    where = "is_dir = 0 AND size_in_bytes > ?"
    params: list[Any] = [MINIMUM_FILE_SIZE]
    if location_id is not None:
        where += " AND location_id = ?"
        params.append(location_id)
    rows_db = [FilePath.decode_row(r) for r in db.query(
        f"SELECT * FROM file_path WHERE {where} ORDER BY id LIMIT ?",
        params + [limit])]
    n = len(rows_db)
    if n < 2:
        return {"groups": [], "pairs": [], "scanned": n, "errors": [],
                "method": "none"}
    if method == "auto":
        method = "all_pairs" if n <= ALL_PAIRS_LIMIT else "banded"

    errors: list[str] = []
    paths, sizes = _paths_of(db, rows_db)
    sigs, lengths = _signatures(paths, sizes, errors)
    thr_k = max(1, int(threshold * K))

    if method == "banded":
        if threshold < 0.7:
            # BANDS/BAND_ROWS are tuned for the 0.8 default; candidate
            # recall degrades at low thresholds (≈0.64 at s=0.5) — say so
            # instead of silently under-reporting vs the all-pairs path
            errors.append(
                f"banded LSH recall degrades below threshold 0.7 "
                f"(requested {threshold}); pairs near the threshold may "
                "be missed — force method='all_pairs' for exhaustive "
                "comparison")
        raw_pairs = _banded_pairs(sigs, lengths > 0, thr_k, errors)
    else:
        raw_pairs = _all_pairs(sigs, lengths > 0, thr_k)

    # union-find grouping from verified pairs
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for i, j, _m in raw_pairs:
        parent[find(j)] = find(i)

    # collapse cliques to spanning pairs: each row keeps only its best
    # match, so a 200-file family emits ≤199 rows, not 19,900 (the banded
    # verifier returns full cliques)
    best: dict[int, tuple[int, int]] = {}
    for i, j, m in raw_pairs:
        for x, y in ((i, j), (j, i)):
            if m > best.get(x, (0, -1))[0]:
                best[x] = (m, y)
    edges: dict[tuple[int, int], int] = {}
    for x, (m, y) in best.items():
        key = (x, y) if x < y else (y, x)
        if m > edges.get(key, 0):
            edges[key] = m
    pairs = [{"a": rows_db[i], "b": rows_db[j], "similarity": float(m) / K}
             for (i, j), m in sorted(edges.items())]

    members: dict[int, list[int]] = {}
    linked = {i for i, _j, _m in raw_pairs} | {j for _i, j, _m in raw_pairs}
    for i in linked:
        members.setdefault(find(i), []).append(i)
    out_groups = [[rows_db[i] for i in sorted(ids)]
                  for ids in members.values() if len(ids) > 1]
    return {"groups": out_groups, "pairs": pairs, "scanned": n,
            "errors": errors, "method": method}


def _all_pairs(sigs: np.ndarray, valid_rows: np.ndarray,
               thr_k: int) -> list[tuple[int, int, int]]:
    """Device all-pairs sweep → verified (i, j, matches) pairs."""
    import jax

    from ..ops.minhash import pad_for_blocks, similar_pairs_count

    n = sigs.shape[0]
    sigs_p, valid = pad_for_blocks(sigs)
    valid[:n] &= valid_rows
    _total, dup = similar_pairs_count(jax.device_put(sigs_p),
                                      jax.device_put(valid), thr_k)
    dup = np.asarray(dup)[:n]
    out: list[tuple[int, int, int]] = []
    for i in range(n):
        if not dup[i]:
            continue
        eq = (sigs[i][None, :] == sigs[:i]).sum(axis=1)
        eq[~valid_rows[:i]] = 0
        j = int(np.argmax(eq))
        if eq[j] >= thr_k:
            out.append((j, i, int(eq[j])))
    return out


def _banded_pairs(sigs: np.ndarray, valid_rows: np.ndarray, thr_k: int,
                  errors: list[str]) -> list[tuple[int, int, int]]:
    """LSH banding: bucket by band keys, exact-verify candidates."""
    from ..ops.minhash import (band_keys, banded_candidate_pairs,
                               verify_pairs)

    keys = band_keys(sigs)
    cand, oversized = banded_candidate_pairs(keys, valid_rows)
    if oversized:
        errors.append(
            f"{oversized} oversized LSH buckets collapsed to "
            "representative pairing (members compared against one "
            "representative instead of all-pairs)")
    return verify_pairs(sigs, cand, thr_k)


def persisted_near_duplicate_groups(db, location_id: int | None = None,
                                    limit: int = 1000) -> dict[str, Any]:
    """Similarity groups from the PERSISTED ``near_duplicate`` pairs the
    chained :class:`DedupDetectorJob` wrote — pure ``library.db`` reads
    (no filesystem, no device), so the ``search.nearDuplicates`` handler
    serving it is pool- and replica-eligible (ISSUE 19 serve rung).

    Same result shape as :func:`find_near_duplicates` minus the live
    probe fields: ``{groups: [[file_path rows]], pairs, scanned, method:
    "persisted", errors: []}`` with ``scanned`` counting the pair rows
    considered. Ordering is fully deterministic (similarity DESC then
    pair id; members by id; groups by smallest member id) — replica
    byte-identity asserts on it."""
    where, params = "1=1", []
    if location_id is not None:
        where = "(fa.location_id = ? OR fb.location_id = ?)"
        params = [location_id, location_id]
    limit = max(0, min(int(limit), 5000))
    pair_rows = db.query(
        f"SELECT nd.id, nd.file_path_a_id AS a, nd.file_path_b_id AS b, "
        f"nd.similarity FROM near_duplicate nd "
        f"JOIN file_path fa ON nd.file_path_a_id = fa.id "
        f"JOIN file_path fb ON nd.file_path_b_id = fb.id "
        f"WHERE {where} ORDER BY nd.similarity DESC, nd.id LIMIT ?",
        params + [limit])

    parent: dict[int, int] = {}

    def find(x: int) -> int:
        parent.setdefault(x, x)
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    pairs = []
    for r in pair_rows:
        parent[find(int(r["b"]))] = find(int(r["a"]))
        pairs.append({"a": int(r["a"]), "b": int(r["b"]),
                      "similarity": r["similarity"]})
    ids = sorted(parent)
    rows_by_id: dict[int, dict] = {}
    if ids:
        marks = ",".join("?" for _ in ids)
        rows_by_id = {r["id"]: FilePath.decode_row(r) for r in db.query(
            f"SELECT * FROM file_path WHERE id IN ({marks})", ids)}
    members: dict[int, list[int]] = {}
    for i in ids:
        members.setdefault(find(i), []).append(i)
    groups = [[rows_by_id[i] for i in sorted(group) if i in rows_by_id]
              for _root, group in sorted(
                  members.items(), key=lambda kv: min(kv[1]))
              if len(group) > 1]
    return {"groups": [g for g in groups if len(g) > 1], "pairs": pairs,
            "scanned": len(pair_rows), "method": "persisted", "errors": []}


class DedupDetectorJob(StatefulJob):
    """Chained detector persisting near-dup pairs into `near_duplicate`
    (this framework's 4th pipeline stage after indexer → identifier →
    media; the reference has no analogue — it only collapses exact
    cas_id matches). ≤ ALL_PAIRS_LIMIT files use the device all-pairs
    sweep; bigger locations switch to LSH banding (candidate buckets +
    exact verification) up to DEVICE_LIMIT, beyond which the window is
    truncated loudly (no silent caps)."""

    NAME = "dedup_detector"
    IS_BATCHED = True

    #: rows per detection pass (signatures stream through the device in
    #: SIG_BATCH batches; banding keeps candidate generation linear)
    DEVICE_LIMIT = 131072

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        count = db.query(
            "SELECT COUNT(*) n FROM file_path WHERE is_dir = 0 "
            "AND location_id = ? AND size_in_bytes > ?",
            [location_id, MINIMUM_FILE_SIZE])[0]["n"]
        if count < 2:
            raise EarlyFinish("not enough sampled-size files for dedup")
        if count > self.DEVICE_LIMIT:
            logger.warning(
                "dedup_detector: location %s has %d eligible files; only the "
                "first %d are compared this pass", location_id, count,
                self.DEVICE_LIMIT)
        data = {"location_id": location_id,
                "threshold": float(self.init_args.get("threshold", 0.8))}
        return data, [{"kind": "detect"}], {"pairs_found": 0, "scanned": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number):
        from ..models import NearDuplicate, utc_now

        db = ctx.library.db
        result = find_near_duplicates(
            ctx.library, data["location_id"], threshold=data["threshold"],
            limit=self.DEVICE_LIMIT)
        rows = []
        for pair in result["pairs"]:
            a, b = pair["a"]["id"], pair["b"]["id"]
            rows.append({"file_path_a_id": min(a, b),
                         "file_path_b_id": max(a, b),
                         "similarity": pair["similarity"],
                         "date_detected": utc_now()})
        with db.transaction():
            # rescan refreshes the location's pair set
            db.query(
                "DELETE FROM near_duplicate WHERE file_path_a_id IN "
                "(SELECT id FROM file_path WHERE location_id = ?)",
                [data["location_id"]])
            if rows:
                db.insert_many(NearDuplicate, rows, or_ignore=True)
        ctx.progress(message=f"{len(rows)} near-duplicate pairs")
        return StepResult(metadata={"pairs_found": len(rows),
                                    "scanned": result["scanned"]},
                          errors=[str(e) for e in result["errors"]])

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        ctx.library.emit("invalidate_query", {"key": "search.duplicates"})
        return run_metadata
