"""ObjectKind + extension registry.

The 24-kind enum matches the reference exactly (crates/file-ext/src/kind.rs:6-55
— "the order of this enum should never change"). Extension → kind resolution
mirrors sd-file-ext's extension tables; magic-byte disambiguation for
conflicting/unknown extensions and text detection live in ``magic.py``
(magic.rs / Extension::resolve_conflicting semantics) and are wired into
the identifier.
"""

from __future__ import annotations


class ObjectKind:
    UNKNOWN = 0
    DOCUMENT = 1
    FOLDER = 2
    TEXT = 3
    PACKAGE = 4
    IMAGE = 5
    AUDIO = 6
    VIDEO = 7
    ARCHIVE = 8
    EXECUTABLE = 9
    ALIAS = 10
    ENCRYPTED = 11
    KEY = 12
    LINK = 13
    WEB_PAGE_ARCHIVE = 14
    WIDGET = 15
    ALBUM = 16
    COLLECTION = 17
    FONT = 18
    MESH = 19
    CODE = 20
    DATABASE = 21
    BOOK = 22
    CONFIG = 23


_EXTENSION_KINDS: dict[int, tuple[str, ...]] = {
    ObjectKind.IMAGE: (
        "jpg", "jpeg", "png", "gif", "bmp", "webp", "tiff", "tif", "heic",
        "heif", "heics", "avif", "svg", "ico", "raw", "dng", "cr2", "nef",
        "arw", "orf", "psd", "kra", "xcf",
    ),
    ObjectKind.VIDEO: (
        "mp4", "mkv", "avi", "mov", "wmv", "flv", "webm", "m4v", "3gp",
        "mts", "m2ts", "ts", "mpg", "mpeg", "ogv", "swf", "vob",
    ),
    ObjectKind.AUDIO: (
        "mp3", "wav", "flac", "ogg", "oga", "aac", "m4a", "wma", "opus",
        "aiff", "aif", "mid", "midi", "amr", "ape",
    ),
    ObjectKind.ARCHIVE: (
        "zip", "rar", "7z", "tar", "gz", "bz2", "xz", "zst", "lz4", "br",
        "tgz", "txz", "cab", "iso", "dmg",
    ),
    ObjectKind.EXECUTABLE: (
        "exe", "msi", "apk", "deb", "rpm", "appimage", "com", "bat", "jar",
    ),
    ObjectKind.DOCUMENT: (
        "pdf", "doc", "docx", "xls", "xlsx", "ppt", "pptx", "odt", "ods",
        "odp", "rtf", "pages", "numbers", "keynote",
    ),
    ObjectKind.TEXT: (
        "txt", "md", "markdown", "log", "csv", "tsv", "rst", "tex", "srt",
        "vtt", "nfo",
    ),
    ObjectKind.CODE: (
        "py", "rs", "js", "ts", "tsx", "jsx", "c", "cpp", "cc", "h", "hpp",
        "java", "go", "rb", "php", "swift", "kt", "cs", "sh", "bash", "zsh",
        "fish", "lua", "sql", "html", "htm", "css", "scss", "sass", "less",
        "vue", "svelte", "r", "jl", "pl", "scala", "clj", "ex", "exs", "hs",
        "ml", "nim", "zig", "dart", "asm", "s", "cmake", "make", "mk",
        "dockerfile", "proto", "graphql", "ipynb",
    ),
    ObjectKind.ENCRYPTED: ("sdenc", "gpg", "pgp", "age", "aes"),
    ObjectKind.KEY: ("pem", "key", "pub", "crt", "cer", "der", "p12", "pfx",
                     "asc", "keystore"),
    ObjectKind.LINK: ("url", "webloc", "desktop", "lnk"),
    ObjectKind.WEB_PAGE_ARCHIVE: ("mhtml", "mht", "warc"),
    ObjectKind.FONT: ("ttf", "otf", "woff", "woff2", "eot"),
    ObjectKind.MESH: ("obj", "stl", "fbx", "gltf", "glb", "dae", "3ds",
                      "blend", "usdz", "ply"),
    ObjectKind.DATABASE: ("db", "sqlite", "sqlite3", "mdb", "accdb", "realm"),
    ObjectKind.BOOK: ("epub", "mobi", "azw", "azw3", "fb2", "cbz", "cbr"),
    ObjectKind.CONFIG: ("json", "yaml", "yml", "toml", "xml", "ini", "cfg",
                        "conf", "plist", "env", "lock", "properties"),
    ObjectKind.PACKAGE: ("app", "bundle", "pkg", "xpi", "crx", "vsix", "nupkg",
                         "whl", "gem"),
    ObjectKind.ALIAS: ("alias", "symlink"),
}

EXTENSION_TO_KIND: dict[str, int] = {
    ext: kind for kind, exts in _EXTENSION_KINDS.items() for ext in exts
}


def kind_from_extension(extension: str | None, is_dir: bool = False) -> int:
    if is_dir:
        return ObjectKind.FOLDER
    if not extension:
        return ObjectKind.UNKNOWN
    return EXTENSION_TO_KIND.get(extension.lower().lstrip("."), ObjectKind.UNKNOWN)


#: overview-category → ObjectKinds grouping (library/cat.rs:77 semantics)
CATEGORY_KINDS: dict[str, tuple[int, ...]] = {
    "Photos": (ObjectKind.IMAGE,),
    "Videos": (ObjectKind.VIDEO,),
    "Movies": (ObjectKind.VIDEO,),
    "Music": (ObjectKind.AUDIO,),
    "Documents": (ObjectKind.DOCUMENT, ObjectKind.TEXT),
    "Encrypted": (ObjectKind.ENCRYPTED,),
    "Projects": (ObjectKind.CODE,),
    "Applications": (ObjectKind.EXECUTABLE, ObjectKind.WIDGET),
    "Archives": (ObjectKind.ARCHIVE,),
    "Databases": (ObjectKind.DATABASE,),
    "Books": (ObjectKind.BOOK,),
}
