"""Spaces, albums, labels — the organizational layer above tags.

Reference: schema.prisma:323-454 defines Space/ObjectInSpace,
Album/ObjectInAlbum, Label/LabelOnObject as LOCAL models (no sync
annotations — unlike Tag they don't replicate) and ships no procedures
for them; here the models get a working CRUD + membership surface so the
schema isn't dead weight. Link rows are unique per (collection, object)
and deletes clear memberships first (the FK is RESTRICT, matching the
reference's non-cascading link tables).
"""

from __future__ import annotations

import uuid
from typing import TYPE_CHECKING, Any

from ..models import (Album, FilePath, Label, LabelOnObject, Object,
                      ObjectInAlbum, ObjectInSpace, Space, utc_now)

if TYPE_CHECKING:
    from ..library import Library


def _invalidate(library: "Library", key: str) -> None:
    library.emit("invalidate_query", {"key": key})


# -- generic collection helpers (Space and Album share their shape) ----------

_LINKS = {Space: (ObjectInSpace, "space_id", "spaces"),
          Album: (ObjectInAlbum, "album_id", "albums")}


def create_collection(library: "Library", model, name: str,
                      **extra: Any) -> dict[str, Any]:
    row = {"pub_id": str(uuid.uuid4()), "name": name,
           "date_created": utc_now(), "date_modified": utc_now(), **extra}
    library.db.insert(model, row)
    _invalidate(library, f"{_LINKS[model][2]}.list")
    return library.db.find_one(model, {"pub_id": row["pub_id"]})


def update_collection(library: "Library", model, collection_id: int,
                      **values: Any) -> None:
    values = {k: v for k, v in values.items() if v is not None}
    if not values:
        return
    values["date_modified"] = utc_now()
    library.db.update(model, {"id": collection_id}, values)
    _invalidate(library, f"{_LINKS[model][2]}.list")


def delete_collection(library: "Library", model, collection_id: int) -> None:
    link_model, fk, key = _LINKS[model]
    with library.db.transaction():
        library.db.delete(link_model, {fk: collection_id})
        library.db.delete(model, {"id": collection_id})
    _invalidate(library, f"{key}.list")


def set_membership(library: "Library", model, collection_id: int,
                   object_ids: list[int], remove: bool = False) -> int:
    """Add/remove objects; returns how many links changed."""
    link_model, fk, key = _LINKS[model]
    if library.db.find_one(model, {"id": collection_id}) is None:
        raise ValueError(f"{model.TABLE} {collection_id} not found")
    changed = 0
    for oid in object_ids:
        if remove:
            changed += library.db.delete(
                link_model, {fk: collection_id, "object_id": oid})
        else:
            if library.db.find_one(Object, {"id": oid}) is None:
                continue
            if library.db.find_one(
                    link_model, {fk: collection_id, "object_id": oid}):
                continue  # already linked: must not count as a change
            row: dict[str, Any] = {fk: collection_id, "object_id": oid}
            if "date_created" in link_model.FIELDS:
                row["date_created"] = utc_now()
            library.db.insert(link_model, row, or_ignore=True)
            changed += 1
    _invalidate(library, f"{key}.list")
    return changed


def collection_objects(library: "Library", model,
                       collection_id: int) -> list[dict[str, Any]]:
    """Member objects with a representative file_path each (display rows)."""
    link_model, fk, _key = _LINKS[model]
    return [FilePath.decode_row(r) for r in library.db.query(
        f"SELECT f.*, o.pub_id AS object_pub_id, o.kind AS object_kind, "
        f"o.favorite FROM {link_model.TABLE} l "
        f"JOIN object o ON o.id = l.object_id "
        f"JOIN file_path f ON f.object_id = o.id "
        f"WHERE l.{fk} = ? GROUP BY o.id ORDER BY f.name",
        [collection_id])]


def list_collections(library: "Library", model) -> list[dict[str, Any]]:
    link_model, fk, _key = _LINKS[model]
    return [model.decode_row(r) | {"object_count": r["object_count"]}
            for r in library.db.query(
        f"SELECT c.*, COUNT(l.object_id) AS object_count "
        f"FROM {model.TABLE} c LEFT JOIN {link_model.TABLE} l "
        f"ON l.{fk} = c.id GROUP BY c.id ORDER BY c.name")]


# -- labels ------------------------------------------------------------------

def ensure_label(library: "Library", name: str) -> dict[str, Any]:
    existing = library.db.find_one(Label, {"name": name})
    if existing is not None:
        return existing
    library.db.insert(Label, {"pub_id": str(uuid.uuid4()), "name": name,
                              "date_created": utc_now(),
                              "date_modified": utc_now()})
    _invalidate(library, "labels.list")
    return library.db.find_one(Label, {"name": name})


def label_objects(library: "Library", label_id: int,
                  object_ids: list[int], remove: bool = False) -> int:
    changed = 0
    for oid in object_ids:
        if remove:
            changed += library.db.delete(
                LabelOnObject, {"label_id": label_id, "object_id": oid})
        else:
            if library.db.find_one(
                    LabelOnObject, {"label_id": label_id, "object_id": oid}):
                continue  # already labeled: not a change
            library.db.insert(LabelOnObject,
                              {"label_id": label_id, "object_id": oid,
                               "date_created": utc_now()}, or_ignore=True)
            changed += 1
    _invalidate(library, "labels.list")
    return changed


def list_labels(library: "Library") -> list[dict[str, Any]]:
    return [Label.decode_row(r) | {"object_count": r["object_count"]}
            for r in library.db.query(
        "SELECT lb.*, COUNT(lo.object_id) AS object_count FROM label lb "
        "LEFT JOIN label_on_object lo ON lo.label_id = lb.id "
        "GROUP BY lb.id ORDER BY lb.name")]


def labels_for_object(library: "Library", object_id: int) -> list[dict[str, Any]]:
    return [Label.decode_row(r) for r in library.db.query(
        "SELECT lb.* FROM label lb JOIN label_on_object lo "
        "ON lo.label_id = lb.id WHERE lo.object_id = ? ORDER BY lb.name",
        [object_id])]
