"""FileIdentifierJob: assign cas_ids and dedup into Objects.

Semantics from core/src/object/file_identifier/{mod,file_identifier_job}.rs:
orphans are file_paths with no object (not directories); each step takes the
next cursor-paginated chunk (id > cursor, file_identifier_job.rs:245-268),
computes cas_ids (empty files get none, mod.rs:80-88), writes them, links
paths to existing objects sharing the cas_id, and batch-creates objects for
the rest (:136-335). ObjectKind comes from the extension registry.

TPU-first deviation: the chunk is the device batch. The reference hashes 100
files per step with per-file tokio tasks; here a step gathers sampled messages
for BATCH_SIZE files and hashes them in one fused device call via the
location's hasher backend. Within-batch duplicates collapse to one object
(the reference creates one object per path and converges on later scans).

Each step is split into the three streaming-pipeline stages
(pipeline/executor.py): ``pipeline_page`` (cursor SELECT + sample-message
gather, read-only), ``pipeline_process`` (the hash batch), and
``pipeline_commit`` (the transaction + CRDT ops + cursor advance). The
sequential path runs the same three callables back-to-back, so pipelined and
sequential runs produce byte-identical DB state and op order
(tests/test_pipeline.py). The committer also warm-starts media processing:
prefixes whose identified rows carry thumbnailable extensions are handed to
LocationsActor.media_warm_start, which spawns media-lane jobs that overlap
the rest of the identify run instead of waiting for it to finish.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Any

from .. import faults, telemetry
from ..jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ..recovery import is_disk_full, note_disk_full
from ..models import FilePath, Location, Object, utc_now
from ..sync.crdt import ref
from .cas import read_sampled_batch_fast as read_sampled_batch
from .hasher import HybridHasher, get_hasher
# imported unconditionally so the sd_chunk_* telemetry families exist on
# /metrics (and in observability.md's drift gate) even with manifests off
from . import manifest as chunk_manifest

_QUARANTINED = telemetry.counter(
    "sd_quarantined_files_total",
    "per-item failures quarantined by the identifier")
_RECOVERED = telemetry.counter(
    "sd_recovered_batches_total",
    "hash batches re-dispatched on the CPU ladder after a device failure")
_SCAN_RATE = telemetry.gauge(
    "sd_scan_files_per_sec",
    "files/s of the most recent completed identify pass")

_THUMBABLE_EXTS: list = []


def _thumbable_exts() -> set[str]:
    """Memoized thumbnailable-extension set (media/processor.py) — consulted
    once per committed batch on the scan hot path."""
    if not _THUMBABLE_EXTS:
        from .media.processor import _thumbable_extensions

        _THUMBABLE_EXTS.append(_thumbable_extensions())
    return _THUMBABLE_EXTS[0]


def ref_obj(pub_id: str):
    """object FK crossing the sync wire as a pub_id reference (crdt.py)."""
    return ref(Object.TABLE, pub_id)

logger = logging.getLogger(__name__)

#: files per step = device batch size (reference CHUNK_SIZE=100 is a CPU
#: tuning; the TPU kernel amortizes over thousands of lanes)
BATCH_SIZE = 1024

#: adaptive page-size clamps (ISSUE 17): pages shrink toward finer
#: pipelining when the hash stage dominates and grow to amortize per-page
#: fixed costs when gather or commit does
ADAPT_MIN_BATCH = 256
ADAPT_MAX_BATCH = 4096

_BATCH_GAUGE = telemetry.gauge(
    "sd_scan_batch_size",
    "files per scan page after adaptive sizing (the fixed BATCH_SIZE "
    "when adaptation is pinned off)")


def _env_batch_pin() -> int | None:
    """Explicit page-size pin (``SD_SCAN_BATCH``) — turns adaptation off
    and sizes every page to exactly this many files."""
    raw = os.environ.get("SD_SCAN_BATCH", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            return None
    return None


def _adaptive_batching() -> bool:
    """Adaptive page sizing is live only at the stock configuration: a
    monkeypatched ``BATCH_SIZE`` (tests pin page boundaries), an explicit
    ``SD_SCAN_BATCH``, or ``SD_SCAN_ADAPT=0`` all mean FIXED pages —
    pipelined page boundaries then match the sequential schedule exactly,
    which is what the byte-identity matrices assert."""
    if BATCH_SIZE != 1024 or _env_batch_pin() is not None:
        return False
    return os.environ.get("SD_SCAN_ADAPT", "1").lower() not in (
        "0", "false", "off")


def _page_limit(scratch: dict) -> int:
    """Files in the next page. With adaptation live, sizes from the
    executor's measured stage balance (``scratch['stage_shares']``,
    target: no stage above 60% of the pipeline wall): a dominant hash
    stage shrinks pages (finer overlap, smaller device batches feed the
    double-buffer sooner), a dominant gather or commit stage grows them
    (amortize the per-page SELECT / txn / uring-round fixed costs), and a
    balanced pipeline drifts back toward the static default. The scratch
    dict is pipeline-local and only the prefetch/split thread touches it."""
    pin = _env_batch_pin()
    if pin is not None:
        return pin
    if not _adaptive_batching():
        return BATCH_SIZE
    cur = int(scratch.get("batch_size") or BATCH_SIZE)
    shares = scratch.get("stage_shares")
    if shares:
        dominant = max(shares, key=shares.get)
        if shares[dominant] > 0.6:
            if dominant == "hash":
                cur = max(cur * 3 // 4, ADAPT_MIN_BATCH)
            else:
                cur = min(cur * 3 // 2, ADAPT_MAX_BATCH)
        else:
            cur += (BATCH_SIZE - cur) // 4
    scratch["batch_size"] = cur
    _BATCH_GAUGE.set(cur)
    return cur


def _orphan_where(location_id: int, sub_path: str | None) -> tuple[str, list]:
    sql = ('object_id IS NULL AND is_dir = 0 AND location_id = ? AND name != ""')
    params: list[Any] = [location_id]
    if sub_path:
        sql += " AND materialized_path LIKE ?"
        params.append(f"/{sub_path.strip('/')}/%")
    return sql, params


class FileIdentifierJob(StatefulJob):
    NAME = "file_identifier"
    IS_BATCHED = True

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        location_id = self.init_args["location_id"]
        location = db.find_one(Location, {"id": location_id})
        if location is None:
            raise JobError(f"location {location_id} not found")
        where, params = _orphan_where(location_id, self.init_args.get("sub_path"))
        count = db.query(f"SELECT COUNT(*) AS n FROM file_path WHERE {where}", params)[0]["n"]
        if count == 0:
            raise EarlyFinish("Found no orphan file paths to process")
        logger.info("Found %d orphan file paths", count)
        # plan steps from the EFFECTIVE page size: an SD_SCAN_BATCH pin
        # below the default would otherwise exhaust init's step budget
        # with orphans left over (the executor only grows the step ledger
        # for adaptive runs, and pinned runs are exact by definition)
        page = _env_batch_pin() or BATCH_SIZE
        steps = [{"kind": "identify"} for _ in range(-(-count // page))]
        data = {"location_id": location_id, "location_path": location["path"],
                # hybrid probes both engines and routes to the winner, so a
                # production scan never takes a known-losing path on hosts
                # where transfers are slow (the bench measures both regimes)
                "hasher": location.get("hasher") or "hybrid", "cursor": 0,
                "sub_path": self.init_args.get("sub_path"),
                "preview_media":
                    location.get("generate_preview_media") is not False}
        return data, steps, {"total_orphan_paths": count, "created_objects": 0,
                             "linked_objects": 0, "hash_time": 0.0,
                             "quarantined_files": 0, "recovered_batches": 0,
                             "chunked_files": 0, "chunk_quarantined": 0}

    def pipeline_spec(self):
        from ..pipeline import PipelineSpec

        return PipelineSpec(page=self.pipeline_page,
                            process=self.pipeline_process,
                            commit=self.pipeline_commit,
                            split=self.pipeline_page_split,
                            shard=self.pipeline_page_shard,
                            merge=self.pipeline_page_merge,
                            adaptive=_adaptive_batching())

    def execute_step(self, ctx: WorkerContext, data: dict, step: dict,
                     step_number: int) -> StepResult:
        # the sequential path IS the pipeline, stages run back-to-back —
        # one implementation, two schedules
        scratch = {"cursor": data["cursor"]}
        batch = self.pipeline_page(ctx, data, scratch)
        if batch is None:
            return StepResult()
        return self.pipeline_commit(ctx, data,
                                    self.pipeline_process(ctx, data, batch))

    # -- stage 1: prefetch (DB reads + file I/O only) ------------------------
    def pipeline_page(self, ctx: WorkerContext, data: dict,
                      scratch: dict) -> dict | None:
        db = ctx.library.db
        cursor = scratch.get("cursor", data["cursor"])
        where, params = _orphan_where(data["location_id"], data.get("sub_path"))
        # only the columns this step consumes, undecoded: size_in_bytes and
        # is_dir are ints, date_created stays an ISO string (Model.encode
        # passes strings through on re-insert) — a SELECT * + full
        # decode_row costs ~15% of the whole identify pass at 100k files.
        # The speculative cursor rides in ``scratch``: rows at id <= cursor
        # are untouched by later commits, so speculative pages see exactly
        # the row sets the sequential loop would
        rows = [dict(r) for r in db.query(
            f"SELECT id, pub_id, name, extension, materialized_path, is_dir, "
            f"size_in_bytes, date_created FROM file_path "
            f"WHERE {where} AND id > ? ORDER BY id LIMIT ?",
            params + [cursor, _page_limit(scratch)],
        )]
        if not rows:
            return None
        scratch["cursor"] = rows[-1]["id"]
        hashable, empty, messages, gather_s = \
            self._gather_rows(ctx, data, rows)
        return {"cursor": rows[-1]["id"], "hashable": hashable, "empty": empty,
                "messages": messages, "gather_s": gather_s}

    def _gather_rows(self, ctx: WorkerContext, data: dict,
                     rows: list[dict]) -> tuple[list, list, list, float]:
        """SELECT'd page (or page-slice) rows → ``(hashable, empty,
        messages, gather_s)``: the size split, the fused sample gather and
        the magic-head attach — shared verbatim by the whole-page and
        sharded-slice prefetch paths, so a merged page is byte-identical
        to a sequential one by construction."""
        hashable, empty = [], []
        for row in rows:
            if (row["size_in_bytes"] or 0) > 0:
                hashable.append(row)
            else:
                empty.append(row)  # "We can't do shit with empty files"

        location_path = data["location_path"]
        # ad-hoc timing goes through spans (telemetry-discipline): the
        # gather duration lands in the report via the span, nests under
        # pipeline.page (or the shard's pipeline.gather) in the job trace,
        # and still measures when telemetry is off (bare-timer degradation)
        paths = [_abs_path(location_path, r) for r in hashable]
        with telemetry.span(getattr(ctx, "trace", None), "identifier.gather",
                            files=len(hashable)) as gather_sp:
            messages = read_sampled_batch(
                paths, [r["size_in_bytes"] for r in hashable])
            gather_sp.set(bytes=sum(len(m) for m in messages
                                    if not isinstance(m, Exception)))
            if chunk_manifest.manifests_enabled():
                # manifest payloads ride the same gather (small files reuse
                # the cas message body byte-for-byte): attached per row, so
                # shard-merge concatenation carries them automatically
                chunk_manifest.pipeline_chunk_gather(paths, hashable, messages)
        # the cas message is size_le_8 ‖ header ‖ … — its head IS the file's
        # first bytes, so magic-byte kind resolution rides the gather for
        # free instead of re-opening every file on the commit thread (the
        # single hottest commit cost at 100k files: one open+read per object)
        from .magic import HEADER_LEN

        for row, msg in zip(hashable, messages):
            row["_kind_head"] = (None if isinstance(msg, Exception)
                                 else bytes(msg[8:8 + HEADER_LEN]))
        for row in empty:
            row["_kind_head"] = b""  # what _read_head returns for empty files
        return hashable, empty, messages, gather_sp.duration_s

    # -- stage 1, sharded (ISSUE 17): split → parallel slices → merge --------
    def pipeline_page_split(self, ctx: WorkerContext, data: dict,
                            scratch: dict) -> dict | None:
        """Split-coordinator half of the page stage: one id-only cursor
        SELECT, chopped into contiguous id-range slices (one per gather
        shard). Contiguity in strict id order is the byte-identity
        argument: the slices' row sets concatenate back into exactly the
        row set — in exactly the order — the unsharded SELECT returns for
        the same cursor window, and commits only ever touch rows at
        ``id <=`` an already-committed cursor, so slice SELECTs re-running
        the predicate later cannot see different rows."""
        db = ctx.library.db
        cursor = scratch.get("cursor", data["cursor"])
        where, params = _orphan_where(data["location_id"],
                                      data.get("sub_path"))
        ids = [r["id"] for r in db.query(
            f"SELECT id FROM file_path WHERE {where} AND id > ? "
            f"ORDER BY id LIMIT ?",
            params + [cursor, _page_limit(scratch)])]
        if not ids:
            return None
        scratch["cursor"] = ids[-1]
        shards = max(1, int(scratch.get("shards") or 1))
        per = -(-len(ids) // shards)
        parts = [{"lo": ids[lo], "hi": ids[min(lo + per, len(ids)) - 1]}
                 for lo in range(0, len(ids), per)]
        return {"cursor": ids[-1], "parts": parts}

    def pipeline_page_shard(self, ctx: WorkerContext, data: dict,
                            part: dict) -> dict:
        """One slice's row SELECT + sample gather — the same read-only
        contract as ``pipeline_page``, safe to run concurrently with the
        other slices (reads serialize on the shared reader connection;
        the fused native gather releases the GIL for the whole slice)."""
        db = ctx.library.db
        where, params = _orphan_where(data["location_id"],
                                      data.get("sub_path"))
        rows = [dict(r) for r in db.query(
            f"SELECT id, pub_id, name, extension, materialized_path, is_dir, "
            f"size_in_bytes, date_created FROM file_path "
            f"WHERE {where} AND id >= ? AND id <= ? ORDER BY id",
            params + [part["lo"], part["hi"]])]
        hashable, empty, messages, gather_s = \
            self._gather_rows(ctx, data, rows)
        return {"hashable": hashable, "empty": empty, "messages": messages,
                "gather_s": gather_s}

    def pipeline_page_merge(self, ctx: WorkerContext, data: dict,
                            header: dict, results: list[dict]) -> dict:
        """Reassemble the slice results (slice order == id order) into
        exactly the payload ``pipeline_page`` returns. Per-list
        concatenation preserves the hashable↔messages alignment because
        each slice's lists are aligned and slices are disjoint id ranges
        in page order. ``gather_s`` is the MAX slice gather — the page's
        gather *wall*, the number shard parallelism is supposed to
        shrink (the per-slice sum would hide the win)."""
        hashable: list = []
        empty: list = []
        messages: list = []
        gather_s = 0.0
        for res in results:
            hashable.extend(res["hashable"])
            empty.extend(res["empty"])
            messages.extend(res["messages"])
            gather_s = max(gather_s, res["gather_s"])
        return {"cursor": header["cursor"], "hashable": hashable,
                "empty": empty, "messages": messages, "gather_s": gather_s}

    # -- stage 2: dispatch (device/CPU compute) ------------------------------
    def pipeline_process(self, ctx: WorkerContext, data: dict,
                         batch: dict) -> dict:
        from .cas import MINIMUM_FILE_SIZE

        hasher = get_hasher(data.get("hasher"), node=ctx.node)
        hashable = batch["hashable"]
        #: _probe_rates needs k = sampled//2 >= 8 files per engine slice —
        #: below that the fused call can't conclude a probe, so re-reading
        #: the files it would do is pure waste (the gather already ran)
        probe_worthy = sum(1 for r in hashable
                           if r["size_in_bytes"] > MINIMUM_FILE_SIZE) >= 16
        with telemetry.span(getattr(ctx, "trace", None), "identifier.hash",
                            files=len(hashable)) as hash_sp:
            try:
                faults.inject("hash")
                if getattr(hasher, "_cpu_rate", None) is None \
                        and isinstance(hasher, HybridHasher) \
                        and hasher._cpu._fast is not None and probe_worthy:
                    # unprobed hybrid: run this batch through the fused path
                    # so the engine probe happens (the gather above left the
                    # page cache warm); later batches take the gathered
                    # route with the verdict
                    location_path = data["location_path"]
                    cas_results = hasher.hash_batch(
                        [_abs_path(location_path, r) for r in hashable],
                        [r["size_in_bytes"] for r in hashable])
                else:
                    cas_results = hasher.hash_gathered(batch["messages"])
            except Exception as e:  # noqa: BLE001 — degradation ladder below
                # mid-batch hasher failure (device wedge, dying backend):
                # this batch re-dispatches on the native CPU path over the
                # already-gathered messages (byte-identical cas_ids), the
                # hybrid verdict flips so later batches skip the dead
                # engine, and the pipeline keeps moving. A CPU-path failure
                # here raises through to stage supervision — there is no
                # rung below the oracle.
                logger.exception("hash dispatch failed mid-batch; "
                                 "re-dispatching batch on the native CPU "
                                 "path")
                degrade = getattr(hasher, "degrade_device", None)
                if degrade is not None:
                    degrade(repr(e))
                cas_results = get_hasher("cpu").hash_gathered(
                    batch["messages"])
                batch["recovered_error"] = repr(e)
                _RECOVERED.inc()
        batch["cas_results"] = cas_results
        batch["hash_s"] = hash_sp.duration_s
        batch["messages"] = None  # the gather buffers are dead weight now
        if chunk_manifest.manifests_enabled():
            # the manifest stage rides the same dispatch thread behind its
            # own router; failures degrade/quarantine inside, never raise
            chunk_manifest.pipeline_chunk_process(
                batch["hashable"], trace=getattr(ctx, "trace", None))
        return batch

    # -- stage 3: commit (the only stage that writes) ------------------------
    def pipeline_commit(self, ctx: WorkerContext, data: dict,
                        batch: dict) -> StepResult:
        db = ctx.library.db
        location_path = data["location_path"]
        hashable, empty = batch["hashable"], batch["empty"]
        errors: list[str] = []

        # per-item quarantine: vanished/permission-denied/truncated files
        # (post-retry) are excluded from this batch's writes and recorded as
        # soft errors — the scan completes COMPLETED_WITH_ERRORS instead of
        # dying, and the next scan retries them as still-orphan paths
        identified: list[tuple[dict, str]] = []
        quarantined = 0
        for row, cas in zip(hashable, batch["cas_results"]):
            if isinstance(cas, Exception):
                errors.append(
                    f"quarantined {_abs_path(location_path, row)}: {cas!r}")
                quarantined += 1
                if is_disk_full(cas):
                    # ENOSPC during the gather (a full disk can fail reads
                    # through mmap'd page allocation and vanished temp
                    # space): degrade per-item like every other quarantine,
                    # but light up the one disk-full series operators watch
                    note_disk_full("gather")
            else:
                identified.append((row, cas))
        if quarantined:
            _QUARANTINED.inc(quarantined)
        chunk_errors: list[str] = []
        if chunk_manifest.manifests_enabled():
            # per-item manifest quarantine: the file still identifies, only
            # its manifest is skipped (next scan rebuilds it)
            chunk_errors = chunk_manifest.quarantine_errors(
                hashable, location_path)
            errors.extend(chunk_errors)
        if batch.get("recovered_error"):
            errors.append(f"hash batch recovered on native CPU path after: "
                          f"{batch['recovered_error']}")

        sync = getattr(ctx.library, "sync", None)
        emit = sync is not None and getattr(sync, "emit_messages", False)
        ops = []  # CRDT ops logged atomically with the writes (write_ops semantics)

        with db.transaction():
            # 1. write cas_ids (one executemany: this loop runs for every
            # file in the location)
            db.executemany_noted(
                "UPDATE file_path SET cas_id = ? WHERE id = ?",
                [(cas, row["id"]) for row, cas in identified],
                "file_path", (row["id"] for row, _cas in identified))
            if emit:
                for row, cas in identified:
                    ops.append(sync.shared_update(FilePath, row["pub_id"], "cas_id", cas))

            # 2. link to existing objects owning these cas_ids
            cas_ids = sorted({cas for _, cas in identified})
            existing: dict[str, tuple[int, str]] = {}
            for chunk_start in range(0, len(cas_ids), 500):
                chunk = cas_ids[chunk_start : chunk_start + 500]
                marks = ",".join("?" for _ in chunk)
                for r in db.query(
                    f"SELECT fp.cas_id AS cas_id, o.id AS oid, o.pub_id AS opub "
                    f"FROM file_path fp JOIN object o ON fp.object_id = o.id "
                    f"WHERE fp.cas_id IN ({marks})", chunk):
                    existing.setdefault(r["cas_id"], (r["oid"], r["opub"]))

            linked = 0
            link_rows: list[tuple[int, int]] = []  # (object_id, file_path_id)
            need_object: dict[str, list[dict]] = {}
            for row, cas in identified:
                if cas in existing:
                    oid, opub = existing[cas]
                    link_rows.append((oid, row["id"]))
                    if emit:
                        ops.append(sync.shared_update(
                            FilePath, row["pub_id"], "object_id", ref_obj(opub)))
                    linked += 1
                else:
                    need_object.setdefault(cas, []).append(row)

            # 3. create one object per unique new cas_id (+ one per empty
            # file) — one executemany then one pub_id->id readback instead
            # of a round-trip per object (this loop also runs for every
            # file in the location)
            creations: list[tuple[dict, list[dict]]] = \
                [(members[0], members) for members in need_object.values()] \
                + [(row, [row]) for row in empty]
            created = len(creations)
            if creations:
                obj_rows = [self._object_row(rep, data["location_path"])
                            for rep, _members in creations]
                db.insert_many(Object, obj_rows)
                oid_of: dict[str, int] = {}
                for start in range(0, len(obj_rows), 500):
                    chunk = obj_rows[start : start + 500]
                    marks = ",".join("?" * len(chunk))
                    for r in db.query(
                            f"SELECT id, pub_id FROM object "
                            f"WHERE pub_id IN ({marks})",
                            [c["pub_id"] for c in chunk]):
                        oid_of[r["pub_id"]] = r["id"]
                for obj, (_rep, members) in zip(obj_rows, creations):
                    oid, opub = oid_of[obj["pub_id"]], obj["pub_id"]
                    if emit:
                        ops.append(sync.shared_create(Object, opub, {
                            "kind": obj["kind"],
                            "date_created": utc_now().isoformat(),
                        }))
                    for row in members:
                        link_rows.append((oid, row["id"]))
                        if emit:
                            ops.append(sync.shared_update(
                                FilePath, row["pub_id"], "object_id",
                                ref_obj(opub)))
            db.executemany_noted(
                "UPDATE file_path SET object_id = ? WHERE id = ?",
                link_rows, "file_path", (fp_id for _oid, fp_id in link_rows))

            # 4. persist chunk manifests (opt-in) in the SAME transaction —
            # a crash between the identify writes and the manifest rows can
            # never surface (the kill matrix pins a SIGKILL here)
            chunked = 0
            if chunk_manifest.manifests_enabled():
                faults.inject("manifest_commit")
                oid_by_fp = {fp_id: oid for oid, fp_id in link_rows}
                items: list[tuple[int, list]] = []
                seen_oids: set[int] = set()
                for row, _cas in identified:
                    m = row.get("_chunk_manifest")
                    oid = oid_by_fp.get(row["id"])
                    if m is None or oid is None or oid in seen_oids:
                        continue  # within-batch cas-duplicates: one copy wins
                    seen_oids.add(oid)
                    items.append((oid, m))
                chunked = chunk_manifest.commit_manifest_rows(db, items)
            if emit and ops:
                sync.log_ops(ops)
        # the checkpoint cursor advances ONLY here, after the transaction
        # committed — a pause/crash resumes at the last committed batch
        data["cursor"] = batch["cursor"]

        # everything below is BEST-EFFORT tail work: the batch is durable,
        # so nothing past this point may raise — the committer's retry
        # (pipeline/executor.COMMIT_RETRY) assumes an exception out of
        # pipeline_commit means the transaction did NOT land, and a re-run
        # here would re-log every CRDT op of the batch
        if emit and ops:
            try:
                sync.created()
            except Exception:
                logger.exception("sync.created broadcast failed (peers "
                                 "will pull on their next round)")
        try:
            self._media_warm_start(ctx, data, identified)
            ctx.progress(message=f"identified {len(identified)} files "
                                 f"({created} new objects, {linked} linked)")
        except Exception:
            logger.exception("post-commit warm-start/progress failed "
                             "(batch is committed; continuing)")
        return StepResult(metadata={"created_objects": created,
                                    "linked_objects": linked,
                                    "hash_time": batch["hash_s"],
                                    "gather_s": batch["gather_s"],
                                    "quarantined_files": quarantined,
                                    "recovered_batches":
                                        1 if batch.get("recovered_error")
                                        else 0,
                                    "chunked_files": chunked,
                                    "chunk_quarantined": len(chunk_errors)},
                          errors=errors)

    def _media_warm_start(self, ctx: WorkerContext, data: dict,
                          identified: list[tuple[dict, str]]) -> None:
        """Hand freshly identified thumbnailable prefixes to the locations
        actor so media-lane jobs start while this job is still hashing the
        rest of the location. Best-effort: the chained whole-location media
        job still sweeps up stragglers (existing thumbnails are skipped)."""
        node = getattr(ctx, "node", None)
        actor = getattr(node, "locations", None)
        if actor is None or not data.get("preview_media", True):
            return
        exts = _thumbable_exts()
        prefixes = set()
        for row, _cas in identified:
            if (row.get("extension") or "").lower() in exts:
                mp = (row.get("materialized_path") or "/").strip("/")
                if mp:
                    prefixes.add(mp.split("/")[0])
        if prefixes:
            actor.media_warm_start(ctx.library, data["location_id"], prefixes)

    def _object_row(self, row: dict, location_path: str | None) -> dict:
        from .magic import resolve_kind

        # magic-byte disambiguation for conflicting/unknown extensions
        # (file_identifier/mod.rs:75 → magic.rs); the head bytes came with
        # the gather (``_kind_head``) so this never touches the disk — the
        # path fallback only fires for rows that skipped the page stage
        kind = resolve_kind(
            row.get("extension"),
            _abs_path(location_path, row) if location_path else None,
            bool(row.get("is_dir")),
            head=row.get("_kind_head"))
        return {"pub_id": str(uuid.uuid4()), "kind": kind,
                "date_created": row.get("date_created") or utc_now()}

    def finalize(self, ctx: WorkerContext, data: dict, run_metadata: dict):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        ctx.library.emit("invalidate_query", {"key": "search.objects"})
        # the operator's headline number: identify throughput of the pass
        # that just finished (elapsed read off the job's root span).
        # Un-resumed passes only: a CROSS-PROCESS resume starts a fresh
        # trace whose elapsed covers just the final run, and dividing the
        # checkpoint-accumulated file total by it would inflate the gauge
        # (an in-process resume continues the original trace, but the gate
        # keys on the checkpoint either way — conservative, never bogus)
        trace = getattr(ctx, "trace", None)
        total = run_metadata.get("total_orphan_paths") or 0
        dyn = getattr(getattr(ctx, "_worker", None), "dyn_job", None)
        resumed = dyn is not None and getattr(dyn, "was_resumed", False)
        if trace is not None and total and not resumed:
            elapsed = trace.elapsed_s()
            if elapsed > 0:
                _SCAN_RATE.set(round(total / elapsed, 1))
        logger.info("file_identifier finished: %s", run_metadata)
        return run_metadata


def _abs_path(location_path: str, row: dict) -> str:
    name = row["name"] or ""
    ext = row["extension"] or ""
    full = f"{name}.{ext}" if ext and not row["is_dir"] else name
    return f"{location_path}{row['materialized_path']}{full}"


def shallow_identify(library, location_id: int, sub_path: str = "") -> dict[str, Any]:
    """Non-job single-directory identify (file_identifier/shallow.rs) used by
    the watcher path."""

    class _ShallowCtx:
        def __init__(self, lib):
            self.library = lib
            self.node = lib.node

        def progress(self, *a, **k):
            pass

        def check_commands(self, *a):
            pass

    job = FileIdentifierJob({"location_id": location_id, "sub_path": sub_path or None})
    ctx = _ShallowCtx(library)
    try:
        data, steps, meta = job.init(ctx)  # type: ignore[arg-type]
    except EarlyFinish:
        return {"identified": 0}
    for i, step in enumerate(steps):
        job.execute_step(ctx, data, step, i)  # type: ignore[arg-type]
    return {"identified": meta.get("total_orphan_paths", 0)}
