"""Filesystem operation jobs: copy, cut (move), delete, erase, create.

Behavioral parity with core/src/object/fs/*.rs through the same job engine
seam (each is a StatefulJob with serializable per-file steps, so a shutdown
mid-copy resumes where it left off):

- FileCopierJob (fs/copy.rs): per-file copy steps; directories expand into
  child steps during the run; name collisions resolve to "name (2).ext" style.
- FileCutterJob (fs/cut.rs): rename within a device, copy+unlink across.
- FileDeleterJob (fs/delete.rs): removes files/dir-trees + their db rows.
- FileEraserJob (fs/erase.rs / sd-crypto fs/erase): multi-pass random
  overwrite sized to the file, then unlink (VSSE-style best effort; SSD
  caveats documented in the reference too).
- create_file / create_directory (fs/create.rs): collision-safe creation.

All jobs finish by light-rescanning the touched directories (the reference
leans on the watcher; headless hosts need the explicit rescan)."""

from __future__ import annotations

import logging
import os
import shutil
import secrets
from pathlib import Path
from typing import Any

from ..jobs import EarlyFinish, JobError, StatefulJob, StepResult, WorkerContext
from ..models import FilePath, Location

logger = logging.getLogger(__name__)

ERASE_BLOCK = 1 << 20  # 1 MiB overwrite blocks (crypto stream block size)


def location_path_of(db, location_id: int) -> Path:
    row = db.find_one(Location, {"id": location_id})
    if row is None:
        raise JobError(f"location {location_id} not found")
    return Path(row["path"])


def file_path_abs(db, file_path_id: int) -> tuple[dict[str, Any], Path]:
    row = db.find_one(FilePath, {"id": file_path_id})
    if row is None:
        raise JobError(f"file_path {file_path_id} not found")
    root = location_path_of(db, row["location_id"])
    rel = (row["materialized_path"] or "/").lstrip("/")
    name = row["name"] + (f".{row['extension']}" if row["extension"] else "")
    return row, root / rel / name


def find_available_name(target: Path) -> Path:
    """'duplicate.txt' → 'duplicate (2).txt' (fs/mod.rs name-collision walk)."""
    if not target.exists():
        return target
    stem, suffix = target.stem, target.suffix
    for i in range(2, 1000):
        candidate = target.with_name(f"{stem} ({i}){suffix}")
        if not candidate.exists():
            return candidate
    raise JobError(f"no available name for {target}")


def create_file(parent: Path, name: str, content: bytes = b"") -> Path:
    target = find_available_name(parent / name)
    with open(target, "xb") as fh:
        fh.write(content)
    return target


def create_directory(parent: Path, name: str) -> Path:
    target = find_available_name(parent / name)
    target.mkdir()
    return target


class _FsJob(StatefulJob):
    """Shared init: resolve sources to absolute paths + target context."""

    def _sources(self, ctx: WorkerContext) -> list[tuple[dict[str, Any], Path]]:
        db = ctx.library.db
        out = []
        for fp_id in self.init_args["sources"]:
            out.append(file_path_abs(db, fp_id))
        return out

    def _rescan(self, ctx: WorkerContext, location_id: int, dirs: set[str]) -> None:
        from ..locations import light_scan_location

        for sub in sorted(dirs):
            try:
                light_scan_location(ctx.library, location_id, sub)
            except Exception:
                logger.exception("post-op rescan failed for %r", sub)


class FileCopierJob(_FsJob):
    """init_args: sources [file_path ids], target_location_id, target_dir
    (location-relative, '' = root)."""

    NAME = "file_copier"

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        target_root = location_path_of(db, self.init_args["target_location_id"])
        target_dir = target_root / self.init_args.get("target_dir", "").strip("/")
        if not target_dir.is_dir():
            raise JobError(f"target directory missing: {target_dir}")
        steps = []
        for row, src in self._sources(ctx):
            steps.append({"kind": "dir" if row["is_dir"] else "file",
                          "src": str(src), "dst": str(target_dir / src.name)})
        if not steps:
            raise EarlyFinish("nothing to copy")
        return ({"target_location_id": self.init_args["target_location_id"],
                 "target_dir": self.init_args.get("target_dir", "")},
                steps, {"copied": 0, "bytes": 0})

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        src, dst = Path(step["src"]), Path(step["dst"])
        try:
            if step["kind"] == "dir":
                dst = find_available_name(dst)
                dst.mkdir()
                more = []
                for entry in sorted(os.scandir(src), key=lambda e: e.name):
                    more.append({
                        "kind": "dir" if entry.is_dir(follow_symlinks=False) else "file",
                        "src": entry.path, "dst": str(dst / entry.name)})
                return StepResult(more_steps=more, metadata={"copied": 1})
            dst = find_available_name(dst)
            shutil.copy2(src, dst)
            return StepResult(metadata={"copied": 1, "bytes": src.stat().st_size})
        except OSError as e:
            return StepResult(errors=[f"copy {src}: {e}"])

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        self._rescan(ctx, data["target_location_id"], {data["target_dir"]})
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata


class FileCutterJob(_FsJob):
    """Move: rename when possible, copy+delete across devices (fs/cut.rs)."""

    NAME = "file_cutter"

    def init(self, ctx: WorkerContext):
        db = ctx.library.db
        target_root = location_path_of(db, self.init_args["target_location_id"])
        target_dir = target_root / self.init_args.get("target_dir", "").strip("/")
        if not target_dir.is_dir():
            raise JobError(f"target directory missing: {target_dir}")
        steps, source_dirs = [], set()
        for row, src in self._sources(ctx):
            steps.append({"src": str(src), "dst": str(target_dir / src.name)})
            source_dirs.add((row["location_id"],
                             (row["materialized_path"] or "/").strip("/")))
        if not steps:
            raise EarlyFinish("nothing to move")
        return ({"target_location_id": self.init_args["target_location_id"],
                 "target_dir": self.init_args.get("target_dir", ""),
                 "source_dirs": sorted(source_dirs)},
                steps, {"moved": 0})

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        src, dst = Path(step["src"]), Path(step["dst"])
        # cut.rs semantics: moving a file onto itself is a no-op, and an
        # existing destination is WouldOverwrite — never rename-away.
        if src == dst:
            return StepResult(metadata={"moved": 0})
        if dst.exists():
            return StepResult(errors=[f"move {src}: would overwrite {dst}"])
        try:
            try:
                os.rename(src, dst)
            except OSError:
                if src.is_dir():
                    shutil.copytree(src, dst)
                    shutil.rmtree(src)
                else:
                    shutil.copy2(src, dst)
                    src.unlink()
            return StepResult(metadata={"moved": 1})
        except OSError as e:
            return StepResult(errors=[f"move {src}: {e}"])

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        for loc_id, sub in data["source_dirs"]:
            self._rescan(ctx, loc_id, {sub})
        self._rescan(ctx, data["target_location_id"], {data["target_dir"]})
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata


class FileDeleterJob(_FsJob):
    NAME = "file_deleter"

    def init(self, ctx: WorkerContext):
        steps = [{"file_path_id": fp, } for fp in self.init_args["sources"]]
        if not steps:
            raise EarlyFinish("nothing to delete")
        return {}, steps, {"deleted": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        db = ctx.library.db
        try:
            row, path = file_path_abs(db, step["file_path_id"])
        except JobError:
            return StepResult(metadata={"deleted": 0})  # row already gone
        try:
            if row["is_dir"]:
                shutil.rmtree(path, ignore_errors=False)
            else:
                path.unlink(missing_ok=True)
        except OSError as e:
            return StepResult(errors=[f"delete {path}: {e}"])
        _remove_rows(ctx.library, row)
        return StepResult(metadata={"deleted": 1})

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata


class FileEraserJob(_FsJob):
    """Secure-overwrite then delete. init_args: sources, passes (default 2)."""

    NAME = "file_eraser"

    def init(self, ctx: WorkerContext):
        steps = []
        for row, src in self._sources(ctx):
            if row["is_dir"]:
                # expand tree: erase every file, then rmdir at finalize
                for dirpath, _dirnames, filenames in os.walk(src):
                    for fname in filenames:
                        steps.append({"path": str(Path(dirpath) / fname),
                                      "file_path_id": None})
                steps.append({"rmtree": str(src), "file_path_id": row["id"]})
            else:
                steps.append({"path": str(src), "file_path_id": row["id"]})
        if not steps:
            raise EarlyFinish("nothing to erase")
        return {"passes": int(self.init_args.get("passes", 2))}, steps, {"erased": 0}

    def execute_step(self, ctx: WorkerContext, data, step, step_number) -> StepResult:
        db = ctx.library.db
        if "rmtree" in step:
            try:
                shutil.rmtree(step["rmtree"], ignore_errors=True)
            except OSError as e:
                return StepResult(errors=[f"rmtree {step['rmtree']}: {e}"])
            row = db.find_one(FilePath, {"id": step["file_path_id"]})
            if row:
                _remove_rows(ctx.library, row)
            return StepResult(metadata={"erased": 1})
        path = Path(step["path"])
        try:
            size = path.stat().st_size
            with open(path, "r+b", buffering=0) as fh:
                for _ in range(data["passes"]):
                    fh.seek(0)
                    remaining = size
                    while remaining > 0:
                        n = min(ERASE_BLOCK, remaining)
                        fh.write(secrets.token_bytes(n))
                        remaining -= n
                    fh.flush()
                    os.fsync(fh.fileno())
            path.unlink()
        except OSError as e:
            return StepResult(errors=[f"erase {path}: {e}"])
        if step["file_path_id"] is not None:
            row = db.find_one(FilePath, {"id": step["file_path_id"]})
            if row:
                _remove_rows(ctx.library, row)
        return StepResult(metadata={"erased": 1})

    def finalize(self, ctx: WorkerContext, data, run_metadata):
        ctx.library.emit("invalidate_query", {"key": "search.paths"})
        return run_metadata


def _remove_rows(library, row: dict[str, Any]) -> None:
    """Drop the file_path row (and its subtree for dirs), emitting sync ops."""
    db = library.db
    sync = getattr(library, "sync", None)
    emit = sync is not None and getattr(sync, "emit_messages", False)
    rows = [row]
    if row["is_dir"]:
        prefix = f"{(row['materialized_path'] or '/')}{row['name']}/"
        rows += db.find(FilePath, {"location_id": row["location_id"]})
        rows = [r for r in rows if r is row or
                (r["materialized_path"] or "").startswith(prefix)]
    ops = []
    with db.transaction():
        for r in rows:
            if emit:
                ops.append(sync.shared_delete(FilePath, r["pub_id"]))
            db.delete(FilePath, {"id": r["id"]})
        if ops:
            sync.log_ops(ops)
    if ops:
        sync.created()
    # removed paths may have orphaned their objects; the actor debounces
    remover = getattr(library, "orphan_remover", None)
    if remover is not None:
        remover.invoke()
