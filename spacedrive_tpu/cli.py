"""CLI shell: `python -m spacedrive_tpu.cli <command>`.

Reference: apps/cli/src/main.rs (85 LoC — inspects sd-crypto encrypted file
headers via FileHeader::from_reader). That surface is `inspect` here; the
CLI additionally fronts a running server through the typed client (the
headless operations a desktop shell would expose):

    inspect <file.bytes>                     encrypted-header details
    serve  [--data-dir D] [--port N]         alias for the server shell
    libraries [--url U]                      list libraries
    scan --library L --location N [--url U]  kick a rescan
    search --library L [--term T] [--url U]  file_path search
    jobs --library L [--url U]               job reports
    duplicates --library L [--url U]         persisted near-dup pairs
"""

from __future__ import annotations

import argparse
import sys


def cmd_inspect(args: argparse.Namespace) -> int:
    """FileHeader::from_reader dump (apps/cli main.rs:14-23)."""
    from .crypto.header import FileHeader
    from .crypto.stream import CryptoError

    try:
        with open(args.file, "rb") as fh:
            header = FileHeader.from_reader(fh)
    except FileNotFoundError:
        print(f"error: no such file: {args.file}", file=sys.stderr)
        return 1
    except CryptoError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"Encrypted file: {args.file}")
    print(f"  header version: {header.version}")
    print(f"  algorithm:      {header.algorithm.name}")
    print(f"  keyslots:       {len(header.keyslots)}")
    for i, slot in enumerate(header.keyslots):
        print(f"    [{i}] v{slot.version} {slot.algorithm.name} "
              f"{slot.hashing_algorithm.kind}/{slot.hashing_algorithm.params.value}")
    print(f"  metadata:       {'present (sealed)' if header.metadata else 'none'}")
    print(f"  preview media:  "
          f"{'present (sealed)' if header.preview_media else 'none'}")
    return 0


def _client(args: argparse.Namespace):
    from .client import SpacedriveClient

    return SpacedriveClient(args.url, auth=getattr(args, "auth", None))


def _resolve_library(client, selector: str) -> str:
    libs = client.query("libraries.list")
    for lib in libs:
        if lib["id"] == selector or lib["name"] == selector:
            return lib["id"]
    names = [f"{l['name']} ({l['id'][:8]})" for l in libs]
    print(f"error: no library {selector!r}; have: {names}", file=sys.stderr)
    raise SystemExit(1)


def cmd_libraries(args) -> int:
    for lib in _client(args).query("libraries.list"):
        print(f"{lib['id']}  {lib['name']}")
    return 0


def cmd_scan(args) -> int:
    client = _client(args)
    lib_id = _resolve_library(client, args.library)
    job_id = client.mutation("locations.fullRescan",
                             {"location_id": args.location}, library_id=lib_id)
    print(f"scan started: job {job_id}")
    return 0


def cmd_search(args) -> int:
    client = _client(args)
    lib_id = _resolve_library(client, args.library)
    arg = {"take": args.take}
    if args.term:
        arg["search"] = args.term
    result = client.query("search.paths", arg, library_id=lib_id)
    for row in result["items"]:
        full = row["name"] + (f".{row['extension']}"
                              if row["extension"] and not row["is_dir"] else "")
        kind = "dir " if row["is_dir"] else "file"
        print(f"{kind} {row['materialized_path']}{full}  "
              f"{row.get('size_in_bytes') or 0}B  cas={row.get('cas_id') or '-'}")
    return 0


def cmd_jobs(args) -> int:
    from .jobs.report import JobStatus

    client = _client(args)
    lib_id = _resolve_library(client, args.library)

    def status_name(value):
        return JobStatus.NAMES.get(value, str(value))

    for report in client.query("jobs.reports", library_id=lib_id):
        print(f"{report['id'][:8]} {report['name']:<18} "
              f"{status_name(report['status'])}")
        for child in report.get("children", []):
            print(f"  └ {child['id'][:8]} {child['name']:<16} "
                  f"{status_name(child['status'])}")
    return 0


def cmd_duplicates(args) -> int:
    client = _client(args)
    lib_id = _resolve_library(client, args.library)
    pairs = client.query("search.duplicates", {}, library_id=lib_id)
    for p in pairs:
        print(f"{p['similarity']:.2f}  {p['a_dir']}{p['a_name']}  ~  "
              f"{p['b_dir']}{p['b_name']}")
    if not pairs:
        print("no near-duplicate pairs recorded")
    return 0


def cmd_serve(args) -> int:
    from .server.__main__ import main as serve_main

    argv = ["--data-dir", args.data_dir, "--port", str(args.port)]
    return serve_main(argv)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="spacedrive_tpu.cli")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("inspect", help="inspect an encrypted .bytes file header")
    p.add_argument("file")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("serve", help="run the headless server shell")
    p.add_argument("--data-dir", default="./sd_data")
    p.add_argument("--port", type=int, default=8080)
    p.set_defaults(fn=cmd_serve)

    def net(p):
        p.add_argument("--url", default="http://127.0.0.1:8080")
        p.add_argument("--auth", default=None)

    p = sub.add_parser("libraries", help="list libraries")
    net(p)
    p.set_defaults(fn=cmd_libraries)

    p = sub.add_parser("scan", help="rescan a location")
    net(p)
    p.add_argument("--library", required=True)
    p.add_argument("--location", type=int, required=True)
    p.set_defaults(fn=cmd_scan)

    p = sub.add_parser("search", help="search file paths")
    net(p)
    p.add_argument("--library", required=True)
    p.add_argument("--term", default=None)
    p.add_argument("--take", type=int, default=50)
    p.set_defaults(fn=cmd_search)

    p = sub.add_parser("jobs", help="list job reports")
    net(p)
    p.add_argument("--library", required=True)
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("duplicates", help="list persisted near-dup pairs")
    net(p)
    p.add_argument("--library", required=True)
    p.set_defaults(fn=cmd_duplicates)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
