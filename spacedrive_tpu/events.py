"""Core event bus.

Equivalent of the reference's ``CoreEvent`` broadcast channel
(core/src/api/mod.rs:18-23) and ``Node::emit`` (core/src/lib.rs:203-229):
a typed broadcast bus that API subscriptions and the job system publish to.

Implemented as a lock-guarded fan-out of bounded per-subscriber queues, the
Python analogue of tokio's ``broadcast`` channel: slow subscribers drop the
oldest events rather than block producers (the job hot path must never stall
on a UI listener).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from typing import Any, Callable, Iterator


@dataclasses.dataclass(frozen=True)
class CoreEvent:
    """A broadcast event. ``kind`` mirrors the reference's enum variants:

    - ``job_progress``      (JobProgress, api/mod.rs:20)
    - ``invalidate_query``  (InvalidateOperation, api/mod.rs:21)
    - ``new_thumbnail``     (NewThumbnail, api/mod.rs:19)
    - ``notification``      (notifications.rs)
    - ``sync_message``      (sync lib.rs:21-24 SyncMessage Created/Ingested)
    """

    kind: str
    payload: Any = None
    library_id: str | None = None


class Subscription:
    """One subscriber's bounded queue. Iterate to receive; ``close()`` to drop."""

    def __init__(self, bus: "EventBus", capacity: int) -> None:
        self._bus = bus
        self._q: queue.Queue[CoreEvent | None] = queue.Queue(maxsize=capacity)
        self.closed = False

    def _offer(self, event: CoreEvent) -> None:
        while True:
            try:
                self._q.put_nowait(event)
                return
            except queue.Full:
                try:  # lossy broadcast: drop oldest, like tokio broadcast lag
                    self._q.get_nowait()
                except queue.Empty:
                    pass

    def get(self, timeout: float | None = None) -> CoreEvent | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self) -> Iterator[CoreEvent]:
        while not self.closed:
            event = self._q.get()
            if event is None:
                return
            yield event

    def close(self) -> None:
        self.closed = True
        self._bus._unsubscribe(self)
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass


class EventBus:
    """Multi-producer broadcast bus with lossy bounded subscribers."""

    def __init__(self, capacity: int = 1024) -> None:
        self._capacity = capacity
        self._lock = threading.Lock()
        self._subs: list[Subscription] = []
        self._hooks: list[Callable[[CoreEvent], None]] = []

    def subscribe(self, capacity: int | None = None) -> Subscription:
        sub = Subscription(self, capacity or self._capacity)
        with self._lock:
            self._subs.append(sub)
        return sub

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def on(self, hook: Callable[[CoreEvent], None]) -> None:
        """Synchronous in-process hook (used by invalidation bookkeeping)."""
        with self._lock:
            self._hooks.append(hook)

    def off(self, hook: Callable[[CoreEvent], None]) -> None:
        """Remove a hook registered with :meth:`on` (the serve pool
        unhooks its watermark bump at stop so a stopped pool is not kept
        alive by the bus)."""
        with self._lock:
            try:
                self._hooks.remove(hook)
            except ValueError:
                pass

    def emit(self, event: CoreEvent) -> None:
        with self._lock:
            subs = list(self._subs)
            hooks = list(self._hooks)
        for hook in hooks:
            try:
                hook(event)
            except Exception:  # a broken listener must never stall the hot path
                logging.getLogger(__name__).exception("event hook failed for %s", event.kind)
        for sub in subs:
            sub._offer(event)

    def emit_kind(self, kind: str, payload: Any = None, library_id: str | None = None) -> None:
        self.emit(CoreEvent(kind=kind, payload=payload, library_id=library_id))
