"""Notifications: node-level (config-stored) and library-level (DB rows),
pushed to the UI over the event bus.

Parity with core/src/notifications.rs + api/notifications.rs:41-167: each
notification gets a monotonically allocated id scoped to its source; dismiss
removes one, dismissAll clears; a "listen" subscription receives pushes (the
event bus kind "notification").
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from .models import Notification, utc_now

if TYPE_CHECKING:
    from .library import Library
    from .node import Node


def emit_node_notification(node: "Node", data: dict[str, Any],
                           expires_at: str | None = None) -> dict[str, Any]:
    cfg = node.config.get()
    notifications = list(cfg.get("notifications", []))
    next_id = (max((n["id"] for n in notifications), default=0)) + 1
    record = {"id": next_id, "data": data, "read": False, "expires_at": expires_at}
    notifications.append(record)
    node.config.write(notifications=notifications)
    node.emit("notification", {"source": "node", **record})
    return record


def emit_library_notification(library: "Library", data: dict[str, Any],
                              expires_at=None) -> dict[str, Any]:
    nid = library.db.insert(Notification, {
        "data": data, "read": False, "expires_at": expires_at})
    record = {"id": nid, "data": data, "read": False, "expires_at": expires_at}
    library.emit("notification", {"source": "library",
                                  "library_id": library.id, **record})
    return record


def get_notifications(node: "Node") -> list[dict[str, Any]]:
    """All node + library notifications, newest first (api get)."""
    out = [{"source": "node", **n}
           for n in node.config.get().get("notifications", [])]
    for library in node.libraries.list():
        for row in library.db.find(Notification, order_by="id DESC"):
            out.append({"source": "library", "library_id": library.id, **row})
    now = utc_now()
    return [n for n in out
            if not n.get("expires_at") or _as_dt(n["expires_at"]) > now]


def dismiss_notification(node: "Node", source: str, notification_id: int,
                         library_id: str | None = None) -> None:
    if source == "node":
        cfg = node.config.get()
        node.config.write(notifications=[
            n for n in cfg.get("notifications", []) if n["id"] != notification_id])
    else:
        node.libraries.get(library_id).db.delete(Notification,
                                                 {"id": notification_id})


def dismiss_all(node: "Node") -> None:
    node.config.write(notifications=[])
    for library in node.libraries.list():
        library.db.execute("DELETE FROM notification")


def _as_dt(value):
    import datetime as dt

    return dt.datetime.fromisoformat(value) if isinstance(value, str) else value
