// GENERATED FILE — do not edit.
// Regenerate: python -m spacedrive_tpu.api.codegen
// Contract source: spacedrive_tpu/api/types.py + the mounted router schema.
window.SD_PROCEDURES = {
 "albums.addObjects": {
  "kind": "mutation",
  "scope": "library"
 },
 "albums.create": {
  "kind": "mutation",
  "scope": "library"
 },
 "albums.delete": {
  "kind": "mutation",
  "scope": "library"
 },
 "albums.list": {
  "kind": "query",
  "scope": "library"
 },
 "albums.objects": {
  "kind": "query",
  "scope": "library"
 },
 "albums.removeObjects": {
  "kind": "mutation",
  "scope": "library"
 },
 "albums.update": {
  "kind": "mutation",
  "scope": "library"
 },
 "backups.backup": {
  "kind": "mutation",
  "scope": "node"
 },
 "backups.delete": {
  "kind": "mutation",
  "scope": "node"
 },
 "backups.getAll": {
  "kind": "query",
  "scope": "node"
 },
 "backups.restore": {
  "kind": "mutation",
  "scope": "node"
 },
 "buildInfo": {
  "kind": "query",
  "scope": "node"
 },
 "categories.list": {
  "kind": "query",
  "scope": "library"
 },
 "files.copyFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.createDirectory": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.createFile": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.cutFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.decryptFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.deleteFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.duplicateFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.encryptFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.eraseFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.get": {
  "kind": "query",
  "scope": "library"
 },
 "files.getEphemeralMediaData": {
  "kind": "query",
  "scope": "node"
 },
 "files.getMediaData": {
  "kind": "query",
  "scope": "library"
 },
 "files.getPath": {
  "kind": "query",
  "scope": "library"
 },
 "files.removeAccessTime": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.renameFile": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.setFavorite": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.setNote": {
  "kind": "mutation",
  "scope": "library"
 },
 "files.updateAccessTime": {
  "kind": "mutation",
  "scope": "library"
 },
 "invalidation.listen": {
  "kind": "subscription",
  "scope": "node"
 },
 "jobs.cancel": {
  "kind": "mutation",
  "scope": "node"
 },
 "jobs.clear": {
  "kind": "mutation",
  "scope": "library"
 },
 "jobs.clearAll": {
  "kind": "mutation",
  "scope": "library"
 },
 "jobs.generateThumbsForLocation": {
  "kind": "mutation",
  "scope": "library"
 },
 "jobs.identifyUniqueFiles": {
  "kind": "mutation",
  "scope": "library"
 },
 "jobs.isActive": {
  "kind": "query",
  "scope": "node"
 },
 "jobs.newThumbnail": {
  "kind": "subscription",
  "scope": "library"
 },
 "jobs.objectValidator": {
  "kind": "mutation",
  "scope": "library"
 },
 "jobs.pause": {
  "kind": "mutation",
  "scope": "node"
 },
 "jobs.progress": {
  "kind": "subscription",
  "scope": "library"
 },
 "jobs.reports": {
  "kind": "query",
  "scope": "library"
 },
 "jobs.resume": {
  "kind": "mutation",
  "scope": "library"
 },
 "keys.add": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.backupKeystore": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.changeMasterPassword": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.clearMasterPassword": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.deleteFromLibrary": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.disableAutoUnlock": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.enableAutoUnlock": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.getDefault": {
  "kind": "query",
  "scope": "node"
 },
 "keys.getKey": {
  "kind": "query",
  "scope": "node"
 },
 "keys.isKeyManagerUnlocking": {
  "kind": "query",
  "scope": "node"
 },
 "keys.isSetup": {
  "kind": "query",
  "scope": "node"
 },
 "keys.isUnlocked": {
  "kind": "query",
  "scope": "node"
 },
 "keys.list": {
  "kind": "query",
  "scope": "node"
 },
 "keys.listMounted": {
  "kind": "query",
  "scope": "node"
 },
 "keys.lockKeyManager": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.mount": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.restoreKeystore": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.setDefault": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.setup": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.unlockKeyManager": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.unmount": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.unmountAll": {
  "kind": "mutation",
  "scope": "node"
 },
 "keys.updateAutomountStatus": {
  "kind": "mutation",
  "scope": "node"
 },
 "labels.assign": {
  "kind": "mutation",
  "scope": "library"
 },
 "labels.getForObject": {
  "kind": "query",
  "scope": "library"
 },
 "labels.list": {
  "kind": "query",
  "scope": "library"
 },
 "libraries.create": {
  "kind": "mutation",
  "scope": "node"
 },
 "libraries.delete": {
  "kind": "mutation",
  "scope": "node"
 },
 "libraries.edit": {
  "kind": "mutation",
  "scope": "node"
 },
 "libraries.list": {
  "kind": "query",
  "scope": "node"
 },
 "libraries.statistics": {
  "kind": "query",
  "scope": "library"
 },
 "locations.addLibrary": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.create": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.delete": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.fullRescan": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.get": {
  "kind": "query",
  "scope": "library"
 },
 "locations.getWithRules": {
  "kind": "query",
  "scope": "library"
 },
 "locations.indexer_rules.create": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.indexer_rules.delete": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.indexer_rules.get": {
  "kind": "query",
  "scope": "library"
 },
 "locations.indexer_rules.list": {
  "kind": "query",
  "scope": "library"
 },
 "locations.indexer_rules.listForLocation": {
  "kind": "query",
  "scope": "library"
 },
 "locations.list": {
  "kind": "query",
  "scope": "library"
 },
 "locations.online": {
  "kind": "subscription",
  "scope": "library"
 },
 "locations.quickRescan": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.relink": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.subPathRescan": {
  "kind": "mutation",
  "scope": "library"
 },
 "locations.update": {
  "kind": "mutation",
  "scope": "library"
 },
 "nodeState": {
  "kind": "query",
  "scope": "node"
 },
 "nodes.edit": {
  "kind": "mutation",
  "scope": "node"
 },
 "nodes.listLocations": {
  "kind": "query",
  "scope": "library"
 },
 "notifications.dismiss": {
  "kind": "mutation",
  "scope": "node"
 },
 "notifications.dismissAll": {
  "kind": "mutation",
  "scope": "node"
 },
 "notifications.get": {
  "kind": "query",
  "scope": "node"
 },
 "notifications.listen": {
  "kind": "subscription",
  "scope": "node"
 },
 "notifications.test": {
  "kind": "mutation",
  "scope": "node"
 },
 "notifications.testLibrary": {
  "kind": "mutation",
  "scope": "library"
 },
 "p2p.acceptSpacedrop": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.cancelSpacedrop": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.debugConnect": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.events": {
  "kind": "subscription",
  "scope": "node"
 },
 "p2p.identity": {
  "kind": "query",
  "scope": "node"
 },
 "p2p.nlmState": {
  "kind": "query",
  "scope": "node"
 },
 "p2p.pair": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.pairingResponse": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.peers": {
  "kind": "query",
  "scope": "node"
 },
 "p2p.spacedrop": {
  "kind": "mutation",
  "scope": "node"
 },
 "p2p.spacedropDelta": {
  "kind": "mutation",
  "scope": "node"
 },
 "preferences.get": {
  "kind": "query",
  "scope": "library"
 },
 "preferences.update": {
  "kind": "mutation",
  "scope": "library"
 },
 "search.chunkDuplicates": {
  "kind": "query",
  "scope": "library"
 },
 "search.duplicates": {
  "kind": "query",
  "scope": "library"
 },
 "search.ephemeralPaths": {
  "kind": "query",
  "scope": "node"
 },
 "search.nearDuplicates": {
  "kind": "query",
  "scope": "library"
 },
 "search.objects": {
  "kind": "query",
  "scope": "library"
 },
 "search.objectsCount": {
  "kind": "query",
  "scope": "library"
 },
 "search.paths": {
  "kind": "query",
  "scope": "library"
 },
 "search.pathsCount": {
  "kind": "query",
  "scope": "library"
 },
 "spaces.addObjects": {
  "kind": "mutation",
  "scope": "library"
 },
 "spaces.create": {
  "kind": "mutation",
  "scope": "library"
 },
 "spaces.delete": {
  "kind": "mutation",
  "scope": "library"
 },
 "spaces.list": {
  "kind": "query",
  "scope": "library"
 },
 "spaces.objects": {
  "kind": "query",
  "scope": "library"
 },
 "spaces.removeObjects": {
  "kind": "mutation",
  "scope": "library"
 },
 "spaces.update": {
  "kind": "mutation",
  "scope": "library"
 },
 "sync.fleetStatus": {
  "kind": "query",
  "scope": "node"
 },
 "sync.messages": {
  "kind": "query",
  "scope": "library"
 },
 "sync.newMessage": {
  "kind": "subscription",
  "scope": "library"
 },
 "tags.assign": {
  "kind": "mutation",
  "scope": "library"
 },
 "tags.create": {
  "kind": "mutation",
  "scope": "library"
 },
 "tags.delete": {
  "kind": "mutation",
  "scope": "library"
 },
 "tags.get": {
  "kind": "query",
  "scope": "library"
 },
 "tags.getForObject": {
  "kind": "query",
  "scope": "library"
 },
 "tags.getWithObjects": {
  "kind": "query",
  "scope": "library"
 },
 "tags.list": {
  "kind": "query",
  "scope": "library"
 },
 "tags.update": {
  "kind": "mutation",
  "scope": "library"
 },
 "telemetry.alerts": {
  "kind": "query",
  "scope": "node"
 },
 "telemetry.jobTrace": {
  "kind": "query",
  "scope": "node"
 },
 "telemetry.requestStats": {
  "kind": "query",
  "scope": "node"
 },
 "telemetry.sloStatus": {
  "kind": "query",
  "scope": "node"
 },
 "telemetry.snapshot": {
  "kind": "query",
  "scope": "node"
 },
 "telemetry.watch": {
  "kind": "subscription",
  "scope": "node"
 },
 "toggleFeatureFlag": {
  "kind": "mutation",
  "scope": "node"
 },
 "volumes.list": {
  "kind": "query",
  "scope": "node"
 }
};
